"""Cold-path phase-breakdown study (rounds 4-7; see the study notes in
antrea_tpu/ops/match.py — cases 2-4 re-measure the ROUND-4 gather-bound
decomposition that set the ~7.4M pps ceiling, case 1 is the round-5
fused baseline, cases 5-6 the round-6/7 overlap and pruning studies).

Measures, at the bench's 100k-rule world and B=32k on the real chip:
  1. fused end-to-end cold classification (the shipped path);
  2. the searchsorted phase alone;
  3. searchsorted + 6 row gathers with a reduction fused into the gather
     loops (the hard gather bound);
  4. the AND-in-XLA + 2-input consumer variant (measured dead-end (c));
  5. (round 6) the OVERLAP DECOMPOSITION of the churn step — fast step
     alone, coalesced drain alone, the two serialized per iteration, and
     the two double-buffered (drain of window i-1 behind fast step i,
     drain_reclaim=True) — the in-repo methodology behind the
     steady_churn_overlap_pps bench regime: serialized-minus-overlapped
     IS the recovered serialization, and fast+drain-minus-overlapped
     bounds what further overlap could still buy;
  6. (round 7) the PRUNING DECOMPOSITION of the two-level
     aggregated-bitmap kernel — summary-gather alone (phase 1: aggregate
     rows + AND + short-circuit), the pruned end-to-end walk per K rung
     (candidate gather + fallback included), and the unpruned kernel as
     the fallback-dispatch reference — plus a fallback-rate-vs-K sweep
     over PRUNE_LADDER and a match-density sweep (fraction of lanes with
     any candidate at all), emitted as one decomposition JSON.

Run directly: python bench_cold_study.py  (several minutes on the
tunneled platform; numbers jitter ~15% run to run).  --cases selects a
subset (e.g. --cases 6), --smoke shrinks the world so case 6 proves the
methodology end-to-end on a CPU container (the --force-host-devices
style smoke; on-chip numbers are the driver's to write), and --json sets
the case-6 output path."""
import argparse
import json
from functools import lru_cache

ap = argparse.ArgumentParser()
ap.add_argument("--cases", default="1,2,3,4,5,6",
                help="comma-separated case numbers to run")
ap.add_argument("--smoke", action="store_true",
                help="toy world + tiny batches: CPU-green methodology "
                     "proof, not a measurement")
ap.add_argument("--json", default="COLD_STUDY_prune.json",
                help="case-6 decomposition JSON output path")
args = ap.parse_args()
CASES = {int(c) for c in args.cases.split(",") if c.strip()}

import jax, jax.numpy as jnp, numpy as np  # noqa: E402
from antrea_tpu.compiler.compile import compile_policy_set  # noqa: E402
from antrea_tpu.ops import match as m  # noqa: E402
from antrea_tpu.simulator.genpolicy import gen_cluster  # noqa: E402
from antrea_tpu.simulator.traffic import gen_traffic  # noqa: E402
from antrea_tpu.utils import ip as iputil  # noqa: E402
from antrea_tpu.utils.timing import device_loop_time  # noqa: E402

SMOKE = args.smoke
B = 1 << (10 if SMOKE else 15)
N_RULES = 3_000 if SMOKE else 100_000
K_SMALL, K_BIG, REPEATS = (2, 4, 1) if SMOKE else (8, 64, 3)
# The fused pallas consumer interprets off-TPU (very slow): the smoke
# exercises the XLA path, the chip runs the shipped fused path.
FUSED = jax.devices()[0].platform != "cpu"

cluster = gen_cluster(N_RULES, n_nodes=64, pods_per_node=32, seed=1)
cps = compile_policy_set(cluster.ps)
drs, meta = m.to_device(cps)
tr = gen_traffic(cluster.pod_ips, B, n_flows=B, seed=3)
src = jnp.asarray(iputil.flip_u32(tr.src_ip))
dst = jnp.asarray(iputil.flip_u32(tr.dst_ip))
proto = jnp.asarray(tr.proto)
dport = jnp.asarray(tr.dst_port)
print("w_in", meta.w_in, "w_out", meta.w_out,
      "NB at", drs.ingress.at.bounds.shape, "peer", drs.ingress.peer.bounds.shape,
      "svc", drs.ingress.svc.bounds.shape, "smoke", SMOKE, flush=True)

def timeit(name, body, carry):
    sec = device_loop_time(body, carry, k_small=K_SMALL, k_big=K_BIG,
                           repeats=REPEATS)
    print(f"{name}: {sec*1e3:.3f} ms/batch -> {B/sec/1e6:.2f}M pps", flush=True)
    return sec

def perturb(dp_, acc):
    return dp_ ^ (acc[0] & 1)

carry = (jnp.zeros(8, jnp.int32), drs, src, dst, proto, dport)

# 1) end-to-end fused (baseline)
def body_full(i, carry):
    acc, drs_, s_, d_, p_, dp_ = carry
    cls = m.classify_batch(drs_, s_, d_, p_, perturb(dp_, acc), meta=meta,
                           fused=FUSED)
    return (acc.at[:1].add(cls["code"].sum(dtype=jnp.int32)), drs_, s_, d_, p_, dp_)
t_full = timeit("end-to-end (unpruned)", body_full, carry) if 1 in CASES else None

# 2) searchsorted phase only (6 dim indices + 2 iso)
def body_ss(i, carry):
    acc, drs_, s_, d_, p_, dp_ = carry
    dp2 = perturb(dp_, acc)
    svc_key = (p_ << 16) | dp2
    tot = jnp.int32(0)
    for tab, x in ((drs_.ingress.at, d_), (drs_.ingress.peer, s_),
                   (drs_.ingress.svc, svc_key), (drs_.egress.at, s_),
                   (drs_.egress.peer, d_), (drs_.egress.svc, svc_key)):
        tot = tot + m._searchsorted_right(tab.bounds, x).sum()
    return (acc.at[:1].add(tot), drs_, s_, d_, p_, dp_)
if 2 in CASES:
    t_ss = timeit("searchsorted only", body_ss, carry)

# 3) gathers only (no consumer): sum of gathered rows (XLA fuses sum into gather)
def body_g(i, carry):
    acc, drs_, s_, d_, p_, dp_ = carry
    dp2 = perturb(dp_, acc)
    svc_key = (p_ << 16) | dp2
    tot = jnp.uint32(0)
    for tab, x in ((drs_.ingress.at, d_), (drs_.ingress.peer, s_),
                   (drs_.ingress.svc, svc_key), (drs_.egress.at, s_),
                   (drs_.egress.peer, d_), (drs_.egress.svc, svc_key)):
        idx = m._searchsorted_right(tab.bounds, x)
        tot = tot + tab.inc[idx].sum()
    return (acc.at[:1].add(tot.astype(jnp.int32)), drs_, s_, d_, p_, dp_)
if 3 in CASES:
    t_g = timeit("searchsorted+gathers+reduce (no consumer)", body_g, carry)

# 4) AND-in-XLA + 2-input pallas consumer
if 4 in CASES:
    from jax.experimental import pallas as pl

    @lru_cache(maxsize=4)
    def consumer2(b, w_in, w_out, in_phases, out_phases):
        def kernel(mi, mo, o_ref):
            i0, ik, ib = m._phase_scan_tile(mi[:], w_in, in_phases)
            o0, ok_, ob = m._phase_scan_tile(mo[:], w_out, out_phases)
            o_ref[:] = jnp.stack([i0, ik, ib, o0, ok_, ob,
                                  jnp.zeros_like(i0), jnp.zeros_like(i0)], axis=1)
        tb = m._FUSE_TB
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((b, 8), jnp.int32),
            grid=(b // tb,),
            in_specs=[pl.BlockSpec((tb, w), lambda i: (i, 0)) for w in (w_in, w_out)],
            out_specs=pl.BlockSpec((tb, 8), lambda i: (i, 0)),
            interpret=jax.devices()[0].platform == "cpu",
        )

    def body_and(i, carry):
        acc, drs_, s_, d_, p_, dp_ = carry
        dp2 = perturb(dp_, acc)
        svc_key = (p_ << 16) | dp2
        ing, egs = drs_.ingress, drs_.egress
        mi = (ing.at.inc[m._searchsorted_right(ing.at.bounds, d_)]
              & ing.peer.inc[m._searchsorted_right(ing.peer.bounds, s_)]
              & ing.svc.inc[m._searchsorted_right(ing.svc.bounds, svc_key)])
        mo = (egs.at.inc[m._searchsorted_right(egs.at.bounds, s_)]
              & egs.peer.inc[m._searchsorted_right(egs.peer.bounds, d_)]
              & egs.svc.inc[m._searchsorted_right(egs.svc.bounds, svc_key)])
        hits = consumer2(B, meta.w_in, meta.w_out, meta.in_phases, meta.out_phases)(
            mi.astype(jnp.int32), mo.astype(jnp.int32))
        return (acc.at[:1].add(hits[:, 0].sum()), drs_, s_, d_, p_, dp_)
    t_and = timeit("AND-in-XLA + 2-input consumer", body_and, carry)

# 5) round-6 overlap decomposition: churn-step cadences over the SAME
# rule world (empty service tables — the overlap under study is the
# drain/commit pipeline, not ServiceLB).  B-lane hot set, n_new fresh
# lanes per step from a one-per-flow pool; the drain runs as ONE
# coalesced round at miss_chunk == n_new with drain_reclaim=True.
if 5 in CASES:
    from antrea_tpu.compiler.services import compile_services
    from antrea_tpu.models import pipeline as pmod

    N_NEW = B // 8
    POOL = 1 << (12 if SMOKE else 18)
    pool_tr = gen_traffic(cluster.pod_ips, POOL, n_flows=POOL, seed=7,
                          one_per_flow=True)
    p_src = jnp.asarray(iputil.flip_u32(pool_tr.src_ip))
    p_dst = jnp.asarray(iputil.flip_u32(pool_tr.dst_ip))
    p_pro = jnp.asarray(pool_tr.proto)
    p_sp = jnp.asarray(pool_tr.src_port)
    p_dp = jnp.asarray(pool_tr.dst_port)
    pool_cols = (p_src, p_dst, p_pro, p_sp, p_dp)
    hot_cols = (src, dst, proto, jnp.asarray(tr.src_port), dport)

    step5, state5, (drs5, dsvc5) = pmod.make_pipeline(
        cps, compile_services([]), flow_slots=1 << (14 if SMOKE else 20),
        miss_chunk=N_NEW, fused=FUSED,
    )
    meta_fast = step5.meta._replace(phases=0)
    meta_drain = step5.meta._replace(drain_reclaim=True)
    for w in (100, 101):  # warm the hot set
        state5, _ = step5(state5, drs5, dsvc5, *hot_cols,
                          jnp.int32(w), jnp.int32(0))

    def overlap_body(fast, drain, deferred):
        """One churn iteration: optional fast step over the mixed batch,
        optional drain of the current (deferred=False) or previous
        (deferred=True) fresh window."""

        def body(i, carry):
            acc, st, drs_, dsvc_, hcols, pcols = carry
            off = (acc[1] * N_NEW) % (POOL - N_NEW)
            off_p = (jnp.maximum(acc[1] - 1, 0) * N_NEW) % (POOL - N_NEW)
            fresh = tuple(jax.lax.dynamic_slice(c, (off,), (N_NEW,))
                          for c in pcols)
            dwin = (tuple(jax.lax.dynamic_slice(c, (off_p,), (N_NEW,))
                          for c in pcols) if deferred else fresh)
            if fast:
                cols = tuple(jnp.concatenate([h[: B - N_NEW], f])
                             for h, f in zip(hcols, fresh))
                st, o = pmod._pipeline_step(st, drs_, dsvc_, *cols, 102 + i, 0,
                                            meta=meta_fast)
                acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            if drain:
                st, od = pmod._pipeline_step(st, drs_, dsvc_, *dwin, 102 + i, 0,
                                             meta=meta_drain)
                acc = acc.at[0].add(od["code"].sum(dtype=jnp.int32)
                                    + od["n_miss"])
            acc = acc.at[1].add(1)
            return (acc, st, drs_, dsvc_, hcols, pcols)

        return body

    carry5 = (jnp.zeros(8, jnp.int32), state5, drs5, dsvc5, hot_cols, pool_cols)
    t_fast = timeit("churn fast step alone (phases=0)",
                    overlap_body(True, False, False), carry5)
    t_drain = timeit("coalesced drain alone (drain_reclaim)",
                     overlap_body(False, True, False), carry5)
    t_serial = timeit("fast + drain SERIALIZED (same window)",
                      overlap_body(True, True, False), carry5)
    t_ovl = timeit("fast + drain OVERLAPPED (window i-1 deferred)",
                   overlap_body(True, True, True), carry5)
    print(f"overlap decomposition: fast {t_fast*1e3:.2f} + drain "
          f"{t_drain*1e3:.2f} = {1e3*(t_fast+t_drain):.2f} ms predicted; "
          f"serialized {t_serial*1e3:.2f} ms, overlapped {t_ovl*1e3:.2f} ms "
          f"-> recovered {1e3*(t_serial-t_ovl):.2f} ms/step "
          f"({B/t_ovl/1e6:.2f}M pps overlapped)", flush=True)

# 6) round-7 pruning decomposition (the two-level aggregated-bitmap
# kernel): summary-only / pruned end-to-end per K / unpruned reference,
# fallback-rate-vs-K over PRUNE_LADDER, and a match-density sweep.
if 6 in CASES:
    drs_p, meta_p1 = m.to_device(cps, prune_budget=m.PRUNE_LADDER[0])
    S_in = int(drs_p.ingress.at.agg.shape[1])
    print(f"prune tables: w_in {meta_p1.w_in} (agg-padded), "
          f"S {S_in} superblocks", flush=True)

    def body_prune(meta_k, summary):
        def body(i, carry):
            acc, drs_, s_, d_, p_, dp_ = carry
            cls = m.classify_batch(
                drs_, s_, d_, p_, perturb(dp_, acc), meta=meta_k,
                fused=FUSED and not summary, summary_only=summary,
            )
            return (acc.at[:1].add(cls["code"].sum(dtype=jnp.int32)),
                    drs_, s_, d_, p_, dp_)
        return body

    carry6 = (jnp.zeros(8, jnp.int32), drs_p, src, dst, proto, dport)
    if t_full is None:
        t_full = timeit("end-to-end (unpruned reference)", body_full, carry)
    t_sum = timeit("summary-only (phase 1: agg gather + AND)",
                   body_prune(meta_p1, True), carry6)

    k_sweep = {}
    for k in m.PRUNE_LADDER:
        meta_k = meta_p1._replace(prune_budget=k)
        t_k = timeit(f"pruned end-to-end K={k}", body_prune(meta_k, False),
                     carry6)
        cls = m.classify_batch(drs_p, src, dst, proto, dport, meta=meta_k)
        k_sweep[str(k)] = {
            "pruned_s_per_batch": t_k,
            "pruned_pps": B / t_k,
            "fallback_rate": float(np.asarray(cls["prune_fb"]).mean()),
            "skip_rate": float(np.asarray(cls["prune_skip"]).mean()),
        }
        print(f"  K={k}: fb_rate {k_sweep[str(k)]['fallback_rate']:.4f} "
              f"skip_rate {k_sweep[str(k)]['skip_rate']:.4f}", flush=True)

    # Match-density sweep: replace a fraction of lanes with non-pod
    # (universe-external) endpoints so the aggregate AND proves no-match
    # — the default-deny / attack-traffic shape the short circuit targets.
    rng = np.random.default_rng(11)
    ext = rng.integers(1, 1 << 24, size=B).astype(np.uint32)  # 0.x.y.z: no pods
    meta_k4 = meta_p1._replace(prune_budget=4)
    density_sweep = {}
    for frac in (0.0, 0.5, 1.0):
        n_ext = int(B * frac)
        d_mix = np.asarray(tr.dst_ip).copy()
        s_mix = np.asarray(tr.src_ip).copy()
        d_mix[:n_ext] = ext[:n_ext]
        s_mix[:n_ext] = ext[::-1][:n_ext]
        cm = (jnp.zeros(8, jnp.int32), drs_p,
              jnp.asarray(iputil.flip_u32(s_mix)),
              jnp.asarray(iputil.flip_u32(d_mix)), proto, dport)
        t_d = timeit(f"pruned K=4, external-lane frac {frac}",
                     body_prune(meta_k4, False), cm)
        cls = m.classify_batch(cm[1], cm[2], cm[3], proto, dport,
                               meta=meta_k4)
        density_sweep[str(frac)] = {
            "pruned_pps": B / t_d,
            "skip_rate": float(np.asarray(cls["prune_skip"]).mean()),
            "fallback_rate": float(np.asarray(cls["prune_fb"]).mean()),
        }

    doc = {
        "metric": "cold_prune_decomposition",
        "smoke": SMOKE,
        "batch": B,
        "n_rules": N_RULES,
        "superblocks": S_in,
        "fused": FUSED,
        "unpruned_s_per_batch": t_full,
        "unpruned_pps": B / t_full,
        "summary_only_s_per_batch": t_sum,
        "summary_only_pps": B / t_sum,
        "k_sweep": k_sweep,
        "density_sweep": density_sweep,
    }
    line = json.dumps(doc)
    print(line, flush=True)
    with open(args.json, "w") as f:
        f.write(line + "\n")
    print(f"# wrote {args.json}", flush=True)
