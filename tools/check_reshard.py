#!/usr/bin/env python
"""Reshard-manifest drift check: every (D,)-sharded state field migrates.

The elastic resharding plane (parallel/reshard.py) moves the stateful
tables — the pytree fields `parallel/mesh._state_specs` shards with a
leading ``data`` axis — to their new home shards when the data axis
resizes.  A NEW stateful field that nobody taught the migrator is a
silent flow-loss bug: the field would ship sharded (tools/check_mesh.py
forces the spec), survive every parity suite on a fixed mesh, and then
silently zero out on the first live resize.

This tool fails the build when any field specced `P(DATA, ...)` in
`_state_specs` has no migration rule in `reshard.RESHARD_MANIFEST` — and
when the manifest itself goes stale (names a field that is not
(D,)-sharded, or carries no rule text).  The migrator copies rows
field-generically from `FlowCache._fields`/`AffinityTable._fields`, so
manifest coverage here is the load-bearing gate.

Dependency-free on purpose (stdlib ast only, no jax, no package import):
runnable standalone in any CI step and invoked from the tier-1 suite
(tests/test_reshard.py).  Exit 0 = covered; 1 = drift (printed).
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
MESH = PKG / "parallel" / "mesh.py"
RESHARD = PKG / "parallel" / "reshard.py"

STATE_BUILDER = "_state_specs"


def data_sharded_fields() -> set:
    """'Class.field' for every kwarg of a constructor call inside
    _state_specs whose value is a P(DATA, ...) spec — the fields that
    carry a leading data axis and therefore must migrate on resize."""
    tree = ast.parse(MESH.read_text())
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == STATE_BUILDER):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            cls = (fn.attr if isinstance(fn, ast.Attribute)
                   else fn.id if isinstance(fn, ast.Name) else None)
            if cls is None:
                continue
            for kw in call.keywords:
                v = kw.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id == "P"
                        and v.args
                        and isinstance(v.args[0], ast.Name)
                        and v.args[0].id == "DATA"):
                    out.add(f"{cls}.{kw.arg}")
    return out


def manifest() -> dict:
    tree = ast.parse(RESHARD.read_text())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        else:
            continue
        if "RESHARD_MANIFEST" in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError(
        "parallel/reshard.py defines no RESHARD_MANIFEST literal")


def check() -> list[str]:
    problems: list[str] = []
    try:
        rules = manifest()
    except (OSError, ValueError) as e:
        return [str(e)]
    sharded = data_sharded_fields()
    if not sharded:
        return [f"parallel/mesh.py {STATE_BUILDER} names no P(DATA, ...) "
                f"fields at all — the parse is broken or the specs moved"]

    for key in sorted(sharded - set(rules)):
        problems.append(
            f"{key} is (D,)-sharded in parallel/mesh.py {STATE_BUILDER} "
            f"but has NO migration rule in reshard.RESHARD_MANIFEST — a "
            f"live resize would silently zero it (flow loss); teach the "
            f"migrator and document the rule")
    for key in sorted(set(rules) - sharded):
        problems.append(
            f"RESHARD_MANIFEST names {key!r}, which is not a (D,)-sharded "
            f"field of {STATE_BUILDER} — stale manifest row")
    for key, rule in rules.items():
        if not (isinstance(rule, str) and rule.strip()):
            problems.append(f"RESHARD_MANIFEST[{key!r}] carries no rule "
                            f"text")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    print(f"reshard manifest covered: {len(data_sharded_fields())} "
          f"(D,)-sharded state fields, {len(manifest())} migration rules")
    return 0


if __name__ == "__main__":
    sys.exit(main())
