#!/usr/bin/env python
"""Mesh partition-spec drift check: every sharded pytree field is specced.

The multichip datapath (parallel/mesh.py + parallel/meshpath.py) places
three pytrees on the (data × rule) mesh — `PipelineState` (with its
`FlowCache`/`AffinityTable` leaves), `DeviceRuleSet` (with its
`DimTable`/`DeviceDirection`/`IsoTable`/`DeltaTable` leaves) and
`DeviceServiceTables` — under the PartitionSpecs built by `_state_specs`
/ `_drs_specs` / `_svc_specs`.  Those builders enumerate every field BY
NAME on purpose: a field that is merely splatted would let a new
single-chip state column ship replicated-by-accident (or worse, sharded
on the wrong axis) the first time someone grows a NamedTuple.

This tool fails the build when any field of the tracked NamedTuples is
neither named as a keyword in one of the spec builders nor waived in
`mesh.MESH_SPEC_ALLOWLIST` with a reason — and when the allowlist itself
goes stale (waives a field that no longer exists, or one that IS
specced, or carries no reason).

Dependency-free on purpose (stdlib ast only, no jax, no package import):
runnable standalone in any CI step and invoked from the tier-1 suite
(tests/test_mesh_datapath.py).  Exit 0 = covered; 1 = drift (printed).
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
MESH = PKG / "parallel" / "mesh.py"

# NamedTuples whose fields must be specced, per defining module.  The
# nested leaf types are tracked alongside their containers so a field
# added anywhere in the tree is caught.
TRACKED = {
    PKG / "models" / "pipeline.py": (
        "PipelineState", "FlowCache", "AffinityTable", "DeviceServiceTables",
    ),
    PKG / "ops" / "match.py": (
        "DeviceRuleSet", "DeviceDirection", "DimTable", "IsoTable",
        "DeltaTable",
    ),
}

SPEC_BUILDERS = ("_state_specs", "_drs_specs", "_svc_specs")


def namedtuple_fields(path: pathlib.Path, classes) -> dict:
    """class name -> ordered field names, parsed via ast (AnnAssign rows
    of NamedTuple class bodies)."""
    tree = ast.parse(path.read_text())
    out: dict[str, list[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name not in classes:
            continue
        fields = [
            stmt.target.id
            for stmt in node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        ]
        out[node.name] = fields
    return out


def specced_kwargs() -> dict:
    """Constructor-class name -> keyword-argument names used at its call
    sites inside the spec builder functions of parallel/mesh.py.  Keyed
    PER CLASS (the callee's name), not pooled: field names legitimately
    collide across the tracked NamedTuples (FlowCache.ts vs
    AffinityTable.ts, DimTable.bounds vs IsoTable.bounds), and a pooled
    set would let a new field ride a same-named field of a DIFFERENT
    class through the gate unspecced."""
    tree = ast.parse(MESH.read_text())
    by_class: dict[str, set] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name not in SPEC_BUILDERS:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name is None:
                continue
            by_class.setdefault(name, set()).update(
                kw.arg for kw in call.keywords if kw.arg)
    return by_class


def allowlist() -> dict:
    tree = ast.parse(MESH.read_text())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target.id]
        else:
            continue
        if "MESH_SPEC_ALLOWLIST" in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError("parallel/mesh.py defines no MESH_SPEC_ALLOWLIST literal")


def check() -> list[str]:
    problems: list[str] = []
    try:
        waived = allowlist()
    except (OSError, ValueError) as e:
        return [str(e)]
    specced = specced_kwargs()
    if not specced:
        return ["parallel/mesh.py spec builders "
                f"{SPEC_BUILDERS} name no fields at all"]

    qualified: set[str] = set()  # "Class.field" of every tracked field
    for path, classes in TRACKED.items():
        fields_by_class = namedtuple_fields(path, classes)
        for cls in classes:
            if cls not in fields_by_class:
                problems.append(
                    f"{path.relative_to(REPO)} no longer defines {cls} — "
                    f"update tools/check_mesh.py's TRACKED table")
                continue
            for field in fields_by_class[cls]:
                qualified.add(f"{cls}.{field}")
                if (field in specced.get(cls, ())
                        or f"{cls}.{field}" in waived):
                    continue
                problems.append(
                    f"{cls}.{field} ({path.relative_to(REPO)}) has no "
                    f"explicit PartitionSpec at a {cls}(...) call in "
                    f"parallel/mesh.py {SPEC_BUILDERS} and no "
                    f"MESH_SPEC_ALLOWLIST waiver — it would ship on the "
                    f"mesh with an accidental layout")

    for key, reason in waived.items():
        cls, _, field = key.partition(".")
        if key not in qualified:
            problems.append(
                f"MESH_SPEC_ALLOWLIST waives {key!r} (expected "
                f"'Class.field' of a tracked NamedTuple) — stale waiver")
        elif field in specced.get(cls, ()):
            problems.append(
                f"MESH_SPEC_ALLOWLIST waives {key!r}, but it IS specced "
                f"in the builders — drop the stale waiver")
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(
                f"MESH_SPEC_ALLOWLIST waiver {key!r} carries no reason")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    n = sum(len(namedtuple_fields(p, c)) for p, c in TRACKED.items())
    specced = specced_kwargs()
    print(f"mesh specs covered: {n} pytree classes, "
          f"{sum(len(v) for v in specced.values())} specced fields "
          f"across {len(specced)} constructors, "
          f"{len(allowlist())} waivers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
