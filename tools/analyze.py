#!/usr/bin/env python
"""Unified static-analysis runner: every drift gate, one invocation.

    python tools/analyze.py [--pass ID [--pass ID ...]] [--json]
                            [--list] [--root PATH]

Runs the registered passes of antrea_tpu/analysis (the nine migrated
tools/check_* gates + the semantic passes: thread-safety,
bounded-cache, jit-purity, donation-safety) over the repo, applies the
BASELINE.analysis.json suppressions, and exits 0 only when every pass
is clean and the baseline is not stale.  `--json` emits one
machine-readable findings report on stdout (CI artifact / tooling
input); `--list` prints the pass inventory.  Tier-1 invokes the full
suite exactly once, via tests/test_static_analysis.py.

Dependency-free on purpose: antrea_tpu/analysis is stdlib-only (ast),
and antrea_tpu/__init__.py is import-light, so this runs on images
without jax."""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from antrea_tpu.analysis import PASSES, run  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pass", dest="passes", action="append", metavar="ID",
                    help="run only this pass (repeatable); default: all")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable findings report")
    ap.add_argument("--list", action="store_true",
                    help="print the pass inventory and exit")
    ap.add_argument("--root", type=pathlib.Path, default=REPO,
                    help="tree to analyze (default: this repo)")
    args = ap.parse_args(argv)

    if args.list:
        for pid, (_fn, invariant) in PASSES.items():
            print(f"{pid:16s} {invariant}")
        return 0

    try:
        result = run(args.root, args.passes)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result.to_json(), indent=1))
        return 0 if result.clean else 1

    for f in result.findings:
        print(f.render())
    for e in result.errors:
        print(f"DRIFT[baseline] {e}")
    if not result.clean:
        print(f"\nanalysis: {len(result.findings)} finding(s), "
              f"{len(result.errors)} baseline error(s) across "
              f"{len(result.pass_ids)} passes")
        return 1
    suppressed = (f" ({len(result.suppressed)} baselined)"
                  if result.suppressed else "")
    print(f"analysis clean: {len(result.pass_ids)} passes{suppressed}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
