#!/usr/bin/env python
"""Phase-mask drift check: pipeline PH_* == profile chains == bench_profile.

The churn profiler's honesty rests on three surfaces staying in lockstep:

  1. the PH_* mask bits defined in antrea_tpu/models/pipeline.py (the
     compile-time phase gates of the slow path), with PH_ALL their OR;
  2. the cumulative chains in antrea_tpu/models/profile.py (PHASE_CHAIN
     for the synchronous regime, ASYNC_PHASE_CHAIN for the decoupled
     drain regime, OVERLAP_PHASE_CHAIN for the double-buffered overlap
     regime, MAINT_PHASE_CHAIN for the unified maintenance-scheduler
     cadence) — each chain must start at 0, grow by exactly one PH_ bit
     per entry, end at PH_ALL, and carry unique names;
  3. bench_profile.py, which must report its phase list FROM the chain
     (importing PHASE_CHAIN), not from a hand-copied name list.

A new PH_ bit added to the pipeline without a chain entry (or a renamed
phase that bench_profile would silently mis-report) fails here.

Dependency-free on purpose (no jax, no package import): the three files
are parsed textually and the mask expressions evaluated over the parsed
PH_ constants, so this runs in any CI step and from the tier-1 suite
(tests/test_profile.py).  Exit 0 = consistent; 1 = drift (diff printed).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PIPELINE = REPO / "antrea_tpu" / "models" / "pipeline.py"
PROFILE = REPO / "antrea_tpu" / "models" / "profile.py"
BENCH = REPO / "bench_profile.py"

_PH_DEF = re.compile(r"^(PH_[A-Z0-9_]+)\s*=\s*(.+?)\s*(?:#.*)?$", re.M)
_CHAIN = re.compile(
    r"^(PHASE_CHAIN|ASYNC_PHASE_CHAIN|OVERLAP_PHASE_CHAIN"
    r"|MAINT_PHASE_CHAIN|PRUNE_PHASE_CHAIN)\s*:.*?=\s*\((.*?)^\)",
    re.M | re.S,
)
_ENTRY = re.compile(r'\(\s*"([a-z0-9_]+)"\s*,\s*([^)]*?)\s*\)', re.S)


def parse_ph_bits() -> dict:
    """PH_* constants from pipeline.py, numerically evaluated in
    definition order (later definitions may reference earlier ones)."""
    text = PIPELINE.read_text()
    bits: dict[str, int] = {}
    for name, expr in _PH_DEF.findall(text):
        try:
            bits[name] = eval(expr, {"__builtins__": {}}, dict(bits))
        except Exception:
            continue  # not a constant definition (e.g. inside a function)
    return bits


def parse_chains() -> dict:
    """{chain name: [(entry name, mask int), ...]} from profile.py."""
    text = PROFILE.read_text()
    bits = parse_ph_bits()
    env = {f"pl.{k}": v for k, v in bits.items()} | dict(bits)
    chains: dict[str, list] = {}
    for cname, body in _CHAIN.findall(text):
        entries = []
        for ename, expr in _ENTRY.findall(body):
            expr = expr.strip().rstrip(",")
            try:
                mask = eval(expr.replace("pl.", ""), {"__builtins__": {}},
                            dict(bits))
            except Exception as e:
                entries.append((ename, None))
                continue
            entries.append((ename, mask))
        chains[cname] = entries
    return chains


def check() -> list[str]:
    problems: list[str] = []
    bits = parse_ph_bits()
    phase_bits = {k: v for k, v in bits.items() if k != "PH_ALL"}
    if "PH_ALL" not in bits:
        return ["pipeline.py defines no PH_ALL"]
    union = 0
    for v in phase_bits.values():
        union |= v
    if union != bits["PH_ALL"]:
        problems.append(
            f"PH_ALL ({bits['PH_ALL']:#x}) != OR of phase bits ({union:#x})"
        )
    for a, va in phase_bits.items():
        if va & (va - 1):
            problems.append(f"{a} ({va:#x}) is not a single bit")
        for b, vb in phase_bits.items():
            if a < b and va & vb:
                problems.append(f"{a} and {b} overlap ({va:#x} & {vb:#x})")

    chains = parse_chains()
    for required in ("PHASE_CHAIN", "ASYNC_PHASE_CHAIN",
                     "OVERLAP_PHASE_CHAIN", "MAINT_PHASE_CHAIN",
                     "PRUNE_PHASE_CHAIN"):
        if required not in chains:
            problems.append(f"profile.py defines no {required}")
    seen_names: set[str] = set()
    for cname, entries in chains.items():
        if not entries:
            problems.append(f"{cname} parsed empty")
            continue
        names = [n for n, _m in entries]
        dup = {n for n in names if names.count(n) > 1}
        if dup:
            problems.append(f"{cname}: duplicate phase names {sorted(dup)}")
        overlap = seen_names & set(names)
        if overlap:
            problems.append(
                f"{cname}: phase names {sorted(overlap)} reused across "
                f"chains (bench/profile consumers key on the name)"
            )
        seen_names |= set(names)
        prev = None
        covered = 0
        for ename, mask in entries:
            if mask is None:
                problems.append(f"{cname}.{ename}: unparseable mask")
                continue
            if prev is None:
                if mask != 0:
                    problems.append(f"{cname} must start at mask 0")
            else:
                added = mask & ~prev
                if mask & prev != prev:
                    problems.append(
                        f"{cname}.{ename}: mask {mask:#x} is not a "
                        f"superset of its predecessor {prev:#x}"
                    )
                if added == 0 or added & (added - 1):
                    problems.append(
                        f"{cname}.{ename}: must add exactly one PH_ bit "
                        f"(adds {added:#x})"
                    )
            prev = mask
            covered |= mask
        if prev != bits["PH_ALL"]:
            problems.append(
                f"{cname} ends at {prev:#x}, not PH_ALL "
                f"({bits['PH_ALL']:#x}) — a PH_ bit has no phase entry"
            )

    bench = BENCH.read_text()
    if not re.search(r"from antrea_tpu\.models\.profile import .*PHASE_CHAIN",
                     bench):
        problems.append("bench_profile.py does not import PHASE_CHAIN")
    if not re.search(r'"phase_chain":.*PHASE_CHAIN', bench):
        problems.append(
            "bench_profile.py does not derive its reported phase_chain "
            "from profile.PHASE_CHAIN"
        )
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    bits = parse_ph_bits()
    chains = parse_chains()
    print(
        f"phases consistent: {len(bits) - 1} PH_ bits, "
        + ", ".join(f"{c} x{len(e)}" for c, e in sorted(chains.items()))
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
