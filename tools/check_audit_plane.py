#!/usr/bin/env python
"""Audit-plane coverage check: every mutable tensor is scrubbed or waived.

The checksum scrub (datapath/audit.py mechanism 2) only protects what it
digests.  The authoritative inventory of everything a commit can touch is
`_commit_snapshot` on the two engines — so this tool fails the build when
a snapshot key is covered by NEITHER:

  1. SCRUB_MANIFEST  (datapath/audit.py): snapshot key -> "rule" | "state"
     — the tensor classes the scrub digests ("rule": golden at settle,
     heal by host-mirror re-upload; "state": digest pinned to the
     accounted-mutation counter, heal by forced full revalidation);
  2. SCRUB_ALLOWLIST (datapath/audit.py): snapshot key -> reason string
     explaining why it needs no scrub (host-side bookkeeping, static
     metas, re-upload SOURCES).

State added by a future PR therefore fails here until it is scrubbed or
explicitly waived with a reason.  Additional consistency:

  * manifest values must be "rule" or "state";
  * allowlist reasons must be non-empty strings;
  * no key may appear in both tables;
  * each engine must implement the scrub hooks
    (_audit_rule_digests / _audit_state_digest / _audit_reupload) and
    inherit AuditableDatapath.

Dependency-free on purpose (no jax, no package import): the files are
parsed textually and the manifest/allowlist literals evaluated with
ast.literal_eval, so this runs in any CI step and from the tier-1 suite
(tests/test_cache_audit.py).  Exit 0 = covered; 1 = drift (diff printed).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
AUDIT = PKG / "datapath" / "audit.py"
MATCH = PKG / "ops" / "match.py"
ENGINES = (
    PKG / "datapath" / "tpuflow.py",
    PKG / "datapath" / "oracle_dp.py",
)
ENGINE_CLASSES = {
    "tpuflow.py": "TpuflowDatapath",
    "oracle_dp.py": "OracleDatapath",
}
HOOKS = ("_audit_rule_digests", "_audit_state_digest", "_audit_reupload",
         "_audit_window", "_audit_fresh", "_audit_evict")

_DICT_LITERAL = r"^{name}\s*(?::[^=]+)?=\s*(\{{.*?^\}})"


def load_table(text: str, name: str) -> dict:
    """Extract + literal-eval a module-level dict assignment from audit.py
    (pure literals by contract — the docstring on the tables says so)."""
    m = re.search(_DICT_LITERAL.format(name=name), text, re.M | re.S)
    if m is None:
        raise ValueError(f"datapath/audit.py defines no {name} literal")
    return ast.literal_eval(m.group(1))


def snapshot_keys(path: pathlib.Path) -> list[str]:
    """String keys of the dict `_commit_snapshot` returns."""
    text = path.read_text()
    m = re.search(r"def _commit_snapshot\(.*?(?=\n    def )", text, re.S)
    if m is None:
        raise ValueError(f"{path.name}: no _commit_snapshot found")
    body = m.group(0)
    ret = body[body.index("return {"):]
    return re.findall(r'^\s*"(\w+)":', ret, re.M)


def check() -> list[str]:
    problems: list[str] = []
    audit_text = AUDIT.read_text() if AUDIT.exists() else ""
    if not audit_text:
        return [f"{AUDIT.relative_to(REPO)} is missing"]
    try:
        manifest = load_table(audit_text, "SCRUB_MANIFEST")
        allowlist = load_table(audit_text, "SCRUB_ALLOWLIST")
    except ValueError as e:
        return [str(e)]

    for key, klass in manifest.items():
        if klass not in ("rule", "state"):
            problems.append(
                f"SCRUB_MANIFEST[{key!r}] = {klass!r} — must be 'rule' or "
                f"'state'"
            )
    for key, reason in allowlist.items():
        if not (isinstance(reason, str) and reason.strip()):
            problems.append(
                f"SCRUB_ALLOWLIST[{key!r}] has no reason — every waived "
                f"snapshot key must say WHY it needs no scrub"
            )
    for key in set(manifest) & set(allowlist):
        problems.append(
            f"{key!r} is both scrubbed (SCRUB_MANIFEST) and waived "
            f"(SCRUB_ALLOWLIST) — pick one"
        )

    # Round-7 aggregate tables: while DimTable carries an `agg` field the
    # SUB-tensor table must carry its "drs.agg" row (a corrupt aggregate
    # bit can flip a verdict — see the SCRUB_SUBTENSORS comment; it rides
    # the `drs` digest, so it must NOT be a manifest row, which would
    # inflate the maintenance scheduler's scrub cost) and vice versa (a
    # stale row must not outlive the field).
    try:
        subtensors = load_table(audit_text, "SCRUB_SUBTENSORS")
    except ValueError as e:
        return problems + [str(e)]
    for key in set(subtensors) & set(manifest):
        problems.append(
            f"{key!r} is in both SCRUB_MANIFEST and SCRUB_SUBTENSORS — "
            f"sub-tensors ride a group digest, they are not extra folds"
        )
    match_text = MATCH.read_text() if MATCH.exists() else ""
    dim_cls = re.search(r"^class DimTable\(.*?(?=^class |^def )",
                        match_text, re.M | re.S)
    has_agg_field = bool(dim_cls) and bool(
        re.search(r"^    agg\s*:", dim_cls.group(0), re.M))
    if has_agg_field and "drs.agg" not in subtensors:
        problems.append(
            "ops/match.DimTable declares `agg` but SCRUB_SUBTENSORS has "
            "no 'drs.agg' row — aggregate/table divergence would go "
            "undocumented/ungated"
        )
    if not has_agg_field and "drs.agg" in subtensors:
        problems.append(
            "SCRUB_SUBTENSORS carries 'drs.agg' but ops/match.DimTable "
            "declares no `agg` field — stale row"
        )

    for path in ENGINES:
        rel = path.relative_to(REPO)
        try:
            keys = snapshot_keys(path)
        except ValueError as e:
            problems.append(str(e))
            continue
        if not keys:
            problems.append(f"{rel}: _commit_snapshot returns no keys?")
        for key in keys:
            if key not in manifest and key not in allowlist:
                problems.append(
                    f"{rel}: _commit_snapshot key {key!r} is neither in "
                    f"SCRUB_MANIFEST nor SCRUB_ALLOWLIST — new state must "
                    f"be checksum-scrubbed or explicitly waived with a "
                    f"reason (datapath/audit.py)"
                )
        text = path.read_text()
        cls = ENGINE_CLASSES[path.name]
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "AuditableDatapath" not in m.group(1):
            problems.append(f"{rel}: {cls} does not inherit AuditableDatapath")
        for hook in HOOKS:
            if not re.search(rf"^\s*def {hook}\(", text, re.M):
                problems.append(f"{rel} does not implement {hook}()")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    audit_text = AUDIT.read_text()
    manifest = load_table(audit_text, "SCRUB_MANIFEST")
    allowlist = load_table(audit_text, "SCRUB_ALLOWLIST")
    print(
        f"audit plane covered: {len(manifest)} scrubbed tensor groups, "
        f"{len(allowlist)} waived host keys, {len(ENGINES)} engines"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
