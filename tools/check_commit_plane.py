#!/usr/bin/env python
"""Commit-plane routing check: every install goes through datapath/commit.py.

Thin CLI shim over the unified static-analysis plane: the logic lives
in antrea_tpu/analysis/commit_plane.py as pass `commit-plane` (one shared AST
engine, typed findings, reasoned allowlists, BASELINE.analysis.json
suppressions — see antrea_tpu/analysis/core.py).  This entry point
keeps every existing invocation working, verdict-identical to the
pre-migration standalone tool (pinned by
tests/test_static_analysis.py); tier-1 runs the FULL pass suite once
via that test instead of one subprocess per gate.  Accepts an optional
`--root PATH` to analyze another tree (the parity harness).

Exit 0 = consistent; 1 = drift (printed)."""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from antrea_tpu.analysis import run_cli  # noqa: E402

if __name__ == "__main__":
    sys.exit(run_cli("commit-plane", sys.argv[1:]))
