#!/usr/bin/env python
"""Commit-plane routing check: every install goes through datapath/commit.py.

The self-healing guarantees of the transactional commit plane (compile ->
canary -> atomic swap -> settle, rollback to last-known-good, degraded
mode) hold only if NO datapath exposes a tensor-swap entry point that
bypasses the plane.  This tool fails the build when:

  1. an engine (tpuflow.py / oracle_dp.py) defines the PUBLIC
     `install_bundle` or `apply_group_delta` itself — those names must
     live only on the TransactionalDatapath mixin in commit.py, with the
     engines implementing `_install_bundle_impl` / `_apply_group_delta_impl`;
  2. anything under antrea_tpu/ CALLS an `_impl` hook outside commit.py
     (a caller reaching past the canary gate);
  3. an engine class does not inherit TransactionalDatapath;
  4. an engine impl performs its own settle (`self._persist()` /
     `self._record_round()`) — durability must wait for the canary, or a
     crash could reboot into a never-certified bundle.

Dependency-free on purpose (no jax, no package import): purely textual,
runnable in any CI step and invoked from the tier-1 suite
(tests/test_selfheal.py).  Exit 0 = consistent; 1 = drift (diff printed).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
COMMIT = PKG / "datapath" / "commit.py"
ENGINES = (
    PKG / "datapath" / "tpuflow.py",
    PKG / "datapath" / "oracle_dp.py",
)
ENGINE_CLASSES = {
    "tpuflow.py": "TpuflowDatapath",
    "oracle_dp.py": "OracleDatapath",
}
PUBLIC = ("install_bundle", "apply_group_delta")
IMPLS = ("_install_bundle_impl", "_apply_group_delta_impl")
SETTLE = (r"self\._persist\(\)", r"self\._record_round\(\)")


def check() -> list[str]:
    problems: list[str] = []
    commit_text = COMMIT.read_text() if COMMIT.exists() else ""
    if not commit_text:
        return [f"{COMMIT.relative_to(REPO)} is missing"]

    # 1 + 3 + 4: per-engine rules.
    for path in ENGINES:
        text = path.read_text()
        rel = path.relative_to(REPO)
        for name in PUBLIC:
            if re.search(rf"^\s*def {name}\(", text, re.M):
                problems.append(
                    f"{rel} defines public {name}() — installs must route "
                    f"through the commit plane (datapath/commit.py)"
                )
        for name in IMPLS:
            if not re.search(rf"^\s*def {name}\(", text, re.M):
                problems.append(f"{rel} does not implement {name}()")
        cls = ENGINE_CLASSES[path.name]
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "TransactionalDatapath" not in m.group(1):
            problems.append(f"{rel}: {cls} does not inherit TransactionalDatapath")
        for pat in SETTLE:
            for ln, line in enumerate(text.splitlines(), 1):
                if re.search(pat, line) and not line.lstrip().startswith("#"):
                    problems.append(
                        f"{rel}:{ln} settles its own persistence "
                        f"({pat.replace(chr(92), '')}) — settle belongs to "
                        f"the commit plane, after the canary"
                    )

    # 2: _impl call sites only inside commit.py.
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO)
        text = path.read_text()
        for name in IMPLS:
            for ln, line in enumerate(text.splitlines(), 1):
                if f"{name}(" not in line:
                    continue
                stripped = line.lstrip()
                if stripped.startswith(("def ", "#")):
                    continue  # the definition / commentary, not a call
                if path == COMMIT:
                    continue
                problems.append(
                    f"{rel}:{ln} calls {name}() outside datapath/commit.py "
                    f"— a tensor swap bypassing the canary gate"
                )

    # The mixin really carries the public surface.
    for name in PUBLIC:
        if not re.search(rf"^\s*def {name}\(", commit_text, re.M):
            problems.append(f"datapath/commit.py defines no {name}()")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    print(
        f"commit plane consistent: {len(ENGINES)} engines route "
        f"{'/'.join(PUBLIC)} through datapath/commit.py"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
