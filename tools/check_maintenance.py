#!/usr/bin/env python
"""Maintenance-plane discipline check: every background loop runs ONLY
via the unified scheduler.

PR 7's consolidation guarantee (datapath/maintenance.py) only holds if
no plane grows a private cadence again: a direct call site of the
off-hot-step loop entry points — `canary_scan(...)`, `audit_scan(...)`,
the slow-path engine's `maintain(...)`, the FQDN controller's
`tick(...)` — anywhere under antrea_tpu/ outside the scheduler module
re-introduces exactly the plane-vs-plane interleaving races the
scheduler's single serialization point retired.  Tests drive the entry
points directly on purpose (they exercise the planes in isolation) and
are exempt.

Checked:

  1. the MAINT_TASKS inventory (datapath/maintenance.py, a pure literal)
     names every consolidated loop — canary, audit-cursor, tensor-scrub,
     cache-maintain, fqdn-ttl, degraded-recompile;
  2. every inventoried task is actually constructed somewhere
     (`MaintenanceTask("<name>", ...)`) under antrea_tpu/;
  3. both engines inherit MaintainableDatapath and call
     `_init_maintenance` (the scheduler exists on every instance);
  4. no forbidden call site outside datapath/maintenance.py:
       .canary_scan(   allowed only in datapath/commit.py (the mixin's
                       own delegation to its plane)
       .audit_scan(    allowed only in datapath/interface.py (the base
                       default of maintenance_force_audit for datapaths
                       without a scheduler; the mixin delegates via
                       _audit.scan)
       .maintain(      allowed only in datapath/slowpath/engine.py
                       (drain()'s lazy stale-epoch heal is on-demand
                       work on the drain path, not a background loop)
       .tick(          allowed only in agent/fqdn.py (the fqdn-ttl task
                       registration wires self.tick as its runner;
                       MaintenanceScheduler.tick is reached via the
                       maintenance_tick wrapper)

Dependency-free on purpose (no jax, no package import): files are parsed
textually and the task table literal evaluated with ast.literal_eval, so
this runs in any CI step and from the tier-1 suite
(tests/test_maintenance.py).  Exit 0 = disciplined; 1 = drift (printed).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
MAINT = PKG / "datapath" / "maintenance.py"
ENGINES = {
    PKG / "datapath" / "tpuflow.py": "TpuflowDatapath",
    PKG / "datapath" / "oracle_dp.py": "OracleDatapath",
}

REQUIRED_TASKS = {
    "canary", "audit-cursor", "tensor-scrub", "cache-maintain",
    "fqdn-ttl", "degraded-recompile",
}

# pattern -> set of package-relative paths allowed to carry it (the
# scheduler module itself is always exempt).
FORBIDDEN = {
    r"\.canary_scan\(": {"datapath/commit.py"},
    # interface.py: the Datapath base default for maintenance_force_audit
    # — the fallback for audit-capable datapaths WITHOUT a scheduler
    # (nothing to serialize against); both engines override through the
    # mixin, which routes via MaintenanceScheduler.force.
    r"\.audit_scan\(": {"datapath/interface.py"},
    r"\.maintain\(": {"datapath/slowpath/engine.py"},
    r"\.tick\(": {"agent/fqdn.py"},
}


def load_tasks(text: str) -> dict:
    m = re.search(r"^MAINT_TASKS\s*(?::[^=]+)?=\s*(\{.*?^\})", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(
            "datapath/maintenance.py defines no MAINT_TASKS literal")
    return ast.literal_eval(m.group(1))


def check() -> list[str]:
    problems: list[str] = []
    maint_text = MAINT.read_text() if MAINT.exists() else ""
    if not maint_text:
        return [f"{MAINT.relative_to(REPO)} is missing"]
    try:
        tasks = load_tasks(maint_text)
    except ValueError as e:
        return [str(e)]

    missing = REQUIRED_TASKS - set(tasks)
    for name in sorted(missing):
        problems.append(
            f"MAINT_TASKS is missing the consolidated loop {name!r}")
    for name, plane in tasks.items():
        if not (isinstance(plane, str) and plane.strip()):
            problems.append(
                f"MAINT_TASKS[{name!r}] names no owning plane")

    # Every inventoried task must be constructed somewhere in the package.
    ctor = re.compile(r"MaintenanceTask\(\s*\n?\s*[\"']([a-z-]+)[\"']")
    constructed: set[str] = set()
    pkg_files = sorted(PKG.rglob("*.py"))
    for p in pkg_files:
        constructed |= set(ctor.findall(p.read_text()))
    for name in sorted(set(tasks) - constructed):
        problems.append(
            f"MAINT_TASKS names {name!r} but no MaintenanceTask("
            f"\"{name}\", ...) is registered anywhere under antrea_tpu/"
        )

    for path, cls in ENGINES.items():
        rel = path.relative_to(REPO)
        text = path.read_text()
        m = re.search(rf"^class {cls}\(([^)]*)\)", text, re.M | re.S)
        if m is None or "MaintainableDatapath" not in m.group(1):
            problems.append(
                f"{rel}: {cls} does not inherit MaintainableDatapath")
        if "_init_maintenance(" not in text:
            problems.append(f"{rel}: {cls} never calls _init_maintenance")

    for p in pkg_files:
        rel = str(p.relative_to(PKG)).replace("\\", "/")
        if rel == "datapath/maintenance.py":
            continue
        text = p.read_text()
        for pat, allowed in FORBIDDEN.items():
            if rel in allowed:
                continue
            for ln, line in enumerate(text.splitlines(), 1):
                stripped = line.strip()
                if stripped.startswith("#"):
                    continue
                if re.search(pat, line):
                    problems.append(
                        f"antrea_tpu/{rel}:{ln}: direct background-loop "
                        f"call site ({pat}) outside the maintenance "
                        f"scheduler — register a MaintenanceTask and run "
                        f"it via MaintenanceScheduler.tick() instead"
                    )
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    tasks = load_tasks(MAINT.read_text())
    print(
        f"maintenance plane disciplined: {len(tasks)} consolidated loops, "
        f"{len(ENGINES)} engines, 0 rogue call sites"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
