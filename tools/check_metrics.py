#!/usr/bin/env python
"""Metric-name drift check: registry == emissions == README table.

Three-way consistency over the `antrea_tpu_*` metric namespace:

  1. every name in the METRICS registry
     (antrea_tpu/observability/metrics.py) appears in README.md's
     "Observability" metric inventory, and vice versa — the README table
     is the operator contract;
  2. every `antrea_tpu_*` literal anywhere under antrea_tpu/ resolves to
     a registered family (histogram `_bucket`/`_sum`/`_count` suffixes
     fold to their family), so nothing can be emitted unregistered.

Dependency-free on purpose (no jax, no package import — metrics.py is
loaded directly from its path, and it must stay importable that way):
runnable standalone in any CI step and invoked from the tier-1 suite
(tests/test_prom_exposition.py).  No cryptography imports here, gated or
otherwise — this tool must run on images without the wheel.

Exit 0 = consistent; 1 = drift (diff printed).
"""

from __future__ import annotations

import importlib.util
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
NAME_RE = re.compile(r"antrea_tpu_[a-z0-9_]+")
_SUFFIXES = ("_bucket", "_sum", "_count")


def load_registry() -> dict:
    """METRICS from observability/metrics.py WITHOUT importing the
    package (keeps this tool jax-free; metrics.py depends only on the
    stdlib by design)."""
    path = REPO / "antrea_tpu" / "observability" / "metrics.py"
    spec = importlib.util.spec_from_file_location("_metrics_standalone", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return dict(mod.METRICS)


def readme_names(registry: dict) -> set:
    """Every antrea_tpu_* token mentioned in README.md."""
    text = (REPO / "README.md").read_text()
    return {_family(n, registry) for n in NAME_RE.findall(text)}


def _family(name: str, registry: dict) -> str:
    """Fold histogram sample suffixes onto their family name."""
    if name in registry:
        return name
    for suf in _SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in registry:
            return name[: -len(suf)]
    return name


def source_names(registry: dict) -> set:
    """Every antrea_tpu_* literal under antrea_tpu/ (emissions + the
    comments that cite them — citing an unregistered name is drift too)."""
    out = set()
    for p in (REPO / "antrea_tpu").rglob("*.py"):
        for n in NAME_RE.findall(p.read_text()):
            out.add(_family(n, registry))
    return out


def check() -> list[str]:
    registry = load_registry()
    reg = set(registry)
    readme = readme_names(registry)
    src = source_names(registry)
    problems = []
    for n in sorted(reg - readme):
        problems.append(f"registered but missing from README.md: {n}")
    for n in sorted(readme - reg):
        problems.append(f"in README.md but not registered: {n}")
    for n in sorted(src - reg):
        problems.append(f"referenced in source but not registered: {n}")
    # The registry itself lives in source, so reg - src only flags names
    # nobody renders NOR documents in code — dead registry entries.
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    print(f"metrics consistent: {len(load_registry())} families")
    return 0


if __name__ == "__main__":
    sys.exit(main())
