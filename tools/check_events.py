#!/usr/bin/env python
"""Flight-recorder / realization-tracing drift check.

The post-mortem journal is only trustworthy if its schema, its emit
sites and its operator documentation agree.  Checked:

  1. every `FlightRecorder.emit(kind="...")` / plane `_emit("...")` call
     site under antrea_tpu/ uses a kind declared in
     observability/flightrec.EVENT_KINDS (variable-kind forwarding shims
     are validated at runtime by FlightRecorder.emit itself, which
     raises on an undeclared kind);
  2. every declared kind has >= 1 emit site — a kind nobody emits is a
     dead schema row that would silently document nothing;
  3. every declared kind has a README row (the event-kind table in the
     "Observability" section is the operator contract);
  4. the realization stage labels (observability/tracing.py
     REALIZATION_STAGES) each have a README row, and the
     antrea_tpu_policy_realization_seconds family is registered in the
     metrics registry (observability/metrics.py METRICS) — the stage
     label set and the histogram family must not drift apart.

Dependency-free on purpose (no jax, no package import — the literals are
parsed textually with ast.literal_eval): runnable standalone in any CI
step and invoked from the tier-1 suite (tests/test_flightrec.py).

Exit 0 = consistent; 1 = drift (diff printed).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"
FLIGHTREC = PKG / "observability" / "flightrec.py"
TRACING = PKG / "observability" / "tracing.py"
METRICS = PKG / "observability" / "metrics.py"
README = REPO / "README.md"

# Emit call sites carrying a LITERAL kind: the recorder's own keyword
# form and the planes' positional `_emit("kind", ...)` helpers.
EMIT_RES = (
    re.compile(r"\.emit\(\s*kind=\"([a-z0-9-]+)\""),
    re.compile(r"\._emit\(\s*\"([a-z0-9-]+)\""),
)


def _literal(path: pathlib.Path, name: str):
    """Evaluate a module-level literal assignment without importing."""
    text = path.read_text()
    m = re.search(rf"^{name}\s*(?::[^=]+)?=\s*(\{{.*?^\}}|\(.*?^\))", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(f"{path.relative_to(REPO)} defines no {name} literal")
    return ast.literal_eval(m.group(1))


def emit_sites() -> dict:
    """kind -> [package-relative paths with a literal emit of it]."""
    out: dict[str, list[str]] = {}
    for p in sorted(PKG.rglob("*.py")):
        text = p.read_text()
        for rx in EMIT_RES:
            for kind in rx.findall(text):
                out.setdefault(kind, []).append(
                    str(p.relative_to(REPO)))
    return out


def check() -> list[str]:
    problems: list[str] = []
    try:
        kinds = _literal(FLIGHTREC, "EVENT_KINDS")
        stages = _literal(TRACING, "REALIZATION_STAGES")
        registry = _literal(METRICS, "METRICS")
    except (OSError, ValueError) as e:
        return [str(e)]
    readme = README.read_text()

    sites = emit_sites()
    for kind in sorted(set(sites) - set(kinds)):
        problems.append(
            f"emit site uses undeclared kind {kind!r} "
            f"({', '.join(sites[kind])}) — declare it in EVENT_KINDS")
    for kind in sorted(set(kinds) - set(sites)):
        problems.append(
            f"declared kind {kind!r} has no emit site under antrea_tpu/ "
            f"— dead schema row")
    for kind in sorted(kinds):
        if f"`{kind}`" not in readme:
            problems.append(
                f"declared kind {kind!r} has no README row (event-kind "
                f"table in the Observability section)")

    fam = "antrea_tpu_policy_realization_seconds"
    if fam not in registry:
        problems.append(
            f"{fam} is not registered in observability/metrics.METRICS")
    if fam not in readme:
        problems.append(f"{fam} has no README row")
    for stage in stages:
        if f"`{stage}`" not in readme:
            problems.append(
                f"realization stage {stage!r} has no README row "
                f"(span-stage table in the Observability section)")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    kinds = _literal(FLIGHTREC, "EVENT_KINDS")
    stages = _literal(TRACING, "REALIZATION_STAGES")
    print(f"events consistent: {len(kinds)} kinds, "
          f"{len(stages)} realization stages")
    return 0


if __name__ == "__main__":
    sys.exit(main())
