#!/usr/bin/env python
"""Tenant-id drift check: every 5-tuple-keyed or per-world surface must
carry the tenant id (datapath/tenancy.py).

A multi-tenant datapath is only isolated if NO surface that hashes,
keys, or commits on the 5-tuple can silently drop the owning world:
one dropped tenant id turns "isolated policy worlds" into cross-tenant
verdict/state bleed.  Checked:

  1. the miss-queue schema carries the tenant column
     (datapath/slowpath/queue.COLUMNS) and the one admission-column
     builder produces it (datapath/interface._queue_cols);
  2. every `_queue_cols(` CALL site under antrea_tpu/ passes `tenant=`
     — an admit path that drops it would queue tenant rows as
     default-world rows and classify them under the wrong policy;
  3. every `shard_of_tuples(` call site under antrea_tpu/ passes
     `tenant=` or is allowlisted with a reason (the shard hash is the
     mesh's 5-tuple home map — without the salt two tenants' identical
     tuples would collide onto one home's cache semantics);
  4. each engine's `_TENANT_WORLD_FIELDS` literal covers the required
     per-world members (generation, state/interpreter estate, the
     quota/eviction meters) — a field missing from the swap list leaks
     one tenant's state into the next world swapped in;
  5. the commit plane's per-world slice (tenancy.COMMIT_WORLD_FIELDS)
     names real CommitPlane attributes and includes the
     degraded/LKG pair — the tenant-scoped-rollback contract;
  6. every `antrea_tpu_tenant_*` family in the metrics registry is
     rendered with a `tenant=` label (observability/metrics.py) —
     unlabeled tenant meters would aggregate worlds together.

Dependency-free on purpose (textual parsing only): runnable standalone
and invoked from the tier-1 suite (tests/test_tenancy.py).

Exit 0 = consistent; 1 = drift (diff printed).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "antrea_tpu"

# shard_of_tuples call sites allowed WITHOUT a tenant= kwarg, with the
# reason each is default-world-only by construction.
SHARD_ALLOWLIST = {
    "parallel/mesh.py":
        "the definition site (tenant defaults to 0 = the default world)",
    "parallel/reshard.py":
        "migration/cutover routing walks the DEFAULT world's tables only "
        "— reshard_begin refuses to start while tenant worlds exist "
        "(parallel/meshpath.reshard_begin)",
}

# _queue_cols call sites allowed WITHOUT tenant= (the definition).
QUEUE_ALLOWLIST = {
    "datapath/interface.py":
        "the definition site (tenant defaults to 0)",
}

REQUIRED_WORLD_FIELDS = {
    "datapath/tpuflow.py": {
        "_ps", "_cps", "_drs", "_meta", "_meta_step", "_state", "_gen",
        "_stats_in", "_stats_out", "_evictions", "_state_mutations",
        "_pipe_kw",
    },
    "datapath/oracle_dp.py": {
        "_ps", "_oracle", "_gen", "_stats_in", "_stats_out",
        "_state_mutations",
    },
}

REQUIRED_COMMIT_FIELDS = {"degraded", "last_error", "lkg_generation",
                          "lkg_at"}


def _literal_tuple(path: pathlib.Path, name: str):
    text = path.read_text()
    m = re.search(rf"^\s*{name}\s*(?::[^=]+)?=\s*(\(.*?\))", text,
                  re.M | re.S)
    if m is None:
        raise ValueError(
            f"{path.relative_to(REPO)} defines no {name} literal")
    return ast.literal_eval(m.group(1))


def _call_sites(pattern: str) -> list[tuple[str, int, str]]:
    """(relpath, lineno, full call text) of every `pattern(` site —
    the call text spans to the balanced closing paren."""
    out = []
    rx = re.compile(re.escape(pattern) + r"\(")
    for p in sorted(PKG.rglob("*.py")):
        text = p.read_text()
        rel = str(p.relative_to(PKG)).replace("\\", "/")
        for m in rx.finditer(text):
            start = m.end() - 1
            depth = 0
            for i in range(start, min(len(text), start + 2000)):
                if text[i] == "(":
                    depth += 1
                elif text[i] == ")":
                    depth -= 1
                    if depth == 0:
                        break
            line = text.count("\n", 0, m.start()) + 1
            out.append((rel, line, text[m.start():i + 1]))
    return out


def check() -> list[str]:
    problems: list[str] = []

    # 1. queue schema + builder.
    qtext = (PKG / "datapath" / "slowpath" / "queue.py").read_text()
    m = re.search(r"^COLUMNS\s*=\s*(\(.*?\))", qtext, re.M | re.S)
    cols = ast.literal_eval(m.group(1)) if m else ()
    if "tenant" not in cols:
        problems.append(
            "datapath/slowpath/queue.COLUMNS has no 'tenant' column — "
            "queued misses cannot be classified in their owner's world")
    itext = (PKG / "datapath" / "interface.py").read_text()
    if '"tenant"' not in itext:
        problems.append(
            "datapath/interface._queue_cols does not produce the "
            "'tenant' column")

    # 2./3. call sites must pass tenant=.
    for pattern, allow, why in (
        ("_queue_cols", QUEUE_ALLOWLIST,
         "queued rows would land in the default world"),
        ("shard_of_tuples", SHARD_ALLOWLIST,
         "two tenants' identical tuples would share one home"),
    ):
        for rel, line, call in _call_sites(pattern):
            if rel in allow:
                continue
            if re.search(r"def\s+" + pattern, call):
                continue
            if "tenant=" not in call:
                problems.append(
                    f"{rel}:{line}: {pattern}(...) drops the tenant id "
                    f"({why}) — pass tenant= or allowlist with a reason")

    # 4. world-field coverage.
    for rel, required in REQUIRED_WORLD_FIELDS.items():
        try:
            fields = set(_literal_tuple(REPO / "antrea_tpu" / rel,
                                        "_TENANT_WORLD_FIELDS"))
        except ValueError as e:
            problems.append(str(e))
            continue
        for name in sorted(required - fields):
            problems.append(
                f"antrea_tpu/{rel}: _TENANT_WORLD_FIELDS is missing "
                f"{name!r} — that state would leak across world swaps")

    # 5. commit-plane slice.
    tenancy = PKG / "datapath" / "tenancy.py"
    try:
        cw = set(_literal_tuple(tenancy, "COMMIT_WORLD_FIELDS"))
    except ValueError as e:
        problems.append(str(e))
        cw = set()
    for name in sorted(REQUIRED_COMMIT_FIELDS - cw):
        problems.append(
            f"datapath/tenancy.COMMIT_WORLD_FIELDS is missing {name!r} — "
            f"a tenant rollback would not be tenant-scoped")
    commit_text = (PKG / "datapath" / "commit.py").read_text()
    for name in sorted(cw):
        if not re.search(rf"self\.{name}\b", commit_text):
            problems.append(
                f"COMMIT_WORLD_FIELDS names {name!r} but CommitPlane has "
                f"no such attribute — the swap would silently no-op")

    # 6. tenant metric families render tenant-labeled.
    mpath = PKG / "observability" / "metrics.py"
    mtext = mpath.read_text()
    m = re.search(r"^METRICS\s*(?::[^=]+)?=\s*(\{.*?^\})", mtext,
                  re.M | re.S)
    registry = ast.literal_eval(m.group(1)) if m else {}
    tenant_fams = [n for n in registry
                   if n.startswith("antrea_tpu_tenant_")
                   and n != "antrea_tpu_tenant_worlds"]
    if not tenant_fams:
        problems.append(
            "no antrea_tpu_tenant_* families in the metrics registry")
    if "_labels(tenant=tid, node=node)" not in mtext:
        problems.append(
            "observability/metrics.py renders no tenant-labeled sample "
            "lines (_labels(tenant=...)) — tenant meters would "
            "aggregate worlds together")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        return 1
    print("tenant surfaces consistent: queue schema, admit/shard call "
          "sites, world-field coverage, commit slice, tenant-labeled "
          "metrics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
