#!/usr/bin/env python
"""Churn-regime phase-breakdown driver (round-5 verdict weak #1).

The verdict found steady_churn_pps (~5M, bench.py) at ~3x below what the
component numbers predict, with the slow-path loop never profiled.  This
driver reproduces bench.py's churn regime EXACTLY (100k rules + 5k
services, universe == flow slots == 2^22, 1/8 of every 2^17-lane batch
genuinely fresh flows) and attributes the per-step time to named phases
via the cumulative phase-mask chain (models/profile.py): fast-path
lookup, miss-detect scaffolding, ServiceLB, classify, cache commit/DNAT
meta write, eviction scan.

Honesty gate: the phase breakdown sums EXACTLY to the chain-end time by
construction (telescoped differencing), and an INDEPENDENT full-step
measurement (separate dispatch, different K values) must agree within
+-15% — the same criterion as "sums to the measured steady_churn_pps
inverse".  Disagreement beyond that exits nonzero AFTER printing, so the
driver always records the numbers.

Emits one JSON line on stdout and writes PROFILE_r<NN>.json (next free
round number in the repo root; --out overrides).
"""

import argparse
import glob
import json
import os
import re

import jax.numpy as jnp
import numpy as np

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models import pipeline as pl
from antrea_tpu.models.profile import (FUSED_PHASE_CHAIN,
                                       MAINT_PHASE_CHAIN,
                                       OVERLAP_PHASE_CHAIN, PHASE_CHAIN,
                                       PRUNE_PHASE_CHAIN, profile_churn,
                                       profile_churn_fused,
                                       profile_churn_maintenance,
                                       profile_churn_overlap,
                                       profile_churn_prune)
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil

# bench.py's churn-regime shape, verbatim.
N_RULES = 100_000
N_SERVICES = 5_000
B = 1 << 17
FLOW_SLOTS = 1 << 22
CHURN_POOL = 1 << 22
CHURN_DIV = 8
AGREEMENT_TOL = 0.15


def _next_out(repo_dir: str) -> str:
    taken = [
        int(m.group(1))
        for p in glob.glob(os.path.join(repo_dir, "PROFILE_r*.json"))
        if (m := re.search(r"PROFILE_r(\d+)\.json$", p))
    ]
    return os.path.join(repo_dir, f"PROFILE_r{max(taken, default=0) + 1:02d}.json")


def _cols(tr):
    return (
        jnp.asarray(np.ascontiguousarray(iputil.flip_u32(tr.src_ip))),
        jnp.asarray(np.ascontiguousarray(iputil.flip_u32(tr.dst_ip))),
        jnp.asarray(np.ascontiguousarray(tr.proto)),
        jnp.asarray(np.ascontiguousarray(tr.src_port)),
        jnp.asarray(np.ascontiguousarray(tr.dst_port)),
    )


def _telemetry_structure_check(out_path: str) -> int:
    """--mode telemetry: the hot-path telemetry schema gate on BOTH
    engines (observability/telemetry.py).  A toy world (this is a
    structure check, not a measurement): each twin runs one instrumented
    probe step via profile(mode="telemetry") and both counter key sets
    must equal TELEMETRY_COUNTERS — the same invariant the
    telemetry-registry analysis pass pins statically, checked here
    against the LIVE kernels."""
    from antrea_tpu.datapath.oracle_dp import OracleDatapath
    from antrea_tpu.datapath.tpuflow import TpuflowDatapath
    from antrea_tpu.observability.telemetry import TELEMETRY_COUNTERS

    cluster = gen_cluster(1_000, n_nodes=8, pods_per_node=8, seed=1)
    tr = gen_traffic(cluster.pod_ips, 1 << 10, n_flows=1 << 8, seed=3)
    counters = {}
    for name, dp in (
        ("tpuflow", TpuflowDatapath(cluster.ps, flow_slots=1 << 12,
                                    aff_slots=1 << 10)),
        ("oracle", OracleDatapath(cluster.ps, flow_slots=1 << 12,
                                  aff_slots=1 << 10)),
    ):
        p = dp.profile(tr, mode="telemetry")
        counters[name] = p["counters"]
    want = sorted(TELEMETRY_COUNTERS)
    ok = all(sorted(c) == want for c in counters.values())
    doc = {
        "metric": "telemetry_structure_check",
        "mode": "telemetry",
        "expected_counters": want,
        "engines": counters,
        "ok": ok,
    }
    line = json.dumps(doc)
    print(line)
    with open(out_path, "w") as f:
        f.write(line + "\n")
    print(f"# wrote {out_path}", flush=True)
    if not ok:
        raise SystemExit(
            f"telemetry counter schema drifted from TELEMETRY_COUNTERS "
            f"{want}: {({n: sorted(c) for n, c in counters.items()})}"
        )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="output JSON path")
    ap.add_argument("--k-small", type=int, default=4)
    ap.add_argument("--k-big", type=int, default=16)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument(
        "--mode", choices=("sync", "overlap", "maintenance", "prune",
                           "fused", "telemetry"),
        default="sync",
        help="sync = the inline slow-path chain (PHASE_CHAIN); overlap = "
             "the round-6 double-buffered regime (OVERLAP_PHASE_CHAIN: "
             "drain of window i-1 overlapping fast step i) — diff the "
             "two runs to attribute the overlap win phase by phase; "
             "maintenance = the unified background plane's cadence "
             "(MAINT_PHASE_CHAIN: the scheduler's fused maintenance pass "
             "riding every step) — maintenance_s is the plane's own "
             "attributed cost; prune = the round-7 two-level kernel's "
             "regime (PRUNE_PHASE_CHAIN: the async cadence over a "
             "prune_budget>0 meta, classify split into summary-gather vs "
             "candidate-gather); fused = the round-8 one-kernel regime "
             "(FUSED_PHASE_CHAIN: the async cadence over a one-pass "
             "meta — the fused_onepass entry is the whole in-VMEM pass); "
             "telemetry = the hot-path counter STRUCTURE check "
             "(observability/telemetry.py): one instrumented probe step "
             "on BOTH engines, both twins' counter key sets pinned to "
             "TELEMETRY_COUNTERS — a schema gate, not a measurement",
    )
    ap.add_argument("--prune-budget", type=int, default=4,
                    help="K budget for --mode prune/fused "
                         "(PRUNE_LADDER rung)")
    args = ap.parse_args()
    out_path = args.out or _next_out(os.path.dirname(os.path.abspath(__file__)))

    if args.mode == "telemetry":
        return _telemetry_structure_check(out_path)

    cluster = gen_cluster(N_RULES, n_nodes=64, pods_per_node=32, seed=1)
    cps = compile_policy_set(cluster.ps)
    services = gen_services(N_SERVICES, cluster.pod_ips, seed=2)
    svc = compile_services(services)
    # Hot set: zipf repeat-flow traffic (the established connections);
    # pool: one packet per universe flow, no repeats (bench.measure_churn's
    # permutation pool — a zipf pool re-hits its head and under-states the
    # miss fraction).
    hot = gen_traffic(cluster.pod_ips, B, n_flows=1 << 15, seed=31,
                      services=services, svc_fraction=0.3)
    pool = gen_traffic(cluster.pod_ips, CHURN_POOL, n_flows=CHURN_POOL,
                       seed=32, services=services, svc_fraction=0.3,
                       one_per_flow=True)
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=4096, fused=True,
        prune_budget=(args.prune_budget
                      if args.mode in ("prune", "fused") else 0),
        # --mode prune pins the STAGED pruned kernel (fused=True +
        # prune_budget>0 would otherwise auto-upgrade to the one-pass,
        # which --mode fused profiles instead).
        onepass=args.mode == "fused",
    )
    hot_c, pool_c = _cols(hot), _cols(pool)
    n_new = B // CHURN_DIV

    if args.mode == "overlap":
        chain = OVERLAP_PHASE_CHAIN
        prof = profile_churn_overlap(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=args.k_small, k_big=args.k_big, repeats=args.repeats,
        )
        # Independent full-step measurement of the SAME overlapped
        # cadence: a 2-entry chain whose end is the full (fast + drain
        # at PH_ALL) step, fresh dispatches, different K values.
        indep = profile_churn_overlap(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=max(2, args.k_small // 2), k_big=2 * args.k_big,
            repeats=args.repeats,
            chain=(("base", 0), ("full", pl.PH_ALL)),
        )
    elif args.mode == "maintenance":
        chain = MAINT_PHASE_CHAIN
        prof = profile_churn_maintenance(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=args.k_small, k_big=args.k_big, repeats=args.repeats,
        )
        # Independent full-step measurement of the SAME maintenance
        # cadence (rider included): fresh dispatches, different K values.
        indep = profile_churn_maintenance(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=max(2, args.k_small // 2), k_big=2 * args.k_big,
            repeats=args.repeats,
            chain=(("base", 0), ("full", pl.PH_ALL)),
        )
    elif args.mode == "fused":
        chain = FUSED_PHASE_CHAIN
        prof = profile_churn_fused(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=args.k_small, k_big=args.k_big, repeats=args.repeats,
        )
        # Independent full-step measurement of the SAME one-kernel
        # cadence: fresh dispatches, different K values.
        indep = profile_churn_fused(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=max(2, args.k_small // 2), k_big=2 * args.k_big,
            repeats=args.repeats,
            chain=(("base", 0), ("full", pl.PH_ALL)),
        )
    elif args.mode == "prune":
        chain = PRUNE_PHASE_CHAIN
        prof = profile_churn_prune(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=args.k_small, k_big=args.k_big, repeats=args.repeats,
        )
        # Independent full-step measurement of the SAME pruned cadence:
        # fresh dispatches, different K values.
        indep = profile_churn_prune(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=max(2, args.k_small // 2), k_big=2 * args.k_big,
            repeats=args.repeats,
            chain=(("base", 0), ("full", pl.PH_ALL)),
        )
    else:
        chain = PHASE_CHAIN
        prof = profile_churn(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=args.k_small, k_big=args.k_big, repeats=args.repeats,
        )
        # Independent full-step measurement: fresh dispatch chain,
        # different K values — the cross-check that the masked-chain end
        # is a real full-step time, not an artifact of its own
        # measurement.
        indep = profile_churn(
            step.meta, state, drs, dsvc, hot_c, pool_c, n_new=n_new,
            k_small=max(2, args.k_small // 2), k_big=2 * args.k_big,
            repeats=args.repeats, chain=(("full", pl.PH_ALL),),
        )
    sum_phases = sum(prof["phases_s"].values())
    agreement = sum_phases / indep["total_s"]
    bottleneck = max(prof["phases_s"], key=prof["phases_s"].get)
    doc = {
        "metric": f"churn_phase_breakdown_{N_RULES // 1000}k_rules",
        "unit": "s/step",
        "mode": args.mode,
        "batch": B,
        "fresh_per_step": n_new,
        "churn_universe": CHURN_POOL,
        "flow_slots": FLOW_SLOTS,
        "phase_chain": [name for name, _m in chain],  # PHASE_CHAIN / OVERLAP_PHASE_CHAIN per --mode
        "phases_s": prof["phases_s"],
        "phase_fractions": prof["phase_fractions"],
        "total_s": prof["total_s"],
        "churn_pps": prof["pps"],
        "bottleneck": bottleneck,
        # Maintenance mode only: the background plane's own attributed
        # per-step cost (maint_fast_path minus a rider-free fast step).
        "maintenance_s": prof.get("maintenance_s"),
        "maintenance_fraction": prof.get("maintenance_fraction"),
        # Prune mode only: the K budget the chain was attributed at.
        "prune_budget": prof.get("prune_budget"),
        "check": {
            "sum_phases_s": sum_phases,
            "independent_step_s": indep["total_s"],
            "independent_churn_pps": indep["pps"],
            "agreement": round(agreement, 4),
            "within_15pct": abs(agreement - 1.0) <= AGREEMENT_TOL,
        },
    }
    line = json.dumps(doc)
    print(line)
    with open(out_path, "w") as f:
        f.write(line + "\n")
    print(f"# wrote {out_path}", flush=True)
    if abs(agreement - 1.0) > AGREEMENT_TOL:
        raise SystemExit(
            f"phase breakdown ({sum_phases:.4f}s) disagrees with the "
            f"independent step time ({indep['total_s']:.4f}s) by more than "
            f"{AGREEMENT_TOL:.0%} — measurement unstable, do not trust the "
            f"attribution"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
