#!/usr/bin/env python
"""Headline benchmark: classified packets/sec/chip at 100k rules.

Mirrors BASELINE.json config 4 (100k-rule multi-tenant mix: K8s NP + ACNP
tiers + CIDR blocks, conjunctive match) plus config 3's service load
(5k ClusterIP services with endpoint selection + session affinity), driven
by the synthetic traffic generator (the antrea-agent-simulator analog) with
a Zipf flow universe so the flow cache sees realistic repeat-flow ratios —
the same property the reference's datapath relies on (OVS megaflow cache +
kernel conntrack only classify the first packet of a flow).

Protocol: steady-state throughput of the full stateful datapath step
(flow-cache fast path + conntrack semantics + ServiceLB/DNAT + conjunctive
classification of cache misses), measured by running K steps inside one
device dispatch (lax.fori_loop) and fetching the result — honest on
runtimes where async dispatch under-reports and per-call round trips
over-report (see antrea_tpu/utils/timing.py).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 10e6 (the BASELINE.json north-star target:
>= 10M classified packets/sec/chip @ 100k rules on v5e-1).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models import pipeline as pl
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil

N_RULES = 100_000
N_SERVICES = 5_000
B = 1 << 17
K = 128
FLOW_SLOTS = 1 << 22
MISS_CHUNK = 256
BASELINE_PPS = 10e6


def main():
    cluster = gen_cluster(N_RULES, n_nodes=64, pods_per_node=32, seed=1)
    cps = compile_policy_set(cluster.ps)
    services = gen_services(N_SERVICES, cluster.pod_ips, seed=2)
    svc = compile_services(services)
    tr = gen_traffic(
        cluster.pod_ips, B, n_flows=1 << 15, seed=3,
        services=services, svc_fraction=0.3,
    )
    src = jnp.asarray(iputil.flip_u32(tr.src_ip))
    dst = jnp.asarray(iputil.flip_u32(tr.dst_ip))
    proto = jnp.asarray(tr.proto)
    sport = jnp.asarray(tr.src_port)
    dport = jnp.asarray(tr.dst_port)

    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, chunk=512, flow_slots=FLOW_SLOTS, miss_chunk=MISS_CHUNK
    )
    # Warm: cold classify of the whole flow universe, then a cache-warm pass.
    state, out = step(state, drs, dsvc, src, dst, proto, sport, dport,
                      jnp.int32(100), jnp.int32(0))
    state, out = step(state, drs, dsvc, src, dst, proto, sport, dport,
                      jnp.int32(101), jnp.int32(0))

    def body(i, carry):
        st, drs_, dsvc_, s_, d_, p_, sp_, dp_, acc = carry
        st, o = pl._pipeline_step(
            st, drs_, dsvc_, s_, d_, p_, sp_, dp_, 102 + i, 0,
            meta=step.meta,
        )
        acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
        return (st, drs_, dsvc_, s_, d_, p_, sp_, dp_, acc)

    carry = (state, drs, dsvc, src, dst, proto, sport, dport,
             jnp.zeros(8, jnp.int32))
    # Two-K differencing cancels the dispatch+fetch round trip (~120ms on
    # the tunneled platform) out of the per-step time.
    sec_per_step = device_loop_time(body, carry, k_small=8, k_big=K, repeats=3)
    pps = B / sec_per_step
    print(json.dumps({
        "metric": f"classified_pkts_per_sec_chip_{N_RULES // 1000}k_rules",
        "value": round(pps, 1),
        "unit": "packets/s",
        "vs_baseline": round(pps / BASELINE_PPS, 4),
    }))


if __name__ == "__main__":
    main()
