#!/usr/bin/env python
"""Headline benchmark: classified packets/sec/chip at 100k rules.

Mirrors BASELINE.json config 4 (100k-rule multi-tenant mix: K8s NP + ACNP
tiers + CIDR blocks, conjunctive match) plus config 3's service load
(5k ClusterIP services with endpoint selection + session affinity), driven
by the synthetic traffic generator (the antrea-agent-simulator analog) with
a Zipf flow universe so the flow cache sees realistic repeat-flow ratios —
the same property the reference's datapath relies on (OVS megaflow cache +
kernel conntrack only classify the first packet of a flow).

Protocol: steady-state throughput of the full stateful datapath step
(flow-cache fast path + conntrack semantics + ServiceLB/DNAT + conjunctive
classification of cache misses), measured by running K steps inside one
device dispatch (lax.fori_loop) and fetching the result — honest on
runtimes where async dispatch under-reports and per-call round trips
over-report (see antrea_tpu/utils/timing.py).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is value / 10e6 (the BASELINE.json north-star target:
>= 10M classified packets/sec/chip @ 100k rules on v5e-1).
"""

import json
import os
import sys
from collections import namedtuple

# --force-host-devices N: provision N virtual CPU devices BEFORE jax
# initializes — the CPU-CI escape hatch that makes the multichip regime
# smoke-testable without a pod slice (the tier-1 suite has its own
# 8-device conftest; this flag is for running bench.py directly).
_FORCED_HOST_DEVICES = 0
if "--force-host-devices" in sys.argv:
    try:
        _FORCED_HOST_DEVICES = int(
            sys.argv[sys.argv.index("--force-host-devices") + 1])
        if _FORCED_HOST_DEVICES <= 0:
            raise ValueError
    except (IndexError, ValueError):
        raise SystemExit(
            "usage: bench.py [--force-host-devices N]  "
            "(N = positive virtual CPU device count for the multichip "
            "smoke)")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_FORCED_HOST_DEVICES}"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models import pipeline as pl
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.ops.match import classify_batch
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil
from antrea_tpu.utils.timing import device_loop_time

N_RULES = 100_000
N_SERVICES = 5_000
B = 1 << 17
# Big enough that K_big - K_small cold iterations take O(100ms) on-device —
# the round-3 bitmap classifier runs ~7M pps cold, and a too-small cold
# workload lets dispatch jitter swamp the two-K differencing (observed as a
# nonsense clamped-at-zero elapsed time).
B_COLD = 1 << 15
K = 128
FLOW_SLOTS = 1 << 22
MISS_CHUNK = 256
BASELINE_PPS = 10e6
# Churn regime (round-4 verdict weak #2): universe == slots, 1/CHURN_DIV
# of each batch are fresh flows.
CHURN_POOL = 1 << 22
CHURN_DIV = 8
# Multichip regime (round-9 tentpole, ROADMAP item 1): aggregate pps over
# a full data-parallel mesh + a rule-sharded capacity point.  The
# acceptance target is >150M pps aggregate on v5e-8; the capacity point
# compiles PAST the single-chip bench scale (the word-axis sharding is
# what buys the headroom, parallel/mesh.py HBM math).
MC_TARGET_PPS = 150e6
MC_CAP_RULES = 150_000
# CPU smoke shapes (--force-host-devices / virtual-CPU platforms): prove
# the regime end-to-end with toy worlds, emitting the same JSON keys.
MC_RULES_SMOKE = 400
MC_CAP_RULES_SMOKE = 1_000


def measure_cold(drs, match_meta, src, dst, proto, dport):
    """All-miss classification pps: the conjunctive-match kernel alone, no
    flow-cache credit (VERDICT round 1 weak #4 — the steady-state number
    measures the cache; this measures classification at full rule count)."""
    s = src[:B_COLD]
    d = dst[:B_COLD]
    p = proto[:B_COLD]
    dp = dport[:B_COLD]

    def body(i, carry):
        # acc leads the carry: device_loop_time fetches the FIRST leaf to
        # detect completion, so it must be one that changes every iteration.
        # drs rides in the carry, NOT the closure: closure-captured device
        # arrays lower to HLO constants, and ~1GB of incidence tables
        # overflows the remote-compile request on the tunneled platform.
        acc, drs_, s_, d_, p_, dp_ = carry
        # Carry-dependent perturbation so XLA cannot hoist the classify out
        # of the loop as loop-invariant.
        dp2 = dp_ ^ (acc[0] & 1)
        # fused=True: the pallas consumer path (ops/match cold-path study).
        cls = classify_batch(drs_, s_, d_, p_, dp2, meta=match_meta,
                             fused=True)
        acc = acc.at[:1].add(cls["code"].sum(dtype=jnp.int32))
        return (acc, drs_, s_, d_, p_, dp_)

    carry = (jnp.zeros(8, jnp.int32), drs, s, d, p, dp)
    sec = device_loop_time(body, carry, k_small=8, k_big=64, repeats=4)
    return B_COLD / sec


# Round-7 prune regime: the K budget the cold_pruned_pps extra measures
# at (bench_cold_study.py case 6 sweeps the full ladder).
PRUNE_K = 4


def measure_cold_pruned(cps, src, dst, proto, dport):
    """All-miss classification pps through the TWO-LEVEL pruned kernel
    (ops/match round 7, prune_budget=PRUNE_K, fused consumer) plus the
    honest fallback/skip rates measured on the same traffic — reported
    BESIDE cold_classify_pps, never replacing it (r05 -> r06 key
    comparability; a pruned number without its fallback rate would hide
    the exactness cost)."""
    try:
        from antrea_tpu.ops.match import to_device

        drs_p, meta_p = to_device(cps, prune_budget=PRUNE_K)
        s = src[:B_COLD]
        d = dst[:B_COLD]
        p = proto[:B_COLD]
        dp = dport[:B_COLD]

        def body(i, carry):
            acc, drs_, s_, d_, p_, dp_ = carry
            dp2 = dp_ ^ (acc[0] & 1)
            cls = classify_batch(drs_, s_, d_, p_, dp2, meta=meta_p,
                                 fused=True)
            acc = acc.at[:1].add(cls["code"].sum(dtype=jnp.int32))
            return (acc, drs_, s_, d_, p_, dp_)

        carry = (jnp.zeros(8, jnp.int32), drs_p, s, d, p, dp)
        sec = device_loop_time(body, carry, k_small=8, k_big=64, repeats=4)
        cls = classify_batch(drs_p, s, d, p, dp, meta=meta_p, fused=True)
        fb_rate = float(np.asarray(cls["prune_fb"]).mean())
        skip_rate = float(np.asarray(cls["prune_skip"]).mean())
        return B_COLD / sec, fb_rate, skip_rate
    except Exception as e:  # report, never sink the bench
        print(f"# pruned cold measurement failed: {e}", flush=True)
        return None, None, None


def measure_fused(cps, svc, src, dst, proto, sport, dport):
    """The round-8 ONE-KERNEL fast path (fused=True + prune_budget=
    PRUNE_K -> meta.onepass): steady_fused_pps is the warmed all-hit
    regime (the fused instance's fast path + the zero-miss skip), and
    cold_fused_pps drives every batch all-miss through the one-pass
    kernel by expiring the cache between iterations (each step therefore
    pays probe + LB + aggregate prune + candidate DMA + resolve +
    commit-row packing + the insert-over-dead reclaim — the full fused
    slow path, commit scatters included, which the staged cold numbers
    never paid in one dispatch).  Reported BESIDE the unchanged
    r05-comparable keys."""
    try:
        step, state, (drs, dsvc) = pl.make_pipeline(
            cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=MISS_CHUNK,
            fused=True, prune_budget=PRUNE_K, ct_timeout_s=3600,
        )
        assert step.meta.onepass
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(100), jnp.int32(0))
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(101), jnp.int32(0))

        def body_steady(i, carry):
            acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
            st, o = pl._pipeline_step(
                st, drs_, dsvc_, s_, d_, p_, sp_, dp_, 102 + i, 0,
                meta=step.meta,
            )
            acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
            return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

        carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, src, dst,
                 proto, sport, dport)
        sec = device_loop_time(body_steady, carry, k_small=8, k_big=K,
                               repeats=3)
        steady = B / sec

        def body_cold(i, carry):
            # A 2*timeout jump per iteration expires every cached entry:
            # each batch re-misses wholesale and walks the one-pass
            # kernel end to end.
            acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
            now_i = 7200 * (i + 2) + acc[0] % 2
            st, o = pl._pipeline_step(
                st, drs_, dsvc_, s_, d_, p_, sp_, dp_, now_i, 0,
                meta=step.meta,
            )
            acc = acc.at[:1].add(o["n_miss"])
            return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

        carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, src, dst,
                 proto, sport, dport)
        sec_c = device_loop_time(body_cold, carry, k_small=4, k_big=16,
                                 repeats=3)
        return steady, B / sec_c
    except Exception as e:  # report, never sink the bench
        print(f"# fused one-pass measurement failed: {e}", flush=True)
        return None, None


def measure_telemetry(cps, svc, src, dst, proto, sport, dport):
    """Telemetry-overhead line (observability/telemetry.py): the HEADLINE
    steady regime with the in-kernel counters compiled IN
    (telemetry=True) — same fused instance, same warmed all-hit loop —
    so the on/off cost of the counter outputs is a pinned number beside
    the unchanged keys.  The counters are a handful of masked reductions
    over values the step already gathers, so this should sit within
    noise of the headline; a real gap here fails the near-zero-cost
    claim before a rollout ships it."""
    try:
        step, state, (drs, dsvc) = pl.make_pipeline(
            cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=MISS_CHUNK,
            fused=True, telemetry=True,
        )
        assert step.meta.telemetry
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(100), jnp.int32(0))
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(101), jnp.int32(0))

        def body(i, carry):
            acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
            st, o = pl._pipeline_step(
                st, drs_, dsvc_, s_, d_, p_, sp_, dp_, 102 + i, 0,
                meta=step.meta,
            )
            acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32)
                                 + o["n_miss"] + o["tel_probe_hit"])
            return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

        carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, src, dst,
                 proto, sport, dport)
        sec = device_loop_time(body, carry, k_small=8, k_big=K, repeats=3)
        return B / sec
    except Exception as e:  # report, never sink the bench
        print(f"# telemetry overhead measurement failed: {e}", flush=True)
        return None


def measure_churn(cps, svc, pod_ips, services):
    """Steady-state throughput UNDER EVICTION PRESSURE (round-4 verdict
    weak #2: the headline is a never-miss cache number).  Flow universe ==
    flow slots (2^22 into 2^22 — kernel-conntrack-at-capacity, megaflow
    revalidation pressure), with a churn mix: CHURN_FRAC of every batch
    are fresh flows from a rolling window over the universe (flow
    arrivals), the rest a fixed hot set (established traffic).  Fresh
    lanes take the slow path AND evict live entries (direct-mapped
    collisions), so this number pays classification + eviction + commit
    every step — a real deployment sits between this and the headline."""
    try:
        return _measure_churn(cps, svc, pod_ips, services)
    except Exception as e:  # report, never sink the bench
        print(f"# churn measurement failed: {e}", flush=True)
        return None


def _measure_churn(cps, svc, pod_ips, services):
    hot = gen_traffic(pod_ips, B, n_flows=1 << 15, seed=31,
                      services=services, svc_fraction=0.3)
    # The churn pool: one packet per universe flow, drawn without repeats
    # (a zipf draw would re-hit its head flows in every window and
    # under-state the miss fraction).
    pool = gen_traffic(pod_ips, CHURN_POOL, n_flows=CHURN_POOL, seed=32,
                       services=services, svc_fraction=0.3,
                       one_per_flow=True)
    n_new = B // CHURN_DIV  # fresh flows per batch

    def col(hot_c, pool_c):
        return jnp.asarray(np.ascontiguousarray(hot_c)), jnp.asarray(
            np.ascontiguousarray(pool_c))

    hs, ps_ = col(iputil.flip_u32(hot.src_ip), iputil.flip_u32(pool.src_ip))
    hd, pd = col(iputil.flip_u32(hot.dst_ip), iputil.flip_u32(pool.dst_ip))
    hp, pp = col(hot.proto, pool.proto)
    hsp, psp = col(hot.src_port, pool.src_port)
    hdp, pdp = col(hot.dst_port, pool.dst_port)

    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=4096, fused=True
    )
    # Warm the hot set.
    state, _ = step(state, drs, dsvc, hs, hd, hp, hsp, hdp,
                    jnp.int32(100), jnp.int32(0))
    state, _ = step(state, drs, dsvc, hs, hd, hp, hsp, hdp,
                    jnp.int32(101), jnp.int32(0))

    def body(i, carry):
        (acc, st, drs_, dsvc_, hs_, hd_, hp_, hsp_, hdp_,
         ps2, pd2, pp2, psp2, pdp2) = carry
        # Rolling fresh-flow window: each step consumes the next n_new
        # pool flows (wraps after CHURN_POOL / n_new steps — far beyond
        # the measurement horizon).
        off = (acc[1] * n_new) % (CHURN_POOL - n_new)
        def mix(hcol, pcol):
            fresh = jax.lax.dynamic_slice(pcol, (off,), (n_new,))
            return jnp.concatenate([hcol[: B - n_new], fresh])
        st, o = pl._pipeline_step(
            st, drs_, dsvc_, mix(hs_, ps2), mix(hd_, pd2), mix(hp_, pp2),
            mix(hsp_, psp2), mix(hdp_, pdp2), 102 + i, 0, meta=step.meta,
        )
        acc = acc.at[0].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
        acc = acc.at[1].add(1)
        return (acc, st, drs_, dsvc_, hs_, hd_, hp_, hsp_, hdp_,
                ps2, pd2, pp2, psp2, pdp2)

    carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, hs, hd, hp, hsp,
             hdp, ps_, pd, pp, psp, pdp)
    sec = device_loop_time(body, carry, k_small=4, k_big=32, repeats=2)
    return B / sec


def measure_churn_async(cps, svc, pod_ips, services):
    """Churn regime under the ASYNC slow-path engine (datapath/slowpath):
    the same universe/fresh-fraction shape as measure_churn, but each step
    is one decoupled FAST dispatch (phases=0 — the n_new fresh lanes are
    admitted, not classified) plus one COALESCED drain dispatch over
    exactly that window (miss_chunk == n_new: a SINGLE slow-path round
    instead of the sync path's n_new/4096 sequential rounds — the
    amortization the PR-2 phase profiler motivated).  Also runs the
    bounded miss queue at the measured cadence on the host and reports
    its overflow count — the number that tells an operator whether this
    drain rate keeps up with this arrival rate.
    -> (async_churn_pps, miss_queue_overflows), (None, None) on failure."""
    try:
        return _measure_churn_async(cps, svc, pod_ips, services)
    except Exception as e:  # report, never sink the bench
        print(f"# async churn measurement failed: {e}", flush=True)
        return None, None


# --- the async-cadence churn regimes: one scaffold, three bodies -----------

# Traced per-iteration context handed to a regime body: the device rule
# tables, the loop index, the completed-iteration counter (acc[1]),
# window i's fresh columns (and the hot batch with them spliced into its
# tail), and the window() maker for regimes that need a second offset
# (overlap's window i-1).
_ChurnIter = namedtuple(
    "_ChurnIter", ["drs", "dsvc", "i", "n", "fresh", "mixed", "window"])


def _count(acc, out):
    return acc.at[0].add(out["code"].sum(dtype=jnp.int32) + out["n_miss"])


def _churn_regime_pps(cps, svc, pod_ips, services, make_body):
    """Shared scaffold of the three async-cadence churn regimes
    (_measure_churn_async / _measure_churn_overlap /
    _measure_churn_maintenance): hot+pool column prep, the
    single-compile pipeline, two cache-warm steps, the rolling
    fresh-flow window, and the timed device loop.  `make_body(meta)`
    returns the regime's per-iteration body
    `run(st, acc, it: _ChurnIter) -> (st, acc)` — the regimes differ
    ONLY in that body; change the scaffold here, never by copying it."""
    hot = gen_traffic(pod_ips, B, n_flows=1 << 15, seed=31,
                      services=services, svc_fraction=0.3)
    pool = gen_traffic(pod_ips, CHURN_POOL, n_flows=CHURN_POOL, seed=32,
                       services=services, svc_fraction=0.3,
                       one_per_flow=True)
    n_new = B // CHURN_DIV

    def col(hot_c, pool_c):
        return jnp.asarray(np.ascontiguousarray(hot_c)), jnp.asarray(
            np.ascontiguousarray(pool_c))

    hs, ps_ = col(iputil.flip_u32(hot.src_ip), iputil.flip_u32(pool.src_ip))
    hd, pd = col(iputil.flip_u32(hot.dst_ip), iputil.flip_u32(pool.dst_ip))
    hp, pp = col(hot.proto, pool.proto)
    hsp, psp = col(hot.src_port, pool.src_port)
    hdp, pdp = col(hot.dst_port, pool.dst_port)

    # The drain chunk is plumbed through make_pipeline (round-6
    # satellite): warm steps and the coalesced drain share ONE compiled
    # miss_chunk == n_new program, instead of compiling a throwaway
    # 4096-chunk variant and then a second one via meta._replace.
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=n_new, fused=True
    )
    run = make_body(step.meta)
    state, _ = step(state, drs, dsvc, hs, hd, hp, hsp, hdp,
                    jnp.int32(100), jnp.int32(0))
    state, _ = step(state, drs, dsvc, hs, hd, hp, hsp, hdp,
                    jnp.int32(101), jnp.int32(0))

    def body(i, carry):
        (acc, st, drs_, dsvc_, hs_, hd_, hp_, hsp_, hdp_,
         ps2, pd2, pp2, psp2, pdp2) = carry
        pcols = (ps2, pd2, pp2, psp2, pdp2)

        def window(off):
            return tuple(jax.lax.dynamic_slice(c, (off,), (n_new,))
                         for c in pcols)

        # Rolling fresh-flow window: each step consumes the next n_new
        # pool flows (wraps after CHURN_POOL / n_new steps — far beyond
        # the measurement horizon).
        fresh = window((acc[1] * n_new) % (CHURN_POOL - n_new))
        mixed = tuple(jnp.concatenate([h[: B - n_new], f]) for h, f in
                      zip((hs_, hd_, hp_, hsp_, hdp_), fresh))
        st, acc = run(st, acc, _ChurnIter(drs_, dsvc_, i, acc[1], fresh,
                                          mixed, window))
        acc = acc.at[1].add(1)
        return (acc, st, drs_, dsvc_, hs_, hd_, hp_, hsp_, hdp_, *pcols)

    carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, hs, hd, hp, hsp,
             hdp, ps_, pd, pp, psp, pdp)
    sec = device_loop_time(body, carry, k_small=4, k_big=32, repeats=2)
    return B / sec


def _measure_churn_async(cps, svc, pod_ips, services):
    def make_body(meta):
        meta_fast = meta._replace(phases=0)

        def run(st, acc, it):
            # Decoupled fast step: hot lanes hit, fresh lanes admitted.
            st, o = pl._pipeline_step(
                st, it.drs, it.dsvc, *it.mixed, 102 + it.i, 0,
                meta=meta_fast,
            )
            # Coalesced drain of exactly this step's admissions.
            st, od = pl._pipeline_step(
                st, it.drs, it.dsvc, *it.fresh, 102 + it.i, 0, meta=meta,
            )
            return st, _count(_count(acc, o), od)

        return run

    pps = _churn_regime_pps(cps, svc, pod_ips, services, make_body)

    # Bounded-queue accounting at the BENCHED cadence, run through the
    # real MissQueue (default capacity 2^16): n_new arrivals + one
    # full-window drain per step.  At this cadence the count is zero by
    # construction (drain keeps pace with arrival and capacity >= n_new)
    # — reported so the field exists and so a future cadence change
    # (drain_batch < n_new, smaller capacity) surfaces here instead of
    # silently claiming zero pressure.
    from antrea_tpu.datapath.slowpath import MissQueue

    n_new = B // CHURN_DIV
    q = MissQueue(1 << 16)
    zeros = {k: np.zeros(n_new, np.int64) for k in
             ("src_ip", "dst_ip", "proto", "src_port", "dst_port",
              "flags", "lens")}
    mask = np.ones(n_new, bool)
    for t in range(64):
        q.admit(zeros, mask, epoch=t, now=t)
        q.pop(n_new)
    return pps, q.overflows_total


def measure_churn_maintenance(cps, svc, pod_ips, services):
    """Churn regime with the unified maintenance scheduler's cadence
    riding it (datapath/maintenance.py, ROADMAP item 5): the async
    fast+drain cadence of measure_churn_async plus ONE fused full-table
    maintenance pass (pl.maintain_scan — the cache-maintain task) per
    step.  Diffed against async_churn_pps this prices the consolidated
    background plane at its most aggressive cadence (every step; the
    scheduler's default runs it far less often), so the reported
    maintenance_overhead_pct is an UPPER bound — r07's "the
    consolidation is free" claim.  -> steady_churn_maint_pps, None on
    failure."""
    try:
        return _measure_churn_maintenance(cps, svc, pod_ips, services)
    except Exception as e:  # report, never sink the bench
        print(f"# maintenance churn measurement failed: {e}", flush=True)
        return None


def _measure_churn_maintenance(cps, svc, pod_ips, services):
    def make_body(meta):
        meta_fast = meta._replace(phases=0)

        def run(st, acc, it):
            st, o = pl._pipeline_step(
                st, it.drs, it.dsvc, *it.mixed, 102 + it.i, 0,
                meta=meta_fast,
            )
            st, od = pl._pipeline_step(
                st, it.drs, it.dsvc, *it.fresh, 102 + it.i, 0, meta=meta,
            )
            acc = _count(_count(acc, o), od)
            # The maintenance rider: the scheduler's fused aging +
            # stale-generation revalidation pass (cost-only here: gen is
            # constant and `now` advances 1/step against hour timeouts).
            st, n_aged, n_stale = pl._maintain_scan(
                st, jnp.int32(102 + it.i), jnp.int32(0),
                timeouts=meta.timeouts,
            )
            return st, acc.at[0].add(n_aged + n_stale)

        return run

    return _churn_regime_pps(cps, svc, pod_ips, services, make_body)


def measure_churn_overlap(cps, svc, pod_ips, services):
    """Churn regime under the OVERLAPPED datapath (round-6 tentpole,
    ROADMAP item 2): the same universe/fresh-fraction shape as
    measure_churn_async, but double-buffered — iteration i dispatches the
    decoupled FAST step over window i's mixed batch and then the
    coalesced drain of window i-1 (the two-slot deferred-commit staging
    of datapath/slowpath).  The deferred drain has no data dependency on
    the fast step's outputs, so XLA can pipeline the two dispatches
    instead of serializing miss-detect -> drain -> commit -> evict behind
    the fast path (the ~3x gap bench_profile attributed to pure
    serialization).  The drain runs at drain_reclaim=True, folding the
    eviction/aging maintenance into the commit pass.  Window i's verdicts
    become visible to window i+1's lookups via the carried state — the
    lost-update guard, and exactly the engine's production overlap
    semantics.  -> steady_churn_overlap_pps, None on failure."""
    try:
        return _measure_churn_overlap(cps, svc, pod_ips, services)
    except Exception as e:  # report, never sink the bench
        print(f"# overlap churn measurement failed: {e}", flush=True)
        return None


def _measure_churn_overlap(cps, svc, pod_ips, services):
    n_new = B // CHURN_DIV

    def make_body(meta):
        meta_fast = meta._replace(phases=0)
        meta_drain = meta._replace(drain_reclaim=True)

        def run(st, acc, it):
            # Decoupled fast step of window i: hot lanes hit, fresh
            # admitted.
            st, o = pl._pipeline_step(
                st, it.drs, it.dsvc, *it.mixed, 102 + it.i, 0,
                meta=meta_fast,
            )
            # Deferred drain of window i-1 — the one-step commit
            # deferral: no dependency on o, only on st.  Iteration 0
            # re-drains window 0 (already-committed lanes re-classify
            # identically; one warmup-shaped iteration in a 32-step
            # loop).
            prev = it.window(
                (jnp.maximum(it.n - 1, 0) * n_new) % (CHURN_POOL - n_new))
            st, od = pl._pipeline_step(
                st, it.drs, it.dsvc, *prev, 102 + it.i, 0,
                meta=meta_drain,
            )
            return st, _count(_count(acc, o), od)

        return run

    return _churn_regime_pps(cps, svc, pod_ips, services, make_body)


def measure_sharded_cold_fused(cps, src, dst, proto, dport):
    """Cold fused classification under a 1x1-mesh shard_map: the fused
    consumer is shard-aware (global word offsets ride word_idx), so the
    sharded walk keeps the cold-path win — this proves it ON the chip
    (round-4 weak #4; expected within noise of cold_classify_pps)."""
    from antrea_tpu.parallel import mesh as pm
    from jax.sharding import PartitionSpec as P

    try:
        mesh = pm.make_mesh(1, 1, devices=jax.devices()[:1])
        drs, meta = pm.shard_rule_set(cps, mesh)
        s, d = src[:B_COLD], dst[:B_COLD]
        p, dp = proto[:B_COLD], dport[:B_COLD]

        def cls_body(drs_, s_, d_, p_, dp_):
            return classify_batch(
                drs_, s_, d_, p_, dp_, meta=meta,
                hit_combine=pm._pmin_rule, fused=True,
            )

        # The version shim (capability probe) — a direct jax.shard_map
        # call broke on images that only carry the experimental module.
        sh = pm._shard_map(
            cls_body, mesh=mesh,
            in_specs=(pm._drs_specs(), P(pm.DATA), P(pm.DATA), P(pm.DATA),
                      P(pm.DATA)),
            out_specs=P(pm.DATA),
        )

        def body(i, carry):
            acc, drs_, s_, d_, p_, dp_ = carry
            dp2 = dp_ ^ (acc[0] & 1)
            cls = sh(drs_, s_, d_, p_, dp2)
            acc = acc.at[:1].add(cls["code"].sum(dtype=jnp.int32))
            return (acc, drs_, s_, d_, p_, dp_)

        carry = (jnp.zeros(8, jnp.int32), drs, s, d, p, dp)
        sec = device_loop_time(body, carry, k_small=8, k_big=64, repeats=2)
        return B_COLD / sec
    except Exception as e:
        print(f"# sharded-cold-fused measurement failed: {e}", flush=True)
        return None


def measure_shard_overhead(cps, svc, src, dst, proto, sport, dport, pps):
    """Steady-state throughput of the SAME datapath step under a 1x1-mesh
    shard_map on the real chip -> percent overhead of the SPMD scaffolding
    (round-3 verdict weak #3: quantify shard overhead on real hardware;
    multi-chip scaling itself is validated on the virtual mesh in
    tests/test_parallel_scale.py).  Timed with the same two-K device-loop
    differencing as the headline (async dispatch on the tunneled platform
    makes host-side timing loops meaningless)."""
    from antrea_tpu.parallel import mesh as pm

    try:
        mesh = pm.make_mesh(1, 1, devices=jax.devices()[:1])
        step, state, (drs, dsvc) = pm.make_sharded_pipeline(
            cps, svc, mesh, flow_slots=FLOW_SLOTS, miss_chunk=MISS_CHUNK,
        )
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(100), jnp.int32(0))
        state, _ = step(state, drs, dsvc, src, dst, proto, sport, dport,
                        jnp.int32(101), jnp.int32(0))

        def body(i, carry):
            acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
            st, o = step(st, drs_, dsvc_, s_, d_, p_, sp_, dp_,
                         102 + i, jnp.int32(0))
            acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32)
                                 + o["n_miss"].sum())
            return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

        carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, src, dst,
                 proto, sport, dport)
        sec = device_loop_time(body, carry, k_small=4, k_big=32, repeats=2)
        sh_pps = B / sec
        return round(sh_pps, 1), round((1 - sh_pps / pps) * 100, 1)
    except Exception as e:  # report, never sink the bench
        print(f"# shard-overhead measurement failed: {e}", flush=True)
        return None, None


def measure_multichip(cps=None, svc=None, pod_ips=None, services=None):
    """The round-9 multichip regime (ROADMAP item 1): REAL aggregate
    steady-state throughput of the full stateful sharded pipeline over
    every available device (data-parallel (D, 1) mesh, per-shard private
    flow caches), with scaling efficiency measured against a single-chip
    reference run of the SAME regime — not the dryrun.  Plus the
    rule-axis capacity point: cold classification of a >100k-rule set
    sharded over a (1, D) mesh (the word-axis sharding that buys HBM
    headroom past the single-chip ceiling).

    On accelerator pods this runs the bench world (100k rules); on CPU
    platforms (the --force-host-devices escape hatch) it swaps in toy
    worlds so the regime is smoke-testable in CI — same JSON keys,
    `smoke: true`.  -> the multichip JSON dict, or None (skipped/failed).
    """
    try:
        return _measure_multichip(cps, svc, pod_ips, services)
    except Exception as e:  # report, never sink the bench
        print(f"# multichip measurement failed: {e}", flush=True)
        return None


def _measure_multichip(cps, svc, pod_ips, services):
    from antrea_tpu.parallel import mesh as pm

    D = jax.device_count()
    if D < 2:
        print(f"# multichip regime skipped: need >= 2 devices, have {D}",
              flush=True)
        return None
    smoke = jax.devices()[0].platform == "cpu"
    if smoke:
        cluster = gen_cluster(MC_RULES_SMOKE, n_nodes=8, pods_per_node=8,
                              seed=41)
        cps = compile_policy_set(cluster.ps)
        services = gen_services(16, cluster.pod_ips, seed=42)
        svc = compile_services(services)
        pod_ips = cluster.pod_ips
        b_rep, slots, ks, kb, reps = 512, 1 << 12, 2, 8, 1
        cap_rules, fused = MC_CAP_RULES_SMOKE, False
    else:
        b_rep, slots, ks, kb, reps = 1 << 15, 1 << 20, 4, 32, 2
        cap_rules, fused = MC_CAP_RULES, True
    B_total = b_rep * D
    tr = gen_traffic(pod_ips, B_total, n_flows=max(256, B_total >> 3),
                     seed=43, services=services, svc_fraction=0.3)
    src = iputil.flip_u32(tr.src_ip)
    dst = iputil.flip_u32(tr.dst_ip)

    # -- data-parallel aggregate: the full stateful step over (D, 1) ------
    mesh = pm.make_mesh(D, 1)
    stepN, stN, (drsN, dsvcN) = pm.make_sharded_pipeline(
        cps, svc, mesh, flow_slots=slots, miss_chunk=MISS_CHUNK)
    for warm in (100, 101):
        stN, _ = stepN(stN, drsN, dsvcN, src, dst, tr.proto, tr.src_port,
                       tr.dst_port, jnp.int32(warm), jnp.int32(0))

    def bodyN(i, carry):
        acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
        st, o = stepN(st, drs_, dsvc_, s_, d_, p_, sp_, dp_,
                      102 + i, jnp.int32(0))
        acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32)
                             + o["n_miss"].sum())
        return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

    carry = (jnp.zeros(8, jnp.int32), stN, drsN, dsvcN, src, dst, tr.proto,
             tr.src_port, tr.dst_port)
    sec = device_loop_time(bodyN, carry, k_small=ks, k_big=kb, repeats=reps)
    aggregate_pps = B_total / sec

    # -- single-chip reference of the SAME regime (honest efficiency) -----
    step1, st1, (drs1, dsvc1) = pl.make_pipeline(
        cps, svc, flow_slots=slots, miss_chunk=MISS_CHUNK)
    s1, d1 = jnp.asarray(src[:b_rep]), jnp.asarray(dst[:b_rep])
    p1 = jnp.asarray(tr.proto[:b_rep])
    sp1 = jnp.asarray(tr.src_port[:b_rep])
    dp1 = jnp.asarray(tr.dst_port[:b_rep])
    for warm in (100, 101):
        st1, _ = step1(st1, drs1, dsvc1, s1, d1, p1, sp1, dp1,
                       jnp.int32(warm), jnp.int32(0))

    def body1(i, carry):
        acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
        st, o = pl._pipeline_step(st, drs_, dsvc_, s_, d_, p_, sp_, dp_,
                                  102 + i, 0, meta=step1.meta)
        acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
        return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

    carry = (jnp.zeros(8, jnp.int32), st1, drs1, dsvc1, s1, d1, p1, sp1, dp1)
    sec1 = device_loop_time(body1, carry, k_small=ks, k_big=kb, repeats=reps)
    ref_pps = b_rep / sec1

    # -- rule-axis capacity point: >100k rules sharded over (1, D) --------
    capacity = None
    try:
        cl_cap = gen_cluster(cap_rules, n_nodes=32, pods_per_node=16,
                             seed=44)
        cps_cap = compile_policy_set(cl_cap.ps)
        mesh_r = pm.make_mesh(1, D)
        drs_r, meta_r = pm.shard_rule_set(cps_cap, mesh_r)
        b_cap = 2048 if smoke else B_COLD
        tc = gen_traffic(cl_cap.pod_ips, b_cap, n_flows=b_cap, seed=45)
        cs, cd = iputil.flip_u32(tc.src_ip), iputil.flip_u32(tc.dst_ip)

        def cls_body(drs_, s_, d_, p_, dp_):
            return classify_batch(drs_, s_, d_, p_, dp_, meta=meta_r,
                                  hit_combine=pm._pmin_rule, fused=fused)

        from jax.sharding import PartitionSpec as P

        sh = pm._shard_map(
            cls_body, mesh=mesh_r,
            in_specs=(pm._drs_specs(), P(pm.DATA), P(pm.DATA), P(pm.DATA),
                      P(pm.DATA)),
            out_specs=P(pm.DATA),
        )

        def body_cap(i, carry):
            acc, drs_, s_, d_, p_, dp_ = carry
            dp2 = dp_ ^ (acc[0] & 1)
            cls = sh(drs_, s_, d_, p_, dp2)
            acc = acc.at[:1].add(cls["code"].sum(dtype=jnp.int32))
            return (acc, drs_, s_, d_, p_, dp_)

        carry = (jnp.zeros(8, jnp.int32), drs_r, jnp.asarray(cs),
                 jnp.asarray(cd), jnp.asarray(tc.proto),
                 jnp.asarray(tc.dst_port))
        sec_cap = device_loop_time(body_cap, carry, k_small=2,
                                   k_big=8 if smoke else 64, repeats=reps)
        capacity = {
            "n_rules": int(cps_cap.ingress.n_rules + cps_cap.egress.n_rules),
            "rule_shards": D,
            "cold_classify_pps": round(b_cap / sec_cap, 1),
            # The term the rule axis divides (parallel/mesh.py HBM math):
            # each shard holds 1/D of the incidence words.
            "incidence_frac_per_shard": round(1.0 / D, 4),
        }
    except Exception as e:
        print(f"# rule-capacity point failed: {e}", flush=True)

    return {
        "metric": "multichip_aggregate_pps",
        "value": round(aggregate_pps, 1),
        "unit": "packets/s",
        "vs_target": round(aggregate_pps / MC_TARGET_PPS, 4),
        "extra": {
            "devices": D,
            "mesh": [D, 1],
            "batch_total": B_total,
            "batch_per_replica": b_rep,
            "per_chip_pps": round(aggregate_pps / D, 1),
            "singlechip_ref_pps": round(ref_pps, 1),
            # Aggregate over D chips vs D × the single-chip SAME-regime
            # reference: 1.0 = perfectly linear data-parallel scaling.
            "scaling_efficiency": round(aggregate_pps / (D * ref_pps), 4),
            "smoke": smoke,
            "rule_capacity": capacity,
        },
    }


# Multi-tenant regime (round-9 tentpole, ROADMAP item 5): aggregate pps
# across MT_TENANTS uneven tenant worlds packed into ONE engine on pow2
# rule-window rungs (datapath/tenancy.py).  The compile-sharing proof
# rides the extras: step executables grow with occupied rungs, never
# with tenant count.
MT_TENANTS = 64


def measure_multitenant():
    """The round-9 multi-tenant regime: MT_TENANTS isolated policy
    worlds — UNEVEN rule counts drawn over a few pow2 rungs — served
    round-robin by one TpuflowDatapath, measuring aggregate pps plus the
    per-tenant quota/eviction meters and the shared-compile evidence
    (XLA step executables vs occupied rungs).

    On CPU platforms the worlds are toy-sized so the regime is
    smoke-testable in CI — same JSON keys, `smoke: true`; the on-chip
    numbers are the driver's to write.  -> the JSON dict, or None."""
    try:
        return _measure_multitenant()
    except Exception as e:  # report, never sink the bench
        print(f"# multitenant measurement failed: {e}", flush=True)
        return None


def _measure_multitenant():
    import time

    from antrea_tpu.datapath.tpuflow import TpuflowDatapath
    from antrea_tpu.models import forwarding as fwd_model

    smoke = jax.devices()[0].platform == "cpu"
    rng = np.random.default_rng(71)
    # Uneven tenant sizes over a handful of rungs (zipf-ish: many small
    # worlds, a few heavy ones) — the SaaS shape the plane exists for.
    sizes = ((4, 7, 14, 28, 60) if smoke else (40, 90, 200, 450, 1000))
    weights = (0.35, 0.30, 0.18, 0.12, 0.05)
    rule_counts = rng.choice(sizes, size=MT_TENANTS, p=weights)
    quota = 1 << (8 if smoke else 12)
    dp = TpuflowDatapath(flow_slots=1 << 12, aff_slots=1 << 8,
                         canary_probes=8, flightrec_slots=256,
                         realization_slots=0)
    exec0 = fwd_model.pipeline_step_full._cache_size()
    t_build0 = time.perf_counter()
    tids = []
    for i, n in enumerate(rule_counts):
        cl = gen_cluster(int(n), n_nodes=2, pods_per_node=8, seed=300 + i)
        tids.append((dp.tenant_create(f"t{i}", cl.ps, quota=quota),
                     cl.pod_ips))
    build_s = time.perf_counter() - t_build0
    Bt = 256 if smoke else 4096
    batches = {
        tid: gen_traffic(pod_ips, Bt, n_flows=max(Bt // 2, 16),
                         seed=500 + tid)
        for tid, pod_ips in tids
    }
    t = 100
    for tid, _ in tids:  # warm round: each rung compiles once
        dp.tenant_step(tid, batches[tid], t)
    rounds = 2 if smoke else 8
    pkts = 0
    t0 = time.perf_counter()
    for _ in range(rounds):
        t += 1
        for tid, _ in tids:
            dp.tenant_step(tid, batches[tid], t)
            pkts += Bt
    dt = time.perf_counter() - t0
    execs = fwd_model.pipeline_step_full._cache_size() - exec0
    ts = dp.tenant_stats()
    return {
        "metric": "multitenant_aggregate_pps",
        "value": round(pkts / max(dt, 1e-9), 1),
        "unit": "packets/s",
        "extra": {
            "n_tenants": MT_TENANTS,
            "rule_count_min": int(min(rule_counts)),
            "rule_count_max": int(max(rule_counts)),
            # The shared-compile proof: occupied rung signatures vs XLA
            # step executables — both must sit far under tenant count
            # (tier-1 asserts equality; here they are the honest record).
            "rule_rungs_occupied": len(dp.tenant_rungs()),
            "step_executables": int(execs),
            "world_build_s": round(build_s, 3),
            "per_tenant_batch": Bt,
            "rounds": rounds,
            "quota_slots": quota,
            "evictions_total": sum(r["evictions_total"]
                                   for r in ts.values()),
            "quota_clamps_total": sum(r["quota_clamps_total"]
                                      for r in ts.values()),
            "occupied_rows_total": sum(r["occupied"] for r in ts.values()),
            "smoke": smoke,
        },
    }


def measure_multitenant_reshard():
    """The round-20 tenant-elasticity regime: MT_TENANTS uneven tenant
    worlds live on one failover-armed `MeshDatapath` while the data
    axis grows 2->4 under round-robin traffic, then a replica is killed
    and the PR 19 quarantine auto-proceeds to a certified skip-replica
    evacuation 4->3 with the worlds still serving — measuring tenant
    migration throughput (rows/s across every world's `_world_ctx`
    walk), per-world cutover certify latency (maintenance ticks from
    resize begin to each `tenant-reshard-cutover`), and per-tenant
    established-flow continuity across both flips.

    On CPU platforms the worlds are toy-sized so the regime is
    smoke-testable in CI — same JSON keys, `smoke: true`; the on-chip
    numbers are the driver's to write.  -> the JSON dict, or None."""
    try:
        return _measure_multitenant_reshard()
    except Exception as e:  # report, never sink the bench
        print(f"# multitenant reshard measurement failed: {e}", flush=True)
        return None


def _measure_multitenant_reshard():
    import time

    from antrea_tpu.dissemination.faults import FaultPlan
    from antrea_tpu.parallel import MeshDatapath

    D = jax.device_count()
    if D < 4:
        print(f"# multitenant reshard regime skipped: need >= 4 devices, "
              f"have {D}", flush=True)
        return None
    smoke = jax.devices()[0].platform == "cpu"
    rng = np.random.default_rng(79)
    # The measure_multitenant SaaS shape: many small worlds, a few heavy
    # ones — all on one quota rung so the windows share executables
    # before, during and after every resize.
    sizes = ((4, 7, 14, 28) if smoke else (40, 90, 200, 450))
    rule_counts = rng.choice(sizes, size=MT_TENANTS,
                             p=(0.40, 0.30, 0.20, 0.10))
    cluster = gen_cluster(40 if smoke else 2000, n_nodes=4,
                          pods_per_node=8, seed=61)
    services = gen_services(8, cluster.pod_ips, seed=62)
    dp = MeshDatapath(cluster.ps, services, n_data=2, n_rule=1,
                      flow_slots=1 << (8 if smoke else 16),
                      aff_slots=1 << 8, canary_probes=8,
                      flightrec_slots=4096, reshard_budget=512,
                      failover=True,
                      failover_knobs=dict(probe_fails=2, readmit_passes=2,
                                          retry_ticks=2))
    quota = 1 << (6 if smoke else 12)
    # Lane counts must divide every topology the arc serves (2, 4 and
    # the post-evacuation 3) — multiples of 12.
    Bt = 48 if smoke else 1536
    t_build0 = time.perf_counter()
    tids, tbs = [], {}
    for i, n in enumerate(rule_counts):
        cl = gen_cluster(int(n), n_nodes=2, pods_per_node=6, seed=700 + i)
        tid = dp.tenant_create(f"t{i}", cl.ps, quota=quota)
        tids.append(tid)
        tbs[tid] = gen_traffic(cl.pod_ips, Bt, n_flows=max(Bt // 2, 16),
                               seed=900 + i)
    build_s = time.perf_counter() - t_build0
    tr = gen_traffic(cluster.pod_ips, Bt, n_flows=Bt // 2, seed=63,
                     services=services, svc_fraction=0.3)

    # Establish flows in every world; the synchronous slow path commits
    # in-step, so the second pass serves established with pinned codes.
    t = 100
    dp.step(tr, t)
    for tid in tids:
        dp.tenant_step(tid, tbs[tid], t)
    t += 1
    est0, code0 = {}, {}
    for tid in tids:
        r = dp.tenant_step(tid, tbs[tid], t)
        est0[tid] = np.asarray(r.est).astype(bool).copy()
        code0[tid] = np.asarray(r.code).copy()

    def drive(done, t, label):
        """Round-robin serve — ONE world (the default world or a tenant,
        rotating) per maintenance tick — until done(); -> (t, wall
        seconds)."""
        i, n1, t0 = 0, len(tids) + 1, time.perf_counter()
        while not done():
            if i % n1 == 0:
                dp.step(tr, t)
            else:
                tid = tids[i % n1 - 1]
                dp.tenant_step(tid, tbs[tid], t)
            dp.maintenance_tick(now=t)
            t += 1
            i += 1
            if t > 1 << 20:
                raise RuntimeError(f"{label} did not converge")
        return t, time.perf_counter() - t0

    def continuity(t):
        """Per-tenant continuity across a flip: every lane keeps its
        pre-resize verdict bitwise, and est retention = established
        lanes still serving est (skip-replica evacuation re-misses the
        dead replica's rows by design — they re-commit on the next
        serve, verdict-identical, then re-establish)."""
        kept = total = 0
        ok = True
        for tid in tids:
            r = dp.tenant_step(tid, tbs[tid], t)
            ok = ok and bool((np.asarray(r.code) == code0[tid]).all())
            now_est = np.asarray(r.est).astype(bool)
            kept += int(now_est[est0[tid]].sum())
            total += int(est0[tid].sum())
        return ok, round(kept / max(total, 1), 4)

    def certify_ticks(begin_t, gen):
        """Per-world cutover certify latency: ticks from the resize
        begin to each world's own tenant-reshard-cutover (its canary
        certification landing).  Keyed by generation so a wrapped
        flight-recorder ring degrades the sample, never mixes flips."""
        at = sorted(e["at"] - begin_t for e in dp.flightrecorder_events()
                    if e["kind"] == "tenant-reshard-cutover"
                    and e["topo_gen"] == gen)
        if not at:
            return {"worlds": 0}
        return {"worlds": len(at), "p50_ticks": int(at[len(at) // 2]),
                "max_ticks": int(at[-1])}

    # -- grow 2 -> 4 with every world live ---------------------------------
    st0 = dp.reshard_stats()
    grow_begin = t
    dp.reshard_begin(4)
    t, dt_g = drive(lambda: dp.reshard_status() is None, t, "grow")
    st1 = dp.reshard_stats()
    if st1["aborts_total"] != st0["aborts_total"] or dp._n_data != 4:
        raise RuntimeError(f"tenanted grow aborted instead of cutting "
                           f"over: {st1}")
    rows_g = st1["tenant_rows_total"] - st0["tenant_rows_total"]
    grow_cert = certify_ticks(grow_begin, dp._topo_gen)
    grow_ok, grow_kept = continuity(t)
    t += 1

    # -- failover-evacuate 4 -> 3: kill a replica; quarantine proceeds
    # to the certified evacuation shrink with the worlds still serving
    # (masked skip-replica ring until the flip).
    plan = FaultPlan(seed=83)
    plan.every("n0.replica_dead", 1, "r1", times=1 << 20)
    dp.arm_failover_faults(plan, "n0")
    evac_begin = t
    t, dt_e = drive(
        lambda: dp.failover_stats()["phase"] == "evacuated", t, "evacuate")
    st2 = dp.reshard_stats()
    if dp._n_data != 3:
        raise RuntimeError(f"evacuation did not land on 3 replicas: "
                           f"{dp.failover_stats()}")
    rows_e = st2["tenant_rows_total"] - st1["tenant_rows_total"]
    evac_cert = certify_ticks(evac_begin, dp._topo_gen)
    # One settle pass re-commits the dead replica's re-missed rows,
    # then measure: verdicts stay pinned, est coverage recovers.
    continuity(t)
    evac_ok, evac_kept = continuity(t + 1)

    total_rows, total_dt = rows_g + rows_e, dt_g + dt_e
    return {
        "metric": "multitenant_reshard_rows_per_s",
        "value": round(total_rows / max(total_dt, 1e-9), 1),
        "unit": "rows/s",
        "extra": {
            "devices": D,
            "n_tenants": MT_TENANTS,
            "rule_count_min": int(min(rule_counts)),
            "rule_count_max": int(max(rule_counts)),
            "world_build_s": round(build_s, 3),
            "grow": {"tenant_rows": int(rows_g),
                     "seconds": round(dt_g, 4),
                     "certify": grow_cert,
                     "verdict_continuity_ok": grow_ok,
                     "est_retention": grow_kept},
            "evacuate": {"tenant_rows": int(rows_e),
                         "seconds": round(dt_e, 4),
                         "certify": evac_cert,
                         "verdict_continuity_ok": evac_ok,
                         "est_retention": evac_kept},
            "tenant_vetoes_total": int(st2["tenant_vetoes_total"]),
            "topology_generation": int(dp._topo_gen),
            "smoke": smoke,
        },
    }


def measure_serving_batched():
    """The round-18 batched-serving regime: the same MT_TENANTS uneven
    worlds, but driven by `gen_bursty` trickle arrivals THROUGH the
    serving batcher — aggregate pps over the canonical pow2 ladder plus
    the batching-delay price (per-tenant p99 wait, seconds) and the
    compile evidence (XLA step executables vs rungs x ladder sizes).

    On CPU platforms the worlds are toy-sized so the regime is
    smoke-testable in CI — same JSON keys, `smoke: true`; the on-chip
    numbers are the driver's to write.  -> the JSON dict, or None."""
    try:
        return _measure_serving_batched()
    except Exception as e:  # report, never sink the bench
        print(f"# serving-batched measurement failed: {e}", flush=True)
        return None


def _measure_serving_batched():
    import time

    from antrea_tpu.datapath.tpuflow import TpuflowDatapath
    from antrea_tpu.models import forwarding as fwd_model
    from antrea_tpu.simulator.traffic import gen_bursty

    smoke = jax.devices()[0].platform == "cpu"
    rng = np.random.default_rng(73)
    n_tenants = 8 if smoke else MT_TENANTS
    sizes = ((4, 7, 14, 28, 60) if smoke else (40, 90, 200, 450, 1000))
    weights = (0.35, 0.30, 0.18, 0.12, 0.05)
    rule_counts = rng.choice(sizes, size=n_tenants, p=weights)
    ladder = (8, 32) if smoke else (16, 64, 256, 1024)
    dp = TpuflowDatapath(flow_slots=1 << 12, aff_slots=1 << 8,
                         canary_probes=8, flightrec_slots=256,
                         realization_slots=0,
                         serving_batcher=True, canonical_sizes=ladder,
                         flush_deadline=4)
    exec0 = fwd_model.pipeline_step_full._cache_size()
    tids = []
    pod_pool = None
    for i, n in enumerate(rule_counts):
        cl = gen_cluster(int(n), n_nodes=2, pods_per_node=8, seed=700 + i)
        tids.append(dp.tenant_create(f"b{i}", cl.ps, quota=1 << 8))
        pod_pool = pod_pool or cl.pod_ips
    n_ticks = 24 if smoke else 256
    sched = gen_bursty(pod_pool, n_ticks, tenants=len(tids),
                       burst_lanes=(8 if smoke else 64), seed=91)
    b = dp.serving_batcher()
    # Warm round: touch every (rung, ladder-size) pair once so the
    # timed loop measures serving, not tracing.
    warm = gen_bursty(pod_pool, 8, tenants=len(tids),
                      burst_lanes=(8 if smoke else 64), seed=92)
    now = 100.0
    for entry in warm:
        now += 1
        if entry is None:
            continue
        lane_tids, batch = entry
        dp.step_tenants(np.asarray([tids[int(t)] for t in lane_tids]),
                        batch, now)
    # Timed region runs the REAL serving loop: stage arrivals into the
    # rings, let depth-OR-deadline policy decide the flushes (the
    # step_tenants wrapper force-flushes, which would hide the wait).
    from antrea_tpu.datapath.tenancy import _sub_batch
    flushed0 = dp.serving_stats()["flushed_lanes"]
    t0 = time.perf_counter()
    for entry in sched:
        now += 1
        if entry is not None:
            lane_tids, batch = entry
            for t in np.unique(lane_tids):
                sel = np.nonzero(lane_tids == t)[0]
                b.submit(_sub_batch(batch, sel), now,
                         tenant=tids[int(t)], shed=False)
        b.tick_flush(now, 8)
    b.flush_all(now)
    dt = time.perf_counter() - t0
    pkts = dp.serving_stats()["flushed_lanes"] - flushed0
    tick_s = dt / max(n_ticks, 1)
    execs = fwd_model.pipeline_step_full._cache_size() - exec0
    st = dp.serving_stats()
    # Wait p99 in ticks per world, priced in wall seconds at the
    # measured tick cadence — the deadline knob's observable cost.
    p99_ticks = max((w["wait_p99_ticks"] for w in st["worlds"].values()),
                    default=0.0)
    return {
        "metric": "multitenant_batched_pps",
        "value": round(pkts / max(dt, 1e-9), 1),
        "unit": "packets/s",
        "extra": {
            "tenant_batch_p99_s": round(p99_ticks * tick_s, 6),
            "tenant_batch_p99_ticks": p99_ticks,
            "n_tenants": n_tenants,
            "canonical_sizes": list(ladder),
            "flush_depth": st["flush_depth"],
            "flush_deadline": st["flush_deadline"],
            "rule_rungs_occupied": len(dp.tenant_rungs()),
            "step_executables": int(execs),
            "compile_bound": len(dp.tenant_rungs()) * len(ladder),
            "submitted_lanes": st["submitted_lanes"],
            "padded_lanes": st["padded_lanes"],
            "dispatches": st["dispatches"],
            "flushes": st["flushes"],
            "busy_ticks": sum(e is not None for e in sched),
            "n_ticks": n_ticks,
            "smoke": smoke,
        },
    }


def measure_attack_floor(ps, services, pod_ips):
    """ROADMAP item 1's pinned-floor satellite: sustained engine pps
    under a pure SYN flood — gen_syn_flood's never-repeating 5-tuples
    make every lane a miss-queue admission, the cache structurally
    useless — with the full flood-defense stack ON: admission="drop"
    (queue-depth early shed), per-source-/24 token buckets and the
    second-chance flow cache.  Emitted beside cold_fused_pps: that is
    the COOPERATIVE all-miss number (one flow universe re-classified),
    this is the ADVERSARIAL one, so the gap between them is a pinned
    number instead of folklore.  -> the JSON dict, or None."""
    try:
        return _measure_attack_floor(ps, services, pod_ips)
    except Exception as e:  # report, never sink the bench
        print(f"# attack-floor measurement failed: {e}", flush=True)
        return None


def _measure_attack_floor(ps, services, pod_ips):
    import time

    from antrea_tpu.datapath.tpuflow import TpuflowDatapath
    from antrea_tpu.simulator.traffic import gen_syn_flood

    smoke = jax.devices()[0].platform == "cpu"
    Bf = 512 if smoke else B
    dp = TpuflowDatapath(
        ps, services,
        flow_slots=1 << (10 if smoke else 18), aff_slots=1 << 8,
        async_slowpath=True,
        miss_queue_slots=1 << (10 if smoke else 14),
        drain_batch=256,
        admission="drop",
        miss_source_rate=4.0, miss_source_burst=16,
        second_chance=True,
        canary_probes=8, flightrec_slots=256, realization_slots=0,
    )
    targets = list(pod_ips[: 1 << 8])
    seq = 0
    now = 100
    for _ in range(2):  # warm: compile the flood-shaped step + drain
        dp.step(gen_syn_flood(targets, Bf, start_seq=seq, seed=5), now)
        dp.maintenance_tick(now=now)
        seq += Bf
        now += 1
    rounds = 8 if smoke else 64
    t0 = time.perf_counter()
    for _ in range(rounds):
        # The production cadence: fast step (all-miss admission) plus
        # one maintenance tick (budgeted coalesced drains) per round.
        dp.step(gen_syn_flood(targets, Bf, start_seq=seq, seed=5), now)
        dp.maintenance_tick(now=now)
        seq += Bf
        now += 1
    dt = time.perf_counter() - t0
    st = dp.slowpath_stats()
    return {
        "metric": "attack_floor_pps",
        "value": round(rounds * Bf / max(dt, 1e-9), 1),
        "unit": "packets/s",
        "extra": {
            "flood_batch": Bf,
            "rounds": rounds,
            "admission": st["admission"],
            "queue_capacity": st["capacity"],
            "admitted_total": st["admitted_total"],
            "early_drops_total": st["early_drops_total"],
            "source_limited_total": st["source_limited_total"],
            "overflows_total": st["overflows_total"],
            "drained_total": st["drained_total"],
            "second_chance": True,
            "smoke": smoke,
        },
    }


def measure_reshard():
    """The round-8 elastic-mesh regime (ROADMAP item 3): a LIVE resize of
    the data axis — grow 2→4 then shrink 4→2 — executed on a serving
    `MeshDatapath` via the budgeted reshard-migrate maintenance task,
    measuring migration throughput (rows/s of the drain-and-migrate
    walk) and asserting established-flow continuity (bitwise verdict
    parity of the pre-resize hot set after each certified cutover).

    On CPU platforms (the --force-host-devices escape hatch) it runs a
    toy world so the regime is smoke-testable in CI — same JSON keys,
    `smoke: true`; the on-chip numbers are the driver's to write.
    -> the reshard JSON dict, or None (skipped/failed)."""
    try:
        return _measure_reshard()
    except Exception as e:  # report, never sink the bench
        print(f"# reshard measurement failed: {e}", flush=True)
        return None


def _measure_reshard():
    import time

    from antrea_tpu.parallel import MeshDatapath

    D = jax.device_count()
    if D < 4:
        print(f"# reshard regime skipped: need >= 4 devices, have {D}",
              flush=True)
        return None
    smoke = jax.devices()[0].platform == "cpu"
    cluster = gen_cluster(MC_RULES_SMOKE if smoke else 2000, n_nodes=8,
                          pods_per_node=8, seed=51)
    services = gen_services(8, cluster.pod_ips, seed=52)
    slots = 1 << (12 if smoke else 20)
    mdp = MeshDatapath(cluster.ps, services, n_data=2, n_rule=1,
                       flow_slots=slots, aff_slots=1 << 8,
                       canary_probes=16)
    B_r = 512 if smoke else 1 << 14
    tr = gen_traffic(cluster.pod_ips, B_r, n_flows=B_r // 2, seed=53,
                     services=services, svc_fraction=0.3)
    mdp.step(tr, 100)
    r0 = mdp.step(tr, 101)
    est0 = int(np.asarray(r0.est).sum())

    def resize(to, t):
        st0 = mdp.reshard_stats()
        mdp.reshard_begin(to)
        units = 0
        t0 = time.perf_counter()
        while mdp.reshard_status() is not None:
            out = mdp.maintenance_tick(now=t)
            units += out["ran"].get("reshard-migrate", 0)
            t += 1
            if t > 1 << 20:
                raise RuntimeError("reshard did not converge")
        st1 = mdp.reshard_stats()
        # An ABORT also ends the loop — and would then "pass" continuity
        # trivially (the old mesh kept serving).  The regime certifies a
        # CUTOVER: the generation must have advanced, cleanly.
        if (st1["aborts_total"] != st0["aborts_total"]
                or st1["topology_generation"]
                != st0["topology_generation"] + 1):
            raise RuntimeError(
                f"resize to {to} aborted instead of cutting over: {st1}")
        # Rows actually re-committed (the migration volume), distinct
        # from scheduler units spent (slots SCANNED + certify probes +
        # audit rows — the sparse-table scan cost, reported beside it).
        rows = st1["migrated_rows_total"] - st0["migrated_rows_total"]
        return rows, units, time.perf_counter() - t0, t

    def continuity(t):
        r = mdp.step(tr, t)
        return (bool((np.asarray(r.code) == np.asarray(r0.code)).all()
                     and int(np.asarray(r.est).sum()) > 0))

    rows_g, units_g, dt_g, t = resize(4, 102)
    grow_ok = continuity(t + 1)
    rows_s, units_s, dt_s, t = resize(2, t + 2)
    shrink_ok = continuity(t + 1)
    total_rows, total_dt = rows_g + rows_s, dt_g + dt_s
    return {
        "metric": "reshard_migration_rows_per_s",
        "value": round(total_rows / max(total_dt, 1e-9), 1),
        "unit": "rows/s",
        "extra": {
            "devices": D,
            "flow_slots_per_replica": slots,
            "grow": {"rows": int(rows_g), "scan_units": int(units_g),
                     "seconds": round(dt_g, 4), "continuity_ok": grow_ok},
            "shrink": {"rows": int(rows_s), "scan_units": int(units_s),
                       "seconds": round(dt_s, 4),
                       "continuity_ok": shrink_ok},
            "established_flows": est0,
            # The PR bar: every established flow serves its pre-resize
            # verdict bitwise after BOTH certified cutovers.
            "established_flow_continuity": bool(grow_ok and shrink_ok),
            "topology_generation": int(mdp._topo_gen),
            "smoke": smoke,
        },
    }


def main():
    cluster = gen_cluster(N_RULES, n_nodes=64, pods_per_node=32, seed=1)
    cps = compile_policy_set(cluster.ps)
    services = gen_services(N_SERVICES, cluster.pod_ips, seed=2)
    svc = compile_services(services)
    tr = gen_traffic(
        cluster.pod_ips, B, n_flows=1 << 15, seed=3,
        services=services, svc_fraction=0.3,
    )
    src = jnp.asarray(iputil.flip_u32(tr.src_ip))
    dst = jnp.asarray(iputil.flip_u32(tr.dst_ip))
    proto = jnp.asarray(tr.proto)
    sport = jnp.asarray(tr.src_port)
    dport = jnp.asarray(tr.dst_port)

    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=FLOW_SLOTS, miss_chunk=MISS_CHUNK, fused=True
    )
    # Warm: cold classify of the whole flow universe, then a cache-warm pass.
    state, out = step(state, drs, dsvc, src, dst, proto, sport, dport,
                      jnp.int32(100), jnp.int32(0))
    state, out = step(state, drs, dsvc, src, dst, proto, sport, dport,
                      jnp.int32(101), jnp.int32(0))

    def body(i, carry):
        # acc leads the carry (see measure_cold): in steady state the flow
        # cache keys never change, so they must not be the completion probe.
        acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_ = carry
        st, o = pl._pipeline_step(
            st, drs_, dsvc_, s_, d_, p_, sp_, dp_, 102 + i, 0,
            meta=step.meta,
        )
        acc = acc.at[:1].add(o["code"].sum(dtype=jnp.int32) + o["n_miss"])
        return (acc, st, drs_, dsvc_, s_, d_, p_, sp_, dp_)

    carry = (jnp.zeros(8, jnp.int32), state, drs, dsvc, src, dst, proto,
             sport, dport)
    # Two-K differencing cancels the dispatch+fetch round trip (~120ms on
    # the tunneled platform) out of the per-step time.
    sec_per_step = device_loop_time(body, carry, k_small=8, k_big=K, repeats=3)
    pps = B / sec_per_step
    cold_pps = measure_cold(drs, step.meta.match, src, dst, proto, dport)
    cold_pruned_pps, prune_fb_rate, prune_skip_rate = measure_cold_pruned(
        cps, src, dst, proto, dport
    )
    churn_pps = measure_churn(cps, svc, cluster.pod_ips, services)
    async_churn_pps, q_overflows = measure_churn_async(
        cps, svc, cluster.pod_ips, services
    )
    overlap_churn_pps = measure_churn_overlap(
        cps, svc, cluster.pod_ips, services
    )
    maint_churn_pps = measure_churn_maintenance(
        cps, svc, cluster.pod_ips, services
    )
    steady_fused_pps, cold_fused_pps = measure_fused(
        cps, svc, src, dst, proto, sport, dport
    )
    steady_telemetry_pps = measure_telemetry(
        cps, svc, src, dst, proto, sport, dport
    )
    attack_floor = measure_attack_floor(cluster.ps, services,
                                        cluster.pod_ips)
    sh_cold_pps = measure_sharded_cold_fused(cps, src, dst, proto, dport)
    sh_pps, sh_overhead = measure_shard_overhead(
        cps, svc, src, dst, proto, sport, dport, pps
    )
    multichip = measure_multichip(cps, svc, cluster.pod_ips, services)
    reshard = measure_reshard()
    multitenant = measure_multitenant()
    multitenant_reshard = measure_multitenant_reshard()
    serving_batched = measure_serving_batched()
    _print_and_gate(pps, cold_pps, sh_pps, sh_overhead, churn_pps,
                    sh_cold_pps, async_churn_pps, q_overflows,
                    overlap_churn_pps, maint_churn_pps,
                    multichip=multichip,
                    cold_pruned_pps=cold_pruned_pps,
                    prune_fb_rate=prune_fb_rate,
                    prune_skip_rate=prune_skip_rate,
                    steady_fused_pps=steady_fused_pps,
                    cold_fused_pps=cold_fused_pps,
                    steady_telemetry_pps=steady_telemetry_pps,
                    attack_floor=attack_floor,
                    reshard=reshard, multitenant=multitenant,
                    multitenant_reshard=multitenant_reshard,
                    serving_batched=serving_batched)


# Regression floors (round-3 verdict weak #6: a silent 10x perf regression
# must fail loud).  Set ~30% under the recorded numbers (steady 17.9M, cold
# 4.6-5.2M) to ride out the tunneled platform's run-to-run jitter (±15%)
# while catching any real regression.  The JSON line prints BEFORE the
# gate so the driver always records the measurement.
STEADY_FLOOR_PPS = 12e6
COLD_FLOOR_PPS = 3.2e6
# Churn-regime floor: calibrated from the round-5 measurement (5.14M pps
# @ universe=slots=2^22, 1/8 genuinely-fresh flows per batch — the
# permutation pool; a zipf pool re-hits its head and inflated this to
# 12.6M) with the same ~30%-under-jitter margin as the others.
CHURN_FLOOR_PPS = 3.5e6


def _print_and_gate(pps, cold_pps, sh_pps=None, sh_overhead=None,
                    churn_pps=None, sh_cold_pps=None,
                    async_churn_pps=None, q_overflows=None,
                    overlap_churn_pps=None, maint_churn_pps=None,
                    multichip=None, cold_pruned_pps=None,
                    prune_fb_rate=None, prune_skip_rate=None,
                    steady_fused_pps=None, cold_fused_pps=None,
                    steady_telemetry_pps=None, attack_floor=None,
                    reshard=None, multitenant=None,
                    multitenant_reshard=None, serving_batched=None):
    maint_overhead_pct = None
    if maint_churn_pps and async_churn_pps:
        maint_overhead_pct = round(
            (async_churn_pps - maint_churn_pps) / async_churn_pps * 100, 2)
    print(json.dumps({
        "metric": f"classified_pkts_per_sec_chip_{N_RULES // 1000}k_rules",
        "value": round(pps, 1),
        "unit": "packets/s",
        "vs_baseline": round(pps / BASELINE_PPS, 4),
        "extra": {
            "cold_classify_pps": round(cold_pps, 1),
            "cold_vs_baseline": round(cold_pps / BASELINE_PPS, 4),
            "steady_batch": B,
            "cold_batch": B_COLD,
            "n_rules": N_RULES,
            "n_services": N_SERVICES,
            # Eviction-pressure regime: universe == slots (2^22), 1/8 of
            # every batch fresh flows — classification + eviction + commit
            # every step.  A deployment sits between this and the
            # headline (never-miss) number.
            "steady_churn_pps": None if churn_pps is None
            else round(churn_pps, 1),
            # The SAME churn regime under the async slow-path engine
            # (datapath/slowpath): decoupled fast step + one coalesced
            # drain round per step, SERIALIZED per iteration — kept for
            # the r05 -> r06 comparison against the overlapped number.
            "async_churn_pps": None if async_churn_pps is None
            else round(async_churn_pps, 1),
            # Round-6 tentpole: the overlapped (double-buffered) regime —
            # drain of window i-1 deferred behind fast step i, fused
            # eviction+aging commit pass (drain_reclaim).  Acceptance
            # target: >= 10M pps @ churn_frac 0.125 on v5e-1; no floor
            # yet (the sync churn floor still guards the path) — the r06
            # verdict calibrates one from the first on-chip measurement.
            "steady_churn_overlap_pps": None if overlap_churn_pps is None
            else round(overlap_churn_pps, 1),
            # ROADMAP item 5 (the unified maintenance scheduler): the
            # async churn cadence with the fused maintenance pass riding
            # EVERY step — an upper bound on what the consolidated
            # background plane costs, reported as a % of the async
            # steady-churn regime so r07 can show the consolidation is
            # free at its real (far sparser) cadence.
            "steady_churn_maint_pps": None if maint_churn_pps is None
            else round(maint_churn_pps, 1),
            "maintenance_overhead_pct": maint_overhead_pct,
            "miss_queue_overflows": q_overflows,
            "async_drain_batch": B // CHURN_DIV,
            "churn_frac": 1 / CHURN_DIV,
            "churn_universe": CHURN_POOL,
            # SPMD scaffolding cost on ONE real chip (1x1-mesh shard_map
            # of the same step); multi-chip scaling is exercised on the
            # virtual mesh (tests/test_parallel_scale.py) since this host
            # has a single TPU.
            "sharded_1x1_pps": sh_pps,
            "shard_overhead_pct": sh_overhead,
            # Shard-aware fused consumer: cold fused classification under
            # a 1x1 shard_map — must sit within noise of
            # cold_classify_pps (the sharded walk keeps the cold win).
            "sharded_cold_fused_pps": None if sh_cold_pps is None
            else round(sh_cold_pps, 1),
            # Round-7 tentpole: the same all-miss regime through the
            # two-level aggregated-bitmap kernel (prune_budget=PRUNE_K)
            # — reported BESIDE cold_classify_pps with the honest
            # fallback rate (the exactness cost) and the aggregate
            # short-circuit rate next to it.  Acceptance target: past
            # the 10M/chip paper number on v5e-1; the r07 verdict
            # calibrates a floor from the first on-chip measurement.
            "cold_pruned_pps": None if cold_pruned_pps is None
            else round(cold_pruned_pps, 1),
            "prune_fallback_rate": None if prune_fb_rate is None
            else round(prune_fb_rate, 4),
            "prune_skip_rate": None if prune_skip_rate is None
            else round(prune_skip_rate, 4),
            "prune_budget": PRUNE_K,
            # Round-8 tentpole: the one-kernel fast path (fused=True +
            # prune_budget=PRUNE_K -> meta.onepass).  steady must sit
            # within noise of the headline (the fast path is shared +
            # a zero-miss skip); cold pays the WHOLE fused slow path —
            # probe, LB, aggregate prune, in-kernel candidate DMA,
            # resolve, commit-row pack AND the commit scatters — in one
            # dispatch per batch, which no staged cold key ever did.
            # Acceptance target: steady toward 2x r05 (>=40M pps/chip),
            # cold comfortably past 10M; the r08 verdict calibrates
            # floors from the first on-chip measurement.
            "steady_fused_pps": None if steady_fused_pps is None
            else round(steady_fused_pps, 1),
            "cold_fused_pps": None if cold_fused_pps is None
            else round(cold_fused_pps, 1),
            # Round-19 pinned floor: the ADVERSARIAL all-miss regime — a
            # never-repeating SYN flood through the engine with the full
            # defense stack on (admission="drop", per-source-/24 buckets,
            # second-chance cache) — beside cold_fused_pps (the
            # cooperative all-miss number), so the flood gap is pinned.
            # Full breakdown prints as its own JSON line below.
            "attack_floor_pps": None if attack_floor is None
            else attack_floor["value"],
            # Hot-path telemetry overhead (observability/telemetry.py):
            # the headline steady regime with the in-kernel counters
            # compiled in — expected within noise of the headline (a
            # handful of masked reductions over already-gathered values);
            # a real gap fails the near-zero-cost claim.
            "steady_telemetry_pps": None if steady_telemetry_pps is None
            else round(steady_telemetry_pps, 1),
        },
    }))
    # The multichip regime prints as its OWN json line (second), so the
    # single-chip headline keeps its first-line position and unchanged
    # keys for the r05 -> r06 comparison.
    if multichip is not None:
        print(json.dumps(multichip))
    # The elastic-mesh resize regime prints third (round 8): migration
    # rows/s + the established-flow-continuity smoke — single-chip keys
    # stay untouched for the r07 -> r08 comparison.
    if reshard is not None:
        print(json.dumps(reshard))
    # The multi-tenant regime prints fourth (round 9): aggregate pps
    # over 64 uneven tenant worlds + the shared-compile evidence —
    # single-chip keys stay untouched for the r08 -> r09 comparison.
    if multitenant is not None:
        print(json.dumps(multitenant))
    # The tenant-elasticity regime prints next (round 20): tenant
    # migration rows/s through a live grow AND a replica-kill
    # evacuation with 64 worlds serving, plus per-world certify
    # latency and continuity — earlier keys stay untouched for the
    # r19 -> r20 comparison.
    if multitenant_reshard is not None:
        print(json.dumps(multitenant_reshard))
    # The batched-serving regime prints fifth (round 18): aggregate pps
    # through the canonical-ladder batcher + the per-tenant p99 wait
    # price of the deadline knob — earlier keys stay untouched for the
    # r17 -> r18 comparison.
    if serving_batched is not None:
        print(json.dumps(serving_batched))
    # The attack-floor regime prints sixth (round 19): the adversarial
    # SYN-flood floor with its defense-stack breakdown (early drops,
    # source-bucket sheds, queue overflows) — earlier keys stay
    # untouched for the r18 -> r19 comparison.
    if attack_floor is not None:
        print(json.dumps(attack_floor))
    # Explicit raises (not assert): the gate must survive python -O.
    if pps < STEADY_FLOOR_PPS:
        raise SystemExit(
            f"steady throughput regressed: {pps/1e6:.2f}M < floor "
            f"{STEADY_FLOOR_PPS/1e6:.0f}M pps"
        )
    if cold_pps < COLD_FLOOR_PPS:
        raise SystemExit(
            f"cold classification regressed: {cold_pps/1e6:.2f}M < floor "
            f"{COLD_FLOOR_PPS/1e6:.0f}M pps"
        )
    if churn_pps is not None and churn_pps < CHURN_FLOOR_PPS:
        raise SystemExit(
            f"churn-regime throughput regressed: {churn_pps/1e6:.2f}M < "
            f"floor {CHURN_FLOOR_PPS/1e6:.1f}M pps"
        )


if __name__ == "__main__":
    main()
