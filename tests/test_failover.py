"""Replica-loss failover plane (parallel/failover.py): tier-1 + chaos.

Kill a data replica UNDER LIVE TRAFFIC — mid-churn, mid-drain and
mid-(ordinary)-resize — and hold the PR bar: bitwise verdict parity vs
the single-chip twin and the scalar oracle on every classified lane,
est continuity for survivor-resident established flows, a bounded
asserted re-miss burst for the dead replica's flows, a canary-certified
emergency cutover (a corrupted survivor vetoes and the OLD mesh keeps
serving with quarantine pending), certified re-admission (auto and
operator), and a journal that reconstructs the probe-fail -> quarantine
-> evacuate -> readmit causal chain from events alone.

Engines share the module-scoped mesh + KW so the jitted sharded step
builders (keyed by (mesh, meta)) compile once per variant.
"""

import json
import pathlib
import sys
import urllib.request

import jax
import numpy as np
import pytest

from antrea_tpu.datapath.tpuflow import TpuflowDatapath
from antrea_tpu.dissemination.faults import FaultPlan
from antrea_tpu.observability.metrics import render_metrics
from antrea_tpu.parallel import MeshDatapath, mesh as pm
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic

KW = dict(flow_slots=1 << 10, aff_slots=1 << 8, canary_probes=16)
ASYNC_KW = dict(async_slowpath=True, miss_queue_slots=1 << 12,
                drain_batch=256)
# Fast state machine for tests: quarantine on 2 consecutive failed
# probes, readmit after 2 quiet rounds, retry a vetoed evacuation after
# 2 ticks.
FO_KW = dict(probe_fails=2, readmit_passes=2, retry_ticks=2)


@pytest.fixture(scope="module")
def world():
    cluster = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=7)
    services = gen_services(8, cluster.pod_ips, seed=11)
    return cluster, services


@pytest.fixture(scope="module")
def mesh():
    return pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])


@pytest.fixture(scope="module")
def batch(world):
    cluster, services = world
    return gen_traffic(cluster.pod_ips, 256, n_flows=96, seed=3,
                       services=services, svc_fraction=0.3)


def _mesh_dp(world, mesh, **extra):
    cluster, services = world
    return MeshDatapath(cluster.ps, services, mesh=mesh, **KW, **extra)


def _kill(mdp, replica=1, times=-1, seed=5):
    """Arm a deterministic persistent death of `replica` (every probe
    round reads it as diverged) -> the plan, for quiesce()/re-arm."""
    plan = FaultPlan(seed=seed)
    plan.every("n0.replica_dead", 1, f"r{replica}", times=times)
    mdp.arm_failover_faults(plan, "n0")
    return plan


def _run_until(mdp, t, phase, sdp=None, batch=None, deadline=500):
    """Tick (stepping live traffic each tick when batch is given, with
    parity against the twin) until the plane reaches `phase`."""
    while mdp.failover_stats()["phase"] != phase:
        if batch is not None:
            rm = mdp.step(batch, t)
            if sdp is not None:
                _verdict_parity(rm, sdp.step(batch, t), f"t={t}")
        mdp.maintenance_tick(now=t)
        t += 1
        assert t < deadline, mdp.failover_stats()
    return t


def _verdict_parity(rm, rs, msg=""):
    """Bitwise verdict parity on every CLASSIFIED lane (pending lanes
    compare pending-for-pending — which lanes re-miss under a topology
    change is a cache-topology observable, the test_reshard caveat)."""
    ok = np.ones(len(np.asarray(rm.code)), bool)
    if rm.pending is not None:
        ok = (np.asarray(rm.pending) == 0) & (np.asarray(rs.pending) == 0)
    for k in ("code", "svc_idx", "dnat_ip", "dnat_port"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rm, k))[ok], np.asarray(getattr(rs, k))[ok],
            err_msg=f"{msg}:{k}")
    ing_m = [r for r, o in zip(rm.ingress_rule, ok) if o]
    ing_s = [r for r, o in zip(rs.ingress_rule, ok) if o]
    egr_m = [r for r, o in zip(rm.egress_rule, ok) if o]
    egr_s = [r for r, o in zip(rs.egress_rule, ok) if o]
    assert ing_m == ing_s, msg
    assert egr_m == egr_s, msg
    return ok


def _slots(b, n_slots=1 << 10):
    """Flow-cache slot per lane (the D-independent direct-mapped hash:
    models/pipeline.py line ~974) — collision EXCLUSION evidence for the
    est-continuity watch: a lane whose slot another flow claims can be
    evicted by ordinary direct-mapped dynamics (the test_reshard
    cache-topology caveat), which is not a failover flap."""
    from antrea_tpu.ops import hashing

    h = hashing.flow_hash(
        np.asarray(b.src_ip, np.uint32), np.asarray(b.dst_ip, np.uint32),
        np.asarray(b.proto), np.asarray(b.src_port),
        np.asarray(b.dst_port), xp=np)
    return (h & np.uint32(n_slots - 1)).astype(np.int64)


def _claim_cols(b, r=None, n_slots=1 << 10):
    """Per-lane slot columns a batch's commits may CLAIM: the forward
    lookup slot plus the reply-row slot (committed allow flows insert a
    reverse entry keyed on the DNAT endpoint —
    models/pipeline._fused_pack_rows: flow_hash(dnat_ip, src, proto,
    dnat_port, sport)).  Both the plain and the DNAT'd reverse variants
    ride along (over-exclusion only shrinks the watch)."""
    from antrea_tpu.ops import hashing

    src = np.asarray(b.src_ip, np.uint32)
    dst = np.asarray(b.dst_ip, np.uint32)
    proto = np.asarray(b.proto)
    sport, dport = np.asarray(b.src_port), np.asarray(b.dst_port)
    cols = [_slots(b, n_slots),
            (hashing.flow_hash(dst, src, proto, dport, sport, xp=np)
             & np.uint32(n_slots - 1)).astype(np.int64)]
    if r is not None:
        dn = np.asarray(r.dnat_ip, np.uint32)
        dp = np.asarray(r.dnat_port)
        cols.append((hashing.flow_hash(dn, src, proto, dp, sport, xp=np)
                     & np.uint32(n_slots - 1)).astype(np.int64))
    return cols


def _chain_indices(kinds, chain):
    """Assert every kind in `chain` occurs, in causal order; -> indices."""
    idx, pos = [], -1
    for want in chain:
        nxt = next((i for i in range(pos + 1, len(kinds))
                    if kinds[i] == want), None)
        assert nxt is not None, (want, kinds)
        idx.append(nxt)
        pos = nxt
    return idx


# --------------------------------------------------------------------------
# Satellite: the plane off is free — same compiled step, disabled surface
# --------------------------------------------------------------------------

def test_failover_disabled_is_free_and_surfaces_disabled_shape(world, mesh,
                                                               batch):
    """The acceptance floor: with the plane disabled (the default) the
    mesh serves the IDENTICAL compiled step — the step builder cache is
    keyed by (mesh, meta, has_arp) only, and a failover-enabled twin
    resolves to the very same jitted executable (byte-identical HLO by
    construction), with bitwise-equal step results.  The disabled
    observability surface reports the stable disabled shape."""
    from antrea_tpu.parallel.meshpath import _mesh_step_full_fn

    a = _mesh_dp(world, mesh)
    b = _mesh_dp(world, mesh, failover=True)
    assert a._meta_step == b._meta_step
    for has_arp in (False, True):
        assert (_mesh_step_full_fn(a._mesh, a._meta_step, has_arp)
                is _mesh_step_full_fn(b._mesh, b._meta_step, has_arp))
    ra, rb = a.step(batch, 100), b.step(batch, 100)
    for k in ("code", "svc_idx", "dnat_ip", "dnat_port", "est"):
        np.testing.assert_array_equal(np.asarray(getattr(ra, k)),
                                      np.asarray(getattr(rb, k)), k)
    st = a.failover_stats()
    assert st["enabled"] == 0 and st["phase"] == "disabled"
    assert st["quarantined_shard"] is None and st["probes_total"] == 0
    with pytest.raises(RuntimeError, match="failover"):
        a.failover_readmit()
    # Disabled plane renders NO failover metric families.
    assert "antrea_tpu_failover" not in render_metrics(a, node="n0")


def test_healthy_mesh_probes_clean_and_never_quarantines(world, mesh, batch):
    """The false-positive floor: an unfaulted mesh probes clean round
    after round — zero probe failures, zero quarantines, phase healthy —
    and the replica-health task is metered in the tick ledger."""
    mdp = _mesh_dp(world, mesh, failover=True)
    mdp.step(batch, 100)
    for t in range(101, 107):
        out = mdp.maintenance_tick(now=t)
        assert out["ran"].get("replica-health", 0) > 0
    st = mdp.failover_stats()
    assert st["phase"] == "healthy" and st["enabled"] == 1
    assert st["probe_failures_total"] == 0
    assert st["quarantines_total"] == 0
    assert st["probes_total"] >= 12  # 2 replicas x 6 rounds
    assert len(st["probe_history"]) == 6
    assert all(rec["failed"] == [] for rec in st["probe_history"])


# --------------------------------------------------------------------------
# Tentpole: replica kill mid-churn -> quarantine -> evacuate -> readmit
# --------------------------------------------------------------------------

def test_replica_kill_mid_churn_evacuates_and_readmits(world, mesh, batch):
    """The acceptance soak: kill replica 1 under live churn.  Probes
    fail consecutively -> quarantine masks it out of serving at once;
    the ring evacuation (certified shrink, no source migration) flips to
    the survivor topology; healing the fault auto-readmits via the
    certified grow-resize.  Every step holds bitwise parity vs the
    single-chip twin; survivor-resident established flows NEVER flap;
    the dead replica's flows re-establish within a bounded re-miss
    burst; and the journal alone reconstructs the causal chain."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    for dp in (mdp, sdp):  # establish the hot set (sync: commit in-step)
        dp.step(batch, 100)
        dp.step(batch, 101)
    # Survivor-resident flows: homed off the doomed replica at gen 0.
    home0 = pm.shard_of_tuples(batch.src_ip, batch.dst_ip, batch.proto,
                               batch.src_port, batch.dst_port, 2, 0)
    surv = home0 != 1
    est0 = np.asarray(mdp.step(batch, 102).est) != 0
    rhot = sdp.step(batch, 102)
    # Never-flap watch: survivor-resident established lanes whose slot no
    # dead-resident flow claims — masking re-homes the dead replica's
    # flows INTO the survivor table, and a direct-mapped same-slot
    # collision evicting the resident is the documented cache-topology
    # observable (test_reshard caveat), not a failover flap.  What the
    # plane itself guarantees: masking and the cutover never disturb a
    # survivor row (D-independent slot hash, order-preserving survivor
    # indexing), so uncontended slots stay est through the WHOLE story.
    slot_hot = _slots(batch)
    dead_claims = np.unique(np.concatenate(
        [c[home0 == 1] for c in _claim_cols(batch, rhot)]))
    watch = surv & est0 & ~np.isin(slot_hot, dead_claims)
    assert watch.sum() > 0 and (~surv).sum() > 0  # both sides populated

    plan = _kill(mdp, replica=1)
    t = 103
    i = 0
    while mdp.failover_stats()["phase"] != "evacuated":
        churn = gen_traffic(cluster.pod_ips, 128, n_flows=64, seed=900 + i)
        rc_m, rc_s = mdp.step(churn, t), sdp.step(churn, t)
        _verdict_parity(rc_m, rc_s, f"churn t={t}")
        rm, rs = mdp.step(batch, t), sdp.step(batch, t)
        _verdict_parity(rm, rs, f"hot t={t}")
        # Survivor-resident established flows never flap — modulo this
        # round's churn lanes contending the same direct-mapped slot
        # (the hot step reclaims such a slot in-round; next round it
        # reads est again).
        churn_claims = np.unique(np.concatenate(_claim_cols(churn, rc_s)))
        ok_round = watch & ~np.isin(slot_hot, churn_claims)
        assert np.asarray(rm.est)[ok_round].all(), f"survivor flap t={t}"
        mdp.maintenance_tick(now=t)
        t += 1
        i += 1
        assert t < 500, mdp.failover_stats()
    st = mdp.failover_stats()
    assert mdp._n_data == 1 and st["quarantines_total"] == 1
    assert st["evacuations_total"] == 1 and st["mask_active"] == 0
    # Bounded re-miss burst: only lanes homed on the dead replica ever
    # re-missed through the mask, and each flow re-establishes once —
    # the burst can never exceed the masked-lane population (hot set +
    # the churn lanes that eventually classified on survivors).
    assert 0 < st["remiss_total"] <= int((home0 == 1).sum()) + 64 * i
    # ... and it STOPS: the survivor topology serves the re-established
    # set from cache, no further re-misses after the flip settles.
    rm = mdp.step(batch, t)
    _verdict_parity(rm, sdp.step(batch, t), "post-evac")
    assert np.asarray(rm.est)[watch].all()
    settled = mdp.failover_stats()["remiss_total"]
    rm = mdp.step(batch, t + 1)
    assert mdp.failover_stats()["remiss_total"] == settled
    assert np.asarray(rm.est).sum() > 0
    sdp.step(batch, t + 1)

    # Heal -> auto-readmission via the ORDINARY certified grow-resize.
    plan.quiesce()
    t = _run_until(mdp, t + 2, "healthy", sdp=sdp, batch=batch)
    st = mdp.failover_stats()
    assert mdp._n_data == 2 and st["readmissions_total"] == 1
    assert st["quarantined_shard"] is None
    rm, rs = mdp.step(batch, t), sdp.step(batch, t)
    _verdict_parity(rm, rs, "post-readmit")
    assert np.asarray(rm.est)[watch].all()  # still no survivor flap

    # The journal reconstructs the chain from events alone — probe
    # failures precede the quarantine, the quarantine precedes the
    # skip-source evacuation resize, its certified cutover precedes the
    # evacuation record, and the readmission closes the story.
    ev = mdp.flightrecorder_events()
    kinds = [e["kind"] for e in ev]
    idx = _chain_indices(kinds, [
        "replica-probe-fail", "replica-quarantine", "reshard-begin",
        "reshard-cutover", "replica-evacuate", "reshard-begin",
        "reshard-cutover", "replica-readmit"])
    assert ev[idx[1]]["replica"] == 1
    assert ev[idx[2]]["skip_replica"] == 1  # the emergency shrink
    assert "skip_replica" not in ev[idx[5]]  # the ordinary readmit grow
    assert ev[idx[4]]["replica"] == 1
    assert ev[idx[7]]["gate"] == "resize" and ev[idx[7]]["mode"] == "auto"

    # Metric families render; the quarantined gauge is back to zero.
    text = render_metrics(mdp, node="n0")
    for fam in ("antrea_tpu_failover_quarantined",
                "antrea_tpu_failover_probes_total",
                "antrea_tpu_failover_probe_failures_total",
                "antrea_tpu_failover_quarantines_total",
                "antrea_tpu_failover_evacuations_total",
                "antrea_tpu_failover_readmissions_total",
                "antrea_tpu_failover_remiss_total"):
        assert fam in text, fam
    for line in text.splitlines():
        if line.startswith("antrea_tpu_failover_quarantined{"):
            assert line.rsplit(" ", 1)[1] == "0", line

    # Post-readmission verdicts are oracle-true on every classified
    # non-service lane (the scalar Oracle deliberately does not model
    # ServiceLB DNAT — service lanes are covered by the bitwise twin
    # parity above, the commit-canary discipline).
    from antrea_tpu.oracle.interpreter import Oracle
    oracle = Oracle(cluster.ps)
    r = mdp.step(batch, t + 1)
    pend = (np.zeros(batch.size, bool) if r.pending is None
            else np.asarray(r.pending) != 0)
    plain = np.asarray(r.svc_idx) < 0
    assert (~pend & plain).sum() > 0
    for i in range(batch.size):
        if not pend[i] and plain[i]:
            assert int(np.asarray(r.code)[i]) == int(
                oracle.classify(batch.packet(i)).code), i


# --------------------------------------------------------------------------
# Chaos: kill mid-drain (async) — queues requeue, serialization holds
# --------------------------------------------------------------------------

def test_replica_kill_mid_drain_requeues_dead_queue(world, mesh):
    """Async chaos: kill the replica while its miss queue holds
    undrained rows and a drain is PINNED in flight.  The scheduler's one
    serialization point defers the whole tick (no quarantine mid-drain);
    after finish_drain the quarantine requeues the dead queue VERBATIM
    onto survivors, the evacuation carries them across the flip, and the
    post-flip drain classifies every row oracle-true."""
    from antrea_tpu.oracle.interpreter import Oracle

    cluster, _services = world
    mdp = _mesh_dp(world, mesh, **ASYNC_KW, failover=True,
                   failover_knobs=FO_KW)
    tr = gen_traffic(cluster.pod_ips, 256, n_flows=128, seed=31)
    mdp.step(tr, 100)  # misses sit queued, undrained
    assert mdp.slowpath_stats()["replica_depths"][1] > 0
    _kill(mdp, replica=1)

    sp = mdp._slowpath
    assert sp.begin_drain(101, 32)  # PARTIAL drain pinned in flight
    out = mdp.maintenance_tick(now=102)
    assert out["blocked"] == "inflight-drain"
    assert "replica-health" in out["deferred"]
    assert mdp.failover_stats()["phase"] == "healthy"  # nothing probed
    sp.finish_drain(103)
    st1 = mdp.slowpath_stats()
    depth1, dead_depth = st1["depth"], st1["replica_depths"][1]
    assert depth1 > 0 and dead_depth > 0  # backlog survived the drain

    # Drive the probe task DIRECTLY to the quarantine (a full tick would
    # first run the drain task and empty the queues — here the dead
    # queue must still hold its backlog when the quarantine requeues it).
    mdp._maint_replica_health(104, 64)
    mdp._maint_replica_health(105, 64)
    st = mdp.failover_stats()
    assert st["phase"] in ("quarantined", "evacuating"), st
    assert st["requeued_total"] == dead_depth  # verbatim, none dropped
    sps = mdp.slowpath_stats()
    assert sps["depth"] == depth1  # nothing lost: survivors hold it all
    assert sps["replica_depths"][1] == 0  # the dead queue is empty

    t = _run_until(mdp, 106, "evacuated")
    sps = mdp.slowpath_stats()
    assert len(sps["replica_depths"]) == 1
    mdp.drain_slowpath(t)
    oracle = Oracle(cluster.ps)
    r = mdp.step(tr, t + 1)
    codes, pend = np.asarray(r.code), np.asarray(r.pending)
    assert (pend == 0).sum() > 0
    for i in range(tr.size):
        if not pend[i]:
            assert codes[i] == int(oracle.classify(tr.packet(i)).code), i


# --------------------------------------------------------------------------
# Chaos: kill mid-(ordinary)-resize — the emergency preempts the elective
# --------------------------------------------------------------------------

def test_replica_kill_preempts_inflight_ordinary_resize(world, mesh, batch):
    """Mid-resize chaos: an elective grow to 4 is mid-migration when the
    replica dies.  The quarantine ABORTS the elective resize (its target
    may involve the dead replica) and installs the emergency evacuation
    in its place; the journal shows the preemption between quarantine
    and the emergency begin."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    mdp.step(batch, 100)
    sdp.step(batch, 100)
    mdp.reshard_begin(4)
    mdp.maintenance_tick(now=101)  # a migration window runs
    assert mdp.reshard_status()["phase"] in ("migrate", "catchup")
    _kill(mdp, replica=1)
    t = _run_until(mdp, 102, "evacuated", sdp=sdp, batch=batch)
    assert mdp._n_data == 1
    rs = mdp.reshard_stats()
    assert rs["aborts_total"] == 1 and rs["cutovers_total"] == 1
    ev = mdp.flightrecorder_events()
    kinds = [e["kind"] for e in ev]
    idx = _chain_indices(kinds, [
        "reshard-begin", "replica-quarantine", "reshard-abort",
        "reshard-begin", "reshard-cutover", "replica-evacuate"])
    assert "quarantine preempts" in ev[idx[2]]["reason"]
    assert "skip_replica" not in ev[idx[0]]  # the elective grow
    assert ev[idx[3]]["skip_replica"] == 1   # the emergency shrink
    _verdict_parity(mdp.step(batch, t), sdp.step(batch, t), "post-preempt")


# --------------------------------------------------------------------------
# Chaos: corrupted survivor vetoes the emergency cutover
# --------------------------------------------------------------------------

def test_corrupted_survivor_vetoes_evacuation_old_mesh_serves(world, mesh,
                                                              batch):
    """The certified-emergency bar: corrupt the SURVIVOR topology's rule
    copies mid-evacuation.  The replica-resolved canary vetoes the flip
    — the OLD mesh keeps serving with the dead replica masked (parity
    holds), quarantine stays pending — and the scheduled retry builds a
    fresh, clean survivor topology that completes."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    mdp.step(batch, 100)
    sdp.step(batch, 100)
    _kill(mdp, replica=1)
    t = _run_until(mdp, 101, "evacuating", sdp=sdp, batch=batch)
    desc = mdp._reshard.corrupt_target(0)  # the lone survivor replica
    assert "replica 0" in desc
    t = _run_until(mdp, t, "quarantined", sdp=sdp, batch=batch)
    # Vetoed: old topology, mask still serving, quarantine pending.
    st = mdp.failover_stats()
    assert mdp._n_data == 2 and mdp._topo_gen == 0
    assert st["mask_active"] == 1 and st["quarantined_shard"] == 1
    assert st["evacuations_total"] == 0
    assert mdp.reshard_stats()["aborts_total"] == 1
    kinds = [e["kind"] for e in mdp.flightrecorder_events()]
    _chain_indices(kinds, ["replica-quarantine", "reshard-begin",
                           "replica-canary-veto", "reshard-abort"])
    assert "replica-evacuate" not in kinds
    _verdict_parity(mdp.step(batch, t), sdp.step(batch, t), "masked-serving")
    # The quarantined gauge reads 1 for the dead shard while pending.
    text = render_metrics(mdp, node="n0")
    assert 'antrea_tpu_failover_quarantined{shard="1"' in text
    for line in text.splitlines():
        if line.startswith('antrea_tpu_failover_quarantined{shard="1"'):
            assert line.rsplit(" ", 1)[1] == "1", line
    # The retry (after retry_ticks) places FRESH target rules and flips.
    t = _run_until(mdp, t + 1, "evacuated", sdp=sdp, batch=batch)
    st = mdp.failover_stats()
    assert st["evacuations_total"] == 1 and mdp._n_data == 1
    _verdict_parity(mdp.step(batch, t), sdp.step(batch, t), "post-retry")


# --------------------------------------------------------------------------
# Readmission: pre-flip heal unmasks; operator surface drives the resize
# --------------------------------------------------------------------------

def test_probe_heal_before_flip_unmasks_without_resize(world, mesh, batch):
    """A probe false-positive heals BEFORE the evacuation cuts over:
    readmission is just dropping the mask — the in-flight evacuation
    aborts, the topology generation never moves, and the journal records
    the unmask-gated readmit."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    mdp.step(batch, 100)
    sdp.step(batch, 100)
    plan = FaultPlan(seed=5)  # exactly 2 failed rounds, then clean
    plan.after("n0.replica_dead", 0, "r1", times=2)
    mdp.arm_failover_faults(plan, "n0")
    t = _run_until(mdp, 101, "evacuating", sdp=sdp, batch=batch)
    assert mdp.failover_stats()["mask_active"] == 1
    t = _run_until(mdp, t, "healthy", sdp=sdp, batch=batch)
    st = mdp.failover_stats()
    assert mdp._n_data == 2 and mdp._topo_gen == 0  # never flipped
    assert st["readmissions_total"] == 1 and st["evacuations_total"] == 0
    assert st["mask_active"] == 0
    ev = mdp.flightrecorder_events()
    readmits = [e for e in ev if e["kind"] == "replica-readmit"]
    assert len(readmits) == 1
    assert readmits[0]["gate"] == "unmask" and readmits[0]["replica"] == 1
    aborts = [e for e in ev if e["kind"] == "reshard-abort"]
    assert any("healed" in e["reason"] for e in aborts)
    _verdict_parity(mdp.step(batch, t), sdp.step(batch, t), "post-unmask")


def test_operator_readmit_via_api_and_bundle_surfaces(world, mesh, batch,
                                                      tmp_path):
    """Operator-driven readmission end to end: auto_readmit off, the
    evacuated mesh stays at D-1 until GET /failover?readmit=1 (the
    antctl path) triggers the certified grow — and the failover surface
    rides the apiserver handler thread and the support bundle."""
    from antrea_tpu.agent.apiserver import AgentApiServer
    from antrea_tpu.observability.supportbundle import collect_bundle

    cluster, services = world
    mdp = _mesh_dp(world, mesh, failover=True,
                   failover_knobs={**FO_KW, "auto_readmit": False})
    mdp.step(batch, 100)
    plan = _kill(mdp, replica=1)
    t = _run_until(mdp, 101, "evacuated")
    plan.quiesce()
    for tt in range(t, t + 6):  # auto_readmit off: nothing moves
        mdp.step(batch, tt)
        mdp.maintenance_tick(now=tt)
    assert mdp.failover_stats()["phase"] == "evacuated"

    srv = AgentApiServer(mdp, node="n1").start()
    try:
        body = json.loads(urllib.request.urlopen(
            srv.address + "/failover").read())
        assert body["enabled"] == 1 and body["phase"] == "evacuated"
        assert body["quarantined_shard"] == 1 and body["n_shards"] == 2
        assert body["probe_history"]
        # PR 20: the surface names worlds still awaiting the evacuation
        # flip — an untenanted mesh serves the key with an empty list.
        assert body["tenants_pending_evacuation"] == []
        kicked = json.loads(urllib.request.urlopen(
            srv.address + "/failover?readmit=1").read())
        assert kicked["phase"] == "readmitting"
    finally:
        srv.close()
    t = _run_until(mdp, t + 6, "healthy", batch=batch)
    st = mdp.failover_stats()
    assert mdp._n_data == 2 and st["readmissions_total"] == 1
    ev = [e for e in mdp.flightrecorder_events()
          if e["kind"] == "replica-readmit"]
    assert ev[-1]["mode"] == "operator" and ev[-1]["gate"] == "resize"

    out = tmp_path / "bundle.tar.gz"
    members = collect_bundle(mdp, str(out), node="n1", now=t)
    assert "failover.json" in members


# --------------------------------------------------------------------------
# Satellite: maintenance stats pin — late-registered tasks always render
# --------------------------------------------------------------------------

def test_maintenance_stats_render_late_registered_tasks(world, mesh, batch):
    """The task-table omission bug: tasks registered AFTER boot (the
    failover plane's emergency reshard-migrate, registered from inside a
    running tick) must render in maintenance_stats()/GET /maintenance —
    the snapshot iterates a stable copy on the handler thread, never the
    live dict."""
    import urllib.request as rq

    from antrea_tpu.agent.apiserver import AgentApiServer

    mdp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    mdp.step(batch, 100)
    ms = mdp.maintenance_stats()
    assert "replica-health" in ms["tasks"]
    _kill(mdp, replica=1)
    t = _run_until(mdp, 101, "evacuating")
    # The emergency migrate task was registered mid-lifecycle (from the
    # replica-health runner's quarantine) — it must be visible NOW.
    ms = mdp.maintenance_stats()
    assert "reshard-migrate" in ms["tasks"]
    assert "replica-health" in ms["tasks"]
    assert ms["scheduler_lag"] >= 0.0
    srv = AgentApiServer(mdp, node="n1").start()
    try:
        body = json.loads(rq.urlopen(srv.address + "/maintenance").read())
        assert "reshard-migrate" in body["tasks"]
        fo = json.loads(rq.urlopen(srv.address + "/failover").read())
        assert fo["phase"] == "evacuating"
        assert fo["tenants_pending_evacuation"] == []
    finally:
        srv.close()
