"""Tenant-aware elastic resharding (PR 20): resize, evacuate and fail
over with tenant worlds LIVE.

The two mutual-refusal ``ConfigError``s are gone: ``reshard_begin``
accepts a tenanted mesh (every world's (D,)-sharded state migrates
under its own ``_world_ctx`` with the generation-composable tenant
salt), and ``tenant_create`` accepts a resharding mesh (the newborn is
adopted mid-flight via ``note_world_created``).  Cutover certification
is per-world: each tenant runs its own replica-resolved canary, a veto
aborts ONLY that world — journaled ``tenant-rollback`` + per-world
topology-generation latch — while certified worlds flip; the latched
world keeps serving its old topology in parity until
``tenant_reshard_resync``.

The failover composition closes the PR 19 loop: quarantine on a
tenanted mesh proceeds to a real evacuation shrink and certified
readmission grows back; a world vetoing the EVACUATION cutover pins a
per-world ``_fo_mask`` and serves masked (skip-replica ring on its own
old topology) until resynced.

Engines share the module-scoped meshes + KW so the jitted sharded step
builders (keyed by (mesh, meta)) compile once per variant; tenant
worlds share one quota rung so the rung-packed rule windows share one
XLA executable before, during and after every resize.
"""

import numpy as np
import pytest

import jax

from antrea_tpu.dissemination.faults import FaultPlan
from antrea_tpu.observability.metrics import render_metrics
from antrea_tpu.oracle.interpreter import Oracle
from antrea_tpu.parallel import MeshDatapath, mesh as pm
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_syn_flood, gen_traffic

KW = dict(flow_slots=1 << 8, aff_slots=1 << 6, canary_probes=8)
FO_KW = dict(probe_fails=2, readmit_passes=2, retry_ticks=2)
N_WORLDS = 8  # the acceptance floor: >= 8 live tenant worlds


@pytest.fixture(scope="module")
def world():
    cluster = gen_cluster(40, n_nodes=4, pods_per_node=6, seed=7)
    services = gen_services(4, cluster.pod_ips, seed=11)
    return cluster, services


@pytest.fixture(scope="module")
def mesh():
    return pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])


@pytest.fixture(scope="module")
def batch(world):
    cluster, services = world
    return gen_traffic(cluster.pod_ips, 128, n_flows=48, seed=3,
                       services=services, svc_fraction=0.3)


@pytest.fixture(scope="module")
def tenant_clusters():
    """Uneven worlds: same seed family as the reshard smoke, one shared
    quota rung (64) so every world's rule window packs into the SAME
    padded executable."""
    return [gen_cluster(20, n_nodes=2, pods_per_node=5, seed=100 + i)
            for i in range(N_WORLDS)]


@pytest.fixture(scope="module")
def tenant_batches(tenant_clusters):
    # World 2's default seed (52) draws an all-denied batch against its
    # policy set — denied flows never establish, which would starve the
    # continuity assertions; seed 56 gives it the usual allow/deny mix.
    return [gen_traffic(c.pod_ips, 64, n_flows=24,
                        seed=56 if i == 2 else 50 + i)
            for i, c in enumerate(tenant_clusters)]


def _mesh_dp(world, mesh, **extra):
    cluster, services = world
    return MeshDatapath(cluster.ps, services, mesh=mesh, **KW, **extra)


def _tenants(dp, tenant_clusters, n=N_WORLDS):
    return [dp.tenant_create(f"w{i}", tenant_clusters[i].ps, quota=64)
            for i in range(n)]


# Want-code memo: the oracle verdict of a FIXED packet against a FIXED
# policy world is deterministic, and these suites re-serve the same
# batches every tick — classify each (world, batch) once, compare every
# step.  Values keep the oracle/batch refs so ids can't be recycled.
_WANT = {}


def _want_codes(oracle, tb):
    key = (id(oracle), id(tb))
    hit = _WANT.get(key)
    if hit is None or hit[0] is not oracle or hit[1] is not tb:
        codes = np.asarray([int(oracle.classify(tb.packet(j)).code)
                            for j in range(tb.size)])
        _WANT[key] = hit = (oracle, tb, codes)
    return hit[2]


def _parity(oracle, tb, r, msg):
    """Bitwise verdict parity vs the per-world oracle on every
    CLASSIFIED lane (pending lanes carry the provisional admission
    verdict until the async drain lands — the PR 9 caveat)."""
    codes = np.asarray(r.code)
    want = _want_codes(oracle, tb)
    pend = (np.zeros(tb.size, bool) if r.pending is None
            else np.broadcast_to(np.asarray(r.pending).astype(bool),
                                 (tb.size,)))
    live = ~pend
    if not (codes[live] == want[live]).all():
        j = int(np.argmax(live & (codes != want)))
        raise AssertionError((msg, j, int(codes[j]), int(want[j])))


def _step_all_in_parity(dp, tids, tbs, oracles, t, msg):
    for i, tid in enumerate(tids):
        _parity(oracles[i], tbs[i], dp.tenant_step(tid, tbs[i], t),
                f"{msg} w{tid} t={t}")


def _resize_under_traffic(dp, batch, tids, tbs, oracles, t, deadline=900):
    """Drive the in-flight resize to completion, serving the default
    world AND every tenant world each tick, parity-checked throughout."""
    while dp.reshard_status() is not None:
        dp.step(batch, t)
        _step_all_in_parity(dp, tids, tbs, oracles, t, "mid-resize")
        dp.maintenance_tick(now=t)
        t += 1
        assert t < deadline, dp.reshard_status()
    return t


# --------------------------------------------------------------------------
# Tentpole acceptance: grow + shrink with >= 8 live worlds, newborn
# adoption mid-flight, established-flow continuity, journal chain.
# --------------------------------------------------------------------------

def test_grow_and_shrink_with_eight_live_tenant_worlds(
        world, mesh, batch, tenant_clusters, tenant_batches):
    dp = _mesh_dp(world, mesh, async_slowpath=True,
                  miss_queue_slots=1 << 10, drain_batch=128)
    tids = _tenants(dp, tenant_clusters)
    oracles = [Oracle(c.ps) for c in tenant_clusters]
    tbs = list(tenant_batches)

    # Establish flows in every world, then drain the shared miss queue
    # EMPTY (one drain moves only drain_batch rows; 9 worlds queue ~6x
    # that) so est is loadbearing in every world.
    dp.step(batch, 100)
    for i, tid in enumerate(tids):
        dp.tenant_step(tid, tbs[i], 100)
    for k in range(8):
        dp.drain_slowpath(101 + k)
    est_before = {}
    for i, tid in enumerate(tids):
        r = dp.tenant_step(tid, tbs[i], 110)
        _parity(oracles[i], tbs[i], r, f"pre w{tid}")
        est_before[tid] = np.asarray(r.est).astype(bool).copy()
        assert est_before[tid].any(), f"w{tid} established nothing"

    # Grow 2 -> 4 under traffic; the old refusal is GONE.
    dp.reshard_begin(4)
    t = _resize_under_traffic(dp, batch, tids, tbs, oracles, 111)
    assert dp._n_data == 4 and dp._topo_gen == 1

    st = dp.reshard_stats()
    assert st["tenant_rows_total"] > 0
    assert st["tenant_vetoes_total"] == 0
    assert st["tenant_worlds_migrating"] == 0
    ts = dp.tenant_stats()
    for tid in tids:
        assert ts[tid]["latched"] == 0
        assert ts[tid]["topology_generation"] == 1
        assert ts[tid]["reshard_rows_total"] > 0

    # Zero established-flow loss: the migrated entries serve straight
    # off the flip (est hits, no re-drain) in every world.  Only
    # direct-mapped collision losers may re-pend on the re-homed slot
    # layout — the documented cache-topology dynamic, never a verdict
    # change on a classified lane (parity held every tick above).
    kept = total = 0
    for i, tid in enumerate(tids):
        r = dp.tenant_step(tid, tbs[i], t)
        _parity(oracles[i], tbs[i], r, f"post-grow w{tid}")
        now_est = np.asarray(r.est).astype(bool)
        assert now_est.any(), f"w{tid} serves nothing from cache"
        kept += int(now_est[est_before[tid]].sum())
        total += int(est_before[tid].sum())
    assert kept / total > 0.85, (kept, total)

    # Shrink 4 -> 2 with a NEWBORN world created mid-flight: the other
    # old refusal is gone too — tenant_create adopts into the plane.
    dp.reshard_begin(2)
    nc = gen_cluster(20, n_nodes=2, pods_per_node=5, seed=777)
    ntid = dp.tenant_create("newborn", nc.ps, quota=64)
    tids.append(ntid)
    tbs.append(gen_traffic(nc.pod_ips, 64, n_flows=24, seed=88))
    oracles.append(Oracle(nc.ps))
    t = _resize_under_traffic(dp, batch, tids, tbs, oracles, t)
    assert dp._n_data == 2 and dp._topo_gen == 2
    ts = dp.tenant_stats()
    for tid in tids:
        assert ts[tid]["latched"] == 0
        assert ts[tid]["topology_generation"] == 2
    for i, tid in enumerate(tids):
        _parity(oracles[i], tbs[i], dp.tenant_step(tid, tbs[i], t),
                f"post-shrink w{tid}")

    # Journal chain: each resize begins, migrates, flips every world,
    # then flips the fleet — and no world ever vetoed or rolled back.
    kinds = [e["kind"] for e in dp.flightrecorder_events()]
    assert kinds.count("reshard-begin") == 2
    assert kinds.count("reshard-cutover") == 2
    # 8 worlds on the grow + 9 on the shrink (newborn adopted).
    assert kinds.count("tenant-reshard-cutover") == N_WORLDS + N_WORLDS + 1
    assert "tenant-reshard-veto" not in kinds
    assert "tenant-rollback" not in kinds
    assert "reshard-abort" not in kinds
    cut = [e for e in dp.flightrecorder_events()
           if e["kind"] == "tenant-reshard-cutover"]
    assert {e["tenant"] for e in cut} == set(tids)

    # Tenant-labeled reshard metrics render.
    text = render_metrics(dp, node="n0")
    assert "antrea_tpu_reshard_tenant_rows_total" in text
    assert "antrea_tpu_tenant_topology_generation" in text
    assert "antrea_tpu_tenant_latched" in text


# --------------------------------------------------------------------------
# Per-tenant certified cutover: one world's veto aborts ONLY its world.
# --------------------------------------------------------------------------

def test_single_tenant_canary_veto_aborts_only_that_world(
        world, mesh, batch, tenant_clusters, tenant_batches):
    dp = _mesh_dp(world, mesh)
    tids = _tenants(dp, tenant_clusters, n=3)
    oracles = [Oracle(tenant_clusters[i].ps) for i in range(3)]
    tbs = tenant_batches[:3]
    dp.step(batch, 100)
    for i, tid in enumerate(tids):
        dp.tenant_step(tid, tbs[i], 100)

    victim = tids[1]
    plan = FaultPlan(seed=9)
    plan.every(f"n0.tenant_canary.t{victim}", 1, "forced", times=1)
    dp.arm_reshard_faults(plan, "n0")

    dp.reshard_begin(4)
    t = _resize_under_traffic(dp, batch, tids, tbs, oracles, 101)
    # The FLEET flipped — one tenant's veto never aborts the resize.
    assert dp._n_data == 4 and dp._topo_gen == 1

    ts = dp.tenant_stats()
    assert ts[victim]["latched"] == 1
    assert ts[victim]["topology_generation"] == 0
    assert ts[victim]["reshard_vetoes_total"] == 1
    for tid in tids:
        if tid != victim:
            assert ts[tid]["latched"] == 0
            assert ts[tid]["topology_generation"] == 1

    # Journal chain pinned: the veto emits tenant-rollback THEN
    # tenant-reshard-veto for the victim, the other worlds flip, the
    # fleet cutover lands last; no fleet-wide abort.
    ev = dp.flightrecorder_events()
    kinds = [e["kind"] for e in ev]
    assert "reshard-abort" not in kinds
    vetoes = [e for e in ev if e["kind"] == "tenant-reshard-veto"]
    assert len(vetoes) == 1 and vetoes[0]["tenant"] == victim
    rollbacks = [e for e in ev if e["kind"] == "tenant-rollback"]
    assert any(e["tenant"] == victim for e in rollbacks)
    assert kinds.index("tenant-rollback") < kinds.index("tenant-reshard-veto")
    cut = {e["tenant"] for e in ev if e["kind"] == "tenant-reshard-cutover"}
    assert cut == {tid for tid in tids if tid != victim}
    assert kinds.index("tenant-reshard-veto") < kinds.index("reshard-cutover")

    # The latched world keeps serving its OLD topology in parity.
    _step_all_in_parity(dp, tids, tbs, oracles, t, "post-veto")

    # Resync re-migrates + re-certifies + flips the latched world.
    res = dp.tenant_reshard_resync(victim, t + 1)
    assert res.get("resynced") == 1, res
    ts = dp.tenant_stats()
    assert ts[victim]["latched"] == 0
    assert ts[victim]["topology_generation"] == dp._topo_gen
    _step_all_in_parity(dp, tids, tbs, oracles, t + 2, "post-resync")
    # A second resync is a fleet-aligned no-op.
    assert dp.tenant_reshard_resync(victim, t + 3).get(
        "reason") == "fleet-aligned"


# --------------------------------------------------------------------------
# Failover composition: quarantine on a tenanted mesh proceeds to a
# REAL evacuation shrink and certified readmission grows back.
# --------------------------------------------------------------------------

def test_quarantine_evacuates_and_readmits_with_live_worlds(
        world, mesh, batch, tenant_clusters, tenant_batches):
    dp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    tids = _tenants(dp, tenant_clusters, n=2)
    oracles = [Oracle(tenant_clusters[i].ps) for i in range(2)]
    tbs = tenant_batches[:2]
    dp.step(batch, 100)
    for i, tid in enumerate(tids):
        dp.tenant_step(tid, tbs[i], 100)

    plan = FaultPlan(seed=5)
    plan.every("n0.replica_dead", 1, "r1", times=6)
    dp.arm_failover_faults(plan, "n0")

    t, seen_pending = 101, None
    while dp.failover_stats()["phase"] != "evacuated":
        dp.step(batch, t)
        _step_all_in_parity(dp, tids, tbs, oracles, t, "mid-evac")
        fs = dp.failover_stats()
        if fs["phase"] in ("quarantined", "evacuating") \
                and seen_pending is None:
            seen_pending = fs["tenants_pending_evacuation"]
        dp.maintenance_tick(now=t)
        t += 1
        assert t < 400, dp.failover_stats()

    # While quarantined, GET /failover names every world still awaiting
    # the evacuation flip; after the flip the list is empty.
    assert seen_pending == sorted(tids)
    assert dp.failover_stats()["tenants_pending_evacuation"] == []
    ts = dp.tenant_stats()
    for tid in tids:
        assert ts[tid]["latched"] == 0
        assert ts[tid]["topology_generation"] == dp._topo_gen
    _step_all_in_parity(dp, tids, tbs, oracles, t, "post-evac")

    # Per-world quarantine context journaled alongside the fleet event.
    q = [e for e in dp.flightrecorder_events()
         if e["kind"] == "replica-quarantine" and "tenant" in e]
    assert {e["tenant"] for e in q} == set(tids)

    # Fault site exhausted -> probes pass -> certified readmission
    # grows back the same tenant-aware way.
    while dp.failover_stats()["phase"] != "healthy":
        dp.step(batch, t)
        _step_all_in_parity(dp, tids, tbs, oracles, t, "readmit")
        dp.maintenance_tick(now=t)
        t += 1
        assert t < 800, dp.failover_stats()
    assert dp._n_data == 2
    ts = dp.tenant_stats()
    for tid in tids:
        assert ts[tid]["latched"] == 0
        assert ts[tid]["topology_generation"] == dp._topo_gen
    _step_all_in_parity(dp, tids, tbs, oracles, t, "post-readmit")


@pytest.mark.chaos
def test_evacuation_veto_masks_only_that_world_until_resync(
        world, mesh, batch, tenant_clusters, tenant_batches):
    """A world vetoing the EVACUATION cutover pins its per-world
    _fo_mask (dead old-topology index, survivor width, survivor gen)
    and serves MASKED on its own old topology — verdict-safe — while
    the fleet and the other world complete the shrink; resync evacuates
    it for real using the pinned skip mapping."""
    dp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW)
    tids = _tenants(dp, tenant_clusters, n=2)
    oracles = [Oracle(tenant_clusters[i].ps) for i in range(2)]
    tbs = tenant_batches[:2]
    dp.step(batch, 100)
    for i, tid in enumerate(tids):
        dp.tenant_step(tid, tbs[i], 100)

    plan = FaultPlan(seed=5)
    plan.every("n0.replica_dead", 1, "r1", times=6)
    dp.arm_failover_faults(plan, "n0")
    vplan = FaultPlan(seed=9)
    vplan.every(f"n0.tenant_canary.t{tids[0]}", 1, "forced", times=1)
    dp.arm_reshard_faults(vplan, "n0")

    t = 101
    while dp.failover_stats()["phase"] != "evacuated":
        dp.step(batch, t)
        _step_all_in_parity(dp, tids, tbs, oracles, t, "mid-evac")
        dp.maintenance_tick(now=t)
        t += 1
        assert t < 400, dp.failover_stats()

    ts = dp.tenant_stats()
    assert ts[tids[0]]["latched"] == 1
    assert ts[tids[1]]["latched"] == 0
    assert dp.failover_stats()["tenants_pending_evacuation"] == [tids[0]]
    # Masked serving on the old topology stays in parity.
    _step_all_in_parity(dp, tids, tbs, oracles, t, "latched-masked")

    res = dp.tenant_reshard_resync(tids[0], t + 1)
    assert res.get("resynced") == 1, res
    assert dp.tenant_stats()[tids[0]]["latched"] == 0
    assert dp.failover_stats()["tenants_pending_evacuation"] == []
    _step_all_in_parity(dp, tids, tbs, oracles, t + 2, "post-resync")


# --------------------------------------------------------------------------
# Chaos soak (satellite): replica kill under 8 live worlds with mixed
# SYN-flood + steady traffic through quarantine -> evacuate -> readmit.
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_replica_kill_under_syn_flood_eight_worlds(
        world, mesh, tenant_clusters, tenant_batches):
    cluster, services = world
    dp = _mesh_dp(world, mesh, failover=True, failover_knobs=FO_KW,
                  async_slowpath=True, miss_queue_slots=1 << 10,
                  drain_batch=128)
    tids = _tenants(dp, tenant_clusters)
    oracles = [Oracle(c.ps) for c in tenant_clusters]
    tbs = list(tenant_batches)
    steady = gen_traffic(cluster.pod_ips, 128, n_flows=48, seed=3,
                         services=services, svc_fraction=0.3)
    dp.step(steady, 100)
    for i, tid in enumerate(tids):
        dp.tenant_step(tid, tbs[i], 100)
    for k in range(8):
        dp.drain_slowpath(101 + k)
    est_before = {}
    for i, tid in enumerate(tids):
        r = dp.tenant_step(tid, tbs[i], 110)
        est_before[tid] = np.asarray(r.est).astype(bool).copy()
        assert est_before[tid].any(), f"w{tid} established nothing"

    # The maintenance grant splits across the default world + 8 tenant
    # worlds, so the evacuation shrink needs ~9x the migration ticks of
    # the untenanted arc — keep the replica dead well past the flip
    # (times=6 would heal BEFORE it and merely unmask).
    plan = FaultPlan(seed=5)
    plan.every("n0.replica_dead", 1, "r1", times=40)
    dp.arm_failover_faults(plan, "n0")

    t, seq, phases = 111, 0, set()
    while True:
        # Adversarial default-world load: never-repeating 5-tuples so
        # every lane is a miss-queue admission, round-robined with the
        # steady established mix.
        if t % 2:
            dp.step(gen_syn_flood(cluster.pod_ips, 128, start_seq=seq), t)
            seq += 128
        else:
            dp.step(steady, t)
        # Every world serves every tick; zero non-parity verdicts
        # through the whole quarantine -> evacuate -> readmit arc.
        _step_all_in_parity(dp, tids, tbs, oracles, t, "soak")
        phases.add(dp.failover_stats()["phase"])
        dp.maintenance_tick(now=t)
        t += 1
        # Phase is sampled per tick but quarantine -> evacuation and
        # evacuated -> readmitting are sub-tick transitions (the PR 19
        # loop closure auto-proceeds inside one maintenance tick), so
        # the JOURNAL is the arc's ground truth: done once the replica
        # was quarantined, evacuated AND certified back in, and the
        # plane reads healthy again.
        if dp.failover_stats()["phase"] == "healthy":
            kinds = {e["kind"] for e in dp.flightrecorder_events()}
            if {"replica-quarantine", "replica-evacuate",
                    "replica-readmit"} <= kinds:
                break
        assert t < 1200, (dp.failover_stats(), sorted(phases))
    assert phases - {"healthy"}, "the fault never perturbed serving"
    # Soak on for a tail of mixed traffic at full width post-recovery.
    for _ in range(12):
        if t % 2:
            dp.step(gen_syn_flood(cluster.pod_ips, 128, start_seq=seq), t)
            seq += 128
        else:
            dp.step(steady, t)
        _step_all_in_parity(dp, tids, tbs, oracles, t, "soak-tail")
        dp.maintenance_tick(now=t)
        t += 1
    assert dp._n_data == 2

    # Established-flow continuity: rows homed on the DEAD replica
    # re-miss by design (the skip-replica evacuation migrates nothing
    # from it — verdict-safe re-classification, parity held every tick
    # above), so a world's cache can run cold mid-arc; once the re-miss
    # burst drains, every world's established set is back in full.
    for _ in range(3):  # serve -> drain rounds settle the burst (the
        for i, tid in enumerate(tids):   # flood shares the bounded
            dp.tenant_step(tid, tbs[i], t)  # queue, so one pass can't)
        for k in range(8):
            dp.drain_slowpath(t)
            t += 1
    kept = total = 0
    for i, tid in enumerate(tids):
        r = dp.tenant_step(tid, tbs[i], t)
        est = np.asarray(r.est).astype(bool)
        assert est.any(), f"w{tid} serves nothing from cache post-soak"
        kept += int(est[est_before[tid]].sum())
        total += int(est_before[tid].sum())
    assert kept / total > 0.85, (kept, total)
    kinds = [e["kind"] for e in dp.flightrecorder_events()]
    assert "replica-quarantine" in kinds
    assert "replica-evacuate" in kinds
    assert "replica-readmit" in kinds
    assert "tenant-reshard-veto" not in kinds


# --------------------------------------------------------------------------
# The do-no-harm pins: untenanted resize and failover=False trace the
# IDENTICAL compiled step as HEAD (cache-identity = byte-identical HLO).
# --------------------------------------------------------------------------

def test_untenanted_paths_share_the_compiled_step(world, mesh, batch):
    from antrea_tpu.parallel.meshpath import _mesh_step_full_fn

    a = _mesh_dp(world, mesh)                 # plain HEAD shape
    b = _mesh_dp(world, mesh, failover=True)  # failover plane armed
    assert a._meta_step == b._meta_step
    for has_arp in (False, True):
        assert (_mesh_step_full_fn(a._mesh, a._meta_step, has_arp)
                is _mesh_step_full_fn(b._mesh, b._meta_step, has_arp))
    ra, rb = a.step(batch, 100), b.step(batch, 100)
    for k in ("code", "svc_idx", "dnat_ip", "dnat_port", "est"):
        np.testing.assert_array_equal(np.asarray(getattr(ra, k)),
                                      np.asarray(getattr(rb, k)), k)
    # An untenanted resize serves through the same cached builders the
    # whole way: the step fn resolved at the target width is the same
    # object any untenanted engine at that width resolves.
    a.reshard_begin(4)
    t = 101
    while a.reshard_status() is not None:
        a.step(batch, t)
        a.maintenance_tick(now=t)
        t += 1
        assert t < 400
    assert a._n_data == 4
    c = MeshDatapath(world[0].ps, world[1],
                     mesh=pm.make_mesh(4, 2, devices=jax.devices("cpu")),
                     **KW)
    for has_arp in (False, True):
        assert (_mesh_step_full_fn(a._mesh, a._meta_step, has_arp)
                is _mesh_step_full_fn(c._mesh, c._meta_step, has_arp))
