"""Multicast + IGMP snooping + packet-in dispatcher tests.

Semantics from the reference's multicast subsystem (pkg/agent/multicast:
IGMP report/leave snooping, member timeouts, MulticastRouting/Output
tables; pkg/agent/openflow/multicast.go: conntrack bypass) and the
packet-in plumbing (pkg/agent/openflow/packetin.go:44-130 categories +
rate-limited queues).  Differential discipline: both datapaths behind the
Datapath boundary.
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from antrea_tpu.agent.multicast import (
    IGMP_LEAVE,
    IGMP_REPORT,
    MulticastController,
)
from antrea_tpu.agent.noderoute import NodeRouteController
from antrea_tpu.agent.packetin import (
    CAT_IGMP,
    PacketInDispatcher,
)
from antrea_tpu.compiler.topology import (
    FWD_DROP_MCAST,
    FWD_MCAST,
    FWD_PUNT,
    OFPORT_REPLICATE,
    PROTO_IGMP,
    McastGroup,
    NodeRoute,
    Topology,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def _topo(mcast=()):
    return Topology(
        node_name="node-a",
        gateway_ip="10.10.0.1",
        pod_cidr="10.10.0.0/24",
        local_pods=[("10.10.0.5", 3), ("10.10.0.6", 4)],
        remote_nodes=[
            NodeRoute(name="node-b", node_ip="192.168.1.2",
                      pod_cidr="10.10.1.0/24"),
        ],
        mcast_groups=list(mcast),
    )


def _pair(topo):
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    return (
        TpuflowDatapath(topology=topo, miss_chunk=64, **kw),
        OracleDatapath(topology=topo, **kw),
    )


def _batch(rows, proto=17):
    """rows: [(src, dst, in_port, sport)]"""
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(s) for s, _, _, _ in rows], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(d) for _, d, _, _ in rows], np.uint32),
        proto=np.full(len(rows), proto, np.int32),
        src_port=np.array([sp for _, _, _, sp in rows], np.int32),
        dst_port=np.full(len(rows), 5000, np.int32),
        in_port=np.array([p for _, _, p, _ in rows], np.int32),
    )


def _diff(a, b):
    for f in ("code", "spoofed", "punt", "fwd_kind", "out_port", "mcast_idx",
              "dec_ttl", "committed", "est"):
        assert getattr(a, f).tolist() == getattr(b, f).tolist(), f
    assert a.n_miss == b.n_miss


def test_mcast_delivery_and_miss():
    groups = [
        McastGroup("239.1.1.1", local_ports=(3, 4), remote_nodes=("node-b",)),
        McastGroup("239.1.1.2", local_ports=(4,)),
    ]
    tpu, orc = _pair(_topo(groups))
    b = _batch([
        ("10.10.0.5", "239.1.1.1", 3, 40000),  # joined group -> replicate
        ("10.10.0.6", "239.1.1.2", 4, 40000),  # joined group -> replicate
        ("10.10.0.5", "239.9.9.9", 3, 40000),  # no receivers -> drop
    ])
    ra, rb = tpu.step(b, now=10), orc.step(b, now=10)
    _diff(ra, rb)
    assert ra.fwd_kind.tolist() == [FWD_MCAST, FWD_MCAST, FWD_DROP_MCAST]
    assert ra.out_port.tolist() == [OFPORT_REPLICATE, OFPORT_REPLICATE, -1]
    # mcast_idx rows are sorted by group IP: 239.1.1.1 < 239.1.1.2.
    assert ra.mcast_idx.tolist() == [0, 1, -1]
    g0 = tpu.mcast_group(0)
    assert g0 == orc.mcast_group(0)
    assert g0["ports"] == [3, 4]
    assert g0["peers"] == [iputil.ip_to_u32("192.168.1.2")]
    # Multicast bypasses conntrack: nothing committed, nothing cached.
    assert ra.committed.tolist() == [0, 0, 0]
    assert tpu.cache_stats()["occupied"] == 0
    assert orc.cache_stats()["occupied"] == 0
    # Re-step: still classified fresh (n_miss counts all mcast lanes).
    ra2, rb2 = tpu.step(b, now=11), orc.step(b, now=11)
    _diff(ra2, rb2)
    assert ra2.est.tolist() == [0, 0, 0]


def test_igmp_punt_no_state():
    tpu, orc = _pair(_topo())
    b = _batch([("10.10.0.5", "239.1.1.1", 3, IGMP_REPORT)], proto=PROTO_IGMP)
    ra, rb = tpu.step(b, now=5), orc.step(b, now=5)
    _diff(ra, rb)
    assert ra.punt.tolist() == [1]
    assert ra.fwd_kind.tolist() == [FWD_PUNT]
    assert ra.out_port.tolist() == [-1]
    assert tpu.cache_stats()["occupied"] == 0
    # Punted lanes are invisible to policy metrics on both sides.
    assert tpu.stats().default_allow == orc.stats().default_allow == 0


def test_igmp_snooping_feedback_loop():
    """IGMP report punt -> dispatcher -> MulticastController -> topology
    reinstall -> subsequent multicast traffic replicates; leave withdraws;
    timeout expires members (the queryInterval/timeout model)."""
    tpu = TpuflowDatapath(topology=_topo(), flow_slots=1 << 10,
                          aff_slots=1 << 8, miss_chunk=64)
    nrc = NodeRouteController(tpu, "node-a", pod_cidr="10.10.0.0/24")
    nrc.pod_added("10.10.0.5", 3)
    nrc.pod_added("10.10.0.6", 4)
    nrc.upsert_node("node-b", "192.168.1.2", "10.10.1.0/24")
    disp = PacketInDispatcher()
    mc = MulticastController(nrc, dispatcher=disp, member_timeout_s=100)

    # Pod 4 joins 239.2.2.2 via an IGMP report.
    rep = _batch([("10.10.0.6", "239.2.2.2", 4, IGMP_REPORT)],
                 proto=PROTO_IGMP)
    r = tpu.step(rep, now=10)
    assert disp.collect(rep, r, now=10) == 1
    assert disp.drain(now=10) == 1

    data = _batch([("10.10.0.5", "239.2.2.2", 3, 40000)])
    r2 = tpu.step(data, now=11)
    assert r2.fwd_kind.tolist() == [FWD_MCAST]
    assert tpu.mcast_group(int(r2.mcast_idx[0]))["ports"] == [4]

    # Leave: group withdrawn, traffic drops again.
    leave = _batch([("10.10.0.6", "239.2.2.2", 4, IGMP_LEAVE)],
                   proto=PROTO_IGMP)
    r3 = tpu.step(leave, now=12)
    disp.collect(leave, r3, now=12)
    disp.drain(now=12)
    assert tpu.step(data, now=13).fwd_kind.tolist() == [FWD_DROP_MCAST]

    # Rejoin, then let it expire.
    r4 = tpu.step(rep, now=20)
    disp.collect(rep, r4, now=20)
    disp.drain(now=20)
    assert tpu.step(data, now=21).fwd_kind.tolist() == [FWD_MCAST]
    assert mc.expire(now=121) == 1  # 101s > 100s timeout
    assert tpu.step(data, now=122).fwd_kind.tolist() == [FWD_DROP_MCAST]


def test_remote_interest_replication():
    tpu = TpuflowDatapath(topology=_topo(), flow_slots=1 << 10,
                          aff_slots=1 << 8, miss_chunk=64)
    nrc = NodeRouteController(tpu, "node-a", pod_cidr="10.10.0.0/24")
    nrc.upsert_node("node-b", "192.168.1.2", "10.10.1.0/24")
    nrc.pod_added("10.10.0.5", 3)
    mc = MulticastController(nrc)
    mc.set_remote_interest("239.3.3.3", ["node-b"])
    data = _batch([("10.10.0.5", "239.3.3.3", 3, 40000)])
    r = tpu.step(data, now=1)
    assert r.fwd_kind.tolist() == [FWD_MCAST]
    g = tpu.mcast_group(int(r.mcast_idx[0]))
    assert g["ports"] == [] and g["peers"] == [iputil.ip_to_u32("192.168.1.2")]
    mc.set_remote_interest("239.3.3.3", [])
    assert tpu.step(data, now=2).fwd_kind.tolist() == [FWD_DROP_MCAST]


def test_packetin_rate_limit_and_categories():
    disp = PacketInDispatcher(rate=0, burst=3)  # 3 tokens, no refill
    got = []
    disp.register(CAT_IGMP, lambda item, now: got.append(item))
    for i in range(5):
        disp.submit(CAT_IGMP, {"i": i}, now=0)
    assert disp.drain(now=0) == 3
    assert disp.dropped(CAT_IGMP) == 2
    assert [g["i"] for g in got] == [0, 1, 2]


def test_mcast_policy_applies_without_caching():
    """Multicast still traverses the security tables (MulticastEgressRule
    analog): an egress drop on the sender applies — and is re-evaluated
    every step (no cached denial)."""
    from antrea_tpu.apis import controlplane as cp
    from antrea_tpu.compiler.ir import PolicySet

    deny = cp.NetworkPolicy(
        uid="np-deny-mcast", name="deny-mcast", namespace="default",
        type=cp.NetworkPolicyType.ANNP,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.OUT, action=cp.RuleAction.DROP, priority=0,
            to_peer=cp.NetworkPolicyPeer(
                ip_blocks=[cp.IPBlock(cidr="239.0.0.0/8")]
            ),
        )],
        applied_to_groups=["atg-sender"],
        tier_priority=cp.TIER_APPLICATION, priority=5,
    )
    ps = PolicySet(
        policies=[deny],
        applied_to_groups={"atg-sender": cp.AppliedToGroup(
            name="atg-sender",
            members=[cp.GroupMember(ip="10.10.0.5")],
        )},
        address_groups={},
    )
    topo = _topo([McastGroup("239.1.1.1", local_ports=(4,))])
    import copy

    tpu = TpuflowDatapath(copy.deepcopy(ps), topology=topo,
                          flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    orc = OracleDatapath(copy.deepcopy(ps), topology=topo,
                         flow_slots=1 << 10, aff_slots=1 << 8)
    b = _batch([
        ("10.10.0.5", "239.1.1.1", 3, 40000),  # denied sender
        ("10.10.0.6", "239.1.1.1", 4, 40000),  # allowed sender
    ])
    for t in (1, 2):
        ra, rb = tpu.step(b, now=t), orc.step(b, now=t)
        _diff(ra, rb)
        assert ra.code.tolist() == [1, 0]
        assert ra.fwd_kind.tolist()[1] == FWD_MCAST
        assert ra.out_port.tolist() == [-1, OFPORT_REPLICATE]
    assert tpu.cache_stats()["occupied"] == 0  # denials not cached either
