"""Agent API server tests: the localhost REST surface antctl's live mode
consumes (ref pkg/agent/apiserver handlers: agentinfo, podinterface,
ovsflows, ovstracing, networkpolicy, memberlist, featuregates + the
Prometheus metrics endpoint)."""

import json
from urllib.request import urlopen

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu import antctl
from antrea_tpu.agent.apiserver import AgentApiServer
from antrea_tpu.agent.memberlist import MemberlistCluster
from antrea_tpu.datapath import TpuflowDatapath
from antrea_tpu.features import FeatureGates
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.utils import ip as iputil


@pytest.fixture(scope="module")
def server():
    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=21)
    services = gen_services(4, cluster.pod_ips, seed=22)
    dp = TpuflowDatapath(cluster.ps, services, flow_slots=1 << 10,
                         aff_slots=1 << 8, miss_chunk=64)
    tr = gen_traffic(cluster.pod_ips, 64, n_flows=32, seed=23,
                     services=services, svc_fraction=0.3)
    dp.step(PacketBatch(src_ip=tr.src_ip, dst_ip=tr.dst_ip, proto=tr.proto,
                        src_port=tr.src_port, dst_port=tr.dst_port), now=50)
    ml = MemberlistCluster("node-a")
    ml.join("node-b")
    srv = AgentApiServer(
        dp, node="node-a", memberlist=ml, gates=FeatureGates(),
    ).start()
    yield srv, dp, cluster
    srv.close()


def _get(srv, path):
    with urlopen(srv.address + path, timeout=10) as r:
        return r.read().decode()


def test_metrics_endpoint(server):
    srv, dp, _ = server
    text = _get(srv, "/metrics")
    assert "antrea_tpu_flow_cache_entries" in text
    assert "antrea_tpu_default_verdict_packets_total" in text


def test_agentinfo_and_cache(server):
    srv, dp, _ = server
    info = json.loads(_get(srv, "/agentinfo?now=60"))
    assert info["nodeName"] == "node-a"
    cache = json.loads(_get(srv, "/cache"))
    assert cache == dp.cache_stats()
    assert cache["occupied"] > 0


def test_ovsflows_dump(server):
    srv, dp, _ = server
    flows = json.loads(_get(srv, "/ovsflows?now=55"))
    assert flows and {"src", "dst", "committed"} <= set(flows[0])


def test_memberlist_and_featuregates(server):
    srv, _, _ = server
    assert json.loads(_get(srv, "/memberlist")) == ["node-a", "node-b"]
    gates = json.loads(_get(srv, "/featuregates"))
    assert gates.get("Traceflow") is True


def test_live_traceflow(server):
    srv, dp, cluster = server
    src = iputil.u32_to_ip(int(cluster.pod_ips[0]))
    dst = iputil.u32_to_ip(int(cluster.pod_ips[1]))
    obs = json.loads(_get(srv, f"/traceflow?src={src}&dst={dst}&dport=80"))
    assert "code" in obs and "fwd_kind" in obs


def test_unknown_route_404(server):
    srv, _, _ = server
    from urllib.error import HTTPError

    with pytest.raises(HTTPError) as e:
        _get(srv, "/nope")
    assert e.value.code == 404


def test_antctl_live_mode(server, capsys):
    srv, _, cluster = server
    assert antctl.main(["get", "memberlist", "--server", srv.address]) == 0
    assert json.loads(capsys.readouterr().out) == ["node-a", "node-b"]
    assert antctl.main(["metrics", "--server", srv.address]) == 0
    assert "antrea_tpu" in capsys.readouterr().out
    src = iputil.u32_to_ip(int(cluster.pod_ips[0]))
    dst = iputil.u32_to_ip(int(cluster.pod_ips[1]))
    assert antctl.main([
        "traceflow", "--server", srv.address, "--src", src, "--dst", dst,
    ]) == 0
    obs = json.loads(capsys.readouterr().out)
    assert obs["verdict"] in ("Allow", "Drop", "Reject")
