"""Datapath profiler (ISSUE 2 tentpole part 1): phase-mask semantics of
the churn loop and the Datapath.profile() surface on both engines.

Timings on the hermetic CPU backend are noise — these tests assert the
STRUCTURE (phase set, telescoped sum identity, state neutrality) and the
phase-mask gating semantics (a commit-less mask never fills the cache);
real numbers come from bench_profile.py on the TPU."""

import numpy as np

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.models import pipeline as pl
from antrea_tpu.models.profile import PHASE_CHAIN, profile_churn
from antrea_tpu.simulator import gen_cluster, gen_traffic

SLOTS = 1 << 10


def _world():
    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=5)
    hot = gen_traffic(cluster.pod_ips, 32, n_flows=16, seed=6)
    fresh = gen_traffic(cluster.pod_ips, 128, n_flows=128, seed=7,
                        one_per_flow=True)
    return cluster, hot, fresh


def test_phase_mask_gating_semantics():
    """PH_COMMIT off -> misses never fill the cache (repeat batch keeps
    missing); full mask -> second step is all hits.  The gating must not
    disturb the fast-path output image."""
    import jax.numpy as jnp

    from antrea_tpu.utils import ip as iputil

    cluster, hot, _fresh = _world()
    cps = compile_policy_set(cluster.ps)
    svc = compile_services([])
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=SLOTS, aff_slots=1 << 8, miss_chunk=16
    )
    cols = (
        jnp.asarray(iputil.flip_u32(hot.src_ip)),
        jnp.asarray(iputil.flip_u32(hot.dst_ip)),
        jnp.asarray(hot.proto), jnp.asarray(hot.src_port),
        jnp.asarray(hot.dst_port),
    )
    no_commit = step.meta._replace(
        phases=pl.PH_SLOW | pl.PH_LB | pl.PH_CLS)
    st = state
    for now in (1, 2):
        st, o = pl.pipeline_step(st, drs, dsvc, *cols, jnp.int32(now),
                                 jnp.int32(0), meta=no_commit)
        assert int(o["n_miss"]) == hot.size  # nothing was committed
    st = state
    st, o1 = pl.pipeline_step(st, drs, dsvc, *cols, jnp.int32(1),
                              jnp.int32(0), meta=step.meta)
    st, o2 = pl.pipeline_step(st, drs, dsvc, *cols, jnp.int32(2),
                              jnp.int32(0), meta=step.meta)
    assert int(o1["n_miss"]) == hot.size and int(o2["n_miss"]) == 0
    # Verdicts of the commit-less walk match the full walk's fresh pass
    # (classify ran identically; only state writes were masked).
    _st, o_nc = pl.pipeline_step(state, drs, dsvc, *cols, jnp.int32(1),
                                 jnp.int32(0), meta=no_commit)
    assert o_nc["code"].tolist() == o1["code"].tolist()


def test_tpuflow_profile_structure_and_state_neutrality():
    cluster, hot, fresh = _world()
    dp = TpuflowDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8,
                         miss_chunk=16)
    dp.step(hot, now=1)  # pre-existing state must survive profiling
    before = dp.cache_stats()
    prof = dp.profile(hot, fresh, n_new=8, k_small=1, k_big=2, repeats=1)
    assert dp.cache_stats() == before  # observable-state neutral
    expected = [name for name, _m in PHASE_CHAIN]
    assert list(prof["phases_s"]) == expected
    assert prof["total_s"] > 0 and prof["pps"] > 0
    # Telescoped-sum identity: the breakdown sums EXACTLY to the chain end.
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-12
    assert abs(sum(prof["phase_fractions"].values()) - 1.0) < 1e-9
    assert prof["fresh_per_step"] == 8 and prof["batch"] == hot.size


def test_oracle_profile_structure_and_state_neutrality():
    cluster, hot, _fresh = _world()
    dp = OracleDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8)
    dp.step(hot, now=1)
    before = (dp.cache_stats(), dp.stats(), dp.step_hist.count)
    prof = dp.profile(hot)
    after = (dp.cache_stats(), dp.stats(), dp.step_hist.count)
    assert before == after
    assert set(prof["phases_s"]) == {"fast_path", "classify",
                                     "commit_residual"}
    assert prof["total_s"] > 0 and prof["pps"] > 0


def test_profile_churn_direct_chain_override():
    """profile_churn's chain override (bench_profile's independent
    cross-check path) times a single full-mask variant."""
    import jax.numpy as jnp

    from antrea_tpu.utils import ip as iputil

    cluster, hot, fresh = _world()
    cps = compile_policy_set(cluster.ps)
    svc = compile_services([])
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=SLOTS, aff_slots=1 << 8, miss_chunk=16
    )

    def cols(tr):
        return (
            jnp.asarray(iputil.flip_u32(tr.src_ip)),
            jnp.asarray(iputil.flip_u32(tr.dst_ip)),
            jnp.asarray(tr.proto), jnp.asarray(tr.src_port),
            jnp.asarray(tr.dst_port),
        )

    prof = profile_churn(
        step.meta, state, drs, dsvc, cols(hot), cols(fresh), n_new=8,
        k_small=1, k_big=2, repeats=1, chain=(("full", pl.PH_ALL),),
    )
    assert list(prof["phases_s"]) == ["full"]
    assert prof["phases_s"]["full"] == prof["total_s"]


def test_tpuflow_profile_async_mode():
    """profile(mode="async") attributes the drain phases
    (ASYNC_PHASE_CHAIN) with the same telescoped-sum identity, state
    untouched."""
    from antrea_tpu.models.profile import ASYNC_PHASE_CHAIN

    cluster, hot, fresh = _world()
    dp = TpuflowDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8,
                         miss_chunk=16)
    dp.step(hot, now=1)
    before = dp.cache_stats()
    prof = dp.profile(hot, fresh, n_new=8, k_small=1, k_big=2, repeats=1,
                      mode="async")
    assert dp.cache_stats() == before
    assert list(prof["phases_s"]) == [n for n, _m in ASYNC_PHASE_CHAIN]
    assert prof["mode"] == "async" and prof["drain_batch"] == 8
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-12
    assert prof["total_s"] > 0 and prof["pps"] > 0


def test_oracle_profile_async_mode_names():
    cluster, hot, fresh = _world()
    dp = OracleDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8)
    prof = dp.profile(hot, fresh, mode="async")
    assert set(prof["phases_s"]) == {"async_fast_path", "drain_classify",
                                     "drain_commit_residual"}


def test_tpuflow_profile_overlap_mode():
    """profile(mode="overlap") attributes the double-buffered cadence
    (OVERLAP_PHASE_CHAIN: drain of window i-1 behind fast step i) with
    the same telescoped-sum identity, state untouched."""
    from antrea_tpu.models.profile import OVERLAP_PHASE_CHAIN

    cluster, hot, fresh = _world()
    dp = TpuflowDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8,
                         miss_chunk=16)
    dp.step(hot, now=1)
    before = dp.cache_stats()
    prof = dp.profile(hot, fresh, n_new=8, k_small=1, k_big=2, repeats=1,
                      mode="overlap")
    assert dp.cache_stats() == before
    assert list(prof["phases_s"]) == [n for n, _m in OVERLAP_PHASE_CHAIN]
    assert prof["mode"] == "overlap" and prof["drain_batch"] == 8
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-12
    assert prof["total_s"] > 0 and prof["pps"] > 0


def test_oracle_profile_overlap_mode_names():
    cluster, hot, fresh = _world()
    dp = OracleDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8)
    prof = dp.profile(hot, fresh, mode="overlap")
    assert set(prof["phases_s"]) == {"overlap_fast_path", "overlap_classify",
                                     "overlap_commit_residual"}


def test_tpuflow_profile_maintenance_mode():
    """profile(mode="maintenance") attributes the unified background
    plane's cadence (MAINT_PHASE_CHAIN: the scheduler's fused
    maintenance pass riding every step) with the telescoped-sum
    identity, state untouched, and reports the plane's own attributed
    cost as maintenance_s."""
    from antrea_tpu.models.profile import MAINT_PHASE_CHAIN

    cluster, hot, fresh = _world()
    dp = TpuflowDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8,
                         miss_chunk=16)
    dp.step(hot, now=1)
    before = dp.cache_stats()
    prof = dp.profile(hot, fresh, n_new=8, k_small=1, k_big=2, repeats=1,
                      mode="maintenance")
    assert dp.cache_stats() == before
    assert list(prof["phases_s"]) == [n for n, _m in MAINT_PHASE_CHAIN]
    assert prof["mode"] == "maintenance" and prof["drain_batch"] == 8
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-12
    assert "maintenance_s" in prof and "maintenance_fraction" in prof
    assert prof["total_s"] > 0 and prof["pps"] > 0


def test_oracle_profile_maintenance_mode_names():
    cluster, hot, fresh = _world()
    dp = OracleDatapath(cluster.ps, flow_slots=SLOTS, aff_slots=1 << 8)
    muts0 = dp._state_mutations
    prof = dp.profile(hot, fresh, mode="maintenance")
    assert set(prof["phases_s"]) == {"maint_fast_path", "maint_classify",
                                     "maint_commit_residual", "maint_sweep"}
    assert prof["maintenance_s"] == prof["phases_s"]["maint_sweep"]
    # Observable-state-neutral including the accounted-mutation counter
    # (the maintenance rider's eviction pass restores with the snapshot).
    assert dp._state_mutations == muts0


# The phase-drift gate (tools/check_phases.py -> analysis pass `phases`)
# runs once for the whole tier-1 suite in tests/test_static_analysis.py.
