"""Multicluster: service export/import across clusters, ACNP replication,
label identities — driven end-to-end into per-cluster datapaths (the
BASELINE config-5 'multicluster' scenario; cross-cluster reachability
rides DNAT to remote pod IPs, the Geneve-tunnel analog)."""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    AntreaAppliedTo,
    AntreaNetworkPolicy,
    AntreaNPRule,
    AntreaPeer,
    IPBlock,
    LabelSelector,
)
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.datapath import TpuflowDatapath
from antrea_tpu.multicluster import ClusterSet, LabelIdentityIndex
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def _probe(dp, src, dst, dport, now=10):
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([41000], np.int32),
        dst_port=np.array([dport], np.int32),
    )
    return dp.step(b, now)


def test_service_export_import_roundtrip():
    cs = ClusterSet()
    east = cs.add_member("east")
    west = cs.add_member("west")

    # east exports prod/web backed by two local pods.
    svc_east = ServiceEntry("10.96.0.10", 80, 6,
                            [Endpoint("10.1.0.5", 8080), Endpoint("10.1.0.6", 8080)],
                            name="web", namespace="prod")
    east.add_local_service("prod", svc_east)
    cs.leader.export_service("east", "prod", svc_east)

    # west sees the import with east's endpoints.
    imp = west.imported[("prod", "web")]
    assert imp.name == "antrea-mc-web"
    assert {e.ip for e in imp.endpoints} == {"10.1.0.5", "10.1.0.6"}
    # east's own import of the same name excludes its own endpoints.
    assert east.imported[("prod", "web")].endpoints == []

    # west also exports the same service name: endpoints merge; east's
    # import now carries west's endpoints (and west's still only east's).
    svc_west = ServiceEntry("10.97.0.10", 80, 6, [Endpoint("10.2.0.9", 8080)],
                            name="web", namespace="prod")
    west.add_local_service("prod", svc_west)
    cs.leader.export_service("west", "prod", svc_west)
    assert {e.ip for e in east.imported[("prod", "web")].endpoints} == {"10.2.0.9"}
    assert {e.ip for e in west.imported[("prod", "web")].endpoints} == {
        "10.1.0.5", "10.1.0.6"}

    # Retraction: west withdraws; east's import empties again.
    cs.leader.retract_export("west", "prod", "web")
    assert east.imported[("prod", "web")].endpoints == []


def test_cross_cluster_traffic_through_datapath():
    """The imported MC service compiles into the member's datapath like any
    Service: traffic to the antrea-mc ClusterIP DNATs to a REMOTE cluster's
    pod IP (the cross-cluster Geneve path of the reference)."""
    cs = ClusterSet()
    east = cs.add_member("east")
    west = cs.add_member("west")
    svc_east = ServiceEntry("10.96.0.10", 80, 6, [Endpoint("10.1.0.5", 8080)],
                            name="web", namespace="prod")
    east.add_local_service("prod", svc_east)
    cs.leader.export_service("east", "prod", svc_east)

    dp_west = TpuflowDatapath(None, west.all_services(),
                              flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=16)
    mc_ip = west.imported[("prod", "web")].cluster_ip
    r = _probe(dp_west, "10.2.0.50", mc_ip, 80)
    assert int(r.code[0]) == 0
    assert int(r.dnat_ip[0]) == iputil.ip_to_u32("10.1.0.5")  # remote pod
    assert int(r.dnat_port[0]) == 8080


def test_acnp_replication_and_late_join():
    cs = ClusterSet()
    east = cs.add_member("east")
    anp = AntreaNetworkPolicy(
        uid="cs-deny", name="cs-deny", priority=1.0,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"app": "db"}))],
        rules=[AntreaNPRule(
            direction=Direction.IN, action=RuleAction.DROP,
            peers=[AntreaPeer(ip_block=IPBlock(cidr="0.0.0.0/0"))],
        )],
    )
    cs.leader.replicate_policy(anp)
    assert "cs-deny" in east.replicated_policies
    # A cluster joining LATER receives existing policies and imports.
    svc = ServiceEntry("10.96.0.10", 80, 6, [Endpoint("10.1.0.5", 8080)],
                       name="web", namespace="prod")
    east.add_local_service("prod", svc)
    cs.leader.export_service("east", "prod", svc)
    south = cs.add_member("south")
    assert "cs-deny" in south.replicated_policies
    assert {e.ip for e in south.imported[("prod", "web")].endpoints} == {"10.1.0.5"}

    # A departing member's exports are GC'd (leader stale controller):
    # with no exporters left, the import is retracted everywhere, and the
    # departed member drops ALL its MC state (member-side stale cleanup).
    east_member = cs.members["east"]
    cs.remove_member("east")
    assert ("prod", "web") not in south.imported
    assert east_member.imported == {} and east_member.replicated_policies == {}
    assert "east" not in cs.members


def test_conflicting_export_specs_surface_not_merge():
    """Two clusters exporting the same name with DIFFERENT port/protocol:
    the cluster-id-ordered first exporter defines the import; the
    conflicting cluster is surfaced in `conflicts` and its endpoints are
    excluded (the reference marks conflicting ResourceExports)."""
    cs = ClusterSet()
    east = cs.add_member("east")
    west = cs.add_member("west")
    cs.leader.export_service("west", "prod", ServiceEntry(
        "10.97.0.10", 443, 6, [Endpoint("10.2.0.9", 8443)],
        name="web", namespace="prod"))
    cs.leader.export_service("east", "prod", ServiceEntry(
        "10.96.0.10", 80, 6, [Endpoint("10.1.0.5", 8080)],
        name="web", namespace="prod"))
    ri = cs.leader._imports()[("prod", "web")]
    assert (ri.port, ri.protocol) == (80, 6)  # east < west: east defines
    assert ri.conflicts == ["west"]
    assert [c for c, _ in ri.endpoints] == ["east"]
    # The import the members hold reflects the deterministic winner.
    assert west.imported[("prod", "web")].port == 80


def test_label_identity_ids_are_clusterset_wide():
    idx = LabelIdentityIndex()
    a = idx.id_of({"env": "prod"}, {"app": "web"})
    b = idx.id_of({"env": "prod"}, {"app": "web"})
    c = idx.id_of({"env": "prod"}, {"app": "db"})
    d = idx.id_of({}, {"app": "web"})
    assert a == b and len({a, c, d}) == 3 and min(a, c, d) >= 1
