"""Sharded full walk at BENCH scale (round-3 verdict weak #3: multi-chip
evidence was fixture-scale only): the 100k-rule bench world on an 8-way
virtual CPU mesh, with per-shard memory accounting that proves the rule
axis actually divides the incidence bytes (the HBM capacity math in
parallel/mesh.py)."""

import numpy as np
import pytest

import jax

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.compiler.topology import Topology, compile_topology
from antrea_tpu.models import pipeline as pl
from antrea_tpu.parallel import mesh as pm
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil

pytestmark = pytest.mark.slow  # ~minutes: 100k-rule world on the CPU mesh

N_RULES = 100_000
B = 2048  # bench-shape batch kept CPU-tractable; the WORLD is bench-scale


def test_sharded_walk_at_bench_scale_with_memory_accounting():
    cluster = gen_cluster(N_RULES, n_nodes=64, pods_per_node=32, seed=1)
    cps = compile_policy_set(cluster.ps)
    services = gen_services(500, cluster.pod_ips, seed=2)
    svc = compile_services(services)
    tr = gen_traffic(cluster.pod_ips, B, n_flows=B, seed=3,
                     services=services, svc_fraction=0.3)

    mesh = pm.make_mesh(2, 4)  # 8-way: DP x TP over the virtual CPU mesh
    step, state, (drs, dsvc) = pm.make_sharded_pipeline(
        cps, svc, mesh, flow_slots=1 << 14, aff_slots=1 << 8,
        miss_chunk=256,
    )

    # ---- per-shard memory accounting (the mesh.py HBM math, asserted) ----
    total_inc = 0
    per_dev: dict = {}
    for dd in (drs.ingress, drs.egress):
        for tab in (dd.at, dd.peer, dd.svc):
            total_inc += tab.inc.nbytes
            for sh in tab.inc.addressable_shards:
                per_dev[sh.device] = per_dev.get(sh.device, 0) + sh.data.nbytes
    n_rule = mesh.shape[pm.RULE]
    assert total_inc > 400e6  # genuinely bench-scale incidence state
    for dev, nbytes in per_dev.items():
        # Each device holds ~1/n_rule of the incidence bytes (word-axis
        # sharding; small padding slack allowed).
        assert nbytes < total_inc / n_rule * 1.05, (dev, nbytes, total_inc)
    assert len(per_dev) == 8

    # ---- one sharded step at bench scale + spot parity vs single-chip ----
    import jax.numpy as jnp

    src = jnp.asarray(iputil.flip_u32(tr.src_ip))
    dst = jnp.asarray(iputil.flip_u32(tr.dst_ip))
    proto = jnp.asarray(tr.proto)
    sport = jnp.asarray(tr.src_port)
    dport = jnp.asarray(tr.dst_port)
    state, out = step(state, drs, dsvc, src, dst, proto, sport, dport,
                      jnp.int32(1), jnp.int32(0))
    codes = np.asarray(out["code"])
    assert codes.shape == (B,)

    # Single-chip reference on a slice of the batch: bit-exact verdicts.
    sl = slice(0, 256)
    step1, state1, (drs1, dsvc1) = pl.make_pipeline(
        cps, svc, flow_slots=1 << 14, aff_slots=1 << 8, miss_chunk=256,
    )
    state1, out1 = step1(state1, drs1, dsvc1, src[sl], dst[sl], proto[sl],
                         sport[sl], dport[sl], jnp.int32(1), jnp.int32(0))
    np.testing.assert_array_equal(codes[sl], np.asarray(out1["code"]))
    np.testing.assert_array_equal(
        np.asarray(out["svc_idx"])[sl], np.asarray(out1["svc_idx"]))

    # Second step: per-data-shard conntrack state serves est hits.
    state, out2 = step(state, drs, dsvc, src, dst, proto, sport, dport,
                       jnp.int32(2), jnp.int32(0))
    est = np.asarray(out2["est"])
    committed = np.asarray(out["committed"])
    # Committed first-step flows est-bypass on step 2, modulo direct-mapped
    # slot collisions (fwd+reply entries of ~1k flows/shard in 2^14 slots
    # evict a few percent — cache semantics, identical on the oracle).
    assert (est[committed == 1]).mean() > 0.9, (est[committed == 1]).mean()
