"""Sharded (mesh) datapath parity vs the single-device reference path.

Runs on the 8 virtual CPU devices set up in conftest.py — the same
environment the driver's multi-chip dryrun uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models.pipeline import make_pipeline
from antrea_tpu.ops.match import make_classifier
from antrea_tpu.parallel import (
    make_mesh,
    make_sharded_classifier,
    make_sharded_pipeline,
)
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil


@pytest.fixture(scope="module")
def cluster():
    return gen_cluster(200, n_nodes=4, pods_per_node=16, seed=7)


@pytest.fixture(scope="module")
def batch(cluster):
    return gen_traffic(cluster.pod_ips, 1024, n_flows=256, seed=3)


def _mesh(n_data, n_rule):
    return make_mesh(n_data, n_rule, devices=jax.devices("cpu"))


def _cols(b):
    # numpy (host) arrays: placeable on either the default platform or the
    # CPU mesh without cross-platform transfers of committed arrays.
    return (
        iputil.flip_u32(b.src_ip),
        iputil.flip_u32(b.dst_ip),
        b.proto,
        b.src_port,
        b.dst_port,
    )


def test_sharded_classifier_matches_single(cluster, batch):
    cps = compile_policy_set(cluster.ps)
    src_f, dst_f, proto, _, dport = _cols(batch)

    ref_fn, _ = make_classifier(cps)
    ref = ref_fn(src_f, dst_f, proto, dport)

    mesh = _mesh(2, 4)
    fn, _drs = make_sharded_classifier(cps, mesh)
    got = fn(src_f, dst_f, proto, dport)

    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]), err_msg=k)


def test_sharded_classifier_rule_only_mesh(cluster, batch):
    """data=1: pure rule-parallelism must also agree."""
    cps = compile_policy_set(cluster.ps)
    src_f, dst_f, proto, _, dport = _cols(batch)
    ref_fn, _ = make_classifier(cps)
    ref = ref_fn(src_f, dst_f, proto, dport)

    mesh = _mesh(1, 8)
    fn, _ = make_sharded_classifier(cps, mesh)
    got = fn(src_f, dst_f, proto, dport)
    np.testing.assert_array_equal(np.asarray(got["code"]), np.asarray(ref["code"]))


def test_sharded_pipeline_matches_single(cluster, batch):
    cps = compile_policy_set(cluster.ps)
    svc = compile_services(gen_services(32, cluster.pod_ips, seed=11))
    src_f, dst_f, proto, sport, dport = _cols(batch)
    now = jnp.int32(1000)

    step1, st1, (drs1, dsvc1) = make_pipeline(
        cps, svc, flow_slots=1 << 14, aff_slots=1 << 12
    )
    mesh = _mesh(2, 4)
    stepN, stN, (drsN, dsvcN) = make_sharded_pipeline(
        cps, svc, mesh, flow_slots=1 << 14, aff_slots=1 << 12
    )

    # Two steps: second sees the conntrack/affinity state of the first.
    for t in range(2):
        st1, out1 = step1(st1, drs1, dsvc1, src_f, dst_f, proto, sport, dport, now + t, jnp.int32(0))
        stN, outN = stepN(stN, drsN, dsvcN, src_f, dst_f, proto, sport, dport, now + t, jnp.int32(0))
        for k in ("code", "est", "svc_idx", "dnat_ip_f", "dnat_port"):
            np.testing.assert_array_equal(
                np.asarray(outN[k]), np.asarray(out1[k]), err_msg=f"step{t}:{k}"
            )
    # Established fast path engaged on step 2 for repeat flows.
    assert int(np.asarray(outN["est"]).sum()) > 0


def test_sharded_full_walk_matches_single(cluster):
    """The FULL sharded walk (SpoofGuard -> pipeline -> forward -> Output,
    make_sharded_pipeline_full) is bit-identical to the single-chip
    pipeline_step_full — the production multi-chip step the driver
    dry-runs (__graft_entry__.dryrun_multichip)."""
    from antrea_tpu.compiler.topology import (
        NodeRoute, Topology, compile_topology,
    )
    from antrea_tpu.models import forwarding as fwd
    from antrea_tpu.parallel import make_sharded_pipeline_full

    cps = compile_policy_set(cluster.ps)
    services = gen_services(8, cluster.pod_ips, seed=9)
    svc = compile_services(services)
    topo = Topology(
        node_name="node-0",
        pod_cidr="10.0.0.0/24",
        local_pods=[
            (iputil.u32_to_ip(int(u)), 3 + i)
            for i, u in enumerate(cluster.pod_ips[:10])
        ],
        # node-1's REAL podCIDR (gen_cluster pods live at 10.0.<node>.x) so
        # cross-node traffic exercises the FWD_TUNNEL/peer_f branch.
        remote_nodes=[NodeRoute(name="node-1", node_ip="192.168.0.2",
                                pod_cidr="10.0.1.0/24")],
    )
    ft = compile_topology(topo)
    tr = gen_traffic(cluster.pod_ips, 1024, n_flows=256, seed=11,
                     services=services, svc_fraction=0.3)
    rng = np.random.default_rng(5)
    in_port = rng.choice(
        np.array([-1, 1, 2, 3, 4, 5], np.int32), size=1024
    )
    src_f, dst_f, proto, sport, dport = _cols(tr)

    step1, st1, (drs1, dsvc1) = make_pipeline(
        cps, svc, flow_slots=1 << 14, aff_slots=1 << 12
    )
    dft1 = fwd.fwd_to_device(ft)
    mesh = _mesh(2, 4)
    stepN, stN, (drsN, dsvcN, dftN) = make_sharded_pipeline_full(
        cps, svc, ft, mesh, flow_slots=1 << 14, aff_slots=1 << 12
    )

    flags = np.where(np.arange(1024) % 9 == 0, 1, 0).astype(np.int32)
    for t in range(2):
        st1, out1 = fwd.pipeline_step_full(
            st1, drs1, dsvc1, dft1, jnp.asarray(src_f), jnp.asarray(dst_f),
            jnp.asarray(proto), jnp.asarray(sport), jnp.asarray(dport),
            jnp.asarray(in_port), jnp.int32(1000 + t), jnp.int32(0),
            jnp.asarray(flags),
            meta=step1.meta,
        )
        stN, outN = stepN(
            stN, drsN, dsvcN, dftN, src_f, dst_f, proto, sport, dport,
            in_port, flags, np.zeros_like(flags), jnp.int32(1000 + t),
            jnp.int32(0),
        )
        for k in ("code", "est", "spoofed", "fwd_kind", "out_port",
                  "peer_f", "dec_ttl", "mcast_idx", "dnat_ip_f"):
            np.testing.assert_array_equal(
                np.asarray(outN[k]), np.asarray(out1[k]),
                err_msg=f"step{t}:{k}",
            )
    assert int(np.asarray(outN["est"]).sum()) > 0
    # The interesting branches actually fired in this world.
    from antrea_tpu.compiler.topology import FWD_TUNNEL
    assert int((np.asarray(outN["fwd_kind"]) == FWD_TUNNEL).sum()) > 0
    assert int(np.asarray(outN["spoofed"]).sum()) > 0


def test_sharded_fused_consumer_matches_single(cluster, batch):
    """fused=True composes with the rule-axis shard seam: each shard's
    pallas consumer receives its global word offset (word_idx[0]) and
    emits GLOBAL rule indices, so the pmin-combined verdicts are
    bit-identical to the single-chip fused path — the sharded walk keeps
    the cold-path win (round-4 weak #4)."""
    cps = compile_policy_set(cluster.ps)
    svc = compile_services(gen_services(16, cluster.pod_ips, seed=13))
    src_f, dst_f, proto, sport, dport = _cols(batch)

    step1, st1, (drs1, dsvc1) = make_pipeline(
        cps, svc, flow_slots=1 << 14, aff_slots=1 << 12, fused=True
    )
    mesh = _mesh(2, 4)
    stepN, stN, (drsN, dsvcN) = make_sharded_pipeline(
        cps, svc, mesh, flow_slots=1 << 14, aff_slots=1 << 12, fused=True
    )
    for t in range(2):
        st1, out1 = step1(st1, drs1, dsvc1, src_f, dst_f, proto, sport,
                          dport, jnp.int32(1000 + t), jnp.int32(0))
        stN, outN = stepN(stN, drsN, dsvcN, src_f, dst_f, proto, sport,
                          dport, jnp.int32(1000 + t), jnp.int32(0))
        for k in ("code", "est", "svc_idx", "dnat_ip_f", "dnat_port",
                  "ingress_rule", "egress_rule"):
            np.testing.assert_array_equal(
                np.asarray(outN[k]), np.asarray(out1[k]),
                err_msg=f"step{t}:{k}",
            )
    assert int(np.asarray(outN["est"]).sum()) > 0
