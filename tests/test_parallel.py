"""Sharded (mesh) datapath parity vs the single-device reference path.

Runs on the 8 virtual CPU devices set up in conftest.py — the same
environment the driver's multi-chip dryrun uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models.pipeline import make_pipeline
from antrea_tpu.ops.match import make_classifier
from antrea_tpu.parallel import (
    make_mesh,
    make_sharded_classifier,
    make_sharded_pipeline,
)
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil


@pytest.fixture(scope="module")
def cluster():
    return gen_cluster(200, n_nodes=4, pods_per_node=16, seed=7)


@pytest.fixture(scope="module")
def batch(cluster):
    return gen_traffic(cluster.pod_ips, 1024, n_flows=256, seed=3)


def _mesh(n_data, n_rule):
    return make_mesh(n_data, n_rule, devices=jax.devices("cpu"))


def _cols(b):
    # numpy (host) arrays: placeable on either the default platform or the
    # CPU mesh without cross-platform transfers of committed arrays.
    return (
        iputil.flip_u32(b.src_ip),
        iputil.flip_u32(b.dst_ip),
        b.proto,
        b.src_port,
        b.dst_port,
    )


def test_sharded_classifier_matches_single(cluster, batch):
    cps = compile_policy_set(cluster.ps)
    src_f, dst_f, proto, _, dport = _cols(batch)

    ref_fn, _ = make_classifier(cps)
    ref = ref_fn(src_f, dst_f, proto, dport)

    mesh = _mesh(2, 4)
    fn, _drs = make_sharded_classifier(cps, mesh)
    got = fn(src_f, dst_f, proto, dport)

    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]), err_msg=k)


def test_sharded_classifier_rule_only_mesh(cluster, batch):
    """data=1: pure rule-parallelism must also agree."""
    cps = compile_policy_set(cluster.ps)
    src_f, dst_f, proto, _, dport = _cols(batch)
    ref_fn, _ = make_classifier(cps)
    ref = ref_fn(src_f, dst_f, proto, dport)

    mesh = _mesh(1, 8)
    fn, _ = make_sharded_classifier(cps, mesh)
    got = fn(src_f, dst_f, proto, dport)
    np.testing.assert_array_equal(np.asarray(got["code"]), np.asarray(ref["code"]))


def test_sharded_pipeline_matches_single(cluster, batch):
    cps = compile_policy_set(cluster.ps)
    svc = compile_services(gen_services(32, cluster.pod_ips, seed=11))
    src_f, dst_f, proto, sport, dport = _cols(batch)
    now = jnp.int32(1000)

    step1, st1, (drs1, dsvc1) = make_pipeline(
        cps, svc, flow_slots=1 << 14, aff_slots=1 << 12
    )
    mesh = _mesh(2, 4)
    stepN, stN, (drsN, dsvcN) = make_sharded_pipeline(
        cps, svc, mesh, flow_slots=1 << 14, aff_slots=1 << 12
    )

    # Two steps: second sees the conntrack/affinity state of the first.
    for t in range(2):
        st1, out1 = step1(st1, drs1, dsvc1, src_f, dst_f, proto, sport, dport, now + t, jnp.int32(0))
        stN, outN = stepN(stN, drsN, dsvcN, src_f, dst_f, proto, sport, dport, now + t, jnp.int32(0))
        for k in ("code", "est", "svc_idx", "dnat_ip_f", "dnat_port"):
            np.testing.assert_array_equal(
                np.asarray(outN[k]), np.asarray(out1[k]), err_msg=f"step{t}:{k}"
            )
    # Established fast path engaged on step 2 for repeat flows.
    assert int(np.asarray(outN["est"]).sum()) > 0
