"""Multicluster Gateway: election, ClusterInfo exchange, and the datapath
route programming that makes cross-cluster traffic take the gateway path
with policy applied (BASELINE config 5; ref member/gateway_controller.go
:57,:80 + pkg/agent/multicluster route programming)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.compiler.topology import FWD_TUNNEL, Topology
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.multicluster.gateway import (
    ClusterInfoExchange,
    GatewayController,
)
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

# Cluster A: nodes a1/a2, pod CIDR 10.10.0.0/16 (a1 10.10.1.0/24).
# Cluster B: nodes b1/b2, pod CIDR 10.20.0.0/16.
A_NODES = {"a1": "172.18.0.11", "a2": "172.18.0.12"}
B_NODES = {"b1": "172.19.0.21", "b2": "172.19.0.22"}
POD_A = "10.10.1.5"   # local pod on a1
POD_B = "10.20.3.9"   # pod in cluster B


def _wire():
    ga = GatewayController("cluster-a", A_NODES)
    gb = GatewayController("cluster-b", B_NODES)
    ex = ClusterInfoExchange()
    ex.register(ga)
    ex.register(gb)
    ex.publish(ga.cluster_info(["10.10.0.0/16"]))
    ex.publish(gb.cluster_info(["10.20.0.0/16"]))
    return ga, gb, ex


def test_election_deterministic_and_failover():
    ga, gb, ex = _wire()
    gw = ga.gateway_node
    assert gw in A_NODES
    # Every node computes the same owner (consistent hash, no leader write).
    assert GatewayController("cluster-a", A_NODES).gateway_node == gw
    # Failover: the gateway dies, the other node takes over, and the
    # re-published ClusterInfo carries the new gateway IP.
    other = next(n for n in A_NODES if n != gw)
    ga.node_failed(gw)
    assert ga.gateway_node == other
    ex.publish(ga.cluster_info(["10.10.0.0/16"]))
    routes = gb.mc_node_routes(gb.gateway_node)
    mc_a = [r for r in routes if r.pod_cidr == "10.10.0.0/16"]
    assert mc_a and mc_a[0].node_ip == A_NODES[other]


def test_two_hop_route_computation():
    ga, gb, _ = _wire()
    gw = ga.gateway_node
    non_gw = next(n for n in A_NODES if n != gw)
    # Gateway node tunnels straight to the REMOTE gateway.
    r_gw = {r.pod_cidr: r.node_ip for r in ga.mc_node_routes(gw)}
    assert r_gw["10.20.0.0/16"] == B_NODES[gb.gateway_node]
    # Other nodes tunnel to the LOCAL gateway (two-hop path).
    r_other = {r.pod_cidr: r.node_ip for r in ga.mc_node_routes(non_gw)}
    assert r_other["10.20.0.0/16"] == A_NODES[gw]


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_cross_cluster_walk_takes_gateway_with_policy(dp_cls):
    """The full datapath walk on an A node: traffic to a cluster-B pod IP
    forwards FWD_TUNNEL toward the gateway path, and a replicated
    (stretched) ACNP drops the denied cross-cluster flow before any
    forwarding happens."""
    ga, gb, _ = _wire()
    gw = ga.gateway_node
    non_gw = next(n for n in A_NODES if n != gw)

    # Stretched NP: the leader-replicated ACNP denies POD_A -> cluster B
    # on port 9999 (ipBlock over B's pod CIDR — label identity indexes
    # compile to the same range form).
    ps = PolicySet()
    ps.applied_to_groups["a-pods"] = cp.AppliedToGroup(
        name="a-pods", members=[cp.GroupMember(ip=POD_A, node="a1")])
    ps.policies.append(cp.NetworkPolicy(
        uid="mc-deny", name="mc-deny", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["a-pods"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.OUT,
            to_peer=cp.NetworkPolicyPeer(
                ip_blocks=[cp.IPBlock("10.20.0.0/16")]),
            services=[cp.Service(protocol=6, port=9999)],
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))

    # Node a1's topology: its local pod + the intra-cluster route to a2 +
    # the MC routes from the gateway controller.
    topo = Topology(
        node_name="a1", gateway_ip="10.10.1.1", pod_cidr="10.10.1.0/24",
        local_pods=[(POD_A, 3)],
        remote_nodes=ga.mc_node_routes("a1"),
    )
    dp = dp_cls(ps, [], flow_slots=1 << 10, aff_slots=1 << 6,
                topology=topo, **({"miss_chunk": 16}
                                  if dp_cls is TpuflowDatapath else {}))

    def probe(dport):
        batch = PacketBatch(
            src_ip=np.array([iputil.ip_to_u32(POD_A)], np.uint32),
            dst_ip=np.array([iputil.ip_to_u32(POD_B)], np.uint32),
            proto=np.array([6], np.int32),
            src_port=np.array([40000], np.int32),
            dst_port=np.array([dport], np.int32),
            in_port=np.array([3], np.int32),
        )
        return dp.step(batch, now=1)

    # Allowed cross-cluster flow: tunnels toward the gateway path.
    r = probe(80)
    assert int(r.code[0]) == 0
    assert int(r.fwd_kind[0]) == FWD_TUNNEL
    expect_peer = (B_NODES[gb.gateway_node] if "a1" == gw
                   else A_NODES[gw])
    assert int(r.peer_ip[0]) == iputil.ip_to_u32(expect_peer)
    assert int(r.dec_ttl[0]) == 1  # routed leg

    # Stretched-NP denial: dropped before forwarding.
    r = probe(9999)
    assert int(r.code[0]) == 1
    assert int(r.out_port[0]) == -1
