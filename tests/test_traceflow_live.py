"""Live-traffic Traceflow (ISSUE 2 tentpole part 2): sampled real-packet
traces, reconstructed per-stage from Datapath.trace(), must agree with the
oracle engine across verdict scenarios — allowed, dropped-by-rule,
default-deny — plus the droppedOnly filter and the 1-in-N sampler.

Parity discipline (PR 1 lesson): every probe is a FRESH 5-tuple (unique
src_port, monotonic now) so established flow-cache entries never mask the
behavior under test."""

import itertools
import json
import threading
import time

import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.traceflow import (
    TraceflowController,
    TraceflowSpec,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

SLOTS = 1 << 10
_SPORT = itertools.count(42000)  # fresh 5-tuples: unique src_port per probe
_NOW = itertools.count(10)

DROPPED_DST = "10.0.0.10"  # ACNP drops traffic FROM 10.0.0.5 only
DENY_DST = "10.0.0.30"  # K8s NP isolates with zero rules: default deny
OPEN_DST = "10.0.0.99"  # unregulated: default allow
BLOCKED_SRC = "10.0.0.5"
OTHER_SRC = "10.0.0.6"


def _ps() -> PolicySet:
    ps = PolicySet()
    ps.applied_to_groups["atg-drop"] = cp.AppliedToGroup(
        "atg-drop", [cp.GroupMember(ip=DROPPED_DST, node="n0")]
    )
    ps.applied_to_groups["atg-deny"] = cp.AppliedToGroup(
        "atg-deny", [cp.GroupMember(ip=DENY_DST, node="n0")]
    )
    ps.address_groups["ag-blocked"] = cp.AddressGroup(
        "ag-blocked", [cp.GroupMember(ip=BLOCKED_SRC, node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="drop-in", name="drop-in", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg-drop"], tier_priority=cp.TIER_APPLICATION,
        priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(address_groups=["ag-blocked"]),
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    # Zero-rule K8s NP with policyTypes=[IN]: pure isolation (default deny).
    ps.policies.append(cp.NetworkPolicy(
        uid="isolate", name="isolate", namespace="default",
        type=cp.NetworkPolicyType.K8S, rules=[],
        applied_to_groups=["atg-deny"], policy_types=[cp.Direction.IN],
    ))
    return ps


def _pkt_batch(rows):
    """rows: (src str, dst str, sport, dport)."""
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(r[0]) for r in rows], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(r[1]) for r in rows], np.uint32),
        proto=np.array([6] * len(rows), np.int32),
        src_port=np.array([r[2] for r in rows], np.int32),
        dst_port=np.array([r[3] for r in rows], np.int32),
    )


def _engines():
    ps = _ps()
    out = []
    for dp in (
        TpuflowDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8,
                        miss_chunk=16),
        OracleDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8),
    ):
        tfc = TraceflowController()
        out.append((tfc, tfc.tap("n0", dp)))
    return out


def _live(dst, name, **kw):
    return TraceflowSpec(
        name=name, dst_ip=dst, proto=6, src_port=0, dst_port=80,
        live_traffic=True, **kw,
    )


def test_live_verdict_scenarios_parity():
    """Allowed / dropped-by-rule / default-deny live traces: sampled from
    one real batch on each engine, identical status + per-stage verdicts."""
    engines = _engines()
    statuses = []
    sport = {k: next(_SPORT) for k in ("open", "drop", "deny")}
    now = next(_NOW)
    for tfc, dp in engines:
        tfc.start_live(_live(OPEN_DST, "t-open"), "n0")
        tfc.start_live(_live(DROPPED_DST, "t-drop"), "n0")
        tfc.start_live(_live(DENY_DST, "t-deny"), "n0")
        batch = _pkt_batch([
            (OTHER_SRC, OPEN_DST, sport["open"], 80),
            (BLOCKED_SRC, DROPPED_DST, sport["drop"], 80),
            (BLOCKED_SRC, DENY_DST, sport["deny"], 80),
        ])
        done = set()
        r = dp.step(batch, now=now)
        done = {n for n in ("t-open", "t-drop", "t-deny")
                if tfc.results[n].phase == "Succeeded"}
        assert done == {"t-open", "t-drop", "t-deny"}, (r.code, done)
        statuses.append({n: tfc.results[n] for n in done})
    tpu, orc = statuses
    for name in ("t-open", "t-drop", "t-deny"):
        assert tpu[name].verdict == orc[name].verdict, name
        assert tpu[name].observations == orc[name].observations, name
    assert tpu["t-open"].verdict == "Allow"
    assert tpu["t-drop"].verdict == "Drop"
    assert tpu["t-deny"].verdict == "Drop"
    # Rule attribution: explicit rule vs K8s isolation (no rule).
    ing = {s["component"]: s for s in tpu["t-drop"].observations}
    assert ing["IngressSecurity"]["networkPolicyRule"] == "drop-in/In/0"
    deny_ing = {s["component"]: s for s in tpu["t-deny"].observations}
    assert deny_ing["IngressSecurity"]["action"] == "Dropped"
    assert deny_ing["IngressSecurity"]["networkPolicyRule"] is None
    # The sampled packet is reported verbatim.
    cap = ing["Classification"]["capturedPacket"]
    assert (cap["srcIP"], cap["srcPort"]) == (BLOCKED_SRC, sport["drop"])


def test_live_dropped_only_skips_allowed_matches():
    """droppedOnly: an ALLOWED packet matching the filter must NOT
    complete the trace; the first DENIED match does — on both engines."""
    for tfc, dp in _engines():
        tfc.start_live(_live(DROPPED_DST, "t-do", dropped_only=True), "n0")
        ok_sport, bad_sport = next(_SPORT), next(_SPORT)
        # OTHER_SRC is not in the blocked group: allowed, matches filter.
        dp.step(_pkt_batch([(OTHER_SRC, DROPPED_DST, ok_sport, 80)]),
                now=next(_NOW))
        assert tfc.results["t-do"].phase == "Running"
        dp.step(_pkt_batch([(BLOCKED_SRC, DROPPED_DST, bad_sport, 80)]),
                now=next(_NOW))
        st = tfc.results["t-do"]
        assert st.phase == "Succeeded" and st.verdict == "Drop"
        cap = st.observations[0]["capturedPacket"]
        assert cap["srcIP"] == BLOCKED_SRC and cap["srcPort"] == bad_sport
        assert st.observations[0]["droppedOnly"] is True


def test_live_sampling_captures_nth_match():
    """sampling=2: the first matching packet is thinned out, the second
    completes the trace."""
    for tfc, dp in _engines():
        tfc.start_live(_live(OPEN_DST, "t-s", sampling=2), "n0")
        s1, s2 = next(_SPORT), next(_SPORT)
        dp.step(_pkt_batch([(OTHER_SRC, OPEN_DST, s1, 80)]), now=next(_NOW))
        assert tfc.results["t-s"].phase == "Running"
        dp.step(_pkt_batch([(OTHER_SRC, OPEN_DST, s2, 80)]), now=next(_NOW))
        st = tfc.results["t-s"]
        assert st.phase == "Succeeded"
        assert st.observations[0]["capturedPacket"]["srcPort"] == s2
        assert st.observations[0]["sampling"] == 2


def test_live_timeout_fails_session():
    """A live session nothing matches fails at GC with a timeout status
    and returns its tag to the pool."""
    clock = [0.0]
    tfc = TraceflowController(clock=lambda: clock[0])
    dp = tfc.tap("n0", OracleDatapath(_ps(), [], flow_slots=SLOTS,
                                      aff_slots=1 << 8))
    tfc.start_live(_live(DROPPED_DST, "t-to",
                         src_ip="10.9.9.9"), "n0")  # never matches
    dp.step(_pkt_batch([(OTHER_SRC, OPEN_DST, next(_SPORT), 80)]),
            now=next(_NOW))
    assert tfc.results["t-to"].phase == "Running"
    clock[0] = 1000.0
    tfc.gc()
    st = tfc.results["t-to"]
    assert st.phase == "Failed"
    assert "timeout" in st.observations[0]["action"]
    assert len(tfc._free) == _free_full()


def _free_full() -> int:
    from antrea_tpu.controller.traceflow import _MAX_TAG

    return _MAX_TAG


def test_antctl_live_traceflow_end_to_end(capsys):
    """antctl traceflow --live against a live agent API server whose
    datapath is tapped: a background stepping loop supplies the traffic,
    the CLI returns the sampled per-stage trace."""
    from antrea_tpu import antctl
    from antrea_tpu.agent.apiserver import AgentApiServer

    tfc = TraceflowController()
    dp = tfc.tap("n0", OracleDatapath(_ps(), [], flow_slots=SLOTS,
                                      aff_slots=1 << 8))
    srv = AgentApiServer(dp, node="n0", tf_controller=tfc).start()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            dp.step(_pkt_batch([
                (BLOCKED_SRC, DROPPED_DST, next(_SPORT), 80),
            ]), now=next(_NOW))
            time.sleep(0.02)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        rc = antctl.main([
            "traceflow", "--live", "--server", srv.address,
            "--dst", DROPPED_DST, "--dport", "80", "--dropped-only",
            "--wait", "10",
        ])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0, out
        assert out["phase"] == "Succeeded" and out["verdict"] == "Drop"
        comps = [o["component"] for o in out["observations"]]
        assert comps[0] == "Classification" and comps[-1] == "Output"
        assert out["observations"][0]["capturedPacket"]["dstIP"] == DROPPED_DST
    finally:
        stop.set()
        t.join(timeout=2)
        srv.close()
