"""Hand-authored connectivity truth-table fixtures.

These scenarios break the oracle<->kernel parity circularity (VERDICT round 1
weak #3): every expected verdict below was written BY HAND from the
reference's documented semantics — NOT derived from the oracle or the kernel.
Both implementations are tested against these tables.

Method modeled on the reference's e2e NetworkPolicy harness: a `Reachability`
truth table over pod pairs diffed against probes
(/root/reference/test/e2e/utils/reachability.go:209-310, policies built by
/root/reference/test/e2e/utils/*_spec_builder.go), plus the worked pipeline
examples in /root/reference/docs/design/ovs-pipeline.md (conjunctive-match
section :1685-1760, ServiceLB/DNAT :1028-1158) and upstream K8s
NetworkPolicy isolation semantics (reference realizes them via the
IngressDefaultRule/EgressDefaultRule tables, ovs-pipeline.md:1226,1271-1272,
1793-1794).

Encoding: expected codes are 0=Allow 1=Drop 2=Reject (VerdictCode).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from antrea_tpu.apis.controlplane import (
    PROTO_ICMP,
    PROTO_TCP,
    PROTO_UDP,
    AddressGroup,
    AppliedToGroup,
    Direction,
    GroupMember,
    IPBlock,
    NetworkPolicy,
    NetworkPolicyPeer,
    NetworkPolicyRule,
    NetworkPolicyType,
    RuleAction,
    Service,
    TIER_APPLICATION,
    TIER_BASELINE,
    TIER_EMERGENCY,
    TIER_SECURITYOPS,
)
from antrea_tpu.compiler.ir import PolicySet

ALLOW, DROP, REJECT = 0, 1, 2

# The pod universe shared by all scenarios (reachability-style fixed pods).
PODS = {
    "client": "10.10.0.26",
    "web": "10.10.0.7",
    "db": "10.10.0.33",
    "other": "10.10.1.5",
}
EXTERNAL = {
    "ext_in_block": "10.0.0.5",  # inside 10.0.0.0/24, outside the except
    "ext_in_except": "10.0.0.200",  # inside 10.0.0.128/25 except hole
    "ext_out_block": "203.0.113.9",
}


def _ip(name: str) -> str:
    return PODS.get(name) or EXTERNAL[name]


def ag(name: str, *pods: str, ip_blocks: list[IPBlock] | None = None) -> AddressGroup:
    return AddressGroup(
        name=name,
        members=[GroupMember(ip=_ip(p)) for p in pods],
        ip_blocks=list(ip_blocks or []),
    )


def atg(name: str, *pods: str) -> AppliedToGroup:
    return AppliedToGroup(name=name, members=[GroupMember(ip=_ip(p)) for p in pods])


def peer(*groups: str, ip_blocks: list[IPBlock] | None = None) -> NetworkPolicyPeer:
    return NetworkPolicyPeer(address_groups=list(groups), ip_blocks=list(ip_blocks or []))


def rule(
    direction: Direction,
    peer_: NetworkPolicyPeer | None = None,
    services: list[Service] | None = None,
    action: RuleAction = RuleAction.ALLOW,
    priority: int = -1,
    applied_to: list[str] | None = None,
) -> NetworkPolicyRule:
    p = peer_ if peer_ is not None else NetworkPolicyPeer()
    kw = dict(
        direction=direction,
        services=list(services or []),
        action=action,
        priority=priority,
        applied_to_groups=list(applied_to or []),
    )
    if direction == Direction.IN:
        return NetworkPolicyRule(from_peer=p, **kw)
    return NetworkPolicyRule(to_peer=p, **kw)


def k8s_np(
    uid: str,
    applied: list[str],
    rules: list[NetworkPolicyRule],
    policy_types: list[Direction],
) -> NetworkPolicy:
    return NetworkPolicy(
        uid=uid, name=uid, namespace="default", type=NetworkPolicyType.K8S,
        rules=rules, applied_to_groups=applied, policy_types=policy_types,
    )


def acnp(
    uid: str,
    applied: list[str],
    rules: list[NetworkPolicyRule],
    tier: int = TIER_APPLICATION,
    priority: float = 5.0,
) -> NetworkPolicy:
    for i, r in enumerate(rules):
        if r.priority < 0:
            r.priority = i
    return NetworkPolicy(
        uid=uid, name=uid, type=NetworkPolicyType.ACNP, rules=rules,
        applied_to_groups=applied, tier_priority=tier, priority=priority,
    )


@dataclass
class Probe:
    src: str  # pod name or external name
    dst: str
    expect: int
    proto: int = PROTO_TCP
    dport: int = 80
    sport: int = 33000


@dataclass
class Scenario:
    name: str
    cite: str  # where in the reference these semantics are documented
    ps: PolicySet
    probes: list[Probe] = field(default_factory=list)


def _ps(policies, addr_groups=(), applied_groups=()) -> PolicySet:
    return PolicySet(
        policies=list(policies),
        address_groups={g.name: g for g in addr_groups},
        applied_to_groups={g.name: g for g in applied_groups},
    )


SCENARIOS: list[Scenario] = []


def S(s: Scenario):
    SCENARIOS.append(s)
    return s


# ---------------------------------------------------------------------------
# K8s NetworkPolicy semantics
# ---------------------------------------------------------------------------

S(Scenario(
    name="no-policy-default-allow",
    cite="K8s NP model: non-isolated pods accept all traffic "
         "(ovs-pipeline.md table-miss allow; no default-deny without a policy)",
    ps=_ps([]),
    probes=[
        Probe("client", "web", ALLOW),
        Probe("web", "db", ALLOW, proto=PROTO_UDP, dport=53),
        Probe("ext_out_block", "other", ALLOW),
    ],
))

S(Scenario(
    name="k8s-ingress-allow-from-group",
    cite="ovs-pipeline.md IngressRule/IngressDefaultRule: selected pod is "
         "ingress-isolated; allow rules punch holes (K8s NP semantics)",
    ps=_ps(
        [k8s_np("np-web", ["at-web"],
                [rule(Direction.IN, peer("g-client"))], [Direction.IN])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW),
        Probe("db", "web", DROP),  # not in the allowed group -> default deny
        Probe("other", "web", DROP),
        Probe("web", "client", ALLOW),  # egress at web unaffected
        Probe("client", "db", ALLOW),  # db not selected -> unaffected
    ],
))

S(Scenario(
    name="k8s-zero-rule-isolation",
    cite="K8s NP: a policy with policyTypes=[Ingress] and no rules isolates "
         "the selected pods completely (deny-all ingress); reference installs "
         "only the default-deny flow (pipeline.go IngressDefaultRule)",
    ps=_ps(
        [k8s_np("deny-all-in", ["at-web"], [], [Direction.IN])],
        [],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP),
        Probe("db", "web", DROP),
        Probe("web", "client", ALLOW),  # egress not in policyTypes
    ],
))

S(Scenario(
    name="k8s-egress-isolation",
    cite="ovs-pipeline.md:1271-1272 — as soon as an egress rule applies to a "
         "pod, its default egress becomes deny",
    ps=_ps(
        [k8s_np("np-client-out", ["at-client"],
                [rule(Direction.OUT, peer("g-web"))], [Direction.OUT])],
        [ag("g-web", "web")],
        [atg("at-client", "client")],
    ),
    probes=[
        Probe("client", "web", ALLOW),
        Probe("client", "db", DROP),
        Probe("client", "ext_out_block", DROP),
        Probe("db", "client", ALLOW),  # ingress at client unaffected
        Probe("web", "db", ALLOW),  # other pods unaffected
    ],
))

S(Scenario(
    name="k8s-port-scoped-rule",
    cite="K8s NP ports: allow rule constrained to TCP/80; other ports and "
         "protocols of an isolated pod stay denied (conjunction dimension 3, "
         "ovs-pipeline.md flows 5/9)",
    ps=_ps(
        [k8s_np("np-web-80", ["at-web"],
                [rule(Direction.IN, peer("g-client"),
                      [Service(PROTO_TCP, 80)])], [Direction.IN])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW, proto=PROTO_TCP, dport=80),
        Probe("client", "web", DROP, proto=PROTO_TCP, dport=8080),
        Probe("client", "web", DROP, proto=PROTO_UDP, dport=80),
        Probe("db", "web", DROP, proto=PROTO_TCP, dport=80),
    ],
))

S(Scenario(
    name="k8s-ipblock-except",
    cite="controlplane.IPBlock (types.go:376): CIDR allow with except holes",
    ps=_ps(
        [k8s_np("np-web-cidr", ["at-web"],
                [rule(Direction.IN,
                      peer(ip_blocks=[IPBlock("10.0.0.0/24",
                                              ("10.0.0.128/25",))]))],
                [Direction.IN])],
        [],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("ext_in_block", "web", ALLOW),
        Probe("ext_in_except", "web", DROP),
        Probe("ext_out_block", "web", DROP),
    ],
))

S(Scenario(
    name="k8s-union-of-policies",
    cite="K8s NP: multiple policies selecting the same pod union their allow "
         "rules",
    ps=_ps(
        [
            k8s_np("np-a", ["at-web"],
                   [rule(Direction.IN, peer("g-client"))], [Direction.IN]),
            k8s_np("np-b", ["at-web"],
                   [rule(Direction.IN, peer("g-db"))], [Direction.IN]),
        ],
        [ag("g-client", "client"), ag("g-db", "db")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW),
        Probe("db", "web", ALLOW),
        Probe("other", "web", DROP),
    ],
))

S(Scenario(
    name="k8s-any-peer-rule",
    cite="K8s NP: empty from-peer means all sources (port-only rule)",
    ps=_ps(
        [k8s_np("np-web-anypeer", ["at-web"],
                [rule(Direction.IN, None, [Service(PROTO_TCP, 443)])],
                [Direction.IN])],
        [],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("ext_out_block", "web", ALLOW, dport=443),
        Probe("client", "web", ALLOW, dport=443),
        Probe("client", "web", DROP, dport=80),
    ],
))

S(Scenario(
    name="egress-deny-wins-over-ingress-allow",
    cite="full-packet combine: egress evaluation at source and ingress at "
         "destination; any deny wins (EgressSecurity stage precedes "
         "IngressSecurity, framework.go:96-118)",
    ps=_ps(
        [
            k8s_np("np-client-out", ["at-client"],
                   [rule(Direction.OUT, peer("g-web"))], [Direction.OUT]),
            k8s_np("np-db-in", ["at-db"],
                   [rule(Direction.IN, peer("g-client"))], [Direction.IN]),
        ],
        [ag("g-web", "web"), ag("g-client", "client")],
        [atg("at-client", "client"), atg("at-db", "db")],
    ),
    probes=[
        # db ingress would allow client, but client egress only allows web.
        Probe("client", "db", DROP),
        Probe("client", "web", ALLOW),
    ],
))

# ---------------------------------------------------------------------------
# Antrea-native policy semantics (tiers, priorities, actions)
# ---------------------------------------------------------------------------

S(Scenario(
    name="acnp-drop-beats-k8s-allow",
    cite="ovs-pipeline.md:1685-1760 — AntreaPolicyIngressRule table is "
         "evaluated before IngressRule (K8s); first match decides",
    ps=_ps(
        [
            acnp("acnp-deny-client", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.DROP)]),
            k8s_np("np-allow-client", ["at-web"],
                   [rule(Direction.IN, peer("g-client"))], [Direction.IN]),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP),
        Probe("db", "web", DROP),  # still K8s-isolated, no allow rule for db
    ],
))

S(Scenario(
    name="acnp-allow-shortcircuits-k8s-isolation",
    cite="AntreaPolicy Allow is final: matching packets jump to metric/output "
         "and never reach the K8s default-deny (ovs-pipeline.md flow 6/10)",
    ps=_ps(
        [
            acnp("acnp-allow-client", ["at-web"],
                 [rule(Direction.IN, peer("g-client"))]),
            k8s_np("deny-all-in", ["at-web"], [], [Direction.IN]),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW),
        Probe("db", "web", DROP),
    ],
))

S(Scenario(
    name="acnp-reject-action",
    cite="RuleAction.Reject (crd/v1beta1): reject-kind verdict, distinct "
         "from Drop (reject.go synthesizes RST/ICMP)",
    ps=_ps(
        [acnp("acnp-reject", ["at-web"],
              [rule(Direction.IN, peer("g-client"),
                    action=RuleAction.REJECT)])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", REJECT),
        Probe("db", "web", ALLOW),  # no K8s isolation here
    ],
))

S(Scenario(
    name="acnp-pass-defers-to-k8s",
    cite="RuleAction.Pass: skips remaining Antrea-native tiers (except "
         "Baseline), defers to K8s NP evaluation",
    ps=_ps(
        [
            acnp("acnp-pass", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.PASS)],
                 tier=TIER_SECURITYOPS),
            # Later tier drop that Pass must skip:
            acnp("acnp-late-drop", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.DROP)],
                 tier=TIER_APPLICATION),
            k8s_np("np-allow-client", ["at-web"],
                   [rule(Direction.IN, peer("g-client"))], [Direction.IN]),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW),  # Pass -> K8s allow
        Probe("db", "web", DROP),  # K8s isolation, no allow rule
    ],
))

S(Scenario(
    name="acnp-pass-to-k8s-deny",
    cite="Pass with no matching K8s allow rule on an isolated pod -> K8s "
         "default deny",
    ps=_ps(
        [
            acnp("acnp-pass", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.PASS)]),
            k8s_np("np-allow-db", ["at-web"],
                   [rule(Direction.IN, peer("g-db"))], [Direction.IN]),
        ],
        [ag("g-client", "client"), ag("g-db", "db")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP),
        Probe("db", "web", ALLOW),
    ],
))

S(Scenario(
    name="tier-ordering",
    cite="spec.tier is the primary priority level (ovs-pipeline.md tier/"
         "priority ordering rules); Emergency tier evaluated before "
         "Application",
    ps=_ps(
        [
            acnp("emergency-drop", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.DROP)],
                 tier=TIER_EMERGENCY),
            acnp("app-allow", ["at-web"],
                 [rule(Direction.IN, peer("g-client"))],
                 tier=TIER_APPLICATION),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[Probe("client", "web", DROP)],
))

S(Scenario(
    name="policy-priority-within-tier",
    cite="spec.priority is the secondary level within a tier; LOWER value = "
         "higher priority (ovs-pipeline.md)",
    ps=_ps(
        [
            acnp("prio2-drop", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.DROP)],
                 priority=2.0),
            acnp("prio1-allow", ["at-web"],
                 [rule(Direction.IN, peer("g-client"))],
                 priority=1.0),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[Probe("client", "web", ALLOW)],
))

S(Scenario(
    name="rule-order-within-policy",
    cite="rules positioned earlier in a policy have higher priority "
         "(ovs-pipeline.md flows 7-13: AllowFromClient at 14600 above the "
         "policy's own Drop default at 14599)",
    ps=_ps(
        [acnp("allow-then-drop", ["at-web"], [
            rule(Direction.IN, peer("g-client"),
                 [Service(PROTO_TCP, 80)], RuleAction.ALLOW),
            rule(Direction.IN, None, action=RuleAction.DROP),
        ])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW, dport=80),
        Probe("client", "web", DROP, dport=8080),
        Probe("db", "web", DROP),
    ],
))

S(Scenario(
    name="baseline-after-k8s",
    cite="Baseline tier is evaluated AFTER K8s NetworkPolicies "
         "(IngressDefaultRule table order; docs/antrea-network-policy "
         "baseline semantics)",
    ps=_ps(
        [
            acnp("baseline-drop", ["at-web"],
                 [rule(Direction.IN, peer("g-client"),
                       action=RuleAction.DROP)],
                 tier=TIER_BASELINE),
            k8s_np("np-allow-client", ["at-web"],
                   [rule(Direction.IN, peer("g-client"))], [Direction.IN]),
        ],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", ALLOW),  # K8s allow decides before baseline
        Probe("db", "web", DROP),  # isolated + no allow
    ],
))

S(Scenario(
    name="baseline-drop-nonisolated",
    cite="Baseline rules apply to pods with no K8s NP (defense-in-depth "
         "default-deny via baseline tier)",
    ps=_ps(
        [acnp("baseline-drop", ["at-web"],
              [rule(Direction.IN, peer("g-client"),
                    action=RuleAction.DROP)],
              tier=TIER_BASELINE)],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP),
        Probe("db", "web", ALLOW),  # baseline rule peer doesn't match
        Probe("client", "db", ALLOW),  # db not in appliedTo
    ],
))

S(Scenario(
    name="acnp-egress-drop",
    cite="AntreaPolicyEgressRule: egress direction evaluated at the source "
         "pod (EgressSecurity stage)",
    ps=_ps(
        [acnp("deny-client-to-ext", ["at-client"],
              [rule(Direction.OUT,
                    peer(ip_blocks=[IPBlock("203.0.113.0/24")]),
                    action=RuleAction.DROP)])],
        [],
        [atg("at-client", "client")],
    ),
    probes=[
        Probe("client", "ext_out_block", DROP),
        Probe("client", "web", ALLOW),
        Probe("db", "ext_out_block", ALLOW),
    ],
))

S(Scenario(
    name="acnp-port-range",
    cite="Service.endPort: port range match (types.go:299)",
    ps=_ps(
        [acnp("range-drop", ["at-web"], [
            rule(Direction.IN, None,
                 [Service(PROTO_TCP, 8000, 9000)], RuleAction.DROP),
        ])],
        [],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP, dport=8500),
        Probe("client", "web", DROP, dport=8000),
        Probe("client", "web", DROP, dport=9000),
        Probe("client", "web", ALLOW, dport=7999),
        Probe("client", "web", ALLOW, dport=9001),
    ],
))

S(Scenario(
    name="acnp-per-rule-applied-to",
    cite="NetworkPolicyRule.AppliedToGroups override (types.go:248): ANNP "
         "rule-level appliedTo",
    ps=_ps(
        [acnp("per-rule-at", ["at-web"], [
            rule(Direction.IN, peer("g-client"), action=RuleAction.DROP,
                 applied_to=["at-db"]),
        ])],
        [ag("g-client", "client")],
        [atg("at-web", "web"), atg("at-db", "db")],
    ),
    probes=[
        Probe("client", "db", DROP),  # rule-level appliedTo wins
        Probe("client", "web", ALLOW),  # policy-level appliedTo NOT used
    ],
))

S(Scenario(
    name="proto-any-service",
    cite="Service.protocol nil = any protocol (types.go:299)",
    ps=_ps(
        [acnp("drop-any-proto", ["at-web"],
              [rule(Direction.IN, peer("g-client"),
                    [Service(None, None)], RuleAction.DROP)])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP, proto=PROTO_TCP, dport=80),
        Probe("client", "web", DROP, proto=PROTO_UDP, dport=53),
        Probe("client", "web", DROP, proto=PROTO_ICMP, dport=0),
    ],
))

S(Scenario(
    name="icmp-ignores-ports",
    cite="port matches apply to TCP/UDP/SCTP only; ICMP rules match on "
         "protocol alone",
    ps=_ps(
        [acnp("drop-icmp", ["at-web"],
              [rule(Direction.IN, None,
                    [Service(PROTO_ICMP, None)], RuleAction.DROP)])],
        [],
        [atg("at-web", "web")],
    ),
    probes=[
        Probe("client", "web", DROP, proto=PROTO_ICMP, dport=0),
        Probe("client", "web", ALLOW, proto=PROTO_TCP, dport=80),
    ],
))
