"""Central control plane tests: grouping index incrementality, raw->internal
policy computation, span dissemination, and the full L4->L2 path (raw K8s
objects -> controller -> compiler -> kernel verdicts vs oracle).

Reference behaviors being mirrored:
  grouping index               group_entity_index.go:57
  syncAddressGroup/AppliedTo   networkpolicy_controller.go:1096,1297
  span computation             networkpolicy_controller.go:1498
"""

import numpy as np

from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    AntreaAppliedTo,
    AntreaNetworkPolicy,
    AntreaNPRule,
    AntreaPeer,
    K8sNetworkPolicy,
    K8sNPRule,
    K8sPeer,
    LabelSelector,
    Namespace,
    Pod,
    PortSpec,
)
from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.controller import (
    GroupEntityIndex,
    GroupSelector,
    NetworkPolicyController,
)
from antrea_tpu.ops.match import flip_ips, make_classifier
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil


def mk_pod(name, ip, node="n0", ns="default", **labels):
    return Pod(namespace=ns, name=name, ip=ip, node=node, labels=labels)


# ---------------------------------------------------------------------------
# Grouping index
# ---------------------------------------------------------------------------


def test_grouping_bucket_and_match():
    idx = GroupEntityIndex()
    events = []
    idx.add_event_handler(lambda keys: events.append(set(keys)))

    key = idx.add_group(GroupSelector(
        namespace="default", pod_selector=LabelSelector.make({"app": "web"})
    ))
    idx.upsert_pod(mk_pod("w1", "10.0.0.1", app="web"))
    idx.upsert_pod(mk_pod("w2", "10.0.0.2", app="web"))
    idx.upsert_pod(mk_pod("c1", "10.0.0.3", app="client"))
    members = {p.name for p in idx.get_members(key)}
    assert members == {"w1", "w2"}
    # Only web-pod churn produced change events; the client pod (matching
    # no group) produced none at all.
    assert len(events) == 2 and all(key in e for e in events)


def test_grouping_label_change_moves_pod():
    idx = GroupEntityIndex()
    key = idx.add_group(GroupSelector(
        namespace="default", pod_selector=LabelSelector.make({"app": "web"})
    ))
    idx.upsert_pod(mk_pod("p", "10.0.0.1", app="web"))
    assert {p.name for p in idx.get_members(key)} == {"p"}
    idx.upsert_pod(mk_pod("p", "10.0.0.1", app="client"))  # relabel
    assert idx.get_members(key) == []
    idx.delete_pod("default/p")
    assert idx.get_members(key) == []


def test_grouping_namespace_selector():
    idx = GroupEntityIndex()
    idx.upsert_namespace(Namespace("prod", {"env": "prod"}))
    idx.upsert_namespace(Namespace("dev", {"env": "dev"}))
    key = idx.add_group(GroupSelector(
        namespace="", ns_selector=LabelSelector.make({"env": "prod"})
    ))
    idx.upsert_pod(mk_pod("a", "10.0.0.1", ns="prod"))
    idx.upsert_pod(mk_pod("b", "10.0.0.2", ns="dev"))
    assert {p.name for p in idx.get_members(key)} == {"a"}
    # Relabel the dev namespace into prod: membership must follow.
    idx.upsert_namespace(Namespace("dev", {"env": "prod"}))
    assert {p.name for p in idx.get_members(key)} == {"a", "b"}


def test_grouping_match_expressions():
    idx = GroupEntityIndex()
    from antrea_tpu.apis.crd import OP_NOT_IN, SelectorRequirement

    key = idx.add_group(GroupSelector(
        namespace="default",
        pod_selector=LabelSelector.make(
            expressions=[SelectorRequirement("tier", OP_NOT_IN, ("db",))]
        ),
    ))
    idx.upsert_pod(mk_pod("a", "10.0.0.1", tier="web"))
    idx.upsert_pod(mk_pod("b", "10.0.0.2", tier="db"))
    idx.upsert_pod(mk_pod("c", "10.0.0.3"))
    assert {p.name for p in idx.get_members(key)} == {"a", "c"}


# ---------------------------------------------------------------------------
# NetworkPolicy controller: computation + incremental deltas + span
# ---------------------------------------------------------------------------


def _small_cluster(ctl):
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(mk_pod("web1", "10.0.0.10", node="nodeA", app="web"))
    ctl.upsert_pod(mk_pod("web2", "10.0.0.11", node="nodeB", app="web"))
    ctl.upsert_pod(mk_pod("cli1", "10.0.0.20", node="nodeB", app="client"))
    ctl.upsert_pod(mk_pod("db1", "10.0.0.30", node="nodeC", app="db"))


def _k8s_np_web_from_client(uid="np1"):
    return K8sNetworkPolicy(
        uid=uid, namespace="default", name=uid,
        pod_selector=LabelSelector.make({"app": "web"}),
        policy_types=[Direction.IN],
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "client"}))],
            ports=[PortSpec(protocol=6, port=80)],
        )],
    )


def test_controller_k8s_np_verdicts():
    ctl = NetworkPolicyController()
    _small_cluster(ctl)
    ctl.upsert_k8s_policy(_k8s_np_web_from_client())
    ps = ctl.policy_set()
    oracle = Oracle(ps)

    def code(src, dst, dport=80):
        return int(oracle.classify(Packet(
            src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
            proto=6, src_port=40000, dst_port=dport,
        )).code)

    assert code("10.0.0.20", "10.0.0.10") == 0  # client -> web :80 allowed
    assert code("10.0.0.30", "10.0.0.10") == 1  # db -> web denied (isolated)
    assert code("10.0.0.20", "10.0.0.10", dport=443) == 1  # wrong port
    assert code("10.0.0.10", "10.0.0.30") == 0  # egress unaffected


def test_controller_incremental_pod_events():
    ctl = NetworkPolicyController()
    events = []
    ctl.subscribe(events.append)
    _small_cluster(ctl)
    ctl.upsert_k8s_policy(_k8s_np_web_from_client())
    events.clear()

    # A new client pod appears: exactly the client AddressGroup updates,
    # with an incremental member delta.
    ctl.upsert_pod(mk_pod("cli2", "10.0.0.21", node="nodeC", app="client"))
    ag_updates = [e for e in events if e.obj_type == "AddressGroup" and e.kind == "UPDATED"]
    assert len(ag_updates) == 1
    assert [m.ip for m in ag_updates[0].added] == ["10.0.0.21"]
    assert ag_updates[0].removed == []
    assert not [e for e in events if e.obj_type == "AppliedToGroup"]

    events.clear()
    # A new web pod on a NEW node: AppliedToGroup delta + NP span gains nodeD.
    ctl.upsert_pod(mk_pod("web3", "10.0.0.12", node="nodeD", app="web"))
    atg_updates = [e for e in events if e.obj_type == "AppliedToGroup" and e.kind == "UPDATED"]
    assert len(atg_updates) == 1
    assert [m.ip for m in atg_updates[0].added] == ["10.0.0.12"]
    np_updates = [e for e in events if e.obj_type == "NetworkPolicy"]
    assert np_updates and "nodeD" in np_updates[0].span

    events.clear()
    # Deleting it reverses the membership.
    ctl.delete_pod("default/web3")
    atg_updates = [e for e in events if e.obj_type == "AppliedToGroup" and e.kind == "UPDATED"]
    assert [m.ip for m in atg_updates[0].removed] == ["10.0.0.12"]


def test_controller_span_filtering():
    ctl = NetworkPolicyController()
    _small_cluster(ctl)
    ctl.upsert_k8s_policy(_k8s_np_web_from_client())
    # web pods are on nodeA and nodeB only.
    assert len(ctl.policy_set_for_node("nodeA").policies) == 1
    assert len(ctl.policy_set_for_node("nodeB").policies) == 1
    assert len(ctl.policy_set_for_node("nodeC").policies) == 0
    # The node snapshot carries the groups the policy references.
    ps_a = ctl.policy_set_for_node("nodeA")
    assert len(ps_a.applied_to_groups) == 1
    assert len(ps_a.address_groups) == 1


def test_controller_group_sharing_and_gc():
    """Two policies with the same peer selector share one AddressGroup
    (content-addressing, the conjMatchFlowContext-sharing analog at the
    control plane); deleting one policy keeps it, deleting both GCs it."""
    ctl = NetworkPolicyController()
    _small_cluster(ctl)
    ctl.upsert_k8s_policy(_k8s_np_web_from_client("np1"))
    np2 = _k8s_np_web_from_client("np2")
    np2.pod_selector = LabelSelector.make({"app": "db"})
    ctl.upsert_k8s_policy(np2)
    ps = ctl.policy_set()
    assert len(ps.address_groups) == 1  # shared client group
    assert len(ps.applied_to_groups) == 2

    events = []
    ctl.subscribe(events.append)
    ctl.delete_policy("np1")
    assert not [e for e in events if e.obj_type == "AddressGroup" and e.kind == "DELETED"]
    ctl.delete_policy("np2")
    assert [e for e in events if e.obj_type == "AddressGroup" and e.kind == "DELETED"]
    assert ctl.policy_set().address_groups == {}


def test_controller_acnp_and_annp():
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("prod", {"env": "prod"}))
    ctl.upsert_pod(mk_pod("w", "10.0.1.1", node="nodeA", ns="prod", app="web"))
    ctl.upsert_pod(mk_pod("c", "10.0.1.2", node="nodeB", ns="prod", app="client"))
    ctl.upsert_pod(mk_pod("x", "10.0.2.1", node="nodeC", ns="default", app="client"))

    acnp = AntreaNetworkPolicy(
        uid="acnp1", name="deny-clients", tier_priority=250, priority=1.0,
        applied_to=[AntreaAppliedTo(pod_selector=LabelSelector.make({"app": "web"}))],
        rules=[AntreaNPRule(
            direction=Direction.IN,
            action=RuleAction.DROP,
            peers=[AntreaPeer(pod_selector=LabelSelector.make({"app": "client"}))],
        )],
    )
    ctl.upsert_antrea_policy(acnp)
    ps = ctl.policy_set()
    oracle = Oracle(ps)

    def code(src, dst):
        return int(oracle.classify(Packet(
            src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
            proto=6, src_port=1234, dst_port=80,
        )).code)

    # ACNP peer selector is cluster-wide: both clients dropped.
    assert code("10.0.1.2", "10.0.1.1") == 1
    assert code("10.0.2.1", "10.0.1.1") == 1

    # ANNP in prod: peer podSelector scoped to prod only.
    ctl.delete_policy("acnp1")
    annp = AntreaNetworkPolicy(
        uid="annp1", name="deny-prod-clients", namespace="prod",
        tier_priority=250, priority=1.0,
        applied_to=[AntreaAppliedTo(pod_selector=LabelSelector.make({"app": "web"}))],
        rules=[AntreaNPRule(
            direction=Direction.IN,
            action=RuleAction.DROP,
            peers=[AntreaPeer(pod_selector=LabelSelector.make({"app": "client"}))],
        )],
    )
    ctl.upsert_antrea_policy(annp)
    oracle = Oracle(ctl.policy_set())
    assert code("10.0.1.2", "10.0.1.1") == 1  # prod client dropped
    assert code("10.0.2.1", "10.0.1.1") == 0  # default-ns client NOT in peer


def test_controller_to_kernel_end_to_end():
    """The full L4->L2 path: raw objects through the controller, compiled,
    classified on the kernel, compared against the oracle."""
    ctl = NetworkPolicyController()
    _small_cluster(ctl)
    ctl.upsert_k8s_policy(_k8s_np_web_from_client())
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="acnp-db", name="protect-db", tier_priority=150, priority=2.0,
        applied_to=[AntreaAppliedTo(pod_selector=LabelSelector.make({"app": "db"}))],
        rules=[
            AntreaNPRule(direction=Direction.IN, action=RuleAction.ALLOW,
                         peers=[AntreaPeer(pod_selector=LabelSelector.make({"app": "web"}))]),
            AntreaNPRule(direction=Direction.IN, action=RuleAction.REJECT),
        ],
    ))
    ps = ctl.policy_set()
    cps = compile_policy_set(ps)
    fn, _ = make_classifier(cps)
    oracle = Oracle(ps)

    ips = ["10.0.0.10", "10.0.0.11", "10.0.0.20", "10.0.0.30", "10.0.9.9"]
    pkts = [
        Packet(src_ip=iputil.ip_to_u32(s), dst_ip=iputil.ip_to_u32(d),
               proto=6, src_port=40000, dst_port=p)
        for s in ips for d in ips if s != d for p in (80, 443)
    ]
    batch = PacketBatch.from_packets(pkts)
    out = fn(flip_ips(batch.src_ip), flip_ips(batch.dst_ip),
             batch.proto.astype(np.int32), batch.dst_port.astype(np.int32))
    codes = np.asarray(out["code"])
    expect = [int(oracle.classify(p).code) for p in pkts]
    assert codes.tolist() == expect
    # Sanity on the truth table itself: web->db allowed, client->db rejected.
    i = pkts.index(Packet(src_ip=iputil.ip_to_u32("10.0.0.10"),
                          dst_ip=iputil.ip_to_u32("10.0.0.30"),
                          proto=6, src_port=40000, dst_port=80))
    assert expect[i] == 0
    j = pkts.index(Packet(src_ip=iputil.ip_to_u32("10.0.0.20"),
                          dst_ip=iputil.ip_to_u32("10.0.0.30"),
                          proto=6, src_port=40000, dst_port=80))
    assert expect[j] == 2
