"""Named-port resolution (ref GroupMember.Ports, types.go:87-88).

The resolution pass (compiler/ir.resolve_named_ports) is shared by the
compiler and the oracle, so the parity tests here exercise BOTH engines on
worlds where `port: "http"` resolves to DIFFERENT numeric ports per member.
"""

import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.compile import ACT_ALLOW, ACT_DROP, compile_policy_set
from antrea_tpu.compiler.ir import PolicySet, resolve_named_ports
from antrea_tpu.ops.match import flip_ips, make_classifier
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

WEB1 = "10.0.0.1"   # exposes http=8080
WEB2 = "10.0.0.2"   # exposes http=9090
NOPORT = "10.0.0.3"  # no named ports
CLIENT = "10.0.1.9"


def _member(ip, ports=()):
    return cp.GroupMember(ip=ip, node="n0", ports=tuple(ports))


def _world():
    ps = PolicySet()
    ps.applied_to_groups["web"] = cp.AppliedToGroup(name="web", members=[
        _member(WEB1, [("http", 8080, 6)]),
        _member(WEB2, [("http", 9090, 6)]),
        _member(NOPORT),
    ])
    ps.address_groups["clients"] = cp.AddressGroup(
        name="clients", members=[_member(CLIENT)])
    ps.policies.append(cp.NetworkPolicy(
        uid="np1", name="allow-http", namespace="ns",
        type=cp.NetworkPolicyType.K8S,
        applied_to_groups=["web"],
        policy_types=[cp.Direction.IN],
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(address_groups=["clients"]),
            services=[cp.Service(protocol=6, port_name="http")],
        )],
    ))
    return ps


def test_resolution_pass_shape():
    rps = resolve_named_ports(_world())
    [p] = rps.policies
    # One expanded rule per resolved value (8080, 9090); NOPORT contributes
    # nothing; the original named service is gone.
    assert len(p.rules) == 2
    ports = sorted(s.port for r in p.rules for s in r.services)
    assert ports == [8080, 9090]
    assert all(not s.port_name for r in p.rules for s in r.services)
    for r in p.rules:
        [key] = r.applied_to_groups
        g = rps.applied_to_groups[key]
        port = r.services[0].port
        assert [m.ip for m in g.members] == [WEB1 if port == 8080 else WEB2]
    # Idempotent.
    assert resolve_named_ports(rps) is rps


def test_named_port_verdicts_oracle_and_kernel():
    ps = _world()
    oracle = Oracle(ps)
    cps = compile_policy_set(ps)
    fn, _ = make_classifier(cps)

    cases = [
        # (dst, dport, expect) — pod isolated in IN by the K8s NP.
        (WEB1, 8080, ACT_ALLOW),   # resolves http on this member
        (WEB1, 9090, ACT_DROP),    # the OTHER member's value: no match
        (WEB2, 9090, ACT_ALLOW),
        (WEB2, 8080, ACT_DROP),
        (NOPORT, 8080, ACT_DROP),  # member has no named port: never matches
    ]
    pkts = [Packet(src_ip=iputil.ip_to_u32(CLIENT),
                   dst_ip=iputil.ip_to_u32(d), proto=6,
                   src_port=40000, dst_port=dp) for d, dp, _ in cases]
    batch = PacketBatch.from_packets(pkts)
    out = fn(flip_ips(batch.src_ip), flip_ips(batch.dst_ip),
             batch.proto.astype(np.int32), batch.dst_port.astype(np.int32))
    codes = np.asarray(out["code"])
    for i, (d, dp, expect) in enumerate(cases):
        o = int(oracle.classify(pkts[i]).code)
        assert o == expect, (d, dp, "oracle", o)
        assert int(codes[i]) == expect, (d, dp, "kernel", int(codes[i]))


def test_named_port_egress_peer_resolution():
    """Egress rules resolve the name on the PEER (destination) members."""
    ps = PolicySet()
    ps.applied_to_groups["clients"] = cp.AppliedToGroup(
        name="clients", members=[_member(CLIENT)])
    ps.address_groups["web"] = cp.AddressGroup(name="web", members=[
        _member(WEB1, [("http", 8080, 6)]),
        _member(WEB2, [("http", 9090, 6)]),
    ])
    ps.policies.append(cp.NetworkPolicy(
        uid="acnp1", name="deny-http", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["clients"],
        tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.OUT,
            to_peer=cp.NetworkPolicyPeer(address_groups=["web"]),
            services=[cp.Service(protocol=6, port_name="http")],
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    oracle = Oracle(ps)
    cps = compile_policy_set(ps)
    fn, _ = make_classifier(cps)
    cases = [
        (WEB1, 8080, ACT_DROP),
        (WEB1, 9090, ACT_ALLOW),  # 9090 is WEB2's value, not WEB1's
        (WEB2, 9090, ACT_DROP),
    ]
    pkts = [Packet(src_ip=iputil.ip_to_u32(CLIENT),
                   dst_ip=iputil.ip_to_u32(d), proto=6,
                   src_port=40000, dst_port=dp) for d, dp, _ in cases]
    batch = PacketBatch.from_packets(pkts)
    out = fn(flip_ips(batch.src_ip), flip_ips(batch.dst_ip),
             batch.proto.astype(np.int32), batch.dst_port.astype(np.int32))
    for i, (d, dp, expect) in enumerate(cases):
        assert int(oracle.classify(pkts[i]).code) == expect, (d, dp, "oracle")
        assert int(np.asarray(out["code"])[i]) == expect, (d, dp, "kernel")


def test_protocolless_named_service_resolves_per_protocol():
    """A service with port_name and NO protocol resolves per (name,
    protocol) pair per member: a member exposing dns/TCP=53 and
    dns/UDP=5353 yields BOTH, each as a protocol-narrowed rule (the
    reference resolves named ports per pair, not first-match)."""
    ps = PolicySet()
    ps.applied_to_groups["dns"] = cp.AppliedToGroup(name="dns", members=[
        _member(WEB1, [("dns", 53, 6), ("dns", 5353, 17)]),
    ])
    ps.address_groups["clients"] = cp.AddressGroup(
        name="clients", members=[_member(CLIENT)])
    ps.policies.append(cp.NetworkPolicy(
        uid="np1", name="allow-dns", namespace="ns",
        type=cp.NetworkPolicyType.K8S,
        applied_to_groups=["dns"],
        policy_types=[cp.Direction.IN],
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(address_groups=["clients"]),
            services=[cp.Service(protocol=None, port_name="dns")],
        )],
    ))
    rps = resolve_named_ports(ps)
    [p] = rps.policies
    resolved = sorted((s.port, s.protocol) for r in p.rules
                      for s in r.services)
    assert resolved == [(53, 6), (5353, 17)]
