"""Native ovsdb_lite config store: transactions, durability, crash-torn
tails, compaction, and native<->python wire compatibility."""

import os
import struct

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.native import ConfigStore, native_available


BACKENDS = ["python"] + (["native"] if native_available() else [])


@pytest.fixture(params=BACKENDS)
def backend(request):
    return request.param


def _open(path, backend):
    return ConfigStore(str(path), force_python=(backend == "python"))


def test_native_toolchain_builds():
    """g++ is baked into this image: the native backend must be available
    (the Python fallback exists for toolchain-less consumers, not here)."""
    assert native_available()


def test_txn_commit_abort_and_reopen(tmp_path, backend):
    p = tmp_path / "db"
    with _open(p, backend) as s:
        assert s.backend == backend
        s.set("round", b"7")
        s.set("iface/pod-a", b'{"ofport": 3}')
        s.commit()
        s.set("round", b"8")
        s.abort()  # staged mutation discarded
        assert s.get("round") == b"7"
        s.set("iface/pod-b", b"x")
        s.delete("iface/pod-a")
        s.commit()
    with _open(p, backend) as s:
        assert s.get("round") == b"7"
        assert s.get("iface/pod-a") is None
        assert s.get("iface/pod-b") == b"x"
        assert s.keys() == ["iface/pod-b", "round"]


def test_torn_tail_record_is_dropped(tmp_path, backend):
    """A crash mid-commit leaves a torn trailing record: replay keeps every
    earlier transaction and drops only the torn one (OVSDB log model)."""
    p = tmp_path / "db"
    with _open(p, backend) as s:
        s.set("a", b"1")
        s.commit()
        s.set("b", b"2")
        s.commit()
    size = os.path.getsize(p)
    with open(p, "r+b") as f:  # tear the last record
        f.truncate(size - 3)
    with _open(p, backend) as s:
        assert s.get("a") == b"1"
        assert s.get("b") is None  # torn transaction atomically lost

    # Corrupt (bit-flipped) tail: checksum rejects it the same way.
    with _open(p, backend) as s:
        s.set("c", b"3")
        s.commit()
    with open(p, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    with _open(p, backend) as s:
        assert s.get("a") == b"1" and s.get("c") is None


def test_compaction_preserves_state(tmp_path, backend):
    p = tmp_path / "db"
    with _open(p, backend) as s:
        for i in range(50):
            s.set(f"k{i}", str(i).encode() * 10)
            s.commit()
        for i in range(0, 50, 2):
            s.delete(f"k{i}")
            s.commit()
        before = os.path.getsize(p)
        s.compact()
        after = os.path.getsize(p)
        assert after < before
        assert s.get("k1") == b"1" * 10 and s.get("k0") is None
    with _open(p, backend) as s:  # compacted journal replays
        assert len(s.keys()) == 25


@pytest.mark.skipif(not native_available(), reason="no g++")
def test_native_and_python_are_wire_compatible(tmp_path):
    """Both implementations speak the same journal format: files written
    by one open cleanly in the other."""
    p = tmp_path / "db"
    with ConfigStore(str(p)) as s:
        assert s.backend == "native"
        s.set("written-by", b"native")
        s.commit()
    with ConfigStore(str(p), force_python=True) as s:
        assert s.get("written-by") == b"native"
        s.set("also", b"python")
        s.commit()
    with ConfigStore(str(p)) as s:
        assert s.get("also") == b"python"
        assert s.get("written-by") == b"native"


def test_datapath_round_storage(tmp_path):
    """The cookie-round / external-IDs usage: the store carries the round
    across a restart (agent.go:486-512 model) next to the snapshot."""
    with ConfigStore(str(tmp_path / "conf.db")) as s:
        s.set("cookie/round", struct.pack("<Q", 41))
        s.set("external-ids/node", b"n0")
        s.commit()
        s.set("cookie/round", struct.pack("<Q", 42))
        s.commit()
    with ConfigStore(str(tmp_path / "conf.db")) as s:
        (round_,) = struct.unpack("<Q", s.get("cookie/round"))
        assert round_ == 42 and s.get("external-ids/node") == b"n0"
