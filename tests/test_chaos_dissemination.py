"""Chaos tier for the dissemination plane: scripted faults, provable healing.

The reference's control plane survives real failure — agents that lose the
apiserver watch reconnect and re-list (ram/store.go:230), and the agent
reconciler requeues failed installs instead of dropping them.  This tier
proves the SAME properties of this build under a deterministic FaultPlan
(dissemination/faults.py): injected connection resets, partial writes,
agent crashes, and datapath install failures, with one convergence bar —
after every fault, every node's datapath verdicts return to parity with an
oracle compiled from the controller's own span-filtered snapshot, and no
watcher queue ever grows past its configured cap.

The single-fault smoke rides the tier-1 'not slow' set; the kill/revive
soak and the wire-level overflow test are marked slow.
"""

import itertools
import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

from antrea_tpu.agent import AgentPolicyController
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.controller.networkpolicy import NetworkPolicyController, WatchEvent
from antrea_tpu.controller.status import StatusAggregator
from antrea_tpu.datapath import OracleDatapath
from antrea_tpu.dissemination import FaultPlan, RamStore
from antrea_tpu.dissemination.faults import FaultySocket, FlakyDatapath
from antrea_tpu.dissemination.netwire import (
    Backoff,
    DisseminationServer,
    NetAgent,
    make_ca,
)
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.simulator.fleet import FakeAgent, FakeAgentFleet
from antrea_tpu.utils import ip as iputil

CAP = 16  # watcher_max_pending for every wire test in this tier

# Monotonic packet clock shared by every parity probe: re-stepping a
# datapath must never reuse a timestamp (flow-cache entries are keyed on
# real time in production too).
_NOW = itertools.count(1000)


def _policy(uid, cidr="192.0.2.0/24"):
    return crd.AntreaNetworkPolicy(
        uid=uid, name=uid, namespace="", tier_priority=250, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make())],
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP,
                                peers=[crd.AntreaPeer(
                                    ip_block=crd.IPBlock(cidr))])],
    )


def _world(tmp_path, nodes, cap=CAP):
    certdir = str(tmp_path / "pki")
    make_ca(certdir)
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agg = StatusAggregator(ctl)
    srv = DisseminationServer(store, certdir, status_aggregator=agg,
                              watcher_max_pending=cap)
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    for i, node in enumerate(nodes, 1):
        ctl.upsert_pod(crd.Pod(namespace="default", name=f"web-{node}",
                               ip=f"10.0.{i}.1", node=node,
                               labels={"app": "web"}))
    return certdir, ctl, store, agg, srv


def _agent(node, srv, certdir, plan=None):
    """NetAgent over an OracleDatapath; with a plan, both the socket
    (post-handshake) and the datapath are wrapped in fault injectors."""
    dp = OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4)
    fault_wrap = None
    if plan is not None:
        dp = FlakyDatapath(dp, plan, node)
        fault_wrap = lambda sock: FaultySocket(sock, plan, node)
    return NetAgent(node, srv.address, certdir, dp,
                    backoff=Backoff(base=0.01, cap=0.05),
                    fault_wrap=fault_wrap)


def _pkts(n_nodes):
    """Probe matrix: every web-pod IP plus one address inside each deny
    CIDR this tier uses — covers both verdict flips a policy change can
    cause."""
    ips = [f"10.0.{i}.1" for i in range(1, n_nodes + 1)]
    ips += ["192.0.2.7", "198.51.100.9", "203.0.113.5"]
    return [(s, d) for s in ips for d in ips if s != d]


def _parity(ctl, agents, pairs):
    # Every probe is a FRESH flow (unique src_port): an allowed flow the
    # datapath committed earlier is an established connection that
    # legitimately survives a policy change (conntrack semantics) — the
    # stateless oracle bar applies to new connections only.
    now = next(_NOW)
    pkts = [Packet(src_ip=iputil.ip_to_u32(s), dst_ip=iputil.ip_to_u32(d),
                   proto=6, src_port=20000 + now % 40000, dst_port=80)
            for s, d in pairs]
    batch = PacketBatch.from_packets(pkts)
    for node, a in agents.items():
        oracle = Oracle(ctl.policy_set_for_node(node))
        want = [int(oracle.classify(p).code) for p in pkts]
        got = [int(x) for x in np.asarray(a.agent.datapath.step(batch, now).code)]
        if got != want:
            return False
    return True


def _converge(ctl, srv, agents, pkts, *, cap=CAP, max_cycles=60):
    """Pump until every node's verdicts match its oracle -> cycles used.
    Every cycle also asserts the zero-unbounded-growth bar: no server-side
    watcher queue past the cap."""
    for cycle in range(max_cycles):
        srv.pump()
        for a in agents.values():
            a.pump(wait=0.02)
            a.sync_and_report()
        for node, w in srv.dissemination_stats()["watchers"].items():
            assert w["pending"] <= cap, (
                f"watcher for {node} grew to {w['pending']} (cap {cap})")
        if _parity(ctl, agents, pkts):
            return cycle + 1
        time.sleep(0.02)
    raise AssertionError(
        f"fleet did not reconverge to oracle parity in {max_cycles} cycles")


# -- tier-1 smoke (single fault, fast) ---------------------------------------


def test_smoke_reconnect_resync_parity(tmp_path):
    """ONE injected connection reset while policy churns: the agent must
    reconnect with backoff, take the server's re-list, retract the stale
    policy, and return to oracle parity — the minimum healing loop, kept
    inside the tier-1 'not slow' set."""
    nodes = ["n1", "n2"]
    certdir, ctl, store, agg, srv = _world(tmp_path, nodes)
    plan = FaultPlan(seed=3)
    try:
        agents = {"n1": _agent("n1", srv, certdir, plan),
                  "n2": _agent("n2", srv, certdir)}
        srv.wait_connected(2)
        pkts = _pkts(len(nodes))
        ctl.upsert_antrea_policy(_policy("P1"))
        _converge(ctl, srv, agents, pkts)
        assert agents["n1"].resyncs_total == 1  # the hello snapshot

        # Next recv on n1 dies (recv only runs when data arrives, so churn
        # first): n1 loses the connection mid-update and the rest of the
        # churn happens while it is down.
        plan.after("n1.recv", plan.hits("n1.recv"), "reset", times=1)
        ctl.delete_policy("P1")
        srv.pump()
        agents["n1"].pump(wait=0.2)
        assert plan.count("reset") == 1
        assert not agents["n1"].connected

        ctl.upsert_antrea_policy(_policy("P2", cidr="198.51.100.0/24"))
        cycles = _converge(ctl, srv, agents, pkts)
        assert cycles <= 60
        a1 = agents["n1"]
        assert a1.reconnects_total >= 1
        assert a1.resyncs_total >= 2  # hello + post-reconnect re-list
        # Re-list retracted the stale policy (deleted while disconnected).
        assert [p.uid for p in a1.agent.policy_set.policies] == ["P2"]
        # The undisturbed node never paid a reconnect.
        assert agents["n2"].reconnects_total == 0
        # The healing is visible on the live scrape surface.
        from antrea_tpu.observability import render_dissemination_metrics

        text = render_dissemination_metrics(srv, agents.values())
        assert 'antrea_tpu_agent_reconnects_total{node="n1"} 1' in text
        assert 'antrea_tpu_dissemination_watcher_pending{node="n1"} 0' in text
        assert "antrea_tpu_dissemination_resyncs_total" in text
        for a in agents.values():
            a.close()
    finally:
        srv.close()


def test_install_retry_counts_and_backoff():
    """install_bundle raising must not crash the agent or drop state: the
    dirty flag survives, sync_failures_total counts each attempt, retries
    wait out a capped backoff, and the rules land once the datapath
    recovers (the reference reconciler's requeue discipline)."""
    plan = FaultPlan()
    plan.every("nX.install", 1, "fail", times=2)  # first two installs raise
    dp = FlakyDatapath(OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4),
                       plan, "nX")
    t = [0.0]
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agent = AgentPolicyController("nX", dp, store, clock=lambda: t[0])
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ctl.upsert_pod(crd.Pod(namespace="default", name="w", ip="10.0.1.1",
                           node="nX", labels={"app": "web"}))
    ctl.upsert_antrea_policy(_policy("P1"))

    agent.sync()  # attempt 1: injected failure
    assert agent.sync_failures_total == 1
    assert "injected" in agent.last_sync_error
    assert dp.generation == 0  # nothing installed
    agent.sync()  # still inside the backoff window: no attempt burned
    assert agent.sync_failures_total == 1 and plan.count("fail") == 1

    t[0] += 1.0
    agent.sync()  # attempt 2: injected failure, backoff doubles
    assert agent.sync_failures_total == 2
    t[0] += 1.0
    agent.sync()  # attempt 3: datapath healthy again
    assert agent.sync_failures_total == 2
    assert dp.generation == 1
    # The retried bundle enforces: deny CIDR drops, web peer passes.
    batch = PacketBatch.from_packets([
        Packet(src_ip=iputil.ip_to_u32("192.0.2.7"),
               dst_ip=iputil.ip_to_u32("10.0.1.1"),
               proto=6, src_port=41000, dst_port=80),
    ])
    assert [int(x) for x in np.asarray(dp.step(batch, next(_NOW)).code)] == [
        int(Oracle(ctl.policy_set_for_node("nX")).classify(p).code)
        for p in [Packet(src_ip=iputil.ip_to_u32("192.0.2.7"),
                         dst_ip=iputil.ip_to_u32("10.0.1.1"),
                         proto=6, src_port=41000, dst_port=80)]
    ]


def test_degraded_datapath_recovery_via_agent_sync():
    """Repeated IN-PLANE install failure (canary rejects the candidate,
    datapath/commit.py): the datapath rolls back to last-known-good,
    degrades, and keeps serving LKG verdicts; the agent's sync loop —
    which folds everything into full-bundle recompiles while the datapath
    is degraded — reconverges to oracle parity once the fault clears, and
    the rollback/degraded metrics observably transition."""
    from antrea_tpu.observability.metrics import render_metrics

    plan = FaultPlan()
    inner = OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4)
    dp = FlakyDatapath(inner, plan, "nX")  # arms nX.compile / nX.canary
    t = [0.0]
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agent = AgentPolicyController("nX", dp, store, clock=lambda: t[0])
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ctl.upsert_pod(crd.Pod(namespace="default", name="w", ip="10.0.1.1",
                           node="nX", labels={"app": "web"}))
    ctl.upsert_antrea_policy(_policy("P1"))
    agent.sync()  # P1 lands clean
    assert dp.generation == 1 and not dp.degraded

    def fresh_parity():
        # Fresh 5-tuples only: an established flow survives policy churn.
        now = next(_NOW)
        pkts = [Packet(src_ip=iputil.ip_to_u32(s),
                       dst_ip=iputil.ip_to_u32("10.0.1.1"),
                       proto=6, src_port=30000 + now % 30000, dst_port=80)
                for s in ("192.0.2.7", "198.51.100.9")]
        oracle = Oracle(ctl.policy_set_for_node("nX"))
        got = [int(x) for x in
               np.asarray(dp.step(PacketBatch.from_packets(pkts), now).code)]
        return got == [int(oracle.classify(p).code) for p in pkts]

    # The next two bundle canaries reject their candidates (persistent
    # miscompile injection), then the fault clears.
    plan.after("nX.canary", plan.hits("nX.canary"), "fail", times=2)
    ctl.upsert_antrea_policy(_policy("P2", cidr="198.51.100.0/24"))

    agent.sync()  # attempt 1: canary blocks the swap -> degraded
    assert agent.sync_failures_total == 1
    assert "canary" in agent.last_sync_error
    assert dp.degraded and dp.generation == 1
    # LKG (P1-only) verdicts keep serving with zero divergence from the
    # P1-only oracle, while upstream already wants P1+P2.
    lkg_oracle = Oracle(agent.policy_set)
    now = next(_NOW)
    probe = Packet(src_ip=iputil.ip_to_u32("192.0.2.7"),
                   dst_ip=iputil.ip_to_u32("10.0.1.1"),
                   proto=6, src_port=31000 + now % 30000, dst_port=80)
    got = int(dp.step(PacketBatch.from_packets([probe]), now).code[0])
    assert got == 1  # P1's deny CIDR still enforced from LKG

    t[0] += 1.0
    agent.sync()  # attempt 2: still injected -> still degraded
    assert agent.sync_failures_total == 2 and dp.degraded
    text = render_metrics(inner, node="nX")
    assert 'antrea_tpu_datapath_degraded{node="nX"} 1' in text
    assert 'antrea_tpu_bundle_rollbacks_total{node="nX"} 2' in text

    t[0] += 2.0
    agent.sync()  # attempt 3: fault exhausted -> recompile certifies
    assert not dp.degraded
    assert agent.sync_failures_total == 2
    assert fresh_parity()
    text = render_metrics(inner, node="nX")
    assert 'antrea_tpu_datapath_degraded{node="nX"} 0' in text
    assert "antrea_tpu_canary_mismatches_total" in text

    # Membership deltas flow again after the quarantine lifted.
    ctl.upsert_pod(crd.Pod(namespace="default", name="w2", ip="10.0.1.2",
                           node="nX", labels={"app": "web"}))
    t[0] += 1.0
    agent.sync()
    assert fresh_parity()


def test_chaos_cache_corruption_detected_repaired_reconverges():
    """ISSUE 5 chaos case: the plan's {name}.cache site REALLY flips a
    sampled cached verdict bit (silent device-state corruption — invisible
    to every fresh-tuple canary and to live fresh-tuple parity), the
    continuous revalidator detects it within <= 2 full audit sweeps,
    repairs by eviction, and the fleet reconverges to oracle verdict
    parity INCLUDING the previously-corrupted cached tuple."""
    plan = FaultPlan()
    inner = OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4,
                           audit_window=1 << 7)  # 2 scans == 1 full sweep
    dp = FlakyDatapath(inner, plan, "nX")  # arms nX.cache / nX.audit too
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agent = AgentPolicyController("nX", dp, store)
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ctl.upsert_pod(crd.Pod(namespace="default", name="w", ip="10.0.1.1",
                           node="nX", labels={"app": "web"}))
    ctl.upsert_antrea_policy(_policy("P1"))
    agent.sync()

    # Cache a denial (the blocked CIDR) and an allowed connection.
    blocked = Packet(src_ip=iputil.ip_to_u32("192.0.2.7"),
                     dst_ip=iputil.ip_to_u32("10.0.1.1"),
                     proto=6, src_port=39001, dst_port=80)
    allowed = Packet(src_ip=iputil.ip_to_u32("10.0.5.5"),
                     dst_ip=iputil.ip_to_u32("10.0.1.1"),
                     proto=6, src_port=39002, dst_port=80)
    dp.step(PacketBatch.from_packets([blocked, allowed]), next(_NOW))
    dp.audit_scan(now=next(_NOW))  # anchor the scrub digests

    # Inject: the next audit scan's .cache site fires, corrupting a live
    # cached verdict at scan start — which that same pass must detect.
    plan.after("nX.cache", plan.hits("nX.cache"), "fail", times=1)
    repaired = 0
    for _ in range(4):  # 4 scans at window N/2 == 2 full sweeps
        out = dp.audit_scan(now=next(_NOW))
        repaired += out["repaired"]
        if repaired:
            break
    assert plan.count("fail") == 1, "the chaos run injected nothing"
    assert repaired >= 1, "corruption not repaired within 2 sweeps"
    assert dp.audit_stats()["divergences"]
    plan.quiesce()

    # Reconvergence bar: fresh tuples AND the cached tuples re-prove to
    # parity with an oracle over the controller's own snapshot.
    oracle = Oracle(ctl.policy_set_for_node("nX"))
    now = next(_NOW)
    probes = [blocked, allowed,
              Packet(src_ip=iputil.ip_to_u32("192.0.2.8"),
                     dst_ip=iputil.ip_to_u32("10.0.1.1"),
                     proto=6, src_port=39000 + now % 20000, dst_port=80)]
    got = [int(c) for c in
           np.asarray(dp.step(PacketBatch.from_packets(probes), now).code)]
    assert got == [int(oracle.classify(p).code) for p in probes]
    assert not dp.degraded
    assert dp.audit_scan(now=next(_NOW))["divergences"] == 0


def test_bounded_watcher_overflow_forces_resync():
    """A consumer that stops pumping must cost one resync, never unbounded
    controller memory — with the coalescing discipline layered in: churn
    that rewrites the SAME key occupies one slot (latest-wins, metered),
    so only DISTINCT-key churn can hit the cap; when it does, the buffer
    drops, needs_resync flips, and the next pump re-lists — including
    retracting objects deleted during the outage (events the dropped
    buffer never delivered)."""
    cap = 8
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agent = FakeAgent(store, "n1", max_pending=cap)
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ctl.upsert_pod(crd.Pod(namespace="default", name="w", ip="10.0.1.1",
                           node="n1", labels={"app": "web"}))
    ctl.upsert_k8s_policy(crd.K8sNetworkPolicy(
        uid="np-web", name="np-web", namespace="default",
        pod_selector=crd.LabelSelector.make({"app": "web"}),
        ingress=[crd.K8sNPRule(peers=[crd.K8sPeer(
            pod_selector=crd.LabelSelector.make({"app": "client"}))])],
    ))
    agent.pump()  # tables populated: the outage below has state to stale
    assert set(agent.policies) == {"np-web"}

    w = agent._watcher
    # Outage phase 1: 20 member-churn events with no pump, ALL rewriting
    # one AddressGroup (np-web's client peer) — a storm a pre-coalescing
    # queue would have overflowed at the cap.  Latest-wins absorbs it in
    # one slot, metered, stream intact.
    for i in range(20):
        ctl.upsert_pod(crd.Pod(namespace="default", name=f"c{i}",
                               ip=f"10.0.2.{i + 1}", node="n2",
                               labels={"app": "client"}))
    assert w.pending() == 1  # one queued key, 19 re-deliveries coalesced
    assert w.coalesced == 19
    assert not w.needs_resync and w.overflows == 0

    # Outage phase 2: DISTINCT-key churn (each policy mints its own
    # NetworkPolicy + AddressGroup keys) — the case coalescing cannot
    # absorb.  The queue caps, drops, and invalidates the stream.
    for i in range(cap):
        ctl.upsert_antrea_policy(
            _policy(f"burst-{i}", cidr=f"198.51.{i}.0/24"))
        assert w.pending() <= cap  # never grows past the cap
    assert w.needs_resync and w.overflows == 1
    assert w.pending() == 0  # overflowed buffer was dropped, not kept

    # Deleted while the stream was invalid: only the re-list can tell.
    ctl.delete_policy("np-web")
    agent.pump()
    assert agent.resyncs_seen == 1
    assert not w.needs_resync
    # Tables now mirror the span-filtered snapshot exactly: np-web and
    # its groups are gone, the burst policies span n1 via its web pod.
    ps = ctl.policy_set_for_node("n1")
    want_uids = {p.uid for p in ps.policies}
    assert "np-web" not in want_uids
    assert set(agent.policies) == want_uids
    assert set(agent.address_groups) == set(ps.address_groups)
    assert set(agent.applied_to_groups) == set(ps.applied_to_groups)
    agent.stop()


def test_store_span_shrink_and_stop_interleaving_bounded():
    """RamStore edge traffic under the bounded-queue path (the round-2
    watcher-leak area): span shrink still delivers DELETED through a
    capped queue, overflow + shrink resolves through the re-list (no
    phantom object, no suppressed re-ADD), and stop() interleaved with
    producer traffic prunes the watcher without leaking buffered events."""
    store = RamStore()
    w1 = store.watch_queue("n1", max_pending=4)
    w2 = store.watch_queue("n2", max_pending=4)

    def upd(name, span, kind="UPDATED"):
        store.apply(WatchEvent(kind=kind, obj_type="AddressGroup",
                               name=name, obj=object(), span=set(span)))

    upd("g1", {"n1", "n2"}, kind="ADDED")
    assert [e.kind for e in w1.drain()] == ["ADDED"]
    # Span shrinks away from n1: retraction arrives as DELETED.
    upd("g1", {"n2"})
    evs = w1.drain()
    assert [(e.kind, e.name) for e in evs] == [("DELETED", "g1")]

    # Overflow w1 (cap 4) with unrelated churn, then shrink g2 away while
    # the stream is invalid: the dropped buffer never says DELETED, the
    # re-list simply omits g2.
    upd("g2", {"n1"}, kind="ADDED")
    for i in range(6):
        upd(f"x{i}", {"n1"}, kind="ADDED")
    assert w1.needs_resync and w1.overflows == 1 and w1.pending() == 0
    upd("g2", set())  # shrink-to-nowhere while overflowed: event dropped
    snap = {e.name for e in store.resync(w1)}
    assert "g2" not in snap and {"x0", "x5"} <= snap
    assert not w1.needs_resync
    # Known-set was rebuilt by the re-list: a later span GROWTH must
    # re-deliver ADDED (a stale known-set would suppress it).
    upd("g2", {"n1"})
    assert [(e.kind, e.name) for e in w1.drain()] == [("ADDED", "g2")]

    # stop() mid-stream: buffered events are cleared immediately, the
    # store prunes the watcher on its next apply, and subsequent producer
    # traffic delivers nowhere — while the surviving watcher still works.
    upd("g3", {"n1", "n2"}, kind="ADDED")
    assert w1.pending() > 0
    before = store.n_watchers
    w1.stop()
    assert w1.pending() == 0
    assert store.n_watchers == before - 1
    upd("g3", {"n2"})  # shrink away from n1 AFTER the stop: no delivery
    assert w1.pending() == 0
    # ...while the surviving watcher (still spanned) got the live stream.
    assert ("UPDATED", "g3") in [(e.kind, e.name) for e in w2.drain()]

    # stop() while needs_resync is pending must not leave a zombie that
    # a later resync would resurrect.
    w3 = store.watch_queue("n1", max_pending=2)
    for i in range(4):
        upd(f"y{i}", {"n1"}, kind="ADDED")
    assert w3.needs_resync
    w3.stop()
    upd("y9", {"n1"}, kind="ADDED")
    assert store.n_watchers == 1  # only w2 remains
    w2.stop()


# -- slow chaos: wire overflow + kill/revive soak ----------------------------


@pytest.mark.slow
def test_wire_overflow_resync_over_mtls(tmp_path):
    """Server-side bounded watcher over the REAL wire: churn bursts larger
    than the cap between pumps overflow the queue; the next pump converts
    that into a bracketed re-list down the socket and the agent converges
    — one snapshot, never unbounded memory."""
    nodes = ["n1", "n2"]
    cap = 4
    certdir, ctl, store, agg, srv = _world(tmp_path, nodes, cap=cap)
    try:
        agents = {n: _agent(n, srv, certdir) for n in nodes}
        srv.wait_connected(2)
        pkts = _pkts(len(nodes))
        ctl.upsert_antrea_policy(_policy("P1"))
        _converge(ctl, srv, agents, pkts, cap=cap)
        base = {n: a.resyncs_total for n, a in agents.items()}

        # Burst: DISTINCT-key churn (each policy mints its own
        # NetworkPolicy + AddressGroup keys, spanning both nodes' web
        # pods) — well past the cap before any pump runs.  Same-key
        # churn would coalesce; distinct keys are the overflow case.
        # B0's CIDR covers a probe source (198.51.100.9), so oracle
        # parity is only reachable THROUGH the re-list.
        for i in range(12):
            ctl.upsert_antrea_policy(
                _policy(f"B{i}", cidr=f"198.51.{100 + i}.0/24"))
        stats = srv.dissemination_stats()
        assert any(w["overflows"] > 0 for w in stats["watchers"].values())
        assert all(w["pending"] <= cap for w in stats["watchers"].values())

        _converge(ctl, srv, agents, pkts, cap=cap)
        assert any(a.resyncs_total > base[n] for n, a in agents.items())
        assert srv.resyncs_total >= 3  # 2 hellos + >=1 overflow re-list
        for a in agents.values():
            a.close()
    finally:
        srv.close()


@pytest.mark.slow
def test_fleet_pump_survives_dead_agent_and_reconnects(tmp_path):
    """FakeAgentFleet.pump with a disconnected member: the dead agent
    must not crash the fleet-wide select (its socket is None while in
    backoff) — it re-dials on its own pump slot and reconverges via the
    server's re-list, while the healthy agent streams on."""
    nodes = ["n1", "n2"]
    certdir, ctl, store, agg, srv = _world(tmp_path, nodes)
    try:
        fleet = FakeAgentFleet(None, nodes, transport="netwire",
                               server=srv, certdir=certdir)
        ctl.upsert_antrea_policy(_policy("P1"))
        for _ in range(10):
            fleet.pump()
            if all(set(a.policies) == {"P1"}
                   for a in fleet.agents.values()):
                break
        a1 = fleet.agents["n1"]
        assert set(a1.policies) == {"P1"}
        a1._backoff = Backoff(base=0.01, cap=0.05)
        a1._sock.close()  # network cut mid-stream
        ctl.delete_policy("P1")
        for _ in range(40):
            fleet.pump()  # must never raise while n1 is down
            if (a1.reconnects_total >= 1
                    and all(not a.policies
                            for a in fleet.agents.values())):
                break
            time.sleep(0.02)
        assert a1.reconnects_total >= 1
        assert all(not a.policies for a in fleet.agents.values())
        fleet.stop()
    finally:
        srv.close()


@pytest.mark.slow
def test_chaos_soak_kill_revive_converges(tmp_path):
    """The full storm, deterministically seeded: probabilistic resets and
    partial writes on two nodes' sockets, install failures on a third, an
    agent hard-crash (fresh process, empty datapath) re-handshaking over
    a live registration, and a mid-stream socket kill — with policy and
    pod churn between every fault burst.  After EVERY round the fleet
    must reconverge to oracle parity within the cycle bound, and no
    watcher queue may ever pass the cap."""
    nodes = ["n1", "n2", "n3"]
    certdir, ctl, store, agg, srv = _world(tmp_path, nodes)
    plan = FaultPlan(seed=11)
    try:
        agents = {n: _agent(n, srv, certdir, plan) for n in nodes}
        srv.wait_connected(3)
        pkts = _pkts(len(nodes))
        ctl.upsert_antrea_policy(_policy("P1"))
        _converge(ctl, srv, agents, pkts)

        # Round 1: wire faults on n1/n2 (bounded so the recovery phase of
        # each convergence is calm), plus churn racing the resets.
        plan.prob("n1.send", 0.5, "reset", times=2)
        plan.prob("n1.recv", 0.5, "reset", times=2)
        plan.prob("n2.send", 0.5, "partial", times=2)
        plan.prob("n2.recv", 0.5, "reset", times=2)
        ctl.upsert_antrea_policy(_policy("P2", cidr="198.51.100.0/24"))
        ctl.delete_policy("P1")
        for i in range(6):
            ctl.upsert_pod(crd.Pod(
                namespace="default", name=f"s{i}", ip=f"10.8.0.{i + 1}",
                node=nodes[i % 3], labels={"app": "web"}))
        _converge(ctl, srv, agents, pkts)

        # Round 2: hard-crash n2 — the process dies, its replacement has
        # an EMPTY datapath and re-handshakes while the server still holds
        # the old registration (the stale-conn eviction path).
        agents["n2"].close()
        ctl.upsert_antrea_policy(_policy("P1"))  # churn during the outage
        agents["n2"] = _agent("n2", srv, certdir, plan)
        _converge(ctl, srv, agents, pkts)
        assert srv.reconnects_total >= 1  # replaced a live registration

        # Round 3: datapath install failures on n3 while rules change —
        # the dirty state must survive the failures and land.
        plan.every("n3.install", 1, "fail", times=3)
        ctl.delete_policy("P2")
        _converge(ctl, srv, agents, pkts)
        assert agents["n3"].agent.sync_failures_total >= 1

        # Round 4: socket killed mid-stream (network cut, not a crash) —
        # the agent discovers the dead fd and re-dials.  P3's CIDR is
        # covered by no other policy, so parity genuinely requires the
        # re-listed P3 on every node.
        agents["n1"]._sock._sock.close()
        ctl.upsert_antrea_policy(_policy("P3", cidr="203.0.113.0/24"))
        _converge(ctl, srv, agents, pkts)
        assert agents["n1"].reconnects_total >= 1

        # The storm actually happened (a chaos run that injected nothing
        # proves nothing) and healing is visible in the counters.
        assert plan.count("reset") >= 1
        assert plan.count("fail") >= 1
        assert sum(a.resyncs_total for a in agents.values()) >= 5
        # Status plane healed too: every node reports the final policies
        # (the reports ride the same sockets, so give them pump rounds).
        for _ in range(20):
            srv.pump()
            for a in agents.values():
                a.pump(wait=0.02)
                a.sync_and_report()
            srv.pump()
            if all(agg.status_of(uid).phase == "Realized"
                   for uid in ("P1", "P3")):
                break
            time.sleep(0.02)
        for uid in ("P1", "P3"):
            st = agg.status_of(uid)
            assert st.phase == "Realized", (uid, st)
        for a in agents.values():
            a.close()
    finally:
        srv.close()
