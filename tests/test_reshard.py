"""Elastic mesh resharding (parallel/reshard.py): tier-1 + chaos tier.

Resize the data axis UNDER TRAFFIC on the 8 forced host devices —
grow 2→4 and shrink 4→2 mid-churn, mid-drain and mid-commit — holding
the PR bar: bitwise verdict parity for every established flow (no flap,
no parity loss), a vetoed cutover aborts back to the old topology with
the generation unchanged, and the reshard manifest gate
(tools/check_reshard.py) stays green.

Engines share the module-scoped meshes + KW so the jitted sharded step
builders (keyed by (mesh, meta)) compile once per variant.
"""

import pathlib
import sys

import jax
import numpy as np
import pytest

from antrea_tpu.datapath.tpuflow import TpuflowDatapath
from antrea_tpu.observability.metrics import render_metrics
from antrea_tpu.parallel import MeshDatapath, mesh as pm
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic

KW = dict(flow_slots=1 << 10, aff_slots=1 << 8, canary_probes=16)
ASYNC_KW = dict(async_slowpath=True, miss_queue_slots=1 << 12,
                drain_batch=256)


@pytest.fixture(scope="module")
def world():
    cluster = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=7)
    services = gen_services(8, cluster.pod_ips, seed=11)
    return cluster, services


@pytest.fixture(scope="module")
def mesh():
    return pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])


@pytest.fixture(scope="module")
def batch(world):
    cluster, services = world
    return gen_traffic(cluster.pod_ips, 256, n_flows=96, seed=3,
                       services=services, svc_fraction=0.3)


def _mesh_dp(world, mesh, **extra):
    cluster, services = world
    return MeshDatapath(cluster.ps, services, mesh=mesh, **KW, **extra)


def _run_to_completion(mdp, t, deadline=400):
    """Tick the maintenance plane until the in-flight resize finishes
    (cutover or abort) -> the next free packet-clock instant."""
    while mdp.reshard_status() is not None:
        mdp.maintenance_tick(now=t)
        t += 1
        assert t < deadline, mdp.reshard_status()
    return t


def _verdict_parity(rm, rs, msg=""):
    """Bitwise verdict parity on every CLASSIFIED lane.  Lanes pending on
    either engine carry the provisional admission verdict — which lanes
    re-miss after an eviction is a cache-TOPOLOGY observable (one 2^10
    table vs D private 2^10 shards evict differently under churn, the
    PR 9 est/committed caveat), so pending lanes compare pending-for-
    pending via the miss image, never verdict-for-verdict."""
    ok = np.ones(len(np.asarray(rm.code)), bool)
    if rm.pending is not None:
        ok = (np.asarray(rm.pending) == 0) & (np.asarray(rs.pending) == 0)
    for k in ("code", "svc_idx", "dnat_ip", "dnat_port"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rm, k))[ok], np.asarray(getattr(rs, k))[ok],
            err_msg=f"{msg}:{k}")
    ing_m = [r for r, o in zip(rm.ingress_rule, ok) if o]
    ing_s = [r for r, o in zip(rs.ingress_rule, ok) if o]
    egr_m = [r for r, o in zip(rm.egress_rule, ok) if o]
    egr_s = [r for r, o in zip(rs.egress_rule, ok) if o]
    assert ing_m == ing_s, msg
    assert egr_m == egr_s, msg
    return ok


# --------------------------------------------------------------------------
# Satellites: the manifest gate + the versioned consistent-ring election
# --------------------------------------------------------------------------

# The reshard-manifest gate (tools/check_reshard.py -> analysis pass
# `reshard`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.


def test_versioned_ring_symmetric_deterministic_minimal_movement():
    """shard_of_tuples' topology generations: gen 0 keeps the PR 9 dense
    map bit-for-bit; gen >= 1 elects on the consistent ring — still
    deterministic and direction-symmetric, and growing the member set
    moves ONLY the keys the new shards' virtual points claim (the
    memberlist ownership property the migration budget rests on)."""
    rng = np.random.default_rng(5)
    src = rng.integers(1, 2 ** 32, 4096, dtype=np.uint32)
    dst = rng.integers(1, 2 ** 32, 4096, dtype=np.uint32)
    proto = np.full(4096, 6, np.int32)
    sport = rng.integers(1024, 65535, 4096).astype(np.int32)
    dport = rng.integers(1, 1024, 4096).astype(np.int32)
    for gen in (1, 2):
        fwd = pm.shard_of_tuples(src, dst, proto, sport, dport, 4, gen)
        again = pm.shard_of_tuples(src, dst, proto, sport, dport, 4, gen)
        rev = pm.shard_of_tuples(dst, src, proto, dport, sport, 4, gen)
        np.testing.assert_array_equal(fwd, again)
        np.testing.assert_array_equal(fwd, rev)
    # The ring depends on the MEMBER SET, not the generation number:
    # two ring generations at the same D elect identically.
    np.testing.assert_array_equal(
        pm.shard_of_tuples(src, dst, proto, sport, dport, 4, 1),
        pm.shard_of_tuples(src, dst, proto, sport, dport, 4, 2))
    # Consistent-hash minimal movement: every key owned by a surviving
    # shard under ring(4) keeps its owner under ring(2) — shrink moves
    # exactly the removed shards' keys, grow the mirror image.
    own4 = pm.shard_of_tuples(src, dst, proto, sport, dport, 4, 1)
    own2 = pm.shard_of_tuples(src, dst, proto, sport, dport, 2, 1)
    stay = own4 < 2
    np.testing.assert_array_equal(own4[stay], own2[stay])
    moved = float((~stay).sum()) / own4.size
    assert 0.3 < moved < 0.7, moved  # ~half the keys, the grown fraction
    # Load spread on the ring stays serviceable (RING_VNODES points).
    counts = np.bincount(own4, minlength=4)
    assert counts.min() > 512, counts
    # gen 0 is bit-stable: the dense mod map of PR 9.
    h_mod = pm.shard_of_tuples(src, dst, proto, sport, dport, 4)
    np.testing.assert_array_equal(
        h_mod, pm.shard_of_tuples(src, dst, proto, sport, dport, 4, 0))


# --------------------------------------------------------------------------
# Tentpole: grow + shrink mid-churn with zero established-flow loss
# --------------------------------------------------------------------------

def test_grow_and_shrink_mid_churn_zero_established_flow_loss(world, mesh,
                                                              batch):
    """The acceptance bar: grow 2→4 then shrink 4→2 executed MID-CHURN
    (fresh flows admitted and drained while migration windows run), with
    bitwise verdict parity for all established flows on every step, the
    established set still served from cache after each cutover, and the
    miss queues re-homed across the flip."""
    cluster, services = world
    adp = _mesh_dp(world, mesh, **ASYNC_KW)
    sdp = TpuflowDatapath(cluster.ps, services, **KW, **ASYNC_KW)
    for dp in (adp, sdp):  # establish the hot set
        dp.step(batch, 100)
        dp.drain_slowpath(101)

    def churn_until_done(t, seed0):
        i = 0
        while adp.reshard_status() is not None:
            churn = gen_traffic(cluster.pod_ips, 128, n_flows=64,
                                seed=seed0 + i)
            ra, rb = adp.step(churn, t), sdp.step(churn, t)
            # Pending lanes carry the provisional admission verdict, a
            # cache-topology observable; classified lanes must agree.
            ok = ((np.asarray(ra.pending) == 0)
                  & (np.asarray(rb.pending) == 0))
            np.testing.assert_array_equal(np.asarray(ra.code)[ok],
                                          np.asarray(rb.code)[ok])
            # The ESTABLISHED set never flaps mid-migration.
            ea, eb = adp.step(batch, t), sdp.step(batch, t)
            _verdict_parity(ea, eb, f"mid-churn t={t}")
            adp.maintenance_tick(now=t)
            t += 1
            i += 1
            assert t < 600
        return t

    adp.reshard_begin(4)
    t = churn_until_done(102, 500)
    assert adp._n_data == 4 and adp._topo_gen == 1
    rs = adp.reshard_stats()
    assert rs["cutovers_total"] == 1 and rs["migrated_rows_total"] > 0
    # Established flows survived the grow: served from the MIGRATED
    # cache, in parity, with the hot set overwhelmingly classified
    # (only direct-mapped collision losers may re-pend, the documented
    # cache dynamic — never a verdict change on a classified lane).
    ra, rb = adp.step(batch, t), sdp.step(batch, t)
    ok = _verdict_parity(ra, rb, "post-grow")
    assert float(ok.mean()) > 0.85, float(ok.mean())
    assert int(np.asarray(ra.est).sum()) > 0
    for dp in (adp, sdp):
        dp.drain_slowpath(t + 1)

    adp.reshard_begin(2)  # ring -> ring: the minimal-movement leg
    t = churn_until_done(t + 2, 700)
    assert adp._n_data == 2 and adp._topo_gen == 2
    # Classified lanes stay bitwise-true straight off the flip, and the
    # MIGRATED entries serve immediately (est hits with no re-drain) —
    # the zero-established-flow-loss claim.  No classified-FRACTION bar
    # here: the churn universe deliberately thrashes the halved capacity
    # (4x1024 slots of est+churn entries merged into 2x1024; the
    # single-chip twin thrashes its lone 1024-slot table even harder),
    # and which lanes re-pend under thrash is the documented
    # cache-topology observable, not a parity loss.
    ra = adp.step(batch, t)
    _verdict_parity(ra, sdp.step(batch, t), "post-shrink")
    assert int(np.asarray(ra.est).sum()) > 0
    for dp in (adp, sdp):
        dp.drain_slowpath(t + 1)
    ra, rb = adp.step(batch, t + 2), sdp.step(batch, t + 2)
    _verdict_parity(ra, rb, "post-shrink-drained")
    assert int(np.asarray(ra.est).sum()) > 0
    assert adp.reshard_stats()["cutovers_total"] == 2
    # The journal carries both full lifecycles in causal order.
    kinds = [e["kind"] for e in adp.flightrecorder_events()
             if e["kind"].startswith("reshard")]
    assert kinds == ["reshard-begin", "reshard-migrated", "reshard-cutover",
                     "reshard-begin", "reshard-migrated", "reshard-cutover"]


def test_reshard_requeues_pending_misses_to_new_homes(world, mesh):
    """Queued (not-yet-classified) misses survive the flip: the cutover
    re-homes every row under the target ring (verbatim, not re-admitted)
    and a post-flip drain classifies them on their owning replicas with
    oracle-true verdicts."""
    from antrea_tpu.oracle.interpreter import Oracle

    cluster, _services = world
    adp = _mesh_dp(world, mesh, **ASYNC_KW)
    tr = gen_traffic(cluster.pod_ips, 256, n_flows=128, seed=31)
    adp.step(tr, 100)  # misses sit queued, undrained
    depth0 = adp.slowpath_stats()["depth"]
    assert depth0 > 0
    adp.reshard_begin(4)
    t = _run_to_completion(adp, 101)
    st = adp.slowpath_stats()
    assert st["depth"] == depth0  # nothing lost crossing the flip
    assert adp.reshard_stats()["requeued_total"] == depth0
    assert len(st["replica_depths"]) == 4
    adp.drain_slowpath(t)
    oracle = Oracle(cluster.ps)
    r = adp.step(tr, t + 1)
    codes, pend = np.asarray(r.code), np.asarray(r.pending)
    for i in range(tr.size):
        if not pend[i]:
            assert codes[i] == int(oracle.classify(tr.packet(i)).code), i


# --------------------------------------------------------------------------
# Chaos tier: vetoed cutover, mid-drain serialization, mid-commit installs
# --------------------------------------------------------------------------

def test_vetoed_cutover_aborts_to_old_topology(world, mesh, batch):
    """Chaos: rule-table corruption on ONE target replica's device
    copies.  The cutover canary's row for that replica diverges and
    vetoes the flip — the old mesh keeps serving (healthy, not even
    degraded), the affinity generation never moves, and the journal
    reconstructs reshard-begin -> replica-canary-veto -> reshard-abort.
    A clean retry then resizes successfully."""
    cluster, services = world
    vdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    vdp.step(batch, 100)
    sdp.step(batch, 100)
    vdp.reshard_begin(4)
    desc = vdp._reshard.corrupt_target(1)
    assert "target" in desc and "replica 1" in desc
    t = _run_to_completion(vdp, 101)
    assert vdp._n_data == 2 and vdp._topo_gen == 0  # generation unchanged
    rs = vdp.reshard_stats()
    assert rs["aborts_total"] == 1 and rs["cutovers_total"] == 0
    assert not vdp.degraded  # the OLD mesh was never implicated
    kinds = [e["kind"] for e in vdp.flightrecorder_events()]
    chain = [k for k in kinds if k in ("reshard-begin",
                                       "replica-canary-veto",
                                       "reshard-abort")]
    assert chain == ["reshard-begin", "replica-canary-veto",
                     "reshard-abort"], kinds
    # Old topology still serving in parity.
    _verdict_parity(vdp.step(batch, t), sdp.step(batch, t), "post-abort")
    # Clean retry: fresh target placement, certified, flipped.
    vdp.reshard_begin(4)
    t = _run_to_completion(vdp, t + 1)
    assert vdp._n_data == 4 and vdp._topo_gen == 1
    _verdict_parity(vdp.step(batch, t), sdp.step(batch, t), "post-retry")


def test_reshard_defers_whole_against_inflight_drain(world, mesh):
    """Mid-drain chaos: a migration window must never interleave with a
    pinned drain block — the scheduler's ONE serialization point defers
    the whole tick (blocked, metered), and migration resumes after
    finish_drain."""
    cluster, _services = world
    adp = _mesh_dp(world, mesh, **ASYNC_KW)
    tr = gen_traffic(cluster.pod_ips, 256, n_flows=128, seed=37)
    adp.step(tr, 100)
    adp.reshard_begin(4)
    sp = adp._slowpath
    assert sp.begin_drain(101)
    out = adp.maintenance_tick(now=102)
    assert out["blocked"] == "inflight-drain"
    assert "reshard-migrate" in out["deferred"]
    assert adp.reshard_status()["progress_ratio"] == 0.0
    sp.finish_drain(103)
    out = adp.maintenance_tick(now=104)
    assert out["ran"].get("reshard-migrate", 0) > 0
    _run_to_completion(adp, 105)
    assert adp._n_data == 4


def test_reshard_mid_commit_absorbs_installs_and_deltas(world, mesh, batch):
    """Mid-commit chaos: a full bundle install AND an O(delta) group
    patch land BETWEEN migration windows.  The lazily-placed target
    tensors re-place at certification (gen-checked), the catch-up sweep
    re-syncs remapped attribution, and post-cutover verdicts/attribution
    match a single-chip twin that saw the identical sequence."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    mdp.step(batch, 100)
    sdp.step(batch, 100)
    mdp.reshard_begin(4)
    mdp.maintenance_tick(now=101)  # a partial migration window
    assert 0 < mdp.reshard_status()["progress_ratio"] < 1
    # Mid-resize bundle: same world re-installed (renumbering bundle,
    # exercises the cached-attribution remap) + a fresh services set.
    services2 = gen_services(8, cluster.pod_ips, seed=12)
    mdp.install_bundle(cluster.ps, services2)
    sdp.install_bundle(cluster.ps, services2)
    # Mid-resize O(delta) patch.
    group = sorted(cluster.ps.address_groups)[0]
    mdp.apply_group_delta(group, ["172.31.9.9"], [])
    sdp.apply_group_delta(group, ["172.31.9.9"], [])
    t = _run_to_completion(mdp, 102)
    assert mdp._n_data == 4 and mdp._topo_gen == 1
    assert mdp.generation == sdp.generation
    _verdict_parity(mdp.step(batch, t), sdp.step(batch, t), "post-cutover")
    tr = gen_traffic(cluster.pod_ips, 128, n_flows=64, seed=41)
    _verdict_parity(mdp.step(tr, t + 1), sdp.step(tr, t + 1), "fresh")


def test_degraded_datapath_pauses_and_rejects_reshard(world, mesh, batch):
    """Resizing is gated on a certifiable commit plane: reshard_begin
    refuses while degraded, and an in-flight resize sheds its task (the
    degraded-mode priority inversion) until recovery."""
    from antrea_tpu.datapath.commit import CanaryMismatchError

    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    mdp.step(batch, 100)
    mdp.corrupt_replica(1)
    with pytest.raises(CanaryMismatchError):
        mdp.install_bundle(None, gen_services(8, cluster.pod_ips, seed=12))
    assert mdp.degraded
    with pytest.raises(RuntimeError, match="degraded"):
        mdp.reshard_begin(4)
    # Recover, begin, then degrade MID-resize: the task sheds.
    mdp.install_bundle(cluster.ps, services)
    assert not mdp.degraded
    mdp.reshard_begin(4)
    mdp._commit.degraded = True
    out = mdp.maintenance_tick(now=101)
    assert "reshard-migrate" in out["shed"]
    assert mdp.reshard_status()["progress_ratio"] == 0.0
    mdp._commit.degraded = False
    t = _run_to_completion(mdp, 102)
    assert mdp._n_data == 4


def test_reshard_begin_rejections(world, mesh):
    mdp = _mesh_dp(world, mesh)
    with pytest.raises(ValueError, match="equals the current"):
        mdp.reshard_begin(2)
    with pytest.raises(ValueError, match="devices"):
        mdp.reshard_begin(64)  # 64 x 2 devices cannot exist here
    with pytest.raises(RuntimeError, match="no reshard"):
        mdp.reshard_abort()
    mdp.reshard_begin(4)
    with pytest.raises(RuntimeError, match="already in flight"):
        mdp.reshard_begin(4)
    mdp.reshard_abort("test teardown")
    assert mdp.reshard_status() is None
    assert mdp.reshard_stats()["aborts_total"] == 1


# --------------------------------------------------------------------------
# Observability: metric families, span, scheduler accounting
# --------------------------------------------------------------------------

def test_reshard_observability_surfaces(world, mesh, batch):
    cluster, _services = world
    mdp = _mesh_dp(world, mesh)
    text = render_metrics(mdp, node="n0")
    for fam in ("antrea_tpu_reshard_topology_generation",
                "antrea_tpu_reshard_active",
                "antrea_tpu_reshard_progress_ratio",
                "antrea_tpu_reshard_migrated_rows_total",
                "antrea_tpu_reshard_resident_rows",
                "antrea_tpu_reshard_cutovers_total",
                "antrea_tpu_reshard_aborts_total"):
        assert f'{fam}{{node="n0"}}' in text, fam
    # Single-chip engines carry NO reshard surface (schema gated on the
    # plane existing, like prune_stats).
    sdp = TpuflowDatapath(None, None, **KW)
    assert "antrea_tpu_reshard" not in render_metrics(sdp, node="n0")
    mdp.step(batch, 100)
    mdp.reshard_begin(4)
    assert render_metrics(mdp, node="n0").count(
        'antrea_tpu_reshard_active{node="n0"} 1') == 1
    t = _run_to_completion(mdp, 101)
    # The resize span: stages clamp monotonic and telescope to total,
    # recorded on the realization tracer beside policy spans.
    span = mdp.reshard_stats()["last_span"]
    assert span["n_data_from"] == 2 and span["n_data_to"] == 4
    total = span["migrate_s"] + span["certify_s"] + span["cutover_s"]
    assert abs(total - span["total_s"]) < 1e-9
    assert all(span[k] >= 0 for k in ("migrate_s", "certify_s",
                                      "cutover_s"))
    assert mdp.realization_stats()["last_resize"] == span
    # The migration ran as a BUDGETED scheduler task, not a free lunch.
    tasks = mdp.maintenance_stats()["tasks"]
    assert "reshard-migrate" not in tasks  # unregistered after cutover
    ticks = [e for e in mdp.flightrecorder_events(kind="maint-tick")
             if "reshard-migrate" in e.get("ran", {})]
    assert ticks, "migration never ran under the scheduler"
    assert max(e["ran"]["reshard-migrate"]
               for e in ticks[:-1] or ticks) <= 4096  # deficit-capped
    del t


# --------------------------------------------------------------------------
# Round-9 residue burn-down: dirty-row catch-up + off-shard DNAT reply legs
# --------------------------------------------------------------------------


def test_dirty_row_tracking_wiring(world, mesh):
    """Tier-1 wiring of the dirty-row plane: live dispatches mark their
    home (replica, slot) pairs into the reshard plane's bitmap, a
    same-ids bundle leaves the bounded set intact, a renumbering bundle
    (the whole-cache attribution remap) flips the full-sweep fallback
    and clears it — all without waiting out a full resize (the end-to-
    end catch-up meter is the slow-tier integration test below)."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    hot = gen_traffic(cluster.pod_ips, 96, n_flows=48, seed=898)
    mdp.step(hot, 100)
    mdp.reshard_begin(4)
    assert mdp.reshard_stats()["catchup_rows_total"] == 0
    st0 = mdp._reshard.status()
    assert st0["dirty_rows"] == 0 and st0["dirty_all"] is False
    mdp.step(hot, 101)  # live traffic mid-resize -> dirty marks
    st1 = mdp._reshard.status()
    assert 0 < st1["dirty_rows"] < 2 * KW["flow_slots"] // 2
    mdp.install_bundle(cluster.ps)  # same ids in same order: no remap
    assert mdp._reshard.dirty_all is False
    ps2 = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=78).ps
    mdp.install_bundle(ps2)  # renumbering bundle: real remap
    assert mdp._reshard.dirty_all is True
    assert mdp._reshard.status()["dirty_rows"] == 0
    mdp.reshard_abort("wiring pinned")
    text = render_metrics(mdp, node="n0")
    assert 'antrea_tpu_reshard_catchup_rows_total{node="n0"}' in text


@pytest.mark.slow
def test_dirty_row_catchup_sweeps_touched_set_not_all_slots(world, mesh,
                                                            batch):
    """ROADMAP item 3's production residue: the cutover catch-up sweep
    walks the DIRTY set — rows the engine recorded as touched
    (committed/refreshed/torn down) after their migration window —
    instead of re-walking all O(slots), metered as
    `reshard_catchup_rows_total`; a mid-resize attribution remap (the
    whole-cache write no bounded set covers) falls back to the full
    sweep, metered identically."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    # A lean private hot set (the module batch would migrate 3x the
    # rows through the certify sweep for no extra coverage here).
    hot = gen_traffic(cluster.pod_ips, 96, n_flows=48, seed=899)
    mdp.step(hot, 100)
    r0 = mdp.step(hot, 101)
    G_grow = 2 * KW["flow_slots"]
    mdp.reshard_begin(4)
    # Live steps mid-migration: their touched (replica, slot) pairs —
    # fwd tuples + committed reply legs — form the dirty set.
    t = 102
    for i in range(2):
        mdp.step(gen_traffic(cluster.pod_ips, 64, n_flows=32,
                             seed=900 + i), t)
        mdp.maintenance_tick(now=t)
        t += 1
    t = _run_to_completion(mdp, t)
    rs = mdp.reshard_stats()
    assert rs["cutovers_total"] == 1
    # Bounded by the touched set (3 x 64 lanes x <= 2 directions + the
    # est-set refreshes), FAR under the full slot space — the whole
    # point of dirty tracking.
    assert 0 < rs["catchup_rows_total"] < G_grow // 2, rs
    # Continuity held: the established set serves its pre-resize
    # verdicts off the migrated cache (the mid-churn test holds the
    # full twin-parity bar; this pins the dirty sweep didn't lose rows).
    r1 = mdp.step(hot, t)
    np.testing.assert_array_equal(np.asarray(r1.code), np.asarray(r0.code))
    assert int(np.asarray(r1.est).sum()) > 0
    # Whole-cache fallback wiring: a mid-resize bundle whose rule
    # renumbering remaps cached attribution dirties EVERYTHING — the
    # bounded set clears and the catch-up will take the full O(slots)
    # walk (the pre-tracking shape, still metered); a same-ids bundle
    # must NOT degrade the bounded set.
    mdp.reshard_begin(2)
    mdp.maintenance_tick(now=t)  # at least one migration window first
    mdp.step(hot, t + 1)  # repopulate some dirty rows
    assert mdp._reshard.dirty_all is False
    mdp.install_bundle(cluster.ps)  # same ids in same order: no remap
    assert mdp._reshard.dirty_all is False
    ps2 = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=77).ps
    mdp.install_bundle(ps2)  # renumbering bundle: real remap
    assert mdp._reshard.dirty_all is True
    assert mdp._reshard.status()["dirty_rows"] == 0
    mdp.reshard_abort("fallback wiring pinned; full-sweep path is the "
                      "pre-PR-12 behavior")
    text = render_metrics(mdp, node="n0")
    assert 'antrea_tpu_reshard_catchup_rows_total{node="n0"}' in text


def test_offshard_dnat_reply_leg_reclassifies_to_identical_verdict(world,
                                                                   mesh):
    """The documented ECMP-asymmetry analog, pinned: a DNAT'd service
    reply leg (endpoint -> client; the frontend address is gone from the
    tuple) can land OFF-SHARD and re-classify.  The contract: the
    re-classification yields the IDENTICAL verdict a fresh scalar walk
    of the reply tuple gives (never a wrong verdict), and processing the
    off-shard reply never flaps the forward leg's established entry."""
    from antrea_tpu.oracle.interpreter import Oracle
    from antrea_tpu.packet import PacketBatch

    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    fwd = gen_traffic(cluster.pod_ips, 256, n_flows=128, seed=41,
                      services=services, svc_fraction=1.0)
    mdp.step(fwd, 100)
    r = mdp.step(fwd, 101)
    svc = (np.asarray(r.svc_idx) >= 0) & (np.asarray(r.est) == 1) & (
        np.asarray(r.dnat_ip) != fwd.dst_ip)  # genuinely DNAT-rewritten
    assert svc.any()
    # The reply tuple: endpoint -> client, ports swapped through DNAT.
    rep = PacketBatch(
        src_ip=np.asarray(r.dnat_ip).astype(np.uint32),
        dst_ip=fwd.src_ip,
        proto=fwd.proto,
        src_port=np.asarray(r.dnat_port).astype(np.int32),
        dst_port=fwd.src_port,
    )
    home_fwd = pm.shard_of_tuples(fwd.src_ip, fwd.dst_ip, fwd.proto,
                                  fwd.src_port, fwd.dst_port, 2)
    home_rep = pm.shard_of_tuples(rep.src_ip, rep.dst_ip, rep.proto,
                                  rep.src_port, rep.dst_port, 2)
    off = svc & (home_fwd != home_rep)
    assert off.any(), "no off-shard reply leg in this world — widen it"
    rr = mdp.step(rep, 102)
    oracle = Oracle(cluster.ps)
    codes = np.asarray(rr.code)
    est_r = np.asarray(rr.est)
    checked = 0
    for i in np.nonzero(off)[0]:
        # Off-shard: the flow's own reply entry is invisible (it lives
        # on the forward leg's home shard).  An aliased est hit is
        # possible — the reply tuple may coincide with ANOTHER flow's
        # committed entry on ITS home shard (correct by that entry's own
        # semantics); every non-aliased lane must re-classify FRESH to
        # the verdict the scalar oracle gives the reply tuple.
        if est_r[i]:
            continue
        checked += 1
        assert codes[i] == int(oracle.classify(rep.packet(int(i))).code), i
    assert checked > 0, "every off-shard reply aliased — widen the world"
    on = svc & (home_fwd == home_rep)
    if on.any():
        # On-shard replies hit their conntrack entry (the est bypass).
        assert est_r[np.nonzero(on)[0]].all()
    # No flap: the FORWARD legs keep their verdicts bitwise.  The reply
    # step's own fresh commits may direct-map-collide with a forward
    # entry on a shared shard (the ordinary bounded-cache dynamic — that
    # lane re-classifies to the identical verdict, asserted below); the
    # established set must otherwise survive intact.
    r2 = mdp.step(fwd, 103)
    sel = np.nonzero(svc)[0]
    np.testing.assert_array_equal(np.asarray(r2.code)[sel],
                                  np.asarray(r.code)[sel])
    assert float(np.asarray(r2.est)[sel].mean()) > 0.9
