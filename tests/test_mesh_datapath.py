"""Multichip datapath (parallel/meshpath.MeshDatapath): tier-1 coverage.

Runs on the 8 virtual CPU devices conftest.py forces
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`), so the
sharded-vs-single-chip verdict parity, the mesh-wide epoch swap and the
replica-canary veto are exercised in CI without a TPU — unlike
tests/test_parallel.py (raw kernel parity, slow tier), these cases drive
the full ENGINE: commit plane, per-replica slow path, striped audit and
the maintenance scheduler on the mesh.

The partition-spec drift gate (analysis pass `mesh`: every sharded pytree
field has an explicit PartitionSpec or a reasoned waiver) and the
_shard_map capability-probe assertion.
"""

import pathlib
import sys

import jax
import numpy as np
import pytest

from antrea_tpu.config import ConfigError
from antrea_tpu.datapath.commit import CanaryMismatchError
from antrea_tpu.datapath.tpuflow import TpuflowDatapath
from antrea_tpu.observability.metrics import render_metrics
from antrea_tpu.oracle.interpreter import Oracle
from antrea_tpu.parallel import MeshDatapath, mesh as pm
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.simulator.traffic import gen_traffic

# One mesh + one knob set for every engine in this module: the jitted
# sharded step/canary builders cache by (mesh, meta), so all engines
# share ONE compiled program per variant instead of recompiling per test.
KW = dict(flow_slots=1 << 10, aff_slots=1 << 8, canary_probes=16)


@pytest.fixture(scope="module")
def world():
    cluster = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=7)
    services = gen_services(8, cluster.pod_ips, seed=11)
    return cluster, services


@pytest.fixture(scope="module")
def mesh():
    return pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])


@pytest.fixture(scope="module")
def batch(world):
    cluster, services = world
    return gen_traffic(cluster.pod_ips, 256, n_flows=96, seed=3,
                       services=services, svc_fraction=0.3)


def _mesh_dp(world, mesh, **extra):
    cluster, services = world
    return MeshDatapath(cluster.ps, services, mesh=mesh, **KW, **extra)


# --------------------------------------------------------------------------
# Satellites: the drift gate + the shard_map capability probe
# --------------------------------------------------------------------------

# The partition-spec coverage gate (tools/check_mesh.py -> analysis pass
# `mesh`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.


def test_shard_map_capability_probe():
    """The shim selects its implementation by CAPABILITY PROBE (does the
    installed jax expose the public alias, and which replication-check
    kwarg does its signature carry) instead of a blanket version guess —
    so the assertion is that the probe picked the best implementation
    this image actually has: the public `jax.shard_map` whenever it
    exists, the experimental module otherwise (this image, jax 0.4.x),
    and a check kwarg that really is in the chosen function's
    signature."""
    import inspect

    expected = ("jax.shard_map" if getattr(jax, "shard_map", None) is not None
                else "jax.experimental.shard_map")
    assert pm.SHARD_MAP_IMPL == expected
    assert pm._SHARD_MAP_CHECK_KW in ("check_vma", "check_rep")
    assert pm._SHARD_MAP_CHECK_KW in inspect.signature(
        pm._SHARD_MAP_FN).parameters


def test_shard_affinity_hash_symmetric_and_spread():
    rng = np.random.default_rng(5)
    src = rng.integers(1, 2 ** 32, 4096, dtype=np.uint32)
    dst = rng.integers(1, 2 ** 32, 4096, dtype=np.uint32)
    proto = np.full(4096, 6, np.int32)
    sport = rng.integers(1024, 65535, 4096).astype(np.int32)
    dport = rng.integers(1, 1024, 4096).astype(np.int32)
    fwd = pm.shard_of_tuples(src, dst, proto, sport, dport, 4)
    # Deterministic + direction-symmetric: the reply leg (src/dst and
    # ports swapped) homes to the same shard as the forward leg.
    again = pm.shard_of_tuples(src, dst, proto, sport, dport, 4)
    rev = pm.shard_of_tuples(dst, src, proto, dport, sport, 4)
    np.testing.assert_array_equal(fwd, again)
    np.testing.assert_array_equal(fwd, rev)
    # Spread: no shard starves or hogs (4096 tuples over 4 shards).
    counts = np.bincount(fwd, minlength=4)
    assert counts.min() > 800 and counts.max() < 1300, counts


# --------------------------------------------------------------------------
# Tentpole: sharded full-pipeline verdict parity
# --------------------------------------------------------------------------

def test_sync_mesh_verdict_parity_vs_single_chip(world, mesh, batch):
    """The sharded stateful pipeline (per-shard private caches, pmin over
    the rule axis) serves bitwise-identical VERDICTS to the single-chip
    engine: code, service resolution, DNAT and rule attribution, across
    repeat steps.  (est/committed are cache-TOPOLOGY observables — which
    lanes sit in which direct-mapped table — and legitimately differ
    between one 2^10 table and two private 2^10 shards.)"""
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    for t in range(2):
        rm = mdp.step(batch, 100 + t)
        rs = sdp.step(batch, 100 + t)
        for k in ("code", "svc_idx", "dnat_ip", "dnat_port"):
            np.testing.assert_array_equal(
                np.asarray(getattr(rm, k)), np.asarray(getattr(rs, k)),
                err_msg=f"step{t}:{k}")
        assert rm.ingress_rule == rs.ingress_rule, f"step{t}"
        assert rm.egress_rule == rs.egress_rule, f"step{t}"
    # The stateful fast path engaged: repeat flows hit their home shard.
    assert int(np.asarray(rm.est).sum()) > 0
    # Global census spans every replica's private table.
    c = mdp.cache_stats()
    assert c["slots"] == 2 * KW["flow_slots"]
    assert c["occupied"] > 0


def test_sync_mesh_verdict_parity_vs_oracle(world, mesh):
    """Shard-for-shard scalar-oracle parity on non-service traffic (the
    svc-free lanes are the ones the policy-only interpreter models)."""
    cluster, _services = world
    mdp = _mesh_dp(world, mesh)
    tr = gen_traffic(cluster.pod_ips, 128, n_flows=64, seed=13)
    oracle = Oracle(cluster.ps)
    for t in range(2):  # step 2 re-proves CACHED verdicts against fresh
        codes = np.asarray(mdp.step(tr, 200 + t).code)
        for i in range(tr.size):
            assert codes[i] == int(oracle.classify(tr.packet(i)).code), i


def test_spill_lanes_classify_but_never_cache_foreign(world, mesh):
    """Hash-skew overflow: a batch whose flows all home to ONE shard
    spills half its lanes to the other replica, which must classify them
    correctly (verdict parity holds) but never cache them — foreign
    tables stay empty, so direct-mapped semantics stay per-shard sound."""
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    big = gen_traffic(cluster.pod_ips, 512, n_flows=256, seed=17)
    shard = pm.shard_of_tuples(big.src_ip, big.dst_ip, big.proto,
                               big.src_port, big.dst_port, 2)
    idx = np.nonzero(shard == 0)[0][:64]
    assert idx.size == 64, "seed must yield >= 64 shard-0 flows"
    skew = gen_traffic(cluster.pod_ips, 512, n_flows=256, seed=17).subset(idx) \
        if hasattr(big, "subset") else None
    if skew is None:
        from antrea_tpu.packet import PacketBatch

        skew = PacketBatch.from_packets([big.packet(int(i)) for i in idx])
    rm = mdp.step(skew, 300)
    rs = sdp.step(skew, 300)
    np.testing.assert_array_equal(np.asarray(rm.code), np.asarray(rs.code))
    # All 64 lanes home to replica 0 with 32 slots of home capacity
    # (B/D) — replica 1 classified the spill but cached NOTHING.
    occ = np.asarray(mdp._state.flow.keys)[:, :-1, -1] != 0
    assert occ[1].sum() == 0, "foreign shard cached a spilled flow"
    assert occ[0].sum() > 0


def test_spill_hold_admission_serves_cached_verdicts(world, mesh):
    """admission="hold" under hash skew: after a drain, spilled
    ESTABLISHED flows must serve their real cached verdicts through the
    home-routed retry dispatch — not provisional DROP forever
    (regression: spilled lanes used to re-miss on the foreign shard on
    every step)."""
    from antrea_tpu.packet import PacketBatch

    cluster, _services = world
    adp = _mesh_dp(world, mesh, async_slowpath=True, admission="hold",
                   miss_queue_slots=1 << 12, drain_batch=256)
    # Single-chip async-hold twin: all 64 flows home to shard 0, whose
    # private table is the same size with the same slot hash — so the
    # twin has the IDENTICAL direct-mapped collision set, and pending/
    # verdicts must match lane-for-lane (collision victims legitimately
    # re-miss on both engines; spill must add NOTHING on top).
    sdp = TpuflowDatapath(cluster.ps, None, async_slowpath=True,
                          admission="hold", miss_queue_slots=1 << 12,
                          drain_batch=256, **KW)
    big = gen_traffic(cluster.pod_ips, 512, n_flows=256, seed=17)
    shard = pm.shard_of_tuples(big.src_ip, big.dst_ip, big.proto,
                               big.src_port, big.dst_port, 2)
    idx = np.nonzero(shard == 0)[0][:64]
    skew = PacketBatch.from_packets([big.packet(int(i)) for i in idx])
    for dp in (adp, sdp):
        dp.step(skew, 100)
        dp.drain_slowpath(101)
    r = adp.step(skew, 102)
    rs = sdp.step(skew, 102)
    np.testing.assert_array_equal(np.asarray(r.pending),
                                  np.asarray(rs.pending))
    np.testing.assert_array_equal(np.asarray(r.code), np.asarray(rs.code))
    # The drained flows serve their REAL verdicts through the retry
    # dispatch: far fewer pending lanes than the 32 spilled ones.
    assert int(np.asarray(r.pending).sum()) < 8
    ms = adp.mesh_stats()
    assert ms["spill_retried_total"] == ms["spill_lanes_total"] > 0


# --------------------------------------------------------------------------
# Tentpole: sharded slow path + mesh-wide epoch swap
# --------------------------------------------------------------------------

def test_async_mesh_drain_and_mesh_wide_epoch_swap(world, mesh, batch):
    cluster, services = world
    adp = _mesh_dp(world, mesh, async_slowpath=True,
                   miss_queue_slots=1 << 12, drain_batch=256)
    r0 = adp.step(batch, 100)
    sp0 = adp.slowpath_stats()
    # Per-replica bounded queues: every miss admitted to its HOME shard.
    assert int(np.asarray(r0.pending).sum()) == sum(sp0["replica_depths"])
    assert all(d > 0 for d in sp0["replica_depths"])
    epoch0 = sp0["epoch"]
    st = adp.drain_slowpath(101)
    assert st["drained"] == sum(sp0["replica_depths"])
    # ONE swap flipped every replica: single epoch bump, journaled as a
    # mesh-epoch-swap event carrying the replica count.
    assert adp.slowpath_stats()["epoch"] == epoch0 + 1
    swaps = adp.flightrecorder_events(kind="mesh-epoch-swap")
    assert swaps and swaps[-1]["replicas"] == 2
    # Drained verdicts serve from the cache now.
    r1 = adp.step(batch, 102)
    assert int(np.asarray(r1.est).sum()) > 0
    assert int(np.asarray(r1.pending).sum()) < int(np.asarray(r0.pending).sum())


def test_mesh_drain_with_oversized_explicit_pop_stays_home(world, mesh):
    """begin_drain(n) with n > drain_batch widens each replica's lane
    slice to n (the popped chunk rides the in-flight record): no
    replica's rows may overflow into the next replica's slice — i.e.
    every committed entry must sit in its HOME replica's private table
    (regression: the layout used to assume drain_batch)."""
    import jax

    from antrea_tpu.utils import ip as iputil

    cluster, _services = world
    adp = _mesh_dp(world, mesh, async_slowpath=True,
                   miss_queue_slots=1 << 12, drain_batch=128)
    tr = gen_traffic(cluster.pod_ips, 512, n_flows=256, seed=29)
    adp.step(tr, 100)
    sp = adp._slowpath
    assert sp.begin_drain(101, n=512)
    out = sp.finish_drain(102)
    assert out["drained"] > 128  # the oversized pop actually took effect
    for r in range(2):
        local = jax.tree.map(lambda x, r=r: x[r], adp._state)
        for e in adp._dump_flows_state(local, 103):
            home = pm.shard_of_tuples(
                np.array([iputil.ip_to_key(e["src"])], np.uint32),
                np.array([iputil.ip_to_key(e["dst"])], np.uint32),
                np.array([e["proto"]]), np.array([e["sport"]]),
                np.array([e["dport"]]), 2)[0]
            assert home == r, (r, e)


def test_mesh_epoch_swap_mid_drain_reclassifies_stale(world, mesh):
    """A bundle swap landing between begin_drain and finish_drain pins
    the in-flight per-replica blocks stale: they re-classify under the
    NEW tensors on every replica (counted, never published stale), and
    re-missed flows re-enqueue idempotently — the PR 6 lost-update guard
    across shards."""
    cluster, services = world
    adp = _mesh_dp(world, mesh, async_slowpath=True,
                   miss_queue_slots=1 << 12, drain_batch=256)
    tr = gen_traffic(cluster.pod_ips, 256, n_flows=64, seed=21)
    adp.step(tr, 100)
    sp = adp._slowpath
    assert sp.begin_drain(101)
    gen0 = adp.generation
    adp.install_bundle(cluster.ps, services)
    assert adp.generation == gen0 + 1
    out = sp.finish_drain(102)
    assert out["stale_reclassified"] == out["drained"] > 0
    # Idempotent re-enqueue: re-step the same traffic, drain again — the
    # same flows re-classify into the same home slots, state stays
    # coherent and verdicts stay oracle-true.
    adp.step(tr, 103)
    adp.drain_slowpath(104)
    oracle = Oracle(cluster.ps)
    codes = np.asarray(adp.step(tr, 105).code)
    pend = np.asarray(adp.step(tr, 105).pending)
    for i in range(tr.size):
        if not pend[i]:
            assert codes[i] == int(oracle.classify(tr.packet(i)).code), i


# --------------------------------------------------------------------------
# Tentpole: replica-gated commit plane (veto + fleet rollback)
# --------------------------------------------------------------------------

def test_replica_canary_veto_rolls_back_all_replicas(world, mesh):
    """Chaos: rule-table corruption on ONE replica's device copies.  A
    services-only install (rules NOT recompiled, so the corrupt copies
    survive into the candidate) must be vetoed by that replica's canary
    row — and the rollback restores the sharded snapshot, i.e. every
    replica: the generation is unchanged fleet-wide and the datapath is
    degraded until a full recompile re-places clean tensors."""
    cluster, services = world
    vdp = _mesh_dp(world, mesh)
    desc = vdp.corrupt_replica(1)
    assert "replica 1" in desc
    gen0 = vdp.generation
    with pytest.raises(CanaryMismatchError) as ei:
        vdp.install_bundle(None, gen_services(
            8, cluster.pod_ips, seed=12))
    replicas = sorted({m["replica"] for m in ei.value.mismatches
                       if "replica" in m})
    assert replicas == [1], ei.value.mismatches[:3]
    assert vdp.generation == gen0  # ONE veto rolled back ALL replicas
    assert vdp.degraded
    assert vdp.commit_stats()["replica_mismatches"].get(1, 0) > 0
    # Recovery: the full-bundle recompile re-places every copy from the
    # host mirror and its canary re-certifies all replicas.
    vdp.install_bundle(cluster.ps, services)
    assert not vdp.degraded


def test_replica_veto_watchdog_chain_in_journal(world, mesh):
    """The live-bundle watchdog catches silent per-replica corruption
    between installs, and the flight recorder reconstructs the causal
    chain — replica-canary-veto -> degrade -> recompile commit ->
    recover — in sequence order, with the scheduler's degraded-recompile
    task driving recovery."""
    cluster, services = world
    vdp = _mesh_dp(world, mesh)
    vdp.corrupt_replica(0)
    scan = vdp.canary_scan(recover=False)
    assert scan["mismatches"] > 0 and scan["degraded"]
    assert vdp.commit_stats()["replica_mismatches"].get(0, 0) > 0
    out = vdp.maintenance_tick(now=100)
    assert out["ran"].get("degraded-recompile") == 1
    assert not vdp.degraded
    kinds = [e["kind"] for e in vdp.flightrecorder_events()]
    chain = [k for k in kinds if k in ("replica-canary-veto", "degrade",
                                      "recover")]
    assert chain == ["replica-canary-veto", "degrade", "recover"], kinds


# --------------------------------------------------------------------------
# Tentpole: striped audit cursor across replicas
# --------------------------------------------------------------------------

def test_striped_audit_detects_and_repairs_replica_corruption(world, mesh,
                                                              batch):
    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    mdp.step(batch, 100)
    sdp.step(batch, 100)
    desc = mdp._audit_corrupt("verdict", now=101)
    assert "replica" in desc
    out = mdp.maintenance_force_audit(now=101)
    assert out["divergences"] >= 1 and out["repaired"] >= 1
    # The striped cursor walked EVERY replica's slice in the one sweep.
    assert out["scanned"] == 2 * KW["flow_slots"]
    ms = mdp.mesh_stats()
    assert all(n > 0 for n in ms["replica_audit_entries"]), ms
    # Eviction + lazy reclassify reconverges: verdicts match single-chip.
    rm = mdp.step(batch, 102)
    rs = sdp.step(batch, 102)
    np.testing.assert_array_equal(np.asarray(rm.code), np.asarray(rs.code))
    # A second sweep is clean.
    out2 = mdp.maintenance_force_audit(now=103)
    assert out2["divergences"] == 0


# --------------------------------------------------------------------------
# Surfaces + config validation
# --------------------------------------------------------------------------

def test_mesh_observability_surfaces(world, mesh, batch):
    mdp = _mesh_dp(world, mesh, async_slowpath=True,
                   miss_queue_slots=1 << 10, drain_batch=256)
    mdp.step(batch, 100)
    text = render_metrics(mdp, node="n0")
    for fam in ("antrea_tpu_replica_miss_queue_depth",
                "antrea_tpu_replica_canary_mismatches_total",
                "antrea_tpu_replica_audit_entries_total"):
        assert f'{fam}{{replica="0",node="n0"}}' in text, fam
        assert f'{fam}{{replica="1",node="n0"}}' in text, fam
    ms = mdp.mesh_stats()
    assert ms["mesh"] == {"data": 2, "rule": 2} and ms["devices"] == 4
    # The aggregate queue view backs the shared dump/trace plumbing.
    assert len(mdp.dump_miss_queue()) == sum(ms["replica_miss_queue_depth"])
    # Single-chip commit stats keep the (empty) replica field — schema
    # stable for scrapers either way.
    sdp = TpuflowDatapath(None, None, **KW)
    assert sdp.commit_stats()["replica_mismatches"] == {}


def test_mesh_config_rejections(world, mesh):
    cluster, services = world
    with pytest.raises(ConfigError, match="v4-only"):
        _mesh_dp(world, mesh, dual_stack=True)
    with pytest.raises(ConfigError, match="single-chip knobs"):
        _mesh_dp(world, mesh, async_slowpath=True, overlap_commits=True)
    with pytest.raises(ConfigError, match="single-chip knobs"):
        _mesh_dp(world, mesh, async_slowpath=True, autotune_drain=True)
    with pytest.raises(ConfigError, match="reshard_budget"):
        _mesh_dp(world, mesh, reshard_budget=0)
    mdp = _mesh_dp(world, mesh)
    with pytest.raises(ValueError, match="not divisible"):
        mdp.step(gen_traffic(cluster.pod_ips, 7, n_flows=7, seed=2), 100)
    with pytest.raises(NotImplementedError):
        mdp.profile(None)


def _fwd_topo(n_pods=3):
    from antrea_tpu.compiler.topology import NodeRoute, Topology

    return Topology(
        node_name="node-a",
        gateway_ip="10.10.0.1",
        pod_cidr="10.10.0.0/24",
        local_pods=[(f"10.10.0.{5 + i}", 3 + i) for i in range(n_pods)],
        remote_nodes=[NodeRoute(name="node-b", node_ip="192.168.1.2",
                                pod_cidr="10.10.1.0/24")],
    )


def test_mesh_forwarding_full_walk_parity(world, mesh):
    """PR 9 follow-up (satellite): the mesh engine serves the FULL
    per-packet walk — SpoofGuard -> policy/service -> L2/L3 forward ->
    Output — through one sharded dispatch, bitwise-identical to the
    single-chip engine on every forwarding observable, and
    install_topology swaps atomically like single-chip."""
    from antrea_tpu.compiler.topology import OFPORT_TUNNEL
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.utils import ip as iputil

    cluster, services = world
    topo = _fwd_topo(3)
    mdp = _mesh_dp(world, mesh, topology=topo)
    sdp = TpuflowDatapath(cluster.ps, services, **KW, topology=topo)
    rows = [
        ("10.10.0.5", "10.10.0.6", 3),   # pod->pod local
        ("10.10.0.5", "10.10.1.9", 3),   # pod->remote (tunnel)
        ("10.10.0.6", "8.8.8.8", 4),     # pod->external via gateway
        ("10.10.0.5", "10.10.0.99", 3),  # local CIDR, no such pod
        ("10.10.1.9", "10.10.0.5", OFPORT_TUNNEL),  # tunnel ingress
        ("10.10.0.9", "10.10.0.6", 3),   # SPOOF: src not bound to port 3
        ("10.10.0.7", "10.10.0.5", 5),
        ("10.10.0.6", "10.10.0.7", 4),
    ]
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(s) for s, _, _ in rows],
                        np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(d) for _, d, _ in rows],
                        np.uint32),
        proto=np.full(len(rows), 6, np.int32),
        src_port=np.full(len(rows), 40000, np.int32),
        dst_port=np.full(len(rows), 80, np.int32),
        in_port=np.array([p for _, _, p in rows], np.int32),
    )
    for t in (100, 101):  # step 2: cached-entry path through the walk
        rm, rs = mdp.step(b, t), sdp.step(b, t)
        for f in ("code", "spoofed", "fwd_kind", "out_port", "peer_ip",
                  "dec_ttl", "tc_act", "tc_port", "punt", "mcast_idx",
                  "l7_redirect", "dnat_ip", "dnat_port"):
            np.testing.assert_array_equal(
                np.asarray(getattr(rm, f)), np.asarray(getattr(rs, f)),
                err_msg=f"step{t}:{f}")
    assert int(np.asarray(rm.spoofed).sum()) == 1  # the guard engaged
    # Topology swap: both engines recompute identically (replicated
    # placement re-lands on the mesh through _place_forwarding).
    topo2 = _fwd_topo(2)
    mdp.install_topology(topo2)
    sdp.install_topology(topo2)
    rm, rs = mdp.step(b, 102), sdp.step(b, 102)
    for f in ("code", "spoofed", "fwd_kind", "out_port", "dec_ttl"):
        np.testing.assert_array_equal(
            np.asarray(getattr(rm, f)), np.asarray(getattr(rs, f)),
            err_msg=f)


def test_mesh_group_delta_o1_slot_path_with_parity(world, mesh):
    """PR 9 follow-up (satellite): incremental deltas take the O(delta)
    device slot path ON THE MESH — the per-slot rule masks upload sharded
    on the word axis (no recompile fold) — still canary-gated, still
    generation-bumping, with verdict AND attribution parity on the
    delta-affected tuples."""
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.utils import ip as iputil

    cluster, services = world
    mdp = _mesh_dp(world, mesh)
    sdp = TpuflowDatapath(cluster.ps, services, **KW)
    group = sorted(cluster.ps.address_groups)[0]
    fresh_ip = "172.31.9.9"
    cps0 = mdp._cps
    g1 = mdp.apply_group_delta(group, [fresh_ip], [])
    g2 = sdp.apply_group_delta(group, [fresh_ip], [])
    assert g1 == g2 == 1
    # The slot path, not a fold: the compiled set is untouched and one
    # delta slot is occupied — same bookkeeping as the single-chip twin.
    assert mdp._cps is cps0
    assert mdp._n_deltas == sdp._n_deltas >= 1
    tr = gen_traffic(cluster.pod_ips, 128, n_flows=64, seed=23)
    rm, rs = mdp.step(tr, 100), sdp.step(tr, 100)
    np.testing.assert_array_equal(np.asarray(rm.code), np.asarray(rs.code))
    assert rm.ingress_rule == rs.ingress_rule
    # The delta-affected tuples themselves (fresh member as src and dst).
    pods = sorted(cluster.pod_ips)[:2]
    key = iputil.ip_to_u32(fresh_ip)
    pod_u = [p if not isinstance(p, str) else iputil.ip_to_u32(p)
             for p in pods]
    db = PacketBatch(
        src_ip=np.array([key, pod_u[0]], np.uint32),
        dst_ip=np.array([pod_u[1], key], np.uint32),
        proto=np.full(2, 6, np.int32),
        src_port=np.full(2, 40000, np.int32),
        dst_port=np.full(2, 80, np.int32),
    )
    rm, rs = mdp.step(db, 101), sdp.step(db, 101)
    np.testing.assert_array_equal(np.asarray(rm.code), np.asarray(rs.code))
    assert rm.ingress_rule == rs.ingress_rule
    assert rm.egress_rule == rs.egress_rule
    # Removal leg clears through the slot path too, and the journal
    # carries the canary-gated delta commits (flightrec assertion).
    mdp.apply_group_delta(group, [], [fresh_ip])
    sdp.apply_group_delta(group, [], [fresh_ip])
    rm, rs = mdp.step(db, 102), sdp.step(db, 102)
    np.testing.assert_array_equal(np.asarray(rm.code), np.asarray(rs.code))
    ev = [e for e in mdp.flightrecorder_events(kind="commit")
          if e.get("delta")]
    assert ev and ev[-1]["outcome"] == "ok" and ev[-1]["stage"] == "settle"
