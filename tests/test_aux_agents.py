"""PacketCapture, SecondaryNetwork, WireGuard, ExternalNode tests —
reference semantics cited per module."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.packetcapture import (
    CaptureSpec,
    PacketCaptureController,
    write_capture_file,
)
from antrea_tpu.agent.secondarynetwork import (
    FIRST_SECONDARY_OFPORT,
    NetworkAttachment,
    SecondaryNetworkController,
)
from antrea_tpu.agent.wireguard import WireGuardClient
from antrea_tpu.datapath import TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def _batch(rows):
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(s) for s, _, _ in rows], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(d) for _, d, _ in rows], np.uint32),
        proto=np.full(len(rows), 6, np.int32),
        src_port=np.full(len(rows), 40000, np.int32),
        dst_port=np.array([p for _, _, p in rows], np.int32),
    )


# ---- PacketCapture ----------------------------------------------------------


def test_packetcapture_first_n_and_upload(tmp_path):
    uploads = {}
    pc = PacketCaptureController(
        uploader=lambda name, recs: uploads.__setitem__(name, recs)
    )
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    pc.start(CaptureSpec(name="cap1", src_cidr="10.1.0.0/24", dst_port=80,
                         first_n=3, timeout_s=100), now=0)
    b = _batch([
        ("10.1.0.5", "10.2.0.1", 80),
        ("10.9.0.5", "10.2.0.1", 80),  # src outside filter
        ("10.1.0.6", "10.2.0.2", 443),  # port outside filter
        ("10.1.0.7", "10.2.0.3", 80),
    ])
    r = dp.step(b, now=1)
    assert pc.observe(b, r, now=1) == 2
    assert pc.status("cap1")["captured"] == 2 and not pc.status("cap1")["done"]
    r2 = dp.step(b, now=2)
    assert pc.observe(b, r2, now=2) == 1  # budget hits 3 -> done
    st = pc.status("cap1")
    assert st["done"] and st["reason"] == "firstNCaptured"
    assert "cap1" in uploads and len(uploads["cap1"]) == 3
    rec = uploads["cap1"][0]
    assert rec["src"] == "10.1.0.5" and rec["dport"] == 80
    assert "verdict" in rec and "fwd_kind" in rec
    path = write_capture_file(str(tmp_path / "cap1.jsonl"), "cap1", uploads["cap1"])
    assert len(open(path).read().splitlines()) == 4


def test_packetcapture_timeout():
    pc = PacketCaptureController()
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    pc.start(CaptureSpec(name="idle", src_cidr="10.1.0.0/24", timeout_s=5), now=0)
    b = _batch([("10.9.0.1", "10.2.0.1", 80)])  # never matches
    r = dp.step(b, now=10)
    pc.observe(b, r, now=10)
    assert pc.status("idle")["done"] and pc.status("idle")["reason"] == "timeout"
    assert pc.stop("idle") == []
    assert pc.status("idle") is None


# ---- SecondaryNetwork -------------------------------------------------------


def test_secondary_attach_detach_and_restart(tmp_path):
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    sn = SecondaryNetworkController(store=store)
    sn.upsert_network(NetworkAttachment("vlan100", vlan=100, cidr="172.16.0.0/28"))
    a = sn.attach("c1", "vlan100")
    assert a.vlan == 100 and a.ofport >= FIRST_SECONDARY_OFPORT
    assert sn.attach("c1", "vlan100") == a  # idempotent CmdAdd replay
    b = sn.attach("c2", "vlan100")
    assert b.ip != a.ip and b.ofport != a.ofport
    with pytest.raises(KeyError):
        sn.attach("c3", "nope")

    # Restart: interfaces re-claimed from the persisted store; the IPAM
    # won't re-hand out held addresses, ofports stay unique.
    sn2 = SecondaryNetworkController(store=ConfigStore(str(tmp_path / "conf.db")))
    sn2.upsert_network(NetworkAttachment("vlan100", vlan=100, cidr="172.16.0.0/28"))
    assert [s.ip for s in sn2.interfaces("c1")] == [a.ip]
    c = sn2.attach("c3", "vlan100")
    assert c.ip not in {a.ip, b.ip} and c.ofport > b.ofport
    assert sn2.detach("c1") == 1
    assert sn2.interfaces("c1") == []


def test_secondary_network_redefinition_refused_after_restart(tmp_path):
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    sn = SecondaryNetworkController(store=store)
    sn.upsert_network(NetworkAttachment("v", vlan=100, cidr="172.16.0.0/28"))
    sn.attach("c1", "v")
    sn2 = SecondaryNetworkController(store=ConfigStore(str(tmp_path / "conf.db")))
    with pytest.raises(ValueError):
        sn2.upsert_network(NetworkAttachment("v", vlan=200, cidr="192.168.0.0/24"))


def test_packetcapture_full_range_filters():
    """/0 and top-of-space /32 filters must not overflow uint32."""
    pc = PacketCaptureController()
    pc.start(CaptureSpec(name="all", src_cidr="0.0.0.0/0",
                         dst_cidr="255.255.255.255/32", first_n=5), now=0)
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    b = _batch([("10.1.0.5", "255.255.255.255", 80)])
    r = dp.step(b, now=1)
    assert pc.observe(b, r, now=1) == 1


# ---- WireGuard --------------------------------------------------------------


def test_wireguard_key_persistence_and_peers(tmp_path):
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    wg = WireGuardClient("node-a", store=store)
    pub = wg.public_key
    # Key persists: restart publishes the same public key (client_linux.go
    # loads the stored private key).
    wg2 = WireGuardClient("node-a", store=ConfigStore(str(tmp_path / "conf.db")))
    assert wg2.public_key == pub

    assert wg.upsert_peer("node-b", "PKB", "192.168.1.2", ["10.10.1.0/24"])
    assert not wg.upsert_peer("node-b", "PKB", "192.168.1.2", ["10.10.1.0/24"])
    assert not wg.upsert_peer("node-a", "SELF", "192.168.1.1", ["10.10.0.0/24"])
    assert wg.upsert_peer("node-c", "PKC", "192.168.1.3", ["10.10.2.0/24"])
    assert [p.node for p in wg.peers()] == ["node-b", "node-c"]
    # Cryptokey routing: destination -> owning peer.
    p = wg.peer_for_ip(iputil.ip_to_u32("10.10.2.7"))
    assert p is not None and p.node == "node-c"
    assert wg.peer_for_ip(iputil.ip_to_u32("8.8.8.8")) is None
    assert wg.delete_peer("node-b") and not wg.delete_peer("node-b")


def test_wireguard_longest_prefix_routing():
    wg = WireGuardClient("node-a")
    wg.upsert_peer("aggregate", "PKA", "192.168.1.9", ["10.0.0.0/8"])
    wg.upsert_peer("zspecific", "PKZ", "192.168.1.8", ["10.1.0.0/16"])
    # Cryptokey routing is most-specific-prefix, not first-by-name.
    assert wg.peer_for_ip(iputil.ip_to_u32("10.1.2.3")).node == "zspecific"
    assert wg.peer_for_ip(iputil.ip_to_u32("10.2.0.1")).node == "aggregate"


# ---- ExternalNode -----------------------------------------------------------


def test_externalnode_policies_reach_vm_agent():
    """An ACNP selecting VM labels applies to the ExternalEntity, spans to
    the VM's agent, and enforces on a policy-only datapath — the
    externalnode end-to-end (controller -> entities -> span -> enforcement)."""
    from antrea_tpu.apis import crd
    from antrea_tpu.controller.externalnode import (
        ExternalNode,
        ExternalNodeController,
    )
    from antrea_tpu.controller.networkpolicy import NetworkPolicyController

    npc = NetworkPolicyController()
    enc = ExternalNodeController(npc)
    en = ExternalNode(name="vm-1", namespace="vms",
                      interface_ips=["172.20.0.5"],
                      labels={"role": "db-vm"})
    keys = enc.upsert(en)
    assert keys == ["vms/vm-1-if0"]

    acnp = crd.AntreaNetworkPolicy(
        uid="acnp-vm", name="deny-vm-ingress", namespace="",
        tier_priority=250, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"role": "db-vm"}),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[crd.AntreaNPRule(
            direction=crd.Direction.IN, action=crd.RuleAction.DROP,
        )],
    )
    npc.upsert_antrea_policy(acnp)
    # Span: the VM's own "node" (its agent identity) receives the policy.
    ps = npc.policy_set_for_node("vm-1")
    assert [p.uid for p in ps.policies] == ["acnp-vm"]
    assert npc.policy_set_for_node("some-k8s-node").policies == []

    # Enforcement on the VM agent's policy-only datapath.
    dp = TpuflowDatapath(ps, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=64)
    b = _batch([("10.9.9.9", "172.20.0.5", 5432)])
    assert dp.step(b, now=1).code.tolist() == [1]

    # Interface removal drops the entity; deletion cleans up.
    enc.upsert(ExternalNode(name="vm-1", namespace="vms",
                            interface_ips=[], labels=en.labels))
    assert npc.policy_set_for_node("vm-1").policies == []
    assert enc.delete("vms/vm-1") == 0


def test_wireguard_x25519_known_answer_and_dh():
    """Real X25519 key math (wgtypes.GeneratePrivateKey analog): RFC 7748
    section 5.2 test vector for the scalar-mult base-point derivation, and
    both peers of a DH agreeing on the shared secret (the Noise handshake
    primitive)."""
    import base64

    from antrea_tpu.agent.wireguard import _derive_public, shared_secret

    # RFC 7748 / NaCl known-answer: Alice's private scalar -> public key.
    alice_priv = base64.b64encode(bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )).decode()
    alice_pub_expect = bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    assert base64.b64decode(_derive_public(alice_priv)) == alice_pub_expect

    bob_priv = base64.b64encode(bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )).decode()
    bob_pub = _derive_public(bob_priv)
    # RFC 7748 shared secret K.
    k_expect = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    s1 = shared_secret(alice_priv, bob_pub)
    s2 = shared_secret(bob_priv, _derive_public(alice_priv))
    assert s1 == s2
    assert base64.b64decode(s1) == k_expect

    # Client-level: two nodes exchange published keys and agree.
    a = WireGuardClient("n1")
    b = WireGuardClient("n2")
    assert a.shared_with(b.public_key) == b.shared_with(a.public_key)


def test_bgp_session_wire_scripted_peer():
    """A REAL BGP-4 session (RFC 4271 OPEN/KEEPALIVE/UPDATE over TCP)
    carries the controller's reconciled routes to a scripted peer that
    actually receives them — the round-4 verdict's bar for this row
    (ref controller.go:190 gobgp.NewGoBGPServer: the speaker is driven
    by the same reconcile seam).  Withdrawals remove routes; a second
    peer gets its own session and full RIB."""
    import time

    from antrea_tpu.agent.bgp import BgpController, BgpPeer, BgpPolicy
    from antrea_tpu.agent.bgp_wire import ScriptedBgpPeer, wire_speaker

    p1 = ScriptedBgpPeer(asn=65001)
    p2 = ScriptedBgpPeer(asn=65002)
    peers = [BgpPeer(address="198.51.100.1", asn=65001),
             BgpPeer(address="198.51.100.2", asn=65002)]
    addr = {peers[0]: p1.address, peers[1]: p2.address}
    speaker = wire_speaker(local_asn=64512, router_id="192.0.2.10",
                           next_hop="192.0.2.10",
                           addr_of=lambda p: addr[p])
    try:
        ctl = BgpController("n0", speaker=speaker)
        ctl.set_service_ips(["10.96.0.10", "10.96.0.11"])
        ctl.set_policy(BgpPolicy(name="bp", local_asn=64512, peers=peers,
                                 advertise_service_ips=True,
                                 advertise_pod_cidrs=True))
        ctl.set_pod_cidrs(["10.10.0.0/24"])
        for p in (p1, p2):
            p.wait_established()
        # The peers saw a well-formed OPEN from our AS.
        assert p1.open_seen["version"] == 4
        assert p1.open_seen["asn"] == 64512
        assert p1.open_seen["router_id"] == "192.0.2.10"

        want = {"10.96.0.10/32", "10.96.0.11/32", "10.10.0.0/24"}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not (
                p1.routes == want and p2.routes == want):
            time.sleep(0.05)
        assert p1.routes == want, p1.routes
        assert p2.routes == want, p2.routes

        # Resource deletion withdraws on the wire.
        ctl.set_service_ips(["10.96.0.10"])
        want2 = {"10.96.0.10/32", "10.10.0.0/24"}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and p1.routes != want2:
            time.sleep(0.05)
        assert p1.routes == want2, p1.routes
        # A dead peer must not poison reconcile for the healthy one.
        p2_sess = speaker.sessions[peers[1]]
        p2_sess.close()
        ctl.set_pod_cidrs([])
        assert speaker.errors, "dead session should be recorded, not raised"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and p1.routes != {"10.96.0.10/32"}:
            time.sleep(0.05)
        assert p1.routes == {"10.96.0.10/32"}, p1.routes
    finally:
        speaker.close()
        p1.close()
        p2.close()


def test_wireguard_x25519_pure_python_fallback(monkeypatch):
    """The pure-Python RFC 7748 ladder (the backend for images without
    the cryptography wheel) forced explicitly, so this KAT runs even
    where the wheel IS installed: same vectors as the primary backend,
    plus the low-order-point rejection the cryptography backend performs
    (a null shared secret must raise, never be handed out)."""
    import base64

    import pytest as _pytest

    from antrea_tpu.agent import wireguard as wg

    monkeypatch.setattr(wg, "X25519PrivateKey", None)
    monkeypatch.setattr(wg, "X25519PublicKey", None)
    alice_priv = base64.b64encode(bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )).decode()
    assert base64.b64decode(wg._derive_public(alice_priv)) == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    bob_priv = base64.b64encode(bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )).decode()
    shared = wg.shared_secret(alice_priv, wg._derive_public(bob_priv))
    assert shared == wg.shared_secret(bob_priv, wg._derive_public(alice_priv))
    assert base64.b64decode(shared) == bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    # Low-order peer point (all-zero u) -> null secret -> must reject.
    with _pytest.raises(ValueError):
        wg.shared_secret(alice_priv,
                         base64.b64encode(b"\x00" * 32).decode())
