"""One-kernel fast path (ISSUE 15 tentpole, ops/match round 8) + its
satellites: bitwise fused-vs-staged-vs-oracle parity across the fallback,
svcref, delta-slot, mesh and async-drain regimes; no-pallas HLO pinning at
fused=False; canary+audit certification of a fused instance; the
interpret-mode CPU smoke; the spill-retry prune-accounting dedupe; the
second-chance replacement seed; and per-source admission rate limiting."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.config import ConfigError
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.models import pipeline as pl
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.utils import ip as iputil

KW = dict(flow_slots=1 << 10, aff_slots=1 << 6, canary_probes=0,
          flightrec_slots=0, realization_slots=0)


def _fused(ps, services=None, prune=2, **kw):
    return TpuflowDatapath(ps, services, fused=True, prune_budget=prune,
                           **{"miss_chunk": 32, **KW, **kw})


def _staged(ps, services=None, prune=2, **kw):
    return TpuflowDatapath(ps, services, prune_budget=prune,
                           **{"miss_chunk": 32, **KW, **kw})


def _oracle(ps, services=None, **kw):
    return OracleDatapath(ps, services, **{**KW, **kw})


def _assert_result_parity(a, b, ctx, est=True):
    assert list(a.code) == list(b.code), ctx
    assert list(a.ingress_rule) == list(b.ingress_rule), ctx
    assert list(a.egress_rule) == list(b.egress_rule), ctx
    assert list(a.svc_idx) == list(b.svc_idx), ctx
    assert list(a.dnat_ip) == list(b.dnat_ip), ctx
    assert list(a.dnat_port) == list(b.dnat_port), ctx
    assert list(a.committed) == list(b.committed), ctx
    assert list(a.snat) == list(b.snat), ctx
    assert list(a.dsr) == list(b.dsr), ctx
    if est:
        assert list(a.est) == list(b.est), ctx
        assert list(a.reply) == list(b.reply), ctx


def _assert_state_parity(a, b, ctx):
    """Commit-row parity: the two engines' flow caches must be bitwise
    identical — the one-pass kernel's packed rows and the staged path's
    XLA-packed rows land the same words in the same slots.  Row N (the
    dump row, the masked-scatter junk target no lookup ever reads) is
    excluded: its junk content legitimately differs between the round
    structures."""
    for name in ("keys", "meta", "ts"):
        av = np.asarray(getattr(a._state.flow, name))[:-1]
        bv = np.asarray(getattr(b._state.flow, name))[:-1]
        assert np.array_equal(av, bv), (ctx, name)


# ---------------------------------------------------------------------------
# Tentpole: fused vs staged vs oracle, fallback path included
# ---------------------------------------------------------------------------


def test_fused_step_parity_steady_cold_fallback_and_delta():
    """A multi-superblock world at K=1 exercises the in-kernel candidate
    path AND the pow2-rung fallback; the fused step must be bitwise
    equal to the staged pruned engine (outputs AND commit rows) and to
    the scalar oracle, cold (all-miss) and steady (all-hit) alike.

    The SAME engines then take pending membership deltas (one world, one
    compile set — the tier-1 wall-clock discipline): SET slots patch the
    aggregate rows conservatively, but the in-kernel candidate words are
    unpatched by design — every lane a slot's range touches must take
    the full-width fallback (where _patch_rows applies the delta
    exactly), bitwise on traffic aimed straight at the added/removed
    members."""
    cluster = gen_cluster(2500, seed=12)
    fd = _fused(cluster.ps, prune=1, delta_slots=16)
    sd = _staged(cluster.ps, prune=1, delta_slots=16)
    od = _oracle(cluster.ps, fused=True, prune_budget=1)
    tr = gen_traffic(cluster.pod_ips, batch=160, seed=5)
    for t in range(3):  # t=0 cold, t>0 mostly established
        rf, rs, ro = (fd.step(tr, now=1 + t), sd.step(tr, now=1 + t),
                      od.step(tr, now=1 + t))
        _assert_result_parity(rf, rs, f"staged t={t}")
        _assert_result_parity(rf, ro, f"oracle t={t}")
        _assert_state_parity(fd, sd, f"state t={t}")
    ps = fd.prune_stats()
    assert ps["fallbacks_total"] > 0, "K=1 never exercised the fallback"
    assert ps["skips_total"] > 0 and ps["classified_total"] > 0
    # --- pending-delta phase: O(1) slot patches force the exact fallback.
    g = next(iter(cluster.ps.address_groups))
    members = cluster.ps.address_groups[g].members
    new_ip = "10.200.1.7"
    rm_ip = members[0].ip if members else None
    for dp in (fd, sd, od):
        dp.apply_group_delta(g, added_ips=[new_ip], removed_ips=[])
        if rm_ip:
            dp.apply_group_delta(g, added_ips=[], removed_ips=[rm_ip])
    assert fd._n_deltas >= 1  # the O(1) slot path, not a recompile fold
    # Fresh unique 5-tuples, every lane featuring a delta'd address on
    # one side — padded to the steady batch size so the delta step rides
    # the already-compiled program variant.
    targets = [new_ip] + ([rm_ip] if rm_ip else [])
    pods = [iputil.u32_to_ip(int(p)) for p in cluster.pod_ips[:128]]
    pkts = []
    sport = 31000
    for b_ in pods:
        for a in targets:
            for src, dst in ((a, b_), (b_, a)):
                sport += 1
                pkts.append(Packet(src_ip=iputil.ip_to_u32(src),
                                   dst_ip=iputil.ip_to_u32(dst),
                                   proto=6, src_port=sport, dst_port=80))
        if len(pkts) >= tr.size:
            break
    batch = PacketBatch.from_packets(pkts[:tr.size])
    assert batch.size == tr.size  # shares the steady step's compile
    fb0 = fd.prune_stats()["fallbacks_total"]
    rf, rs, ro = (fd.step(batch, now=10), sd.step(batch, now=10),
                  od.step(batch, now=10))
    _assert_result_parity(rf, rs, "delta staged")
    _assert_result_parity(rf, ro, "delta oracle")
    _assert_state_parity(fd, sd, "delta state")
    # Every lane touched a delta slot's range -> all were fallback-forced.
    assert fd.prune_stats()["fallbacks_total"] - fb0 == batch.size


def test_fused_churn_and_teardown_parity():
    """Churn shape: fresh flows every step plus FIN teardown of
    established ones — the commit/reclaim/teardown interleavings the
    one-pass kernel's packed rows must reproduce bitwise.  (Runs on the
    interpret-smoke world so the fused compile is shared across the
    tier-1 suite.)"""
    cluster = gen_cluster(600, seed=3)
    fd = _fused(cluster.ps)
    sd = _staged(cluster.ps)
    for t in range(4):
        tr = gen_traffic(cluster.pod_ips, batch=96, seed=20 + t)
        rf, rs = fd.step(tr, now=10 + t), sd.step(tr, now=10 + t)
        _assert_result_parity(rf, rs, f"churn t={t}")
        _assert_state_parity(fd, sd, f"churn state t={t}")


def test_fused_svcref_parity():
    """toServices (svcref) worlds OR a second aggregate row and a second
    in-kernel candidate DMA — frontends of the referenced Service drop,
    direct-to-endpoint traffic does not, bitwise vs the oracle."""
    import test_toservices as t

    kws = dict(aff_slots=1 << 4, node_ips=[t.NODE_IP], node_name="n1")
    fd = _fused(t._ps(), t.SVCS, **kws)
    sd = _staged(t._ps(), t.SVCS, **kws)
    od = OracleDatapath(t._ps(), t.SVCS, fused=True, prune_budget=2,
                        **{**KW, **kws})
    assert fd._meta.match.svcref
    probes = [t._pkt(t.CLIENT, "10.96.0.10", 5432),
              t._pkt(t.CLIENT, t.NODE_IP, 30032),
              t._pkt(t.CLIENT, t.DB_EP, 5432),
              t._pkt(t.CLIENT, "10.96.0.11", 80),
              t._pkt("10.0.8.8", "10.96.0.10", 5432)]
    b = PacketBatch.from_packets(probes)
    for now in (1, 2):
        rf, rs, ro = fd.step(b, now=now), sd.step(b, now=now), od.step(
            b, now=now)
        _assert_result_parity(rf, rs, f"svcref staged now={now}")
        _assert_result_parity(rf, ro, f"svcref oracle now={now}")
        _assert_state_parity(fd, sd, f"svcref state now={now}")


def test_fused_mesh_parity():
    """The rule-sharded mesh: the kernel emits GLOBAL hits for the pmin
    seam (resolve/commit-pack post-allreduce) — verdict + attribution
    parity vs the scalar oracle on (data x rule) = (2, 2).  (The oracle
    is the comparator here — fused-vs-staged parity is pinned by the
    single-chip regimes above, and the oracle twin costs no second XLA
    compile.)"""
    from antrea_tpu.parallel.meshpath import MeshDatapath

    cluster = gen_cluster(2500, seed=12)
    md = MeshDatapath(cluster.ps, n_data=2, n_rule=2, miss_chunk=16,
                      fused=True, prune_budget=2, **KW)
    od = _oracle(cluster.ps, fused=True, prune_budget=2)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=14)
    for now in (1, 2):
        rm, ro = md.step(tr, now=now), od.step(tr, now=now)
        assert list(rm.code) == list(ro.code), now
        assert list(rm.ingress_rule) == list(ro.ingress_rule), now
        assert list(rm.egress_rule) == list(ro.egress_rule), now
        assert list(rm.svc_idx) == list(ro.svc_idx), now
    assert md.prune_stats()["classified_total"] > 0
    # The replica-resolved canary must walk the SERVING (fused) consumer
    # too — its jit key carries the instance's fused meta.
    import antrea_tpu.ops.match as mops

    probes = PacketBatch.from_packets([tr.packet(i) for i in range(8)])
    seen = []
    orig = mops.classify_batch

    def _rec(*a, **k):
        seen.append(bool(k.get("fused", False)))
        return orig(*a, **k)

    mops.classify_batch = _rec
    try:
        got = md._canary_classify(probes, now=3)
    finally:
        mops.classify_batch = orig
    assert seen and all(seen), seen
    assert got.shape == (2, probes.size)


def test_fused_async_drain_parity():
    """The async engine's coalesced drains run the one-pass kernel
    (miss_chunk == the popped block); verdict + established parity vs
    the oracle twin across admit -> drain -> re-hit."""
    cluster = gen_cluster(600, seed=3)
    fd = _fused(cluster.ps, async_slowpath=True, drain_batch=64)
    od = _oracle(cluster.ps, async_slowpath=True, drain_batch=64)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=14)
    rf, ro = fd.step(tr, now=1), od.step(tr, now=1)
    assert list(rf.code) == list(ro.code)
    assert list(rf.pending) == list(ro.pending)
    fd.drain_slowpath(now=2)
    od.drain_slowpath(now=2)
    rf, ro = fd.step(tr, now=3), od.step(tr, now=3)
    _assert_result_parity(rf, ro, "post-drain")
    assert int(np.asarray(rf.est).sum()) > 0  # drains established flows


# ---------------------------------------------------------------------------
# HLO pinning at fused=False + interpret smoke
# ---------------------------------------------------------------------------


def test_step_hlo_no_pallas_and_identical_with_fused_disabled():
    """fused=False must stay the staged program: (1) its lowered step
    carries NO pallas custom-call, and (2) an explicit onepass=False over
    fused+pruned knobs (the bench_profile --mode prune contract) lowers
    BIT-IDENTICALLY to the plain staged pruned instance."""
    cluster = gen_cluster(300, seed=7)
    cps = compile_policy_set(cluster.ps)
    from antrea_tpu.compiler.services import compile_services

    svc = compile_services([])

    def lowered(**kw):
        step, st, (drs, dsvc) = pl.make_pipeline(
            cps, svc, flow_slots=1 << 8, aff_slots=1 << 4, miss_chunk=32,
            **kw)
        cols = (jnp.zeros(128, jnp.int32),) * 5
        return jax.jit(
            pl._pipeline_step, static_argnames=("meta",),
        ).lower(st, drs, dsvc, *cols, jnp.int32(1), jnp.int32(0),
                meta=step.meta).as_text()

    staged = lowered(prune_budget=2)
    # Explicit onepass=False / default knobs lower BIT-IDENTICALLY to the
    # plain staged pruned program (the fused=False contract; the vs-HEAD
    # half of the acceptance bar was verified against the pre-PR tree).
    pinned_off = lowered(prune_budget=2, fused=False, onepass=False)
    assert pinned_off == staged
    assert lowered(prune_budget=2, second_chance=False) == staged
    # The one-pass program is genuinely different (on the CPU tier the
    # kernel lowers through interpret mode, so the evidence is program
    # inequality + the scatter structure, not a custom-call marker).
    fused = lowered(prune_budget=2, fused=True)
    assert fused != staged


def test_fused_interpret_smoke():
    """The whole one-pass kernel — probe, DMA double-buffer, first
    match, resolve, commit-row pack — executes under pallas interpret
    mode on the CPU tier (the conftest platform), end to end."""
    assert jax.devices()[0].platform == "cpu"
    cluster = gen_cluster(600, seed=3)
    fd = _fused(cluster.ps)
    assert fd._meta.onepass
    tr = gen_traffic(cluster.pod_ips, batch=96, seed=4)
    r = fd.step(tr, now=1)
    assert len(list(r.code)) == 96
    st = fd.prune_stats()
    assert st["classified_total"] > 0
    r2 = fd.step(tr, now=2)
    assert int(np.asarray(r2.est).sum()) > 0  # commits landed


# ---------------------------------------------------------------------------
# Canary + audit certification on a fused instance
# ---------------------------------------------------------------------------


def test_canary_and_audit_certify_fused_instance():
    """The eager twin walks carry the fused meta: a fused instance's
    install canary and a full audit sweep certify the serving
    configuration (zero mismatches, zero divergences).  (Same world and
    shapes as the interpret smoke — the serving-step compile is shared;
    the planes themselves run eager twin walks.)"""
    cluster = gen_cluster(600, seed=3)
    dp = TpuflowDatapath(cluster.ps, miss_chunk=32, fused=True,
                         prune_budget=2, flow_slots=1 << 10,
                         aff_slots=1 << 6, canary_probes=16,
                         flightrec_slots=64, realization_slots=16)
    assert dp._meta.onepass and dp._meta.fused
    tr = gen_traffic(cluster.pod_ips, batch=96, seed=10)
    dp.step(tr, now=1)
    gen0 = dp.generation
    dp.install_bundle(cluster.ps)  # canary-gated (fused trace walk)
    cp = dp.commit_stats()
    assert dp.generation == gen0 + 1 and not cp["degraded"]
    assert cp["canary_probes_total"] > 0
    assert cp["canary_mismatches_total"] == 0
    dp.audit_scan(now=2, full=True)  # fresh re-proof via the fused walk
    au = dp.audit_stats()
    assert au["entries_total"] > 0
    assert au["repairs_total"] == 0 and not au["divergences"]
    # The certification is only worth its name if the probes walked the
    # SERVING consumer: pin that the canary's classify carries the
    # instance's fused meta (a fused=False canary would certify the
    # shadow XLA path and pass all the green checks above regardless).
    seen = []
    orig = pl.classify_batch

    def _rec(*a, **k):
        seen.append(bool(k.get("fused", False)))
        return orig(*a, **k)

    pl.classify_batch = _rec
    try:
        dp._canary_classify(tr, now=3)
    finally:
        pl.classify_batch = orig
    assert seen and all(seen), seen


# ---------------------------------------------------------------------------
# Autotune compatibility (meta-only K swaps)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fused_autotune_retune_is_meta_only():
    """A PruneAutotuner retune under the fused path swaps K in the meta
    (a new jit-cached one-pass variant per rung) — serving stays
    parity-correct across the move."""
    cluster = gen_cluster(2500, seed=2)
    fd = _fused(cluster.ps, prune=1, autotune_prune=True)
    sd = _staged(cluster.ps, prune=1)
    tr = gen_traffic(cluster.pod_ips, batch=160, seed=5)
    k0 = fd._prune_budget
    # The K=1 multi-superblock world produces a high fallback rate; two
    # sticky signals move the rung up.
    for t in range(4):
        tr_t = gen_traffic(cluster.pod_ips, batch=160, seed=40 + t)
        fd.step(tr_t, now=1 + t)
        sd.step(tr_t, now=1 + t)
    # The K=1 fallback pressure retunes UP, and the then-clean K=2 rung
    # retunes back DOWN — both moves serve through jit-cached one-pass
    # variants (every move is a meta-only swap).
    assert fd.prune_stats()["retunes_total"] > 0, (
        "fallback pressure never retuned K")
    assert fd._prune_tuner.decisions_up > 0
    assert fd._meta.match.prune_budget == fd._prune_budget
    del k0
    # Post-retune parity (fresh traffic through the new rung's variant).
    sd2 = _staged(cluster.ps, prune=fd._prune_budget)
    tr2 = gen_traffic(cluster.pod_ips, batch=96, seed=77)
    rf, rs = fd.step(tr2, now=50), sd2.step(tr2, now=50)
    assert list(rf.code) == list(rs.code)
    assert list(rf.ingress_rule) == list(rs.ingress_rule)


# ---------------------------------------------------------------------------
# Profile mode + config errors
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_profile_fused_mode_both_engines():
    from antrea_tpu.models.profile import FUSED_PHASE_CHAIN

    cluster = gen_cluster(400, seed=5)
    kw = dict(flow_slots=1 << 8, aff_slots=1 << 4, canary_probes=0,
              flightrec_slots=0, realization_slots=0)
    fd = TpuflowDatapath(cluster.ps, miss_chunk=32, fused=True,
                         prune_budget=2, **kw)
    od = OracleDatapath(cluster.ps, fused=True, prune_budget=2, **kw)
    hot = gen_traffic(cluster.pod_ips, batch=64, seed=6)
    fresh = gen_traffic(cluster.pod_ips, batch=64, seed=7)
    prof = fd.profile(hot, fresh, n_new=16, k_small=1, k_big=2, repeats=1,
                      mode="fused")
    names = [n for n, _m in FUSED_PHASE_CHAIN]
    assert list(prof["phases_s"].keys()) == names
    assert prof["mode"] == "fused" and prof["prune_budget"] == 2
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-9
    po = od.profile(hot, fresh, mode="fused")
    assert po["mode"] == "fused"
    assert set(po["phases_s"]) == {"fused_fast_path", "fused_onepass",
                                   "fused_commit_residual"}
    # Both engines refuse the mode on a non-one-pass instance.
    sd = TpuflowDatapath(cluster.ps, miss_chunk=32, prune_budget=2, **kw)
    on = OracleDatapath(cluster.ps, prune_budget=2, **kw)
    for dp in (sd, on):
        with pytest.raises(ValueError):
            dp.profile(hot, fresh, mode="fused")


def test_profile_fused_mode_surface():
    """Tier-1 shard of the profile surface (the full device-timed chain
    runs in the slow tier): the scalar twin's fused names and both
    engines' refusal on a non-one-pass instance."""
    cluster = gen_cluster(400, seed=5)
    kw = dict(flow_slots=1 << 8, aff_slots=1 << 4, canary_probes=0,
              flightrec_slots=0, realization_slots=0)
    od = OracleDatapath(cluster.ps, fused=True, prune_budget=2, **kw)
    hot = gen_traffic(cluster.pod_ips, batch=32, seed=6)
    po = od.profile(hot, mode="fused")
    assert po["mode"] == "fused" and po["prune_budget"] == 2
    assert set(po["phases_s"]) == {"fused_fast_path", "fused_onepass",
                                   "fused_commit_residual"}
    sd = TpuflowDatapath(cluster.ps, miss_chunk=32, prune_budget=2, **kw)
    on = OracleDatapath(cluster.ps, prune_budget=2, **kw)
    for dp in (sd, on):
        with pytest.raises(ValueError):
            dp.profile(hot, mode="fused")


def test_fused_config_errors():
    cluster = gen_cluster(200, seed=5)
    # One-pass is v4-only: fused + pruned + dual_stack rejected, both
    # engines, at construction.
    for cls in (TpuflowDatapath, OracleDatapath):
        with pytest.raises(ConfigError):
            cls(cluster.ps, fused=True, prune_budget=2, dual_stack=True,
                **KW)
    # fused + dual_stack WITHOUT pruning stays legal (staged consumer).
    TpuflowDatapath(cluster.ps, fused=True, dual_stack=True, **KW)
    # Source rate limiting configures the async admission only.
    for cls in (TpuflowDatapath, OracleDatapath):
        with pytest.raises(ConfigError):
            cls(cluster.ps, miss_source_rate=8, **KW)
        with pytest.raises(ConfigError):
            cls(cluster.ps, async_slowpath=True, miss_source_rate=0, **KW)
        with pytest.raises(ConfigError):
            cls(cluster.ps, async_slowpath=True, miss_source_rate=8,
                miss_source_burst=0, **KW)


# ---------------------------------------------------------------------------
# Satellite: spill-retry prune-accounting dedupe (skew batch)
# ---------------------------------------------------------------------------


def test_mesh_spill_retry_prune_evidence_exactly_once():
    """Prune evidence under hash-skew spill: each lane feeds the
    PruneAutotuner band exactly once, from its HOME (serving) walk — the
    mesh's counters must equal a single-chip twin's on the same traffic
    (the main dispatch excludes spilled lanes; their home-routed retry
    accounts them instead)."""
    from antrea_tpu.parallel.meshpath import MeshDatapath

    cluster = gen_cluster(2500, seed=12)
    md = MeshDatapath(cluster.ps, n_data=2, n_rule=1, miss_chunk=16,
                      prune_budget=1, **KW)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=13)
    spills = 0
    n_miss_sum = 0
    for now in (1, 2, 3):
        r = md.step(tr, now=now)
        n_miss_sum += int(r.n_miss)
        spills = int(md.mesh_stats()["spill_lanes_total"])
    mp = md.prune_stats()
    assert spills > 0, "the batch never spilled — no skew to pin"
    # Exactly-once, home-walk evidence: the merged per-lane miss mask IS
    # the home-walk image (a retried lane that HITS its home cache is
    # not a classification), so the classified meter must equal the
    # summed miss counts bit for bit.  The pre-fix accounting kept the
    # foreign walk's evidence — always-miss for spilled lanes — which
    # inflates classified_total past the home-walk misses from the
    # second step on (established flows re-hit at home).
    assert mp["classified_total"] == n_miss_sum, (
        mp["classified_total"], n_miss_sum)
    assert 0 < mp["fallbacks_total"] <= mp["classified_total"]


# ---------------------------------------------------------------------------
# Satellite: second-chance replacement (thrash resistance)
# ---------------------------------------------------------------------------


def _est_flow_batch(pairs, sport=9000, dport=80):
    return PacketBatch.from_packets([
        Packet(src_ip=iputil.ip_to_u32(s), dst_ip=iputil.ip_to_u32(d),
               proto=6, src_port=sport + i, dst_port=dport)
        for i, (s, d) in enumerate(pairs)])


def _reply_batch(est: PacketBatch) -> PacketBatch:
    """The reverse-direction legs of `est` (no services: dnat == dst)."""
    return PacketBatch.from_packets([
        Packet(src_ip=int(est.dst_ip[i]), dst_ip=int(est.src_ip[i]),
               proto=int(est.proto[i]), src_port=int(est.dst_port[i]),
               dst_port=int(est.src_port[i]))
        for i in range(est.size)])


def _allowed_pairs(cluster, n):
    """Pod pairs the policy world ALLOWS (a denial entry is never
    CONFIRMED-established, so it gets no second chance by design)."""
    from antrea_tpu.oracle import Oracle

    oracle = Oracle(cluster.ps)
    pods = [iputil.u32_to_ip(int(p)) for p in cluster.pod_ips[:64]]
    out = []
    for i, s in enumerate(pods):
        for d in pods[i + 1:]:
            p = Packet(src_ip=iputil.ip_to_u32(s),
                       dst_ip=iputil.ip_to_u32(d), proto=6,
                       src_port=9000, dst_port=80)
            if oracle.classify(p).code == 0:
                out.append((s, d))
                break
        if len(out) >= n:
            break
    assert len(out) >= n, "world has too few allowed pairs"
    return out[:n]


def test_second_chance_pins_established_under_thrash():
    """A gen_cache_thrash storm (universe >> slots) cannot evict an
    ACTIVE established flow: with second_chance=True the established
    table rows survive the storm bitwise on both engines, in full
    oracle parity; with the knob off the same storm evicts some of
    them (the control that proves the mechanism)."""
    from antrea_tpu.simulator.traffic import gen_cache_thrash

    cluster = gen_cluster(600, seed=3)
    est = _est_flow_batch(_allowed_pairs(cluster, 8))
    rep = _reply_batch(est)

    def run(second_chance, with_oracle=True):
        # miss_chunk >= every batch: single-round commit passes, so the
        # device's once-per-pass counter bump matches the oracle's
        # once-per-step bookkeeping exactly (the documented multi-round
        # divergence of the chunked sync path).  The control run (knob
        # off) only has to prove the storm EVICTS — it skips the oracle
        # twin, parity is the ON run's claim.
        dp = TpuflowDatapath(cluster.ps, miss_chunk=256, second_chance=
                             second_chance, flow_slots=1 << 6,
                             aff_slots=1 << 4, canary_probes=0,
                             flightrec_slots=0, realization_slots=0)
        od = OracleDatapath(cluster.ps, second_chance=second_chance,
                            flow_slots=1 << 6, aff_slots=1 << 4,
                            canary_probes=0, flightrec_slots=0,
                            realization_slots=0) if with_oracle else None
        engines = (dp, od) if od is not None else (dp,)
        now = 1
        for e in engines:
            e.step(est, now=now)   # forward leg commits both directions
            e.step(rep, now=now)   # reply leg CONFIRMS the connection
        now += 1
        r = dp.step(est, now=now)
        if od is not None:
            od.step(est, now=now)
        assert list(r.code) == [0] * est.size  # genuinely allowed
        # A self-collision inside the est set itself (direct-mapped) may
        # cost a lane at establishment time; the storm pin covers the
        # rows that DID establish.
        alive = int(np.asarray(r.est).sum())
        assert alive >= 6, "est set mostly self-collided — widen the cache"
        keys0 = np.asarray(dp._state.flow.keys).copy()
        rows0 = {i for i in range(keys0.shape[0] - 1) if keys0[i, 3] != 0}
        # Exactly CHANCE_MAX storm passes between refreshes: a confirmed
        # row's counter reaches at most CHANCE_MAX and never yields.
        for rnd in range(3):
            now += 1
            storm = gen_cache_thrash(cluster.pod_ips, 128,
                                     n_flows=1 << 12, seed=50 + rnd)
            rd = dp.step(storm, now=now)
            if od is not None:
                ro = od.step(storm, now=now)
                assert list(rd.code) == list(ro.code), (second_chance, rnd)
            now += 1
            rd = dp.step(est, now=now)
            if od is not None:
                ro = od.step(est, now=now)
                assert list(rd.code) == list(ro.code)
            # Active connections are TWO-WAY: the reply legs' own hits
            # are what reset THEIR counters (a forward hit refreshes
            # only its own row at this cadence).
            rr = dp.step(rep, now=now)
            if od is not None:
                rro = od.step(rep, now=now)
                assert list(rr.code) == list(rro.code)
            if second_chance:
                # Every established flow still serves from its entry
                # (its own hits keep resetting the collision counter).
                assert int(np.asarray(rd.est).sum()) == alive, rnd
        keys1 = np.asarray(dp._state.flow.keys)
        survived = all(np.array_equal(keys0[i], keys1[i]) for i in rows0)
        return survived, dp, od

    survived_on, dp_on, od_on = run(True)
    assert survived_on, "second_chance failed to pin the established rows"
    assert od_on._oracle.chance_suppressed > 0
    survived_off, _dp, _od = run(False, with_oracle=False)
    assert not survived_off, (
        "the storm never collided with an established row — the control "
        "case proves nothing; shrink flow_slots or grow the storm")


def test_second_chance_yields_after_max_collisions():
    """A SILENT (non-refreshing but confirmed-established) entry yields
    after CHANCE_MAX colliding passes — bounded protection, never a
    wedged slot."""
    from antrea_tpu.models.pipeline import CHANCE_MAX

    cluster = gen_cluster(600, seed=3)
    est = _est_flow_batch(_allowed_pairs(cluster, 2))
    rep = _reply_batch(est)
    # Same shapes as the thrash test's second_chance=True engines: the
    # staged consumer compile is shared; the smaller est set (2 flows in
    # 64 slots) still collides every storm pass.
    dp = TpuflowDatapath(cluster.ps, miss_chunk=256, second_chance=True,
                         flow_slots=1 << 6, aff_slots=1 << 4,
                         canary_probes=0, flightrec_slots=0,
                         realization_slots=0)
    od = OracleDatapath(cluster.ps, second_chance=True, flow_slots=1 << 6,
                        aff_slots=1 << 4, canary_probes=0,
                        flightrec_slots=0, realization_slots=0)
    for e in (dp, od):
        e.step(est, now=1)
        e.step(rep, now=1)  # CONFIRM — unconfirmed entries get no chance
    keys0 = np.asarray(dp._state.flow.keys).copy()
    live0 = (keys0[:, 3] != 0).sum()
    # Storm WITHOUT ever refreshing the established flow: after more
    # than CHANCE_MAX colliding passes every slot is reclaimable.
    from antrea_tpu.simulator.traffic import gen_cache_thrash

    for rnd in range(CHANCE_MAX + 3):
        storm = gen_cache_thrash(cluster.pod_ips, 128, n_flows=1 << 12,
                                 seed=80 + rnd)
        rd, ro = dp.step(storm, now=2 + rnd), od.step(storm, now=2 + rnd)
        assert list(rd.code) == list(ro.code), rnd
    keys1 = np.asarray(dp._state.flow.keys)
    changed = any(
        keys0[i, 3] != 0 and not np.array_equal(keys0[i], keys1[i])
        for i in range(keys0.shape[0] - 1))
    assert changed, (
        f"no established slot was ever reclaimed after "
        f"{CHANCE_MAX + 3} storm passes over {live0} live rows")


# ---------------------------------------------------------------------------
# Satellite: per-source slow-path rate limiting
# ---------------------------------------------------------------------------


def _world_async(**kw):
    cluster = gen_cluster(400, seed=5)
    common = dict(flow_slots=1 << 8, aff_slots=1 << 4,
                  async_slowpath=True, miss_queue_slots=256,
                  drain_batch=32, canary_probes=0, flightrec_slots=0,
                  realization_slots=0, node_name="n1", **kw)
    return (cluster,
            TpuflowDatapath(cluster.ps, miss_chunk=64, **common),
            OracleDatapath(cluster.ps, **common))


def test_source_rate_limit_parity_under_syn_flood():
    """The per-source-/24 bucket clamps a flooding prefix ahead of the
    early-drop ramp, deterministically — full verdict parity every step,
    identical nonzero shed counts on both engines, and an innocent
    source's misses keep admitting while the attacker is clamped."""
    from antrea_tpu.simulator.traffic import gen_syn_flood

    cluster, t, o = _world_async(miss_source_rate=4, miss_source_burst=16)
    dst = [int(cluster.pod_ips[0])]
    seq = 0
    for rnd in range(5):
        flood = gen_syn_flood(dst, 96, start_seq=seq)
        seq += 96
        now = 10 + rnd
        rt, ro = t.step(flood, now=now), o.step(flood, now=now)
        assert list(rt.code) == list(ro.code), rnd
        assert list(rt.pending) == list(ro.pending), rnd
    ts_, os_ = (t._slowpath.source_limited_total,
                o._slowpath.source_limited_total)
    assert ts_ == os_ > 0, (ts_, os_)
    for dp in (t, o):
        assert dp.slowpath_stats()["source_limited_total"] == ts_
    # Metric renders as its registered family.
    from antrea_tpu.observability.metrics import render_metrics

    assert (f'antrea_tpu_miss_queue_source_limited_total{{node="n1"}} {ts_}'
            in render_metrics(t, node="n1"))
    # An innocent source (different /24) still admits at full rate.
    before = t._slowpath.queue.admitted_total
    innocent = PacketBatch.from_packets([
        Packet(src_ip=iputil.ip_to_u32(f"10.77.3.{i + 1}"),
               dst_ip=int(cluster.pod_ips[0]), proto=6,
               src_port=40000 + i, dst_port=80) for i in range(8)])
    t.step(innocent, now=100)
    assert t._slowpath.queue.admitted_total - before == 8


def test_source_rate_limit_refills_on_packet_clock():
    """Token refill is pure clock arithmetic: after the flooding prefix
    goes quiet for rate*dt worth of tokens, its misses admit again."""
    cluster, t, o = _world_async(miss_source_rate=2, miss_source_burst=4)
    src = iputil.ip_to_u32("10.50.0.9")

    def burst(now, n, base):
        b = PacketBatch.from_packets([
            Packet(src_ip=src, dst_ip=int(cluster.pod_ips[0]), proto=6,
                   src_port=base + i, dst_port=80) for i in range(n)])
        return t.step(b, now=now), o.step(b, now=now)

    burst(10, 8, 50000)  # burst of 4 exhausted, 4 shed
    assert t._slowpath.source_limited_total == 4
    assert o._slowpath.source_limited_total == 4
    burst(11, 4, 51000)  # only 2 tokens refilled (rate=2/s, dt=1)
    assert t._slowpath.source_limited_total == 6
    burst(100, 4, 52000)  # long quiet: full burst back
    assert t._slowpath.source_limited_total == 6
    # Out-of-order clock: an OLDER now must neither drive tokens negative
    # (mis-counting sheds) nor rewind the refill stamp (over-refilling the
    # next in-order batch).  Tokens are 0 at stamp 100: the stale batch
    # sheds exactly its 4 lanes, and the next in-order second refills
    # rate*1 = 2 tokens, not a full burst.
    burst(50, 4, 53000)
    assert t._slowpath.source_limited_total == 10
    burst(101, 2, 54000)
    assert t._slowpath.source_limited_total == 10
    assert t._slowpath.source_limited_total == o._slowpath.source_limited_total


def test_source_rate_limit_mesh_replica_independent():
    """On the mesh the limiter runs ONCE per batch ahead of the
    per-replica ramps — shed totals are per-source, not per-replica."""
    from antrea_tpu.parallel.meshpath import MeshDatapath
    from antrea_tpu.simulator.traffic import gen_syn_flood

    cluster = gen_cluster(400, seed=5)
    md = MeshDatapath(cluster.ps, n_data=2, miss_chunk=64,
                      async_slowpath=True, miss_queue_slots=128,
                      drain_batch=32, miss_source_rate=4,
                      miss_source_burst=8, flow_slots=1 << 8,
                      aff_slots=1 << 4, canary_probes=0, flightrec_slots=0,
                      realization_slots=0)
    sd = TpuflowDatapath(cluster.ps, miss_chunk=64, async_slowpath=True,
                         miss_queue_slots=128, drain_batch=32,
                         miss_source_rate=4, miss_source_burst=8,
                         flow_slots=1 << 8, aff_slots=1 << 4,
                         canary_probes=0, flightrec_slots=0,
                         realization_slots=0)
    dst = [int(cluster.pod_ips[0])]
    flood = gen_syn_flood(dst, 64, start_seq=0)
    md.step(flood, now=1)
    sd.step(flood, now=1)
    assert (md._slowpath.source_limited_total
            == sd._slowpath.source_limited_total > 0)
