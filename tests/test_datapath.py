"""Datapath plugin boundary tests (VERDICT r1 items #5/#6): everything here
drives ONLY the `Datapath` interface — no kernel internals — and diffs the
tpuflow implementation against the oracle implementation, the way the
reference diffs its flow pipeline against real OVS
(test/integration/agent/openflow_test.go model).

Also covers the incremental-update path: a membership delta must produce
identical verdicts to a from-scratch compile of the mutated policy set,
WITHOUT recompiling (same bitmap tensors, small delta upload only).
"""

import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.datapath import (
    DatapathType,
    OracleDatapath,
    TpuflowDatapath,
    make_datapath,
)
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.utils import ip as iputil


def _mk_pair(n_rules=120, n_services=12, seed=3, delta_slots=64, **dp_kw):
    cluster = gen_cluster(n_rules, n_nodes=4, pods_per_node=8, seed=seed)
    services = gen_services(n_services, cluster.pod_ips, seed=seed + 1)
    import copy

    tpu = TpuflowDatapath(
        copy.deepcopy(cluster.ps), services,
        flow_slots=1 << 12, aff_slots=1 << 10, miss_chunk=64,
        delta_slots=delta_slots, **dp_kw,
    )
    orc = OracleDatapath(
        copy.deepcopy(cluster.ps), services,
        flow_slots=1 << 12, aff_slots=1 << 10,
    )
    return cluster, services, tpu, orc


def _diff(tr, a, b, *, check_rules=True):
    assert a.code.tolist() == b.code.tolist()
    assert a.est.tolist() == b.est.tolist()
    assert a.reply.tolist() == b.reply.tolist()
    assert a.reject_kind.tolist() == b.reject_kind.tolist()
    assert a.snat.tolist() == b.snat.tolist()
    assert a.svc_idx.tolist() == b.svc_idx.tolist()
    assert a.dnat_ip.tolist() == b.dnat_ip.tolist()
    assert a.dnat_port.tolist() == b.dnat_port.tolist()
    assert a.committed.tolist() == b.committed.tolist()
    assert a.n_miss == b.n_miss
    if check_rules:
        # Rule attribution is exact for freshly classified packets; for
        # cached hits both sides report at-commit attribution, which a
        # renumbering bundle can legitimately skew (ct_label semantics) —
        # so compare only non-est, non-hit packets after bundles.
        for i in range(len(a.ingress_rule)):
            if a.est[i] == 0 and a.committed[i] == 0 and a.code[i] != 0:
                assert a.ingress_rule[i] == b.ingress_rule[i], i
                assert a.egress_rule[i] == b.egress_rule[i], i


def _batch(cluster, services, n, seed):
    tr = gen_traffic(
        cluster.pod_ips, n, n_flows=max(8, n // 3), seed=seed,
        services=services, svc_fraction=0.3,
    )
    return PacketBatch(
        src_ip=tr.src_ip, dst_ip=tr.dst_ip, proto=tr.proto,
        src_port=tr.src_port, dst_port=tr.dst_port,
    )


def test_factory():
    dp = make_datapath("oracle")
    assert dp.datapath_type == DatapathType.ORACLE
    dp = make_datapath(DatapathType.TPUFLOW)
    assert dp.datapath_type == DatapathType.TPUFLOW


def test_differential_steady_and_bundles():
    cluster, services, tpu, orc = _mk_pair()
    for step_i in range(3):
        b = _batch(cluster, services, 192, seed=10 + step_i)
        _diff(b, tpu.step(b, now=100 + step_i), orc.step(b, now=100 + step_i))

    # Bundle commit: swap in a different policy set; established survive.
    cluster2 = gen_cluster(80, n_nodes=4, pods_per_node=8, seed=99)
    import copy

    assert tpu.install_bundle(ps=copy.deepcopy(cluster2.ps)) == orc.install_bundle(
        ps=copy.deepcopy(cluster2.ps)
    )
    for step_i in range(2):
        b = _batch(cluster, services, 192, seed=10 + step_i)  # same flows
        ra, rb = tpu.step(b, now=200 + step_i), orc.step(b, now=200 + step_i)
        _diff(b, ra, rb, check_rules=False)
    assert int(ra.est.sum()) > 0  # some connections survived the bundle


def test_differential_group_delta():
    cluster, services, tpu, orc = _mk_pair()
    b = _batch(cluster, services, 160, seed=21)
    _diff(b, tpu.step(b, now=50), orc.step(b, now=50))

    # Move two pods in/out of an address group, incrementally.
    ag = sorted(cluster.ps.address_groups)[0]
    victim = cluster.ps.address_groups[ag].members[0].ip
    newcomer = "10.9.9.9"
    g1 = tpu.apply_group_delta(ag, added_ips=[newcomer], removed_ips=[victim])
    g2 = orc.apply_group_delta(ag, added_ips=[newcomer], removed_ips=[victim])
    assert g1 == g2
    # The tpuflow side must NOT have recompiled (delta path taken).
    assert tpu._n_deltas > 0

    b2 = _batch(cluster, services, 160, seed=22)
    # Include the newcomer as a source against every dst in the batch.
    b2.src_ip[:32] = iputil.ip_to_u32(newcomer)
    _diff(b2, tpu.step(b2, now=60), orc.step(b2, now=60))

    # Also re-touch existing flows: denials must have been revalidated.
    _diff(b, tpu.step(b, now=61), orc.step(b, now=61), check_rules=False)


def test_noop_delta_keeps_generation_both_datapaths():
    """A refcount-only delta (re-adding an already-present member) changes
    no verdict, so NEITHER datapath bumps its generation — cached denials
    stay cached (no needless slow-path revalidation) and the differential
    harness still sees identical generations."""
    cluster, services, tpu, orc = _mk_pair()
    b = _batch(cluster, services, 160, seed=31)
    _diff(b, tpu.step(b, now=50), orc.step(b, now=50))

    ag = sorted(cluster.ps.address_groups)[0]
    from collections import Counter as _C
    counts = _C(m.ip for m in cluster.ps.address_groups[ag].members)
    present = next(ip for ip, c in counts.items() if c == 1)  # unique member
    g0t, g0o = tpu.generation, orc.generation
    g1 = tpu.apply_group_delta(ag, added_ips=[present], removed_ips=[])
    g2 = orc.apply_group_delta(ag, added_ips=[present], removed_ips=[])
    assert g1 == g0t and g2 == g0o and g1 == g2

    # Cached verdicts (incl. denials) are served from cache on both sides —
    # the handful of misses are forward entries evicted by reverse-tuple
    # inserts (slot collisions, identical on both implementations).
    ra, rb = tpu.step(b, now=60), orc.step(b, now=60)
    _diff(b, ra, rb, check_rules=False)
    assert ra.n_miss == rb.n_miss and ra.n_miss < 8

    # Dropping one of the two refcounts is still a no-op; dropping the last
    # one is a real change and bumps both.
    assert tpu.apply_group_delta(ag, [], [present]) == g1
    assert orc.apply_group_delta(ag, [], [present]) == g2
    assert tpu.apply_group_delta(ag, [], [present]) == g1 + 1
    assert orc.apply_group_delta(ag, [], [present]) == g2 + 1


def test_delta_matches_fresh_compile():
    cluster, services, tpu, _ = _mk_pair()
    ag = sorted(cluster.ps.address_groups)[1]
    atg = sorted(cluster.ps.applied_to_groups)[2]
    bitmap_before = tpu._drs.ingress.at.inc
    tpu.apply_group_delta(ag, added_ips=["10.8.8.8"], removed_ips=[])
    victim = cluster.ps.applied_to_groups[atg].members[-1].ip
    tpu.apply_group_delta(atg, added_ips=[], removed_ips=[victim])
    assert tpu._drs.ingress.at.inc is bitmap_before  # no recompile happened
    assert tpu._n_deltas > 0

    # From-scratch datapath over the mutated policy set (tpu._ps is kept in
    # sync by the delta path).
    import copy

    fresh = TpuflowDatapath(
        copy.deepcopy(tpu._ps), services,
        flow_slots=1 << 12, aff_slots=1 << 10, miss_chunk=64,
    )
    b = _batch(cluster, services, 256, seed=31)
    b.src_ip[:16] = iputil.ip_to_u32("10.8.8.8")
    ra = tpu.step(b, now=80)
    rb = fresh.step(b, now=80)
    # Fresh instance has a cold cache; compare pure classification outputs.
    assert ra.code.tolist() == rb.code.tolist()
    assert ra.dnat_ip.tolist() == rb.dnat_ip.tolist()
    assert ra.ingress_rule == rb.ingress_rule
    assert ra.egress_rule == rb.egress_rule


def test_delta_overflow_folds_into_recompile():
    cluster, services, tpu, orc = _mk_pair(delta_slots=4)
    ag = sorted(cluster.ps.address_groups)[0]
    for i in range(8):
        ip = f"10.7.7.{i + 1}"
        tpu.apply_group_delta(ag, added_ips=[ip], removed_ips=[])
        orc.apply_group_delta(ag, added_ips=[ip], removed_ips=[])
    # Overflow folded at least once; either way verdicts agree.
    b = _batch(cluster, services, 128, seed=41)
    for i in range(4):
        b.src_ip[i * 8] = iputil.ip_to_u32(f"10.7.7.{i + 1}")
    _diff(b, tpu.step(b, now=90), orc.step(b, now=90))


def test_delta_latency_beats_recompile():
    """VERDICT #5 'done' criterion: a single-member delta costs bounded host
    work + a small upload, far below a full bundle recompile."""
    # canary_probes=0: the commit plane's certification is a CONSTANT both
    # install paths share (its own latency and correctness are guarded by
    # tests/test_selfheal.py); this test guards the delta-vs-recompile
    # asymmetry, which probe classification would flatten into the noise.
    cluster, services, tpu, _ = _mk_pair(n_rules=2000, seed=5,
                                         delta_slots=512, canary_probes=0)
    ag = sorted(cluster.ps.address_groups)[0]

    t0 = time.perf_counter()
    tpu.apply_group_delta(ag, added_ips=["10.6.6.6"], removed_ips=[])
    t_delta = time.perf_counter() - t0

    import copy

    t0 = time.perf_counter()
    tpu.install_bundle(ps=copy.deepcopy(tpu._ps))
    t_bundle = time.perf_counter() - t0

    assert t_delta < t_bundle / 5, (t_delta, t_bundle)


def test_stats_parity():
    """Per-rule metric counters (IngressMetric/EgressMetric analog) must
    agree between tpuflow and the oracle datapath."""
    cluster, services, tpu, orc = _mk_pair()
    for i in range(3):
        b = _batch(cluster, services, 160, seed=50 + i)
        tpu.step(b, now=100 + i)
        orc.step(b, now=100 + i)
    sa, sb = tpu.stats(), orc.stats()
    assert sa.ingress == sb.ingress
    assert sa.egress == sb.egress
    assert sa.default_allow == sb.default_allow
    assert sa.default_deny == sb.default_deny
    total = sum(sa.ingress.values()) + sum(sa.egress.values()) + sa.default_allow + sa.default_deny
    assert total > 0


def test_trace_mode():
    """Traceflow analog: per-packet stage trace, read-only, matching the
    oracle's observations on a cold cache."""
    cluster, services, tpu, orc = _mk_pair()
    b = _batch(cluster, services, 96, seed=61)
    ta = tpu.trace(b, now=10)
    to = orc.trace(b, now=10)
    for i in range(b.size):
        assert ta[i]["cache_hit"] is False and to[i]["cache_hit"] is False
        assert ta[i]["code"] == to[i]["code"], i
        assert ta[i]["svc_idx"] == to[i]["svc_idx"], i
        assert ta[i]["dnat_ip"] == to[i]["dnat_ip"], i
        assert ta[i]["dnat_port"] == to[i]["dnat_port"], i
        assert ta[i]["ingress_rule"] == to[i]["ingress_rule"], i
        assert ta[i]["egress_rule"] == to[i]["egress_rule"], i
    # Tracing mutated nothing: a real step still sees an all-cold batch.
    ra = tpu.step(b, now=11)
    assert ra.n_miss == b.size
    # Now the trace shows the cache overlay.
    ta2 = tpu.trace(b, now=12)
    assert any(t["cache_hit"] for t in ta2)
    assert all(t["cache_hit"] for t in ta2 if t["code"] == 0) or True
