"""In-kernel ARP pipeline (round-3 verdict missing #9): ARPSpoofGuard +
ARPResponder run inside the datapath walk on BOTH engines (ref
pipeline.go:114-195 ARP tables), not host-side only."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.compiler.topology import (
    ARP_OP_REPLY,
    ARP_OP_REQUEST,
    FWD_ARP_FLOOD,
    FWD_ARP_REPLY,
    FWD_DROP_SPOOF,
    NodeRoute,
    Topology,
    arp_respond,
    mac_of_ip,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

GW = "10.10.1.1"
POD = "10.10.1.5"
POD2 = "10.10.1.6"
REMOTE_NODE = "172.18.0.9"


def _topo():
    return Topology(
        node_name="n1", gateway_ip=GW, pod_cidr="10.10.1.0/24",
        local_pods=[(POD, 3), (POD2, 4)],
        remote_nodes=[NodeRoute(name="n2", node_ip=REMOTE_NODE,
                                pod_cidr="10.10.2.0/24")],
    )


def _arp(dp, sender, target, op=ARP_OP_REQUEST, in_port=3, now=1):
    batch = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(sender)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(target)], np.uint32),
        proto=np.array([0], np.int32),
        src_port=np.array([0], np.int32),
        dst_port=np.array([0], np.int32),
        in_port=np.array([in_port], np.int32),
        arp_op=np.array([op], np.int32),
    )
    return dp.step(batch, now)


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_arp_responder_and_spoofguard(dp_cls):
    kw = {"miss_chunk": 16} if dp_cls is TpuflowDatapath else {}
    dp = dp_cls(PolicySet(), [], flow_slots=1 << 8, aff_slots=1 << 4,
                topology=_topo(), **kw)
    t = dp.datapath_type

    # Request for the gateway from the pod's own port: answered out in_port.
    r = _arp(dp, POD, GW)
    assert int(r.fwd_kind[0]) == FWD_ARP_REPLY, t
    assert int(r.out_port[0]) == 3, t
    assert int(r.punt[0]) == 0 and int(r.code[0]) == 0, t

    # Remote node IPs and local pods are answerable too.
    assert int(_arp(dp, POD, REMOTE_NODE, now=2).fwd_kind[0]) == FWD_ARP_REPLY, t
    assert int(_arp(dp, POD, POD2, now=3).fwd_kind[0]) == FWD_ARP_REPLY, t

    # Unknown target: flood (OFPP_NORMAL), no reply port.
    r = _arp(dp, POD, "10.10.1.99", now=4)
    assert int(r.fwd_kind[0]) == FWD_ARP_FLOOD and int(r.out_port[0]) == -1, t

    # Reply opcode is never answered by the responder.
    assert int(_arp(dp, POD, GW, op=ARP_OP_REPLY, now=5).fwd_kind[0]) \
        == FWD_ARP_FLOOD, t

    # ARPSpoofGuard: sender IP not bound to the ingress port -> drop.
    r = _arp(dp, POD2, GW, in_port=3, now=6)
    assert int(r.fwd_kind[0]) == FWD_DROP_SPOOF, t
    assert int(r.spoofed[0]) == 1, t

    # ARP lanes touch no conntrack state.
    assert dp.cache_stats()["occupied"] == 0, t


def test_arp_mac_resolution_matches_spec():
    """The reply MAC comes from the deterministic scheme both datapaths and
    restarted agents share (mac_of_ip); arp_respond is the host surface."""
    topo = _topo()
    assert arp_respond(topo, GW) == mac_of_ip(GW)
    assert arp_respond(topo, REMOTE_NODE) == mac_of_ip(REMOTE_NODE)
    assert arp_respond(topo, "10.10.1.99") is None
