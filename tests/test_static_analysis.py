"""Unified static-analysis plane (ISSUE 14 tentpole): one AST engine
(antrea_tpu/analysis/), the nine migrated drift gates, the semantic
passes, and the baseline discipline.

Tier-1 invokes the FULL pass suite exactly ONCE here — the nine
scattered per-test subprocess invocations (test_profile/test_selfheal/
test_mesh_datapath/...) were retired with the migration; the legacy
tools/check_*.py CLIs remain as thin shims whose verdict parity with
the pass-based engine is pinned below, clean tree AND synthetically
broken tree per tool.

Each of the semantic passes additionally proves it FIRES on a
seeded violation (a minimal synthetic tree carrying exactly the bug
class the pass pins), so a future refactor that silently lobotomizes a
pass fails here, not in review."""

import json
import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from antrea_tpu.analysis import PASSES, run  # noqa: E402

ALL_PASSES = (
    "mesh", "metrics", "phases", "events", "commit-plane", "audit-plane",
    "maintenance", "reshard", "tenant",
    "thread-safety", "bounded-cache", "jit-purity", "donation-safety",
    "bounded-buffer", "telemetry-registry", "canonical-shape",
)


def _shim(tool: str, root: Path) -> int:
    """Run a legacy tools/check_*.py CLI shim against `root` -> exit."""
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / f"{tool}.py"),
         "--root", str(root)],
        capture_output=True, text=True).returncode


# ---------------------------------------------------------------------------
# The ONE tier-1 invocation of the whole suite (acceptance: analyze.py
# exits 0 on HEAD; all passes registered; --json machine-readable).
# ---------------------------------------------------------------------------

def test_full_suite_clean_on_head_one_invocation():
    assert tuple(PASSES) == ALL_PASSES
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "analyze.py"), "--json"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["clean"] is True
    assert tuple(report["passes"]) == ALL_PASSES
    # Machine-readable rows: every finding (there are none unsuppressed
    # on HEAD) carries pass/path/line/obj/reason/key/suppressed.
    for row in report["findings"]:
        assert set(row) == {"pass", "path", "line", "obj", "reason", "key",
                            "suppressed"}
        assert row["suppressed"] is True


# ---------------------------------------------------------------------------
# Migration parity: per legacy tool, the shim CLI's exit code matches
# the engine pass verdict on the clean tree AND on a synthetically
# broken one.
# ---------------------------------------------------------------------------

def _mutate_mesh(t: Path):
    p = t / "antrea_tpu" / "models" / "pipeline.py"
    txt = p.read_text()
    new = txt.replace("class FlowCache(NamedTuple):\n",
                      "class FlowCache(NamedTuple):\n"
                      "    bogus_unspecced_field: int\n", 1)
    assert new != txt
    p.write_text(new)


def _mutate_metrics(t: Path):
    p = t / "antrea_tpu" / "observability" / "flowexport.py"
    p.write_text(p.read_text()
                 + '\n_SEEDED = "antrea_tpu_bogus_unregistered_total"\n')


def _mutate_phases(t: Path):
    p = t / "antrea_tpu" / "models" / "pipeline.py"
    p.write_text(p.read_text() + "\nPH_BOGUS_SEEDED = 1 << 29\n")


def _mutate_events(t: Path):
    p = t / "antrea_tpu" / "observability" / "flightrec.py"
    p.write_text(p.read_text()
                 + '\n\ndef _seeded_violation(rec):\n'
                   '    rec.emit(kind="not-a-declared-kind")\n')


def _mutate_commit(t: Path):
    p = t / "antrea_tpu" / "datapath" / "tpuflow.py"
    p.write_text(p.read_text()
                 + "\n\ndef install_bundle(self):\n    pass\n")


def _mutate_audit(t: Path):
    p = t / "antrea_tpu" / "datapath" / "audit.py"
    txt = p.read_text()
    new = txt.replace('"drs": "rule",', '"drs": "bogus",', 1)
    assert new != txt
    p.write_text(new)


def _mutate_maintenance(t: Path):
    p = t / "antrea_tpu" / "datapath" / "audit.py"
    p.write_text(p.read_text()
                 + "\n\ndef _rogue_loop(dp):\n"
                   "    return dp.canary_scan(0)\n")


def _mutate_reshard(t: Path):
    p = t / "antrea_tpu" / "parallel" / "reshard.py"
    txt = p.read_text()
    new = txt.replace('"FlowCache.keys"', '"BogusCache.keys"', 1)
    assert new != txt
    p.write_text(new)


def _mutate_tenant(t: Path):
    p = t / "antrea_tpu" / "datapath" / "tenancy.py"
    p.write_text(p.read_text()
                 + "\n\ndef _rogue_shard(mesh, tuples):\n"
                   "    return mesh.shard_of_tuples(tuples)\n")


LEGACY = [
    ("check_mesh", "mesh", _mutate_mesh),
    ("check_metrics", "metrics", _mutate_metrics),
    ("check_phases", "phases", _mutate_phases),
    ("check_events", "events", _mutate_events),
    ("check_commit_plane", "commit-plane", _mutate_commit),
    ("check_audit_plane", "audit-plane", _mutate_audit),
    ("check_maintenance", "maintenance", _mutate_maintenance),
    ("check_reshard", "reshard", _mutate_reshard),
    ("check_tenant", "tenant", _mutate_tenant),
]


@pytest.fixture(scope="module")
def tree_template(tmp_path_factory):
    """A copy of everything the passes read: the package sources plus
    the repo-root surfaces (README, bench_profile, baseline)."""
    base = tmp_path_factory.mktemp("analysis") / "template"
    (base / "antrea_tpu").mkdir(parents=True)
    for src in (REPO / "antrea_tpu").rglob("*.py"):
        rel = src.relative_to(REPO)
        dst = base / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(src, dst)
    for name in ("README.md", "bench_profile.py", "BASELINE.analysis.json"):
        shutil.copy(REPO / name, base / name)
    return base


@pytest.mark.parametrize("tool,pass_id,mutate",
                         LEGACY, ids=[t for t, _p, _m in LEGACY])
def test_legacy_tool_verdict_parity(tool, pass_id, mutate, tree_template,
                                    tmp_path):
    # Clean tree: both verdicts green.
    clean = run(tree_template, [pass_id])
    assert clean.clean, [f.render() for f in clean.findings] + clean.errors
    assert _shim(tool, tree_template) == 0
    # Synthetically broken tree: both verdicts red.
    broken = tmp_path / "broken"
    shutil.copytree(tree_template, broken)
    mutate(broken)
    res = run(broken, [pass_id])
    assert not res.clean, f"{pass_id} missed the seeded breakage"
    assert _shim(tool, broken) == 1


# ---------------------------------------------------------------------------
# Seeded violations: each NEW semantic pass fires on the bug class it
# pins (and stays quiet on the adjacent legal shape).
# ---------------------------------------------------------------------------

def _mini_tree(tmp_path: Path, files: dict) -> Path:
    root = tmp_path / "mini"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return root


def test_thread_safety_pass_fires_on_seeded_violations(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/agent/apiserver.py": (
            'HANDLER_SAFE = ("good_stats", "tickle")\n\n\n'
            "class AgentApiServer:\n"
            "    def _json_route(self, route, q):\n"
            "        self._dp.good_stats()\n"
            "        return self._dp.evil_poke()\n"
        ),
        "antrea_tpu/datapath/fake.py": (
            "class FakeDp:\n"
            "    def good_stats(self):\n"
            "        with self._world_ctx(1):\n"
            "            return {}\n\n"
            "    def tickle(self):\n"
            "        self.hits = 1\n"
            "        return 0\n\n"
            "    def unrelated(self):\n"
            "        self.fine = 2  # not handler-declared: no finding\n"
        ),
    })
    objs = {f.obj for f in run(root, ["thread-safety"]).findings}
    assert "undeclared:evil_poke" in objs
    assert "world-ctx:FakeDp.good_stats" in objs
    assert "mutates:FakeDp.tickle:hits" in objs
    assert not any("unrelated" in o for o in objs)


def test_bounded_cache_pass_fires_on_seeded_violations(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/x.py": (
            "from functools import cache, lru_cache\n"
            "import jax\n\n\n"
            "@lru_cache(maxsize=None)\n"
            "def leaky(n):\n"
            "    return jax.jit(lambda x: x + n)\n\n\n"
            "@cache\n"
            "def leaky2():\n"
            "    return jax.jit(lambda x: x)\n\n\n"
            "@lru_cache\n"
            "def leaky3(n):\n"
            "    return jax.vmap(lambda x: x * n)\n\n\n"
            "@lru_cache(maxsize=32)\n"
            "def bounded(n):\n"
            "    return jax.jit(lambda x: x * n)\n\n\n"
            "@lru_cache(maxsize=None)\n"
            "def host_data(n):\n"
            "    return list(range(n))\n"
        ),
    })
    objs = {f.obj for f in run(root, ["bounded-cache"]).findings}
    assert objs == {"x.py:leaky", "x.py:leaky2", "x.py:leaky3"}


def test_canonical_shape_pass_fires_on_seeded_violations(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/datapath/bad.py": (
            "from .tenancy import _sub_batch\n\n\n"
            "class Dp:\n"
            "    def step_groups(self, tids, batch, now):\n"
            "        for tid in set(tids):\n"
            "            sub = _sub_batch(batch, [0])\n"
            "            self.step(sub, now)  # tainted name\n"
            "        return self.tenant_step(1, _sub_batch(batch, [1]),\n"
            "                                now)  # inline\n\n"
            "    def staged(self, batch, now):\n"
            "        # The sanctioned pattern: subsets go INTO the\n"
            "        # batcher, which dispatches canonical shapes.\n"
            "        t = self.batcher.submit(_sub_batch(batch, [0]), now)\n"
            "        self.batcher.flush_all(now)\n"
            "        return self.step(batch, now)\n"
        ),
    })
    objs = {f.obj for f in run(root, ["canonical-shape"]).findings}
    assert objs == {"datapath/bad.py:step_groups:step",
                    "datapath/bad.py:step_groups:tenant_step"}


def test_jit_purity_pass_fires_on_seeded_violations(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/y.py": (
            "import jax\n\n\n"
            "def _step(state, x, meta):\n"
            "    n = int(x)  # tracer coercion\n"
            "    return x\n\n\n"
            "step = jax.jit(_step, static_argnames=('meta',))\n\n\n"
            "def _ok(a, meta):\n"
            "    k = int(meta.chunk)  # static arg: exempt\n"
            "    return a\n\n\n"
            "ok = jax.jit(_ok, static_argnames=('meta',))\n\n\n"
            "def _sync(a):\n"
            "    return a.sum().item()\n\n\n"
            "sync = jax.jit(_sync)\n\n\n"
            "class C:\n"
            "    @jax.jit\n"
            "    def m(self, x):\n"
            "        self.cached = x\n"
            "        return x\n\n\n"
            "def host(a):\n"
            "    return int(a)  # not jitted: no finding\n"
        ),
    })
    objs = {f.obj for f in run(root, ["jit-purity"]).findings}
    assert any(o.startswith("y.py:_step:int") for o in objs)
    assert any(o.startswith("y.py:_sync:item") for o in objs)
    assert "y.py:m:self.cached" in objs
    assert not any("_ok" in o or "host" in o for o in objs)


def test_donation_safety_pass_fires_on_seeded_violation(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/z.py": (
            "import jax\n\n\n"
            "def _f(s, x):\n"
            "    return s\n\n\n"
            "f_don = jax.jit(_f, donate_argnums=(0,))\n\n\n"
            "class Eng:\n"
            "    def caller_bad(self):\n"
            "        out = f_don(self._state, 1)\n"
            "        return self._state.sum()  # read of donated buffers\n\n"
            "    def caller_ok(self):\n"
            "        out = f_don(self._state, 1)\n"
            "        self._state = out  # rebind kills the taint\n"
            "        return self._state.sum()\n\n"
            "    def caller_alias(self):\n"
            "        fn = f_don if True else _f\n"
            "        out = fn(self._state, 1)\n"
            "        return self._state.sum()  # alias tracked too\n\n"
            "    def caller_loop_bad(self, blocks):\n"
            "        acc = 0\n"
            "        for b in blocks:\n"
            "            acc += self._state.rows  # rereads next iter\n"
            "            out = f_don(self._state, b)\n"
            "        return acc\n\n"
            "    def caller_loop_ok(self, blocks):\n"
            "        for b in blocks:\n"
            "            out = f_don(self._state, b)\n"
            "            self._state = out  # rebind each iteration\n"
            "        return self._state.rows\n\n"
            "    def caller_same_line(self):\n"
            "        return f_don(self._state, 1), self._state.rows\n"
        ),
    })
    objs = {f.obj for f in run(root, ["donation-safety"]).findings}
    assert any(o.startswith("z.py:caller_bad:self._state") for o in objs)
    assert any(o.startswith("z.py:caller_alias:self._state") for o in objs)
    # Execution-order discipline: a dispatch inside a loop wraps around
    # (the body's earlier read runs again AFTER it), a same-iteration
    # rebind kills the taint, and a same-LINE read after the call counts.
    assert "z.py:caller_loop_bad:self._state" in objs
    assert "z.py:caller_same_line:self._state" in objs
    assert not any("caller_loop_ok" in o for o in objs)
    assert not any("caller_ok" in o for o in objs)


def test_bounded_buffer_pass_fires_on_seeded_violations(tmp_path):
    root = _mini_tree(tmp_path, {
        "antrea_tpu/dissemination/wild.py": (
            "from collections import deque\n\n"
            'BUFFER_CAPS = {\n'
            '    "W.good_queue": "bounded at max_pending",\n'
            '    "W.ghost_buf": "names a buffer nobody assigns",\n'
            "}\n\n\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self.good_queue = deque()  # declared: no finding\n"
            "        self.evil_backlog = []  # undeclared buffer\n"
            "        self._rdbuf: bytes = b''  # AnnAssign form, undeclared\n"
            "        self.count = 0  # not buffer-shaped: no finding\n"
        ),
        # Buffers OUTSIDE dissemination/ are out of scope for this pass.
        "antrea_tpu/datapath/elsewhere.py": (
            "class E:\n"
            "    def __init__(self):\n"
            "        self.free_queue = []\n"
        ),
        # The failover plane is a SINGLE-FILE scan entry (its probe-
        # history ring sits between an every-tick producer and a
        # maybe-never supportbundle consumer): a declared ring passes,
        # an undeclared buffer beside it fires.
        "antrea_tpu/parallel/failover.py": (
            "from collections import deque\n\n"
            'BUFFER_CAPS = {\n'
            '    "FailoverPlane.probe_ring": "deque(maxlen=PROBE_RING)",\n'
            "}\n\n\n"
            "class FailoverPlane:\n"
            "    def __init__(self):\n"
            "        self.probe_ring = deque(maxlen=64)\n"
            "        self.sneaky_backlog = []  # undeclared buffer\n"
        ),
        # Sibling parallel/ modules stay OUT of scope: the entry names
        # one file, not the package.
        "antrea_tpu/parallel/meshpath.py": (
            "class M:\n"
            "    def __init__(self):\n"
            "        self.replica_queue = []\n"
        ),
    })
    objs = {f.obj for f in run(root, ["bounded-buffer"]).findings}
    assert "dissemination/wild.py:W.evil_backlog" in objs
    assert "dissemination/wild.py:W._rdbuf" in objs
    # Stale declarations are findings too: a cap row cannot outlive the
    # buffer it excuses.
    assert "dissemination/wild.py:W.ghost_buf:stale" in objs
    assert "parallel/failover.py:FailoverPlane.sneaky_backlog" in objs
    assert not any("good_queue" in o for o in objs)
    assert not any("probe_ring" in o for o in objs)
    assert not any("count" in o for o in objs)
    assert not any("elsewhere" in o for o in objs)
    assert not any("meshpath" in o for o in objs)


def test_telemetry_registry_pass_fires_on_seeded_violations(tree_template,
                                                            tmp_path):
    # Clean on the real tree (the tier-1 full-suite test pins this too;
    # here it anchors the seeded deltas below).
    clean = run(tree_template, ["telemetry-registry"])
    assert clean.clean, [f.render() for f in clean.findings] + clean.errors

    # A kernel counter output nobody declared: the plane would silently
    # drop it on account().
    broken = tmp_path / "undeclared"
    shutil.copytree(tree_template, broken)
    p = broken / "antrea_tpu" / "models" / "pipeline.py"
    p.write_text(p.read_text()
                 + '\n\ndef _seeded(out):\n'
                   '    out["tel_bogus_counter"] = 0\n')
    objs = {f.obj for f in run(broken, ["telemetry-registry"]).findings}
    assert "undeclared:bogus_counter" in objs

    # A declared counter with no kernel emit site, no metric family row
    # and no README row: dead accumulator across every layer.
    broken2 = tmp_path / "unmeasured"
    shutil.copytree(tree_template, broken2)
    t = broken2 / "antrea_tpu" / "observability" / "telemetry.py"
    txt = t.read_text()
    new = txt.replace('    "dma_hb",', '    "dma_hb",\n    "ghost_total",', 1)
    assert new != txt
    t.write_text(new)
    objs2 = {f.obj for f in run(broken2, ["telemetry-registry"]).findings}
    assert {"unmeasured:ghost_total", "family-unmapped:ghost_total",
            "undocumented:ghost_total"} <= objs2

    # A regime dropped from the README table is drift, not a doc nit.
    broken3 = tmp_path / "undocumented-regime"
    shutil.copytree(tree_template, broken3)
    r = broken3 / "README.md"
    rt = r.read_text()
    new = rt.replace("| `attack-shed` |", "| attack shed |")
    assert new != rt
    r.write_text(new)
    objs3 = {f.obj for f in run(broken3, ["telemetry-registry"]).findings}
    assert "regime-undocumented:attack-shed" in objs3


def test_reshard_world_migration_fires_on_seeded_violation(tree_template,
                                                           tmp_path):
    """The PR 20 tenant extension of the reshard pass: a NEW
    _TENANT_WORLD_FIELDS member of the mesh engine assigned from a
    sharded-state builder but absent from reshard.WORLD_MIGRATION is
    flow loss for EVERY tenant at once — the pass must fire on it (and
    on a stale rule naming no such field), and stay clean at HEAD."""
    clean = run(tree_template, ["reshard"])
    assert clean.clean, [f.render() for f in clean.findings] + clean.errors

    # A sharded per-world field nobody taught the per-world migrator.
    broken = tmp_path / "unmigrated-world"
    shutil.copytree(tree_template, broken)
    p = broken / "antrea_tpu" / "parallel" / "meshpath.py"
    txt = p.read_text()
    new = txt.replace('        "_fo_mask",\n',
                      '        "_fo_mask", "_shadow_state",\n', 1)
    assert new != txt
    p.write_text(new + "\n\ndef _seeded(self, st):\n"
                       "    self._shadow_state = self._pin_state(st)\n")
    objs = {f.obj for f in run(broken, ["reshard"]).findings}
    assert "unmigrated-world:_shadow_state" in objs

    # A WORLD_MIGRATION rule whose field no longer exists: stale rule.
    broken2 = tmp_path / "stale-world"
    shutil.copytree(tree_template, broken2)
    r = broken2 / "antrea_tpu" / "parallel" / "reshard.py"
    txt = r.read_text()
    new = txt.replace('WORLD_MIGRATION = {\n',
                      'WORLD_MIGRATION = {\n'
                      '    "_ghost_state": "row-migrate a field that '
                      'no longer exists",\n', 1)
    assert new != txt
    r.write_text(new)
    objs2 = {f.obj for f in run(broken2, ["reshard"]).findings}
    assert "stale-world:_ghost_state" in objs2


# ---------------------------------------------------------------------------
# Baseline discipline: suppression works, staleness fails the build.
# ---------------------------------------------------------------------------

def _leaky_tree(tmp_path: Path) -> Path:
    return _mini_tree(tmp_path, {
        "antrea_tpu/x.py": (
            "from functools import lru_cache\n"
            "import jax\n\n\n"
            "@lru_cache(maxsize=None)\n"
            "def leaky(n):\n"
            "    return jax.jit(lambda x: x + n)\n"
        ),
    })


def test_baseline_suppresses_by_key_and_fails_when_stale(tmp_path):
    root = _leaky_tree(tmp_path)
    [finding] = run(root, ["bounded-cache"]).findings
    # A baselined finding is suppressed (run goes clean, row reported).
    (root / "BASELINE.analysis.json").write_text(json.dumps(
        {"findings": {finding.key: "known leak, tracked in ISSUE-XX"}}))
    res = run(root, ["bounded-cache"])
    assert res.clean and [s.key for s in res.suppressed] == [finding.key]
    # A stale row (nothing fires for it any more) fails the build.
    (root / "antrea_tpu" / "x.py").write_text("X = 1\n")
    res2 = run(root, ["bounded-cache"])
    assert not res2.clean
    assert any("stale" in e for e in res2.errors), res2.errors
    # A reasonless row is rejected outright.
    (root / "BASELINE.analysis.json").write_text(json.dumps(
        {"findings": {finding.key: ""}}))
    assert any("no reason" in e for e in run(root, ["bounded-cache"]).errors)


def test_runner_rejects_unknown_pass():
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "analyze.py"),
         "--pass", "no-such-pass"],
        capture_output=True, text=True)
    assert proc.returncode == 2
    assert "no-such-pass" in proc.stderr


def test_every_pass_declares_an_invariant():
    for pid, (_fn, invariant) in PASSES.items():
        assert isinstance(invariant, str) and invariant.strip(), pid
    # Finding keys are stable identities: pass:path:obj.
    from antrea_tpu.analysis import Finding

    f = Finding("mesh", "a/b.py", 3, "why", obj="Cls.field")
    assert f.key == "mesh:a/b.py:Cls.field"
    assert re.match(r"DRIFT\[mesh\] a/b\.py:3: why", f.render())
