"""Forwarding-plane tests: SpoofGuard, L2/L3 forwarding, TrafficControl,
L3DecTTL, ARP responder, node-route controller — semantics from the
reference's table inventory (pkg/agent/openflow/pipeline.go SpoofGuard /
L2ForwardingCalc / L3Forwarding / TrafficControl / L3DecTTL / Output) and
the noderoute controller (pkg/agent/controller/noderoute).

The differential discipline matches tests/test_datapath.py: everything
drives the Datapath boundary and diffs tpuflow against the oracle.
"""

import copy

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.compiler.topology import (
    FWD_DROP_SPOOF,
    FWD_DROP_UNKNOWN,
    FWD_GATEWAY,
    FWD_LOCAL,
    FWD_TUNNEL,
    OFPORT_GATEWAY,
    OFPORT_TUNNEL,
    TC_MIRROR,
    TC_NONE,
    TC_REDIRECT,
    NodeRoute,
    Topology,
    TrafficControlRule,
    arp_respond,
    compile_topology,
    mac_of_ip,
)
from antrea_tpu.agent.noderoute import NodeRouteController
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.simulator.genservice import gen_services
from antrea_tpu.utils import ip as iputil


def _topo(tc_rules=()):
    """A 3-node world as seen from node-a: pods 10.10.0.0/24 local (ofports
    3/4/5), nodes b/c remote."""
    return Topology(
        node_name="node-a",
        gateway_ip="10.10.0.1",
        pod_cidr="10.10.0.0/24",
        local_pods=[("10.10.0.5", 3), ("10.10.0.6", 4), ("10.10.0.7", 5)],
        remote_nodes=[
            NodeRoute(name="node-b", node_ip="192.168.1.2", pod_cidr="10.10.1.0/24"),
            NodeRoute(name="node-c", node_ip="192.168.1.3", pod_cidr="10.10.2.0/24"),
        ],
        tc_rules=list(tc_rules),
    )


def _batch(rows):
    """rows: [(src, dst, in_port)] -> TCP/80 PacketBatch."""
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(s) for s, _, _ in rows], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(d) for _, d, _ in rows], np.uint32),
        proto=np.full(len(rows), 6, np.int32),
        src_port=np.full(len(rows), 40000, np.int32),
        dst_port=np.full(len(rows), 80, np.int32),
        in_port=np.array([p for _, _, p in rows], np.int32),
    )


def _pair(topo, ps=None, services=None):
    tpu = TpuflowDatapath(
        copy.deepcopy(ps), services, flow_slots=1 << 12, aff_slots=1 << 10,
        miss_chunk=64, topology=topo,
    )
    orc = OracleDatapath(
        copy.deepcopy(ps), services, flow_slots=1 << 12, aff_slots=1 << 10,
        topology=topo,
    )
    return tpu, orc


def _diff_fwd(a, b):
    for f in ("code", "spoofed", "fwd_kind", "out_port", "peer_ip",
              "dec_ttl", "tc_act", "tc_port", "est", "committed"):
        av, bv = getattr(a, f), getattr(b, f)
        assert av.tolist() == bv.tolist(), f
    assert a.n_miss == b.n_miss


# ---- forwarding kinds -------------------------------------------------------


def test_forward_kinds_and_ports():
    tpu, orc = _pair(_topo())
    rows = [
        ("10.10.0.5", "10.10.0.6", 3),   # pod->pod local
        ("10.10.0.5", "10.10.1.9", 3),   # pod->remote node-b
        ("10.10.0.6", "10.10.2.20", 4),  # pod->remote node-c
        ("10.10.0.5", "8.8.8.8", 3),     # pod->external via gateway
        ("10.10.0.5", "10.10.0.99", 3),  # local CIDR, no such pod
        ("10.10.1.9", "10.10.0.5", OFPORT_TUNNEL),  # tunnel ingress -> local
    ]
    b = _batch(rows)
    ra, rb = tpu.step(b, now=100), orc.step(b, now=100)
    _diff_fwd(ra, rb)
    assert ra.fwd_kind.tolist() == [
        FWD_LOCAL, FWD_TUNNEL, FWD_TUNNEL, FWD_GATEWAY,
        FWD_DROP_UNKNOWN, FWD_LOCAL,
    ]
    assert ra.out_port.tolist() == [4, OFPORT_TUNNEL, OFPORT_TUNNEL,
                                    OFPORT_GATEWAY, -1, 3]
    assert ra.peer_ip.tolist() == [
        0, iputil.ip_to_u32("192.168.1.2"), iputil.ip_to_u32("192.168.1.3"),
        0, 0, 0,
    ]
    # L3DecTTL: routed legs only — intra-node pod->pod keeps its TTL;
    # tunnel/gateway egress and routed local delivery decrement.
    assert ra.dec_ttl.tolist() == [0, 1, 1, 1, 0, 1]


def test_empty_topology_routes_to_gateway():
    tpu, orc = _pair(Topology())
    b = _batch([("1.2.3.4", "5.6.7.8", -1)])
    ra, rb = tpu.step(b, now=1), orc.step(b, now=1)
    _diff_fwd(ra, rb)
    assert ra.fwd_kind.tolist() == [FWD_GATEWAY]
    assert ra.out_port.tolist() == [OFPORT_GATEWAY]


# ---- SpoofGuard -------------------------------------------------------------


def test_spoofguard_drops_wrong_source():
    tpu, orc = _pair(_topo())
    rows = [
        ("10.10.0.5", "10.10.0.6", 3),   # correct binding
        ("10.10.0.6", "10.10.0.7", 3),   # pod 3 spoofing pod 4's address
        ("9.9.9.9", "10.10.0.6", 4),     # unknown source from a pod port
        ("9.9.9.9", "10.10.0.6", OFPORT_TUNNEL),  # tunnel ingress: exempt
        ("10.10.0.5", "10.10.0.6", 77),  # unknown pod port: nothing legit
    ]
    b = _batch(rows)
    ra, rb = tpu.step(b, now=5), orc.step(b, now=5)
    _diff_fwd(ra, rb)
    assert ra.spoofed.tolist() == [0, 1, 1, 0, 1]
    assert ra.fwd_kind.tolist()[1] == FWD_DROP_SPOOF
    assert ra.code.tolist()[1] == 1  # dropped
    assert ra.out_port.tolist()[1] == -1


def test_spoofed_packet_commits_no_state():
    """SpoofGuard sits before conntrack (framework.go stage order): a
    spoofed packet must not create an established entry that would later
    bypass a deny for the same tuple."""
    from antrea_tpu.compiler.ir import PolicySet

    tpu, orc = _pair(_topo(), ps=PolicySet())
    spoofed = _batch([("10.10.0.6", "10.10.0.7", 3)])  # wrong port binding
    ra = tpu.step(spoofed, now=10)
    rb = orc.step(spoofed, now=10)
    _diff_fwd(ra, rb)
    assert tpu.cache_stats()["occupied"] == 0
    assert orc.cache_stats()["occupied"] == 0
    # The same tuple from the RIGHT port (4) classifies fresh — not est.
    legit = _batch([("10.10.0.6", "10.10.0.7", 4)])
    ra2, rb2 = tpu.step(legit, now=11), orc.step(legit, now=11)
    _diff_fwd(ra2, rb2)
    assert ra2.est.tolist() == [0]
    assert ra2.committed.tolist() == [1]


# ---- TrafficControl ---------------------------------------------------------


def test_trafficcontrol_mirror_and_redirect():
    tc = [
        TrafficControlRule(name="mirror-7", pod_ips=("10.10.0.7",),
                           action=TC_MIRROR, target_port=99, direction="ingress"),
        TrafficControlRule(name="redirect-5", pod_ips=("10.10.0.5",),
                           action=TC_REDIRECT, target_port=88, direction="egress"),
    ]
    tpu, orc = _pair(_topo(tc))
    rows = [
        ("10.10.0.6", "10.10.0.7", 4),  # to mirrored pod: mirror, port kept
        ("10.10.0.5", "10.10.1.9", 3),  # from redirected pod: output -> 88
        ("10.10.0.6", "10.10.1.9", 4),  # unaffected
    ]
    b = _batch(rows)
    ra, rb = tpu.step(b, now=20), orc.step(b, now=20)
    _diff_fwd(ra, rb)
    assert ra.tc_act.tolist() == [TC_MIRROR, TC_REDIRECT, TC_NONE]
    assert ra.out_port.tolist() == [5, 88, OFPORT_TUNNEL]
    assert ra.tc_port.tolist() == [99, 88, 0]


# ---- service DNAT + forwarding composition ---------------------------------


def test_service_dnat_forwards_to_endpoint_and_reply_to_client():
    """A ClusterIP flow DNATs to an endpoint and forwards toward IT (local
    or tunnel); the reply leg forwards toward the CLIENT, not the un-DNAT
    frontend (UnSNAT restores the source only)."""
    svc = ServiceEntry(
        cluster_ip="10.96.0.10", port=80, protocol=6,
        endpoints=[Endpoint(ip="10.10.1.9", port=8080, node="node-b")],
        name="web", namespace="default",
    )
    tpu, orc = _pair(_topo(), services=[svc])
    fwd = _batch([("10.10.0.5", "10.96.0.10", 3)])
    ra, rb = tpu.step(fwd, now=30), orc.step(fwd, now=30)
    _diff_fwd(ra, rb)
    # DNAT to the node-b endpoint -> tunnel to node-b.
    assert ra.fwd_kind.tolist() == [FWD_TUNNEL]
    assert ra.peer_ip.tolist() == [iputil.ip_to_u32("192.168.1.2")]
    # Reply: endpoint -> client, entering via the tunnel.
    reply = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32("10.10.1.9")], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32("10.10.0.5")], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([8080], np.int32),
        dst_port=np.array([40000], np.int32),
        in_port=np.array([OFPORT_TUNNEL], np.int32),
    )
    ra2, rb2 = tpu.step(reply, now=31), orc.step(reply, now=31)
    _diff_fwd(ra2, rb2)
    assert ra2.reply.tolist() == [1]
    # Un-DNAT source rewrite reported in dnat fields; forwarding goes to
    # the client pod locally.
    assert ra2.dnat_ip.tolist() == [iputil.ip_to_u32("10.96.0.10")]
    assert ra2.fwd_kind.tolist() == [FWD_LOCAL]
    assert ra2.out_port.tolist() == [3]
    assert ra2.dec_ttl.tolist() == [1]  # arrived via tunnel: routed leg


# ---- randomized differential ------------------------------------------------


def test_forwarding_parity_random():
    """Random policy/service/topology world; every packet gets a random
    in_port (pod/tunnel/gateway/unset) — full StepResult parity."""
    rng = np.random.default_rng(11)
    cluster = gen_cluster(150, n_nodes=4, pods_per_node=8, seed=9)
    services = gen_services(10, cluster.pod_ips, seed=10)
    # Build a topology over the cluster's pods: node 0 is "us".
    pod_ips = [iputil.u32_to_ip(u) for u in cluster.pod_ips]
    local = pod_ips[:8]
    topo = Topology(
        node_name="node-0",
        gateway_ip="10.0.0.1",
        pod_cidr="10.0.0.0/26",
        local_pods=[(ip, 3 + i) for i, ip in enumerate(local)],
        remote_nodes=[
            NodeRoute(name=f"node-{k}", node_ip=f"192.168.0.{k+1}",
                      pod_cidr=f"10.0.{k}.0/26")
            for k in range(1, 4)
        ],
        tc_rules=[TrafficControlRule(
            name="mirror-0", pod_ips=(local[0],), action=TC_MIRROR,
            target_port=200, direction="both",
        )],
    )
    # gen_cluster pods may not align with /26 splits; rebuild ranges from
    # actual pod ips per node instead if needed — keep packets synthetic.
    tpu, orc = _pair(topo, ps=cluster.ps, services=services)
    tr = gen_traffic(cluster.pod_ips, 256, n_flows=96, seed=12,
                     services=services, svc_fraction=0.3)
    ports = rng.choice(
        np.array([-1, OFPORT_TUNNEL, OFPORT_GATEWAY, 3, 4, 5, 6], np.int32),
        size=256,
    )
    for t in range(4):
        b = PacketBatch(
            src_ip=tr.src_ip, dst_ip=tr.dst_ip, proto=tr.proto,
            src_port=tr.src_port, dst_port=tr.dst_port, in_port=ports,
        )
        ra, rb = tpu.step(b, now=40 + t), orc.step(b, now=40 + t)
        _diff_fwd(ra, rb)


# ---- compile-time validation ------------------------------------------------


def test_compile_rejects_bad_topologies():
    with pytest.raises(ValueError):
        compile_topology(Topology(local_pods=[("10.0.0.5", 3), ("10.0.0.5", 4)]))
    with pytest.raises(ValueError):
        compile_topology(Topology(local_pods=[("10.0.0.5", 3), ("10.0.0.6", 3)]))
    with pytest.raises(ValueError):
        compile_topology(Topology(local_pods=[("10.0.0.5", OFPORT_TUNNEL)]))
    with pytest.raises(ValueError):
        compile_topology(Topology(remote_nodes=[
            NodeRoute("b", "1.1.1.1", "10.0.0.0/24"),
            NodeRoute("c", "1.1.1.2", "10.0.0.128/25"),
        ]))


# ---- ARP responder / MACs ---------------------------------------------------


def test_arp_responder():
    t = _topo()
    assert arp_respond(t, "10.10.0.1") == mac_of_ip("10.10.0.1")  # gateway
    assert arp_respond(t, "10.10.0.5") == mac_of_ip("10.10.0.5")  # local pod
    assert arp_respond(t, "192.168.1.2") is not None  # remote node
    assert arp_respond(t, "8.8.8.8") is None  # not ours
    assert mac_of_ip("10.10.0.5") == "0a:00:0a:0a:00:05"


# ---- node-route controller --------------------------------------------------


def test_noderoute_controller_reconciles():
    tpu = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    ctl = NodeRouteController(tpu, "node-a", pod_cidr="10.10.0.0/24",
                              gateway_ip="10.10.0.1")
    ctl.pod_added("10.10.0.5", 3)
    ctl.upsert_node("node-b", "192.168.1.2", "10.10.1.0/24")
    ctl.upsert_node("node-a", "192.168.1.1", "10.10.0.0/24")  # self: ignored
    b = _batch([("10.10.0.5", "10.10.1.9", 3)])
    r = tpu.step(b, now=1)
    assert r.fwd_kind.tolist() == [FWD_TUNNEL]
    assert r.peer_ip.tolist() == [iputil.ip_to_u32("192.168.1.2")]
    # Node deletion: the route disappears, dst falls back to gateway.
    ctl.delete_node("node-b")
    r2 = tpu.step(b, now=2)
    assert r2.fwd_kind.tolist() == [FWD_GATEWAY]
    # Pod deletion: local delivery stops.
    ctl.pod_deleted("10.10.0.5")
    b2 = _batch([("10.10.1.9", "10.10.0.5", OFPORT_TUNNEL)])
    r3 = tpu.step(b2, now=3)
    assert r3.fwd_kind.tolist() == [FWD_DROP_UNKNOWN]


def test_noderoute_syncs_from_interface_store(tmp_path):
    """CNI-created interfaces feed the topology; a restarted controller
    rebuilds local-pod forwarding from the persisted interface store
    (agent.go:279 restart model)."""
    from antrea_tpu.agent.cni import CniServer
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    cni = CniServer("node-a", "10.10.0.0/26", store)
    ic = cni.cmd_add("c1", "default", "web-1")
    tpu = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    ctl = NodeRouteController(tpu, "node-a", pod_cidr="10.10.0.0/26")
    ctl.sync_interfaces(cni.ifaces.all())
    b = _batch([("10.10.1.9", ic.ip, OFPORT_TUNNEL)])
    assert tpu.step(b, now=1).fwd_kind.tolist() == [FWD_LOCAL]
    assert tpu.step(b, now=1).out_port.tolist() == [ic.ofport]

    # Restart: fresh store handle, fresh controller — same forwarding.
    store2 = ConfigStore(str(tmp_path / "conf.db"))
    cni2 = CniServer("node-a", "10.10.0.0/26", store2)
    tpu2 = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    ctl2 = NodeRouteController(tpu2, "node-a", pod_cidr="10.10.0.0/26")
    ctl2.sync_interfaces(cni2.ifaces.all())
    assert tpu2.step(b, now=2).out_port.tolist() == [ic.ofport]


# ---- topology persistence ---------------------------------------------------


def test_topology_survives_datapath_restart(tmp_path):
    topo = _topo()
    tpu = TpuflowDatapath(
        flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64,
        persist_dir=str(tmp_path),
    )
    tpu.install_topology(topo)
    b = _batch([("10.10.0.5", "10.10.1.9", 3)])
    assert tpu.step(b, now=1).fwd_kind.tolist() == [FWD_TUNNEL]
    # Reconstruct without explicit state: snapshot restores the topology.
    tpu2 = TpuflowDatapath(
        flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64,
        persist_dir=str(tmp_path),
    )
    r = tpu2.step(b, now=2)
    assert r.fwd_kind.tolist() == [FWD_TUNNEL]
    assert r.peer_ip.tolist() == [iputil.ip_to_u32("192.168.1.2")]


# ---- trace parity -----------------------------------------------------------


def test_trace_reports_forwarding():
    tpu, orc = _pair(_topo())
    b = _batch([
        ("10.10.0.5", "10.10.0.6", 3),
        ("10.10.0.6", "10.10.0.7", 3),  # spoofed
        ("10.10.0.5", "10.10.1.9", 3),
    ])
    ta, tb = tpu.trace(b, now=1), orc.trace(b, now=1)
    for ra, rb in zip(ta, tb):
        assert ra["spoofed"] == rb["spoofed"]
        assert ra["fwd_kind"] == rb["fwd_kind"]
        assert ra["out_port"] == rb["out_port"]
    assert [r["spoofed"] for r in ta] == [False, True, False]
    assert [r["fwd_kind"] for r in ta] == [FWD_LOCAL, FWD_LOCAL, FWD_TUNNEL]
