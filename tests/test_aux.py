"""Aux subsystems: central Traceflow controller (tag allocation + GC),
support bundle collection, agent-info heartbeat."""

import json
import tarfile

import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.traceflow import TraceflowController, TraceflowSpec
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.observability.agentinfo import collect_agent_info
from antrea_tpu.observability.supportbundle import collect_bundle


def _env():
    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        "atg", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="deny-in", name="deny-in", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    services = [ServiceEntry("10.96.0.1", 80, 6,
                             [Endpoint("10.0.0.10", 8080)], name="svc")]
    return ps, services


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_traceflow_run_and_observations(dp_cls):
    ps, services = _env()
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    if dp_cls is TpuflowDatapath:
        kw["miss_chunk"] = 16
    tfc = TraceflowController()
    tfc.register_datapath("n0", dp_cls(ps, services, **kw))

    # Service traffic: LB observation + post-DNAT denial attribution.
    st = tfc.run(TraceflowSpec("tf1", "10.0.0.5", "10.96.0.1", dst_port=80), "n0")
    assert st.phase == "Succeeded" and st.verdict == "Drop"
    comps = [o["component"] for o in st.observations]
    assert comps == ["Classification", "LB", "EgressSecurity",
                     "IngressSecurity", "Output"]
    lb = st.observations[1]
    assert lb["translatedDstIP"] == "10.0.0.10" and lb["translatedDstPort"] == 8080
    ing = st.observations[3]
    assert ing["action"] == "Dropped" and ing["networkPolicyRule"] == "deny-in/In/0"

    # Unknown node fails cleanly; same name reuses its tag.
    st2 = tfc.run(TraceflowSpec("tf1", "10.0.0.5", "10.0.0.99"), "ghost")
    assert st2.phase == "Failed" and st2.tag == st.tag


def test_traceflow_tag_allocation_and_gc():
    clock = [0.0]
    tfc = TraceflowController(clock=lambda: clock[0])
    tfc.register_datapath("n0", OracleDatapath(*_env(),
                                               flow_slots=1 << 10, aff_slots=1 << 8))
    tags = set()
    for i in range(63):
        tags.add(tfc.run(TraceflowSpec(f"tf{i}", "10.0.0.5", "10.0.0.99",
                                       timeout_s=100), "n0").tag)
    assert len(tags) == 63 and 0 not in tags  # 6-bit space, 0 reserved
    with pytest.raises(RuntimeError, match="tag space exhausted"):
        tfc.run(TraceflowSpec("overflow", "10.0.0.5", "10.0.0.99"), "n0")
    # After the deadline the stale tags GC and allocation resumes.
    clock[0] = 200.0
    st = tfc.run(TraceflowSpec("fresh", "10.0.0.5", "10.0.0.99"), "n0")
    assert st.phase == "Succeeded"
    tfc.release("fresh")


def test_support_bundle_collection(tmp_path):
    ps, services = _env()
    dp = TpuflowDatapath(None, None, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=16, persist_dir=str(tmp_path / "state"))
    dp.install_bundle(ps=ps, services=services)
    import numpy as np
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.utils import ip as iputil

    dp.step(PacketBatch(
        src_ip=np.array([iputil.ip_to_u32("10.0.0.5")], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32("10.0.0.77")], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([40000], np.int32),
        dst_port=np.array([80], np.int32),
    ), 5)

    out = tmp_path / "bundle.tar.gz"
    names = collect_bundle(dp, str(out), node="n0", now=6,
                           persist_dir=str(tmp_path / "state"))
    assert {"meta.json", "stats.json", "cache_stats.json", "flows.json",
            "metrics.prom", "datapath_snapshot.json"} <= set(names)
    with tarfile.open(out) as tar:
        flows = json.load(tar.extractfile("flows.json"))
        assert len(flows) == 2  # fwd + reply conntrack entries
        meta = json.load(tar.extractfile("meta.json"))
        assert meta["generation"] == 1 and meta["node"] == "n0"
        snap = json.load(tar.extractfile("datapath_snapshot.json"))
        assert snap["generation"] == 1


def test_agent_info_heartbeat():
    ps, services = _env()
    dp = OracleDatapath(ps, services, flow_slots=1 << 10, aff_slots=1 << 8)
    info = collect_agent_info(dp, "n0", now=123)
    assert info["kind"] == "AntreaAgentInfo" and info["nodeName"] == "n0"
    assert info["heartbeatUnix"] == 123
    assert info["datapath"]["type"] == "oracle"
    assert info["conditions"][0]["type"] == "AgentHealthy"


def test_traceflow_gate_disabled_fails_cleanly():
    from antrea_tpu.features import FeatureGates

    tfc = TraceflowController()
    tfc.register_datapath("n0", OracleDatapath(
        *_env(), flow_slots=1 << 10, aff_slots=1 << 8,
        feature_gates=FeatureGates({"Traceflow": False})))
    st = tfc.run(TraceflowSpec("tf-gated", "10.0.0.5", "10.0.0.99"), "n0")
    assert st.phase == "Failed"
    assert "Traceflow" in st.observations[0]["action"]
    assert "tf-gated" not in tfc._tags  # tag returned to the pool


def test_mc_ip_recycling():
    from antrea_tpu.multicluster import ClusterSet
    cs = ClusterSet()
    m = cs.add_member("east")
    # Cycle far past the /24 capacity: retracted imports recycle their IPs.
    for i in range(600):
        svc = ServiceEntry("10.96.0.9", 80, 6, [Endpoint("10.9.0.9", 80)],
                           name=f"s{i}", namespace="prod")
        cs.leader.export_service("west", "prod", svc)
        cs.leader.retract_export("west", "prod", f"s{i}")
    assert m.imported == {}
