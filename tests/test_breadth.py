"""Breadth components: Tier CRDs, ClusterGroups, endpoint querier, feature
gates, typed config, antctl CLI."""

import json
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    AntreaAppliedTo,
    AntreaNetworkPolicy,
    AntreaNPRule,
    AntreaPeer,
    ClusterGroup,
    IPBlock,
    LabelSelector,
    Namespace,
    Pod,
    Tier,
)
from antrea_tpu.controller import NetworkPolicyController
from antrea_tpu.controller.endpoint_querier import query_endpoint
from antrea_tpu.features import FeatureGates
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet
from antrea_tpu.utils import ip as iputil


def mk_pod(name, ip, node="n0", ns="default", **labels):
    return Pod(namespace=ns, name=name, ip=ip, node=node, labels=labels)


def _base(ctl):
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(mk_pod("web", "10.0.0.10", app="web"))
    ctl.upsert_pod(mk_pod("cli", "10.0.0.20", app="cli"))


def _anp(uid, tier="", action=RuleAction.DROP, peer=None, prio=5.0):
    return AntreaNetworkPolicy(
        uid=uid, name=uid, tier=tier, priority=prio,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"app": "web"}))],
        rules=[AntreaNPRule(
            direction=Direction.IN, action=action,
            peers=[peer] if peer else [],
        )],
    )


def _probe(ctl, src="10.0.0.20", dst="10.0.0.10"):
    o = Oracle(ctl.policy_set())
    return int(o.classify(Packet(
        src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
        proto=6, src_port=41000, dst_port=80,
    )).code)


def test_named_tiers_resolve_and_order():
    """A custom tier with a lower priority than a default tier wins the
    cross-tier evaluation order (ref default tiers + Tier CRD)."""
    ctl = NetworkPolicyController()
    _base(ctl)
    ctl.upsert_tier(Tier("urgent", priority=10))
    # application-tier ALLOW vs urgent-tier DROP: urgent evaluates first.
    ctl.upsert_antrea_policy(_anp("allow-app", tier="application",
                                  action=RuleAction.ALLOW))
    ctl.upsert_antrea_policy(_anp("drop-urgent", tier="urgent"))
    assert _probe(ctl) == 1
    # Unknown tier is a config error.
    with pytest.raises(ValueError, match="does not exist"):
        ctl.upsert_antrea_policy(_anp("x", tier="nope"))
    # A referenced tier refuses deletion; a tier priority change re-sorts.
    with pytest.raises(ValueError, match="referenced"):
        ctl.delete_tier("urgent")
    ctl.upsert_tier(Tier("urgent", priority=252))  # now AFTER application
    assert _probe(ctl) == 0


def test_cluster_groups_resolve_union_and_update():
    """ClusterGroup peers: selector form, ipBlocks form, childGroups union;
    spec updates re-resolve referencing policies (ref group.go)."""
    ctl = NetworkPolicyController()
    _base(ctl)
    ctl.upsert_cluster_group(ClusterGroup(
        "clients", pod_selector=LabelSelector.make({"app": "cli"})))
    ctl.upsert_cluster_group(ClusterGroup(
        "corp", ip_blocks=[IPBlock(cidr="192.168.0.0/16")]))
    ctl.upsert_cluster_group(ClusterGroup(
        "all-sources", child_groups=["clients", "corp"]))
    ctl.upsert_antrea_policy(_anp(
        "drop-sources", peer=AntreaPeer(group="all-sources")))
    assert _probe(ctl, src="10.0.0.20") == 1  # via child selector group
    assert _probe(ctl, src="192.168.3.4") == 1  # via child ipBlock
    assert _probe(ctl, src="10.0.0.99") == 0  # not in the union

    # Unknown group is an error; deletion of a referenced group refuses.
    with pytest.raises(ValueError, match="does not exist"):
        ctl.upsert_antrea_policy(_anp("y", peer=AntreaPeer(group="ghost")))
    with pytest.raises(ValueError, match="referenced"):
        ctl.delete_cluster_group("clients")

    # Spec update re-resolves the referencing policy.
    ctl.upsert_cluster_group(ClusterGroup(
        "clients", pod_selector=LabelSelector.make({"app": "other"})))
    assert _probe(ctl, src="10.0.0.20") == 0  # cli no longer matched
    assert _probe(ctl, src="192.168.3.4") == 1  # corp block still does


def test_endpoint_querier():
    ctl = NetworkPolicyController()
    _base(ctl)
    ctl.upsert_cluster_group(ClusterGroup(
        "clients", pod_selector=LabelSelector.make({"app": "cli"})))
    ctl.upsert_antrea_policy(_anp("p1", peer=AntreaPeer(group="clients")))
    r = query_endpoint(ctl, "default", "web")
    assert [u for u, _ in r.applied] == ["p1"]
    r2 = query_endpoint(ctl, "default", "cli")
    assert r2.applied == [] and r2.ingress_from == [("p1", 0)]
    assert query_endpoint(ctl, "default", "ghost").applied == []


def test_feature_gates_registry_and_wiring(tmp_path):
    import numpy as np

    from antrea_tpu.datapath import OracleDatapath
    from antrea_tpu.observability import AuditLogger
    from antrea_tpu.packet import PacketBatch

    with pytest.raises(ValueError, match="unknown feature gate"):
        FeatureGates({"NotAGate": True})
    gates = FeatureGates({"Traceflow": False, "NetworkPolicyStats": False,
                          "AntreaPolicy": False, "AuditLogging": False})

    ctl = NetworkPolicyController(feature_gates=gates)
    _base(ctl)
    with pytest.raises(RuntimeError, match="AntreaPolicy"):
        ctl.upsert_antrea_policy(_anp("p"))

    dp = OracleDatapath(feature_gates=gates)
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32("10.0.0.1")], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32("10.0.0.2")], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([1], np.int32), dst_port=np.array([2], np.int32),
    )
    dp.step(b, 1)
    assert dp.stats().default_allow == 0  # stats gated off
    with pytest.raises(RuntimeError, match="Traceflow"):
        dp.trace(b, 1)
    with pytest.raises(RuntimeError, match="AuditLogging"):
        AuditLogger(feature_gates=gates)


def test_agent_config_load_and_build(tmp_path):
    from antrea_tpu.config import build_datapath, load_agent_config

    cfg_path = tmp_path / "antrea-agent.conf"
    cfg_path.write_text(
        "nodeName: n7\n"
        "nodeIPs: [172.18.0.9]\n"
        "flowSlots: 4096\n"
        "affinitySlots: 256\n"
        "datapathType: oracle\n"
        "featureGates:\n  Traceflow: false\n"
    )
    cfg = load_agent_config(str(cfg_path))
    assert cfg.node_name == "n7" and cfg.flow_slots == 4096
    assert not cfg.feature_gates.enabled("Traceflow")
    dp = build_datapath(cfg)
    assert dp.datapath_type.value == "oracle"

    bad = tmp_path / "bad.conf"
    bad.write_text("flowSlots: 1000\n")  # not a power of two
    with pytest.raises(ValueError, match="power of two"):
        load_agent_config(str(bad))
    bad.write_text("noSuchKey: 1\n")
    with pytest.raises(ValueError, match="unknown agent config key"):
        load_agent_config(str(bad))


def test_antctl_cli(tmp_path):
    """The CLI surface end-to-end: snapshot a datapath, then get/traceflow/
    query through the antctl subprocess."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from antrea_tpu.datapath import OracleDatapath
    from antrea_tpu.compiler.ir import PolicySet
    from antrea_tpu.apis import controlplane as cp

    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        "atg", [cp.GroupMember(ip="10.0.0.10", node="n0",
                               pod_namespace="default", pod_name="web")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="deny-in", name="deny-in", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    services = [ServiceEntry("10.96.0.1", 80, 6,
                             [Endpoint("10.0.0.10", 8080)], name="svc")]
    dp = OracleDatapath(persist_dir=str(tmp_path))
    dp.install_bundle(ps=ps, services=services)

    def antctl(*argv):
        out = subprocess.run(
            [sys.executable, "-m", "antrea_tpu.antctl", *argv],
            capture_output=True, text=True, cwd="/root/repo",
        )
        assert out.returncode == 0, out.stderr
        return out.stdout

    got = json.loads(antctl("get", "networkpolicies", "--state", str(tmp_path)))
    assert got["items"][0]["uid"] == "deny-in" and got["generation"] == 1
    got = json.loads(antctl("get", "services", "--state", str(tmp_path)))
    assert got["items"][0]["clusterIP"] == "10.96.0.1"

    tf = json.loads(antctl(
        "traceflow", "--state", str(tmp_path),
        "--src", "10.0.0.5", "--dst", "10.96.0.1", "--dport", "80",
    ))
    assert tf["verdict"] == "Drop"  # DNAT to 10.0.0.10, denied there
    assert tf["dnat_ip"] == "10.0.0.10"
    assert tf["ingress_rule"] == "deny-in/In/0"

    q = json.loads(antctl(
        "query", "endpoint", "--state", str(tmp_path), "--ip", "10.0.0.10",
    ))
    assert q["appliedPolicies"][0]["policy"] == "deny-in"
    assert antctl("version").strip()
