"""Dissemination across a REAL process boundary: controller -> RamStore
(queued watchers) -> serialized WatchEvents over pipes -> agent subprocess
-> datapath, probed remotely.

The serialized-watch architecture of the reference (protobuf over HTTPS,
architecture.md:50-64; per-watcher channel, ram/store.go:230) realized with
dissemination/serde.py + transport.py.  Everything the remote agent
enforces provably crossed the wire — it shares no memory with the
controller.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    K8sNetworkPolicy,
    K8sNPRule,
    K8sPeer,
    LabelSelector,
    Namespace,
    Pod,
    PortSpec,
)
from antrea_tpu.controller import NetworkPolicyController
from antrea_tpu.dissemination import RamStore
from antrea_tpu.dissemination.transport import AgentDiedError, SubprocessAgent
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

NODES = ["nodeA", "nodeB"]


def mk_pod(name, ip, node, ns="default", **labels):
    return Pod(namespace=ns, name=name, ip=ip, node=node, labels=labels)


def _pods(ctl):
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(mk_pod("web1", "10.0.0.10", "nodeA", app="web"))
    ctl.upsert_pod(mk_pod("web2", "10.0.0.11", "nodeB", app="web"))
    ctl.upsert_pod(mk_pod("cli1", "10.0.0.20", "nodeB", app="client"))


def _np_web(uid="np-web"):
    return K8sNetworkPolicy(
        uid=uid, name=uid, namespace="default",
        pod_selector=LabelSelector.make({"app": "web"}),
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "client"}))],
            ports=[PortSpec(protocol=6, port=80)],
        )],
    )


def _probe(agent, src, dst, dport=80, now=10):
    batch = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([42000], np.int32),
        dst_port=np.array([dport], np.int32),
    )
    return agent.step(batch, now)


@pytest.fixture
def wired():
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agents = {n: SubprocessAgent(n, store) for n in NODES}
    yield ctl, store, agents
    for a in agents.values():
        a.stop()


def test_policy_enforced_across_process_boundary(wired):
    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    for a in agents.values():
        a.pump()
        a.sync()

    # nodeA hosts web1: client allowed on 80, stranger denied, port 443 denied.
    a = agents["nodeA"]
    assert _probe(a, "10.0.0.20", "10.0.0.10", 80)["code"] == [0]
    assert _probe(a, "10.0.5.5", "10.0.0.10", 80)["code"] == [1]
    assert _probe(a, "10.0.0.20", "10.0.0.10", 443)["code"] == [1]

    # The remote PolicySet crossed the wire intact (content-addressed names).
    summary = a.state_summary()
    assert summary["policies"] == ["np-web"]
    assert len(summary["appliedToGroups"]) == 1


def test_remote_agents_match_local_oracle(wired):
    """Every remote verdict equals an oracle over the controller's direct
    span-filtered snapshot — the dissemination parity bar of
    test_dissemination, now across the wire."""
    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    ips = ["10.0.0.10", "10.0.0.11", "10.0.0.20", "10.0.9.9"]
    pkts = [
        Packet(src_ip=iputil.ip_to_u32(s), dst_ip=iputil.ip_to_u32(d),
               proto=6, src_port=43000, dst_port=p)
        for s in ips for d in ips if s != d for p in (80, 443)
    ]
    batch = PacketBatch.from_packets(pkts)
    for node, agent in agents.items():
        agent.pump()
        agent.sync()
        got = agent.step(batch, now=5)["code"]
        oracle = Oracle(ctl.policy_set_for_node(node))
        want = [int(oracle.classify(p).code) for p in pkts]
        assert got == want, node


def test_incremental_delta_and_retraction_over_wire(wired):
    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    a = agents["nodeA"]
    a.pump(); a.sync()
    assert _probe(a, "10.0.0.20", "10.0.0.10", 80)["code"] == [0]

    # Pod churn: a NEW client pod joins the allowed group -> incremental
    # delta crosses the wire; the new client is allowed without a bundle.
    ctl.upsert_pod(mk_pod("cli2", "10.0.0.21", "nodeB", app="client"))
    sent = a.pump()
    assert sent > 0
    a.sync()
    assert _probe(a, "10.0.0.21", "10.0.0.10", 80, now=20)["code"] == [0]

    # Policy deletion retracts enforcement.
    ctl.delete_policy("np-web")
    a.pump(); a.sync()
    assert _probe(a, "10.0.5.5", "10.0.0.10", 80, now=30)["code"] == [0]


def test_queued_watcher_does_not_block_and_unsubscribes(wired):
    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    # Events buffer while the consumer does not pump (slow consumer): the
    # producer has already moved on, nothing blocked.
    w = agents["nodeA"]._watcher
    assert w.pending() > 0
    before = store.n_watchers
    agents["nodeA"].stop()
    assert store.n_watchers == before - 1
    # Producer keeps going with a dead watcher registered — next apply
    # prunes it without delivering anywhere.
    ctl.upsert_pod(mk_pod("cli9", "10.0.0.99", "nodeB", app="client"))
    assert w.pending() == 0
    del agents["nodeA"]


# -- failure model: agent death is typed, diagnosed, and bounded --------------


@pytest.mark.chaos
def test_agent_killed_mid_stream_raises_typed_error(wired):
    """Kill-mid-stream regression: the child dying between frames must
    surface as AgentDiedError carrying the node and exit code — never a
    bare BrokenPipeError from _proc.stdin.write."""
    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    a = agents["nodeA"]
    a.pump(); a.sync()  # healthy first: the stream was live

    a._proc.kill()
    a._proc.wait(timeout=10)
    # Churn queues more events; shipping them hits the dead pipe.
    ctl.upsert_pod(mk_pod("cli2", "10.0.0.21", "nodeB", app="client"))
    with pytest.raises(AgentDiedError) as ei:
        a.pump()
    e = ei.value
    assert e.node == "nodeA"
    assert e.exit_code == -9  # SIGKILL, reaped and reported
    assert "died" in str(e)
    assert not isinstance(e, BrokenPipeError)
    # stop() after death is a clean no-op (no second exception).
    a.stop()


@pytest.mark.chaos
def test_wedged_agent_hits_rpc_deadline_and_is_killed(wired):
    """A wedged child (SIGSTOP: alive but unresponsive) must not block
    _rpc forever: the read deadline fires, the child is killed, and the
    caller gets the typed error — the controller never hangs on one
    node."""
    import os
    import signal
    import time as _time

    ctl, store, agents = wired
    _pods(ctl)
    ctl.upsert_k8s_policy(_np_web())
    a = agents["nodeA"]
    a.pump(); a.sync()  # prove the child responds when healthy (and is
    a._rpc_timeout = 2.0  # past its slow import-time boot)

    os.kill(a._proc.pid, signal.SIGSTOP)
    t0 = _time.monotonic()
    with pytest.raises(AgentDiedError) as ei:
        a.sync()
    assert _time.monotonic() - t0 < 30  # bounded, not forever
    assert "wedged" in str(ei.value)
    assert a._proc.poll() is not None  # the wedged child was reaped


@pytest.mark.chaos
def test_agent_died_error_carries_stderr_tail(wired):
    """The typed error ships the child's stderr tail — the diagnostic an
    operator needs without attaching a debugger."""
    ctl, store, agents = wired
    a = agents["nodeA"]
    # A malformed event makes the child log to stderr (and survive); the
    # following sync() response proves the log line was written.
    a._send_frame({"ev": {"malformed": True}})
    a.sync()
    a._proc.kill()
    a._proc.wait(timeout=10)
    with pytest.raises(AgentDiedError) as ei:
        a._rpc({"cmd": "summary"})
    assert "bad frame" in ei.value.stderr_tail or (
        "event failed" in ei.value.stderr_tail)


@pytest.mark.chaos
def test_bounded_watcher_resync_crosses_process_boundary():
    """Overflowing a capped watcher behind a SubprocessAgent converts
    into the bracketed re-list over the pipe: the child retracts state
    deleted during the overflow window (same protocol as the wire)."""
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    with SubprocessAgent("nodeA", store, watcher_max_pending=4) as a:
        _pods(ctl)
        ctl.upsert_k8s_policy(_np_web())
        a.pump(); a.sync()
        assert a.state_summary()["policies"] == ["np-web"]

        w = a._watcher
        for i in range(8):  # churn past the cap with no pump
            ctl.upsert_pod(mk_pod(f"c{i}", f"10.0.7.{i + 1}", "nodeB",
                                  app="client"))
        assert w.needs_resync and w.pending() == 0
        ctl.delete_policy("np-web")  # invisible to the dropped buffer
        a.pump()  # ships resync_begin / snapshot / resync_end
        a.sync()
        s = a.state_summary()
        assert s["policies"] == []  # stale policy retracted by the re-list
        assert s["addressGroups"] == [] and s["appliedToGroups"] == []


@pytest.mark.chaos
def test_injected_pipe_fault_surfaces_as_typed_error(wired):
    """FaultyPipe chaos on the parent->child stream: an injected
    BrokenPipeError mid-frame takes the same typed-death path as a real
    crash (the transport cannot distinguish them, and must not)."""
    from antrea_tpu.dissemination.faults import FaultPlan, FaultyPipe

    ctl, store, agents = wired
    _pods(ctl)
    a = agents["nodeA"]
    a.pump(); a.sync()

    plan = FaultPlan()
    plan.every("nodeA.pipe.write", 1, "reset", times=1)  # next write dies
    a._proc.stdin = FaultyPipe(a._proc.stdin, plan, "nodeA.pipe")
    with pytest.raises(AgentDiedError) as ei:
        a.sync()
    assert plan.count("reset") == 1
    # The pipe close was an orderly EOF to the child: it exited cleanly,
    # and the typed error still reports the reaped code.
    assert ei.value.exit_code is not None
