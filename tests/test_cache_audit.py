"""Continuous flow-cache revalidator (ISSUE 5 tentpole): audit-and-repair
for stateful device tensors, differential tpuflow-vs-oracle throughout.

The acceptance bar: an injected cached-verdict flip and an injected
rule-tensor word flip are each (a) NOT detected by the existing
fresh-tuple canary — demonstrating the blind spot PR 4 left, (b) detected
by the audit plane within two full sweeps, (c) repaired with zero
post-repair parity mismatches against the scalar oracle, on both engines,
including with the async slow path enabled; plus the audits-racing-drain/
epoch-swap interleavings, the divergence-rate escalation ladder, the
poison-bundle (PolicyCapacityError) no-retry-storm behavior, the /audit
API + antctl surface (the scrub-coverage gate runs as analysis pass
`audit-plane` in tests/test_static_analysis.py).

Probe discipline: every oracle-parity assertion uses FRESH 5-tuples (a
monotonic source-port counter) — an established flow legitimately
survives policy churn; tests that probe a CACHED entry reuse its tuple
explicitly.
"""

import itertools
import json
import random
import sys
from pathlib import Path

import numpy as np
import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.networkpolicy import WatchEvent
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.dissemination import FaultPlan
from antrea_tpu.dissemination.faults import FlakyDatapath
from antrea_tpu.models import pipeline as pl
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

CLIENT, SRV, BLOCKED = "10.0.1.1", "10.0.0.10", "10.0.9.9"
VIP = "10.96.0.1"

_NOW = itertools.count(1000)
_SPORT = itertools.count(20000)

SMALL = dict(flow_slots=1 << 8, aff_slots=1 << 4)


def _world():
    """One policy (drop BLOCKED -> SRV ingress) + one service so every
    entry class exists: committed forward/reply legs, a denial, and
    service tables for the canary-blind tensor-flip case."""
    ps = PolicySet(
        policies=[cp.NetworkPolicy(
            uid="p1", name="p1", type=cp.NetworkPolicyType.ACNP,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.IN,
                from_peer=cp.NetworkPolicyPeer(address_groups=["blocked"]),
                action=cp.RuleAction.DROP, priority=0)],
            applied_to_groups=["web"], tier_priority=250, priority=1.0)],
        address_groups={"blocked": cp.AddressGroup(
            name="blocked", members=[cp.GroupMember(ip=BLOCKED)])},
        applied_to_groups={"web": cp.AppliedToGroup(
            name="web", members=[cp.GroupMember(ip=SRV)])},
    )
    svcs = [ServiceEntry(cluster_ip=VIP, port=80, protocol=6, name="web",
                         namespace="default",
                         endpoints=[Endpoint(ip=SRV, port=8080)])]
    return ps, svcs


def _dp(dp_cls, ps, svcs, **kw):
    if dp_cls is TpuflowDatapath:
        kw.setdefault("miss_chunk", 16)
    return dp_cls(ps, svcs, **SMALL, **kw)


def _fresh(src, dst=SRV, dport=80):
    return Packet(src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
                  proto=6, src_port=next(_SPORT), dst_port=dport)


def _fresh_parity(dp, ps, srcs=(BLOCKED, "192.0.2.7", CLIENT)) -> int:
    """Step FRESH probes and diff every verdict vs Oracle(ps) -> mismatches."""
    now = next(_NOW)
    pkts = [_fresh(s) for s in srcs]
    got = dp.step(PacketBatch.from_packets(pkts), now).code
    oracle = Oracle(ps)
    return sum(int(got[i]) != int(oracle.classify(p).code)
               for i, p in enumerate(pkts))


def _warm(dp):
    """Populate every entry class: a committed service connection (fwd +
    reply legs) and a denial entry, on provably DISTINCT cache slots —
    the direct-mapped table would otherwise let a sport-dependent slot
    collision evict one fixture entry under another and make the
    corruption/repair assertions racy.  Returns the cached tuples."""
    from antrea_tpu.ops import hashing

    N = SMALL["flow_slots"]

    def slot(src, dst, sport, dport):
        return int(hashing.flow_hash(
            np.uint32(iputil.ip_to_u32(src)), np.uint32(iputil.ip_to_u32(dst)),
            6, sport, dport)) & (N - 1)

    while True:
        s1, s2 = next(_SPORT), next(_SPORT)
        # est fwd (CLIENT -> VIP), its reply leg (endpoint -> CLIENT,
        # post-DNAT ports), and the denial (BLOCKED -> SRV).
        slots = {slot(CLIENT, VIP, s1, 80), slot(SRV, CLIENT, 8080, s1),
                 slot(BLOCKED, SRV, s2, 80)}
        if len(slots) == 3:
            break
    est = Packet(src_ip=iputil.ip_to_u32(CLIENT),
                 dst_ip=iputil.ip_to_u32(VIP), proto=6,
                 src_port=s1, dst_port=80)
    den = Packet(src_ip=iputil.ip_to_u32(BLOCKED),
                 dst_ip=iputil.ip_to_u32(SRV), proto=6,
                 src_port=s2, dst_port=80)
    now = next(_NOW)
    dp.step(PacketBatch.from_packets([est, den]), now)
    if dp._slowpath is not None:
        dp.drain_slowpath(now)
    return est, den


def _step_codes(dp, pkts):
    return [int(c) for c in
            np.asarray(dp.step(PacketBatch.from_packets(pkts),
                               next(_NOW)).code)]


# ---------------------------------------------------------------------------
# The acceptance differential: blind spot -> detection <= 2 sweeps -> repair
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_cached_verdict_flip_blind_spot_detect_repair(dp_cls):
    """(a) a flipped cached verdict bit is invisible to the fresh-tuple
    canary AND keeps serving the wrong verdict; (b) the cursor-window
    revalidation finds it within two full sweeps even when the state
    digest cannot help (mutation accounted — the revalidation-bug shape);
    (c) eviction repairs it with zero post-repair parity mismatches."""
    ps, svcs = _world()
    # window = half the slot space: one full sweep == 2 scans.
    dp = _dp(dp_cls, ps, svcs, audit_window=SMALL["flow_slots"] // 2)
    est, den = _warm(dp)
    dp.audit_scan(now=next(_NOW))  # anchor digests on healthy state

    desc = dp._audit_corrupt("verdict")
    assert "verdict" in desc
    # Model a revalidation BUG rather than bit rot: the wrong value was
    # written by an accounted mutation, so the digest re-anchors over it
    # and only the row checks can catch it.
    dp._state_mutations += 1

    # (a) the blind spot: the canary watchdog sees nothing wrong...
    scan = dp.canary_scan(now=next(_NOW))
    assert scan["mismatches"] == 0 and not dp.degraded
    # ...and fresh-tuple traffic keeps full parity while a CACHED tuple
    # serves a wrong verdict (committed ALLOW flipped to DROP, the denial
    # flipped to ALLOW, or the reply leg flipped — whichever live slot the
    # injection hit, it diverges from the oracle).
    assert _fresh_parity(dp, ps) == 0
    oracle = Oracle(ps)
    reply = Packet(src_ip=iputil.ip_to_u32(SRV),
                   dst_ip=iputil.ip_to_u32(CLIENT), proto=6,
                   src_port=8080, dst_port=est.src_port)
    cached = [est, den, reply]
    # Truth: the service flow and its reply leg are ALLOW, the denial is
    # whatever the stateless oracle says for its raw tuple (DROP).
    want = [0, int(oracle.classify(den).code), 0]
    got = _step_codes(dp, cached)
    assert got != want, "the flip must actually serve a wrong verdict"

    # (b) detection within two full sweeps (== 4 scans at window = N/2).
    repaired_at = None
    for i in range(4):
        out = dp.audit_scan(now=next(_NOW))
        if out["repaired"]:
            repaired_at = i
            break
    assert repaired_at is not None, "audit missed the flip within 2 sweeps"
    st = dp.audit_stats()
    assert st["divergences"].get("verdict", 0) >= 1
    assert st["repairs_total"] >= 1

    # (c) zero post-repair parity mismatches: the evicted entry
    # re-classifies to the oracle verdict, fresh traffic stays clean, and
    # further scans are quiet.
    assert _step_codes(dp, cached) == want
    assert _fresh_parity(dp, ps) == 0
    out = dp.audit_scan(now=next(_NOW))
    assert out["divergences"] == 0 and not dp.degraded


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_rule_tensor_flip_blind_spot_detect_repair(dp_cls):
    """A flipped service-table word (the canary-BLIND tensor class: canary
    probes deliberately avoid service frontends) is (a) invisible to the
    canary, (b) caught by the checksum scrub on the next scan, (c) healed
    by host-mirror re-upload with zero post-repair parity mismatches —
    including the service DNAT resolution the flip corrupted."""
    ps, svcs = _world()
    dp = _dp(dp_cls, ps, svcs)
    _warm(dp)
    dp.audit_scan(now=next(_NOW))  # anchor

    desc = dp._audit_corrupt("tensor")
    assert "flip" in desc

    # (a) canary-blind: probes avoid frontends, so the corrupted service
    # tables certify clean.
    scan = dp.canary_scan(now=next(_NOW))
    assert scan["mismatches"] == 0 and not dp.degraded
    # The corruption is LIVE though: a fresh service flow resolves the
    # wrong endpoint port.
    vip_probe = _fresh("10.0.3.3", dst=VIP)
    r = dp.step(PacketBatch.from_packets([vip_probe]), next(_NOW))
    if dp._slowpath is None:
        assert int(r.dnat_port[0]) != 8080  # serving the flipped port

    # (b) the scrub detects on the next scan and heals by re-upload.
    out = dp.audit_scan(now=next(_NOW))
    assert out.get("healed"), out
    assert dp.audit_stats()["scrub"].get("corrupt", 0) >= 1
    assert dp.audit_stats()["scrub"].get("healed", 0) >= 1

    # (c) post-repair: fresh service traffic resolves the true endpoint
    # (the corrupted-port entry itself was evicted by the forced full
    # revalidation or re-proves clean), and parity holds.
    probe2 = _fresh("10.0.3.4", dst=VIP)
    r2 = dp.step(PacketBatch.from_packets([probe2]), next(_NOW))
    if dp._slowpath is None:
        assert int(r2.dnat_port[0]) == 8080
    assert _fresh_parity(dp, ps) == 0
    out = dp.audit_scan(now=next(_NOW))
    assert out["divergences"] == 0 and "healed" not in out


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_audit_repair_parity_async_slowpath(dp_cls):
    """The acceptance's async leg: with the background slow-path engine
    enabled, a verdict flip on a drained-and-cached entry is detected and
    repaired, and post-repair verdicts (via admission -> drain -> cached
    re-step) match the scalar oracle exactly."""
    ps, svcs = _world()
    dp = _dp(dp_cls, ps, svcs, async_slowpath=True, miss_queue_slots=64,
             drain_batch=16, audit_window=SMALL["flow_slots"] // 2)
    est, den = _warm(dp)
    dp.audit_scan(now=next(_NOW))

    dp._audit_corrupt("verdict")
    dp._state_mutations += 1  # revalidation-bug shape: digest blind
    for _ in range(4):
        out = dp.audit_scan(now=next(_NOW))
        if out["repaired"]:
            break
    assert dp.audit_stats()["repairs_total"] >= 1

    # Post-repair: each cached tuple re-admits, drains, and re-proves to
    # the oracle verdict.  Per-tuple batches (admission -> drain ->
    # cached re-step) so a direct-mapped slot collision between the two
    # tuples cannot evict one mid-assertion.
    oracle = Oracle(ps)
    for p, expect in ((est, 0), (den, int(oracle.classify(den).code))):
        now = next(_NOW)
        dp.step(PacketBatch.from_packets([p]), now)
        dp.drain_slowpath(now)
        assert _step_codes(dp, [p]) == [expect]
    assert dp.audit_scan(now=next(_NOW))["divergences"] == 0


def test_mode_for_mode_plane_parity():
    """The scalar twin implements identical audit semantics: the same
    traffic + the same corruption sequence produces the same divergence
    kinds, repair counts, and sweep accounting on both engines."""
    ps, svcs = _world()
    planes = []
    for dp_cls in (TpuflowDatapath, OracleDatapath):
        dp = _dp(dp_cls, ps, svcs, audit_window=SMALL["flow_slots"] // 2)
        _warm(dp)
        dp.audit_scan(now=500)
        dp._audit_corrupt("verdict")
        dp._state_mutations += 1
        for _ in range(4):
            dp.audit_scan(now=501)
        dp._audit_corrupt("tensor")
        dp.audit_scan(now=502)
        st = dp.audit_stats()
        planes.append({
            "divergences": st["divergences"],
            "repairs_total": st["repairs_total"],
            "sweeps_total": st["sweeps_total"],
            "entries_min": st["entries_total"] >= 3,
        })
    assert planes[0] == planes[1], planes


# ---------------------------------------------------------------------------
# Interleavings: the unified scheduler racing drains and epoch swaps.
# (PR 7 replaced the hand-enumerated pairwise interleaving cases with the
# scheduler-driven randomized-schedule property test below — the scheduler
# is now the ONLY way the background loops interleave in production, so
# the property is over ALL registered tasks at once, not plane pairs.)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_scheduler_randomized_schedule_racing_drain_and_epoch_swap(dp_cls):
    """Seeded randomized-schedule property test: interleave maintenance
    ticks (all registered tasks — canary, audit cursor, scrub, fused
    cache-maintain, recompile) with traffic steps, split in-flight drains
    (begin/finish), and bundle installs (epoch swaps).  Invariants held
    at every point: a tick landing inside begin_drain..finish_drain
    defers WHOLE (the serialization point — the pinned block is never
    audited/aged under an in-flight drain); a budgeted tick never spends
    past its budget; and after the storm the engine reconverges — drains
    classify to exact oracle parity, a forced full audit sweep is quiet,
    and nothing is degraded."""
    import copy

    rng = random.Random(0xA11CE)
    ps, svcs = _world()
    dp = _dp(dp_cls, ps, svcs, async_slowpath=True, miss_queue_slots=64,
             drain_batch=16, canary_probes=8, audit_window=32)
    eng = dp._slowpath
    oracle = Oracle(ps)
    inflight = False
    stepped: list = []
    for _op in range(40):
        now = next(_NOW)
        op = rng.choice(["tick", "tick", "budget_tick", "step", "begin",
                         "finish", "install"])
        if op in ("tick", "budget_tick"):
            budget = rng.choice([8, 16, 64]) if op == "budget_tick" else None
            out = dp.maintenance_tick(now=now, budget=budget)
            if inflight:
                assert out["blocked"] == "inflight-drain", out
                assert not out["ran"] and out["spent"] == 0
            else:
                assert out["blocked"] is None
            if budget is not None:
                assert out["spent"] <= budget, out
        elif op == "step":
            pkts = [_fresh(rng.choice([BLOCKED, CLIENT, "192.0.2.7",
                                       "198.51.100.9"]))
                    for _ in range(2)]
            stepped.extend(pkts)
            dp.step(PacketBatch.from_packets(pkts), now)
        elif op == "begin":
            if not inflight:
                inflight = eng.begin_drain(now)
        elif op == "finish":
            if inflight:
                eng.finish_drain(now)
                inflight = False
        elif op == "install":
            # An epoch swap mid-storm (and legitimately mid-drain: the
            # stale-reclassify path) — the scheduler's next unblocked
            # tick promotes the fused heal.
            dp.install_bundle(ps=copy.deepcopy(ps))
    if inflight:
        eng.finish_drain(next(_NOW))
    # Reconvergence: settle the queue (drain() heals any stale epoch with
    # the fused maintenance pass), then every invariant at once.  Parity
    # probes on the async engine go admit -> drain -> cached re-step
    # (fresh misses are provisional until drained).
    dp.drain_slowpath(next(_NOW))
    assert not eng.stale
    probe = stepped[-2:] or [_fresh(BLOCKED)]
    now = next(_NOW)
    dp.step(PacketBatch.from_packets(probe), now)
    dp.drain_slowpath(now)
    got = _step_codes(dp, probe)
    assert got == [int(oracle.classify(p).code) for p in probe]
    quiet = dp.maintenance_force_audit(now=next(_NOW))
    assert quiet["divergences"] == 0, quiet
    assert not dp.degraded
    st = dp.maintenance_stats()
    # The storm exercised both sides of the serialization point.
    assert st["ticks_total"] > 0
    assert all(row["overruns_total"] == 0 for row in st["tasks"].values())


# ---------------------------------------------------------------------------
# Divergence policy: the shared escalation ladder + fault sites
# ---------------------------------------------------------------------------


def test_divergence_rate_trips_degraded_escalation():
    """Findings at/above the trip threshold feed the PR 4 machinery: the
    datapath degrades and the immediate full recompile — itself
    canary-gated — recovers it, exactly like canary_scan."""
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, audit_divergence_trip=1)
    plan = FaultPlan()
    dp.arm_audit_faults(plan, "n1")
    _warm(dp)

    plan.after("n1.audit", plan.hits("n1.audit"), "fail", times=1)
    out = dp.audit_scan(now=next(_NOW))
    assert out["divergences"] == 1  # the forced false positive
    assert out["recovered"] and not dp.degraded  # recompile certified
    assert dp.audit_stats()["divergences"].get("injected") == 1
    assert _fresh_parity(dp, ps) == 0

    # With the recompile ALSO failing (persistent miscompile injection),
    # the trip leaves the datapath safely degraded on LKG verdicts.
    dp.arm_commit_faults(plan, "n1")
    plan.after("n1.audit", plan.hits("n1.audit"), "fail", times=1)
    plan.after("n1.canary", plan.hits("n1.canary"), "fail", times=1)
    out = dp.audit_scan(now=next(_NOW))
    assert not out["recovered"] and dp.degraded
    assert _fresh_parity(dp, ps) == 0  # LKG keeps serving correctly
    dp.install_bundle(ps=ps)  # fault exhausted: agent-style recovery
    assert not dp.degraded


def test_affinity_drift_repairs_without_tripping_degrade():
    """A divergent row on an affinity-bearing program may be DRIFT (the
    fresh walk reads the CURRENT affinity table, which can have expired
    or been overwritten since insert), not corruption: it is repaired by
    eviction but reported as kind 'affinity' and excluded from the
    degrade trip — a burst of expired affinity learns can never
    quarantine a node.  Plane-level test over a stub owner so the drift
    is deterministic."""
    from antrea_tpu.datapath.audit import AuditPlane

    def row(slot, aff, dnat):
        return {"slot": slot, "src": 1, "dst": 2, "proto": 6, "sport": 1000,
                "dport": 80, "code": 1, "svc": 0, "dnat_ip": dnat,
                "dnat_port": 80, "rule_in": "r", "rule_out": None,
                "committed": False, "reply": False, "aff": aff}

    class _Commit:
        def __init__(self):
            self.degraded = False
            self.last_error = ""
            self.recompiles = 0

        def run_bundle(self, ps, services):
            self.recompiles += 1

    class _Stub:
        generation = 0

        def __init__(self):
            self._state_mutations = 0
            self._commit = _Commit()
            self.evicted = []

        def _audit_slots(self):
            return 8

        def _audit_window(self, cursor, k, now):
            # One affinity-bearing row whose service selection drifted,
            # one identical row WITHOUT affinity (proven corruption).
            return [row(1, True, dnat=111), row(2, False, dnat=222)]

        def _audit_fresh(self, rows, now):
            return [{"code": 1, "svc": 0, "dnat_ip": 999, "dnat_port": 80,
                     "rule_in": "r", "rule_out": None} for _ in rows]

        def _audit_evict(self, slots):
            self.evicted.extend(slots)
            self._state_mutations += 1

        def _audit_rule_digests(self):
            return {"rules": 1}

        def _audit_state_digest(self):
            return self._state_mutations  # tracks mutations: never corrupt

    # Mixed scan: both rows repaired; the proven (non-affinity) one trips.
    owner = _Stub()
    plane = AuditPlane(owner, window=8, divergence_trip=1)
    plane.refresh_golden()
    out = plane.scan(now=1)
    assert sorted(owner.evicted) == [1, 2] and out["repaired"] == 2
    assert plane.divergences["affinity"] == 1
    assert plane.divergences["service"] == 1
    assert owner._commit.recompiles == 1  # escalation fired on the proof

    # Affinity-only scan: repaired, metered, but NEVER trips the ladder.
    class _AffOnly(_Stub):
        def _audit_window(self, cursor, k, now):
            return [row(1, True, dnat=111)]

    owner2 = _AffOnly()
    plane2 = AuditPlane(owner2, window=8, divergence_trip=1)
    plane2.refresh_golden()
    out2 = plane2.scan(now=1)
    assert out2["repaired"] == 1 and owner2.evicted == [1]
    assert plane2.divergences == {"affinity": 1}
    assert not owner2._commit.degraded
    assert owner2._commit.recompiles == 0


def test_flaky_wrapper_arms_audit_sites_and_scan_self_detects():
    """FlakyDatapath auto-arms {name}.cache / {name}.audit; a .cache
    firing REALLY corrupts state at scan start and the same scan detects
    and repairs its own injection (state digest anchored pre-scan)."""
    ps, svcs = _world()
    plan = FaultPlan()
    dp = FlakyDatapath(_dp(OracleDatapath, ps, svcs), plan, "nX")
    _warm(dp)
    dp.audit_scan(now=next(_NOW))  # anchor

    plan.after("nX.cache", plan.hits("nX.cache"), "fail", times=1)
    out = dp.audit_scan(now=next(_NOW))
    assert "injected_corruption" in out
    assert out["full"]  # state-digest mismatch forced the full sweep
    assert out["repaired"] >= 1
    assert plan.count("fail") == 1
    assert _fresh_parity(dp, ps) == 0

    # kind "partial" targets the rule-side tensors instead.
    plan.after("nX.cache", plan.hits("nX.cache"), "partial", times=1)
    out = dp.audit_scan(now=next(_NOW))
    assert out.get("healed"), out
    assert _fresh_parity(dp, ps) == 0


# ---------------------------------------------------------------------------
# Hot path unharmed + counters + tooling + typed capacity errors
# ---------------------------------------------------------------------------


def test_step_hlo_bit_identical_with_audit_plane():
    """The audit plane lives entirely off the hot step: the compiled step
    of an audit-configured datapath — before AND after scans — lowers to
    byte-identical HLO vs a default-config twin (the check_phases-style
    bit-identity bar for the plane)."""
    ps, svcs = _world()
    a = _dp(TpuflowDatapath, ps, svcs, audit_window=8,
            audit_divergence_trip=2)
    b = _dp(TpuflowDatapath, ps, svcs)
    assert a._meta_step == b._meta_step

    def lower_text(dp):
        import jax.numpy as jnp

        z = np.zeros(4, np.int32)
        return pl.pipeline_step.lower(
            dp._state, dp._drs, dp._dsvc,
            jnp.asarray(z), jnp.asarray(z), jnp.asarray(z),
            jnp.asarray(z), jnp.asarray(z),
            jnp.int32(0), jnp.int32(0), meta=dp._meta_step,
        ).as_text()

    before = lower_text(a)
    assert before == lower_text(b)
    _warm(a)
    a.audit_scan(now=next(_NOW), full=True)
    assert lower_text(a) == before


def test_audit_scan_leaves_counters_and_census_intact():
    """A clean scan is observable-state-neutral: flow-cache census,
    per-rule stats, and cache contents are untouched (the counter
    interaction proper lives in test_flow_counters.py)."""
    ps, svcs = _world()
    dp = _dp(TpuflowDatapath, ps, svcs)
    _warm(dp)
    before = (dp.cache_stats(), dp.stats().ingress,
              sorted((f["src"], f["sport"])
                     for f in dp.dump_flows(now=next(_NOW))))
    dp.audit_scan(now=next(_NOW), full=True)
    after = (dp.cache_stats(), dp.stats().ingress,
             sorted((f["src"], f["sport"])
                    for f in dp.dump_flows(now=next(_NOW))))
    assert before[0] == after[0] and before[1] == after[1]
    assert before[2] == after[2]


# The scrub-coverage gate (tools/check_audit_plane.py -> analysis pass
# `audit-plane`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.


def test_policy_capacity_error_is_typed():
    """check_rule_capacity raises the typed PolicyCapacityError (still a
    ValueError for pre-existing callers)."""
    from types import SimpleNamespace

    cps = SimpleNamespace(ingress=SimpleNamespace(n_rules=0xFFFE),
                          egress=SimpleNamespace(n_rules=3))
    with pytest.raises(pl.PolicyCapacityError):
        pl.check_rule_capacity(cps)
    with pytest.raises(ValueError):
        pl.check_rule_capacity(cps)


def test_poison_bundle_reports_failed_and_stops_hot_retrying():
    """A deterministic compile rejection (PolicyCapacityError) is
    classified PERMANENT: one attempt, a Failed realization reported
    upstream with the reason, and NO retry storm — until new upstream
    state arrives, which earns exactly one fresh attempt.  Transient
    errors keep the existing backoff-retry discipline."""
    from antrea_tpu.agent.controller import AgentPolicyController

    class _PoisonDP:
        degraded = False

        def __init__(self, exc):
            self.calls = 0
            self.exc = exc

        def install_bundle(self, ps=None, services=None):
            self.calls += 1
            raise self.exc

    reports = []
    t = [0.0]
    dp = _PoisonDP(pl.PolicyCapacityError("too many rules"))
    agent = AgentPolicyController(
        "n1", dp, clock=lambda: t[0],
        status_reporter=lambda node, realized, failure="": reports.append(
            (node, failure)))
    agent._rules_dirty = True
    for _ in range(8):
        t[0] += 10.0  # far past any backoff window
        agent.sync()
    assert dp.calls == 1, "poison bundle must not hot-retry"
    assert agent.sync_failures_total == 1
    assert "too many rules" in agent.permanent_failure
    assert any("too many rules" in f for _n, f in reports)

    # New upstream state clears the quarantine: exactly one new attempt.
    policy = cp.NetworkPolicy(uid="P9", name="P9",
                              type=cp.NetworkPolicyType.ACNP,
                              applied_to_groups=[], rules=[],
                              tier_priority=250, priority=1.0)
    agent.handle_event(WatchEvent(kind="ADDED", obj_type="NetworkPolicy",
                                  name="P9", obj=policy))
    assert agent.permanent_failure == ""
    t[0] += 10.0
    agent.sync()
    assert dp.calls == 2

    # Contrast: a TRANSIENT error keeps retrying with backoff.
    dp2 = _PoisonDP(RuntimeError("flaky install"))
    agent2 = AgentPolicyController("n2", dp2, clock=lambda: t[0])
    agent2._rules_dirty = True
    for _ in range(4):
        t[0] += 10.0
        agent2.sync()
    assert dp2.calls == 4 and agent2.permanent_failure == ""


# ---------------------------------------------------------------------------
# API + antctl + metrics surface
# ---------------------------------------------------------------------------


def test_audit_api_route_and_forced_sweep_and_antctl(capsys):
    """GET /audit serves the plane's status; ?force=1 runs a synchronous
    full sweep; `antctl audit --server URL --force` drives it end to end;
    the new metric families render and carry the scan counts."""
    import urllib.request

    from antrea_tpu.agent.apiserver import AgentApiServer
    from antrea_tpu.antctl import main as antctl_main
    from antrea_tpu.observability.metrics import render_metrics

    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs)
    _warm(dp)
    srv = AgentApiServer(dp, node="n1").start()
    try:
        body = json.loads(urllib.request.urlopen(
            srv.address + "/audit").read())
        assert {"cursor", "coverage_ratio", "last_divergence",
                "scans_total"} <= set(body)
        forced = json.loads(urllib.request.urlopen(
            srv.address + "/audit?force=1&now=9").read())
        assert forced["sweeps_total"] >= 1
        assert forced["last_scan"]["full"] is True

        rc = antctl_main(["audit", "--server", srv.address, "--force"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["sweeps_total"] >= 2
    finally:
        srv.close()

    text = render_metrics(dp, node="n1")
    assert "antrea_tpu_cache_audit_scans_total" in text
    assert "antrea_tpu_audit_cursor_coverage_ratio" in text
    assert "antrea_tpu_tensor_scrub_total" in text


# ---------------------------------------------------------------------------
# Full reachability fixtures (the acceptance's fixture sweep; slow tier)
# ---------------------------------------------------------------------------


def _fixture_probe(p):
    from fixtures_reachability import _ip

    return Packet(src_ip=iputil.ip_to_u32(_ip(p.src)),
                  dst_ip=iputil.ip_to_u32(_ip(p.dst)),
                  proto=p.proto, src_port=p.sport + next(_SPORT) % 10000,
                  dst_port=p.dport)


def _fixture_sweep(dp_cls, scenarios):
    for si, scenario in enumerate(scenarios):
        kw = {"miss_chunk": 8} if dp_cls is TpuflowDatapath else {}
        dp = dp_cls(scenario.ps, [], **SMALL, **kw)
        probes = [_fixture_probe(p) for p in scenario.probes]
        L = max(8, len(probes))  # stable lane count: one compile per meta
        dp.step(PacketBatch.from_packets((probes * L)[:L]), next(_NOW))
        dp.audit_scan(now=next(_NOW))  # anchor
        dp._audit_corrupt("verdict" if si % 2 == 0 else "tensor")
        out = dp.audit_scan(now=next(_NOW))  # digest -> forced full sweep
        assert out["full"], (scenario.name, out)
        # Post-repair: fresh-sport probes re-prove the fixture's expected
        # verdicts — zero mismatches vs the hand-authored truth table.
        fresh = [_fixture_probe(p) for p in scenario.probes]
        codes = np.asarray(dp.step(PacketBatch.from_packets(
            (fresh * L)[:L]), next(_NOW)).code)
        bad = [(scenario.name, p.src, p.dst, "expected", p.expect, "got",
                int(codes[i]))
               for i, p in enumerate(scenario.probes)
               if int(codes[i]) != p.expect]
        assert not bad, bad
        quiet = dp.audit_scan(now=next(_NOW), full=True)
        assert quiet["divergences"] == 0, (scenario.name, quiet)


@pytest.mark.slow
def test_fixture_sweep_oracle_engine():
    from fixtures_reachability import SCENARIOS

    _fixture_sweep(OracleDatapath, SCENARIOS)


@pytest.mark.slow
def test_fixture_sweep_tpuflow_engine():
    from fixtures_reachability import SCENARIOS

    _fixture_sweep(TpuflowDatapath, SCENARIOS)
