"""Controller scale tests: the analog of the reference's
networkpolicy_controller_perf_test.go:46-52 (TestInitXLargeScale*: full NP
compute over 25k namespaces / 100k pods / 75k NPs in 5.84-6.42s) at a
CI-friendly scale, plus the property the round-2 verdict demanded: pod-churn
cost independent of total policy count.

The full-scale run lives in bench_controller.py (same workload shape as the
reference test, 100k pods / 75k NPs); this file keeps the suite fast while
still exercising the same code paths at 10k/7.5k.
"""

import time

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis.crd import Pod
from antrea_tpu.controller.networkpolicy import NetworkPolicyController

# Single source of truth for the xLargeScale workload builder: the
# full-scale benchmark script at the repo root.
from bench_controller import populate as _populate


def test_full_compute_10k_pods():
    """2.5k namespaces x 4 pods x 3 NPs == 10k pods / 7.5k NPs: the
    reference computes 10x this in ~6s (Go); the Python control plane must
    land within a usable envelope and produce the right group structure."""
    ctrl = NetworkPolicyController()
    events = []
    ctrl.subscribe(events.append)
    t0 = time.perf_counter()
    _populate(ctrl, n_ns=2500, pods_per_ns=4, nps_per_ns=3)
    wall = time.perf_counter() - t0
    ps = ctrl.policy_set()
    assert len(ps.policies) == 7500
    # Selectors are content-addressed per namespace: 2 app selectors per
    # namespace appear in both ATG (applied) and AG (peer) roles.
    assert len(ps.applied_to_groups) == 5000
    assert len(ps.address_groups) == 5000
    # Regression gate with teeth (round-3 verdict weak #6): this computes in
    # well under 10s on the CI machine; 15s catches any real (>~2x) perf
    # regression instead of waving a 10x one through.  Reference context:
    # 5.84-6.42s for 10x this workload (xLargeScale).
    assert wall < 15, f"full compute took {wall:.1f}s (regression gate)"
    print(f"\nfull-compute 10k pods/7.5k NPs: {wall:.2f}s, "
          f"{len(events)} events")


def _churn_cost(n_ns: int, reps: int = 50) -> float:
    ctrl = NetworkPolicyController()
    _populate(ctrl, n_ns=n_ns, pods_per_ns=4, nps_per_ns=3)
    # Steady-state churn: re-upsert one pod with a changed IP (same labels,
    # same bucket) and add/remove a pod in an existing bucket.
    t0 = time.perf_counter()
    for r in range(reps):
        ctrl.upsert_pod(Pod(
            name="pod-0", namespace="ns-0", labels={"app": "app-0"},
            ip=f"10.99.0.{r + 1}", node="node-0",
        ))
    return (time.perf_counter() - t0) / reps


def test_pod_churn_independent_of_policy_count():
    """Round-2 verdict weak #4: pod churn must not scan every policy.  The
    per-event cost at 8x the policy count must stay within a small factor
    (reverse indexes make it O(groups-of-bucket + referencing policies))."""
    small = _churn_cost(n_ns=100)
    large = _churn_cost(n_ns=800)
    # Allow generous noise; before the reverse-index fix this ratio was ~8x
    # (linear in policies), after it is ~1x.
    assert large < small * 4 + 2e-3, (
        f"churn cost grew with policy count: {small * 1e6:.0f}us -> "
        f"{large * 1e6:.0f}us"
    )
    print(f"\nchurn cost: {small * 1e6:.0f}us @100ns vs {large * 1e6:.0f}us @800ns")
