"""NetworkPolicy realization-status aggregation tests
(status_controller.go:270 syncHandler semantics; VERDICT round-3 item 4).

The headline scenario: a policy reads partially-realized (Realizing) while
one fleet agent lags, and Realized once every spanned agent catches up.
"""

from antrea_tpu.apis import crd
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.controller.status import (
    PHASE_FAILED,
    PHASE_REALIZED,
    PHASE_REALIZING,
    StatusAggregator,
)
from antrea_tpu.dissemination import RamStore
from antrea_tpu.simulator.fleet import FakeAgentFleet

N_NODES = 6


def _world():
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agg = StatusAggregator(ctl)
    nodes = [f"node-{i}" for i in range(N_NODES)]
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    for ni, node in enumerate(nodes):
        ctl.upsert_pod(crd.Pod(
            namespace="default", name=f"pod-{ni}", ip=f"10.0.{ni}.1",
            node=node, labels={"app": "web"},
        ))
    return ctl, store, agg, nodes


def _policy(uid="p1", prio=1.0):
    return crd.AntreaNetworkPolicy(
        uid=uid, name=uid, namespace="", tier_priority=250, priority=prio,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP)],
    )


def test_realizing_while_one_agent_lags_then_realized():
    ctl, store, agg, nodes = _world()
    fleet = FakeAgentFleet(store, nodes,
                           status_reporter=agg.make_agent_reporter())
    ctl.upsert_antrea_policy(_policy())

    # Pump every agent EXCEPT the last: the policy spans all 6 nodes but
    # only 5 have realized the current generation.
    for node in nodes[:-1]:
        fleet.agents[node].pump()
    st = agg.status_of("p1")
    assert st.phase == PHASE_REALIZING
    assert st.desired_nodes == N_NODES
    assert st.current_nodes == N_NODES - 1
    assert st.observed_generation == 1

    # The laggard catches up -> Realized.
    fleet.agents[nodes[-1]].pump()
    st = agg.status_of("p1")
    assert st.phase == PHASE_REALIZED
    assert st.current_nodes == st.desired_nodes == N_NODES


def test_spec_update_resets_realization():
    ctl, store, agg, nodes = _world()
    fleet = FakeAgentFleet(store, nodes,
                           status_reporter=agg.make_agent_reporter())
    ctl.upsert_antrea_policy(_policy())
    fleet.pump()
    assert agg.status_of("p1").phase == PHASE_REALIZED

    # Spec change bumps the generation: stale node reports no longer count.
    ctl.upsert_antrea_policy(_policy(prio=2.0))
    st = agg.status_of("p1")
    assert st.phase == PHASE_REALIZING
    assert st.observed_generation == 2
    assert st.current_nodes == 0
    fleet.pump()
    assert agg.status_of("p1").phase == PHASE_REALIZED


def test_failure_and_span_shrink_and_delete():
    ctl, store, agg, nodes = _world()
    ctl.upsert_antrea_policy(_policy())
    gen = ctl.np_realization_view()["p1"][0]
    # All nodes report the current generation; one reports failure.
    for node in nodes[:-1]:
        agg.update_status("p1", node, gen)
    agg.update_status("p1", nodes[-1], gen, failure=True, message="boom")
    st = agg.status_of("p1")
    assert st.phase == PHASE_FAILED
    assert st.failed_nodes == [nodes[-1]]
    assert st.current_nodes == N_NODES - 1

    # The failing node's pod moves away: span shrinks, status drops, the
    # policy becomes Realized on the remaining span.
    ctl.delete_pod(f"default/pod-{N_NODES - 1}")
    st = agg.status_of("p1")
    assert st.desired_nodes == N_NODES - 1
    assert st.phase == PHASE_REALIZED

    # Deletion clears everything.
    ctl.delete_policy("p1")
    assert agg.status_of("p1") is None
    assert agg.all_statuses() == []


def test_zero_span_policy_is_realized():
    """A processed policy with a zero-node span is Realized, not Pending:
    syncHandler yields Realized when currentNodes == desiredNodes == 0 and
    reserves Pending for unprocessed policies (status_controller.go:303-343)."""
    ctl = NetworkPolicyController()
    agg = StatusAggregator(ctl)
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ctl.upsert_antrea_policy(_policy())  # no pods -> empty span
    st = agg.status_of("p1")
    assert st.phase == PHASE_REALIZED
    assert st.desired_nodes == 0


def test_real_agent_reports_through_sync():
    """AgentPolicyController (the real agent) reports after a successful
    datapath apply — wire a store-watched agent with an OracleDatapath."""
    from antrea_tpu.agent.controller import AgentPolicyController
    from antrea_tpu.datapath import OracleDatapath

    ctl, store, agg, nodes = _world()
    agent = AgentPolicyController(
        nodes[0], OracleDatapath(), store=store,
        status_reporter=agg.make_agent_reporter(),
    )
    ctl.upsert_antrea_policy(_policy())
    agent.sync()
    st = agg.status_of("p1")
    assert st.current_nodes == 1  # this agent realized the current gen
    assert st.phase == PHASE_REALIZING  # the other 5 span nodes lag


def test_subprocess_agent_realization_report():
    """The report crosses the process boundary: the subprocess agent's sync
    response carries {uid: generation} and the parent relays it."""
    from antrea_tpu.dissemination.transport import SubprocessAgent

    ctl, store, agg, nodes = _world()
    with SubprocessAgent(nodes[0], store) as sub:
        ctl.upsert_antrea_policy(_policy())
        sub.pump()
        resp = sub.sync()
        agg.update_node_statuses(nodes[0], resp["realized"])
        st = agg.status_of("p1")
        assert st.current_nodes == 1
        assert resp["realized"] == {"p1": 1}


def test_controller_info_surfaces_realization():
    from antrea_tpu.observability.agentinfo import collect_controller_info

    ctl, store, agg, nodes = _world()
    fleet = FakeAgentFleet(store, nodes,
                           status_reporter=agg.make_agent_reporter())
    ctl.upsert_antrea_policy(_policy())
    fleet.pump()
    info = collect_controller_info(ctl, store=store, status=agg, now=1)
    real = info["networkPolicyRealization"]
    assert real["realized"] == real["total"] == 1
    assert real["policies"][0]["phase"] == PHASE_REALIZED


def test_antctl_surfaces_policystatus():
    """VERDICT item 4 'antctl surfaces it': the controller api server
    serves /policystatus and antctl renders it in live mode."""
    import json as _json
    import subprocess
    import sys

    from antrea_tpu.controller.apiserver import ControllerApiServer

    ctl, store, agg, nodes = _world()
    fleet = FakeAgentFleet(store, nodes,
                           status_reporter=agg.make_agent_reporter())
    ctl.upsert_antrea_policy(_policy())
    for node in nodes[:-1]:
        fleet.agents[node].pump()  # one agent lags -> Realizing
    srv = ControllerApiServer(ctl, store=store, status=agg).start()
    try:
        url = f"http://{srv.address[0]}:{srv.address[1]}"
        out = subprocess.run(
            [sys.executable, "-m", "antrea_tpu.antctl", "get",
             "policystatus", "--server", url],
            capture_output=True, text=True, timeout=60, check=True,
        )
        body = _json.loads(out.stdout)
        [row] = body["items"]
        assert row["phase"] == PHASE_REALIZING
        assert row["currentNodesRealized"] == N_NODES - 1
        assert row["desiredNodesRealized"] == N_NODES
        # controllerinfo route carries the same summary.
        out = subprocess.run(
            [sys.executable, "-m", "antrea_tpu.antctl", "get",
             "controllerinfo", "--server", url],
            capture_output=True, text=True, timeout=60, check=True,
        )
        info = _json.loads(out.stdout)
        assert info["networkPolicyRealization"]["total"] == 1
    finally:
        srv.stop()
