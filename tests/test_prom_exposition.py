"""Strict Prometheus text-exposition validation of every render_* output
(ISSUE 2 satellite): TYPE before samples, one TYPE per family, proper
label syntax/escaping, no duplicate series, histogram bucket monotonicity
with le="+Inf" == _count — the metric-name drift check (analysis pass
`metrics`) runs once in tests/test_static_analysis.py
riding tier-1."""

import re
import sys
from pathlib import Path
from types import SimpleNamespace

import numpy as np

from antrea_tpu.agent.controller import AgentPolicyController
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.dissemination.store import RamStore
from antrea_tpu.observability import Histogram, render_metrics
from antrea_tpu.observability.metrics import (
    METRICS,
    render_controller_metrics,
    render_dissemination_metrics,
)
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\[\\"n])*)"')
_SAMPLE = re.compile(rf"^({_NAME})(?:\{{(.*)\}})? (\S+)$")
_TYPE = re.compile(
    rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary|untyped)$"
)
_SUFFIXES = ("_bucket", "_sum", "_count")


def _family(name: str, families: dict) -> str:
    if name in families:
        return name
    for suf in _SUFFIXES:
        base = name[: -len(suf)] if name.endswith(suf) else None
        if base in families:
            assert families[base] == "histogram", (
                f"{name}: sample suffix on non-histogram family {base}"
            )
            return base
    raise AssertionError(f"sample {name!r} has no preceding # TYPE")


def parse_exposition(text: str):
    """Strict parse -> (families {name: type},
    per_family {family: {(sample_name, labels): value}}).
    AssertionError on any format violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict[str, str] = {}
    per_family: dict[str, dict] = {}
    seen: set = set()
    for line in text.splitlines():
        assert line == line.strip() and line, f"bad line: {line!r}"
        if line.startswith("#"):
            m = _TYPE.match(line)
            assert m, f"malformed comment (only # TYPE allowed): {line!r}"
            name, typ = m.groups()
            assert name not in families, f"duplicate TYPE for {name}"
            families[name] = typ
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, label_body, value = m.groups()
        fam = _family(name, families)  # TYPE-before-samples enforced here
        labels: tuple = ()
        if label_body is not None:
            assert label_body, f"empty label braces: {line!r}"
            parts = _LABEL.findall(label_body)
            reconstructed = ",".join(f'{k}="{v}"' for k, v in parts)
            assert reconstructed == label_body, (
                f"bad label syntax/escaping: {label_body!r}"
            )
            keys = [k for k, _v in parts]
            assert len(keys) == len(set(keys)), f"duplicate label: {line!r}"
            labels = tuple(parts)
        key = (name, labels)
        assert key not in seen, f"duplicate series: {line!r}"
        seen.add(key)
        per_family.setdefault(fam, {})[key] = float(value)
    _check_histograms(families, per_family)
    return families, per_family


def _check_histograms(families: dict, per_family: dict) -> None:
    for fam, typ in families.items():
        if typ != "histogram" or fam not in per_family:
            continue
        rows = per_family[fam]
        # Group by the non-le label set.
        by_series: dict[tuple, dict] = {}
        for (name, labels), value in rows.items():
            base_labels = tuple(kv for kv in labels if kv[0] != "le")
            s = by_series.setdefault(base_labels, {"buckets": [], })
            if name == fam + "_bucket":
                le = dict(labels)["le"]
                s["buckets"].append((le, value))
            elif name == fam + "_sum":
                s["sum"] = value
            elif name == fam + "_count":
                s["count"] = value
            else:
                raise AssertionError(f"stray histogram sample {name}")
        for base_labels, s in by_series.items():
            assert "sum" in s and "count" in s, (
                f"{fam}{dict(base_labels)}: missing _sum/_count"
            )
            assert s["buckets"], f"{fam}: no buckets"
            les = [le for le, _v in s["buckets"]]
            assert les[-1] == "+Inf", f"{fam}: last bucket must be +Inf"
            finite = [float(le) for le in les[:-1]]
            assert finite == sorted(finite), f"{fam}: le not ascending"
            counts = [v for _le, v in s["buckets"]]
            assert counts == sorted(counts), (
                f"{fam}: bucket counts not monotonic: {counts}"
            )
            assert counts[-1] == s["count"], (
                f"{fam}: +Inf bucket ({counts[-1]}) != _count ({s['count']})"
            )


# -- fixtures ----------------------------------------------------------------

SLOTS = 1 << 10


def _deny_ps() -> PolicySet:
    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        # A member name with label-hostile characters exercises escaping
        # via the rule-id label.
        "atg", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid='deny "q" \\ backslash', name="deny-in",
        type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg"], tier_priority=cp.TIER_APPLICATION,
        priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN, action=cp.RuleAction.REJECT,
            priority=0,
        )],
    ))
    return ps


def _batch():
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32("10.0.0.5")] * 2, np.uint32),
        dst_ip=np.array([iputil.ip_to_u32("10.0.0.10"),
                         iputil.ip_to_u32("10.0.0.99")], np.uint32),
        proto=np.array([6, 6], np.int32),
        src_port=np.array([41000, 41001], np.int32),
        dst_port=np.array([80, 80], np.int32),
        pkt_len=np.array([100, 200], np.int32),
    )


# -- tests -------------------------------------------------------------------

def test_histogram_primitive():
    h = Histogram(bounds=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 5
    assert abs(h.sum - 5.605) < 1e-12
    assert h.bucket_counts() == [1, 3, 4, 5]
    fams, per = parse_exposition(
        "# TYPE antrea_tpu_datapath_step_seconds histogram\n"
        + "\n".join(h.sample_lines("antrea_tpu_datapath_step_seconds",
                                   node="n0")) + "\n"
    )
    assert fams["antrea_tpu_datapath_step_seconds"] == "histogram"


def test_parser_rejects_violations():
    import pytest

    good_type = "# TYPE antrea_tpu_flow_cache_slots gauge\n"
    with pytest.raises(AssertionError):  # sample before TYPE
        parse_exposition("antrea_tpu_flow_cache_slots 3\n")
    with pytest.raises(AssertionError):  # duplicate series
        parse_exposition(good_type + "antrea_tpu_flow_cache_slots 3\n" * 2)
    with pytest.raises(AssertionError):  # duplicate TYPE
        parse_exposition(good_type * 2)
    with pytest.raises(AssertionError):  # broken escaping
        parse_exposition(
            good_type + 'antrea_tpu_flow_cache_slots{node="a"b"} 3\n'
        )
    with pytest.raises((AssertionError, ValueError)):  # malformed value
        parse_exposition(good_type + "antrea_tpu_flow_cache_slots x\n")
    with pytest.raises(AssertionError):  # non-monotonic histogram
        parse_exposition(
            "# TYPE antrea_tpu_agent_sync_seconds histogram\n"
            'antrea_tpu_agent_sync_seconds_bucket{le="0.1"} 5\n'
            'antrea_tpu_agent_sync_seconds_bucket{le="+Inf"} 3\n'
            "antrea_tpu_agent_sync_seconds_sum 1.0\n"
            "antrea_tpu_agent_sync_seconds_count 3\n"
        )


def test_datapath_render_is_strictly_valid():
    """Both datapath engines' scrapes parse strictly, with and without the
    node label, including rule-id escaping and the step histogram."""
    ps = _deny_ps()
    for dp in (
        TpuflowDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8,
                        miss_chunk=16),
        OracleDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8),
    ):
        dp.step(_batch(), now=1)
        for node in ("n0", ""):
            fams, per = parse_exposition(render_metrics(dp, node=node))
            for fam, typ in fams.items():
                assert METRICS.get(fam) == typ, f"unregistered family {fam}"
            assert "antrea_tpu_rule_packets_total" in per
            assert "antrea_tpu_rule_bytes_total" in per  # lens were carried
            assert "antrea_tpu_datapath_step_seconds" in per


def test_controller_render_is_strictly_valid():
    ctl = NetworkPolicyController()
    store = RamStore()
    fams, _per = parse_exposition(render_controller_metrics(ctl, store))
    for fam, typ in fams.items():
        assert METRICS.get(fam) == typ


def test_dissemination_render_is_strictly_valid():
    """Real AgentPolicyController (sync + dissemination histograms live)
    plus a fake server snapshot — the full dissemination scrape parses
    strictly and the latency histograms carry observations."""

    class _Srv:
        def dissemination_stats(self):
            return {
                "watchers": {
                    'no"de': {"pending": 3, "overflows": 1,
                              "needs_resync": True},
                    "n2": {"pending": 0, "overflows": 0,
                           "needs_resync": False},
                },
                "resyncs_total": 4,
                "reconnects_total": 2,
            }

    store = RamStore()
    agent_dp = OracleDatapath(flow_slots=SLOTS, aff_slots=1 << 8)
    agent = AgentPolicyController("n1", agent_dp, store)
    # Drive the agent through the store directly: a stamped event ->
    # pending work -> successful sync observes both histograms.
    from antrea_tpu.controller.networkpolicy import WatchEvent

    store.apply(WatchEvent(
        kind="ADDED", obj_type="AppliedToGroup", name="atg",
        obj=cp.AppliedToGroup("atg", [cp.GroupMember(ip="10.0.0.10",
                                                     node="n1")]),
        span={"n1"},
    ))
    agent.sync()
    assert agent.sync_hist.count >= 1
    assert agent.dissemination_hist.count >= 1
    wire = SimpleNamespace(node="n2", reconnects_total=2, resyncs_total=3,
                           agent=SimpleNamespace(sync_failures_total=5))
    text = render_dissemination_metrics(_Srv(), [agent, wire])
    fams, per = parse_exposition(text)
    for fam, typ in fams.items():
        assert METRICS.get(fam) == typ
    assert "antrea_tpu_agent_sync_seconds" in per
    assert "antrea_tpu_dissemination_latency_seconds" in per
    # Escaped node label survived round-trip.
    assert 'no\\"de' in text
    # Agent-only scrape still parses.
    parse_exposition(render_dissemination_metrics(None, [agent]))


# The metric-name drift gate (tools/check_metrics.py -> analysis pass
# `metrics`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.
