"""Breadth round 2: FQDN feedback loop, Egress + consistent-hash
ownership, flow export/aggregation."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.fqdn import FqdnController, fqdn_matches
from antrea_tpu.agent.memberlist import ConsistentHash, MemberlistCluster
from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    AntreaAppliedTo,
    AntreaNetworkPolicy,
    AntreaNPRule,
    AntreaPeer,
    LabelSelector,
    Namespace,
    Pod,
)
from antrea_tpu.controller import NetworkPolicyController
from antrea_tpu.controller.egress import (
    EgressController,
    EgressPolicy,
    build_egress_table,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.observability.flowexport import FlowAggregator, FlowExporter
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def _probe(dp, src, dst, dport=443, now=10, proto=6, sport=40000):
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([proto], np.int32),
        src_port=np.array([sport], np.int32),
        dst_port=np.array([dport], np.int32),
    )
    return dp.step(b, now)


def test_fqdn_match_semantics():
    assert fqdn_matches("example.com", "EXAMPLE.com.")
    assert not fqdn_matches("example.com", "a.example.com")
    assert fqdn_matches("*.example.com", "a.example.com")
    assert fqdn_matches("*.example.com", "b.a.example.com")
    assert not fqdn_matches("*.example.com", "example.com")


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_fqdn_feedback_loop(dp_cls):
    """An FQDN egress rule starts empty; DNS observations (the packet-in
    feedback) populate it via incremental deltas; TTL expiry removes the
    learned addresses (fqdn.go model)."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(Pod(namespace="default", name="c", ip="10.0.0.5",
                       node="n0", labels={"app": "cli"}))
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="block-bad", name="block-bad", priority=1.0,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"app": "cli"}))],
        rules=[AntreaNPRule(
            direction=Direction.OUT, action=RuleAction.DROP,
            peers=[AntreaPeer(fqdn="*.bad.example")],
        )],
    ))
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    if dp_cls is TpuflowDatapath:
        kw["miss_chunk"] = 16
    dp = dp_cls(ctl.policy_set(), [], **kw)
    fq = FqdnController(dp)
    fq.configure(ctl.policy_set())

    bad_ip = "203.0.113.7"
    # Before any DNS observation the learned set is empty: allowed.
    assert int(_probe(dp, "10.0.0.5", bad_ip, now=1).code[0]) == 0
    # evil.bad.example resolves to bad_ip -> the group learns it.
    n = fq.observe_dns("evil.bad.example", [bad_ip], ttl_s=30, now=2)
    assert n == 1
    r = _probe(dp, "10.0.0.5", bad_ip, now=3, sport=40001)
    assert int(r.code[0]) == 1
    assert r.egress_rule[0] == "block-bad/Out/0"
    # A non-matching name changes nothing.
    assert fq.observe_dns("good.example", ["198.51.100.9"], 30, now=4) == 0
    # TTL expiry removes the learned address; new flows pass again.
    assert fq.tick(now=40) == 1
    assert int(_probe(dp, "10.0.0.5", bad_ip, now=41, sport=40002).code[0]) == 0


def test_consistent_hash_stability_and_failover():
    """Ownership is deterministic across agents, stable under unrelated
    churn, and moves ONLY for keys owned by a departed node (the Egress
    failover property, cluster.go:89 + consistenthash)."""
    nodes = [f"node-{i}" for i in range(5)]
    clusters = [MemberlistCluster(n) for n in nodes]
    for c in clusters:
        for n in nodes:
            c.join(n)
    keys = [f"10.10.{i}.{j}" for i in range(4) for j in range(16)]
    owners = {k: clusters[0].owner_of(k) for k in keys}
    # Every agent elects the same owner; exactly one owner claims each key.
    for k in keys:
        assert all(c.owner_of(k) == owners[k] for c in clusters)
        assert sum(c.should_own(k) for c in clusters) == 1
    # Spread: every node owns something at 64 keys / 5 nodes.
    assert len(set(owners.values())) == 5

    # node-2 dies: only its keys move; everyone re-elects identically.
    events = []
    clusters[0].add_event_handler(lambda alive: events.append(set(alive)))
    for c in clusters:
        c.leave("node-2")
    assert events and "node-2" not in events[-1]
    for k in keys:
        new = clusters[0].owner_of(k)
        assert all(c.owner_of(k) == new for c in clusters)
        if owners[k] != "node-2":
            assert new == owners[k], "unrelated ownership must not move"
        else:
            assert new != "node-2"


def test_egress_assignment_and_table():
    from antrea_tpu.controller.grouping import GroupEntityIndex

    index = GroupEntityIndex()
    ctl = EgressController(index)
    changes = []
    ctl.subscribe(lambda: changes.append(1))
    index.upsert_namespace(Namespace("prod", {}))
    index.upsert_pod(Pod(namespace="prod", name="a", ip="10.0.0.1",
                         node="n0", labels={"team": "x"}))
    index.upsert_pod(Pod(namespace="prod", name="b", ip="10.0.0.2",
                         node="n1", labels={"team": "y"}))
    ctl.upsert(EgressPolicy("eg-x", "172.16.0.10",
                            pod_selector=LabelSelector.make({"team": "x"})))
    ctl.upsert(EgressPolicy("eg-y", "172.16.0.11",
                            pod_selector=LabelSelector.make({"team": "y"})))
    asg = ctl.assignments()
    assert asg == [("10.0.0.1", "172.16.0.10", "eg-x"),
                   ("10.0.0.2", "172.16.0.11", "eg-y")]

    table = build_egress_table(asg)
    assert table.egress_ip_for(iputil.ip_to_u32("10.0.0.1")) == "172.16.0.10"
    assert table.egress_ip_for(iputil.ip_to_u32("10.0.0.2")) == "172.16.0.11"
    assert table.egress_ip_for(iputil.ip_to_u32("10.0.0.3")) is None

    # Pod churn re-notifies (the agent rebuilds its table).
    n = len(changes)
    index.upsert_pod(Pod(namespace="prod", name="c", ip="10.0.0.3",
                         node="n0", labels={"team": "x"}))
    assert len(changes) > n
    assert build_egress_table(ctl.assignments()).egress_ip_for(
        iputil.ip_to_u32("10.0.0.3")) == "172.16.0.10"
    ctl.delete("eg-x")
    assert build_egress_table(ctl.assignments()).egress_ip_for(
        iputil.ip_to_u32("10.0.0.1")) is None


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_flow_export_and_aggregation(dp_cls):
    """Conntrack-poll export: new connections export once, the reply leg
    correlates into one biflow, idle-ended connections emit a final
    record (flowexporter -> flowaggregator model)."""
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8, ct_timeout_s=50)
    if dp_cls is TpuflowDatapath:
        kw["miss_chunk"] = 16
    dp = dp_cls(None, [], **kw)
    agg = FlowAggregator()
    exp = FlowExporter(dp, node="n0", active_timeout_s=60, sink=agg.ingest,
                       keep_records=True)

    _probe(dp, "10.0.0.5", "10.0.0.80", dport=80, now=1)
    n = exp.poll(now=2)
    assert n == 2  # fwd + reply conntrack entries -> one new record each
    # Reply leg arrives; no NEW records on re-poll (same connection).
    _probe(dp, "10.0.0.80", "10.0.0.5", dport=40000, sport=80, now=3)
    assert exp.poll(now=4) == 0
    bi = agg.snapshot()
    assert len(bi) == 1 and bi[0]["reply_seen"]
    assert bi[0]["src"] == "10.0.0.5" and bi[0]["dst"] == "10.0.0.80"

    # Idle out: the end record is emitted with reason=idle-end, and the
    # aggregator evicts the correlated biflow (bounded table).
    n = exp.poll(now=120)
    assert n == 2
    ends = [r for r in exp.records if r["event"] == "end"]
    assert len(ends) == 2 and all(r["reason"] == "idle-end" for r in ends)
    assert agg.snapshot() == []


def test_fqdn_membership_survives_bundle():
    """A structural bundle resets fqdn-- groups to the central (empty)
    state; configure() must restore the per-node learned membership, or
    FQDN deny rules fail open (review repro)."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(Pod(namespace="default", name="c", ip="10.0.0.5",
                       node="n0", labels={"app": "cli"}))
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="block-bad", name="block-bad", priority=1.0,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"app": "cli"}))],
        rules=[AntreaNPRule(
            direction=Direction.OUT, action=RuleAction.DROP,
            peers=[AntreaPeer(fqdn="*.bad.example")],
        )],
    ))
    dp = TpuflowDatapath(ctl.policy_set(), [], flow_slots=1 << 10,
                         aff_slots=1 << 8, miss_chunk=16)
    fq = FqdnController(dp)
    fq.configure(ctl.policy_set())
    fq.observe_dns("evil.bad.example", ["203.0.113.7"], ttl_s=1000, now=1)
    assert int(_probe(dp, "10.0.0.5", "203.0.113.7", now=2).code[0]) == 1

    # Unrelated policy change -> agent does a structural bundle + configure.
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="other", name="other", priority=9.0,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"app": "zzz"}))],
        rules=[AntreaNPRule(direction=Direction.IN, action=RuleAction.ALLOW)],
    ))
    dp.install_bundle(ps=ctl.policy_set())
    fq.configure(ctl.policy_set())
    r = _probe(dp, "10.0.0.5", "203.0.113.7", now=3, sport=40009)
    assert int(r.code[0]) == 1, "learned FQDN membership must survive bundles"


def test_flow_dump_high_ips_and_reply_first_aggregation():
    """dump_flows must decode IPs >= 128.0.0.0 (numpy-2 uint32 bounds;
    review repro), and the aggregator must produce forward-oriented
    biflows regardless of which direction dumps first."""
    dp = TpuflowDatapath(None, [], flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=16)
    _probe(dp, "192.168.1.1", "203.0.113.250", dport=443, now=1)
    flows = dp.dump_flows(now=2)
    assert {f["src"] for f in flows} == {"192.168.1.1", "203.0.113.250"}

    # Reply-first ingestion: feed the records reply-leg first.
    agg = FlowAggregator()
    for rec in sorted(flows, key=lambda r: not r["reply"]):
        agg.ingest({**rec, "node": "n0", "event": "new"})
    bi = agg.snapshot()
    assert len(bi) == 1
    assert bi[0]["src"] == "192.168.1.1" and bi[0]["dst"] == "203.0.113.250"
    assert bi[0]["sport"] == 40000 and bi[0]["dport"] == 443
    assert bi[0]["reply_seen"] and not bi[0]["reply"]


def test_shared_index_group_survives_cross_controller_delete():
    """NP and Egress controllers share one grouping index; a content-
    addressed group used by BOTH must survive either controller's delete
    (owner-scoped index deletion; review repro: Egress delete froze the
    ACNP's group membership)."""
    ctl = NetworkPolicyController()
    ec = EgressController(ctl.index)
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(Pod(namespace="default", name="a", ip="10.0.0.1",
                       node="n0", labels={"team": "x"}))
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="drop-x", name="drop-x", priority=1.0,
        applied_to=[AntreaAppliedTo(
            pod_selector=LabelSelector.make({"team": "x"}),
            ns_selector=LabelSelector.make({}))],
        rules=[AntreaNPRule(direction=Direction.IN, action=RuleAction.DROP)],
    ))
    # Same content-addressed selector registered by the Egress controller.
    ec.upsert(EgressPolicy("eg-x", "172.16.0.10",
                           pod_selector=LabelSelector.make({"team": "x"}),
                           ns_selector=LabelSelector.make({})))
    ec.delete("eg-x")  # must NOT delete the NP's group from the index
    ctl.upsert_pod(Pod(namespace="default", name="b", ip="10.0.0.2",
                       node="n0", labels={"team": "x"}))
    atg = next(iter(ctl.policy_set().applied_to_groups.values()))
    assert {m.ip for m in atg.members} == {"10.0.0.1", "10.0.0.2"}, (
        "new pod must keep flowing into the shared group after egress delete"
    )
