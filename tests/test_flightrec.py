"""Realization tracing + flight recorder (ISSUE 8 tentpole).

The acceptance bar: an end-to-end realization span for a policy churn
event covers controller-commit -> first live hit with every stage >= 0
and the stages summing EXACTLY to the end-to-end latency, on both
engines (oracle parity of the span STRUCTURE); the PR 4
miscompile-rollback and PR 5 cache-corruption chaos cases are
reconstructable from the flight recorder ALONE (full causal chain in
sequence order: injected fault -> canary mismatch -> rollback ->
degrade -> recompile -> recover); the ring drops OLDEST under overflow
and never blocks; fast-path step HLO is bit-identical with the plane
enabled; the API/antctl/supportbundle surfaces serve it; and
tools/check_events.py + tools/check_metrics.py hold the schema, the
emit sites, the stage labels and the README tables together.
"""

import itertools
import json
import sys
from pathlib import Path

import numpy as np
import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.networkpolicy import WatchEvent
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.datapath.commit import CanaryMismatchError
from antrea_tpu.dissemination import FaultPlan
from antrea_tpu.dissemination.faults import FlakyDatapath
from antrea_tpu.dissemination.store import RamStore
from antrea_tpu.observability.flightrec import EVENT_KINDS, FlightRecorder
from antrea_tpu.observability.metrics import (render_dissemination_metrics,
                                              render_metrics)
from antrea_tpu.observability.tracing import (REALIZATION_STAGES,
                                              RealizationTracer)
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

CLIENT, SRV, BLOCKED = "10.0.1.1", "10.0.0.10", "10.0.9.9"
VIP = "10.96.0.1"

_NOW = itertools.count(1000)
_SPORT = itertools.count(42000)

SMALL = dict(flow_slots=1 << 8, aff_slots=1 << 4)


def _world(cidr: str = "192.0.2.0/24", uid: str = "p1", gen: int = 1):
    ps = PolicySet(
        policies=[cp.NetworkPolicy(
            uid=uid, name=uid, type=cp.NetworkPolicyType.ACNP,
            generation=gen,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.IN,
                from_peer=cp.NetworkPolicyPeer(
                    address_groups=["blocked"],
                    ip_blocks=[cp.IPBlock(cidr=cidr)]),
                action=cp.RuleAction.DROP, priority=0)],
            applied_to_groups=["web"], tier_priority=250, priority=1.0)],
        address_groups={"blocked": cp.AddressGroup(
            name="blocked", members=[cp.GroupMember(ip=BLOCKED)])},
        applied_to_groups={"web": cp.AppliedToGroup(
            name="web", members=[cp.GroupMember(ip=SRV)])},
    )
    svcs = [ServiceEntry(cluster_ip=VIP, port=80, protocol=6, name="web",
                         namespace="default",
                         endpoints=[Endpoint(ip=SRV, port=8080)])]
    return ps, svcs


def _dp(dp_cls, ps=None, svcs=None, **kw):
    if dp_cls is TpuflowDatapath:
        kw.setdefault("miss_chunk", 16)
    return dp_cls(ps, svcs, **SMALL, **kw)


def _fresh(src, dst=SRV, dport=80):
    return Packet(src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
                  proto=6, src_port=next(_SPORT), dst_port=dport)


def _fresh_parity(dp, ps, srcs=(BLOCKED, "192.0.2.7", CLIENT)) -> int:
    now = next(_NOW)
    pkts = [_fresh(s) for s in srcs]
    got = dp.step(PacketBatch.from_packets(pkts), now).code
    oracle = Oracle(ps)
    return sum(int(got[i]) != int(oracle.classify(p).code)
               for i, p in enumerate(pkts))


def _assert_chain(events: list, chain: list) -> list:
    """Assert `chain` — [(label, predicate)] — is a SUBSEQUENCE of the
    journal in sequence order; returns the matched events."""
    assert events == sorted(events, key=lambda e: e["seq"])
    matched, i = [], 0
    for label, pred in chain:
        while i < len(events) and not pred(events[i]):
            i += 1
        assert i < len(events), (
            f"causal chain broken: no {label!r} after "
            f"{[m['kind'] for m in matched]} in "
            f"{[(e['seq'], e['kind']) for e in events]}")
        matched.append(events[i])
        i += 1
    return matched


# ---------------------------------------------------------------------------
# Ring journal semantics
# ---------------------------------------------------------------------------


def test_ring_drop_oldest_under_overflow():
    """Overflow loses the OLDEST telemetry (drop-oldest, metered), never
    the newest, never blocking; seq stays monotonic across the wrap and
    per-kind counters survive it."""
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.emit(kind="epoch-swap", epoch=i)
    ev = rec.events()
    assert [e["ts"] for e in ev] == [0] * 8  # no clock wired yet
    assert [e["seq"] for e in ev] == list(range(12, 20))  # newest 8 kept
    assert [e["epoch"] for e in ev] == list(range(12, 20))
    st = rec.stats()
    assert st["dropped_total"] == 12 and st["seq"] == 20
    assert st["retained"] == 8
    assert st["kinds"]["epoch-swap"] == 20  # counter survives the wrap
    # tail/kind filters compose; tail=0 means NO events, not all of them
    # (a stats-only probe must not pull a full journal dump).
    assert [e["seq"] for e in rec.events(tail=3)] == [17, 18, 19]
    assert rec.events(tail=0) == [] and rec.events(tail=-2) == []
    assert rec.events(kind="rollback") == []


def test_emit_rejects_undeclared_kind_and_disabled_capacity():
    rec = FlightRecorder(capacity=4)
    with pytest.raises(ValueError, match="undeclared"):
        rec.emit(kind="not-a-kind")
    off = FlightRecorder(capacity=0)
    off.emit(kind="rollback")
    assert off.events() == [] and off.stats()["seq"] == 1


def test_recorder_timebase_is_the_maintenance_tick_clock():
    """Events stamp with the scheduler's tick clock — fault-injected
    time (FaultClock) drives the journal deterministically."""
    from antrea_tpu.dissemination.faults import FaultClock

    clk = FaultClock(start=50)
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, maint_clock=clk)
    dp.maintenance_tick()
    clk.advance(25)
    dp.maintenance_tick()
    ticks = dp.flightrecorder_events(kind="maint-tick")
    assert [e["ts"] for e in ticks] == [50, 75]


# ---------------------------------------------------------------------------
# The end-to-end realization span (acceptance: stages >= 0, exact sum,
# span-structure parity across engines)
# ---------------------------------------------------------------------------


def _drive_realization(dp_cls):
    """One policy churn event through store -> agent -> datapath -> live
    traffic; returns the closed span."""
    from antrea_tpu.agent.controller import AgentPolicyController

    store = RamStore()
    dp = _dp(dp_cls)
    agent = AgentPolicyController("n1", dp, store)
    ps, svcs = _world()
    dp.install_bundle(services=svcs)
    store.apply(WatchEvent(
        kind="ADDED", obj_type="AppliedToGroup", name="web",
        obj=ps.applied_to_groups["web"], span={"n1"}))
    store.apply(WatchEvent(
        kind="ADDED", obj_type="AddressGroup", name="blocked",
        obj=ps.address_groups["blocked"], span={"n1"}))
    store.apply(WatchEvent(
        kind="ADDED", obj_type="NetworkPolicy", name="p1",
        obj=ps.policies[0], span={"n1"}))
    agent.sync()
    tr = dp.realization_tracer
    assert tr.stats()["awaiting_first_hit"] == 1  # bound, not yet hit
    # First LIVE packet on the new generation closes the span.
    out = dp.step(PacketBatch.from_packets([_fresh(BLOCKED)]), next(_NOW))
    assert int(out.code[0]) == 1  # the policy is really enforced
    spans = tr.spans(uid="p1")
    assert len(spans) == 1 and spans[0]["state"] == "closed"
    return spans[0], tr, dp


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_realization_span_end_to_end(dp_cls):
    span, tr, dp = _drive_realization(dp_cls)
    assert span["generation"] == 1  # the spec generation the event carried
    assert span["bundle_generation"] == dp.generation
    stages = span["stages_s"]
    assert tuple(stages) == REALIZATION_STAGES  # order AND completeness
    assert all(v >= 0.0 for v in stages.values())
    # The telescoping invariant: stages sum EXACTLY to the end-to-end
    # latency (monotonic clamping happens at stamp time, not at diff
    # time, so no tolerance beyond float addition is needed).
    assert sum(stages.values()) == pytest.approx(span["total_s"], abs=1e-9)
    st = tr.stats()
    assert st["spans_closed_total"] == 1 and st["p99_s"] is not None
    # Every stage observed into the histograms exactly once.
    for s in (*REALIZATION_STAGES, "total"):
        assert tr.hist[s].count == 1
    # The journal closes the loop: one `realization` event, after the
    # commit's settle event, in sequence order.
    _assert_chain(dp.flightrecorder_events(), [
        ("commit", lambda e: e["kind"] == "commit"
         and e["outcome"] == "ok"),
        ("agent-sync", lambda e: e["kind"] == "agent-sync"
         and e["outcome"] == "ok"),
        ("realization", lambda e: e["kind"] == "realization"
         and e["uid"] == "p1"),
    ])


def test_span_structure_oracle_parity():
    """The span STRUCTURE — stage names, order, lifecycle states — is
    identical across the two engines (acceptance criterion)."""
    span_o, _tr, _dp = _drive_realization(OracleDatapath)
    span_t, _tr2, _dp2 = _drive_realization(TpuflowDatapath)
    assert list(span_o["stages_s"]) == list(span_t["stages_s"])
    assert (set(span_o) - {"closed_at"}) == (set(span_t) - {"closed_at"})
    assert span_o["state"] == span_t["state"] == "closed"


def test_retries_extend_queue_wait_not_restart_it():
    """An install that fails and retries must LENGTHEN the span (earliest
    controller stamp wins; the successful commit's stamps bind) — the
    honest realization latency the histogram contract promises."""
    from antrea_tpu.agent.controller import AgentPolicyController

    dp = _dp(OracleDatapath)
    plan = FaultPlan()
    flaky = FlakyDatapath(dp, plan, "n1")
    agent = AgentPolicyController("n1", flaky, None,
                                  retry_backoff_base=0.0)
    ps, _svcs = _world()
    plan.after("n1.install", 0, "fail", times=1)
    t0 = dp.realization_tracer.now()
    agent.handle_event(WatchEvent(
        kind="ADDED", obj_type="NetworkPolicy", name="p1",
        obj=ps.policies[0], span={"n1"}, ts=t0))
    agent.sync()  # injected failure: span stays pending
    assert agent.sync_failures_total == 1
    assert dp.realization_tracer.stats()["pending"] == 1
    agent.sync()  # retry succeeds
    dp.step(PacketBatch.from_packets([_fresh(CLIENT)]), next(_NOW))
    [span] = dp.realization_tracer.spans(uid="p1")
    assert span["state"] == "closed"
    assert span["controller_ts"] == t0  # the ORIGINAL commit stamp held


def test_unstamped_events_metered_not_guessed():
    """ts=0 events (resync replays) never open spans or observe into the
    histograms — they are counted (the README failure-model row)."""
    from antrea_tpu.agent.controller import AgentPolicyController

    dp = _dp(OracleDatapath)
    agent = AgentPolicyController("n1", dp, None)
    ps, _svcs = _world()
    agent.handle_event(WatchEvent(
        kind="ADDED", obj_type="NetworkPolicy", name="p1",
        obj=ps.policies[0], span={"n1"}))  # ts=0.0
    st = dp.realization_tracer.stats()
    assert st["unstamped_total"] == 1 and st["pending"] == 0
    agent.sync()
    assert dp.realization_tracer.hist["total"].count == 0


def test_pending_stamp_cap_truncation_metered():
    """Satellite: stamps past the 4096 _pending_ts cap used to vanish
    silently; now they count into realization_stamps_dropped_total and
    the counter renders per node."""
    from antrea_tpu.agent.controller import AgentPolicyController

    dp = _dp(OracleDatapath)
    agent = AgentPolicyController("n1", dp, None)
    agent._pending_ts_cap = 4
    ps, _svcs = _world()
    for i in range(7):
        agent.handle_event(WatchEvent(
            kind="UPDATED", obj_type="NetworkPolicy", name="p1",
            obj=ps.policies[0], span={"n1"}, ts=1.0 + i))
    assert len(agent._pending_ts) == 4  # oldest kept: worst-case latency
    assert agent.realization_stamps_dropped_total == 3
    text = render_dissemination_metrics(agents=[agent])
    assert ('antrea_tpu_realization_stamps_dropped_total{node="n1"} 3'
            in text)


def test_readded_policy_opens_new_span():
    """A deleted-then-re-added policy restarts its spec generation at 1
    (controller lifetime semantics), so the key (uid, 1) collides with
    the CLOSED span of the previous lifetime.  The new realization must
    still be traced — only true re-deliveries (controller stamp at or
    before the close) of an already-closed realization are ignored."""
    tr = RealizationTracer()

    def realize(ts, gen_bundle):
        tr.policy_event("p1", 1, ts=ts)
        tr.commit_begin()
        for s in ("compile", "canary", "swap", "settle"):
            tr.commit_stage(s)
        tr.commit_done(gen=gen_bundle)
        tr.realized()
        tr.first_hit(gen_bundle, batch_size=1)

    realize(tr.now(), 1)
    assert tr.spans_closed_total == 1
    closed_at = tr.spans(uid="p1")[0]["closed_at"]
    # A re-delivery of the SAME realization (stamp predates the close)
    # stays ignored.
    tr.policy_event("p1", 1, ts=closed_at - 1e-6)
    assert tr.stats()["pending"] == 0
    # The re-add: a fresh controller stamp AFTER the close opens a new
    # span for the new lifetime, retiring the old closed entry.
    realize(tr.now(), 2)
    assert tr.spans_closed_total == 2
    spans = tr.spans(uid="p1")
    assert len(spans) == 1 and spans[0]["bundle_generation"] == 2


def test_readded_policy_while_awaiting_first_hit():
    """uid reuse while the OLD lifetime's span still awaits its first
    live hit: the stale span is retired METERED (its first-hit would
    belong to the new lifetime) and the new realization is traced."""
    tr = RealizationTracer()

    def commit(gen):
        tr.commit_begin()
        for s in ("compile", "canary", "swap", "settle"):
            tr.commit_stage(s)
        tr.commit_done(gen=gen)

    t0 = tr.now()
    tr.policy_event("p1", 1, ts=t0)
    commit(1)
    tr.realized()  # no live traffic yet: span awaits first hit
    assert tr.stats()["awaiting_first_hit"] == 1
    tr.policy_event("p1", 1, ts=t0)  # re-delivery: still just in flight
    assert tr.stats()["pending"] == 0
    tr.policy_event("p1", 1, ts=tr.now())  # the re-add's fresh stamp
    st = tr.stats()
    assert st["awaiting_first_hit"] == 0 and st["pending"] == 1
    assert st["spans_dropped_total"] == 1
    commit(2)
    tr.realized()
    tr.first_hit(2, batch_size=1)
    spans = tr.spans(uid="p1")
    assert len(spans) == 1 and spans[0]["state"] == "closed"
    assert spans[0]["bundle_generation"] == 2


def test_settle_failure_journaled_and_commit_aborted():
    """A settle-stage persistence failure must journal like every other
    failed commit stage (the 'deciding stage' contract) and abort the
    tracer's open transaction so the retry's stamps bind cleanly."""
    dp = _dp(OracleDatapath)
    ps, svcs = _world()
    dp.install_bundle(ps, svcs)

    def boom():
        raise IOError("disk full")

    dp._persist = boom
    ps2, _svcs = _world(gen=2)
    with pytest.raises(IOError):
        dp.install_bundle(ps2, svcs)
    errs = [e for e in dp.flightrecorder_events(kind="commit")
            if e["outcome"] == "error"]
    assert errs and errs[-1]["stage"] == "settle"
    assert dp.realization_tracer._open_commit is None


def test_span_table_bounded_drop_oldest():
    """The tracer's tables are bounded: overflow drops the OLDEST span,
    metered — never unbounded memory, never silent."""
    tr = RealizationTracer(span_slots=4, pending_slots=4)
    for i in range(6):
        tr.policy_event(f"p{i}", 1, ts=1.0)
    st = tr.stats()
    assert st["pending"] == 4 and st["spans_dropped_total"] == 2
    # Close spans through a commit + first hit; the CLOSED table caps too.
    tr.commit_begin()
    for s in ("compile", "canary", "swap", "settle"):
        tr.commit_stage(s)
    tr.commit_done(gen=1)
    tr.realized()
    tr.first_hit(1, batch_size=1)
    assert tr.stats()["closed"] == 4
    for i in range(6, 9):
        tr.policy_event(f"p{i}", 2, ts=2.0)
    tr.commit_begin()
    tr.commit_stage("settle")
    tr.commit_done(gen=2)
    tr.realized()
    tr.first_hit(2, batch_size=1)
    st = tr.stats()
    assert st["closed"] == 4  # drop-oldest kept the table at its cap
    assert st["spans_closed_total"] == 7


# ---------------------------------------------------------------------------
# Chaos post-mortems: the journal alone reconstructs the causal chain
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_postmortem_miscompile_rollback_chain(dp_cls):
    """PR 4 chaos rerun: injected miscompile -> canary blocks -> rollback
    -> degraded -> recompile passes -> recovered, and the FLIGHT RECORDER
    ALONE carries that chain in sequence order."""
    ps_a, _ = _world("192.0.2.0/24")
    ps_b, _ = _world("198.51.100.0/24")
    dp = _dp(dp_cls)
    plan = FaultPlan()
    dp.arm_commit_faults(plan, "n1")
    g1 = dp.install_bundle(ps=ps_a)

    plan.after("n1.canary", plan.hits("n1.canary"), "fail", times=1)
    with pytest.raises(CanaryMismatchError):
        dp.install_bundle(ps=ps_b)
    assert dp.generation == g1 and dp.degraded
    assert _fresh_parity(dp, ps_a) == 0  # LKG keeps serving correctly

    # Fault exhausted: recovery recompiles and recovers.
    dp.install_bundle(ps=ps_b)
    assert not dp.degraded and _fresh_parity(dp, ps_b) == 0

    events = dp.flightrecorder_events()
    matched = _assert_chain(events, [
        ("fault-injected", lambda e: e["kind"] == "fault-injected"
         and e["site"] == "n1.canary"),
        ("canary-mismatch", lambda e: e["kind"] == "canary-mismatch"),
        ("commit/mismatch", lambda e: e["kind"] == "commit"
         and e["outcome"] == "mismatch" and e["stage"] == "canary"),
        ("rollback", lambda e: e["kind"] == "rollback"
         and e["lkg_generation"] == g1),
        ("degrade", lambda e: e["kind"] == "degrade"),
        ("recompile", lambda e: e["kind"] == "commit"
         and e["outcome"] == "ok" and e["stage"] == "settle"),
        ("recover", lambda e: e["kind"] == "recover"),
    ])
    # The chain is reconstructable from the journal ALONE: every matched
    # event is typed and ordered by the monotonic seq.
    assert [m["seq"] for m in matched] == sorted(m["seq"] for m in matched)


def test_postmortem_cache_corruption_chain():
    """PR 5 chaos rerun: injected cache corruption -> audit finding ->
    repair, journaled in order; with the divergence trip at 1, the
    escalation ladder (degrade -> recompile -> recover) journals too."""
    ps, svcs = _world()
    plan = FaultPlan()
    dp = FlakyDatapath(_dp(OracleDatapath, ps, svcs), plan, "nX")
    # Warm one denial entry so the verdict-flip corruption has a victim.
    den = _fresh(BLOCKED)
    dp.step(PacketBatch.from_packets([den]), next(_NOW))
    dp.audit_scan(now=next(_NOW))  # anchor the digests

    plan.after("nX.cache", plan.hits("nX.cache"), "fail", times=1)
    out = dp.audit_scan(now=next(_NOW))
    assert out["repaired"] >= 1
    assert _fresh_parity(dp, ps) == 0
    _assert_chain(dp.flightrecorder_events(), [
        ("fault-injected", lambda e: e["kind"] == "fault-injected"
         and e["site"] == "nX.cache"),
        ("audit-finding", lambda e: e["kind"] == "audit-finding"),
        ("audit-repair", lambda e: e["kind"] == "audit-repair"),
    ])

    # Escalation variant: trip=1 degrades and the canary-gated recompile
    # recovers — the full PR 4 ladder, reconstructed from the journal.
    dp2 = _dp(OracleDatapath, ps, svcs, audit_divergence_trip=1)
    plan2 = FaultPlan()
    dp2.arm_audit_faults(plan2, "n2")
    plan2.after("n2.audit", plan2.hits("n2.audit"), "fail", times=1)
    out = dp2.audit_scan(now=next(_NOW))
    assert out["recovered"] and not dp2.degraded
    _assert_chain(dp2.flightrecorder_events(), [
        ("fault-injected", lambda e: e["kind"] == "fault-injected"
         and e["site"] == "n2.audit"),
        ("audit-finding", lambda e: e["kind"] == "audit-finding"
         and e["injected"] == 1),
        ("degrade", lambda e: e["kind"] == "degrade"
         and "divergence" in e["reason"]),
        ("recompile", lambda e: e["kind"] == "commit"
         and e["outcome"] == "ok"),
        ("recover", lambda e: e["kind"] == "recover"),
    ])


def test_slowpath_events_overflow_drain_epoch():
    """The slow-path emit sites: admission overflow, drain begin/finish,
    epoch swap — journaled with queue state attached."""
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, async_slowpath=True,
             miss_queue_slots=4, drain_batch=4)
    now = next(_NOW)
    pkts = [_fresh(CLIENT, dst=SRV, dport=80) for _ in range(8)]
    dp.step(PacketBatch.from_packets(pkts), now)  # 8 misses into 4 slots
    assert dp.slowpath_stats()["overflows_total"] > 0
    dp.drain_slowpath(next(_NOW))
    ev = dp.flightrecorder_events()
    _assert_chain(ev, [
        ("queue-overflow", lambda e: e["kind"] == "queue-overflow"
         and e["dropped"] > 0),
        ("drain-begin", lambda e: e["kind"] == "drain-begin"
         and e["n"] == 4),
        ("drain-finish", lambda e: e["kind"] == "drain-finish"
         and e["drained"] == 4),
        ("epoch-swap", lambda e: e["kind"] == "epoch-swap"),
    ])


def test_maintenance_tick_and_observability_task_accounting():
    """Ticks journal their grants/sheds; the `observability` task spends
    the recording cost (events + stamps since its last grant) so the
    plane's overhead is visible in the scheduler accounting, not
    smeared."""
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs)
    dp.install_bundle(ps=ps)  # journal some events -> recording cost
    out = dp.maintenance_tick(now=next(_NOW))
    assert out["ran"].get("observability", 0) > 0
    st = dp.maintenance_stats()["tasks"]["observability"]
    assert st["spent_total"] > 0
    ticks = dp.flightrecorder_events(kind="maint-tick")
    assert ticks and "observability" in ticks[-1]["ran"]
    # A blocked tick journals as maint-blocked.
    dpa = _dp(OracleDatapath, ps, svcs, async_slowpath=True,
              miss_queue_slots=32, drain_batch=4)
    dpa.step(PacketBatch.from_packets([_fresh(CLIENT)]), next(_NOW))
    assert dpa._slowpath.begin_drain(next(_NOW))
    blocked = dpa.maintenance_tick(now=next(_NOW))
    assert blocked["blocked"] == "inflight-drain"
    assert dpa.flightrecorder_events(kind="maint-blocked")
    dpa._slowpath.finish_drain(next(_NOW))


# ---------------------------------------------------------------------------
# Hot path unharmed: HLO bit-identity with the plane enabled
# ---------------------------------------------------------------------------


def test_step_hlo_bit_identical_with_tracing_enabled():
    """The whole plane is host-side: a tracing+recording twin lowers the
    compiled step to byte-identical HLO vs a disabled twin, before AND
    after spans close and events journal."""
    import jax.numpy as jnp

    from antrea_tpu.models import pipeline as pl

    ps, svcs = _world()
    a = _dp(TpuflowDatapath, ps, svcs)  # plane enabled (defaults)
    b = _dp(TpuflowDatapath, ps, svcs, flightrec_slots=0,
            realization_slots=0)
    assert a._flightrec is not None and b._flightrec is None
    assert a._meta_step == b._meta_step

    def lower_text(dp):
        z = np.zeros(4, np.int32)
        return pl.pipeline_step.lower(
            dp._state, dp._drs, dp._dsvc,
            jnp.asarray(z), jnp.asarray(z), jnp.asarray(z),
            jnp.asarray(z), jnp.asarray(z),
            jnp.int32(0), jnp.int32(0), meta=dp._meta_step,
        ).as_text()

    before = lower_text(a)
    assert before == lower_text(b)
    # Exercise the plane: install (journal + span stamps) + live steps
    # (the first-hit latch) + a tick (the observability task).
    a.install_bundle(ps=ps)
    a.step(PacketBatch.from_packets([_fresh(BLOCKED)]), next(_NOW))
    a.maintenance_tick(now=next(_NOW))
    assert a._flightrec.seq > 0
    assert lower_text(a) == before


# ---------------------------------------------------------------------------
# Surfaces: API routes, antctl tables, support bundle, metrics, tooling
# ---------------------------------------------------------------------------


def test_api_routes_antctl_metrics_bundle(capsys, tmp_path):
    """GET /realization?uid= and GET /flightrecorder?tail=&kind= serve
    the plane; antctl renders tables; the support bundle carries
    flightrecorder.json + realization.json; the families render."""
    import tarfile
    import urllib.request

    from antrea_tpu.agent.apiserver import AgentApiServer
    from antrea_tpu.antctl import main as antctl_main
    from antrea_tpu.observability.supportbundle import collect_bundle

    span, _tr, dp = _drive_realization(OracleDatapath)
    srv = AgentApiServer(dp, node="n1").start()
    try:
        body = json.loads(urllib.request.urlopen(
            srv.address + "/realization?uid=p1").read())
        assert body["stages"] == list(REALIZATION_STAGES)
        assert len(body["spans"]) == 1
        assert body["spans"][0]["total_s"] == pytest.approx(span["total_s"])
        assert json.loads(urllib.request.urlopen(
            srv.address + "/realization?uid=nope").read())["spans"] == []

        fr = json.loads(urllib.request.urlopen(
            srv.address + "/flightrecorder?tail=2").read())
        assert len(fr["events"]) == 2 and fr["seq"] >= 2
        only = json.loads(urllib.request.urlopen(
            srv.address + "/flightrecorder?kind=realization").read())
        assert {e["kind"] for e in only["events"]} == {"realization"}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                srv.address + "/flightrecorder?kind=bogus")
        assert ei.value.code == 400

        rc = antctl_main(["realization", "--server", srv.address,
                          "--uid", "p1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "UID" in out and "FIRST_HIT" in out and "p1" in out

        rc = antctl_main(["flightrecorder", "--server", srv.address,
                          "--tail", "3"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "SEQ" in out and "KIND" in out

        rc = antctl_main(["flightrecorder", "--server", srv.address,
                          "--json"])
        assert rc == 0
        assert "events" in json.loads(capsys.readouterr().out)
    finally:
        srv.close()

    text = render_metrics(dp, node="n1")
    for fam in ("antrea_tpu_policy_realization_seconds",
                "antrea_tpu_realization_spans",
                "antrea_tpu_realization_spans_dropped_total",
                "antrea_tpu_flightrecorder_events_total",
                "antrea_tpu_flightrecorder_dropped_total",
                "antrea_tpu_flightrecorder_seq"):
        assert fam in text, fam
    assert 'stage="first_hit"' in text and 'kind="realization"' in text

    out_tar = tmp_path / "bundle.tar.gz"
    members = collect_bundle(dp, str(out_tar), node="n1")
    assert {"flightrecorder.json", "realization.json"} <= set(members)
    with tarfile.open(out_tar) as tar:
        frj = json.load(tar.extractfile("flightrecorder.json"))
        rzj = json.load(tar.extractfile("realization.json"))
    assert frj["seq"] == dp.flightrecorder_stats()["seq"]
    assert len(frj["events"]) == frj["retained"]
    assert any(sp["uid"] == "p1" for sp in rzj["spans"])


def test_routes_404_without_the_plane():
    import urllib.request

    from antrea_tpu.agent.apiserver import AgentApiServer

    dp = _dp(OracleDatapath, flightrec_slots=0, realization_slots=0)
    srv = AgentApiServer(dp, node="n1").start()
    try:
        for route in ("/realization", "/flightrecorder"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.address + route)
            assert ei.value.code == 404
    finally:
        srv.close()


def test_fleet_realization_p99_plumbing():
    """simulator/fleet.py carries the span plumbing: stamped events land
    in per-agent histograms, unstamped resync replays are metered out,
    and the fleet-wide p99 folds one bucket space."""
    from antrea_tpu.simulator.fleet import FakeAgentFleet

    store = RamStore()
    fleet = FakeAgentFleet(store, ["n1", "n2"])
    ps, _svcs = _world()
    store.apply(WatchEvent(
        kind="ADDED", obj_type="NetworkPolicy", name="p1",
        obj=ps.policies[0], span={"n1", "n2"}))
    assert fleet.pump() == 2
    assert fleet.realization_hist().count == 2
    assert fleet.realization_p99_s() > 0.0
    # An unstamped replay (watcher overflow -> resync) meters, never
    # observes: the p99 is honest about what it measured.
    before = fleet.realization_hist().count
    fleet.agents["n1"]._apply(WatchEvent(
        kind="ADDED", obj_type="NetworkPolicy", name="p2",
        obj=ps.policies[0], span={"n1"}))  # ts=0.0
    assert fleet.realization_hist().count == before
    assert fleet.realization_unstamped_total() == 1


# The event-schema drift gate (tools/check_events.py -> analysis pass
# `events`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.


def test_event_kinds_schema_is_complete():
    """Every kind the journal can carry is declared with an owning
    plane; the schema is a pure literal (check_events parses it
    dependency-free)."""
    assert len(EVENT_KINDS) >= 18
    for kind, desc in EVENT_KINDS.items():
        assert kind == kind.lower() and " " not in kind
        assert isinstance(desc, str) and desc.strip()
