"""NodePortLocal tests: port-cache allocation, persistence, and DNAT
through the datapath (semantics from pkg/agent/nodeportlocal: portcache
allocation + iptables DNAT + pod annotation)."""

import copy

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.nodeportlocal import (
    DEFAULT_PORT_RANGE,
    NplController,
    PortAllocationError,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

NODE_IP = "192.168.1.10"


def _batch(dst_ip, dst_port, src="203.0.113.7", sport=40000):
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst_ip)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([sport], np.int32),
        dst_port=np.array([dst_port], np.int32),
    )


def test_allocation_idempotent_and_release():
    npl = NplController([NODE_IP], port_range=(61000, 61010))
    p1 = npl.add_pod_port("10.0.0.5", 6, 8080)
    assert p1 == npl.add_pod_port("10.0.0.5", 6, 8080)  # idempotent
    p2 = npl.add_pod_port("10.0.0.5", 6, 9090)
    assert p2 != p1
    assert npl.remove_pod_port("10.0.0.5", 6, 8080)
    p3 = npl.add_pod_port("10.0.0.6", 17, 53)
    assert 61000 <= p3 < 61010
    assert npl.remove_pod("10.0.0.5") == 1  # 9090 mapping remains -> released
    assert npl.mappings() == {("10.0.0.6", 17, 53): p3}


def test_range_exhaustion():
    npl = NplController([NODE_IP], port_range=(61000, 61002))
    npl.add_pod_port("10.0.0.5", 6, 1)
    npl.add_pod_port("10.0.0.5", 6, 2)
    with pytest.raises(PortAllocationError):
        npl.add_pod_port("10.0.0.5", 6, 3)


def test_persisted_port_cache_survives_restart(tmp_path):
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    npl = NplController([NODE_IP], store=store)
    p = npl.add_pod_port("10.0.0.5", 6, 8080)
    # Restart: fresh store handle, fresh controller — same node port (the
    # portcache rule-restore contract: advertised ports never change).
    npl2 = NplController([NODE_IP], store=ConfigStore(str(tmp_path / "conf.db")))
    assert npl2.add_pod_port("10.0.0.5", 6, 8080) == p
    # And the allocator won't hand the restored port to someone else.
    q = npl2.add_pod_port("10.0.0.5", 6, 9090)
    assert q != p


def test_npl_dnat_through_datapath():
    """External client -> node_ip:npl_port DNATs to the pod, client IP
    preserved (snat=0), reply leg un-DNATs — on both datapaths."""
    npl = NplController([NODE_IP], port_range=DEFAULT_PORT_RANGE)
    port = npl.add_pod_port("10.0.0.5", 6, 8080)
    svcs = npl.service_entries()
    tpu = TpuflowDatapath(None, copy.deepcopy(svcs), flow_slots=1 << 10,
                          aff_slots=1 << 8, miss_chunk=64)
    orc = OracleDatapath(None, copy.deepcopy(svcs), flow_slots=1 << 10,
                         aff_slots=1 << 8)
    b = _batch(NODE_IP, port)
    ra, rb = tpu.step(b, now=1), orc.step(b, now=1)
    for f in ("code", "snat", "dnat_port", "committed"):
        assert getattr(ra, f).tolist() == getattr(rb, f).tolist(), f
    assert ra.dnat_ip.tolist() == rb.dnat_ip.tolist()
    assert ra.code.tolist() == [0]
    assert ra.dnat_ip.tolist() == [iputil.ip_to_u32("10.0.0.5")]
    assert ra.dnat_port.tolist() == [8080]
    assert ra.snat.tolist() == [0]  # client IP preserved
    # Reply: pod -> client restores the node frontend as source.
    reply = _batch("203.0.113.7", 40000, src="10.0.0.5", sport=8080)
    ra2, rb2 = tpu.step(reply, now=2), orc.step(reply, now=2)
    assert ra2.reply.tolist() == rb2.reply.tolist() == [1]
    assert ra2.dnat_ip.tolist() == [iputil.ip_to_u32(NODE_IP)]
    assert ra2.dnat_port.tolist() == [port]


def test_annotation_shape():
    npl = NplController([NODE_IP])
    assert npl.annotation("10.0.0.5") is None
    p = npl.add_pod_port("10.0.0.5", 6, 8080)
    import json

    rows = json.loads(npl.annotation("10.0.0.5"))
    assert rows == [{"podPort": 8080, "nodeIP": NODE_IP, "nodePort": p,
                     "protocol": 6}]
