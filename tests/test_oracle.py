"""Oracle semantics tests: hand-built policy sets with known verdicts.

These encode the OVS-pipeline decision procedure from
docs/design/ovs-pipeline.md (reference) as concrete cases; the batched kernels
are later tested against the oracle, so this file is the root of the parity
chain.
"""

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.oracle import Oracle, VerdictCode
from antrea_tpu.packet import Packet
from antrea_tpu.utils import ip as iputil


POD_A = "10.0.0.2"  # appliedTo pod
POD_B = "10.0.1.2"  # peer pod
POD_C = "10.0.2.2"  # unrelated pod


def members(*ips):
    return [cp.GroupMember(ip=i, node="n0") for i in ips]


def base_ps() -> PolicySet:
    ps = PolicySet()
    ps.applied_to_groups["atg-a"] = cp.AppliedToGroup("atg-a", members(POD_A))
    ps.address_groups["ag-b"] = cp.AddressGroup("ag-b", members(POD_B))
    return ps


def pkt(src, dst, proto=cp.PROTO_TCP, dport=80, sport=12345):
    return Packet(
        src_ip=iputil.ip_to_u32(src),
        dst_ip=iputil.ip_to_u32(dst),
        proto=proto,
        src_port=sport,
        dst_port=dport,
    )


def k8s_ingress_allow(ps, uid="knp-1", port=80):
    ps.policies.append(
        cp.NetworkPolicy(
            uid=uid,
            name=uid,
            namespace="ns",
            type=cp.NetworkPolicyType.K8S,
            applied_to_groups=["atg-a"],
            policy_types=[cp.Direction.IN],
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    from_peer=cp.NetworkPolicyPeer(address_groups=["ag-b"]),
                    services=[cp.Service(protocol=cp.PROTO_TCP, port=port)],
                )
            ],
        )
    )


def test_default_allow_no_policies():
    o = Oracle(base_ps())
    v = o.classify(pkt(POD_B, POD_A))
    assert v.code == VerdictCode.ALLOW
    assert v.ingress.rule is None and v.egress.rule is None


def test_k8s_isolation_and_allow():
    ps = base_ps()
    k8s_ingress_allow(ps)
    o = Oracle(ps)
    # allowed peer/port
    assert o.classify(pkt(POD_B, POD_A, dport=80)).code == VerdictCode.ALLOW
    # isolated pod, wrong port -> drop
    assert o.classify(pkt(POD_B, POD_A, dport=81)).code == VerdictCode.DROP
    # isolated pod, wrong peer -> drop
    assert o.classify(pkt(POD_C, POD_A, dport=80)).code == VerdictCode.DROP
    # non-isolated pod as dst -> allow
    assert o.classify(pkt(POD_A, POD_C)).code == VerdictCode.ALLOW


def test_k8s_empty_policy_isolates():
    ps = base_ps()
    ps.policies.append(
        cp.NetworkPolicy(
            uid="knp-deny",
            name="knp-deny",
            namespace="ns",
            type=cp.NetworkPolicyType.K8S,
            applied_to_groups=["atg-a"],
            policy_types=[cp.Direction.IN],
            rules=[],
        )
    )
    o = Oracle(ps)
    assert o.classify(pkt(POD_B, POD_A)).code == VerdictCode.DROP


def test_acnp_drop_beats_k8s_allow():
    ps = base_ps()
    k8s_ingress_allow(ps)
    ps.policies.append(
        cp.NetworkPolicy(
            uid="acnp-1",
            name="acnp-1",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_SECURITYOPS,
            priority=5.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    from_peer=cp.NetworkPolicyPeer(address_groups=["ag-b"]),
                    action=cp.RuleAction.DROP,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    v = o.classify(pkt(POD_B, POD_A, dport=80))
    assert v.code == VerdictCode.DROP
    assert v.ingress.rule == "acnp-1/In/0"


def test_acnp_pass_falls_to_k8s():
    ps = base_ps()
    k8s_ingress_allow(ps)
    ps.policies.append(
        cp.NetworkPolicy(
            uid="acnp-pass",
            name="acnp-pass",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_SECURITYOPS,
            priority=5.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    from_peer=cp.NetworkPolicyPeer(address_groups=["ag-b"]),
                    action=cp.RuleAction.PASS,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    assert o.classify(pkt(POD_B, POD_A, dport=80)).code == VerdictCode.ALLOW
    assert o.classify(pkt(POD_B, POD_A, dport=99)).code == VerdictCode.DROP


def test_tier_ordering():
    ps = base_ps()
    for uid, tier, action in [
        ("low", cp.TIER_APPLICATION, cp.RuleAction.DROP),
        ("high", cp.TIER_EMERGENCY, cp.RuleAction.ALLOW),
    ]:
        ps.policies.append(
            cp.NetworkPolicy(
                uid=uid,
                name=uid,
                type=cp.NetworkPolicyType.ACNP,
                applied_to_groups=["atg-a"],
                tier_priority=tier,
                priority=1.0,
                rules=[
                    cp.NetworkPolicyRule(
                        direction=cp.Direction.IN,
                        from_peer=cp.NetworkPolicyPeer(address_groups=["ag-b"]),
                        action=action,
                        priority=0,
                    )
                ],
            )
        )
    o = Oracle(ps)
    v = o.classify(pkt(POD_B, POD_A))
    assert v.code == VerdictCode.ALLOW
    assert v.ingress.rule == "high/In/0"


def test_policy_priority_within_tier():
    ps = base_ps()
    for uid, prio, action in [("p9", 9.0, cp.RuleAction.DROP), ("p1", 1.0, cp.RuleAction.ALLOW)]:
        ps.policies.append(
            cp.NetworkPolicy(
                uid=uid,
                name=uid,
                type=cp.NetworkPolicyType.ACNP,
                applied_to_groups=["atg-a"],
                tier_priority=cp.TIER_APPLICATION,
                priority=prio,
                rules=[
                    cp.NetworkPolicyRule(
                        direction=cp.Direction.IN,
                        from_peer=cp.NetworkPolicyPeer(address_groups=["ag-b"]),
                        action=action,
                        priority=0,
                    )
                ],
            )
        )
    o = Oracle(ps)
    assert o.classify(pkt(POD_B, POD_A)).ingress.rule == "p1/In/0"


def test_baseline_cannot_override_k8s_isolation():
    ps = base_ps()
    k8s_ingress_allow(ps)  # isolates POD_A ingress; allows only POD_B:80
    ps.policies.append(
        cp.NetworkPolicy(
            uid="base-allow",
            name="base-allow",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_BASELINE,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    action=cp.RuleAction.ALLOW,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    # K8s isolation still drops the non-allowed peer.
    assert o.classify(pkt(POD_C, POD_A, dport=80)).code == VerdictCode.DROP


def test_baseline_applies_when_not_isolated():
    ps = base_ps()
    ps.applied_to_groups["atg-c"] = cp.AppliedToGroup(
        "atg-c", [cp.GroupMember(ip=POD_C, node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="base-drop",
            name="base-drop",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-c"],
            tier_priority=cp.TIER_BASELINE,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    action=cp.RuleAction.DROP,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    assert o.classify(pkt(POD_B, POD_C)).code == VerdictCode.DROP
    assert o.classify(pkt(POD_B, POD_A)).code == VerdictCode.ALLOW


def test_egress_direction():
    ps = base_ps()
    ps.policies.append(
        cp.NetworkPolicy(
            uid="acnp-eg",
            name="acnp-eg",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.OUT,
                    to_peer=cp.NetworkPolicyPeer(ip_blocks=[cp.IPBlock(cidr="10.0.1.0/24")]),
                    action=cp.RuleAction.REJECT,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    v = o.classify(pkt(POD_A, POD_B))  # POD_A egress to 10.0.1.x
    assert v.code == VerdictCode.REJECT
    assert v.egress.rule == "acnp-eg/Out/0"
    # Other destinations unaffected.
    assert o.classify(pkt(POD_A, "192.168.1.1")).code == VerdictCode.ALLOW


def test_service_port_range():
    ps = base_ps()
    ps.policies.append(
        cp.NetworkPolicy(
            uid="acnp-ports",
            name="acnp-ports",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    services=[cp.Service(protocol=cp.PROTO_TCP, port=8000, end_port=8100)],
                    action=cp.RuleAction.DROP,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    assert o.classify(pkt(POD_B, POD_A, dport=8050)).code == VerdictCode.DROP
    assert o.classify(pkt(POD_B, POD_A, dport=8101)).code == VerdictCode.ALLOW
    assert (
        o.classify(pkt(POD_B, POD_A, proto=cp.PROTO_UDP, dport=8050)).code == VerdictCode.ALLOW
    )


def test_ipblock_except_in_peer():
    ps = base_ps()
    ps.policies.append(
        cp.NetworkPolicy(
            uid="acnp-exc",
            name="acnp-exc",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-a"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN,
                    from_peer=cp.NetworkPolicyPeer(
                        ip_blocks=[cp.IPBlock(cidr="10.0.0.0/8", excepts=("10.0.1.0/24",))]
                    ),
                    action=cp.RuleAction.DROP,
                    priority=0,
                )
            ],
        )
    )
    o = Oracle(ps)
    assert o.classify(pkt(POD_C, POD_A)).code == VerdictCode.DROP  # in cidr
    assert o.classify(pkt(POD_B, POD_A)).code == VerdictCode.ALLOW  # in except
