"""Fake-agent fleet scale test: span-filtered fan-out over many agents
(the antrea-agent-simulator model, cmd/antrea-agent-simulator)."""

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis import crd
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.dissemination import RamStore
from antrea_tpu.simulator.fleet import FakeAgentFleet

N_NODES = 40
PODS_PER_NODE = 4


def _world():
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    nodes = [f"node-{i:03d}" for i in range(N_NODES)]
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    ip = 0
    for ni, node in enumerate(nodes):
        for p in range(PODS_PER_NODE):
            ip += 1
            ctl.upsert_pod(crd.Pod(
                namespace="default", name=f"pod-{ni}-{p}",
                ip=f"10.{(ip >> 8) & 0xFF}.{ip & 0xFF}.1", node=node,
                # Tag pods on even nodes so policies can target half the
                # fleet.
                labels={"tier": "even" if ni % 2 == 0 else "odd"},
            ))
    return ctl, store, nodes


def test_span_filtered_fanout_at_fleet_scale():
    ctl, store, nodes = _world()
    fleet = FakeAgentFleet(store, nodes)
    fleet.pump()

    # A policy applying to even-node pods must reach exactly the even
    # nodes' agents.
    ctl.upsert_antrea_policy(crd.AntreaNetworkPolicy(
        uid="acnp-even", name="even-only", namespace="",
        tier_priority=250, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"tier": "even"}),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP)],
    ))
    fleet.pump()
    for i, node in enumerate(nodes):
        expect = {"acnp-even"} if i % 2 == 0 else set()
        assert fleet.policies_on(node) == expect, node

    # Fan-out cost: the policy event went only to spanned agents — the
    # whole point of span dissemination (architecture.md:57-60).  Every
    # agent also got the appliedTo group (spanned the same way), so the
    # per-change delivery is O(span), not O(agents).
    before = fleet.total_events()
    ctl.upsert_pod(crd.Pod(
        namespace="default", name="pod-0-0", ip="10.0.1.1",
        node="node-000", labels={"tier": "even", "extra": "1"},
    ))
    delta = fleet.pump()
    # A single-pod relabel churns only the groups containing it: events
    # reach the spanned half of the fleet at most, not everyone.
    assert delta <= N_NODES // 2 + 2, delta
    assert fleet.total_events() == before + delta

    # Deletion withdraws everywhere it was delivered.
    ctl.delete_policy("acnp-even")
    fleet.pump()
    assert all(not fleet.policies_on(n) for n in nodes)
    fleet.stop()
    assert store.n_watchers == 0


def test_fleet_sees_consistent_groups():
    ctl, store, nodes = _world()
    fleet = FakeAgentFleet(store, nodes)
    ctl.upsert_antrea_policy(crd.AntreaNetworkPolicy(
        uid="acnp-all", name="all-pods", namespace="",
        tier_priority=250, priority=2,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make(),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[crd.AntreaNPRule(direction=cp.Direction.OUT,
                                action=cp.RuleAction.ALLOW)],
    ))
    fleet.pump()
    # Every agent got the policy and its appliedTo group; the group object
    # an agent holds contains members (the full group — per-node member
    # filtering is the agent's own concern in this build).
    for node in nodes:
        a = fleet.agents[node]
        assert set(a.policies) == {"acnp-all"}
        assert len(a.applied_to_groups) == 1
    fleet.stop()


def test_netwire_fleet_scale(tmp_path):
    """The fleet over the PRODUCTION transport: 16 agents as real mTLS
    TCP clients of a DisseminationServer (apiserver.go:97-99 — the
    reference's ONE dissemination path).  Span-filtered fan-out over
    sockets; realization statuses flow back over the same channels and
    surface through antctl policystatus against the LIVE controller API."""
    import json as _json
    import subprocess
    import sys

    from antrea_tpu.controller.apiserver import ControllerApiServer
    from antrea_tpu.controller.status import PHASE_REALIZED, StatusAggregator
    from antrea_tpu.dissemination.netwire import DisseminationServer, make_ca

    n_net = 16
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    nodes = [f"node-{i:03d}" for i in range(n_net)]
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    for ni, node in enumerate(nodes):
        ctl.upsert_pod(crd.Pod(
            namespace="default", name=f"pod-{ni}", ip=f"10.9.{ni}.1",
            node=node,
            labels={"tier": "even" if ni % 2 == 0 else "odd"},
        ))
    certdir = str(tmp_path / "pki")
    make_ca(certdir)
    agg = StatusAggregator(ctl)
    srv = DisseminationServer(store, certdir, status_aggregator=agg)
    try:
        fleet = FakeAgentFleet(None, nodes, transport="netwire",
                               server=srv, certdir=certdir)
        fleet.pump()

        ctl.upsert_antrea_policy(crd.AntreaNetworkPolicy(
            uid="acnp-even", name="even-only", namespace="",
            tier_priority=250, priority=1,
            applied_to=[crd.AntreaAppliedTo(
                pod_selector=crd.LabelSelector.make({"tier": "even"}),
                ns_selector=crd.LabelSelector.make(),
            )],
            rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                    action=cp.RuleAction.DROP)],
        ))
        fleet.pump()
        for i, node in enumerate(nodes):
            expect = {"acnp-even"} if i % 2 == 0 else set()
            assert fleet.policies_on(node) == expect, node

        # Statuses crossed the wire: the policy is Realized on its span,
        # visible through antctl against the live controller API.
        api = ControllerApiServer(ctl, store=store, status=agg).start()
        try:
            url = f"http://{api.address[0]}:{api.address[1]}"
            out = subprocess.run(
                [sys.executable, "-m", "antrea_tpu.antctl", "get",
                 "policystatus", "--server", url],
                capture_output=True, text=True, timeout=60, check=True,
            )
            [row] = _json.loads(out.stdout)["items"]
            assert row["phase"] == PHASE_REALIZED
            assert row["currentNodesRealized"] == n_net // 2
        finally:
            api.stop()

        # Deletion withdraws over the sockets too.
        ctl.delete_policy("acnp-even")
        fleet.pump()
        assert all(not fleet.policies_on(n) for n in nodes)
        fleet.stop()
    finally:
        srv.close()
