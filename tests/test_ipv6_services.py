"""IPv6 service plane (ServiceLB/DNAT/affinity/DSR) — dual-stack proxy.

Hand-authored expectations from the reference's dual-stack proxy
(/root/reference/pkg/agent/proxy/proxier.go:1379-1465 metaProxier: one
proxier per family, each seeing only its family's ClusterIPs/endpoints/
node addresses), asserted as a device-kernel vs scalar-oracle differential
over the wide (10-column) flow cache: v6 ClusterIP DNAT, v6 reply un-DNAT,
v6 ClientIP affinity, v6 NodePort SNAT marks, v6 DSR delivery, no-endpoint
reject, and family-purity validation.
"""

import pytest

pytestmark = pytest.mark.slow

import jax.numpy as jnp
import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import ETP_LOCAL, Endpoint, ServiceEntry
from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models import pipeline as pl
from antrea_tpu.ops.match import flip_ips
from antrea_tpu.oracle.pipeline import PipelineOracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

VIP6 = "fd00:96::10"
EP6A = "2001:db8:0:1::10"
EP6B = "2001:db8:0:1::11"
CLIENT6 = "2001:db8:0:2::7"
CLIENT6B = "2001:db8:0:2::8"
NODE6 = "2001:db8:ffff::1"
NODE4 = "192.168.0.1"
EXT6 = "fd00:ee::5"

VIP4 = "10.96.0.10"
EP4 = "10.0.0.10"
CLIENT4 = "10.0.1.7"


def _pkt(src, dst, dport=80, proto=6, sport=40000):
    return Packet(
        src_ip=iputil.ip_to_key(src), dst_ip=iputil.ip_to_key(dst),
        proto=proto, src_port=sport, dst_port=dport,
    )


def _mk(services, node_ips=(), node_name="n0", ps=None):
    ps = ps if ps is not None else PolicySet()
    cps = compile_policy_set(ps)
    svc = compile_services(list(services), node_ips=list(node_ips),
                           node_name=node_name)
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=1 << 10, aff_slots=1 << 6, miss_chunk=16,
        dual_stack=True,
    )
    po = PipelineOracle(ps, list(services), flow_slots=1 << 10,
                        aff_slots=1 << 6, node_ips=list(node_ips),
                        node_name=node_name, dual_stack=True)
    return step, state, drs, dsvc, po


def _step_both(step, state, drs, dsvc, po, pkts, now, gen=0):
    batch = PacketBatch.from_packets(pkts)
    v6 = None
    if batch.is6 is not None:
        v6 = (jnp.asarray(flip_ips(batch.src_ip6)),
              jnp.asarray(flip_ips(batch.dst_ip6)),
              jnp.asarray(batch.is6))
    state, out = pl.pipeline_step(
        state, drs, dsvc,
        jnp.asarray(flip_ips(batch.src_ip)),
        jnp.asarray(flip_ips(batch.dst_ip)),
        jnp.asarray(batch.proto.astype(np.int32)),
        jnp.asarray(batch.src_port.astype(np.int32)),
        jnp.asarray(batch.dst_port.astype(np.int32)),
        jnp.int32(now), jnp.int32(gen), meta=step.meta, v6=v6,
    )
    outs = po.step(batch, now, gen=gen)
    dev = {k: np.asarray(v) for k, v in out.items()}
    for i, o in enumerate(outs):
        assert int(dev["code"][i]) == o.code, (i, "code")
        assert int(dev["est"][i]) == int(o.est), (i, "est")
        assert int(dev["reply"][i]) == int(o.reply), (i, "reply")
        assert int(dev["committed"][i]) == int(o.committed), (i, "committed")
        assert int(dev["svc_idx"][i]) == o.svc_idx, (i, "svc")
        assert int(dev["snat"][i]) == int(o.snat), (i, "snat")
        assert int(dev["dsr"][i]) == int(o.dsr), (i, "dsr")
        assert int(dev["dnat_port"][i]) == o.dnat_port, (i, "dnat_port")
    return state, dev, outs


def _dev_dnat_key(dev, i) -> int:
    """Device wide DNAT words -> combined-keyspace int (oracle space)."""
    words = [iputil.unflip_u32(int(w)) for w in dev["dnat_w_f"][i]]
    v = (words[0] << 96) | (words[1] << 64) | (words[2] << 32) | words[3]
    if (v >> 32) == 0xFFFF:  # v4-mapped
        return v & 0xFFFFFFFF
    return iputil.V6_OFF + v


def test_v6_clusterip_dnat_and_reply_unnat():
    """A v6 ClusterIP DNATs to its v6 endpoint (proxier.go ipv6 proxier
    serviceMap path); the reply leg un-DNATs back to the frontend."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(EP6A, 8080)])
    step, state, drs, dsvc, po = _mk([svc])
    pkts = [_pkt(CLIENT6, VIP6, 80, sport=43000)]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert outs[0].svc_idx == 0 and outs[0].code == 0
    assert outs[0].dnat_ip == iputil.ip_to_key(EP6A)
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(EP6A)
    assert int(dev["dnat_port"][0]) == 8080

    # Established hit keeps the cached wide resolution.
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=2)
    assert int(dev["est"][0]) == 1
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(EP6A)

    # Reply (endpoint -> client): reverse-tuple est hit carrying the
    # un-DNAT rewrite back to the v6 frontend.
    rev = [Packet(src_ip=iputil.ip_to_key(EP6A),
                  dst_ip=iputil.ip_to_key(CLIENT6),
                  proto=6, src_port=8080, dst_port=43000)]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, rev, now=3)
    assert int(dev["reply"][0]) == 1 and int(dev["est"][0]) == 1
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(VIP6)
    assert int(dev["dnat_port"][0]) == 80


def test_v6_clientip_affinity_sticks():
    """ClientIP affinity keys on the full 128-bit client address: the same
    v6 client re-selects its learned endpoint across NEW connections;
    a different client may hash elsewhere (serviceLearnFlow analog)."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(EP6A, 8080), Endpoint(EP6B, 8080)],
                       affinity_timeout_s=300)
    step, state, drs, dsvc, po = _mk([svc])
    # Distinct source ports = distinct connections; affinity (not the flow
    # cache) must make them agree.
    first = None
    for sport in (43100, 43101, 43102):
        pkts = [_pkt(CLIENT6, VIP6, 80, sport=sport)]
        state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts,
                                      now=sport - 43090)
        got = _dev_dnat_key(dev, 0)
        assert got == outs[0].dnat_ip  # device == oracle per-lane
        if first is None:
            first = got
        assert got == first, "affinity must pin the endpoint"
    assert first in (iputil.ip_to_key(EP6A), iputil.ip_to_key(EP6B))


def test_v6_no_endpoint_reject():
    """A v6 service with no endpoints rejects (SvcReject before the policy
    tables, EndpointDNAT order) — reject kind derives from proto."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6, endpoints=[])
    step, state, drs, dsvc, po = _mk([svc])
    state, dev, outs = _step_both(
        step, state, drs, dsvc, po, [_pkt(CLIENT6, VIP6, 80)], now=1)
    assert outs[0].code == 2  # REJECT
    assert int(dev["reject_kind"][0]) == int(pl.REJECT_TCP_RST)


def test_v6_nodeport_binds_v6_node_ips_only():
    """NodePort frontends bind per family (metaProxier: the v6 proxier sees
    only v6 node addresses): the v6 service answers on the v6 node IP with
    the ETP=Cluster SNAT mark; the v4 node IP does NOT expose it."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(EP6A, 8080)], node_port=30080)
    step, state, drs, dsvc, po = _mk([svc], node_ips=[NODE4, NODE6])
    pkts = [
        _pkt(CLIENT6, NODE6, 30080, sport=43200),  # v6 NodePort: hit + SNAT
        _pkt(CLIENT4, NODE4, 30080, sport=43201),  # v4 node IP: no frontend
    ]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert outs[0].svc_idx == 0
    assert int(dev["snat"][0]) == 1
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(EP6A)
    assert outs[1].svc_idx == -1  # the v4 family never exposes a v6 service


def test_v6_external_ip_dsr():
    """A v6 external IP under DSR: endpoint selected (drives forwarding),
    destination NOT rewritten... is signaled via dsr=1 with snat=0 and no
    reply conntrack leg (pipeline.go:698-708)."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(EP6A, 80)],
                       external_ips=[EXT6], dsr=True)
    step, state, drs, dsvc, po = _mk([svc])
    pkts = [_pkt(CLIENT6, EXT6, 80, sport=43300)]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert int(dev["dsr"][0]) == 1 and int(dev["snat"][0]) == 0
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(EP6A)
    # DSR commits no reply leg: the endpoint->client tuple misses.
    rev = [Packet(src_ip=iputil.ip_to_key(EP6A),
                  dst_ip=iputil.ip_to_key(CLIENT6),
                  proto=6, src_port=80, dst_port=43300)]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, rev, now=2)
    assert int(dev["reply"][0]) == 0 and not outs[0].hit


def test_v6_etp_local_filters_endpoints():
    """externalTrafficPolicy=Local on a v6 service: external-frontend
    traffic only selects endpoints on this node; with none local, the
    no-endpoint treatment applies (proxier.go externalPolicyLocal)."""
    svc = ServiceEntry(
        cluster_ip=VIP6, port=80, protocol=6,
        endpoints=[Endpoint(EP6A, 8080, node="other")],
        external_ips=[EXT6], external_traffic_policy=ETP_LOCAL,
    )
    step, state, drs, dsvc, po = _mk([svc], node_name="n0")
    pkts = [
        _pkt(CLIENT6, EXT6, 80, sport=43400),  # external: no LOCAL ep -> reject
        _pkt(CLIENT6, VIP6, 80, sport=43401),  # cluster view still serves
    ]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert outs[0].code == 2
    assert outs[1].code == 0
    assert _dev_dnat_key(dev, 1) == iputil.ip_to_key(EP6A)


def test_dual_stack_twin_services_coexist():
    """A dual-stack Service is TWO ServiceEntry rows (one per family, the
    metaProxier split): both families LB to their own endpoints in one
    mixed batch, and the policy plane sees post-DNAT tuples."""
    svc6 = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                        endpoints=[Endpoint(EP6A, 8080)])
    svc4 = ServiceEntry(cluster_ip=VIP4, port=80, protocol=6,
                        endpoints=[Endpoint(EP4, 8080)])
    step, state, drs, dsvc, po = _mk([svc6, svc4])
    pkts = [
        _pkt(CLIENT6, VIP6, 80, sport=43500),
        _pkt(CLIENT4, VIP4, 80, sport=43501),
    ]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert outs[0].svc_idx == 0 and outs[1].svc_idx == 1
    assert _dev_dnat_key(dev, 0) == iputil.ip_to_key(EP6A)
    assert _dev_dnat_key(dev, 1) == iputil.ip_to_key(EP4)
    assert int(dev["dnat_ip_f"][1]) == int(
        flip_ips(np.array([iputil.ip_to_u32(EP4)], np.uint32))[0]
    )


def test_family_mismatch_raises_on_both_compilers():
    """Mixed-family endpoints or external IPs are a config error on BOTH
    engines (family purity, one ServiceEntry per family)."""
    bad_ep = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                          endpoints=[Endpoint(EP4, 8080)])
    bad_ext = ServiceEntry(cluster_ip=VIP4, port=80, protocol=6,
                           endpoints=[Endpoint(EP4, 8080)],
                           external_ips=[EXT6])
    for bad in (bad_ep, bad_ext):
        with pytest.raises(ValueError):
            compile_services([bad])
        with pytest.raises(ValueError):
            PipelineOracle(PolicySet(), [bad], dual_stack=True)


def test_v6_service_with_policy_on_post_dnat_tuple():
    """Policy evaluates the POST-DNAT tuple (EndpointDNAT before the
    policy tables): a drop rule on the v6 ENDPOINT fires for ClusterIP
    traffic that DNATs onto it."""
    ps = PolicySet()
    ps.applied_to_groups["web"] = cp.AppliedToGroup(
        name="web", members=[cp.GroupMember(ip=EP6A, node="n0")])
    ps.policies.append(cp.NetworkPolicy(
        uid="p", name="p", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["web"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(
                ip_blocks=[cp.IPBlock("2001:db8:0:2::/64")]),
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(EP6A, 8080)])
    step, state, drs, dsvc, po = _mk([svc], ps=ps)
    pkts = [
        _pkt(CLIENT6, VIP6, 80, sport=43600),   # DNAT -> EP6A -> dropped
        _pkt("2001:db8:ffff::9", VIP6, 80, sport=43601),  # other src: allowed
    ]
    state, dev, outs = _step_both(step, state, drs, dsvc, po, pkts, now=1)
    assert outs[0].code == 1
    assert outs[1].code == 0
