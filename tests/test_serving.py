"""Serving batcher (ROADMAP item 3, batching half): canonical-shape
admission in front of the engines.

Pillars pinned here:

1. COMPILE BOUND — 64 uneven tenant worlds under `gen_bursty` arrivals
   produce at most rungs x len(canonical_sizes) XLA step executables
   (counted via the jit cache), never one per traffic-shaped lane count.
2. LANE EXACTNESS — `step_tenants` through the batcher de-interleaves
   back to per-lane verdicts that match the oracle AND the unbatched
   per-tenant dispatch, `n_miss` bookkeeping included; padded lanes are
   masked (`valid`), never visible in results or state.
3. DEADLINE DETERMINISM — the depth-OR-deadline flush runs on the
   maintenance tick clock, so a `FaultClock` drives a deadline flush at
   the EXACT configured tick, replayably.
4. OFF == OFF — with the batcher off (or merely unused), `step()` traces
   the identical program: zero new executables, identical verdicts.
5. PLANE EXCLUSION — elastic reshard and tenant creation refuse each
   other symmetrically with typed ConfigErrors naming the other plane.
"""

import copy

import numpy as np
import pytest

import jax

from antrea_tpu.config import ConfigError
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.dissemination.faults import FaultClock
from antrea_tpu.serving import ServingBatcher
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.simulator.traffic import gen_bursty

QUOTA = 1 << 8
AFFQ = 1 << 6
KW = dict(flow_slots=1 << 10, aff_slots=1 << 8, flightrec_slots=256,
          realization_slots=0)


def _dp(cls, cluster=None, **extra):
    kw = dict(KW)
    kw.update(extra)
    ps = None if cluster is None else copy.deepcopy(cluster.ps)
    return cls(ps, **kw) if ps is not None else cls(**kw)


def _batch(cluster, n, seed):
    return gen_traffic(cluster.pod_ips, n, n_flows=max(8, n // 2),
                       seed=seed)


# ---------------------------------------------------------------------------
# config surface


def test_batcher_config_rejections():
    dummy = object()
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, canonical_sizes=())
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, canonical_sizes=(8, 24))  # 24 not pow2
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, canonical_sizes=(32, 8))  # not ascending
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, canonical_sizes=(8, 8))  # duplicate rung
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, flush_depth=0)
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, flush_deadline=0)
    with pytest.raises(ConfigError):
        ServingBatcher(dummy, canonical_sizes=(8,), flush_depth=8,
                       ring_slots=4)  # ring can't hold one flush


def test_submit_unknown_tenant_raises():
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=41)
    dp = _dp(TpuflowDatapath, c, serving_batcher=True,
             canonical_sizes=(8,))
    with pytest.raises(KeyError):
        dp.serving_batcher().submit(_batch(c, 4, seed=1), 0.0, tenant=99)


# ---------------------------------------------------------------------------
# pillar 1: the compile bound


def test_compile_bound_64_uneven_tenants_under_bursty():
    """64 tenants on 4 rung shapes, trickling bursty arrivals: XLA step
    executables stay under rungs x len(canonical_sizes) — the ladder is
    the bound, traffic shape is irrelevant."""
    from antrea_tpu.models import forwarding as fwd_model

    shapes = [gen_cluster(n, n_nodes=2, pods_per_node=8, seed=s)
              for n, s in ((6, 1), (20, 2), (45, 3), (100, 4))]
    ladder = (8, 32)
    dp = _dp(TpuflowDatapath, None, flightrec_slots=0,
             serving_batcher=True, canonical_sizes=ladder,
             flush_deadline=2)
    exec0 = fwd_model.pipeline_step_full._cache_size()
    tids = []
    for i in range(64):
        c = shapes[i % 4]
        tids.append(dp.tenant_create(f"t{i}", copy.deepcopy(c.ps),
                                     quota=QUOTA, aff_quota=AFFQ))
    assert dp.tenant_count == 64
    rungs = dp.tenant_rungs()
    assert len(rungs) == 4

    # Bursty per-tenant trickle: uneven 1..6-lane sub-batches — WITHOUT
    # the ladder each distinct lane count per rung would compile fresh.
    sched = gen_bursty(shapes[0].pod_ips, 10, tenants=64, burst_lanes=6,
                       seed=17)
    now = 100
    served = 0
    for entry in sched:
        now += 1
        if entry is None:
            continue
        idx, batch = entry
        res = dp.step_tenants(np.asarray([tids[int(i)] for i in idx]),
                              batch, now)
        served += int(np.asarray(res.code).shape[0])
    assert served == sum(e[0].size for e in sched if e is not None)

    execs = fwd_model.pipeline_step_full._cache_size() - exec0
    bound = len(rungs) * len(ladder)
    assert 0 < execs <= bound, (
        f"{execs} step executables for 64 bursty tenants — the batcher "
        f"must bound compiles by rungs x ladder ({bound}), not traffic")
    st = dp.serving_stats()
    assert st["submitted_lanes"] == served
    assert st["shed_lanes"] == 0  # step_tenants path is lossless


# ---------------------------------------------------------------------------
# pillar 2: lane exactness


def _as_rows(res):
    """Per-lane comparable rows from a StepResult (scalar columns)."""
    code = np.asarray(res.code)
    est = np.asarray(res.est)
    committed = np.asarray(res.committed)
    return list(zip(code.tolist(), est.tolist(), committed.tolist(),
                    list(res.ingress_rule), list(res.egress_rule)))


@pytest.mark.parametrize("cls", [TpuflowDatapath, OracleDatapath])
def test_step_tenants_lane_exact_vs_unbatched(cls):
    """The batched mixed-tenant step returns exactly what per-tenant
    unbatched dispatch returns, lane for lane, and n_miss sums once per
    dispatch (not per padded lane)."""
    c0 = gen_cluster(8, n_nodes=2, pods_per_node=8, seed=11)
    c1 = gen_cluster(14, n_nodes=2, pods_per_node=8, seed=12)
    mk = lambda: _dp(cls, c0, serving_batcher=True,  # noqa: E731
                     canonical_sizes=(8, 32), flush_deadline=2)
    dp_b, dp_u = mk(), mk()
    t_b = dp_b.tenant_create("a", copy.deepcopy(c1.ps), quota=QUOTA,
                             aff_quota=AFFQ)
    t_u = dp_u.tenant_create("a", copy.deepcopy(c1.ps), quota=QUOTA,
                             aff_quota=AFFQ)

    batch = _batch(c0, 24, seed=5)
    lane_tids = np.asarray([0, t_b] * 12)
    res = dp_b.step_tenants(lane_tids, batch, 1.0)
    assert np.asarray(res.code).shape[0] == 24

    # Unbatched reference: same lanes through plain step/tenant_step.
    from antrea_tpu.datapath.tenancy import _sub_batch
    rows = [None] * 24
    n_miss = 0
    for tid_ref, tid_sel in ((0, 0), (t_u, t_b)):
        sel = np.nonzero(lane_tids == tid_sel)[0]
        sub = _sub_batch(batch, sel)
        r = (dp_u.step(sub, 1.0) if tid_ref == 0
             else dp_u.tenant_step(tid_ref, sub, 1.0))
        n_miss += int(r.n_miss)
        for lane, row in zip(sel, _as_rows(r)):
            rows[int(lane)] = row
    assert _as_rows(res) == rows
    assert int(res.n_miss) == n_miss  # padded lanes never count as misses


def test_step_tenants_oracle_parity_bursty():
    """Batched tpuflow == batched oracle over a bursty multi-tenant
    schedule (stateful across ticks: flow-cache hits included)."""
    c0 = gen_cluster(8, n_nodes=2, pods_per_node=8, seed=21)
    c1 = gen_cluster(12, n_nodes=2, pods_per_node=8, seed=22)
    dps = {}
    for cls in (TpuflowDatapath, OracleDatapath):
        dp = _dp(cls, c0, serving_batcher=True, canonical_sizes=(8, 32),
                 flush_deadline=2)
        t = dp.tenant_create("a", copy.deepcopy(c1.ps), quota=QUOTA,
                             aff_quota=AFFQ)
        dps[cls] = (dp, t)
    sched = gen_bursty(c0.pod_ips, 8, tenants=2, burst_lanes=5, seed=29)
    now = 10
    for entry in sched:
        now += 1
        if entry is None:
            continue
        idx, batch = entry
        outs = []
        for dp, t in dps.values():
            tids = np.where(np.asarray(idx) == 0, 0, t)
            outs.append(dp.step_tenants(tids, batch, now))
        a, b = outs
        assert np.array_equal(np.asarray(a.code), np.asarray(b.code))
        assert np.array_equal(np.asarray(a.committed),
                              np.asarray(b.committed))
        assert int(a.n_miss) == int(b.n_miss)


# ---------------------------------------------------------------------------
# pillar 3: deadline determinism on the FaultClock


def test_deadline_flush_at_exact_faultclock_tick():
    clk = FaultClock(start=0)
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=31)
    dp = _dp(TpuflowDatapath, c, serving_batcher=True,
             canonical_sizes=(8,), flush_deadline=3, maint_clock=clk)
    b = dp.serving_batcher()
    assert "serving-flush" in dp.maintenance.task_names

    tickets = b.submit(_batch(c, 3, seed=2), 0.0)  # sub-depth: waits
    assert (tickets >= 0).all()
    for _ in range(2):  # ticks 1, 2: due at neither
        clk.advance()
        assert b.tick_flush(0.0, budget=4) == 0
        assert all(b.poll(int(t)) is None for t in tickets)
        assert dp.serving_stats()["staged_lanes"] == 3
    clk.advance()  # tick 3 == flush_deadline: flush fires NOW
    assert b.tick_flush(0.0, budget=4) == 1
    outs = [b.poll(int(t)) for t in tickets]
    assert all(o is not None for o in outs)
    ev = dp._flightrec.events(kind="batch-flush")
    assert ev and ev[-1]["reason"] == "deadline"
    assert ev[-1]["age_ticks"] == 3
    # Flushed AT the deadline, not past it: no exceeded event.
    assert dp._flightrec.events(kind="batch-deadline-exceeded") == []
    assert dp.serving_stats()["flushes"]["deadline"] == 1


def test_deadline_exceeded_meters_and_emits():
    clk = FaultClock(start=0)
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=32)
    dp = _dp(TpuflowDatapath, c, serving_batcher=True,
             canonical_sizes=(8,), flush_deadline=2, maint_clock=clk)
    b = dp.serving_batcher()
    b.submit(_batch(c, 2, seed=3), 0.0)
    for _ in range(5):  # starve the flush well past the deadline
        clk.advance()
    assert b.tick_flush(0.0, budget=4) == 1
    ev = dp._flightrec.events(kind="batch-deadline-exceeded")
    assert len(ev) == 1 and ev[0]["age_ticks"] == 5
    assert dp.serving_stats()["deadline_exceeded"] == 1


def test_depth_flush_and_ring_overflow_shed():
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=33)
    dp = _dp(TpuflowDatapath, c, serving_batcher=True,
             canonical_sizes=(8,), flush_depth=8, serving_ring_slots=16,
             flush_deadline=64)
    b = dp.serving_batcher()
    b.submit(_batch(c, 8, seed=4), 1.0)
    assert b.tick_flush(1.0, budget=4) == 1  # depth-due, deadline far off
    st = dp.serving_stats()
    assert st["flushes"]["depth"] == 1 and st["staged_lanes"] == 0

    # shed=True: lanes beyond ring_slots tail-drop with -1 tickets.
    tk = b.submit(_batch(c, 20, seed=5), 2.0)
    assert (tk[:16] >= 0).all() and (tk[16:] == -1).all()
    assert dp.serving_stats()["shed_lanes"] == 4
    # shed=False on the same overflow force-flushes instead of dropping.
    tk2 = b.submit(_batch(c, 20, seed=6), 3.0, shed=False)
    assert (tk2 >= 0).all()
    assert dp.serving_stats()["flushes"]["overflow"] >= 1


# ---------------------------------------------------------------------------
# pillar 4: batcher off == bit-identical step


def test_step_traces_identically_with_batcher_configured():
    """`step()` with the batcher merely configured compiles ZERO new
    executables vs the batcher-less engine and returns identical
    verdicts — the unbatched path is untouched (valid=None traces the
    same program)."""
    from antrea_tpu.models import forwarding as fwd_model

    c = gen_cluster(10, n_nodes=2, pods_per_node=8, seed=51)
    batch = _batch(c, 32, seed=7)
    dp_off = _dp(TpuflowDatapath, c)
    r_off = dp_off.step(batch, 1.0)
    exec0 = fwd_model.pipeline_step_full._cache_size()
    dp_on = _dp(TpuflowDatapath, c, serving_batcher=True,
                canonical_sizes=(8, 32))
    r_on = dp_on.step(batch, 1.0)
    assert fwd_model.pipeline_step_full._cache_size() == exec0, (
        "step() with the batcher configured must reuse the exact "
        "executable of the batcher-less engine (valid=None is not a "
        "program change)")
    assert np.array_equal(np.asarray(r_off.code), np.asarray(r_on.code))
    assert int(r_off.n_miss) == int(r_on.n_miss)


# ---------------------------------------------------------------------------
# pillar 5: reshard-vs-tenant composition (PR 20 — the PR 18 mutual
# refusals are GONE; tests/test_tenant_reshard.py drives the full arcs)


@pytest.fixture(scope="module")
def mesh_world():
    from antrea_tpu.parallel import MeshDatapath, mesh as pm
    from antrea_tpu.simulator.genservice import gen_services

    cluster = gen_cluster(30, n_nodes=4, pods_per_node=8, seed=61)
    services = gen_services(4, cluster.pod_ips, seed=62)
    mesh = pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])
    return MeshDatapath, cluster, services, mesh


def test_reshard_begin_accepts_tenants(mesh_world):
    MeshDatapath, cluster, services, mesh = mesh_world
    mdp = MeshDatapath(cluster.ps, services, mesh=mesh,
                       flow_slots=1 << 10, aff_slots=1 << 8,
                       canary_probes=16)
    c1 = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=63)
    tid = mdp.tenant_create("t", copy.deepcopy(c1.ps), quota=QUOTA)
    mdp.reshard_begin(4)  # the old tenancy-plane refusal is gone
    assert mdp.reshard_status() is not None
    assert mdp.reshard_stats()["tenant_worlds_migrating"] == 1
    assert mdp.tenant_stats()[tid]["latched"] == 0


def test_tenant_create_adopts_during_reshard(mesh_world):
    MeshDatapath, cluster, services, mesh = mesh_world
    mdp = MeshDatapath(cluster.ps, services, mesh=mesh,
                       flow_slots=1 << 10, aff_slots=1 << 8,
                       canary_probes=16)
    mdp.reshard_begin(4)
    c1 = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=64)
    # The old resharding-plane refusal is gone: the newborn world is
    # adopted mid-flight (reshard.note_world_created) so the cutover
    # flips and certifies it with the rest of the fleet.
    tid = mdp.tenant_create("t", copy.deepcopy(c1.ps), quota=QUOTA)
    assert mdp.reshard_status() is not None
    assert mdp.reshard_stats()["tenant_worlds_migrating"] == 1
    assert tid in mdp.tenant_stats()


@pytest.mark.parametrize("cls", [TpuflowDatapath, OracleDatapath])
def test_tenant_create_ignores_reshard_marker_both_engines(cls):
    """The tenancy-side refusal is gone engine-generically: an in-flight
    reshard marker no longer blocks world creation (the mesh plane
    adopts via note_world_created; single-chip engines carry no plane to
    join, so creation simply proceeds)."""
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=65)
    dp = _dp(cls, c)
    dp._reshard = object()  # simulate an in-flight resize marker
    c1 = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=66)
    tid = dp.tenant_create("t", copy.deepcopy(c1.ps), quota=QUOTA)
    assert tid in dp.tenant_stats()


# ---------------------------------------------------------------------------
# traffic generator + observability surfaces


def test_gen_bursty_deterministic_and_tenant_scoped():
    c = gen_cluster(6, n_nodes=2, pods_per_node=4, seed=71)
    s1 = gen_bursty(c.pod_ips, 12, tenants=3, seed=9)
    s2 = gen_bursty(c.pod_ips, 12, tenants=[0, 1, 2], seed=9)
    assert len(s1) == 12
    for a, b in zip(s1, s2):
        assert (a is None) == (b is None)
        if a is None:
            continue
        assert np.array_equal(a[0], b[0])
        assert np.array_equal(np.asarray(a[1].src_ip),
                              np.asarray(b[1].src_ip))
        assert set(np.unique(a[0])) <= {0, 1, 2}
        assert a[0].shape[0] == a[1].size
    assert any(e is not None for e in s1)


def test_serving_metrics_render_and_stats():
    c0 = gen_cluster(8, n_nodes=2, pods_per_node=8, seed=81)
    c1 = gen_cluster(10, n_nodes=2, pods_per_node=8, seed=82)
    dp = _dp(TpuflowDatapath, c0, serving_batcher=True,
             canonical_sizes=(8, 32), flush_deadline=2)
    t = dp.tenant_create("a", copy.deepcopy(c1.ps), quota=QUOTA,
                         aff_quota=AFFQ)
    batch = _batch(c0, 12, seed=8)
    dp.step_tenants(np.asarray([0, t] * 6), batch, 1.0)

    st = dp.serving_stats()
    assert st["submitted_lanes"] == 12
    assert st["flushed_lanes"] == 12
    assert set(st["worlds"]) == {0, t}
    assert st["worlds"][t]["flushed_lanes"] == 6

    from antrea_tpu.observability.metrics import render_metrics
    txt = render_metrics(dp, node="n0")
    for fam in ("antrea_tpu_serving_submitted_lanes_total",
                "antrea_tpu_serving_dispatches_total",
                "antrea_tpu_serving_flushes_total",
                "antrea_tpu_serving_wait_ticks_bucket"):
        assert fam in txt, f"{fam} missing from exposition"
    # Engines without the batcher render no serving families.
    dp_off = _dp(TpuflowDatapath, c0)
    assert "antrea_tpu_serving" not in render_metrics(dp_off, node="n0")
