"""L7 NetworkPolicy seam: matched-allow traffic marks for the L7 engine.

The reference enforces L7 rules by redirecting their matches to Suricata
over a VLAN tap (network_policy.go:2213 l7NPTrafficControlFlows; reg0 L7
bit in fields.go).  Here the datapath emits l7_redirect for packets whose
DECIDING allow rule carries L7 protocols — the handoff seam, with the
inspection engine itself out of scope exactly as in SURVEY §2.5."""

import copy

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.features import FeatureGates
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

WEB, CLIENT, OTHER = "10.0.0.10", "10.0.0.12", "10.0.0.13"


def _controller():
    ctl = NetworkPolicyController(
        feature_gates=FeatureGates({"L7NetworkPolicy": True})
    )
    ctl.upsert_namespace(crd.Namespace(name="prod", labels={}))
    for name, ip, labels in [
        ("web", WEB, {"app": "web"}),
        ("client", CLIENT, {"app": "client"}),
        ("other", OTHER, {"app": "other"}),
    ]:
        ctl.upsert_pod(crd.Pod(namespace="prod", name=name, ip=ip,
                               node="n1", labels=labels))
    return ctl


def _anp_l7():
    return crd.AntreaNetworkPolicy(
        uid="acnp-l7", name="l7-http", namespace="",
        tier_priority=cp.TIER_APPLICATION, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[
            crd.AntreaNPRule(
                direction=cp.Direction.IN, action=cp.RuleAction.ALLOW,
                peers=[crd.AntreaPeer(
                    pod_selector=crd.LabelSelector.make({"app": "client"}),
                    ns_selector=crd.LabelSelector.make(),
                )],
                l7_protocols=("http",),
            ),
            crd.AntreaNPRule(
                direction=cp.Direction.IN, action=cp.RuleAction.ALLOW,
                peers=[crd.AntreaPeer(
                    pod_selector=crd.LabelSelector.make({"app": "other"}),
                    ns_selector=crd.LabelSelector.make(),
                )],
            ),
        ],
    )


def _b(src, dst):
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([40000], np.int32),
        dst_port=np.array([80], np.int32),
    )


def test_l7_mark_on_deciding_rule():
    ctl = _controller()
    ctl.upsert_antrea_policy(_anp_l7())
    ps = ctl.policy_set()
    tpu = TpuflowDatapath(copy.deepcopy(ps), flow_slots=1 << 10,
                          aff_slots=1 << 8, miss_chunk=32)
    orc = OracleDatapath(copy.deepcopy(ps), flow_slots=1 << 10,
                         aff_slots=1 << 8)
    for t, (src, want) in enumerate([
        (CLIENT, 1),  # decided by the L7 http rule -> redirect
        (OTHER, 0),   # decided by the plain allow rule -> normal output
    ]):
        b = _b(src, WEB)
        ra, rb = tpu.step(b, now=t + 1), orc.step(b, now=t + 1)
        assert ra.code.tolist() == rb.code.tolist() == [0]
        assert ra.l7_redirect.tolist() == rb.l7_redirect.tolist() == [want]
        # Cached hit keeps the mark (attribution rides the flow entry).
        ra2, rb2 = tpu.step(b, now=t + 10), orc.step(b, now=t + 10)
        assert ra2.est.tolist() == [1]
        assert ra2.l7_redirect.tolist() == rb2.l7_redirect.tolist() == [want]


def test_l7_validation_and_gate():
    ctl = _controller()
    bad = _anp_l7()
    bad.rules[0].action = cp.RuleAction.DROP
    with pytest.raises(ValueError):
        ctl.upsert_antrea_policy(bad)
    gated = NetworkPolicyController()  # default gates: L7 off
    gated.upsert_namespace(crd.Namespace(name="prod", labels={}))
    with pytest.raises(RuntimeError):
        gated.upsert_antrea_policy(_anp_l7())
    # Rejected policies leak NOTHING: validation runs before conversion,
    # so no group refs or watch events exist for them.
    assert ctl.policy_set().applied_to_groups == {}
    assert gated.policy_set().applied_to_groups == {}


def test_l7_attribution_survives_rebundle_both_datapaths():
    """ADVICE round-3: cached attribution follows rule IDENTITY across a
    renumbering bundle (TpuflowDatapath._remap_cached_attribution / the
    oracle's identity filter): an established L7-allowed connection keeps
    its l7_redirect mark and per-rule stats attribution after an unrelated
    policy renumbers the rule table; removing the deciding rule drops
    attribution to none on BOTH datapaths."""
    from antrea_tpu.features import FeatureGates

    gates = FeatureGates({"L7NetworkPolicy": True, "AntreaPolicy": True,
                          "NetworkPolicyStats": True, "Traceflow": True})
    ctl = _controller()
    ctl.upsert_antrea_policy(_anp_l7())
    ps1 = copy.deepcopy(ctl.policy_set())

    # A second, earlier-tier policy inserted later renumbers everything.
    ctl.upsert_antrea_policy(crd.AntreaNetworkPolicy(
        uid="acnp-front", name="front", namespace="",
        tier_priority=cp.TIER_SECURITYOPS, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "nothing"}),
            ns_selector=crd.LabelSelector.make(),
        )],
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP,
                                peers=[crd.AntreaPeer(
                                    ip_block=crd.IPBlock("192.0.2.0/24"))])],
    ))
    ps2 = copy.deepcopy(ctl.policy_set())

    ctl.delete_policy("acnp-l7")
    ps3 = copy.deepcopy(ctl.policy_set())

    def probe(dp, now):
        batch = PacketBatch(
            src_ip=np.array([iputil.ip_to_u32(CLIENT)], np.uint32),
            dst_ip=np.array([iputil.ip_to_u32(WEB)], np.uint32),
            proto=np.array([6], np.int32),
            src_port=np.array([41000], np.int32),
            dst_port=np.array([80], np.int32),
        )
        return dp.step(batch, now)

    for dp in (TpuflowDatapath(copy.deepcopy(ps1), [], flow_slots=1 << 10,
                               aff_slots=1 << 6, miss_chunk=16,
                               feature_gates=gates),
               OracleDatapath(copy.deepcopy(ps1), [], flow_slots=1 << 10,
                              aff_slots=1 << 6, feature_gates=gates)):
        t = dp.datapath_type
        r = probe(dp, now=1)
        assert int(r.code[0]) == 0 and int(r.l7_redirect[0]) == 1, t
        rule_id_before = r.ingress_rule[0]
        assert rule_id_before is not None, t

        # Renumbering bundle: established hit keeps identity + L7 mark.
        dp.install_bundle(ps=copy.deepcopy(ps2))
        r = probe(dp, now=2)
        assert int(r.est[0]) == 1, t
        assert r.ingress_rule[0] == rule_id_before, t
        assert int(r.l7_redirect[0]) == 1, t

        # Deciding rule removed: attribution drops to none, L7 mark off.
        dp.install_bundle(ps=copy.deepcopy(ps3))
        r = probe(dp, now=3)
        assert int(r.est[0]) == 1, t  # connection itself survives
        assert r.ingress_rule[0] is None, t
        assert int(r.l7_redirect[0]) == 0, t
