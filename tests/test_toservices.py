"""ACNP `toServices` egress peer kind (ISSUE 3 satellite; ref crd
types.go:598, controller resolution antreanetworkpolicy.go:130-131, agent
ServiceGroupID conjunction): controlplane type -> compiler lowering into
the svc-key reference sub-space -> oracle parity on both engines.

The discriminating property (which an IP-space lowering could not
express): traffic addressed to ANY frontend of the referenced Service
matches, while traffic sent DIRECTLY to the very same endpoint does not.
"""

import numpy as np
import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.controller.admission import AdmissionDenied
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

CLIENT = "10.0.1.1"
DB_EP, WEB_EP = "10.0.2.2", "10.0.3.3"
NODE_IP = "172.16.0.9"

SVCS = [
    ServiceEntry(cluster_ip="10.96.0.10", port=5432, protocol=6,
                 name="db", namespace="prod",
                 endpoints=[Endpoint(ip=DB_EP, port=5432)],
                 node_port=30032),
    ServiceEntry(cluster_ip="10.96.0.11", port=80, protocol=6,
                 name="web", namespace="prod",
                 endpoints=[Endpoint(ip=WEB_EP, port=8080)]),
]


def _ps():
    return PolicySet(
        policies=[cp.NetworkPolicy(
            uid="deny-db", name="deny-db", type=cp.NetworkPolicyType.ACNP,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.OUT,
                to_peer=cp.NetworkPolicyPeer(to_services=[
                    cp.ServiceReference(name="db", namespace="prod")]),
                action=cp.RuleAction.DROP, priority=0)],
            applied_to_groups=["clients"], tier_priority=250, priority=1.0,
        )],
        applied_to_groups={"clients": cp.AppliedToGroup(
            name="clients", members=[cp.GroupMember(ip=CLIENT)])},
    )


def _pkt(src, dst, dport, sport=40000):
    return Packet(src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
                  proto=6, src_port=sport, dst_port=dport)


def _mk(cls, ps, svcs=SVCS):
    kw = {"miss_chunk": 16} if cls is TpuflowDatapath else {}
    return cls(ps, svcs, flow_slots=1 << 10, aff_slots=1 << 4,
               node_ips=[NODE_IP], node_name="n1", **kw)


@pytest.mark.parametrize("cls", [TpuflowDatapath, OracleDatapath])
def test_toservices_matches_frontends_not_endpoints(cls):
    """Every frontend of the referenced Service (ClusterIP + NodePort)
    drops; DIRECT traffic to the same endpoint — and to other services —
    is untouched."""
    dp = _mk(cls, _ps())
    probes = [
        _pkt(CLIENT, "10.96.0.10", 5432),         # db ClusterIP -> DROP
        _pkt(CLIENT, NODE_IP, 30032),             # db NodePort  -> DROP
        _pkt(CLIENT, DB_EP, 5432),                # direct to endpoint -> ALLOW
        _pkt(CLIENT, "10.96.0.11", 80),           # other service -> ALLOW
        _pkt("10.0.8.8", "10.96.0.10", 5432),     # other client  -> ALLOW
    ]
    r = dp.step(PacketBatch.from_packets(probes), now=5)
    assert list(r.code) == [1, 1, 0, 0, 0]
    assert r.egress_rule[0] == r.egress_rule[1] == "deny-db/Out/0"
    assert r.egress_rule[2] is None
    # Cached entries replay the verdict (fresh tuples on re-probe).
    probes2 = [_pkt(CLIENT, "10.96.0.10", 5432, sport=40001),
               _pkt(CLIENT, DB_EP, 5432, sport=40001)]
    r2 = dp.step(PacketBatch.from_packets(probes2), now=6)
    assert list(r2.code) == [1, 0]


def test_toservices_device_oracle_parity_randomized():
    a, b = _mk(TpuflowDatapath, _ps()), _mk(OracleDatapath, _ps())
    rng = np.random.default_rng(7)
    dsts = [("10.96.0.10", 5432), (NODE_IP, 30032), (DB_EP, 5432),
            ("10.96.0.11", 80), (WEB_EP, 8080), ("10.0.7.7", 443)]
    for now in range(1, 4):
        pkts = []
        for _ in range(24):
            d, dport = dsts[int(rng.integers(len(dsts)))]
            src = CLIENT if rng.random() < 0.6 else "10.0.8.8"
            pkts.append(_pkt(src, d, dport,
                             sport=int(rng.integers(41000, 41100))))
        ra = a.step(PacketBatch.from_packets(pkts), now)
        rb = b.step(PacketBatch.from_packets(pkts), now)
        assert list(ra.code) == list(rb.code)
        assert ra.egress_rule == rb.egress_rule
        assert list(ra.svc_idx) == list(rb.svc_idx)


@pytest.mark.parametrize("cls", [TpuflowDatapath, OracleDatapath])
def test_toservices_service_set_changes_track(cls):
    """Service-only bundles renumber the service list; the reference
    lowering follows IDENTITY (a reorder keeps matching, a deletion makes
    the reference dangle -> matches nothing)."""
    dp = _mk(cls, _ps())
    r = dp.step(PacketBatch.from_packets(
        [_pkt(CLIENT, "10.96.0.10", 5432)]), now=1)
    assert list(r.code) == [1]
    # Reorder: indices shift, identity keeps matching (fresh tuple).
    dp.install_bundle(services=[SVCS[1], SVCS[0]])
    r2 = dp.step(PacketBatch.from_packets(
        [_pkt(CLIENT, "10.96.0.10", 5432, sport=40002)]), now=2)
    assert list(r2.code) == [1]
    # Delete db: the reference dangles; its old ClusterIP is no longer a
    # service frontend and classifies by address alone (fresh tuple).
    dp.install_bundle(services=[SVCS[1]])
    r3 = dp.step(PacketBatch.from_packets(
        [_pkt(CLIENT, "10.96.0.10", 5432, sport=40003)]), now=3)
    assert list(r3.code) == [0]


def test_controller_conversion_and_admission():
    """crd AntreaPeer.to_services -> internal ServiceReference peers via
    the NP controller; admission rejects the combinations the reference
    rejects (toServices in ingress / with ports / with other peer
    fields)."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(crd.Namespace(name="default"))
    ctl.upsert_pod(crd.Pod(namespace="default", name="c1", ip=CLIENT,
                           node="n1", labels={"app": "client"}))

    def acnp(rules, uid="ts1"):
        return crd.AntreaNetworkPolicy(
            uid=uid, name=uid, namespace="", tier_priority=250, priority=1,
            applied_to=[crd.AntreaAppliedTo(
                pod_selector=crd.LabelSelector.make({"app": "client"}),
                ns_selector=crd.LabelSelector.make())],
            rules=rules,
        )

    ref = crd.ServiceReference(name="db", namespace="prod")
    ctl.upsert_antrea_policy(acnp([crd.AntreaNPRule(
        direction=cp.Direction.OUT, action=cp.RuleAction.DROP,
        peers=[crd.AntreaPeer(to_services=(ref,))])]))
    ps = ctl.policy_set()
    [np_] = [p for p in ps.policies if p.uid == "ts1"]
    assert np_.rules[0].to_peer.to_services == [
        cp.ServiceReference(name="db", namespace="prod")]

    # The converted set enforces on both engines (full path: crd ->
    # controller -> compiler -> verdict).
    for cls in (TpuflowDatapath, OracleDatapath):
        dp = _mk(cls, ps)
        r = dp.step(PacketBatch.from_packets(
            [_pkt(CLIENT, "10.96.0.10", 5432),
             _pkt(CLIENT, DB_EP, 5432)]), now=1)
        assert list(r.code) == [1, 0], cls

    with pytest.raises(AdmissionDenied):
        ctl.upsert_antrea_policy(acnp([crd.AntreaNPRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(to_services=(ref,))])], uid="bad1"))
    with pytest.raises(AdmissionDenied):
        ctl.upsert_antrea_policy(acnp([crd.AntreaNPRule(
            direction=cp.Direction.OUT, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(to_services=(ref,))],
            ports=[crd.PortSpec(protocol=6, port=5432)])], uid="bad2"))
    with pytest.raises(AdmissionDenied):
        ctl.upsert_antrea_policy(acnp([crd.AntreaNPRule(
            direction=cp.Direction.OUT, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(
                to_services=(ref,),
                ip_block=crd.IPBlock("10.0.0.0/8"))])], uid="bad3"))
    # toServices must be the rule's ONLY peer (upstream rejects it
    # combined with `to`): a sibling selector peer would otherwise be
    # silently dropped by the merged lowering.
    with pytest.raises(AdmissionDenied):
        ctl.upsert_antrea_policy(acnp([crd.AntreaNPRule(
            direction=cp.Direction.OUT, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(to_services=(ref,)),
                   crd.AntreaPeer(pod_selector=crd.LabelSelector.make(
                       {"app": "victim"}))])], uid="bad4"))
    # The compiler itself refuses a merged peer that bypassed admission.
    from antrea_tpu.compiler.compile import compile_policy_set
    bad_ps = _ps()
    bad_ps.policies[0].rules[0].to_peer.ip_blocks = [cp.IPBlock("10.0.0.0/8")]
    with pytest.raises(ValueError):
        compile_policy_set(bad_ps, services=SVCS)


def test_toservices_serde_round_trip():
    from antrea_tpu.dissemination import serde

    ps = _ps()
    doc = serde.encode_policy_set(ps)
    back = serde.decode_policy_set(doc)
    peer = back.policies[0].rules[0].to_peer
    assert peer.to_services == [
        cp.ServiceReference(name="db", namespace="prod")]
