import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.compile import (
    _svc_key_ranges,
    compile_policy_set,
)
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.simulator import gen_cluster
from antrea_tpu.utils import ip as iputil


def test_svc_key_ranges_any():
    # FULL_SPACE spans the combined dual-stack keyspace; the svc key space
    # only occupies its low 2^24, which the range trivially covers.
    assert _svc_key_ranges([]) == ((0, iputil.KEYSPACE_END),)


def test_svc_key_ranges_tcp_port():
    r = _svc_key_ranges([cp.Service(protocol=cp.PROTO_TCP, port=80)])
    assert r == ((cp.PROTO_TCP << 16 | 80, cp.PROTO_TCP << 16 | 81),)


def test_svc_key_ranges_port_range():
    r = _svc_key_ranges([cp.Service(protocol=cp.PROTO_TCP, port=80, end_port=90)])
    assert r == ((cp.PROTO_TCP << 16 | 80, cp.PROTO_TCP << 16 | 91),)


def test_svc_key_ranges_port_65535():
    # Regression: range ending at 65535 crosses into bit 16; OR-packing would
    # corrupt the end key for odd protocol numbers (e.g. UDP=17).
    r = _svc_key_ranges([cp.Service(protocol=cp.PROTO_UDP, port=60000, end_port=65535)])
    key = cp.PROTO_UDP << 16 | 65535
    assert any(lo <= key < hi for lo, hi in r)
    r32 = _svc_key_ranges([cp.Service(protocol=cp.PROTO_UDP, port=65535)])
    assert any(lo <= key < hi for lo, hi in r32)
    assert not any(lo <= (cp.PROTO_UDP << 16 | 65534) < hi for lo, hi in r32)


def test_svc_key_ranges_proto_only():
    r = _svc_key_ranges([cp.Service(protocol=cp.PROTO_UDP)])
    assert r == ((cp.PROTO_UDP << 16, (cp.PROTO_UDP + 1) << 16),)


def test_svc_key_ranges_icmp_ignores_port():
    r = _svc_key_ranges([cp.Service(protocol=cp.PROTO_ICMP, port=80)])
    assert r == ((cp.PROTO_ICMP << 16, (cp.PROTO_ICMP + 1) << 16),)


def test_svc_key_ranges_wildcard_proto_with_port():
    # protocol=None + port: TCP/UDP/SCTP constrained, other protos full rows.
    r = _svc_key_ranges([cp.Service(port=443)])
    # ICMP (proto 1) full row must be covered:
    key_icmp = cp.PROTO_ICMP << 16 | 7
    assert any(lo <= key_icmp < hi for lo, hi in r)
    # TCP port 443 in, 444 out:
    assert any(lo <= (cp.PROTO_TCP << 16 | 443) < hi for lo, hi in r)
    assert not any(lo <= (cp.PROTO_TCP << 16 | 444) < hi for lo, hi in r)


def test_compile_dedupes_groups():
    cluster = gen_cluster(500, seed=3)
    cps = compile_policy_set(cluster.ps)
    n_rules = cps.ingress.n_rules + cps.egress.n_rules
    # Content-addressing must keep group count well below rule count.
    assert cps.n_ip_groups < n_rules
    assert cps.n_svc_groups < n_rules // 2
    # Phase segment bookkeeping is consistent.
    for d in (cps.ingress, cps.egress):
        assert d.n_phase0 + d.n_k8s + d.n_baseline == len([r for r in d.rule_ids if r])


def test_bitmap_membership_matches_scalar():
    """The ACTUAL compiled interval table + bitmap must agree with scalar
    range membership for every named address group, on random IPs, pod IPs,
    and exact interval boundaries (the edge-sensitive values)."""
    cluster = gen_cluster(200, seed=11)
    ps = cluster.ps
    cps = compile_policy_set(ps)

    # Un-flip the device bounds back to unsigned space.
    bounds_u = (cps.ip_bounds.view(np.uint32) ^ np.uint32(0x80000000)).astype(np.uint64)
    assert (np.diff(bounds_u.astype(np.int64)) > 0).all()  # sorted, unique

    rng = np.random.default_rng(0)
    samples = np.unique(
        np.concatenate(
            [
                rng.integers(0, 1 << 32, size=256, dtype=np.uint64),
                np.asarray(cluster.pod_ips, dtype=np.uint64),
                bounds_u,  # exact boundaries
                np.clip(bounds_u.astype(np.int64) - 1, 0, None).astype(np.uint64),
                np.array([0, (1 << 32) - 1], dtype=np.uint64),
            ]
        )
    )
    ivs = np.searchsorted(bounds_u, samples, side="right")

    checked = 0
    for name, g in ps.address_groups.items():
        gid = cps.ag_gids[name]
        ranges = g.ranges()
        bits = (cps.ip_bitmap[ivs, gid >> 5] >> np.uint32(gid & 31)) & 1
        want = np.array(
            [any(lo <= ip < hi for lo, hi in ranges) for ip in samples], dtype=np.uint32
        )
        np.testing.assert_array_equal(bits, want, err_msg=name)
        checked += 1
    assert checked > 20
