"""Multi-tenant serving plane (datapath/tenancy.py) — the round-9
acceptance suite.

The three contract pillars, each proved as a test:

  * PARITY — a packed N-tenant instance serves every tenant bitwise
    like N independent single-tenant instances (scalar oracle, tpuflow
    sync, tpuflow async and mesh modes).  Rung padding (phase
    capacities, entry axes) must be semantically invisible.
  * ISOLATION — one tenant's churn/attack storm evicts ZERO of another
    tenant's established flows (structural per-world quota tables) and
    its miss-queue admissions clamp at its in-queue quota (metered +
    journaled); one tenant's canary veto rolls back and degrades ONLY
    that tenant.
  * SHARED COMPILES — over 64 uneven tenants, XLA step-executable count
    equals the occupied rung-signature count, never the tenant count.
"""

import copy

import numpy as np
import pytest

from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.config import ConfigError
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.datapath.commit import CanaryMismatchError
from antrea_tpu.dissemination.faults import FaultPlan
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic
from antrea_tpu.simulator.traffic import gen_cache_thrash, gen_syn_flood

QUOTA = 1 << 8
AFFQ = 1 << 6


def _worlds(n=2, base_seed=11, rule_counts=(8, 70)):
    """n tenant worlds: (cluster, services=None) with uneven rule sets."""
    return [gen_cluster(rule_counts[i % len(rule_counts)], n_nodes=2,
                        pods_per_node=8, seed=base_seed + i)
            for i in range(n)]


def _batch(cluster, n, seed):
    return gen_traffic(cluster.pod_ips, n, n_flows=max(8, n // 2),
                       seed=seed)


def _packed(cls, clusters, **kw):
    dp = cls(flow_slots=1 << 10, aff_slots=1 << 8, flightrec_slots=256,
             realization_slots=16, **kw)
    tids = [dp.tenant_create(f"t{i}", copy.deepcopy(c.ps), quota=QUOTA,
                             aff_quota=AFFQ)
            for i, c in enumerate(clusters)]
    return dp, tids


def _single(cls, cluster, **kw):
    return cls(copy.deepcopy(cluster.ps), flow_slots=QUOTA, aff_slots=AFFQ,
               flightrec_slots=0, realization_slots=0, **kw)


def _assert_result_parity(a, b, *, est=True, rules=True):
    assert a.code.tolist() == b.code.tolist()
    if est:
        assert a.est.tolist() == b.est.tolist()
        assert a.committed.tolist() == b.committed.tolist()
        assert a.reply.tolist() == b.reply.tolist()
    assert a.svc_idx.tolist() == b.svc_idx.tolist()
    assert a.dnat_ip.tolist() == b.dnat_ip.tolist()
    assert a.dnat_port.tolist() == b.dnat_port.tolist()
    assert a.reject_kind.tolist() == b.reject_kind.tolist()
    if rules:
        # Stable rule IDS (not indices): rung padding renumbers indices
        # but attribution resolves to the identical id strings.
        assert a.ingress_rule == b.ingress_rule
        assert a.egress_rule == b.egress_rule


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_packed_vs_single_tenant_parity(cls):
    """Acceptance pillar 1: every tenant in a packed instance matches an
    independent single-tenant instance bitwise — fresh round (miss +
    classify + commit) AND established round (cache hits), rule-id
    attribution and per-rule stats included."""
    clusters = _worlds()
    dp, tids = _packed(cls, clusters)
    singles = [_single(cls, c) for c in clusters]
    for rnd, now in enumerate((100, 101)):
        for i, (tid, c) in enumerate(zip(tids, clusters)):
            b = _batch(c, 64, seed=40 + i)
            got = dp.tenant_step(tid, b, now)
            want = singles[i].step(b, now)
            _assert_result_parity(got, want)
    for i, tid in enumerate(tids):
        got = dp.tenant_datapath_stats(tid)
        want = singles[i].stats()
        assert got.ingress == want.ingress
        assert got.egress == want.egress
        assert got.default_allow == want.default_allow
        assert got.default_deny == want.default_deny
        # The conntrack dump decodes identically (same quota rung).
        assert (sorted(map(str, dp.tenant_dump_flows(tid, 102)))
                == sorted(map(str, singles[i].dump_flows(102))))


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_packed_async_parity(cls):
    """Pillar 1 in ASYNC slow-path mode: tenant misses carry the tenant
    column through the shared queue, drains classify each row in its
    owner's world, and the post-drain cache matches the single-tenant
    async twin's."""
    # Same-shaped worlds (distinct seeds): the drain partition + queue
    # tenant column are under test here, not rung diversity (the sync
    # parity test owns that) — one rung halves the compile volume.
    clusters = _worlds(2, rule_counts=(8, 8))
    kw = dict(async_slowpath=True, miss_queue_slots=1 << 10,
              drain_batch=64)
    dp, tids = _packed(cls, clusters, **kw)
    singles = [_single(cls, c, **kw) for c in clusters]
    bats = [_batch(c, 48, seed=60 + i) for i, c in enumerate(clusters)]
    for i, tid in enumerate(tids):
        got = dp.tenant_step(tid, bats[i], 100)
        want = singles[i].step(bats[i], 100)
        _assert_result_parity(got, want, rules=False)
        assert got.pending.tolist() == want.pending.tolist()
    # ONE drain on the packed engine classifies BOTH tenants' rows in
    # their own worlds; each single drains its own queue.
    dp.drain_slowpath(101)
    for s in singles:
        s.drain_slowpath(101)
    for i, tid in enumerate(tids):
        got = dp.tenant_step(tid, bats[i], 102)
        want = singles[i].step(bats[i], 102)
        _assert_result_parity(got, want)
        assert (sorted(map(str, dp.tenant_dump_flows(tid, 102)))
                == sorted(map(str, singles[i].dump_flows(102))))


def test_packed_mesh_parity():
    """Pillar 1 on the mesh: verdict fields are bitwise vs a
    single-tenant mesh twin.  est/committed are cache-TOPOLOGY
    observables (the tenant shard salt legitimately re-homes flows, the
    PR 9 convention) — the FIRST round, where no cache exists, is
    asserted in full."""
    from antrea_tpu.parallel.meshpath import MeshDatapath

    clusters = _worlds(2, rule_counts=(12, 12))
    dp = MeshDatapath(n_data=2, n_rule=1, flow_slots=QUOTA, aff_slots=AFFQ,
                      flightrec_slots=64, realization_slots=0)
    tids = [dp.tenant_create(f"t{i}", copy.deepcopy(c.ps), quota=QUOTA,
                             aff_quota=AFFQ)
            for i, c in enumerate(clusters)]
    # ONE twin suffices for the parity diff (construction is the
    # expensive part — mesh step variants compile per rule shape); the
    # second tenant serves interleaved to prove world separation.
    single = MeshDatapath(copy.deepcopy(clusters[0].ps), n_data=2,
                          n_rule=1, flow_slots=QUOTA, aff_slots=AFFQ,
                          flightrec_slots=0, realization_slots=0)
    bats = [_batch(c, 64, seed=70 + i) for i, c in enumerate(clusters)]
    for now in (100, 101):
        dp.tenant_step(tids[1], bats[1], now)  # interleaved other world
        got = dp.tenant_step(tids[0], bats[0], now)
        want = single.step(bats[0], now)
        # est/committed are cache-TOPOLOGY observables on the mesh
        # (the tenant shard salt re-homes lanes, changing per-shard
        # collision/spill patterns — the PR 9 convention); VERDICT
        # fields and rule-id attribution must stay bitwise.
        _assert_result_parity(got, want, est=False)
    # Established serving works in the packed worlds (volume, not lanes).
    for tid, b in zip(tids, bats):
        assert int(dp.tenant_step(tid, b, 102).est.sum()) > 0


def test_mixed_batch_step_tenants():
    """step_tenants partitions a mixed-tenant batch per world and merges
    lane-exact: every lane equals its per-tenant dispatch image."""
    clusters = _worlds(2, rule_counts=(10, 24))
    dp, tids = _packed(TpuflowDatapath, clusters)
    twin, twin_tids = _packed(TpuflowDatapath, clusters)
    b0 = _batch(clusters[0], 32, seed=80)
    b1 = _batch(clusters[1], 32, seed=81)
    mixed = PacketBatch(
        src_ip=np.concatenate([b0.src_ip, b1.src_ip]),
        dst_ip=np.concatenate([b0.dst_ip, b1.dst_ip]),
        proto=np.concatenate([b0.proto, b1.proto]),
        src_port=np.concatenate([b0.src_port, b1.src_port]),
        dst_port=np.concatenate([b0.dst_port, b1.dst_port]),
    )
    lane_tids = np.concatenate([np.full(32, tids[0]), np.full(32, tids[1])])
    # Shuffle so the partition actually reorders lanes.
    perm = np.random.default_rng(5).permutation(64)
    mixed = PacketBatch(**{
        f: getattr(mixed, f)[perm]
        for f in ("src_ip", "dst_ip", "proto", "src_port", "dst_port")})
    lane_tids = lane_tids[perm]
    merged = dp.step_tenants(lane_tids, mixed, 100)
    # Expectation: each tenant's lanes, extracted in the SAME partition
    # order step_tenants uses, stepped through an identical twin.
    want_code = np.empty(64, np.int64)
    want_miss = 0
    for tid, twin_tid in zip(tids, twin_tids):
        lanes = np.nonzero(lane_tids == tid)[0]
        sub = PacketBatch(**{
            f: getattr(mixed, f)[lanes]
            for f in ("src_ip", "dst_ip", "proto", "src_port", "dst_port")})
        want = twin.tenant_step(twin_tid, sub, 100)
        want_code[lanes] = np.asarray(want.code)
        want_miss += want.n_miss
    assert merged.code.tolist() == want_code.tolist()
    assert merged.n_miss == want_miss


def test_isolation_attack_storm_evicts_nothing_cross_tenant():
    """Acceptance pillar 2 (quota isolation): tenant A's SYN-flood +
    cache-thrash storm — never-repeating tuples, flow universe >> its
    quota — evicts ZERO of tenant B's established flows; A's queue
    admissions clamp at its in-queue quota, metered and journaled."""
    clusters = _worlds(2, rule_counts=(6, 6))
    dp, (tid_a, tid_b) = _packed(
        TpuflowDatapath, clusters, async_slowpath=True,
        miss_queue_slots=1 << 10, drain_batch=128)
    # B establishes a hot set — SETTLED: step/drain until no lane is
    # pending, so nothing of B's sits in the shared queue when the storm
    # starts (a leftover B row draining mid-storm would be B's own
    # legitimate commit, not cross-tenant damage).
    b_hot = _batch(clusters[1], 64, seed=90)
    for now in (100, 102, 104):
        r_est = dp.tenant_step(tid_b, b_hot, now)
        dp.drain_slowpath(now + 1)
    est0 = int(r_est.est.sum())
    assert est0 > 0
    assert dp.tenant_stats()[tid_b]["queued"] == 0
    evict_b0 = dp.tenant_stats()[tid_b]["evictions_total"]
    flows_b0 = sorted(map(str, dp.tenant_dump_flows(tid_b, 104)))
    # A storms: never-repeating SYN flood + thrash universe >> quota.
    seq = 0
    for rnd in range(6):
        flood = gen_syn_flood(clusters[0].pod_ips, 256, start_seq=seq,
                              seed=1)
        seq += 256
        dp.tenant_step(tid_a, flood, 104 + rnd)
        thrash = gen_cache_thrash(clusters[0].pod_ips, 256,
                                  n_flows=QUOTA * 16, seed=rnd)
        dp.tenant_step(tid_a, thrash, 104 + rnd)
        dp.drain_slowpath(110 + rnd)
    st = dp.tenant_stats()
    # The clamp engaged (A's backlog exceeded its in-queue quota)...
    assert st[tid_a]["quota_clamps_total"] > 0
    kinds = {e["kind"] for e in dp.flightrecorder_events()}
    assert "tenant-quota-clamp" in kinds
    # ... and B lost NOTHING: zero NEW evictions, identical flow table,
    # every established flow still serves from cache.
    assert st[tid_b]["evictions_total"] == evict_b0
    assert sorted(map(str, dp.tenant_dump_flows(tid_b, 115))) == flows_b0
    r_after = dp.tenant_step(tid_b, b_hot, 116)
    assert int(r_after.est.sum()) >= est0
    # A's own world absorbed the damage (evictions inside its quota).
    assert st[tid_a]["evictions_total"] > 0


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_tenant_canary_veto_rolls_back_only_that_tenant(cls):
    """Acceptance pillar 2 (blast radius): a canary mismatch on tenant
    A's install rolls back and degrades ONLY tenant A — tenant B and the
    default world keep their generations and stay serviceable — and A
    recovers via an ordinary re-install."""
    clusters = _worlds(2, rule_counts=(10, 10))
    dp, (tid_a, tid_b) = _packed(cls, clusters)
    ps_a2 = copy.deepcopy(clusters[0].ps)
    plan = FaultPlan(seed=1)
    plan.after("dp.canary", 0, "fail", times=1)
    dp.arm_commit_faults(plan, "dp")
    with pytest.raises(CanaryMismatchError):
        dp.tenant_install_bundle(tid_a, ps_a2)
    st = dp.tenant_stats()
    assert st[tid_a]["degraded"] == 1
    assert st[tid_a]["generation"] == 0  # rolled back, not advanced
    assert st[tid_a]["rollbacks_total"] == 1
    # Blast radius: B and the default world untouched.
    assert st[tid_b]["degraded"] == 0
    assert st[tid_b]["generation"] == 0
    assert not dp.degraded
    assert dp.generation == 0
    assert dp.tenant_install_bundle(tid_b, copy.deepcopy(
        clusters[1].ps)) == 1
    assert dp.tenant_stats()[tid_a]["degraded"] == 1  # B's pass ≠ A's cure
    kinds = {e["kind"] for e in dp.flightrecorder_events()}
    assert "tenant-rollback" in kinds
    # Recovery: the fault is exhausted; a re-install passes its canary
    # and lifts ONLY A's quarantine.
    assert dp.tenant_install_bundle(tid_a, ps_a2) == 1
    st = dp.tenant_stats()
    assert st[tid_a]["degraded"] == 0
    assert st[tid_a]["generation"] == 1


def test_shared_compile_executables_track_rungs_not_tenants():
    """Acceptance pillar 3 over 64 uneven tenants: XLA step-executable
    growth equals the occupied rung-signature count — compile cost is a
    function of the rung ladder, never of tenant count."""
    from antrea_tpu.models import forwarding as fwd_model

    # 4 world SHAPES (uneven rule counts on distinct rungs), 16 tenants
    # each: every tenant compiles its own tables, but same-rung tenants
    # must share one executable.
    shapes = [gen_cluster(n, n_nodes=2, pods_per_node=8, seed=s)
              for n, s in ((6, 1), (20, 2), (45, 3), (100, 4))]
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                         flightrec_slots=0, realization_slots=0)
    exec0 = fwd_model.pipeline_step_full._cache_size()
    tids = []
    for i in range(64):
        c = shapes[i % 4]
        tids.append((dp.tenant_create(f"t{i}", copy.deepcopy(c.ps),
                                      quota=QUOTA, aff_quota=AFFQ), c))
    assert dp.tenant_count == 64
    rungs = dp.tenant_rungs()
    assert len(rungs) == 4  # one signature per world shape
    b = {id(c): _batch(c, 32, seed=77) for c in shapes}
    for tid, c in tids:
        dp.tenant_step(tid, b[id(c)], 100)
    execs = fwd_model.pipeline_step_full._cache_size() - exec0
    assert execs == len(rungs), (
        f"{execs} step executables for 64 tenants on {len(rungs)} rungs "
        f"— compile count must track rungs, not tenants")


def test_pad_rung_floor_collapses_small_worlds():
    """Two tenants with DIFFERENT small rule counts land on the same
    rung (phase floor + entry floor) — the padding itself is what makes
    them shape-identical."""
    c1 = gen_cluster(3, n_nodes=2, pods_per_node=4, seed=21)
    c2 = gen_cluster(3, n_nodes=2, pods_per_node=4, seed=21)
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                         flightrec_slots=0, realization_slots=0)
    dp.tenant_create("a", copy.deepcopy(c1.ps), quota=QUOTA)
    dp.tenant_create("b", copy.deepcopy(c2.ps), quota=QUOTA)
    assert len(dp.tenant_rungs()) == 1


def test_tenant_config_rejections():
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                         flightrec_slots=0, realization_slots=0)
    with pytest.raises(ConfigError):
        dp.tenant_create("bad", quota=100)  # not pow2
    with pytest.raises(ConfigError):
        dp.tenant_create("bad", quota=256, aff_quota=100)
    # toServices tenants are rejected (shared service view).
    from antrea_tpu.apis.controlplane import (
        Direction, NetworkPolicy, NetworkPolicyPeer, NetworkPolicyRule,
        RuleAction, ServiceReference)

    ps = PolicySet()
    ps.policies.append(NetworkPolicy(
        uid="svc-ref", name="svc-ref",
        rules=[NetworkPolicyRule(
            direction=Direction.OUT,
            to_peer=NetworkPolicyPeer(
                to_services=[ServiceReference(namespace="d", name="s")]),
            action=RuleAction.ALLOW)],
    ))
    with pytest.raises(ConfigError):
        dp.tenant_create("svcref", ps, quota=256)
    # ... and the INSTALL path enforces the same admission rule (a later
    # push must not slip a svcref world past the create-time gate).
    tid = dp.tenant_create("clean", quota=256)
    with pytest.raises(ConfigError):
        dp.tenant_install_bundle(tid, ps)
    assert dp.tenant_stats()[tid]["generation"] == 0
    # Dual-stack engines have no tenant worlds (v4-only, like async).
    ds = TpuflowDatapath(flow_slots=1 << 8, aff_slots=1 << 6,
                         dual_stack=True, flightrec_slots=0,
                         realization_slots=0)
    with pytest.raises(ConfigError):
        ds.tenant_create("v6", quota=256)


def test_tenant_maintenance_task_registered_and_runs():
    """The 'tenant-maintain' task joins the scheduler on first
    tenant_create only, and its granted ticks age tenant worlds through
    the ordinary DRR discipline."""
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                         flightrec_slots=64, realization_slots=0)
    assert "tenant-maintain" not in dp._maintenance.task_names
    c = gen_cluster(8, n_nodes=2, pods_per_node=4, seed=31)
    tid = dp.tenant_create("t", copy.deepcopy(c.ps), quota=QUOTA)
    assert "tenant-maintain" in dp._maintenance.task_names
    b = _batch(c, 32, seed=32)
    dp.tenant_step(tid, b, 100)
    occupied0 = dp.tenant_cache_stats(tid)["occupied"]
    assert occupied0 > 0
    # Far past the idle timeout: the rotated fused maintain pass must
    # physically reclaim the expired rows of the tenant world.
    ran = 0
    for i in range(8):
        out = dp.maintenance_tick(now=100 + 3600 * (i + 2))
        ran += out["ran"].get("tenant-maintain", 0)
    assert ran > 0
    assert dp.tenant_cache_stats(tid)["occupied"] == 0


def test_tenant_metrics_rendered_and_registered():
    from antrea_tpu.observability.metrics import render_metrics

    clusters = _worlds(1, rule_counts=(8,))
    dp, (tid,) = _packed(TpuflowDatapath, clusters)
    dp.tenant_step(tid, _batch(clusters[0], 16, seed=41), 100)
    text = render_metrics(dp, node="n1")
    assert f'antrea_tpu_tenant_worlds{{node="n1"}} 1' in text
    for fam in ("antrea_tpu_tenant_generation",
                "antrea_tpu_tenant_flow_quota_slots",
                "antrea_tpu_tenant_flow_occupied",
                "antrea_tpu_tenant_quota_clamps_total"):
        assert f'{fam}{{tenant="{tid}",node="n1"}}' in text
    # Untenanted datapaths keep the surface absent entirely.
    bare = TpuflowDatapath(flow_slots=1 << 8, aff_slots=1 << 6,
                           flightrec_slots=0, realization_slots=0)
    assert "antrea_tpu_tenant_" not in render_metrics(bare, node="n1")


# The tenant/event/metric drift gates (tools/check_tenant.py et al. ->
# analysis passes `tenant`/`events`/`metrics`) run once for the whole
# tier-1 suite in tests/test_static_analysis.py.


def test_bench_controller_fleet_empty_histogram_guard():
    """A churn-0 (or all-unstamped) fleet run emits a NULL metric with
    the unstamped count — never a fabricated 0-second p99, never a
    crash."""
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "bench_controller", root / "bench_controller.py")
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    out = bc.fleet_realization(2, churn=0)
    assert out["value"] is None
    assert out["vs_baseline"] is None
    assert out["extra"]["events_measured"] == 0
    assert "unstamped_excluded" in out["extra"]
    # The normal path still reports a real quantile.
    out2 = bc.fleet_realization(2, churn=3)
    assert out2["extra"]["events_measured"] > 0
    assert out2["value"] is not None


def test_default_world_unchanged_by_tenancy():
    """The default world of a tenanted engine serves bit-identically to
    an untenanted instance — worlds swap fully out."""
    c = gen_cluster(20, n_nodes=2, pods_per_node=8, seed=51)
    dp = TpuflowDatapath(copy.deepcopy(c.ps), flow_slots=1 << 10,
                         aff_slots=1 << 8, flightrec_slots=64,
                         realization_slots=0)
    twin = TpuflowDatapath(copy.deepcopy(c.ps), flow_slots=1 << 10,
                           aff_slots=1 << 8, flightrec_slots=0,
                           realization_slots=0)
    t = dp.tenant_create("t", copy.deepcopy(c.ps), quota=QUOTA)
    b = _batch(c, 48, seed=52)
    bt = _batch(c, 48, seed=53)
    dp.tenant_step(t, bt, 99)  # interleave tenant traffic
    r1 = dp.step(b, 100)
    w1 = twin.step(b, 100)
    dp.tenant_step(t, bt, 100)
    r2 = dp.step(b, 101)
    w2 = twin.step(b, 101)
    _assert_result_parity(r1, w1)
    _assert_result_parity(r2, w2)
    assert dp.cache_stats() == twin.cache_stats()


def test_overlap_deferred_drain_metrics_land_in_owner_world():
    """Overlap mode: a tenant drain's DEFERRED finalizer (the two-slot
    staging retires it long after the dispatch's world swap exited) must
    re-enter the owning world — its rule metrics/verdict counters land
    in the tenant, never in whichever world is active at retire time."""
    clusters = _worlds(1, rule_counts=(12,))
    dp, (tid,) = _packed(
        TpuflowDatapath, clusters, async_slowpath=True,
        miss_queue_slots=1 << 10, drain_batch=64, overlap_commits=True)
    b = _batch(clusters[0], 32, seed=95)
    dp.tenant_step(tid, b, 100)
    dp.drain_slowpath(101)
    dp.flush_slowpath()  # retire the staged tenant finalizer
    got = dp.tenant_datapath_stats(tid)
    base = dp.stats()
    # The drained rows' verdicts were counted exactly once, in the
    # tenant's world; the default world saw none of them.
    assert (got.default_allow + got.default_deny
            + sum(got.ingress.values()) + sum(got.egress.values())) > 0
    assert base.default_allow == 0 and base.default_deny == 0
    assert base.ingress == {} and base.egress == {}
    # And parity with a single-tenant overlap twin still holds.
    twin = _single(TpuflowDatapath, clusters[0], async_slowpath=True,
                   miss_queue_slots=1 << 10, drain_batch=64,
                   overlap_commits=True)
    twin.step(b, 100)
    twin.drain_slowpath(101)
    twin.flush_slowpath()
    want = twin.stats()
    assert got.ingress == want.ingress and got.egress == want.egress
    assert got.default_allow == want.default_allow
    assert got.default_deny == want.default_deny


def test_tenant_stats_is_snapshot_based_never_swaps_worlds():
    """tenant_stats serves the /metrics scrape path, which runs on the
    apiserver's handler THREAD: it must read the stored world snapshots
    only — callable even while a world swap is active (previously the
    occupancy decode entered _world_ctx and would either raise the
    nesting guard or interleave with the engine thread's swap)."""
    clusters = _worlds(1, rule_counts=(8,))
    dp, (tid,) = _packed(TpuflowDatapath, clusters)
    dp.tenant_step(tid, _batch(clusters[0], 16, seed=43), 100)
    with dp._world_ctx(tid):
        st = dp.tenant_stats()  # mid-swap scrape: must not nest/raise
    assert st[tid]["occupied"] > 0
    # Consistent with the swap-based operator surface once quiescent.
    assert st[tid]["occupied"] == dp.tenant_cache_stats(tid)["occupied"]
