"""AdminNetworkPolicy / BaselineAdminNetworkPolicy tests.

Precedence contract under test (sig-network policy-api, realized by the
reference as NetworkPolicyType.ADMIN internal policies): ANP evaluates
before K8s NetworkPolicies (Deny/Allow terminal, Pass delegates), BANP
evaluates after them (only for pods no K8s NP isolates)."""

import copy

import numpy as np
import pytest

from antrea_tpu.apis import crd
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def _controller_with_pods():
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(crd.Namespace(name="prod", labels={"env": "prod"}))
    for name, ip, labels in [
        ("web", "10.0.0.10", {"app": "web"}),
        ("db", "10.0.0.11", {"app": "db"}),
        ("client", "10.0.0.12", {"app": "client"}),
    ]:
        ctl.upsert_pod(crd.Pod(namespace="prod", name=name, ip=ip,
                               node="node-a", labels=labels))
    return ctl


def _step(ps, src, dst, port=80):
    tpu = TpuflowDatapath(copy.deepcopy(ps), flow_slots=1 << 10,
                          aff_slots=1 << 8, miss_chunk=64)
    orc = OracleDatapath(copy.deepcopy(ps), flow_slots=1 << 10, aff_slots=1 << 8)
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([40000], np.int32),
        dst_port=np.array([port], np.int32),
    )
    ra, rb = tpu.step(b, now=1), orc.step(b, now=1)
    assert ra.code.tolist() == rb.code.tolist()
    return int(ra.code[0])


def _subject(app):
    return crd.AntreaAppliedTo(
        pod_selector=crd.LabelSelector.make({"app": app}),
        ns_selector=crd.LabelSelector.make(),
    )


def _peer(app):
    return crd.AntreaPeer(pod_selector=crd.LabelSelector.make({"app": app}),
                          ns_selector=crd.LabelSelector.make())


def test_anp_deny_beats_k8s_allow():
    ctl = _controller_with_pods()
    # K8s NP allows client -> web.
    ctl.upsert_k8s_policy(crd.K8sNetworkPolicy(
        uid="np-allow", name="allow-client", namespace="prod",
        pod_selector=crd.LabelSelector.make({"app": "web"}),
        ingress=[crd.K8sNPRule(peers=[crd.K8sPeer(
            pod_selector=crd.LabelSelector.make({"app": "client"}))])],
        policy_types=[cp.Direction.IN],
    ))
    assert _step(ctl.policy_set(), "10.0.0.12", "10.0.0.10") == 0
    # ANP Deny wins over the K8s allow (evaluated earlier).
    ctl.upsert_admin_policy(crd.AdminNetworkPolicy(
        name="lockdown", priority=10, subject=_subject("web"),
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP,
                                peers=[_peer("client")])],
    ))
    assert _step(ctl.policy_set(), "10.0.0.12", "10.0.0.10") == 1
    # ANP Pass delegates back to the K8s NP (allow again); lower priority
    # value evaluates first, so the Pass at priority 5 shadows the Deny.
    ctl.upsert_admin_policy(crd.AdminNetworkPolicy(
        name="exempt", priority=5, subject=_subject("web"),
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.PASS,
                                peers=[_peer("client")])],
    ))
    assert _step(ctl.policy_set(), "10.0.0.12", "10.0.0.10") == 0


def test_banp_applies_only_without_k8s_isolation():
    ctl = _controller_with_pods()
    # BANP denies everything to db pods.
    ctl.upsert_baseline_admin_policy(crd.BaselineAdminNetworkPolicy(
        subject=_subject("db"),
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP)],
    ))
    ps = ctl.policy_set()
    assert _step(ps, "10.0.0.12", "10.0.0.11") == 1  # baseline deny
    assert _step(ps, "10.0.0.12", "10.0.0.10") == 0  # web unaffected
    # A K8s NP isolating db takes over: its allow decides, baseline is
    # never consulted for isolated pods.
    ctl.upsert_k8s_policy(crd.K8sNetworkPolicy(
        uid="np-db", name="allow-web-to-db", namespace="prod",
        pod_selector=crd.LabelSelector.make({"app": "db"}),
        ingress=[crd.K8sNPRule(peers=[crd.K8sPeer(
            pod_selector=crd.LabelSelector.make({"app": "web"}))])],
        policy_types=[cp.Direction.IN],
    ))
    ps2 = ctl.policy_set()
    assert _step(ps2, "10.0.0.10", "10.0.0.11") == 0  # K8s allow
    assert _step(ps2, "10.0.0.12", "10.0.0.11") == 1  # K8s default deny


def test_admin_validation():
    ctl = _controller_with_pods()
    with pytest.raises(ValueError):
        ctl.upsert_admin_policy(crd.AdminNetworkPolicy(
            name="bad", priority=2000, subject=_subject("web")))
    with pytest.raises(ValueError):
        ctl.upsert_baseline_admin_policy(crd.BaselineAdminNetworkPolicy(
            name="not-default", subject=_subject("web")))
    with pytest.raises(ValueError):
        ctl.upsert_baseline_admin_policy(crd.BaselineAdminNetworkPolicy(
            subject=_subject("web"),
            rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                    action=cp.RuleAction.PASS)],
        ))
    # Internal type is ADMIN; deletion cleans up.
    ctl.upsert_admin_policy(crd.AdminNetworkPolicy(
        name="ok", priority=1, subject=_subject("web"),
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP)],
    ))
    ps = ctl.policy_set()
    admin = [p for p in ps.policies if p.type == cp.NetworkPolicyType.ADMIN]
    assert [p.uid for p in admin] == ["anp-ok"]
    ctl.delete_policy("anp-ok")
    assert all(p.type != cp.NetworkPolicyType.ADMIN
               for p in ctl.policy_set().policies)


def test_admin_type_survives_cluster_group_resync():
    """A ClusterGroup update re-converts referencing policies; an ANP
    referencing one must come back as type ADMIN, not flip to ACNP."""
    ctl = _controller_with_pods()
    ctl.upsert_cluster_group(crd.ClusterGroup(
        name="clients", pod_selector=crd.LabelSelector.make({"app": "client"}),
        ns_selector=crd.LabelSelector.make(),
    ))
    ctl.upsert_admin_policy(crd.AdminNetworkPolicy(
        name="via-cg", priority=3, subject=_subject("web"),
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP,
                                peers=[crd.AntreaPeer(group="clients")])],
    ))
    ctl.upsert_cluster_group(crd.ClusterGroup(
        name="clients", pod_selector=crd.LabelSelector.make({"app": "db"}),
        ns_selector=crd.LabelSelector.make(),
    ))
    types = {p.uid: p.type for p in ctl.policy_set().policies}
    assert types["anp-via-cg"] == cp.NetworkPolicyType.ADMIN
