from antrea_tpu.utils import ip as iputil


def test_roundtrip():
    assert iputil.ip_to_u32("10.0.0.1") == 0x0A000001
    assert iputil.u32_to_ip(0x0A000001) == "10.0.0.1"


def test_cidr_range():
    assert iputil.cidr_to_range("10.0.0.0/8") == (0x0A000000, 0x0B000000)
    assert iputil.cidr_to_range("1.2.3.4") == (0x01020304, 0x01020305)
    assert iputil.cidr_to_range("0.0.0.0/0") == (0, 1 << 32)


def test_cidr_nonaligned_base_is_masked():
    # 10.0.0.7/24 -> 10.0.0.0/24
    assert iputil.cidr_to_range("10.0.0.7/24") == (0x0A000000, 0x0A000100)


def test_merge_ranges():
    rs = iputil.cidrs_to_ranges(["10.0.0.0/25", "10.0.0.128/25", "192.168.0.0/24"])
    assert rs == [(0x0A000000, 0x0A000100), (0xC0A80000, 0xC0A80100)]


def test_ipblock_except():
    rs = iputil.ipblock_to_ranges("10.0.0.0/24", ["10.0.0.64/26"])
    assert rs == [(0x0A000000, 0x0A000040), (0x0A000080, 0x0A000100)]
    assert iputil.ip_in_ranges(iputil.ip_to_u32("10.0.0.1"), rs)
    assert not iputil.ip_in_ranges(iputil.ip_to_u32("10.0.0.65"), rs)


def test_ipblock_except_outside_cidr_ignored():
    rs = iputil.ipblock_to_ranges("10.0.0.0/24", ["192.168.0.0/16"])
    assert rs == [(0x0A000000, 0x0A000100)]
