"""Pod lifecycle: CNI server + host-local IPAM + persisted interface store
(rebuild-on-restart), wired into the policy controller and datapath."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.cni import CniServer, HostLocalIPAM, IPAMError
from antrea_tpu.apis.crd import (
    K8sNetworkPolicy,
    K8sNPRule,
    K8sPeer,
    LabelSelector,
    Namespace,
)
from antrea_tpu.controller import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath
from antrea_tpu.native import ConfigStore
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil


def test_host_local_ipam_semantics():
    ipam = HostLocalIPAM("10.10.0.0/29")  # .0 net, .1 gw, .7 bcast -> .2-.6
    assert ipam.gateway == "10.10.0.1"
    a = ipam.allocate("c1")
    assert a == "10.10.0.2"
    assert ipam.allocate("c1") == a  # idempotent by container id
    ips = {ipam.allocate(f"c{i}") for i in range(2, 6)}
    assert len(ips) == 4
    with pytest.raises(IPAMError):
        ipam.allocate("overflow")
    # Release returns the smallest-free address to the pool.
    assert ipam.release("c1") == a
    assert ipam.allocate("c9") == a


def test_cni_add_del_and_restart_recovery(tmp_path):
    store = ConfigStore(str(tmp_path / "conf.db"))
    srv = CniServer("n0", "10.10.0.0/24", store)
    ic1 = srv.cmd_add("cid-1", "default", "web-1")
    ic2 = srv.cmd_add("cid-2", "default", "web-2")
    assert ic1.ip != ic2.ip and ic1.ofport != ic2.ofport
    assert srv.cmd_add("cid-1", "default", "web-1").ip == ic1.ip  # idempotent
    assert srv.cmd_check("cid-1") and not srv.cmd_check("ghost")
    assert srv.cmd_del("cid-2") and not srv.cmd_del("cid-2")
    store.close()

    # Agent restart: the interface store rebuilds from the native config
    # store (the OVSDB external-IDs recovery, agent.go:279), IPAM re-claims
    # allocated addresses and ofports keep advancing.
    store2 = ConfigStore(str(tmp_path / "conf.db"))
    srv2 = CniServer("n0", "10.10.0.0/24", store2)
    assert srv2.cmd_check("cid-1")
    assert srv2.ifaces.get("cid-1").ip == ic1.ip
    ic3 = srv2.cmd_add("cid-3", "default", "web-3")
    assert ic3.ip not in (ic1.ip,)  # no double allocation after restart
    assert ic3.ofport > ic1.ofport


def test_cni_feeds_policy_controller_to_datapath(tmp_path):
    """The pod path end-to-end: CmdAdd -> controller pod upsert -> policy
    membership -> datapath verdicts (the kubelet -> cniserver -> openflow
    chain of SURVEY §3.2)."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", name="np-web", namespace="default",
        pod_selector=LabelSelector.make({"app": "web"}),
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "cli"}))],
        )],
    ))
    store = ConfigStore(str(tmp_path / "conf.db"))
    srv = CniServer("n0", "10.10.0.0/24", store, controller=ctl)
    web = srv.cmd_add("cid-web", "default", "web-1", labels={"app": "web"})
    cli = srv.cmd_add("cid-cli", "default", "cli-1", labels={"app": "cli"})

    dp = OracleDatapath(ctl.policy_set_for_node("n0"), [],
                        flow_slots=1 << 10, aff_slots=1 << 8)

    def probe(src, dst, sport):
        b = PacketBatch(
            src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
            dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
            proto=np.array([6], np.int32),
            src_port=np.array([sport], np.int32),
            dst_port=np.array([80], np.int32),
        )
        return int(dp.step(b, 5).code[0])

    assert probe(cli.ip, web.ip, 41000) == 0   # allowed peer
    assert probe("10.10.0.99", web.ip, 41001) == 1  # isolated: default deny

    # Pod deletion flows back: the policy no longer spans the node once its
    # last selected pod is gone.
    srv.cmd_del("cid-web")
    assert ctl.policy_set_for_node("n0").policies == []


def test_restart_recovery_preserves_labels(tmp_path):
    """Review repro: restart must re-notify pods with their REAL labels
    (persisted in the interface-store row) — an empty-label upsert would
    silently evict every pod from its selector groups."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", name="np-web", namespace="default",
        pod_selector=LabelSelector.make({"app": "web"}),
        ingress=[K8sNPRule(peers=[K8sPeer(
            pod_selector=LabelSelector.make({"app": "cli"}))])],
    ))
    store = ConfigStore(str(tmp_path / "conf.db"))
    srv = CniServer("n0", "10.10.0.0/24", store, controller=ctl)
    web = srv.cmd_add("cid-web", "default", "web-1", labels={"app": "web"})
    assert "n0" in {m.node for g in
                    ctl.policy_set().applied_to_groups.values()
                    for m in g.members}
    store.close()

    # Fresh controller + restarted agent: membership must be rebuilt with
    # labels intact.
    ctl2 = NetworkPolicyController()
    ctl2.upsert_namespace(Namespace("default", {}))
    ctl2.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", name="np-web", namespace="default",
        pod_selector=LabelSelector.make({"app": "web"}),
        ingress=[K8sNPRule(peers=[K8sPeer(
            pod_selector=LabelSelector.make({"app": "cli"}))])],
    ))
    srv2 = CniServer("n0", "10.10.0.0/24",
                     ConfigStore(str(tmp_path / "conf.db")), controller=ctl2)
    members = {m.ip for g in ctl2.policy_set().applied_to_groups.values()
               for m in g.members}
    assert web.ip in members, "recovered pod must keep its selector groups"


def test_stale_del_keeps_recreated_pod(tmp_path):
    """A late DEL for an old sandbox of a RECREATED pod must not remove
    the live pod from the controller (CNI allows stale/duplicate DELs)."""
    ctl = NetworkPolicyController()
    ctl.upsert_namespace(Namespace("default", {}))
    srv = CniServer("n0", "10.10.0.0/24",
                    ConfigStore(str(tmp_path / "conf.db")), controller=ctl)
    srv.cmd_add("cid-old", "default", "web-1", labels={"app": "web"})
    new = srv.cmd_add("cid-new", "default", "web-1", labels={"app": "web"})
    assert srv.cmd_del("cid-old")  # stale DEL arrives late
    # The recreated pod is still known to the grouping index.
    assert ctl.index.groups_of_pod("default/web-1") is not None
    srv.controller.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np", name="np", namespace="default",
        pod_selector=LabelSelector.make({"app": "web"}),
    ))
    members = {m.ip for g in ctl.policy_set().applied_to_groups.values()
               for m in g.members}
    assert new.ip in members
    # The FINAL del does remove it.
    srv.cmd_del("cid-new")
    assert ctl.policy_set_for_node("n0").policies == []


def test_cni_socket_wire_from_separate_process(tmp_path):
    """CNI add/del/check round-trip over a unix-domain socket from a REAL
    separate process (the kubelet seam: cni.proto:67-75 — a gRPC service
    on a unix socket; here framed JSON with the same versioned
    request/response shape), plus in-process concurrent clients and the
    unsupported-version error path."""
    import json as _json
    import subprocess
    import sys

    from antrea_tpu.agent.cni import CNI_WIRE_VERSION, CniClient, CniSocketServer
    from antrea_tpu.native import ConfigStore

    store = ConfigStore(str(tmp_path / "conf.db"))
    srv = CniSocketServer(
        CniServer("n0", "10.10.0.0/24", store), str(tmp_path / "cni.sock"))
    try:
        # Cross-process: the client lives in its own python process.
        script = f"""
import json, socket, sys
s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
s.connect({str(tmp_path / 'cni.sock')!r})
def rpc(body):
    s.sendall(json.dumps(body).encode() + b"\\n")
    buf = b""
    while b"\\n" not in buf:
        buf += s.recv(65536)
    return json.loads(buf.split(b"\\n", 1)[0])
add = rpc({{"version": {CNI_WIRE_VERSION!r}, "cmd": "add",
           "containerId": "c-远1", "podNamespace": "default",
           "podName": "p1", "labels": {{"app": "web"}}}})
chk = rpc({{"version": {CNI_WIRE_VERSION!r}, "cmd": "check",
           "containerId": "c-远1"}})
dele = rpc({{"version": {CNI_WIRE_VERSION!r}, "cmd": "del",
            "containerId": "c-远1"}})
chk2 = rpc({{"version": {CNI_WIRE_VERSION!r}, "cmd": "check",
            "containerId": "c-远1"}})
bad = rpc({{"version": "0.9", "cmd": "add", "containerId": "x"}})
print(json.dumps([add, chk, dele, chk2, bad]))
"""
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=60,
                             check=True, cwd="/root/repo")
        add, chk, dele, chk2, bad = _json.loads(out.stdout)
        assert add["ok"] and add["ip"].startswith("10.10.0.")
        assert add["gateway"] == "10.10.0.1"
        assert chk == {"ok": True, "exists": True}
        assert dele == {"ok": True, "released": True}
        assert chk2 == {"ok": True, "exists": False}
        assert not bad["ok"] and "version" in bad["error"]

        # Concurrent clients allocate distinct addresses (the kubelet's
        # parallel sandbox adds).
        c1, c2 = CniClient(srv.sock_path), CniClient(srv.sock_path)
        a1 = c1.add("c-a", "default", "pa")
        a2 = c2.add("c-b", "default", "pb")
        assert a1["ip"] != a2["ip"]
        # Idempotent re-ADD over the wire returns the same address.
        assert c2.add("c-a", "default", "pa")["ip"] == a1["ip"]
        c1.close(); c2.close()
    finally:
        srv.close()
