"""TCP conntrack teardown: FIN/RST ends the established fast path.

The semantic under test (kernel-ct close, conservatively simplified — see
models/pipeline.py teardown comment): after a FIN or RST on an established
connection, BOTH tuple directions leave the cache, so the next same-tuple
packet re-classifies under the CURRENT policy — a closed connection can
never est-bypass a deny installed after it closed.  Closing segments that
MISS the cache classify but never establish."""

import pytest

pytestmark = pytest.mark.slow

import copy

import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.models.pipeline import TCP_FIN, TCP_RST
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

CLIENT, SERVER = "10.0.0.5", "10.0.0.9"


def _pair(ps=None):
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    return (
        TpuflowDatapath(copy.deepcopy(ps), miss_chunk=32, **kw),
        OracleDatapath(copy.deepcopy(ps), **kw),
    )


def _b(src, dst, sport, dport, flags=0):
    return PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([6], np.int32),
        src_port=np.array([sport], np.int32),
        dst_port=np.array([dport], np.int32),
        tcp_flags=np.array([flags], np.int32),
    )


def _deny_all():
    return PolicySet(
        policies=[cp.NetworkPolicy(
            uid="np-deny", name="deny", namespace="d",
            type=cp.NetworkPolicyType.ANNP,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.IN, action=cp.RuleAction.DROP,
                priority=0,
            )],
            applied_to_groups=["atg"],
            tier_priority=cp.TIER_APPLICATION, priority=1,
        )],
        applied_to_groups={"atg": cp.AppliedToGroup(
            name="atg", members=[cp.GroupMember(ip=SERVER)],
        )},
        address_groups={},
    )


def _diff(a, b):
    for f in ("code", "est", "reply", "committed"):
        assert getattr(a, f).tolist() == getattr(b, f).tolist(), f
    assert a.n_miss == b.n_miss


def test_fin_ends_est_bypass_for_new_policy():
    tpu, orc = _pair()
    fwd = _b(CLIENT, SERVER, 40000, 80)
    for dp in (tpu, orc):
        assert dp.step(fwd, now=1).committed.tolist() == [1]
        assert dp.step(fwd, now=2).est.tolist() == [1]
    # Deny installed mid-connection: established traffic still bypasses
    # (ovs-pipeline.md:1685-1691) — on both datapaths.
    for dp in (tpu, orc):
        dp.install_bundle(_deny_all())
    ra, rb = tpu.step(fwd, now=3), orc.step(fwd, now=3)
    _diff(ra, rb)
    assert ra.code.tolist() == [0] and ra.est.tolist() == [1]
    # FIN closes the connection (the FIN itself still rides est)...
    fin = _b(CLIENT, SERVER, 40000, 80, flags=TCP_FIN)
    ra, rb = tpu.step(fin, now=4), orc.step(fin, now=4)
    _diff(ra, rb)
    assert ra.est.tolist() == [1]
    # ...after which the same tuple re-classifies under the deny, and the
    # reply direction is gone too.
    ra, rb = tpu.step(fwd, now=5), orc.step(fwd, now=5)
    _diff(ra, rb)
    assert ra.code.tolist() == [1] and ra.est.tolist() == [0]
    rev = _b(SERVER, CLIENT, 80, 40000)
    ra, rb = tpu.step(rev, now=6), orc.step(rev, now=6)
    _diff(ra, rb)
    assert ra.reply.tolist() == [0]
    assert tpu.cache_stats()["committed"] == orc.cache_stats()["committed"]


def test_rst_on_reply_direction_tears_down_both():
    tpu, orc = _pair()
    fwd = _b(CLIENT, SERVER, 41000, 80)
    for dp in (tpu, orc):
        dp.step(fwd, now=1)
    rst = _b(SERVER, CLIENT, 80, 41000, flags=TCP_RST)
    ra, rb = tpu.step(rst, now=2), orc.step(rst, now=2)
    _diff(ra, rb)
    assert ra.reply.tolist() == [1]  # the RST itself is the reply leg
    for dp, name in ((tpu, "tpu"), (orc, "orc")):
        assert dp.cache_stats()["committed"] == 0, name
    ra, rb = tpu.step(fwd, now=3), orc.step(fwd, now=3)
    _diff(ra, rb)
    assert ra.est.tolist() == [0]


def test_closing_segment_never_establishes():
    """A FIN that MISSES the cache (no prior connection) classifies but
    commits nothing — a closing segment is not a new flow."""
    tpu, orc = _pair()
    fin = _b(CLIENT, SERVER, 42000, 80, flags=TCP_FIN)
    ra, rb = tpu.step(fin, now=1), orc.step(fin, now=1)
    _diff(ra, rb)
    assert ra.code.tolist() == [0] and ra.committed.tolist() == [0]
    assert tpu.cache_stats()["occupied"] == orc.cache_stats()["occupied"] == 0


def test_plain_flags_do_not_tear_down():
    """SYN/ACK/PSH traffic never touches the teardown path; UDP with the
    same flag bits set is ignored entirely."""
    tpu, orc = _pair()
    fwd = _b(CLIENT, SERVER, 43000, 80, flags=0x18)  # PSH|ACK
    for dp in (tpu, orc):
        dp.step(fwd, now=1)
        assert dp.step(fwd, now=2).est.tolist() == [1]
    udp = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(CLIENT)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(SERVER)], np.uint32),
        proto=np.array([17], np.int32),
        src_port=np.array([5353], np.int32),
        dst_port=np.array([53], np.int32),
        tcp_flags=np.array([TCP_RST], np.int32),  # nonsense on UDP: ignored
    )
    for dp in (tpu, orc):
        assert dp.step(udp, now=3).committed.tolist() == [1]
        assert dp.step(udp, now=4).est.tolist() == [1]
