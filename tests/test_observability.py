"""Audit-log stream + metrics surface + eviction measurement (SURVEY §5;
ref audit_logging.go:48-171 dedup buffering, prometheus.go:33-188)."""

import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.observability import AuditLogger, render_metrics
from antrea_tpu.observability.audit import deny_rule_ids
from antrea_tpu.ops import hashing
from antrea_tpu.packet import PacketBatch
from antrea_tpu.utils import ip as iputil

SLOTS = 1 << 10


def _deny_ps(target_ip: str) -> PolicySet:
    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        "atg", [cp.GroupMember(ip=target_ip, node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="deny-in", name="deny-in", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg"], tier_priority=cp.TIER_APPLICATION,
        priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN, action=cp.RuleAction.REJECT, priority=0,
        )],
    ))
    return ps


def _dps(ps):
    return [
        TpuflowDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8, miss_chunk=16),
        OracleDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8),
    ]


def _batch(rows):
    return PacketBatch(
        src_ip=np.array([r[0] for r in rows], np.uint32),
        dst_ip=np.array([r[1] for r in rows], np.uint32),
        proto=np.array([r[2] for r in rows], np.int32),
        src_port=np.array([r[3] for r in rows], np.int32),
        dst_port=np.array([r[4] for r in rows], np.int32),
    )


def test_audit_dedup_and_parity(tmp_path):
    """Denied/rejected packets produce dedup-buffered audit records with
    rule attribution and reject kinds — identical from both datapaths."""
    target = iputil.ip_to_u32("10.0.0.10")
    src = iputil.ip_to_u32("10.0.0.5")
    b = _batch([
        (src, target, 6, 41000, 80),      # REJECT by rule -> tcp-rst
        (src, target, 17, 41000, 53),     # REJECT by rule -> icmp-unreach
        (src, iputil.ip_to_u32("10.0.0.99"), 6, 41000, 80),  # allowed
    ])
    lines = []
    ps = _deny_ps("10.0.0.10")
    for dp in _dps(ps):
        log = AuditLogger(dedup_s=5, deny_rules=deny_rule_ids(ps),
                          path=str(tmp_path / f"{dp.datapath_type.value}.log"))
        # Same flow observed at t=1,2,3 (inside the window), then at t=20.
        for now in (1, 2, 3):
            log.observe(b, dp.step(b, now), now)
        log.observe(b, dp.step(b, 20), 20)
        log.flush(now=99, force=True)
        got = sorted(r.line() for r in log.records)
        lines.append(got)
        # Two flows x two windows = 4 records; counts aggregate the window.
        assert len(got) == 4, got
        assert any("deny-in/In/0 Reject tcp-rst" in x and "x3" in x for x in got)
        assert any("icmp-unreach" in x for x in got)
        assert any("x1" in x for x in got)
        assert not any("10.0.0.99" in x for x in got)  # allows are not audited
    assert lines[0] == lines[1]  # audit parity across datapaths


def test_default_deny_attribution_in_audit():
    """K8s isolation drops (no explicit rule) audit as DefaultDeny."""
    from antrea_tpu.apis.crd import LabelSelector
    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        "atg", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="np", name="np", namespace="default",
        type=cp.NetworkPolicyType.K8S, rules=[],
        applied_to_groups=["atg"], policy_types=[cp.Direction.IN],
    ))
    dp = OracleDatapath(ps, [], flow_slots=SLOTS, aff_slots=1 << 8)
    b = _batch([(iputil.ip_to_u32("10.0.0.5"), iputil.ip_to_u32("10.0.0.10"),
                 6, 42000, 80)])
    log = AuditLogger()
    log.observe(b, dp.step(b, 1), 1)
    recs = log.flush(99, force=True)
    assert len(recs) == 1 and recs[0].rule == "DefaultDeny"
    assert recs[0].verdict == "Drop"


def test_deny_attribution_prefers_denying_direction():
    """An egress Drop + an opposite-direction ingress Allow both attribute
    on the denied packet; the audit line must name the DENYING rule, not
    the allow (review finding: `in or out` picked the allow)."""
    ps = PolicySet()
    ps.applied_to_groups["atg-src"] = cp.AppliedToGroup(
        "atg-src", [cp.GroupMember(ip="10.0.0.5", node="n0")]
    )
    ps.applied_to_groups["atg-dst"] = cp.AppliedToGroup(
        "atg-dst", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(cp.NetworkPolicy(
        uid="allow-in", name="allow-in", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg-dst"], tier_priority=cp.TIER_APPLICATION,
        priority=2.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN, action=cp.RuleAction.ALLOW, priority=0,
        )],
    ))
    ps.policies.append(cp.NetworkPolicy(
        uid="drop-out", name="drop-out", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["atg-src"], tier_priority=cp.TIER_APPLICATION,
        priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.OUT, action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    b = _batch([(iputil.ip_to_u32("10.0.0.5"), iputil.ip_to_u32("10.0.0.10"),
                 6, 43000, 80)])
    for dp in _dps(ps):
        log = AuditLogger(deny_rules=deny_rule_ids(ps))
        r = dp.step(b, 1)
        assert int(r.code[0]) == 1
        log.observe(b, r, 1)
        recs = log.flush(99, force=True)
        assert len(recs) == 1, dp.datapath_type
        assert recs[0].rule == "drop-out/Out/0", (dp.datapath_type, recs[0])


def _colliding_flows(n_slots, count=4):
    """Find distinct 5-tuples sharing one cache slot (forced evictions)."""
    base = None
    out = []
    sport = 30000
    while len(out) < count:
        sport += 1
        src = iputil.ip_to_u32("10.1.0.1")
        dst = iputil.ip_to_u32("10.1.0.2")
        h = int(hashing.flow_hash(np.uint32(src), np.uint32(dst), 6, sport, 80))
        slot = h & (n_slots - 1)
        if base is None:
            base = slot
        if slot == base:
            # The reply tuple must not share the slot (keep the count exact).
            rh = int(hashing.flow_hash(np.uint32(dst), np.uint32(src), 6, 80, sport))
            if (rh & (n_slots - 1)) != base:
                out.append((src, dst, 6, sport, 80))
    return out


def test_eviction_counting_and_cache_stats():
    """Direct-mapped collisions are measured (round-2 verdict weak #5):
    distinct tuples hashed to one slot evict each other, counted identically
    by kernel and oracle; cache_stats reports the census."""
    flows = _colliding_flows(SLOTS, count=3)
    for dp in _dps(PolicySet()):
        for i, f in enumerate(flows):
            dp.step(_batch([f]), now=i + 1)  # sequential: each evicts prior
        c = dp.cache_stats()
        # flow 1 evicts flow 0's fwd entry, flow 2 evicts flow 1's: 2
        # forward evictions (reply slots chosen collision-free).
        assert c["evictions"] == 2, (dp.datapath_type, c)
        assert c["slots"] == SLOTS
        assert c["committed"] >= 4  # surviving fwd + all reply entries
        assert c["occupied"] == c["committed"] + c["denials"]


def test_metrics_rendering():
    dp = OracleDatapath(_deny_ps("10.0.0.10"), [], flow_slots=SLOTS, aff_slots=1 << 8)
    b = _batch([
        (iputil.ip_to_u32("10.0.0.5"), iputil.ip_to_u32("10.0.0.10"), 6, 41000, 80),
        (iputil.ip_to_u32("10.0.0.5"), iputil.ip_to_u32("10.0.0.77"), 6, 41000, 80),
    ])
    dp.step(b, 1)
    text = render_metrics(dp, node="n0")
    assert 'antrea_tpu_rule_packets_total{direction="ingress",rule="deny-in/In/0",node="n0"} 1' in text
    assert 'antrea_tpu_default_verdict_packets_total{verdict="allow",node="n0"} 1' in text
    assert 'antrea_tpu_flow_cache_entries{kind="occupied",node="n0"}' in text
    assert "antrea_tpu_flow_cache_evictions_total" in text


def test_dissemination_metrics_rendering():
    """Scrape format of the dissemination-health surface: per-watcher
    queue depth/overflow/needs-resync from a server's
    dissemination_stats(), per-agent reconnect/resync counters, and the
    reconciler's sync_failures_total — duck-typed exactly as the real
    DisseminationServer / NetAgent / AgentPolicyController expose them."""
    from types import SimpleNamespace

    from antrea_tpu.observability import render_dissemination_metrics

    class _Srv:
        def dissemination_stats(self):
            return {
                "watchers": {
                    "n1": {"pending": 3, "overflows": 1,
                           "needs_resync": True},
                    "n2": {"pending": 0, "overflows": 0,
                           "needs_resync": False},
                },
                "resyncs_total": 4,
                "reconnects_total": 2,
            }

    agents = [
        # A NetAgent: wire counters + embedded controller's failure count.
        SimpleNamespace(node="n1", reconnects_total=2, resyncs_total=3,
                        agent=SimpleNamespace(sync_failures_total=5)),
        # A bare AgentPolicyController: only the failure counter.
        SimpleNamespace(node="n2", sync_failures_total=0),
    ]
    text = render_dissemination_metrics(_Srv(), agents)
    assert text.endswith("\n")
    assert "# TYPE antrea_tpu_dissemination_watcher_pending gauge" in text
    assert 'antrea_tpu_dissemination_watcher_pending{node="n1"} 3' in text
    assert 'antrea_tpu_dissemination_watcher_overflows_total{node="n1"} 1' in text
    assert 'antrea_tpu_dissemination_watcher_needs_resync{node="n1"} 1' in text
    assert 'antrea_tpu_dissemination_watcher_needs_resync{node="n2"} 0' in text
    assert "antrea_tpu_dissemination_resyncs_total 4" in text
    assert "antrea_tpu_dissemination_reconnects_total 2" in text
    assert 'antrea_tpu_agent_reconnects_total{node="n1"} 2' in text
    assert 'antrea_tpu_agent_resyncs_total{node="n1"} 3' in text
    assert 'antrea_tpu_agent_sync_failures_total{node="n1"} 5' in text
    assert 'antrea_tpu_agent_sync_failures_total{node="n2"} 0' in text
    # Every exposed family is TYPEd (scrape-format discipline).
    for line in text.splitlines():
        if line.startswith("#"):
            assert line.startswith("# TYPE "), line

    # Agent-only scrape (no server reachable) still renders.
    agent_only = render_dissemination_metrics(None, agents)
    assert "dissemination_watcher_pending" not in agent_only
    assert 'antrea_tpu_agent_sync_failures_total{node="n2"} 0' in agent_only
