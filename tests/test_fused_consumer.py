"""Parity of the fused pallas consumer path (classify_batch fused=True)
against the XLA scan path — the cold-path kernel the bench measures.

Runs in pallas interpret mode on CPU (tests/conftest.py pins JAX_PLATFORMS
=cpu); the same code compiles on TPU where the bench uses it.
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.ops import match
from antrea_tpu.simulator.genpolicy import gen_cluster
from antrea_tpu.simulator.traffic import gen_traffic
from antrea_tpu.utils import ip as iputil


def _world(n_rules=600, batch=256):
    cluster = gen_cluster(n_rules, n_nodes=8, pods_per_node=8, seed=5)
    cps = compile_policy_set(cluster.ps)
    drs, meta = match.to_device(cps)
    tr = gen_traffic(cluster.pod_ips, batch, n_flows=batch, seed=6)
    args = (
        iputil.flip_u32(tr.src_ip),
        iputil.flip_u32(tr.dst_ip),
        tr.proto.astype(np.int32),
        tr.dst_port.astype(np.int32),
    )
    return drs, meta, args


def _compare(drs, meta, args):
    ref = match.classify_batch(drs, *args, meta=meta)
    got = match.classify_batch(drs, *args, meta=meta, fused=True)
    for k in ("code", "egress_code", "egress_rule", "ingress_code",
              "ingress_rule"):
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k
        )


def test_fused_consumer_parity_random_world():
    drs, meta, args = _world()
    _compare(drs, meta, args)


def test_fused_consumer_parity_odd_batch_padding():
    # Non-multiple-of-tile batch exercises the internal padding path.
    drs, meta, args = _world(batch=37)
    _compare(drs, meta, args)


def test_fused_datapath_step_parity():
    """The production switch (TpuflowDatapath(fused=True)) routes cache
    misses through the fused consumer: verdicts at the Datapath boundary
    match the unfused twin exactly."""
    from antrea_tpu.datapath import TpuflowDatapath
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.simulator.genpolicy import gen_cluster
    from antrea_tpu.simulator.traffic import gen_traffic

    cluster = gen_cluster(400, n_nodes=8, pods_per_node=8, seed=9)
    tr = gen_traffic(cluster.pod_ips, 160, n_flows=80, seed=10)
    batch = PacketBatch(
        src_ip=tr.src_ip, dst_ip=tr.dst_ip, proto=tr.proto,
        src_port=tr.src_port, dst_port=tr.dst_port,
    )
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 6, miss_chunk=64)
    dp_f = TpuflowDatapath(cluster.ps, [], fused=True, **kw)
    dp_u = TpuflowDatapath(cluster.ps, [], fused=False, **kw)
    for now in (1, 2):  # miss round, then cache-hit round
        rf = dp_f.step(batch, now)
        ru = dp_u.step(batch, now)
        np.testing.assert_array_equal(rf.code, ru.code, err_msg=f"now={now}")
        np.testing.assert_array_equal(rf.est, ru.est)
        assert rf.ingress_rule == ru.ingress_rule
        assert rf.egress_rule == ru.egress_rule
