"""Fault-injected churn-storm soak for the dissemination plane.

The failure shape that kills a watch-fanout control plane at fleet scale
is the REPLAY STORM: a policy burst touching every span overflows every
bounded watcher queue at once and every agent demands a synchronous full
snapshot in the same pump round.  This tier drives real storms
(simulator/fleet.run_churn_storm: distinct-key churn past the watcher
cap + same-key rewrite bursts) through live fleets under FaultPlan chaos
and holds four bars every cycle:

  * span-exact reconvergence — every node's tables match the
    controller's policy_set_for_node oracle, generations included
    (generation parity pins latest-wins coalescing: a stale buffered
    payload shows up as a lagging generation);
  * bounded memory — no watcher's pending ever exceeds the cap, and no
    more than resync_concurrency resync cursors are ever in flight;
  * metered storms — coalescing absorbed the same-key churn
    (coalesced_total), overflow re-lists were chunked
    (resync_chunks_total) and admission-gated (resyncs_shed_total);
  * no head-of-line blocking — a stalled reader mid-resync delays only
    its own node; healthy agents' live delivery stays in the no-fault
    envelope (the chunked-pump pin, test_storm_stalled_reader below).

The inproc/netwire smokes ride tier-1; the 1k-agent wire soak is slow.
"""

import time

import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.controller.status import StatusAggregator
from antrea_tpu.dissemination import FaultPlan, RamStore
from antrea_tpu.dissemination.faults import FaultySocket
from antrea_tpu.dissemination.netwire import (
    Backoff,
    DisseminationServer,
    make_ca,
)
from antrea_tpu.simulator.fleet import (
    FakeAgentFleet,
    fleet_converged,
    run_churn_storm,
)

pytestmark = pytest.mark.chaos


def _world(n_nodes: int):
    """Controller + store + one web pod per node -> (ctl, store, nodes)."""
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    nodes = [f"node-{i}" for i in range(n_nodes)]
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    for i, node in enumerate(nodes):
        ctl.upsert_pod(crd.Pod(
            namespace="default", name=f"web-{i}",
            ip=f"10.{(i >> 8) & 255}.{i & 255}.1", node=node,
            labels={"app": "web"}))
    return ctl, store, nodes


def _netwire_world(tmp_path, n_nodes: int, *, cap, resync_chunk,
                   resync_concurrency, drain_max, send_budget=None,
                   fault_plan=None):
    certdir = str(tmp_path / "pki")
    make_ca(certdir)
    ctl, store, nodes = _world(n_nodes)
    srv = DisseminationServer(
        store, certdir, status_aggregator=StatusAggregator(ctl),
        watcher_max_pending=cap, resync_chunk=resync_chunk,
        resync_concurrency=resync_concurrency, drain_max=drain_max,
        send_budget=send_budget)
    fleet = FakeAgentFleet(
        None, nodes, transport="netwire", server=srv, certdir=certdir,
        fault_plan=fault_plan,
        backoff_factory=lambda n: Backoff(base=0.01, cap=0.1, node=n))
    return ctl, store, nodes, srv, fleet


# -- tier-1 smoke ------------------------------------------------------------


def test_storm_smoke_inproc_fleet():
    """~160 inproc agents, two storm rounds, churn past the cap: every
    round forces fleet-wide overflow (distinct keys) and a same-key
    rewrite burst (coalesced), and the fleet reconverges span-exactly —
    the storm-soak engine's own smoke."""
    cap = 32
    ctl, store, nodes = _world(160)
    fleet = FakeAgentFleet(store, nodes, max_pending=cap)
    fleet.pump()
    meters = run_churn_storm(ctl, fleet, nodes, rounds=2, churn=64,
                             cap=cap, max_cycles=200)
    # The storm was real: distinct-key churn overflowed bounded queues
    # fleet-wide, same-key churn coalesced instead of growing them.
    assert meters["overflows_total"] > 0
    assert meters["coalesced_total"] > 0
    assert meters["agent_resyncs_seen"] >= meters["overflows_total"] > 0
    assert meters["max_pending_seen"] <= cap
    # run_churn_storm returned => every round reached span-exact
    # convergence; pin it once more at rest.
    assert fleet_converged(ctl, fleet, nodes)
    fleet.stop()


def test_storm_smoke_netwire_chunked_gated(tmp_path):
    """The production-shaped smoke: 32 mTLS agents behind a chunked,
    admission-gated, budgeted server, with a deterministic socket reset
    landing mid-storm.  Overflow re-lists ship in bounded chunks, at most
    resync_concurrency cursors ever run, the excess is shed (metered) —
    and the fleet still reconverges span-exactly under the fault."""
    cap, conc = 16, 4
    plan = FaultPlan(seed=11)
    # One certain mid-storm reset (prob-only chaos can prove nothing):
    # node-0's 3rd recv onward dies once; its reconnect re-lists.
    plan.after("node-0.recv", 2, "reset", times=1)
    plan.prob("node-7.send", 0.05, "reset", times=2)
    ctl, store, nodes, srv, fleet = _netwire_world(
        tmp_path, 32, cap=cap, resync_chunk=8, resync_concurrency=conc,
        drain_max=16, send_budget=4000, fault_plan=plan)
    try:
        fleet.pump()
        meters = run_churn_storm(ctl, fleet, nodes, rounds=2, churn=48,
                                 cap=cap, resync_concurrency=conc,
                                 max_cycles=600)
        assert meters["overflows_total"] > 0
        assert meters["coalesced_total"] > 0
        # Chunking and admission control actually engaged: re-lists were
        # shipped in bounded chunks and excess cursors were parked.
        assert meters["resync_chunks_total"] > 0
        assert 0 < meters["max_resyncs_inflight"] <= conc
        assert meters["resyncs_shed_total"] > 0
        # The scripted fault fired and was absorbed by reconnect+re-list.
        assert plan.count("reset") >= 1
        assert fleet.agents["node-0"].reconnects_total >= 1
        assert fleet_converged(ctl, fleet, nodes)
        assert meters["max_pending_seen"] <= cap
    finally:
        fleet.stop()
        srv.close()


def _hot_policy(uid: str, cidr: str):
    """Policy applied to app=hot pods only — its span is exactly the
    nodes hosting one (the stalled-reader test gives only ONE node a hot
    pod, so this churn overflows one watcher and no other)."""
    return crd.AntreaNetworkPolicy(
        uid=uid, name=uid, namespace="", tier_priority=250, priority=7.0,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "hot"}),
            ns_selector=crd.LabelSelector.make())],
        rules=[crd.AntreaNPRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(ip_block=crd.IPBlock(cidr))])],
    )


def _live_policy(gen_tag: int):
    """Same-uid rewrite applied to every web pod: the live traffic whose
    delivery latency the stalled-reader test measures on healthy nodes."""
    return crd.AntreaNetworkPolicy(
        uid="live-0", name="live-0", namespace="", tier_priority=250,
        priority=5.0,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make())],
        rules=[crd.AntreaNPRule(
            direction=cp.Direction.IN, action=cp.RuleAction.DROP,
            peers=[crd.AntreaPeer(
                ip_block=crd.IPBlock(f"203.0.{gen_tag % 250}.0/24"))])],
    )


def test_storm_stalled_reader_no_head_of_line(tmp_path):
    """The pump() head-of-line pin: one agent's socket turns molasses
    (50ms per server-side send, injected mid-session) and its watcher is
    then overflowed into a ~33-object re-list.  Pre-chunking, that
    re-list was ONE synchronous loop in pump() — every send delayed,
    ~1.65s of wall inside a single round while every healthy agent
    waited.  Chunked + budgeted (chunk=2, drain=2), each round ships at
    most chunk+drain+markers (~5 delayed sends, ~0.25s): no single pump
    may exceed 1.2s, healthy agents keep realizing live churn inside the
    no-fault envelope while the stalled node's cursor is still open, and
    the stalled node itself converges once the fault lifts."""
    cap = 8
    plan = FaultPlan(seed=5)
    ctl, store, nodes, srv, fleet = _netwire_world(
        tmp_path, 5, cap=cap, resync_chunk=2, resync_concurrency=2,
        drain_max=2)
    stalled, healthy = nodes[0], nodes[1:]
    try:
        # The stalled node also hosts the only app=hot pod: the hot-churn
        # below spans JUST it.
        ctl.upsert_pod(crd.Pod(
            namespace="default", name="hot-pod", ip="10.7.0.1",
            node=stalled, labels={"app": "hot"}))
        ctl.upsert_antrea_policy(_live_policy(0))
        for _ in range(20):
            fleet.pump()
            if fleet_converged(ctl, fleet, nodes):
                break
        assert fleet_converged(ctl, fleet, nodes)

        # Interpose the delay on the SERVER side of the stalled node's
        # live connection: every send to it now costs 50ms.
        st = srv._conns[stalled]
        plan.every("srv-stall.send", 1, "delay", delay_s=0.05)
        st.conn.sock = FaultySocket(st.conn.sock, plan, "srv-stall")

        # Overflow ONLY the stalled watcher: 30 distinct hot policies
        # (a ~33-key snapshot, cap 8) spanning just its node.
        for i in range(30):
            ctl.upsert_antrea_policy(
                _hot_policy(f"hot-{i}", f"198.51.{i}.0/24"))
        qs = srv.dissemination_stats()["watchers"]
        assert qs[stalled]["needs_resync"]
        assert all(not qs[h]["needs_resync"] for h in healthy)

        # Live churn while the stalled node trickles through its chunked
        # re-list: healthy nodes must realize each rewrite promptly, and
        # no single pump round may stall on the slow socket.
        max_pump_wall = 0.0
        saw_interleaving = False
        for gen_tag in range(1, 9):
            ctl.upsert_antrea_policy(_live_policy(gen_tag))
            for _ in range(2):
                t0 = time.perf_counter()
                fleet.pump()
                max_pump_wall = max(max_pump_wall,
                                    time.perf_counter() - t0)
            stats = srv.dissemination_stats()
            if (stats["resyncs_inflight"] >= 1
                    and fleet_converged(ctl, fleet, healthy)):
                # The healthy fleet is span-exact (latest live-0
                # generation included) while the stalled node's cursor
                # is STILL open: live traffic interleaved with the
                # re-list instead of queueing behind it.
                saw_interleaving = True
        assert saw_interleaving, (
            "stalled node's chunked resync never overlapped a healthy "
            "live delivery — the head-of-line case was not exercised")
        assert max_pump_wall < 1.2, (
            f"a single pump round took {max_pump_wall:.2f}s — the "
            f"stalled reader's re-list is blocking the round again")
        # Healthy agents' live realization stayed in the no-fault
        # envelope (delivery ~= one pump round, nowhere near the ~1.75s
        # serial replay).
        for h in healthy:
            hist = fleet.agents[h].realization_hist
            assert hist.count > 0
            assert hist.quantile(0.99) < 1.0

        # Fault lifts: the trickled node drains its cursor and lands on
        # the same span-exact state as everyone else.
        plan.quiesce()
        for _ in range(40):
            fleet.pump()
            if fleet_converged(ctl, fleet, nodes):
                break
        assert fleet_converged(ctl, fleet, nodes)
        assert plan.count("delay") > 0  # the stall actually happened
    finally:
        fleet.stop()
        srv.close()


# -- slow soak ---------------------------------------------------------------


@pytest.mark.slow
def test_storm_soak_fleet_netwire(tmp_path):
    """The production-shaped soak rung (ROADMAP item 2's fleet ladder):
    hundreds of mTLS agents (ANTREA_TPU_SOAK_AGENTS scales it to the
    1k/10k rungs on bigger hosts; 300 fits this tier's single-core
    budget), two churn storms past the cap under probabilistic socket
    resets, chunked + admission-gated + budgeted dissemination.  Bars:
    every cycle bounded (pending <= cap, inflight <= concurrency),
    span-exact reconvergence after each round, storms metered not
    replayed.  resync_chunk (48) is deliberately SMALLER than the
    ~100-key storm snapshot so cursors genuinely span rounds — that is
    what drives inflight to the bound and forces admission shedding."""
    import os

    n = int(os.environ.get("ANTREA_TPU_SOAK_AGENTS", "300"))
    cap, conc = 64, 32
    plan = FaultPlan(seed=7)
    for i in range(0, n, 100):  # ~1% of the fleet armed
        plan.prob(f"node-{i}.send", 0.05, "reset", times=2)
        plan.prob(f"node-{i}.recv", 0.05, "reset", times=2)
    ctl, store, nodes, srv, fleet = _netwire_world(
        tmp_path, n, cap=cap, resync_chunk=48, resync_concurrency=conc,
        drain_max=64, send_budget=100_000, fault_plan=plan)
    try:
        fleet.pump()
        meters = run_churn_storm(ctl, fleet, nodes, rounds=2, churn=96,
                                 cap=cap, resync_concurrency=conc,
                                 max_cycles=2000)
        assert meters["overflows_total"] > 0
        assert meters["coalesced_total"] > 0
        assert meters["resync_chunks_total"] > 0
        # The gate was EXERCISED, not just respected: cursors spanned
        # rounds, inflight reached the bound, and the excess was parked.
        assert 0 < meters["max_resyncs_inflight"] <= conc
        assert meters["resyncs_shed_total"] > 0
        assert meters["max_pending_seen"] <= cap
        assert fleet_converged(ctl, fleet, nodes)
    finally:
        fleet.stop()
        srv.close()
