"""Unified maintenance scheduler (ISSUE 7 tentpole): ONE budgeted
background plane for canary, audit, aging, FQDN, and recompile loops.

The acceptance bar: all five pre-existing loops run only via
`MaintenanceScheduler.tick()` (tools/check_maintenance.py green), the
hot-step HLO is bit-identical with the scheduler enabled, per-tick
budgets are never exceeded and no task starves across 1k randomized
ticks, priority inverts under degradation (recompile + canary preempt,
cosmetic scrubs shed, nothing starves after recovery), and the whole
plane serializes against in-flight drains / overlap finalizers / epoch
swaps behind one point.
"""

import itertools
import json
import random
import sys
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.config import ConfigError
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.datapath.maintenance import (MAINT_TASKS,
                                             MaintenanceScheduler,
                                             MaintenanceTask)
from antrea_tpu.dissemination import FaultPlan
from antrea_tpu.dissemination.faults import FaultClock
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

CLIENT, SRV, BLOCKED = "10.0.1.1", "10.0.0.10", "10.0.9.9"
VIP = "10.96.0.1"

_NOW = itertools.count(1000)
_SPORT = itertools.count(30000)

SMALL = dict(flow_slots=1 << 8, aff_slots=1 << 4)


def _world():
    ps = PolicySet(
        policies=[cp.NetworkPolicy(
            uid="p1", name="p1", type=cp.NetworkPolicyType.ACNP,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.IN,
                from_peer=cp.NetworkPolicyPeer(address_groups=["blocked"]),
                action=cp.RuleAction.DROP, priority=0)],
            applied_to_groups=["web"], tier_priority=250, priority=1.0)],
        address_groups={"blocked": cp.AddressGroup(
            name="blocked", members=[cp.GroupMember(ip=BLOCKED)])},
        applied_to_groups={"web": cp.AppliedToGroup(
            name="web", members=[cp.GroupMember(ip=SRV)])},
    )
    svcs = [ServiceEntry(cluster_ip=VIP, port=80, protocol=6, name="web",
                         namespace="default",
                         endpoints=[Endpoint(ip=SRV, port=8080)])]
    return ps, svcs


def _dp(dp_cls, ps, svcs, **kw):
    if dp_cls is TpuflowDatapath:
        kw.setdefault("miss_chunk", 16)
    return dp_cls(ps, svcs, **SMALL, **kw)


def _fresh(src, dst=SRV, dport=80):
    return Packet(src_ip=iputil.ip_to_u32(src), dst_ip=iputil.ip_to_u32(dst),
                  proto=6, src_port=next(_SPORT), dst_port=dport)


def _stub_owner(degraded=False):
    return SimpleNamespace(degraded=degraded, _slowpath=None)


# ---------------------------------------------------------------------------
# Scheduler semantics (stub-owner level): DRR, budgets, starvation, shed
# ---------------------------------------------------------------------------


def test_registration_and_typed_budget_errors():
    sched = MaintenanceScheduler(_stub_owner())
    sched.register(MaintenanceTask("a", lambda n, b: 1, budget=4))
    with pytest.raises(ValueError, match="already registered"):
        sched.register(MaintenanceTask("a", lambda n, b: 1, budget=4))
    with pytest.raises(ConfigError, match="budget must be positive"):
        MaintenanceTask("bad", lambda n, b: 1, budget=0)
    with pytest.raises(ConfigError, match="min_cost must be positive"):
        MaintenanceTask("bad", lambda n, b: 1, budget=4, min_cost=-1)
    with pytest.raises(ConfigError, match="tick_budget must be positive"):
        MaintenanceScheduler(_stub_owner(), tick_budget=0)


def test_min_cost_exceeding_tick_budget_is_a_config_error():
    """A task whose min_cost exceeds a finite global tick budget could
    never be granted (give is clamped to the remaining budget, so deficit
    banking cannot help): registration fails loudly instead of the task
    starving silently forever."""
    sched = MaintenanceScheduler(_stub_owner(), tick_budget=8)
    with pytest.raises(ConfigError, match="starve"):
        sched.register(MaintenanceTask("big", lambda n, b: b, budget=16,
                                       min_cost=16))
    # Engine level: default canary_probes (64) over a tighter maint_budget.
    ps, svcs = _world()
    with pytest.raises(ConfigError, match="canary"):
        _dp(TpuflowDatapath, ps, svcs, maint_budget=8)
    # Shrinking the probe batch to fit is the documented fix.
    dp = _dp(OracleDatapath, ps, svcs, maint_budget=8, canary_probes=4)
    assert dp.maintenance is not None


def test_scheduler_lag_ignores_shed_and_pre_tick_time():
    """The lag gauge measures DENIED opportunity only: deliberately-shed
    tasks had their turn (the scheduler chose), and before the first real
    round nothing has been denied — even if observe() already folded a
    large packet-clock now into the tick clock."""
    sched = MaintenanceScheduler(_stub_owner(degraded=True))
    sched.register(MaintenanceTask("work", lambda n, b: 1, budget=4))
    sched.register(MaintenanceTask("cosmetic", lambda n, b: 1, budget=4,
                                   shed_when_degraded=True))
    sched.observe(1000)  # traffic time arrives before any round
    assert sched.scheduler_lag() == 0
    for t in range(1001, 1031):
        sched.tick(now=t)
    assert sched.stats()["tasks"]["cosmetic"]["shed_total"] == 30
    assert sched.scheduler_lag() == 0  # shedding is a decision, not lag


def test_corruption_escalated_scrub_cost_is_metered(monkeypatch):
    """A scrub that detects corruption escalates to a full-cache sweep
    inside the same scan; the task must report that TRUE cost so tick()
    clamps the accounting and meters the overrun, instead of a
    full-table pass hiding inside a tiny scrub grant."""
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, canary_probes=0)
    real = dp._audit.scan

    def corrupted(now=0, full=False, **kw):
        out = real(now, full, **kw)
        if kw.get("scrub"):
            out = dict(out, scanned=out.get("scanned", 0) + 500)
        return out

    monkeypatch.setattr(dp._audit, "scan", corrupted)
    out = dp.maintenance_tick(now=5)
    grant = out["ran"]["tensor-scrub"]
    assert grant <= dp.maintenance.stats()["tasks"]["tensor-scrub"]["budget"]
    st = dp.maintenance_stats()["tasks"]["tensor-scrub"]
    assert st["overruns_total"] == 1
    assert st["spent_total"] == grant  # clamped, not the 500-row sweep


def test_per_call_tick_budget_must_be_positive():
    """GET /maintenance?tick=1&budget=0 must be rejected like the
    construction-time tick_budget=0, not count a tick that defers every
    task and distorts starvation counters."""
    sched = MaintenanceScheduler(_stub_owner())
    sched.register(MaintenanceTask("a", lambda n, b: 1, budget=4))
    for bad in (0, -3):
        with pytest.raises(ConfigError, match="budget must be positive"):
            sched.tick(budget=bad)
    assert sched.ticks_total == 0
    assert sched.stats()["tasks"]["a"]["deferrals_total"] == 0


def test_drr_budget_clamp_deficit_and_min_cost():
    """Per-task grants honor the global budget; a task whose min cost
    exceeds one tick's grant defers, banks deficit, and runs once it can
    afford it — budget-clamped, never budget-exceeding."""
    sched = MaintenanceScheduler(_stub_owner(), tick_budget=16)
    spent_log = []
    sched.register(MaintenanceTask(
        "greedy", lambda n, b: spent_log.append(("greedy", b)) or b,
        budget=6, priority=1))
    # min_cost 12 > the 6/tick quantum: must wait for the deficit.
    sched.register(MaintenanceTask(
        "expensive", lambda n, b: spent_log.append(("expensive", b)) or 12,
        budget=6, min_cost=12, priority=2))
    out1 = sched.tick()
    assert out1["ran"] == {"greedy": 6}
    assert "expensive" in out1["deferred"]
    assert out1["spent"] <= 16
    # Tick 2: greedy banks+spends its quantum first, leaving 16-6=10 of
    # the global budget: under the expensive task's min cost, so it is
    # still deferred even though its banked deficit (12) could afford it.
    out2 = sched.tick()
    assert out2["spent"] <= 16 and "expensive" in out2["deferred"]
    # A roomier tick lets the banked deficit pay the full min cost.
    out3 = sched.tick(budget=32)
    assert out3["ran"].get("expensive") == 12
    assert out3["spent"] <= 32


def test_overrun_is_clamped_and_metered():
    sched = MaintenanceScheduler(_stub_owner())
    sched.register(MaintenanceTask("rogue", lambda n, b: b + 99, budget=4))
    out = sched.tick(budget=4)
    assert out["ran"]["rogue"] == 4  # clamped to the grant
    st = sched.stats()["tasks"]["rogue"]
    assert st["overruns_total"] == 1 and st["spent_total"] == 4


def test_no_starvation_across_1k_randomized_ticks():
    """The acceptance property: random per-tick global budgets over a
    diverse task set — per-tick budgets are NEVER exceeded, and no task
    starves (every task keeps running throughout; the starvation boost
    guarantees progress even for the most expensive, lowest-priority
    task under tight budgets).  Seeded and deterministic."""
    rng = random.Random(7)
    sched = MaintenanceScheduler(_stub_owner())
    names = []
    for i in range(6):
        name = f"t{i}"
        names.append(name)
        sched.register(MaintenanceTask(
            name, (lambda nm: lambda n, b: min(b, rng.randint(1, b)))(name),
            budget=rng.randint(1, 16),
            min_cost=rng.randint(1, 8),
            priority=rng.randint(0, 5)))
    last_ran = {n: 0 for n in names}
    gaps = {n: 0 for n in names}
    for t in range(1, 1001):
        budget = rng.choice([4, 8, 16, 64, None])
        out = sched.tick(budget=budget)
        if budget is not None:
            assert out["spent"] <= budget, (t, out)
        for n in out["ran"]:
            gaps[n] = max(gaps[n], t - last_ran[n])
            last_ran[n] = t
    st = sched.stats()
    for n in names:
        assert st["tasks"][n]["runs_total"] > 0, f"{n} never ran"
        gaps[n] = max(gaps[n], 1000 - last_ran[n])
        # Progress bound: the starvation boost fires after 8 deferred
        # ticks, so no task should ever wait ~an order beyond that.
        assert gaps[n] <= 64, f"{n} starved for {gaps[n]} ticks"
    assert st["scheduler_lag"] <= 64


def test_priority_inversion_and_shed_under_degradation():
    """While degraded: degraded_priority reorders (recompile first) and
    shed_when_degraded tasks are shed, metered; recovery restores the
    normal order and shed tasks resume — nothing starves after."""
    owner = _stub_owner(degraded=True)
    order = []
    sched = MaintenanceScheduler(owner)
    sched.register(MaintenanceTask(
        "recompile", lambda n, b: order.append("recompile") or 1,
        budget=1, priority=6, degraded_priority=0))
    sched.register(MaintenanceTask(
        "canary", lambda n, b: order.append("canary") or 1,
        budget=1, priority=2, degraded_priority=1))
    sched.register(MaintenanceTask(
        "scrub", lambda n, b: order.append("scrub") or 1,
        budget=1, priority=4, shed_when_degraded=True))
    out = sched.tick()
    assert order == ["recompile", "canary"]
    assert out["shed"] == ["scrub"]
    assert sched.stats()["tasks"]["scrub"]["shed_total"] == 1
    # Recovery: normal priorities, scrub resumes.
    owner.degraded = False
    order.clear()
    out = sched.tick()
    assert order == ["canary", "scrub", "recompile"]
    assert not out["shed"]


def test_fault_clock_drives_the_tick_clock():
    clk = FaultClock(start=100)
    sched = MaintenanceScheduler(_stub_owner(), clock=clk)
    seen = []
    sched.register(MaintenanceTask("t", lambda n, b: seen.append(n) or 1,
                                   budget=1))
    sched.tick()
    clk.advance(41)
    sched.tick()
    assert seen == [100, 141]  # the injected clock, monotonic
    assert sched.clock() == 141
    with pytest.raises(ValueError, match="monotonic"):
        clk.advance(-1)


def test_held_fault_clock_is_never_outrun():
    """tick() with now=None must not self-advance past an injected
    clock: a FaultClock held still IS time standing still, so backoff
    windows and TTL expiries cannot elapse by counting ticks."""
    clk = FaultClock(start=100)
    sched = MaintenanceScheduler(_stub_owner(), clock=clk)
    seen = []
    sched.register(MaintenanceTask("t", lambda n, b: seen.append(n) or 1,
                                   budget=1))
    for _ in range(5):
        sched.tick()
    assert seen == [100] * 5
    assert sched.clock() == 100
    clk.advance(7)
    sched.tick()
    assert seen[-1] == 107


# ---------------------------------------------------------------------------
# Engine-level: task set, serialization, HLO identity, clocks
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_engines_register_the_consolidated_task_set(dp_cls):
    """Both twins register the same inventory (cache-maintain rides the
    async engine only; fqdn-ttl is the agent-side registration)."""
    ps, svcs = _world()
    dp = _dp(dp_cls, ps, svcs)
    assert set(dp.maintenance.task_names) == {
        "canary", "audit-cursor", "tensor-scrub", "degraded-recompile",
        "observability"}
    dpa = _dp(dp_cls, ps, svcs, async_slowpath=True, miss_queue_slots=32,
              drain_batch=16)
    assert set(dpa.maintenance.task_names) == {
        "canary", "audit-cursor", "tensor-scrub", "degraded-recompile",
        "cache-maintain", "observability"}
    # Every name is in the parseable inventory (tools/check_maintenance).
    # fqdn-ttl is the agent-side registration; reshard-migrate is the
    # mesh engine's, registered only while a resize is in flight;
    # tenant-maintain joins on the first tenant_create only
    # (datapath/tenancy — untenanted engines keep this base set);
    # telemetry-sentinel registers only on telemetry=True engines;
    # serving-flush joins when the serving batcher materializes;
    # replica-health is the mesh engine's failover probe loop
    # (failover=True only — single-chip twins have no replicas to lose).
    assert (set(dpa.maintenance.task_names)
            | {"fqdn-ttl", "reshard-migrate", "tenant-maintain",
               "telemetry-sentinel", "serving-flush", "replica-health"}
            == set(MAINT_TASKS))
    tdp = _dp(dp_cls, ps, svcs, telemetry=True)
    assert "telemetry-sentinel" in tdp.maintenance.task_names
    out = dpa.maintenance_tick(now=next(_NOW))
    assert set(out["ran"]) >= {"canary", "audit-cursor", "tensor-scrub",
                               "cache-maintain"}


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_tick_serializes_against_inflight_drain(dp_cls):
    """The ONE serialization point: a tick between begin_drain and
    finish_drain defers WHOLE (blocked, metered) — the popped block's
    pinned cache state is never mutated under it — and the forced-audit
    path refuses outright."""
    ps, svcs = _world()
    dp = _dp(dp_cls, ps, svcs, async_slowpath=True, miss_queue_slots=32,
             drain_batch=16)
    eng = dp._slowpath
    now = next(_NOW)
    dp.step(PacketBatch.from_packets([_fresh(BLOCKED), _fresh(CLIENT)]), now)
    assert eng.begin_drain(now)
    out = dp.maintenance_tick(now=next(_NOW))
    assert out["blocked"] == "inflight-drain" and not out["ran"]
    assert dp.maintenance_stats()["blocked_ticks_total"] == 1
    with pytest.raises(RuntimeError, match="inflight-drain"):
        dp.maintenance_force_audit(now=next(_NOW))
    one = eng.finish_drain(next(_NOW))
    assert one["drained"] == 2
    out = dp.maintenance_tick(now=next(_NOW))
    assert out["blocked"] is None and out["ran"]
    # Post-storm parity: the blocked tick protected the drain.
    oracle = Oracle(ps)
    pkts = [_fresh(BLOCKED), _fresh("192.0.2.9")]
    now = next(_NOW)
    dp.step(PacketBatch.from_packets(pkts), now)
    dp.drain_slowpath(now)
    got = [int(c) for c in np.asarray(
        dp.step(PacketBatch.from_packets(pkts), next(_NOW)).code)]
    assert got == [int(oracle.classify(p).code) for p in pkts]


def test_stale_epoch_promotes_cache_maintain_and_overlap_flushes():
    """An epoch swap (bundle install) promotes cache-maintain to the
    front of the next tick (the fused heal lands before audits walk the
    cache), and staged overlapped drain commits retire at tick start."""
    import copy

    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, async_slowpath=True,
             miss_queue_slots=32, drain_batch=16, overlap_commits=True)
    eng = dp._slowpath
    now = next(_NOW)
    dp.step(PacketBatch.from_packets([_fresh(BLOCKED)]), now)
    assert eng.begin_drain(now)
    eng.finish_drain(now)  # overlap mode: finalizer staged
    assert eng.overlap_depth == 1
    dp.install_bundle(ps=copy.deepcopy(ps))
    assert eng.stale
    out = dp.maintenance_tick(now=next(_NOW))
    assert out["overlap_flushed"] == 1 and eng.overlap_depth == 0
    assert out["ran"].get("cache-maintain") == 1
    assert not eng.stale  # the promoted task healed the epoch
    # cache-maintain ran BEFORE the audit cursor walked the cache.
    ran_order = list(out["ran"])
    assert ran_order.index("cache-maintain") < ran_order.index("audit-cursor")


def test_step_hlo_bit_identical_with_scheduler_enabled():
    """The scheduler lives entirely off the hot step: a
    maintenance-configured kernel twin lowers the compiled step to
    byte-identical HLO vs a default twin, before AND after ticks."""
    from antrea_tpu.models import pipeline as pl
    import jax.numpy as jnp

    ps, svcs = _world()
    a = _dp(TpuflowDatapath, ps, svcs, maint_budget=64)
    b = _dp(TpuflowDatapath, ps, svcs)
    assert a._meta_step == b._meta_step

    def lower_text(dp):
        z = np.zeros(4, np.int32)
        return pl.pipeline_step.lower(
            dp._state, dp._drs, dp._dsvc,
            jnp.asarray(z), jnp.asarray(z), jnp.asarray(z),
            jnp.asarray(z), jnp.asarray(z),
            jnp.int32(0), jnp.int32(0), meta=dp._meta_step,
        ).as_text()

    before = lower_text(a)
    assert before == lower_text(b)
    a.maintenance_tick(now=next(_NOW))
    a.maintenance_tick(now=next(_NOW))
    assert lower_text(a) == before


def test_fqdn_ttl_runs_as_scheduler_task_on_the_tick_clock():
    """Satellite: FQDN TTL expiry consults the scheduler's monotonic
    tick clock (FaultClock-driven here), runs as the fqdn-ttl task, and
    honors the per-tick expiry budget."""
    from antrea_tpu.agent.fqdn import FqdnController

    ps, svcs = _world()
    ps.address_groups["fqdn--*.bad.example"] = cp.AddressGroup(
        name="fqdn--*.bad.example", members=[])
    clk = FaultClock(start=0)
    dp = _dp(OracleDatapath, ps, svcs, maint_clock=clk)
    fq = FqdnController(dp)
    fq.register_maintenance(dp.maintenance, budget=1)
    assert "fqdn-ttl" in dp.maintenance.task_names
    fq.configure(ps)
    fq.observe_dns("evil.bad.example", ["203.0.113.7", "203.0.113.8"],
                   ttl_s=50, now=clk.now)
    # Before expiry: a tick expires nothing.
    clk.advance(10)
    out = dp.maintenance_tick()
    assert "fqdn-ttl" not in out["ran"]
    assert len(fq._learned) == 2
    # Past the TTL on the INJECTED clock: expiry honors the 1-learn/tick
    # quantum — direct limit semantics first, then the scheduler's grant.
    clk.advance(100)
    assert fq.tick(limit=1) == 1 and len(fq._learned) == 1
    out = dp.maintenance_tick()
    assert out["ran"].get("fqdn-ttl", 0) >= 1 and not fq._learned
    # tick() without a now and without a scheduler is a hard error.
    with pytest.raises(ValueError, match="explicit now"):
        FqdnController(dp).tick()


# ---------------------------------------------------------------------------
# Chaos: priority inversion end to end (recompile preempts, scrub sheds)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_priority_inversion_under_degraded_mode():
    """Degrade the commit plane via an injected canary failure: while
    degraded, degraded-recompile ticks run FIRST and tensor-scrub ticks
    are shed; the recompile backoff paces attempts on the tick clock;
    once the fault exhausts, recovery restores normal order, shed tasks
    resume, and fresh parity holds — nothing starves after recovery."""
    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, canary_probes=8)
    plan = FaultPlan()
    dp.arm_commit_faults(plan, "n1")
    # Fail the NEXT two canary gates: the install degrades the plane,
    # and the first recompile attempt fails too (stays degraded).
    plan.after("n1.canary", plan.hits("n1.canary"), "fail", times=2)
    with pytest.raises(Exception):
        dp.install_bundle(ps=ps)
    assert dp.degraded

    out1 = dp.maintenance_tick(now=next(_NOW))
    ran = list(out1["ran"])
    assert ran and ran[0] == "degraded-recompile"
    assert "tensor-scrub" in out1["shed"]
    assert dp.degraded  # first retry burned the second injected failure

    # Backoff on the tick clock: the immediate next tick must NOT burn
    # another recompile attempt (retry_at = now + backoff).
    out2 = dp.maintenance_tick(now=out1["now"])
    assert "degraded-recompile" not in out2["ran"]
    assert dp.degraded

    # Advance past the backoff: recovery succeeds (fault exhausted).
    out3 = dp.maintenance_tick(now=out1["now"] + 10)
    assert not dp.degraded
    # Post-recovery: normal order, scrub resumes, nothing starved.
    out4 = dp.maintenance_tick(now=next(_NOW))
    assert "tensor-scrub" in out4["ran"] and not out4["shed"]
    sched = dp.maintenance_stats()
    assert sched["tasks"]["tensor-scrub"]["shed_total"] >= 1
    # Fresh parity after the storm.
    oracle = Oracle(ps)
    pkts = [_fresh(BLOCKED), _fresh(CLIENT)]
    got = [int(c) for c in np.asarray(
        dp.step(PacketBatch.from_packets(pkts), next(_NOW)).code)]
    assert got == [int(oracle.classify(p).code) for p in pkts]


def test_agent_sync_shares_the_scheduler_recompile_backoff():
    """agent/controller.py's degraded-mode forced recompile consults the
    scheduler's shared backoff (maintenance_recovery_due): inside the
    window opened by a failed scheduler recompile attempt, sync() does
    NOT burn another run_bundle; once due (or on non-scheduler
    datapaths), the pre-existing discipline is unchanged."""
    from antrea_tpu.agent.controller import AgentPolicyController
    from antrea_tpu.datapath.commit import STAGE_COMPILE

    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, canary_probes=8)
    plan = FaultPlan()
    dp.arm_commit_faults(plan, "n1")
    plan.after("n1.canary", plan.hits("n1.canary"), "fail", times=2)
    with pytest.raises(Exception):
        dp.install_bundle(ps=ps)
    assert dp.degraded
    out1 = dp.maintenance_tick(now=next(_NOW))  # failed retry opens backoff
    assert dp.degraded and not dp.maintenance_recovery_due()

    agent = AgentPolicyController("n1", dp, clock=lambda: 1e9)
    compiles0 = dp.commit_stats()["commits"].get(f"{STAGE_COMPILE}/ok", 0)
    agent.sync()  # inside the scheduler's backoff window: no attempt
    assert dp.degraded
    assert dp.commit_stats()["commits"].get(
        f"{STAGE_COMPILE}/ok", 0) == compiles0
    # Past the window the scheduler task recovers (fault exhausted)...
    dp.maintenance_tick(now=out1["now"] + 10)
    assert not dp.degraded and dp.maintenance_recovery_due()
    # ...and a healthy datapath never gates sync.
    agent.sync()
    assert not dp.degraded


def test_failed_sync_recovery_opens_the_scheduler_backoff_window():
    """The sharing is bidirectional: a FAILED sync()-driven recovery
    install opens the scheduler's backoff window too, so the
    degraded-recompile task does not fire a second full compile+canary
    run_bundle right behind the failure."""
    from antrea_tpu.agent.controller import AgentPolicyController
    from antrea_tpu.datapath.commit import STAGE_COMPILE

    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs, canary_probes=8)
    plan = FaultPlan()
    dp.arm_commit_faults(plan, "n1")
    plan.after("n1.canary", plan.hits("n1.canary"), "fail", times=2)
    with pytest.raises(Exception):
        dp.install_bundle(ps=ps)
    # Degraded, scheduler window still closed: sync is the first driver.
    assert dp.degraded and dp.maintenance_recovery_due()

    agent = AgentPolicyController("n1", dp, clock=lambda: 1e9)
    agent.sync()  # due -> attempts -> the armed canary fails the install
    assert dp.degraded
    # Sync paces its own retries on the AGENT clock; the scheduler-facing
    # window is what the failure must open.
    assert dp.maintenance_recovery_due()
    assert dp._maint_retry_at > 0
    compiles0 = dp.commit_stats()["commits"].get(f"{STAGE_COMPILE}/ok", 0)
    out = dp.maintenance_tick(now=0)  # same tick-instant: inside window
    assert "degraded-recompile" not in out["ran"]
    assert dp.commit_stats()["commits"].get(
        f"{STAGE_COMPILE}/ok", 0) == compiles0
    # Past the window (faults exhausted) the scheduler task recovers.
    for t in (10, 30, 70):
        if not dp.degraded:
            break
        dp.maintenance_tick(now=t)
    assert not dp.degraded


# ---------------------------------------------------------------------------
# Typed config validation (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp_cls", [OracleDatapath, TpuflowDatapath])
def test_config_error_knob_combos(dp_cls):
    ps, svcs = _world()
    with pytest.raises(ConfigError, match="async_slowpath"):
        _dp(dp_cls, ps, svcs, overlap_commits=True)
    with pytest.raises(ConfigError, match="async_slowpath"):
        _dp(dp_cls, ps, svcs, autotune_drain=True)
    with pytest.raises(ConfigError, match="canary_probes=0"):
        _dp(dp_cls, ps, svcs, canary_probes=0, audit_divergence_trip=2)
    with pytest.raises(ConfigError, match="maint"):
        _dp(dp_cls, ps, svcs, maint_budget=0)
    # Still a ValueError for pre-existing callers, and the legal
    # canary_probes=0 default-trip combination keeps working.
    assert issubclass(ConfigError, ValueError)
    dp = _dp(dp_cls, ps, svcs, canary_probes=0)
    assert dp.maintenance is not None


def test_agent_config_maint_budget_key(tmp_path):
    from antrea_tpu.config import AgentConfig, load_agent_config

    p = tmp_path / "agent.conf"
    p.write_text("maintBudget: 128\n")
    assert load_agent_config(str(p)).maint_budget == 128
    p.write_text("maintBudget: 0\n")
    with pytest.raises(ConfigError):
        load_agent_config(str(p))
    assert AgentConfig().maint_budget is None


# ---------------------------------------------------------------------------
# Tooling + API + metrics + supportbundle surface
# ---------------------------------------------------------------------------


# The loop-discipline gate (tools/check_maintenance.py -> analysis pass
# `maintenance`) runs once for the whole tier-1 suite in
# tests/test_static_analysis.py.


def test_force_audit_base_default_without_a_scheduler():
    """A Datapath subclass with an audit surface but no maintenance
    mixin still serves the /audit?force=1 path: the base-class
    maintenance_force_audit default falls back to a direct full sweep
    (nothing to serialize against without a scheduler)."""
    from antrea_tpu.datapath.interface import Datapath, DatapathType

    class _AuditOnly(Datapath):
        calls: list = []

        @property
        def datapath_type(self):
            return DatapathType.ORACLE

        @property
        def generation(self):
            return 0

        def install_bundle(self, ps=None, services=None):
            return None

        def apply_group_delta(self, name, added, removed):
            return None

        def install_topology(self, topo):
            return None

        def step(self, batch, now=0.0):
            return None

        def stats(self):
            return None

        def trace(self, batch, now=0.0):
            return []

        def audit_stats(self):
            return {"scans_total": len(self.calls)}

        def audit_scan(self, now=0, full=False):
            self.calls.append((now, full))
            return {"scanned": 0, "full": full}

    dp = _AuditOnly()
    out = dp.maintenance_force_audit(now=7)
    assert out == {"scanned": 0, "full": True}
    assert dp.calls == [(7, True)]
    # Without an audit plane the default stays inert (None), matching
    # the route's 404 discipline.
    assert Datapath.maintenance_force_audit(_stub_owner_dp()) is None


def _stub_owner_dp():
    return SimpleNamespace(audit_stats=lambda: None)


def test_maintenance_api_route_antctl_metrics_bundle(capsys, tmp_path):
    """GET /maintenance serves scheduler state; ?tick=1 runs one
    synchronous round; `antctl maintenance --server URL --tick` drives it
    end to end; the metric families render; the support bundle carries
    maintenance.json."""
    import tarfile
    import urllib.request

    from antrea_tpu.agent.apiserver import AgentApiServer
    from antrea_tpu.antctl import main as antctl_main
    from antrea_tpu.observability.metrics import render_metrics
    from antrea_tpu.observability.supportbundle import collect_bundle

    ps, svcs = _world()
    dp = _dp(OracleDatapath, ps, svcs)
    srv = AgentApiServer(dp, node="n1").start()
    try:
        body = json.loads(urllib.request.urlopen(
            srv.address + "/maintenance").read())
        assert {"ticks_total", "scheduler_lag", "tasks"} <= set(body)
        assert set(body["tasks"]) == set(dp.maintenance.task_names)
        ticked = json.loads(urllib.request.urlopen(
            srv.address + "/maintenance?tick=1&budget=256").read())
        assert ticked["ticks_total"] == body["ticks_total"] + 1
        assert ticked["last_tick"]["spent"] <= 256

        rc = antctl_main(["maintenance", "--server", srv.address, "--tick"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["ticks_total"] >= 2 and "last_tick" in out

        # --budget/--now without --tick would be silently dropped: reject.
        rc = antctl_main(["maintenance", "--server", srv.address,
                          "--budget", "4"])
        assert rc == 2
        assert "--tick" in capsys.readouterr().err

        # The forced audit sweep rides the scheduler's serialization.
        forced = json.loads(urllib.request.urlopen(
            srv.address + "/audit?force=1&now=9").read())
        assert forced["last_scan"]["full"] is True
        assert json.loads(urllib.request.urlopen(
            srv.address + "/maintenance").read())["forced_total"] == 1
    finally:
        srv.close()

    text = render_metrics(dp, node="n1")
    for fam in ("antrea_tpu_maintenance_ticks_total",
                "antrea_tpu_maintenance_task_runs_total",
                "antrea_tpu_maintenance_budget_spent_total",
                "antrea_tpu_maintenance_deferrals_total",
                "antrea_tpu_maintenance_shed_total",
                "antrea_tpu_maintenance_scheduler_lag"):
        assert fam in text, fam
    assert 'task="canary"' in text

    out_tar = tmp_path / "bundle.tar.gz"
    members = collect_bundle(dp, str(out_tar), node="n1")
    assert "maintenance.json" in members
    with tarfile.open(out_tar) as tar:
        got = json.load(tar.extractfile("maintenance.json"))
    assert got["ticks_total"] == dp.maintenance_stats()["ticks_total"]
