"""Run BOTH the scalar oracle and the batched kernel against the
hand-authored truth tables in fixtures_reachability.py.

The expectations were written from the reference's documented semantics, not
from either implementation — this is the non-circular leg of the parity
triangle (reference docs -> fixtures <- oracle <- kernel).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models import pipeline as pl
from antrea_tpu.ops.match import flip_ips, make_classifier
from antrea_tpu.oracle import Oracle
from antrea_tpu.oracle.pipeline import PipelineOracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

from fixtures_reachability import ALLOW, DROP, REJECT, SCENARIOS, _ip, ag, atg

import jax.numpy as jnp


def _probe_packet(p) -> Packet:
    return Packet(
        src_ip=iputil.ip_to_u32(_ip(p.src)),
        dst_ip=iputil.ip_to_u32(_ip(p.dst)),
        proto=p.proto,
        src_port=p.sport,
        dst_port=p.dport,
    )


@pytest.mark.parametrize("scenario", SCENARIOS, ids=lambda s: s.name)
def test_oracle_matches_fixture(scenario):
    oracle = Oracle(scenario.ps)
    bad = []
    for p in scenario.probes:
        got = int(oracle.classify(_probe_packet(p)).code)
        if got != p.expect:
            bad.append((p, "expected", p.expect, "got", got))
    assert not bad, (scenario.name, scenario.cite, bad)


@pytest.mark.parametrize("scenario", SCENARIOS, ids=lambda s: s.name)
def test_kernel_matches_fixture(scenario):
    cps = compile_policy_set(scenario.ps)
    fn, _ = make_classifier(cps)
    pkts = [_probe_packet(p) for p in scenario.probes]
    batch = PacketBatch.from_packets(pkts)
    out = fn(
        flip_ips(batch.src_ip),
        flip_ips(batch.dst_ip),
        batch.proto.astype(np.int32),
        batch.dst_port.astype(np.int32),
    )
    codes = np.asarray(out["code"])
    bad = [
        (p, "expected", p.expect, "got", int(codes[i]))
        for i, p in enumerate(scenario.probes)
        if int(codes[i]) != p.expect
    ]
    assert not bad, (scenario.name, scenario.cite, bad)


# ---------------------------------------------------------------------------
# Pipeline-level fixtures: ServiceLB/DNAT + conntrack semantics, expectations
# authored from ovs-pipeline.md ServiceLB/EndpointDNAT (:1028-1158) and the
# established-bypass rules (:1685-1691).
# ---------------------------------------------------------------------------

CLIENT = "10.10.0.26"
EP = "10.10.0.7"  # the web pod is the service endpoint
VIP = "10.96.0.10"


def _svc(endpoints, affinity=0):
    return ServiceEntry(
        name="svc", namespace="default", cluster_ip=VIP, port=80, protocol=6,
        endpoints=endpoints, affinity_timeout_s=affinity,
    )


def _mk_pipeline(ps, services):
    cps = compile_policy_set(ps)
    svc = compile_services(services)
    step, state, (drs, dsvc) = pl.make_pipeline(
        cps, svc, flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=32
    )
    return step, state, drs, dsvc


def _one(step, state, drs, dsvc, src, dst, dport, now, gen, proto=6, sport=40000):
    state, out = step(
        state, drs, dsvc,
        jnp.asarray(flip_ips(np.array([iputil.ip_to_u32(src)], np.uint32))),
        jnp.asarray(flip_ips(np.array([iputil.ip_to_u32(dst)], np.uint32))),
        jnp.asarray(np.array([proto], np.int32)),
        jnp.asarray(np.array([sport], np.int32)),
        jnp.asarray(np.array([dport], np.int32)),
        jnp.int32(now), jnp.int32(gen),
    )
    return state, {k: np.asarray(v) for k, v in out.items()}


def test_fixture_service_dnat_policy_on_endpoint():
    """A drop policy on the ENDPOINT pod must apply to traffic addressed to
    the ClusterIP: classification happens post-DNAT (PreRouting precedes
    EgressSecurity, framework.go:96-118)."""
    from antrea_tpu.apis.controlplane import Direction, RuleAction
    from fixtures_reachability import acnp, rule, peer, _ps

    ps = _ps(
        [acnp("deny-client-to-ep", ["at-ep"],
              [rule(Direction.IN, peer("g-client"), action=RuleAction.DROP)])],
        [ag("g-client", "client")],
        [atg("at-ep", "web")],
    )
    step, state, drs, dsvc = _mk_pipeline(ps, [_svc([Endpoint(EP, 8080)])])
    state, out = _one(step, state, drs, dsvc, CLIENT, VIP, 80, now=10, gen=0)
    assert int(out["code"][0]) == DROP
    # DNAT resolution is still reported (the verdict is post-DNAT):
    dnat_ip = int(np.uint32(np.asarray(out["dnat_ip_f"][0]) ^ np.int32(-(2**31))))
    assert dnat_ip == iputil.ip_to_u32(EP)
    assert int(out["dnat_port"][0]) == 8080
    # An unrelated source is allowed and DNATed.
    state, out = _one(step, state, drs, dsvc, "10.10.0.33", VIP, 80, now=11, gen=0)
    assert int(out["code"][0]) == ALLOW


def test_fixture_service_no_endpoints_rejects():
    """ovs-pipeline.md EndpointDNAT: a service with no endpoints gets the
    SvcReject treatment (REJECT, not silent drop)."""
    from fixtures_reachability import _ps

    step, state, drs, dsvc = _mk_pipeline(_ps([]), [_svc([])])
    state, out = _one(step, state, drs, dsvc, CLIENT, VIP, 80, now=5, gen=0)
    assert int(out["code"][0]) == REJECT
    # Non-service traffic unaffected.
    state, out = _one(step, state, drs, dsvc, CLIENT, EP, 80, now=6, gen=0)
    assert int(out["code"][0]) == ALLOW


def test_fixture_established_bypass_survives_policy_change():
    """ovs-pipeline.md:1685-1691 — established connections go straight to
    the metric table; a policy update does not affect ongoing connections,
    but NEW connections see the new rules."""
    from antrea_tpu.apis.controlplane import Direction, RuleAction
    from fixtures_reachability import acnp, rule, peer, _ps

    step, state, drs0, dsvc = _mk_pipeline(_ps([]), [])
    # Establish client->web under no policy.
    state, out = _one(step, state, drs0, dsvc, CLIENT, EP, 80, now=1, gen=0)
    assert int(out["code"][0]) == ALLOW and int(out["committed"][0]) == 1

    # Bundle commit: a new rule set that drops client->web; gen bumps.
    ps2 = _ps(
        [acnp("deny", ["at-ep"],
              [rule(Direction.IN, peer("g-client"), action=RuleAction.DROP)])],
        [ag("g-client", "client")],
        [atg("at-ep", "web")],
    )
    cps2 = compile_policy_set(ps2)
    from antrea_tpu.ops.match import to_device
    drs2, _meta2 = to_device(cps2)

    # Same flow: established bypass -> still allowed under the new rules.
    state, out = _one(step, state, drs2, dsvc, CLIENT, EP, 80, now=2, gen=1)
    assert int(out["code"][0]) == ALLOW
    assert int(out["est"][0]) == 1
    # A NEW flow (different sport) is classified by the new rules -> drop.
    state, out = _one(step, state, drs2, dsvc, CLIENT, EP, 80, now=3, gen=1,
                      sport=40001)
    assert int(out["code"][0]) == DROP
    assert int(out["est"][0]) == 0


def test_fixture_denied_flow_revalidated_after_relax():
    """The inverse: cached denials are generation-tagged and re-evaluated
    after a bundle commit (megaflow revalidation analog)."""
    from antrea_tpu.apis.controlplane import Direction, RuleAction
    from antrea_tpu.ops.match import to_device
    from fixtures_reachability import acnp, rule, peer, _ps

    ps1 = _ps(
        [acnp("deny", ["at-ep"],
              [rule(Direction.IN, peer("g-client"), action=RuleAction.DROP)])],
        [ag("g-client", "client")],
        [atg("at-ep", "web")],
    )
    step, state, drs1, dsvc = _mk_pipeline(ps1, [])
    state, out = _one(step, state, drs1, dsvc, CLIENT, EP, 80, now=1, gen=0)
    assert int(out["code"][0]) == DROP
    # Cached denial: same flow, same gen -> still drop, from the cache.
    state, out = _one(step, state, drs1, dsvc, CLIENT, EP, 80, now=2, gen=0)
    assert int(out["code"][0]) == DROP and int(out["n_miss"]) == 0

    # Relax: empty policy set, gen bump -> the denial is re-classified.
    cps2 = compile_policy_set(_ps([]))
    drs2, _ = to_device(cps2)
    state, out = _one(step, state, drs2, dsvc, CLIENT, EP, 80, now=3, gen=1)
    assert int(out["code"][0]) == ALLOW and int(out["n_miss"]) == 1


# ---------------------------------------------------------------------------
# Reply-direction fixtures: ct reply state + un-DNAT + reject kinds,
# expectations authored from ovs-pipeline.md (UnSNAT :863-889 undoes NAT on
# reply packets via ct; ConntrackState/:1200 "reply traffic is never dropped
# because of an Antrea-native NetworkPolicy or K8s NetworkPolicy rule") and
# pkg/agent/controller/networkpolicy/reject.go (TCP -> RST, else ICMP
# port-unreachable).  Run at the Datapath boundary on BOTH implementations.
# ---------------------------------------------------------------------------


def _both_datapaths(ps, services):
    from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath

    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    return [
        TpuflowDatapath(ps, services, miss_chunk=32, **kw),
        OracleDatapath(ps, services, **kw),
    ]


def _probe(dp, src, dst, dport, now, proto=6, sport=40000):
    batch = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
        proto=np.array([proto], np.int32),
        src_port=np.array([sport], np.int32),
        dst_port=np.array([dport], np.int32),
    )
    return dp.step(batch, now)


def test_fixture_service_reply_undnat_both_datapaths():
    """ovs-pipeline.md UnSNAT/ct: the reply leg of a DNAT'd Service
    connection (endpoint -> client) is ct-established and its source is
    restored to the Service frontend — on both datapaths identically."""
    from fixtures_reachability import _ps

    for dp in _both_datapaths(_ps([]), [_svc([Endpoint(EP, 8080)])]):
        r = _probe(dp, CLIENT, VIP, 80, now=1)
        assert int(r.code[0]) == ALLOW and int(r.committed[0]) == 1, dp.datapath_type
        # Reply: endpoint -> client, ports swapped (ep_port 8080 -> sport).
        r = _probe(dp, EP, CLIENT, dport=40000, sport=8080, now=2)
        assert int(r.est[0]) == 1, dp.datapath_type
        assert int(r.reply[0]) == 1, dp.datapath_type
        assert int(r.code[0]) == ALLOW, dp.datapath_type
        # un-DNAT: reported rewrite is the original frontend tuple.
        assert int(r.dnat_ip[0]) == iputil.ip_to_u32(VIP), dp.datapath_type
        assert int(r.dnat_port[0]) == 80, dp.datapath_type


def test_fixture_reply_never_dropped_by_policy_both_datapaths():
    """ovs-pipeline.md:1200 — reply traffic of an established connection is
    never dropped by an NP rule, even one that would deny it as a fresh
    flow."""
    from antrea_tpu.apis.controlplane import Direction, RuleAction
    from fixtures_reachability import _ps, acnp, rule, peer

    # Deny ALL ingress to the client pod (would kill the reply as a fresh
    # flow), but the client's own egress connection must still work both ways.
    ps = _ps(
        [acnp("deny-to-client", ["at-client"],
              [rule(Direction.IN, peer("g-web"), action=RuleAction.DROP)])],
        [ag("g-web", "web")],
        [atg("at-client", "client")],
    )
    for dp in _both_datapaths(ps, []):
        r = _probe(dp, CLIENT, EP, 80, now=1)
        assert int(r.code[0]) == ALLOW and int(r.committed[0]) == 1, dp.datapath_type
        r = _probe(dp, EP, CLIENT, dport=40000, sport=80, now=2)
        assert int(r.code[0]) == ALLOW and int(r.reply[0]) == 1, dp.datapath_type
        # The same packet WITHOUT the prior commit is a fresh flow -> DROP
        # (different sport so it misses the reverse entry).
        r = _probe(dp, EP, CLIENT, dport=40000, sport=81, now=3)
        assert int(r.code[0]) == DROP and int(r.reply[0]) == 0, dp.datapath_type


def test_fixture_reject_kinds_both_datapaths():
    """reject.go: REJECT synthesizes a TCP RST for TCP flows and an ICMP
    port-unreachable for UDP; SvcReject (no endpoints) gets the same
    treatment."""
    from antrea_tpu.apis.controlplane import Direction, RuleAction
    from fixtures_reachability import _ps, acnp, rule, peer

    ps = _ps(
        [acnp("reject-to-web", ["at-web"],
              [rule(Direction.IN, peer("g-client"), action=RuleAction.REJECT)])],
        [ag("g-client", "client")],
        [atg("at-web", "web")],
    )
    for dp in _both_datapaths(ps, [_svc([])]):
        r = _probe(dp, CLIENT, EP, 80, now=1)  # TCP -> RST
        assert int(r.code[0]) == REJECT and int(r.reject_kind[0]) == 1, dp.datapath_type
        r = _probe(dp, CLIENT, EP, 53, now=2, proto=17)  # UDP -> ICMP
        assert int(r.code[0]) == REJECT and int(r.reject_kind[0]) == 2, dp.datapath_type
        # SvcReject: VIP with no endpoints, TCP -> RST kind.
        r = _probe(dp, "10.10.0.33", VIP, 80, now=3)
        assert int(r.code[0]) == REJECT and int(r.reject_kind[0]) == 1, dp.datapath_type


# ---------------------------------------------------------------------------
# Service-mode fixtures: NodePort / LoadBalancer / externalTrafficPolicy /
# unbounded endpoints, authored from proxier.go (installServices :690,
# installServiceFlows :853, syncProxyRules :986, externalPolicyLocal) and
# pipeline.go (NodePortMark / SNATMark / serviceEndpointGroup).  Run at the
# Datapath boundary on BOTH implementations.
# ---------------------------------------------------------------------------

NODE_IP = "172.18.0.3"
NODE2_IP = "172.18.0.4"
LB_VIP = "203.0.113.80"


def _mode_dps(ps, services):
    from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath

    kw = dict(flow_slots=1 << 12, aff_slots=1 << 8,
              node_ips=[NODE_IP, NODE2_IP], node_name="n0")
    return [
        TpuflowDatapath(ps, services, miss_chunk=32, **kw),
        OracleDatapath(ps, services, **kw),
    ]


def test_fixture_nodeport_cluster_policy_both_datapaths():
    """proxier.go:690 + pipeline.go NodePortMark: traffic to ANY node IP on
    the node port is load-balanced like ClusterIP traffic, and under
    externalTrafficPolicy=Cluster it carries the SNAT mark (SNATMark)."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    svc = ServiceEntry(
        cluster_ip=VIP, port=80, protocol=6, node_port=30080,
        endpoints=[Endpoint(EP, 8080, node="n1")],
    )
    for dp in _mode_dps(_ps([]), [svc]):
        for nip in (NODE_IP, NODE2_IP):
            r = _probe(dp, CLIENT, nip, 30080, now=1)
            assert int(r.code[0]) == ALLOW, dp.datapath_type
            assert int(r.dnat_ip[0]) == iputil.ip_to_u32(EP), dp.datapath_type
            assert int(r.dnat_port[0]) == 8080, dp.datapath_type
            assert int(r.snat[0]) == 1, dp.datapath_type  # ETP=Cluster
        # ClusterIP traffic to the same service never carries the mark.
        r = _probe(dp, CLIENT, VIP, 80, now=2)
        assert int(r.code[0]) == ALLOW and int(r.snat[0]) == 0, dp.datapath_type
        # A non-NodePort port on the node IP is not service traffic.
        r = _probe(dp, CLIENT, NODE_IP, 31000, now=3)
        assert int(r.svc_idx[0]) == -1, dp.datapath_type


def test_fixture_loadbalancer_vip_both_datapaths():
    """proxier.go:853: LoadBalancer ingress IPs (and externalIPs) get the
    same frontend treatment as the ClusterIP."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    svc = ServiceEntry(
        cluster_ip=VIP, port=80, protocol=6, external_ips=[LB_VIP],
        endpoints=[Endpoint(EP, 8080, node="n1")],
    )
    for dp in _mode_dps(_ps([]), [svc]):
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=1)
        assert int(r.code[0]) == ALLOW, dp.datapath_type
        assert int(r.dnat_ip[0]) == iputil.ip_to_u32(EP), dp.datapath_type
        assert int(r.snat[0]) == 1, dp.datapath_type


def test_fixture_external_traffic_policy_local_both_datapaths():
    """third_party/proxy ExternalPolicyLocal: external-frontend traffic may
    only use endpoints on THIS node; client IP is preserved (no SNAT); a
    Local service with no local endpoints gets the no-endpoint reject.
    ClusterIP traffic is unaffected by the policy."""
    from antrea_tpu.apis.service import ETP_LOCAL, Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    local_ep = Endpoint("10.10.0.7", 8080, node="n0")
    remote_ep = Endpoint("10.10.0.33", 8080, node="n1")
    svc_mixed = ServiceEntry(
        cluster_ip=VIP, port=80, protocol=6, node_port=30080,
        endpoints=[local_ep, remote_ep],
        external_traffic_policy=ETP_LOCAL,
    )
    svc_remote_only = ServiceEntry(
        cluster_ip="10.96.0.11", port=80, protocol=6, node_port=30081,
        endpoints=[remote_ep],
        external_traffic_policy=ETP_LOCAL,
    )
    for dp in _mode_dps(_ps([]), [svc_mixed, svc_remote_only]):
        # NodePort on the mixed service must pick the LOCAL endpoint only.
        for sport in (40000, 40001, 40002, 40003):
            r = _probe(dp, "10.0.99.7", NODE_IP, 30080, now=1, sport=sport)
            assert int(r.code[0]) == ALLOW, dp.datapath_type
            assert int(r.dnat_ip[0]) == iputil.ip_to_u32("10.10.0.7"), dp.datapath_type
            assert int(r.snat[0]) == 0, dp.datapath_type  # client IP preserved
        # ClusterIP traffic still balances over ALL endpoints.
        seen = set()
        for sport in range(41000, 41032):
            r = _probe(dp, "10.0.99.7", VIP, 80, now=2, sport=sport)
            assert int(r.code[0]) == ALLOW, dp.datapath_type
            seen.add(int(r.dnat_ip[0]))
        assert len(seen) == 2, (dp.datapath_type, seen)
        # Local service with no local endpoints: reject on the node port...
        r = _probe(dp, "10.0.99.7", NODE_IP, 30081, now=3)
        assert int(r.code[0]) == REJECT, dp.datapath_type
        # ...but fine via the ClusterIP (cluster view has the remote ep).
        r = _probe(dp, "10.0.99.7", "10.96.0.11", 80, now=4)
        assert int(r.code[0]) == ALLOW, dp.datapath_type


def test_fixture_unbounded_endpoints_both_datapaths():
    """serviceEndpointGroup buckets are unbounded in the reference; the
    round-2 64-endpoint cap is gone — 200 endpoints compile and the hash
    select spreads across them deterministically and identically on both
    datapaths."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    eps = [Endpoint(f"10.20.{i // 256}.{i % 256}", 9000) for i in range(200)]
    svc = ServiceEntry(cluster_ip=VIP, port=80, protocol=6, endpoints=eps)
    dps = _mode_dps(_ps([]), [svc])
    picks = []
    for dp in dps:
        seen = set()
        for sport in range(42000, 42128):
            r = _probe(dp, CLIENT, VIP, 80, now=1, sport=sport)
            assert int(r.code[0]) == ALLOW, dp.datapath_type
            seen.add((sport, int(r.dnat_ip[0])))
        picks.append(seen)
    assert picks[0] == picks[1]  # identical endpoint choice per flow
    assert len({ip for _, ip in picks[0]}) > 32  # real spread over 200 eps


def test_fixture_snat_mark_pinned_across_service_updates():
    """ct-mark persistence: an established NodePort connection keeps its
    SNAT mark even when a later service update renumbers LB programs
    (the mark was committed into the conntrack entry, like the reference
    stores it in ct_mark, not re-derived per packet)."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    svc_a = ServiceEntry(cluster_ip=VIP, port=80, protocol=6, node_port=30080,
                         endpoints=[Endpoint(EP, 8080, node="n1")])
    svc_b = ServiceEntry(cluster_ip="10.96.0.50", port=80, protocol=6,
                         endpoints=[Endpoint("10.10.0.33", 8080)])
    # sport 40001: the default 40000 happens to put this connection's fwd
    # and reply tuples in the SAME direct-mapped slot (a genuine low-bit
    # hash collision, identical on both datapaths) — the reply insert then
    # legitimately evicts the fwd entry, which is cache behavior, not the
    # property under test.
    for dp in _mode_dps(_ps([]), [svc_a]):
        r = _probe(dp, CLIENT, NODE_IP, 30080, now=1, sport=40001)
        assert int(r.snat[0]) == 1 and int(r.committed[0]) == 1, dp.datapath_type
        # Insert an unrelated service ahead of A — programs renumber.
        dp.install_bundle(services=[svc_b, svc_a])
        r = _probe(dp, CLIENT, NODE_IP, 30080, now=2, sport=40001)
        assert int(r.est[0]) == 1, dp.datapath_type
        assert int(r.snat[0]) == 1, dp.datapath_type  # mark survives
        # A fresh ClusterIP flow to B carries no mark.
        r = _probe(dp, CLIENT, "10.96.0.50", 80, now=3)
        assert int(r.snat[0]) == 0, dp.datapath_type


def test_fixture_dsr_delivery_both_datapaths():
    """pipeline.go:145 DSRServiceMarkTable + :698-708 DSR service flows:
    external-frontend traffic on a DSR service SELECTS an endpoint (dnat
    fields carry the delivery target for forwarding) but is delivered
    without L3 rewrite and without SNAT; no reply-direction conntrack leg
    is committed (the endpoint answers the client directly); fast-path
    hits recover the mark from the cached program index; the ClusterIP
    path of the same service stays regular DNAT."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    svc = ServiceEntry(
        cluster_ip=VIP, port=80, protocol=6, external_ips=[LB_VIP],
        endpoints=[Endpoint(EP, 8080, node="n1")],
        dsr=True,
    )
    for dp in _mode_dps(_ps([]), [svc]):
        t = dp.datapath_type
        # Miss path: endpoint selected, DSR mark on, no SNAT, committed.
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=1)
        assert int(r.code[0]) == ALLOW, t
        assert int(r.dsr[0]) == 1, t
        assert int(r.snat[0]) == 0, t
        assert int(r.dnat_ip[0]) == iputil.ip_to_u32(EP), t
        assert int(r.committed[0]) == 1, t
        # Fast path: established hit keeps the mark (recovered via svc_idx).
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=2)
        assert int(r.est[0]) == 1 and int(r.dsr[0]) == 1, t
        assert int(r.snat[0]) == 0, t
        # No reply-direction leg was committed: the conntrack dump holds no
        # reply entry, and the endpoint->client tuple is NOT a reply hit
        # (it classifies fresh — as an ordinary flow it then commits its
        # OWN pair, which is why the dump check comes first).
        assert not any(e["reply"] for e in dp.dump_flows(now=2)), t
        r = _probe(dp, EP, "10.0.99.7", dport=40000, sport=8080, now=3)
        assert int(r.reply[0]) == 0, t
        # ClusterIP traffic to the same service: regular DNAT, no DSR mark.
        r = _probe(dp, CLIENT, VIP, 80, now=4)
        assert int(r.code[0]) == ALLOW and int(r.dsr[0]) == 0, t
        assert int(r.dnat_ip[0]) == iputil.ip_to_u32(EP), t


def test_fixture_dsr_etp_local_both_datapaths():
    """DSR composed with externalTrafficPolicy=Local: the local shadow view
    carries the DSR mark and restricts endpoints to this node."""
    from antrea_tpu.apis.service import ETP_LOCAL, Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    svc = ServiceEntry(
        cluster_ip=VIP, port=80, protocol=6, node_port=30080,
        endpoints=[Endpoint("10.10.0.7", 8080, node="n0"),
                   Endpoint("10.10.0.33", 8080, node="n1")],
        external_traffic_policy=ETP_LOCAL, dsr=True,
    )
    for dp in _mode_dps(_ps([]), [svc]):
        t = dp.datapath_type
        for sport in (40000, 40001, 40002):
            r = _probe(dp, "10.0.99.7", NODE_IP, 30080, now=1, sport=sport)
            assert int(r.code[0]) == ALLOW, t
            assert int(r.dsr[0]) == 1 and int(r.snat[0]) == 0, t
            assert int(r.dnat_ip[0]) == iputil.ip_to_u32("10.10.0.7"), t


def test_fixture_dsr_mark_pinned_across_service_updates():
    """ct-mark persistence for the DSR delivery mark (meta3 bit 30): a
    service update that renumbers LB programs — or flips the service's own
    DSR mode — cannot change an ESTABLISHED connection's delivery mode,
    exactly like the SNAT mark."""
    from antrea_tpu.apis.service import Endpoint, ServiceEntry
    from fixtures_reachability import _ps

    dsr_svc = ServiceEntry(cluster_ip=VIP, port=80, protocol=6,
                           external_ips=[LB_VIP],
                           endpoints=[Endpoint(EP, 8080, node="n1")],
                           dsr=True)
    other = ServiceEntry(cluster_ip="10.96.0.50", port=80, protocol=6,
                         endpoints=[Endpoint("10.10.0.33", 8080)])
    for dp in _mode_dps(_ps([]), [dsr_svc]):
        t = dp.datapath_type
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=1, sport=40001)
        assert int(r.dsr[0]) == 1 and int(r.committed[0]) == 1, t
        # Renumber programs AND turn the service's DSR mode off.
        from dataclasses import replace
        dp.install_bundle(services=[other, replace(dsr_svc, dsr=False)])
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=2, sport=40001)
        assert int(r.est[0]) == 1, t
        assert int(r.dsr[0]) == 1, t  # pinned at commit
        # A FRESH connection to the now-regular service has no mark.
        r = _probe(dp, "10.0.99.7", LB_VIP, 80, now=3, sport=40002)
        assert int(r.dsr[0]) == 0, t


def test_fixture_per_state_conntrack_timeouts_both_datapaths():
    """Per-state conntrack lifetimes (kernel nf_conntrack_tcp_timeout_*
    distinctions, polled by the reference's flow exporter via
    conntrack_linux.go): a half-open TCP connection (no reply seen) times
    out on the SYN lifetime; once reply traffic confirms it, both tuple
    directions live on the ESTABLISHED lifetime; non-TCP uses its own
    (shorter) lifetimes."""
    from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
    from fixtures_reachability import _ps

    kw = dict(flow_slots=1 << 12, aff_slots=1 << 8,
              ct_timeout_s=3600, ct_syn_timeout_s=100,
              ct_other_new_s=50, ct_other_est_s=200)
    for dp in (TpuflowDatapath(_ps([]), [], miss_chunk=32, **kw),
               OracleDatapath(_ps([]), [], **kw)):
        t = dp.datapath_type
        # Half-open: committed at now=0, never answered.  Within the syn
        # lifetime it est-bypasses; past it, the entry is dead (re-miss).
        r = _probe(dp, CLIENT, EP, 80, now=0, sport=41000)
        assert int(r.committed[0]) == 1, t
        r = _probe(dp, CLIENT, EP, 80, now=90, sport=41000)
        assert int(r.est[0]) == 1, t
        # (the now=90 hit refreshed ts; idle out past syn lifetime again)
        assert not any(
            e["sport"] == 41000 and not e["reply"]
            for e in dp.dump_flows(now=250)
        ), t  # expired half-open is dead to the conntrack dump too
        r = _probe(dp, CLIENT, EP, 80, now=300, sport=41000)
        assert int(r.est[0]) == 0, t  # expired half-open -> reclassified

        # Confirmed: commit at now=0, reply at now=1 confirms BOTH legs;
        # the forward leg then survives far past the syn lifetime.
        r = _probe(dp, CLIENT, EP, 80, now=0, sport=41001)
        assert int(r.committed[0]) == 1, t
        r = _probe(dp, EP, CLIENT, dport=41001, sport=80, now=1)
        assert int(r.reply[0]) == 1, t
        r = _probe(dp, CLIENT, EP, 80, now=1000, sport=41001)
        assert int(r.est[0]) == 1, t  # established lifetime applies

        # Non-TCP (UDP): unreplied dies at other_new; replied lives to
        # other_est.
        r = _probe(dp, CLIENT, EP, 53, now=0, proto=17, sport=41002)
        assert int(r.committed[0]) == 1, t
        r = _probe(dp, CLIENT, EP, 53, now=60, proto=17, sport=41002)
        assert int(r.est[0]) == 0, t  # past other_new: reclassified
        r = _probe(dp, CLIENT, EP, 53, now=61, proto=17, sport=41003)
        assert int(r.committed[0]) == 1, t
        r = _probe(dp, EP, CLIENT, dport=41003, sport=53, now=62, proto=17)
        assert int(r.reply[0]) == 1, t
        r = _probe(dp, CLIENT, EP, 53, now=211, proto=17, sport=41003)
        assert int(r.est[0]) == 1, t  # confirmed UDP: other_est lifetime
