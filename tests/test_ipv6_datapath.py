"""Dual-stack DATAPATH-BOUNDARY fixtures: v6 service + forwarding plane.

Hand-authored reachability/delivery verdicts from the reference's
dual-stack behavior (proxier.go:1379-1465 metaProxier; route_linux.go v6
routes/neighbors), driven through BOTH Datapath implementations
(TpuflowDatapath(dual_stack=True) and OracleDatapath(dual_stack=True)) —
the full walk: SpoofGuard -> ServiceLB/DNAT -> policy -> L3 forward ->
Output, with v6 pod-to-pod across nodes, v6 ClusterIP/NodePort/DSR, ND
responder lanes, and conntrack dump over wide keys.
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.compiler.topology import (
    ARP_OP_REQUEST,
    FWD_ARP_FLOOD,
    FWD_ARP_REPLY,
    FWD_DROP_SPOOF,
    FWD_DROP_UNKNOWN,
    FWD_GATEWAY,
    FWD_LOCAL,
    FWD_TUNNEL,
    NodeRoute,
    Topology,
)
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

# This node (n0): dual-stack pods + podCIDRs; n1 is the remote node
# reachable over a v4 underlay tunnel.
GW4, GW6 = "10.10.0.1", "fd00:10::1"
POD_A4, POD_A6 = "10.10.0.5", "fd00:10::5"     # local pod, ofport 3
POD_B6 = "fd00:10::6"                           # local v6-only pod, ofport 4
REMOTE_POD6 = "fd00:10:0:1::9"                  # on n1's v6 podCIDR
NODE1_V4 = "192.168.1.2"
VIP6 = "fd00:96::10"
EXT6 = "fd00:ee::5"


def _topo():
    return Topology(
        node_name="n0",
        gateway_ip=GW4, gateway_ip6=GW6,
        pod_cidr="10.10.0.0/24", pod_cidr6="fd00:10:0:0::/64",
        local_pods=[(POD_A4, 3), (POD_A6, 3), (POD_B6, 4)],
        remote_nodes=[
            NodeRoute("n1", NODE1_V4, "10.10.1.0/24"),
            NodeRoute("n1", NODE1_V4, "fd00:10:0:1::/64"),
        ],
    )


def _mk(cls, services=(), ps=None):
    return cls(
        ps if ps is not None else PolicySet(), list(services),
        flow_slots=1 << 10, aff_slots=1 << 6, topology=_topo(),
        node_ips=[NODE1_V4, GW6], dual_stack=True,
        **({"miss_chunk": 16} if cls is TpuflowDatapath else {}),
    )


def _pkt(src, dst, dport=80, proto=6, sport=40000):
    return Packet(src_ip=iputil.ip_to_key(src), dst_ip=iputil.ip_to_key(dst),
                  proto=proto, src_port=sport, dst_port=dport)


def _batch(pkts, in_ports=None, arp=None):
    b = PacketBatch.from_packets(pkts)
    if in_ports is not None:
        b.in_port = np.asarray(in_ports, np.int32)
    if arp is not None:
        b.arp_op = np.asarray(arp, np.int32)
    return b


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_v6_forwarding_walk(cls):
    """v6 pod-to-pod: local delivery, cross-node tunnel (v6-over-v4
    underlay), gateway default, unknown-in-local-CIDR drop, spoof drop."""
    dp = _mk(cls)
    cases = [
        # (src, dst, in_port, kind, out_port)
        (POD_A6, POD_B6, 3, FWD_LOCAL, 4),            # local v6 pod
        (POD_A6, REMOTE_POD6, 3, FWD_TUNNEL, 1),      # v6 across nodes
        (POD_A6, "fd00:99::1", 3, FWD_GATEWAY, 2),    # external v6
        (REMOTE_POD6, POD_A6, 1, FWD_LOCAL, 3),       # tunnel ingress
        (POD_A6, "fd00:10::77", 3, FWD_DROP_UNKNOWN, -1),  # local CIDR, no pod
        ("fd00:bad::1", POD_B6, 3, FWD_DROP_SPOOF, -1),    # v6 spoof
    ]
    r = dp.step(_batch([_pkt(s, d) for s, d, *_ in cases],
                       in_ports=[c[2] for c in cases]), now=1)
    for i, (s, d, _ip, kind, port) in enumerate(cases):
        assert int(r.fwd_kind[i]) == kind, (cls.__name__, i, s, d,
                                            int(r.fwd_kind[i]), "want", kind)
        assert int(r.out_port[i]) == port, (cls.__name__, i, s, d)
    # The v6 tunnel leg rides the v4 underlay peer.
    assert r.peer_key[1] == iputil.ip_to_key(NODE1_V4)
    assert int(r.dec_ttl[1]) == 1
    # Spoofed lane committed nothing.
    assert int(r.spoofed[5]) == 1 and int(r.committed[5]) == 0


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_v6_clusterip_walk_and_conntrack(cls):
    """v6 ClusterIP through the FULL walk: DNAT to a local v6 endpoint,
    delivery to its ofport, reply un-DNAT, FIN teardown; dump_flows shows
    the wide entries."""
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(POD_B6, 8080, node="n0")])
    dp = _mk(cls, [svc])
    r = dp.step(_batch([_pkt(POD_A6, VIP6, 80, sport=41000)],
                       in_ports=[3]), now=1)
    assert int(r.code[0]) == 0 and int(r.svc_idx[0]) == 0
    assert r.dnat_key[0] == iputil.ip_to_key(POD_B6)
    assert int(r.dnat_port[0]) == 8080
    # Forwarding follows the DNAT resolution to the endpoint's port.
    assert int(r.fwd_kind[0]) == FWD_LOCAL and int(r.out_port[0]) == 4
    assert int(r.committed[0]) == 1

    # Established fast path.
    r = dp.step(_batch([_pkt(POD_A6, VIP6, 80, sport=41000)],
                       in_ports=[3]), now=2)
    assert int(r.est[0]) == 1
    assert r.dnat_key[0] == iputil.ip_to_key(POD_B6)

    # Reply: endpoint -> client, un-DNAT to the frontend, delivered to the
    # client's pod port.
    rev = Packet(src_ip=iputil.ip_to_key(POD_B6),
                 dst_ip=iputil.ip_to_key(POD_A6),
                 proto=6, src_port=8080, dst_port=41000)
    r = dp.step(_batch([rev], in_ports=[4]), now=3)
    assert int(r.reply[0]) == 1 and int(r.est[0]) == 1
    assert r.dnat_key[0] == iputil.ip_to_key(VIP6)
    assert int(r.fwd_kind[0]) == FWD_LOCAL and int(r.out_port[0]) == 3

    # Conntrack dump decodes the wide keys to real v6 addresses.
    flows = dp.dump_flows(now=3)
    srcs = {f["src"] for f in flows}
    assert POD_A6 in srcs and POD_B6 in srcs
    fwd_e = [f for f in flows if not f["reply"]][0]
    assert fwd_e["dst"] == VIP6 and fwd_e["dnat_ip"] == POD_B6


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_v6_nd_responder(cls):
    """Neighbor Discovery lanes (the v6 twin of the in-kernel ARP lanes):
    NS for addresses this node owns (gateway6 / local v6 pods / remote v6
    node IPs) answers out the ingress port; others flood."""
    dp = _mk(cls)
    pkts = [
        _pkt(POD_A6, GW6, 0, proto=0),          # NS for the v6 gateway
        _pkt(POD_A6, POD_B6, 0, proto=0),       # NS for a local v6 pod
        _pkt(POD_A6, "fd00:77::1", 0, proto=0),  # not ours -> flood
    ]
    r = dp.step(_batch(pkts, in_ports=[3, 3, 3],
                       arp=[ARP_OP_REQUEST] * 3), now=1)
    assert int(r.fwd_kind[0]) == FWD_ARP_REPLY
    assert int(r.out_port[0]) == 3
    assert int(r.fwd_kind[1]) == FWD_ARP_REPLY
    assert int(r.fwd_kind[2]) == FWD_ARP_FLOOD


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_v6_policy_on_walk(cls):
    """Dual-stack policy + service + forwarding in ONE walk: an ACNP drop
    on the v6 endpoint fires for ClusterIP traffic after DNAT, while the
    allowed client's traffic is delivered."""
    ps = PolicySet()
    ps.applied_to_groups["web"] = cp.AppliedToGroup(
        name="web", members=[cp.GroupMember(ip=POD_B6, node="n0")])
    ps.address_groups["bad"] = cp.AddressGroup(
        name="bad", members=[cp.GroupMember(ip=POD_A6, node="n0")])
    ps.policies.append(cp.NetworkPolicy(
        uid="p", name="p", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["web"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(address_groups=["bad"]),
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    svc = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                       endpoints=[Endpoint(POD_B6, 8080, node="n0")])
    dp = _mk(cls, [svc], ps=ps)
    pkts = [
        _pkt(POD_A6, VIP6, 80, sport=42000),        # DNAT->POD_B6: dropped
        _pkt(REMOTE_POD6, VIP6, 80, sport=42001),   # other client: allowed
    ]
    r = dp.step(_batch(pkts, in_ports=[3, 1]), now=1)
    assert int(r.code[0]) == 1 and int(r.out_port[0]) == -1
    assert r.ingress_rule[0] is not None
    assert int(r.code[1]) == 0
    assert int(r.fwd_kind[1]) == FWD_LOCAL and int(r.out_port[1]) == 4


def test_v6_differential_randomized():
    """Randomized dual-stack differential at the datapath boundary: both
    engines agree on every verdict/forwarding field over mixed-family
    service + policy + cross-node traffic."""
    rng = np.random.default_rng(7)
    svc6 = ServiceEntry(cluster_ip=VIP6, port=80, protocol=6,
                        endpoints=[Endpoint(POD_B6, 8080, node="n0"),
                                   Endpoint(REMOTE_POD6, 8080, node="n1")])
    svc4 = ServiceEntry(cluster_ip="10.96.0.10", port=80, protocol=6,
                        endpoints=[Endpoint(POD_A4, 8080, node="n0")])
    ps = PolicySet()
    ps.applied_to_groups["web"] = cp.AppliedToGroup(
        name="web", members=[cp.GroupMember(ip=POD_B6, node="n0"),
                             cp.GroupMember(ip=POD_A4, node="n0")])
    ps.policies.append(cp.NetworkPolicy(
        uid="p", name="p", type=cp.NetworkPolicyType.ACNP,
        applied_to_groups=["web"], tier_priority=250, priority=1.0,
        rules=[cp.NetworkPolicyRule(
            direction=cp.Direction.IN,
            from_peer=cp.NetworkPolicyPeer(
                ip_blocks=[cp.IPBlock("fd00:10:0:1::/64"),
                           cp.IPBlock("10.10.1.0/24")]),
            action=cp.RuleAction.DROP, priority=0,
        )],
    ))
    a = _mk(TpuflowDatapath, [svc6, svc4], ps=ps)
    b = _mk(OracleDatapath, [svc6, svc4], ps=ps)

    srcs = [POD_A6, POD_A4, POD_B6, REMOTE_POD6, "10.10.1.7", "fd00:99::3"]
    dsts = [VIP6, "10.96.0.10", POD_B6, POD_A4, REMOTE_POD6, "fd00:10::77"]
    ports = {POD_A6: 3, POD_A4: 3, POD_B6: 4}
    for now in range(1, 4):
        pkts, inp = [], []
        for _ in range(32):
            s = srcs[rng.integers(len(srcs))]
            d = dsts[rng.integers(len(dsts))]
            if iputil.is_v6(s) != iputil.is_v6(d):
                continue  # mixed-family packets are undefined
            pkts.append(_pkt(s, d, sport=int(rng.integers(40000, 40500))))
            inp.append(ports.get(s, 1 if s == REMOTE_POD6 else -1))
        ra = a.step(_batch(pkts, in_ports=inp), now=now)
        rb = b.step(_batch(pkts, in_ports=inp), now=now)
        for i in range(len(pkts)):
            for f in ("code", "est", "reply", "committed", "svc_idx",
                      "snat", "dsr", "fwd_kind", "out_port", "dec_ttl",
                      "spoofed", "dnat_port"):
                assert int(getattr(ra, f)[i]) == int(getattr(rb, f)[i]), (
                    f, i, pkts[i])
            assert ra.dnat_key[i] == rb.dnat_key[i], (i, pkts[i])
            assert ra.peer_key[i] == rb.peer_key[i], (i, pkts[i])


def test_narrow_datapath_rejects_v6_batch():
    """A v4-only datapath must reject v6 lanes loudly, not mis-classify
    them through don't-care narrow columns."""
    for cls in (OracleDatapath, TpuflowDatapath):
        dp = cls(PolicySet(), [], topology=Topology())
        with pytest.raises(ValueError):
            dp.step(_batch([_pkt("fd00::1", "fd00::2")]), now=1)


@pytest.mark.parametrize("cls", [OracleDatapath, TpuflowDatapath])
def test_dual_stack_topology_survives_restart(cls, tmp_path):
    """gateway_ip6/pod_cidr6 round-trip the topology snapshot: after a
    restart the ND responder still answers for the v6 gateway and an
    unknown v6 dst inside the local podCIDR still drops (not gateway)."""
    kw = {"miss_chunk": 16} if cls is TpuflowDatapath else {}
    dp = cls(PolicySet(), [], flow_slots=1 << 10, aff_slots=1 << 6,
             dual_stack=True, persist_dir=str(tmp_path), **kw)
    dp.install_topology(_topo())
    del dp
    dp2 = cls(flow_slots=1 << 10, aff_slots=1 << 6, dual_stack=True,
              persist_dir=str(tmp_path), **kw)
    r = dp2.step(_batch([_pkt(POD_A6, GW6, 0, proto=0)], in_ports=[3],
                        arp=[ARP_OP_REQUEST]), now=1)
    assert int(r.fwd_kind[0]) == FWD_ARP_REPLY
    r = dp2.step(_batch([_pkt(POD_A6, "fd00:10::77")], in_ports=[3]), now=2)
    assert int(r.fwd_kind[0]) == FWD_DROP_UNKNOWN
