"""End-to-end control-plane dissemination: central controller -> RAM watch
store (span-filtered) -> per-node agents -> datapaths.

The multi-node analog of the reference's controller->apiserver->agent watch
path (architecture.md:50-64; ram/store.go watch fan-out;
agent networkpolicy_controller.go:910).  Each agent builds its PolicySet
from WATCH EVENTS ONLY; correctness = its datapath verdicts match an oracle
compiled directly from the controller's span-filtered snapshot.
"""

import pytest

pytestmark = pytest.mark.slow

import numpy as np

from antrea_tpu.agent import AgentPolicyController
from antrea_tpu.apis.controlplane import Direction, RuleAction
from antrea_tpu.apis.crd import (
    AntreaAppliedTo,
    AntreaNetworkPolicy,
    AntreaNPRule,
    AntreaPeer,
    K8sNetworkPolicy,
    K8sNPRule,
    K8sPeer,
    LabelSelector,
    Namespace,
    Pod,
    PortSpec,
)
from antrea_tpu.controller import NetworkPolicyController
from antrea_tpu.datapath import TpuflowDatapath
from antrea_tpu.dissemination import RamStore
from antrea_tpu.oracle import Oracle
from antrea_tpu.packet import Packet, PacketBatch
from antrea_tpu.utils import ip as iputil

NODES = ["nodeA", "nodeB", "nodeC"]


def mk_pod(name, ip, node, ns="default", **labels):
    return Pod(namespace=ns, name=name, ip=ip, node=node, labels=labels)


def _wire():
    """controller -> store -> one agent+datapath per node."""
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agents = {}
    for node in NODES:
        dp = TpuflowDatapath(
            flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=32,
            delta_slots=32,
        )
        agents[node] = AgentPolicyController(node, dp, store)
    return ctl, store, agents


def _pods(ctl):
    ctl.upsert_namespace(Namespace("default", {}))
    ctl.upsert_pod(mk_pod("web1", "10.0.0.10", "nodeA", app="web"))
    ctl.upsert_pod(mk_pod("web2", "10.0.0.11", "nodeB", app="web"))
    ctl.upsert_pod(mk_pod("cli1", "10.0.0.20", "nodeB", app="client"))
    ctl.upsert_pod(mk_pod("db1", "10.0.0.30", "nodeC", app="db"))


def _probe_batch():
    ips = ["10.0.0.10", "10.0.0.11", "10.0.0.20", "10.0.0.30", "10.0.5.5"]
    pkts = [
        Packet(src_ip=iputil.ip_to_u32(s), dst_ip=iputil.ip_to_u32(d),
               proto=6, src_port=41000, dst_port=p)
        for s in ips for d in ips if s != d for p in (80, 443)
    ]
    return pkts, PacketBatch.from_packets(pkts)


def _assert_agent_matches_snapshot(ctl, agents, now):
    """Every node's datapath (fed only by watch events) must agree with an
    oracle over the controller's direct span-filtered snapshot."""
    pkts, batch = _probe_batch()
    for node, agent in agents.items():
        agent.sync()
        res = agent.datapath.trace(batch, now=now)  # read-only: no ct noise
        oracle = Oracle(ctl.policy_set_for_node(node))
        for i, p in enumerate(pkts):
            want = int(oracle.classify(p).code)
            assert res[i]["fresh_code"] == want, (node, i, pkts[i])


def test_watch_bootstrap_and_policy_add():
    ctl, store, agents = _wire()
    _pods(ctl)
    ctl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", namespace="default", name="np-web",
        pod_selector=LabelSelector.make({"app": "web"}),
        policy_types=[Direction.IN],
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "client"}))],
            ports=[PortSpec(protocol=6, port=80)],
        )],
    ))
    for node, agent in agents.items():
        agent.sync()
    # Span filtering: nodeC hosts no web pod -> no policies disseminated.
    assert len(agents["nodeA"].policy_set.policies) == 1
    assert len(agents["nodeB"].policy_set.policies) == 1
    assert len(agents["nodeC"].policy_set.policies) == 0
    _assert_agent_matches_snapshot(ctl, agents, now=10)


def test_late_subscriber_replay():
    """An agent that starts AFTER the policies exist gets the replay."""
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    _pods(ctl)
    ctl.upsert_antrea_policy(AntreaNetworkPolicy(
        uid="acnp", name="acnp", tier_priority=250, priority=1.0,
        applied_to=[AntreaAppliedTo(pod_selector=LabelSelector.make({"app": "web"}))],
        rules=[AntreaNPRule(
            direction=Direction.IN, action=RuleAction.DROP,
            peers=[AntreaPeer(pod_selector=LabelSelector.make({"app": "db"}))],
        )],
    ))
    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=32)
    agent = AgentPolicyController("nodeA", dp, store)
    agent.sync()
    assert len(agent.policy_set.policies) == 1
    pkts, batch = _probe_batch()
    res = dp.trace(batch, now=5)
    oracle = Oracle(ctl.policy_set_for_node("nodeA"))
    for i, p in enumerate(pkts):
        assert res[i]["fresh_code"] == int(oracle.classify(p).code), i


def test_pod_churn_flows_as_incremental_deltas():
    ctl, store, agents = _wire()
    _pods(ctl)
    ctl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", namespace="default", name="np-web",
        pod_selector=LabelSelector.make({"app": "web"}),
        policy_types=[Direction.IN],
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "client"}))],
        )],
    ))
    for agent in agents.values():
        agent.sync()
    dp_a = agents["nodeA"].datapath
    bitmap_before = dp_a._drs.ingress.at.inc

    # New client pod on nodeC: for nodeA this is a pure AddressGroup member
    # delta -> incremental path, no recompile.
    ctl.upsert_pod(mk_pod("cli2", "10.0.0.21", "nodeC", app="client"))
    agents["nodeA"].sync()
    assert dp_a._drs.ingress.at.inc is bitmap_before
    assert dp_a._n_deltas > 0
    _assert_agent_matches_snapshot(ctl, agents, now=20)

    # Remove it again: membership reverts, still incremental.
    ctl.delete_pod("default/cli2")
    agents["nodeA"].sync()
    assert dp_a._drs.ingress.at.inc is bitmap_before
    _assert_agent_matches_snapshot(ctl, agents, now=30)


def test_span_growth_delivers_policy_and_groups():
    ctl, store, agents = _wire()
    _pods(ctl)
    ctl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np-web", namespace="default", name="np-web",
        pod_selector=LabelSelector.make({"app": "web"}),
        policy_types=[Direction.IN],
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "client"}))],
        )],
    ))
    for agent in agents.values():
        agent.sync()
    assert len(agents["nodeC"].policy_set.policies) == 0

    # A web pod lands on nodeC: span grows, nodeC must receive the policy
    # AND its groups purely through the watch.
    ctl.upsert_pod(mk_pod("web3", "10.0.0.12", "nodeC", app="web"))
    agents["nodeC"].sync()
    ps = agents["nodeC"].policy_set
    assert len(ps.policies) == 1
    assert len(ps.applied_to_groups) == 1
    assert len(ps.address_groups) == 1
    _assert_agent_matches_snapshot(ctl, agents, now=40)

    # And when the pod leaves, the span shrinks and nodeC retracts it all.
    ctl.delete_pod("default/web3")
    agents["nodeC"].sync()
    assert len(agents["nodeC"].policy_set.policies) == 0
    _assert_agent_matches_snapshot(ctl, agents, now=50)
