"""Incremental-vs-fresh controller parity (the dissemination plane's
ground-truth property).

Every storm assertion in this repo compares agents against
`policy_set_for_node` of the controller THAT LIVED THROUGH the churn —
which is only an oracle if incremental maintenance (span deltas, group
ref-counting, tier re-conversion, selector re-evaluation) converges to
the same state a from-scratch controller computes from the final inputs.
This property test drives seeded-random interleaved churn (namespace
relabels, pod add/delete/relabel/move, K8s + Antrea policy
upsert/delete, tier priority churn and retirement) through one
controller, rebuilds a second controller from nothing but the surviving
objects, and requires byte-identical canonical `policy_set_for_node`
output for every node.  A divergence here means the storm soaks are
converging to the wrong truth."""

import dataclasses
import json
import random

import pytest

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis import crd
from antrea_tpu.controller.networkpolicy import NetworkPolicyController

NODES = [f"n{i}" for i in range(4)]
NAMESPACES = ["ns-a", "ns-b", "ns-c"]
APPS = ["web", "db", "cache"]
ENVS = ["prod", "dev"]
# Custom-tier priority pool: disjoint from the reserved defaults
# (50/100/150/200/250/253 + the ANP tier) and from each other.
TIER_PRIORITIES = [41, 60, 73, 97, 130, 171, 205, 230]


def _canon(obj) -> str:
    """Canonical JSON for one controlplane object: dataclass tree dumped
    with sorted keys, enums via str, generation zeroed (the incremental
    controller bumps it per spec change; a fresh build starts at 0 —
    parity is about the SPEC, not the edit count)."""
    d = dataclasses.asdict(obj)
    d.pop("generation", None)
    return json.dumps(d, sort_keys=True, default=str)


def _canon_node(ctl, node: str) -> dict:
    ps = ctl.policy_set_for_node(node)
    return {
        "policies": sorted(_canon(p) for p in ps.policies),
        "address_groups": {
            name: sorted(_canon(m) for m in g.members)
            + sorted(_canon(b) for b in g.ip_blocks)
            for name, g in ps.address_groups.items()
        },
        "applied_to_groups": {
            name: sorted(_canon(m) for m in g.members)
            for name, g in ps.applied_to_groups.items()
        },
    }


class _ChurnDriver:
    """Seeded-random churn against a live controller, mirroring the
    SURVIVING inputs (not the op log) so the fresh rebuild sees exactly
    the final world."""

    def __init__(self, ctl, rng):
        self.ctl = ctl
        self.rng = rng
        self.namespaces: dict[str, crd.Namespace] = {}
        self.pods: dict[str, crd.Pod] = {}
        self.tiers: dict[str, crd.Tier] = {}
        self.anps: dict[str, crd.AntreaNetworkPolicy] = {}
        self.k8snps: dict[str, crd.K8sNetworkPolicy] = {}
        self._pod_seq = 0
        for name in NAMESPACES:
            self.op_ns_relabel(name=name)

    # -- object builders -----------------------------------------------------

    def _rand_anp(self, uid: str) -> crd.AntreaNetworkPolicy:
        r = self.rng
        namespace = r.choice(["", r.choice(NAMESPACES)])
        peers = []
        if r.random() < 0.7:
            peers.append(crd.AntreaPeer(ip_block=crd.IPBlock(
                f"192.0.{r.randrange(8)}.0/24")))
        if r.random() < 0.5:
            peers.append(crd.AntreaPeer(
                ns_selector=crd.LabelSelector.make(
                    {"env": r.choice(ENVS)}),
                pod_selector=crd.LabelSelector.make(
                    {"app": r.choice(APPS)})))
        tier = ""
        if self.tiers and r.random() < 0.4:
            tier = r.choice(sorted(self.tiers))
        return crd.AntreaNetworkPolicy(
            uid=uid, name=uid, namespace=namespace, tier=tier,
            priority=r.choice([1.0, 3.5, 5.0, 7.25]),
            applied_to=[crd.AntreaAppliedTo(
                pod_selector=crd.LabelSelector.make(
                    {"app": r.choice(APPS)}),
                ns_selector=crd.LabelSelector.make(
                    {} if namespace else {"env": r.choice(ENVS)}))],
            rules=[crd.AntreaNPRule(
                direction=r.choice([cp.Direction.IN, cp.Direction.OUT]),
                action=r.choice([cp.RuleAction.ALLOW, cp.RuleAction.DROP]),
                peers=peers)],
        )

    def _rand_k8snp(self, uid: str) -> crd.K8sNetworkPolicy:
        r = self.rng
        peers = []
        if r.random() < 0.6:
            peers.append(crd.K8sPeer(ip_block=crd.IPBlock(
                f"203.0.{r.randrange(8)}.0/24")))
        if r.random() < 0.5:
            peers.append(crd.K8sPeer(
                ns_selector=crd.LabelSelector.make({"env": r.choice(ENVS)})))
        return crd.K8sNetworkPolicy(
            uid=uid, namespace=r.choice(NAMESPACES), name=uid,
            pod_selector=crd.LabelSelector.make({"app": r.choice(APPS)}),
            policy_types=[cp.Direction.IN],
            ingress=[crd.K8sNPRule(peers=peers)],
        )

    # -- churn ops (each keeps self.* mirrors in sync) -----------------------

    def op_ns_relabel(self, name=None):
        ns = crd.Namespace(
            name=name or self.rng.choice(NAMESPACES),
            labels={"env": self.rng.choice(ENVS)})
        self.namespaces[ns.name] = ns
        self.ctl.upsert_namespace(ns)

    def op_pod_add(self):
        i = self._pod_seq
        self._pod_seq += 1
        pod = crd.Pod(
            namespace=self.rng.choice(NAMESPACES), name=f"pod-{i}",
            ip=f"10.{(i >> 8) & 255}.{i & 255}.9",
            node=self.rng.choice(NODES),
            labels={"app": self.rng.choice(APPS)})
        self.pods[pod.key] = pod
        self.ctl.upsert_pod(pod)

    def op_pod_delete(self):
        if not self.pods:
            return
        key = self.rng.choice(sorted(self.pods))
        del self.pods[key]
        self.ctl.delete_pod(key)

    def op_pod_mutate(self):
        """Relabel and/or move a live pod — the span-shift op."""
        if not self.pods:
            return
        old = self.pods[self.rng.choice(sorted(self.pods))]
        pod = crd.Pod(
            namespace=old.namespace, name=old.name, ip=old.ip,
            node=self.rng.choice(NODES),
            labels={"app": self.rng.choice(APPS)})
        self.pods[pod.key] = pod
        self.ctl.upsert_pod(pod)

    def op_anp_upsert(self):
        uid = f"anp-{self.rng.randrange(12)}"
        anp = self._rand_anp(uid)
        self.anps[uid] = anp
        self.ctl.upsert_antrea_policy(anp)

    def op_anp_delete(self):
        if not self.anps:
            return
        uid = self.rng.choice(sorted(self.anps))
        del self.anps[uid]
        self.ctl.delete_policy(uid)

    def op_k8snp_upsert(self):
        uid = f"knp-{self.rng.randrange(8)}"
        np = self._rand_k8snp(uid)
        self.k8snps[uid] = np
        self.ctl.upsert_k8s_policy(np)

    def op_k8snp_delete(self):
        if not self.k8snps:
            return
        uid = self.rng.choice(sorted(self.k8snps))
        del self.k8snps[uid]
        self.ctl.delete_policy(uid)

    def op_tier_upsert(self):
        """Create a tier or churn an existing one's priority — priority
        changes re-convert every referencing policy."""
        name = f"tier-{self.rng.randrange(4)}"
        taken = {t.priority for n, t in self.tiers.items() if n != name}
        free = [p for p in TIER_PRIORITIES if p not in taken]
        tier = crd.Tier(name, self.rng.choice(free))
        self.tiers[name] = tier
        self.ctl.upsert_tier(tier)

    def op_tier_delete(self):
        """Tiers are only deletable while unreferenced (the controller
        refuses otherwise — mirroring the reference's webhook)."""
        unref = [n for n in self.tiers
                 if all(a.tier != n for a in self.anps.values())]
        if not unref:
            return
        name = self.rng.choice(sorted(unref))
        del self.tiers[name]
        self.ctl.delete_tier(name)

    def step(self):
        ops = [
            (self.op_ns_relabel, 2), (self.op_pod_add, 4),
            (self.op_pod_delete, 2), (self.op_pod_mutate, 3),
            (self.op_anp_upsert, 5), (self.op_anp_delete, 2),
            (self.op_k8snp_upsert, 3), (self.op_k8snp_delete, 1),
            (self.op_tier_upsert, 2), (self.op_tier_delete, 1),
        ]
        picks = [op for op, w in ops for _ in range(w)]
        self.rng.choice(picks)()

    def rebuild_fresh(self) -> NetworkPolicyController:
        """A controller that never saw the churn: final objects only,
        dependency order (tiers before the policies naming them,
        namespaces/pods before the selectors that match them)."""
        fresh = NetworkPolicyController()
        for tier in self.tiers.values():
            fresh.upsert_tier(tier)
        for ns in self.namespaces.values():
            fresh.upsert_namespace(ns)
        for pod in self.pods.values():
            fresh.upsert_pod(pod)
        for anp in self.anps.values():
            fresh.upsert_antrea_policy(anp)
        for np in self.k8snps.values():
            fresh.upsert_k8s_policy(np)
        return fresh


@pytest.mark.parametrize("seed", [7, 23, 1009])
def test_incremental_matches_fresh_rebuild(seed):
    rng = random.Random(seed)
    ctl = NetworkPolicyController()
    driver = _ChurnDriver(ctl, rng)
    for step in range(160):
        driver.step()
        # Mid-churn spot checks catch divergence near its cause instead
        # of 100 ops later (cheap: 2 of 160 steps).
        if step in (40, 100):
            fresh = driver.rebuild_fresh()
            for node in NODES:
                assert _canon_node(ctl, node) == _canon_node(fresh, node), (
                    f"divergence at step {step}, node {node} (seed {seed})")
    fresh = driver.rebuild_fresh()
    for node in NODES:
        incr, scratch = _canon_node(ctl, node), _canon_node(fresh, node)
        assert incr == scratch, (
            f"incremental controller diverged from fresh rebuild on "
            f"{node} (seed {seed}): the churn oracle is not a fixpoint")
