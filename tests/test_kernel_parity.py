"""Verdict parity: the batched JAX kernel must agree with the scalar oracle
bit-for-bit — the TPU-build analog of the reference's OVS differential tests
(test/integration/agent/openflow_test.go model, SURVEY.md section 4 tier 2)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.ops.match import flip_ips, make_classifier
from antrea_tpu.oracle import Oracle
from antrea_tpu.simulator import gen_cluster, gen_traffic


def run_parity(n_rules: int, seed: int, batch: int = 192, chunk: int = 64):
    cluster = gen_cluster(n_rules, seed=seed)
    traffic = gen_traffic(cluster.pod_ips, batch=batch, seed=seed + 1)
    cps = compile_policy_set(cluster.ps)
    fn, _ = make_classifier(cps)

    out = fn(
        flip_ips(traffic.src_ip),
        flip_ips(traffic.dst_ip),
        traffic.proto.astype(np.int32),
        traffic.dst_port.astype(np.int32),
    )
    out = {k: np.asarray(v) for k, v in out.items()}

    oracle = Oracle(cluster.ps)
    mismatches = []
    for i in range(traffic.size):
        v = oracle.classify(traffic.packet(i))
        if int(out["code"][i]) != int(v.code):
            mismatches.append((i, traffic.packet(i), v, int(out["code"][i])))
            continue
        # Rule attribution parity (map kernel idx -> rule_id).
        for dirn, key_code, key_rule, dv in (
            ("ingress", "ingress_code", "ingress_rule", v.ingress),
            ("egress", "egress_code", "egress_rule", v.egress),
        ):
            if int(out[key_code][i]) != int(dv.code):
                mismatches.append((i, dirn, "code", dv, int(out[key_code][i])))
                continue
            ridx = int(out[key_rule][i])
            ids = cps.ingress.rule_ids if dirn == "ingress" else cps.egress.rule_ids
            got = ids[ridx] if ridx >= 0 else None
            if got != dv.rule:
                mismatches.append((i, dirn, "rule", dv.rule, got))
    assert not mismatches, mismatches[:5]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_parity_small(seed):
    run_parity(60, seed=seed)


def test_parity_medium():
    run_parity(400, seed=7, batch=256)


def test_parity_k8s_only():
    cluster = gen_cluster(100, seed=5, acnp_fraction=0.0)
    _parity_cluster(cluster)


def test_parity_acnp_only():
    cluster = gen_cluster(100, seed=6, acnp_fraction=1.0)
    _parity_cluster(cluster)


def _parity_cluster(cluster, batch=160):
    traffic = gen_traffic(cluster.pod_ips, batch=batch, seed=9)
    cps = compile_policy_set(cluster.ps)
    fn, _ = make_classifier(cps)
    out = fn(
        flip_ips(traffic.src_ip),
        flip_ips(traffic.dst_ip),
        traffic.proto.astype(np.int32),
        traffic.dst_port.astype(np.int32),
    )
    codes = np.asarray(out["code"])
    oracle = Oracle(cluster.ps)
    for i in range(traffic.size):
        assert int(codes[i]) == int(oracle.classify(traffic.packet(i)).code), i
