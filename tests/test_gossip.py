"""SWIM gossip failure detection (cluster.go:180 memberlist.Create, :227
Join): death is DETECTED by probes over real UDP sockets, never announced
— the round-4 verdict's missing e2e property for memberlist-driven
failover."""

import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.gossip import ALIVE, DEAD, SwimNode
from antrea_tpu.agent.memberlist import MemberlistCluster

FAST = dict(probe_interval_s=0.1, probe_timeout_s=0.15,
            suspect_timeout_s=0.4)


def _wait(pred, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_join_and_gossip_convergence():
    """Three in-proc nodes: one join() each against the seed; piggybacked
    membership converges everyone onto everyone (no full-mesh joins)."""
    nodes = {}
    clusters = {}
    try:
        for name in ("a", "b", "c"):
            clusters[name] = MemberlistCluster(name)
            nodes[name] = SwimNode(name, clusters[name], **FAST)
        nodes["b"].join(nodes["a"].address)
        nodes["c"].join(nodes["a"].address)
        _wait(lambda: all(clusters[n].alive == {"a", "b", "c"}
                          for n in nodes),
              what="3-node convergence")
        # Every node elects the SAME owner for any key.
        owners = {clusters[n].owner_of("egress-ip-1") for n in nodes}
        assert len(owners) == 1
    finally:
        for n in nodes.values():
            n.close()


def test_killed_process_detected_and_reelected():
    """3+ PROCESSES: two subprocess agents + one in-proc observer.  One
    subprocess is SIGKILLed (no leave call anywhere); the observer's
    probes fail -> suspect -> dead, the ring drops the node, and keys it
    owned re-elect onto survivors — Egress/ServiceExternalIP/MC-gateway
    failover by detected death (cluster.go probe/suspect semantics)."""
    cluster = MemberlistCluster("observer")
    obs = SwimNode("observer", cluster, **FAST)
    procs = []
    try:
        import json as _json

        for name in ("agent-1", "agent-2"):
            p = subprocess.Popen(
                [sys.executable, "-m", "antrea_tpu.agent.gossip", name,
                 f"{obs.address[0]}:{obs.address[1]}"],
                stdout=subprocess.PIPE, text=True, cwd="/root/repo",
            )
            procs.append(p)
            _json.loads(p.stdout.readline())  # bound-address handshake
        _wait(lambda: cluster.alive == {"observer", "agent-1", "agent-2"},
              what="subprocess agents joining")

        # Find keys owned by each subprocess agent (so the kill provably
        # moves ownership).
        keys = {}
        for i in range(200):
            owner = cluster.owner_of(f"egress-{i}")
            keys.setdefault(owner, f"egress-{i}")
            if {"agent-1", "agent-2"} <= set(keys):
                break
        assert "agent-1" in keys, "no key elected onto agent-1"
        victim_key = keys["agent-1"]

        procs[0].kill()  # SIGKILL: no leave(), no FIN — pure death
        procs[0].wait()
        _wait(lambda: "agent-1" not in cluster.alive, timeout=15,
              what="detected death of agent-1")
        assert obs.members()["agent-1"]["state"] == DEAD
        # Re-election without any explicit leave call: the dead node's
        # key lands on a survivor, identically derivable on every node.
        new_owner = cluster.owner_of(victim_key)
        assert new_owner in ("observer", "agent-2")
    finally:
        obs.close()
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_suspect_refutes_with_incarnation_bump():
    """A SLOW (but alive) node that gets suspected refutes via an
    incarnation bump: it returns to ALIVE everywhere instead of being
    declared dead (SWIM's refutation rule)."""
    ca, cb = MemberlistCluster("a"), MemberlistCluster("b")
    a = SwimNode("a", ca, **FAST)
    b = SwimNode("b", cb, **FAST)
    try:
        b.join(a.address)
        _wait(lambda: ca.alive == {"a", "b"}, what="join")
        # Inject a suspicion about b at a (as if a probe had failed):
        # b must learn of it via piggyback and refute.
        with a._lock:
            a._members["b"]["state"] = 1  # SUSPECT
        _wait(lambda: a.members()["b"]["state"] == ALIVE
              and a.members()["b"]["inc"] > 0,
              what="refutation via incarnation bump")
        assert "b" in ca.alive
    finally:
        a.close()
        b.close()
