"""Hot-path telemetry plane (observability/telemetry.py): the tentpole's
three acceptance bars.

1. Knob discipline — `telemetry=False` lowers step HLO BIT-IDENTICAL to
   the uninstrumented program across the default, fused+pruned, pruned,
   second-chance and dual-stack variants (the counters are free unless
   bought), and `telemetry=True` genuinely changes the program.
2. Counter parity — the in-kernel tel_* counters match a host-side
   recomputation by the scalar oracle twin EXACTLY across the cold,
   steady and churn regimes, single chip and mesh (the oracle's
   documented divergence: it has no probe-generation staleness, no
   second-chance clock and no DMA engine, so those meters stay 0).
3. The sentinel chaos case — a FaultClock-driven injected slowdown is
   reconstructed as a `perf-regression` flight-recorder event from the
   journal ALONE (regime, window p99, baseline p99, sample count, ratio,
   scheduler-clock timestamps), and the verdict is journal-and-meter
   only: the commit plane never degrades or rolls back on it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.dissemination.faults import FaultClock
from antrea_tpu.models import pipeline as pl
from antrea_tpu.observability.telemetry import (REGIMES, TELEMETRY_COUNTERS,
                                                classify_regime)
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_traffic

KW = dict(flow_slots=1 << 10, aff_slots=1 << 6, canary_probes=0)


def _concat(a: PacketBatch, b: PacketBatch, na: int, nb: int) -> PacketBatch:
    """First `na` lanes of batch a followed by the first `nb` of b."""
    cut = lambda f: np.concatenate([getattr(a, f)[:na], getattr(b, f)[:nb]])
    return PacketBatch(src_ip=cut("src_ip"), dst_ip=cut("dst_ip"),
                       proto=cut("proto"), src_port=cut("src_port"),
                       dst_port=cut("dst_port"))


# ---------------------------------------------------------------------------
# 1. Knob discipline: telemetry=False is bit-free
# ---------------------------------------------------------------------------


def test_step_hlo_bit_identical_with_telemetry_off():
    """The trailing-knob contract every PipelineMeta flag honors: an
    explicit telemetry=False lowers BIT-IDENTICALLY to the default
    program on every knob variant the acceptance bar names (default,
    fused+pruned one-pass, staged pruned, second-chance, dual-stack) —
    so the instrumentation costs nothing unless bought — while
    telemetry=True produces a genuinely different program."""
    cluster = gen_cluster(300, seed=7)
    cps = compile_policy_set(cluster.ps)
    svc = compile_services([])

    def lowered(**kw):
        step, st, (drs, dsvc) = pl.make_pipeline(
            cps, svc, flow_slots=1 << 8, aff_slots=1 << 4, miss_chunk=32,
            **kw)
        cols = (jnp.zeros(128, jnp.int32),) * 5
        return jax.jit(
            pl._pipeline_step, static_argnames=("meta",),
        ).lower(st, drs, dsvc, *cols, jnp.int32(1), jnp.int32(0),
                meta=step.meta).as_text()

    variants = (
        dict(),
        dict(fused=True, prune_budget=2),
        dict(prune_budget=2),
        dict(second_chance=True),
        dict(dual_stack=True),
    )
    for kw in variants:
        assert lowered(telemetry=False, **kw) == lowered(**kw), kw
    # The instrumented program is real: extra outputs, different HLO.
    assert lowered(telemetry=True) != lowered()


def test_telemetry_off_engine_is_inert():
    """Engines built without the knob carry NO plane: the accessors the
    API/bundle/antctl surfaces poll all answer None, so telemetry=False
    deployments serve a 404, not zeros."""
    dp = TpuflowDatapath(gen_cluster(60, seed=3).ps, **KW)
    assert dp.telemetry_plane is None
    assert dp.telemetry_stats() is None


# ---------------------------------------------------------------------------
# 2. Counter parity vs the host-side oracle recomputation
# ---------------------------------------------------------------------------


def test_classify_regime_precedence():
    assert classify_regime(96, 0) == "steady"
    assert classify_regime(96, 1) == "churn"
    assert classify_regime(96, 47) == "churn"
    assert classify_regime(96, 48) == "cold"   # >= half the batch missed
    assert classify_regime(96, 96) == "cold"
    assert classify_regime(96, 0, shed=1) == "attack-shed"  # wins over all
    # "drain" never classifies from a step (observe_scoped only) but IS
    # a declared regime the sentinel sweeps.
    assert "drain" in REGIMES


def test_counter_parity_vs_oracle_across_regimes():
    """Kernel counters vs the scalar oracle twin on IDENTICAL traffic
    through three regimes: cold (first sight of every flow), steady (the
    same batch re-stepped — every lane hits), churn (a quarter of the
    lanes new).  probe_hit/probe_miss must agree EXACTLY; the oracle's
    stale/second-chance/DMA meters are 0 by construction (documented
    divergence — the interpreter has no probe generations, no clock
    hand, no DMA engine)."""
    from antrea_tpu.compiler.compile import ACT_ALLOW

    cluster = gen_cluster(300, seed=12)
    tpu = TpuflowDatapath(cluster.ps, telemetry=True, miss_chunk=64, **KW)
    orc = OracleDatapath(cluster.ps, telemetry=True, **KW)
    t1 = gen_traffic(cluster.pod_ips, batch=96, seed=5)
    t2 = gen_traffic(cluster.pod_ips, batch=96, seed=6)
    mix = _concat(t1, t2, 72, 24)  # 24/96 new lanes at most => not cold
    r1 = tpu.step(t1, now=1)
    orc.step(t1, now=1)
    # Allowed lanes are cached; deny verdicts are NOT (re-stepping the
    # full batch would re-miss them, keeping churn).  A batch of only
    # allowed lanes is the guaranteed all-hit steady probe.
    ok = np.asarray(r1.code) == ACT_ALLOW  # sync engine: no pending lanes
    assert r1.pending is None or not np.asarray(r1.pending).any()
    assert ok.sum() >= 8
    # The HIGHEST-index allowed lane: commit rows scatter in lane order
    # (last write wins), so its entry cannot have been evicted by a
    # same-step slot collision — tiled, it is the guaranteed all-hit
    # steady batch.
    i = int(np.nonzero(ok)[0][-1])
    pick = lambda f: np.repeat(getattr(t1, f)[i:i + 1], 8)
    steady = PacketBatch(src_ip=pick("src_ip"), dst_ip=pick("dst_ip"),
                         proto=pick("proto"), src_port=pick("src_port"),
                         dst_port=pick("dst_port"))
    for now, b in ((2, steady), (3, mix)):
        tpu.step(b, now=now)
        orc.step(b, now=now)

    st, so = tpu.telemetry_stats(), orc.telemetry_stats()
    ct, co = st["counters"], so["counters"]
    assert set(ct) == set(co) == set(TELEMETRY_COUNTERS)
    assert ct["probe_hit"] == co["probe_hit"] > 0
    assert ct["probe_miss"] == co["probe_miss"] > 0
    assert (co["probe_stale"], co["chance_bumps"], co["dma_hb"]) == (0, 0, 0)
    # Probe-split conservation: every lane of every step lands in exactly
    # one of hit/stale/miss.
    lanes = 2 * len(t1.proto) + 8
    assert ct["probe_hit"] + ct["probe_stale"] + ct["probe_miss"] == lanes
    # Both twins classified the same step sequence into the same regimes
    # (classify_regime is history-free, shared by construction), and the
    # three-step drive hit all three step-classifiable regimes.
    assert st["regimes"]["engine"].keys() == so["regimes"]["engine"].keys()
    assert set(st["regimes"]["engine"]) == {"cold", "steady", "churn"}
    for regime, row in st["regimes"]["engine"].items():
        assert row["count"] == so["regimes"]["engine"][regime]["count"]
    assert st["steps_total"] == so["steps_total"] == 3


def test_mesh_counter_parity_and_replica_scopes():
    """Sharded dispatch vs single chip on identical traffic.  The
    per-replica tel_* vectors are replica-additive, and per-step probe
    conservation holds on BOTH engines; the one accounting difference is
    by design — a spilled lane's probe counters belong to its home-shard
    RETRY dispatch (meshpath masks spills out of the main dispatch, same
    as the prune evidence), which probes AFTER the slow-path install, so
    every retried first-sight lane moves from the single-chip miss column
    to the mesh hit column, one for one.  The mesh also carries
    per-replica regime scopes the single-chip plane does not."""
    if len(jax.devices("cpu")) < 4:
        pytest.skip("needs 4 virtual CPU devices")
    from antrea_tpu.parallel import MeshDatapath, mesh as pm

    mesh = pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4])
    cluster = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=7)
    mdp = MeshDatapath(cluster.ps, mesh=mesh, telemetry=True,
                       flow_slots=1 << 10, aff_slots=1 << 8,
                       canary_probes=16)
    sdp = TpuflowDatapath(cluster.ps, telemetry=True,
                          flow_slots=1 << 10, aff_slots=1 << 8,
                          canary_probes=16)
    batch = gen_traffic(cluster.pod_ips, 256, n_flows=96, seed=3)
    for now in (1, 2):
        rm, rs = mdp.step(batch, now=now), sdp.step(batch, now=now)
        assert rm.code.tolist() == rs.code.tolist()  # verdict parity

    mc = mdp.telemetry_stats()["counters"]
    sc = sdp.telemetry_stats()["counters"]
    lanes = 2 * len(batch.proto)
    assert mc["probe_hit"] + mc["probe_stale"] + mc["probe_miss"] == lanes
    assert sc["probe_hit"] + sc["probe_stale"] + sc["probe_miss"] == lanes
    # Retry conversion: R spilled lanes re-probed post-install.
    retried = mc["probe_hit"] - sc["probe_hit"]
    assert retried >= 0
    assert sc["probe_miss"] - mc["probe_miss"] == retried
    assert mc["probe_hit"] > 0 and mc["probe_miss"] > 0
    scopes = set(mdp.telemetry_stats()["regimes"])
    assert "engine" in scopes
    assert {"replica0", "replica1"} <= scopes, scopes
    assert not any(s.startswith("replica")
                   for s in sdp.telemetry_stats()["regimes"])


# ---------------------------------------------------------------------------
# 3. Sentinel chaos: injected slowdown, reconstructed from the journal
# ---------------------------------------------------------------------------


def test_sentinel_reconstructs_perf_regression_from_journal_alone():
    """FaultClock-driven chaos case: 32 fast steady-regime steps build
    the rolling baseline across budgeted sweeps, then an injected 20x
    slowdown over the next window fires EXACTLY one `perf-regression`
    event.  Everything the post-mortem needs — regime, window p99,
    baseline p99, sample count, trip ratio, and WHEN on the scheduler's
    fault-injectable clock — is reconstructed from the flight-recorder
    journal alone, and the verdict is journal-and-meter only: the commit
    plane stays healthy (no rollback, no degraded mode)."""
    clk = FaultClock(start=100)
    dp = TpuflowDatapath(gen_cluster(60, seed=3).ps, telemetry=True,
                         maint_clock=clk, **KW)
    plane = dp.telemetry_plane

    def run(dt, steps=32, ticks=3):
        for _ in range(steps):
            plane.note_regime("engine", "steady")
            plane.observe_step(dt)
        # sentinel budget is 2 regimes/tick; 3 ticks cover all 5 and
        # revisit steady, guaranteeing the window is judged.
        for _ in range(ticks):
            clk.advance(60)
            dp.maintenance_tick()

    run(0.001)  # baseline epoch: fast steps, window rolls into baseline
    assert dp.flightrecorder_events(kind="perf-regression") == []
    sent = plane.stats()["sentinel"]["steady"]
    assert sent["baseline_samples"] == 32
    assert sent["baseline_p99_seconds"] > 0

    run(0.020)  # injected slowdown: 20x the baseline step time
    evs = dp.flightrecorder_events(kind="perf-regression")
    assert len(evs) == 1
    ev = evs[0]
    # The journal record alone reconstructs the regression.
    assert ev["kind"] == "perf-regression"
    assert ev["regime"] == "steady"
    assert ev["samples"] == 32
    assert ev["baseline_p99"] > 0
    assert ev["p99"] > ev["ratio"] * ev["baseline_p99"]
    # Clocked by the scheduler tick: both stamps are FaultClock values
    # inside the second epoch's tick window.
    assert 280 < ev["at"] <= clk.now
    assert ev["ts"] == ev["at"]
    # Journal-and-meter ONLY: metered, never acted on.
    assert plane.stats()["regressions_total"] == 1
    assert not dp._commit.degraded
    assert dp.commit_stats()["rollbacks_total"] == 0
    # A sustained slowdown keeps firing (the regressed window was
    # quarantined, not merged into the baseline).
    run(0.020)
    assert len(dp.flightrecorder_events(kind="perf-regression")) == 2
