"""Restart/recovery: datapath snapshot + agent filestore fallback.

Models the reference's recovery design (SURVEY §5): cookie-round restart
(pkg/agent/openflow/cookie/allocator.go:76-135, agent.go:486-512), agent
filestore fallback (pkg/agent/controller/networkpolicy/filestore.go +
watcher.FallbackFunc).  The test kills and reconstructs a datapath and an
AgentPolicyController and demands identical verdicts post-restart.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.controller import AgentPolicyController
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.dissemination import serde
from antrea_tpu.dissemination.store import RamStore
from antrea_tpu.apis.crd import LabelSelector, Namespace, Pod, K8sNetworkPolicy, K8sNPRule, K8sPeer, PortSpec
from antrea_tpu.simulator import gen_cluster, gen_services, gen_traffic


def _fields(r):
    return {
        "code": r.code.tolist(), "svc": r.svc_idx.tolist(),
        "dnat_ip": r.dnat_ip.tolist(), "dnat_port": r.dnat_port.tolist(),
        "reject_kind": r.reject_kind.tolist(), "snat": r.snat.tolist(),
        "rules_in": r.ingress_rule, "rules_out": r.egress_rule,
    }


def test_serde_roundtrip_policy_set_and_events():
    cluster = gen_cluster(60, n_nodes=4, pods_per_node=8, seed=11)
    ps = cluster.ps
    ps2 = serde.decode_policy_set(serde.encode_policy_set(ps))
    assert serde.encode_policy_set(ps2) == serde.encode_policy_set(ps)
    assert len(ps2.policies) == len(ps.policies)
    assert ps2.address_groups.keys() == ps.address_groups.keys()

    services = gen_services(6, cluster.pod_ips, seed=12)
    for s in services:
        s2 = serde.decode_service_entry(serde.encode_service_entry(s))
        assert serde.encode_service_entry(s2) == serde.encode_service_entry(s)

    from antrea_tpu.controller.networkpolicy import WatchEvent

    ev = WatchEvent(
        kind="UPDATED", obj_type="AddressGroup", name="g1",
        obj=list(ps.address_groups.values())[0],
        span={"n0", "n1"},
        added=list(list(ps.applied_to_groups.values())[0].members[:2]),
        removed=[],
        span_only=False,
    )
    ev2 = serde.event_from_wire(serde.event_to_wire(ev))
    assert serde.event_to_wire(ev2) == serde.event_to_wire(ev)
    assert ev2.span == ev.span and ev2.kind == ev.kind


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_datapath_restart_recovers_state(tmp_path, dp_cls):
    """Kill + reconstruct a datapath from its persist dir: policy and
    service state and the generation survive; verdicts match a twin that
    never restarted (established flows re-classify, same verdicts)."""
    cluster = gen_cluster(80, n_nodes=4, pods_per_node=8, seed=21)
    services = gen_services(8, cluster.pod_ips, seed=22)
    traffic = gen_traffic(cluster.pod_ips, batch=128, seed=23,
                          services=services, svc_fraction=0.4)
    kw = dict(flow_slots=1 << 12, aff_slots=1 << 8)
    if dp_cls is TpuflowDatapath:
        kw["miss_chunk"] = 32

    dp = dp_cls(persist_dir=str(tmp_path), **kw)
    g1 = dp.install_bundle(ps=cluster.ps, services=services)
    r_before = dp.step(traffic, now=10)
    twin = dp_cls(cluster.ps, services, **kw)
    del dp  # "crash"

    dp2 = dp_cls(persist_dir=str(tmp_path), **kw)
    assert dp2.generation == g1  # monotonic across restart
    r_after = dp2.step(traffic, now=20)
    r_twin = twin.step(traffic, now=20)
    assert _fields(r_after) == _fields(r_twin)
    # Verdicts also match the pre-restart run (same inputs, same state).
    assert r_after.code.tolist() == r_before.code.tolist()
    # Conntrack state was dropped: the restarted datapath re-commits.
    assert int(r_after.est.sum()) == 0 and int(r_after.committed.sum()) > 0

    # A post-restart bundle keeps the generation monotonic and persists.
    g2 = dp2.install_bundle(services=services)
    assert g2 == g1 + 1
    dp3 = dp_cls(persist_dir=str(tmp_path), **kw)
    assert dp3.generation == g2

    # Delta-path generation bumps are journaled (cookie-round append in
    # the native config store) even though the snapshot is not rewritten:
    # a crash right after deltas must NOT roll the generation back (a
    # rolled-back gen could alias pre-crash cached denials).
    ag = sorted(cluster.ps.address_groups)[0]
    g3 = dp3.apply_group_delta(ag, added_ips=["10.77.0.1"], removed_ips=[])
    g4 = dp3.apply_group_delta(ag, added_ips=["10.77.0.2"], removed_ips=[])
    assert g4 > g3 >= g2
    del dp3  # crash with snapshot stale but round journal current
    dp4 = dp_cls(persist_dir=str(tmp_path), **kw)
    assert dp4.generation == g4


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_two_slot_snapshot_corrupt_latest_falls_back(tmp_path, dp_cls):
    """The two-slot store (datapath/persist.py): a corrupt or truncated
    NEWEST snapshot recovers to the last-known-good slot — one bundle
    behind, never a fresh boot — and the round journal keeps the
    generation monotonic across the fallback."""
    from antrea_tpu.datapath import persist

    cluster_a = gen_cluster(40, n_nodes=2, pods_per_node=6, seed=31)
    cluster_b = gen_cluster(40, n_nodes=2, pods_per_node=6, seed=32)
    kw = dict(flow_slots=1 << 12, aff_slots=1 << 8)
    if dp_cls is TpuflowDatapath:
        kw["miss_chunk"] = 32

    dp = dp_cls(persist_dir=str(tmp_path), **kw)
    dp.install_bundle(ps=cluster_a.ps)
    g2 = dp.install_bundle(ps=cluster_b.ps)  # rotation: latest=B, lkg=A
    twin = dp_cls(cluster_a.ps, **kw)
    del dp  # crash

    # Bit-rot the newest slot: the checksum must reject it.
    latest = persist.snapshot_path(str(tmp_path))
    body = latest and open(latest).read()
    with open(latest, "w") as f:
        f.write(body.replace('"generation":2', '"generation":9'))
    assert persist.load_snapshot(str(tmp_path))[2] == 1  # the LKG slot

    dp2 = dp_cls(persist_dir=str(tmp_path), **kw)
    # Enforcing the LKG bundle (A), with the generation still monotonic
    # (round journal wins over the older snapshot's gen).
    assert dp2.generation == g2
    traffic = gen_traffic(cluster_a.pod_ips, batch=64, seed=33)
    assert (_fields(dp2.step(traffic, now=10))
            == _fields(twin.step(traffic, now=10)))

    # Truncation (torn write) falls back the same way.
    with open(latest, "w") as f:
        f.write('{"v": 2, "genera')
    assert persist.load_snapshot(str(tmp_path))[2] == 1


def test_crash_between_slot_writes_never_loses_both(tmp_path):
    """Fault-injected crash between the LKG rotation and the latest
    write: the old state survives in BOTH slots (rotation is a copy, not
    a move), so recovery never loses the certified bundle."""
    from antrea_tpu.datapath import persist

    cluster_a = gen_cluster(30, n_nodes=2, pods_per_node=5, seed=41)
    cluster_b = gen_cluster(30, n_nodes=2, pods_per_node=5, seed=42)
    dp = OracleDatapath(persist_dir=str(tmp_path),
                        flow_slots=1 << 8, aff_slots=1 << 4)
    g1 = dp.install_bundle(ps=cluster_a.ps)

    class Crash(RuntimeError):
        pass

    def crash(site):
        assert site == "between_slots"
        raise Crash(site)

    dp._persist_fault = crash
    # The commit itself succeeds in memory (canary passed); only the
    # settle-stage durability crashes.
    with pytest.raises(Crash):
        dp.install_bundle(ps=cluster_b.ps)
    assert dp.commit_stats()["commits"]["settle/error"] == 1
    del dp  # the "crash"

    # Both slots hold the certified pre-crash bundle A.
    got = persist.load_snapshot(str(tmp_path))
    assert got is not None and got[2] == g1
    dp2 = OracleDatapath(persist_dir=str(tmp_path),
                         flow_slots=1 << 8, aff_slots=1 << 4)
    assert dp2.generation == g1
    assert len(dp2._ps.policies) == len(cluster_a.ps.policies)

    # And with latest ALSO destroyed post-crash, the LKG copy still loads.
    import os

    os.remove(persist.snapshot_path(str(tmp_path)))
    got = persist.load_snapshot(str(tmp_path))
    assert got is not None and got[2] == g1


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_tenant_worlds_survive_restart(tmp_path, dp_cls):
    """Tenant worlds ride the two-slot checksummed snapshot: a restarted
    engine rebuilds the registry — tids, specs and per-tenant generations
    preserved, tensors recompiled from the persisted policy sets — and
    serves every tenant bitwise like a twin that never restarted (flow
    caches re-classify, same verdicts; the default world untouched)."""
    import copy

    base = gen_cluster(40, n_nodes=2, pods_per_node=6, seed=51)
    worlds = [gen_cluster(rc, n_nodes=2, pods_per_node=6, seed=52 + i)
              for i, rc in enumerate((8, 40))]
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    tkw = dict(quota=1 << 8, aff_quota=1 << 6)

    dp = dp_cls(persist_dir=str(tmp_path), **kw)
    dp.install_bundle(ps=base.ps)
    tids = [dp.tenant_create(f"t{i}", copy.deepcopy(c.ps), **tkw)
            for i, c in enumerate(worlds)]
    # A per-tenant install bumps THAT tenant's generation; the snapshot
    # must carry it across the restart (monotonicity is per world).
    g_t0 = dp.tenant_install_bundle(tids[0], copy.deepcopy(worlds[0].ps))
    assert g_t0 == 1

    twin = dp_cls(copy.deepcopy(base.ps), **kw)
    twin_tids = [twin.tenant_create(f"t{i}", copy.deepcopy(c.ps), **tkw)
                 for i, c in enumerate(worlds)]
    twin.tenant_install_bundle(twin_tids[0], copy.deepcopy(worlds[0].ps))
    del dp  # crash

    dp2 = dp_cls(persist_dir=str(tmp_path), **kw)
    assert dp2.tenant_count == len(worlds)
    stats = dp2.tenant_stats()
    assert sorted(stats) == sorted(tids)  # tids preserved verbatim
    assert stats[tids[0]]["generation"] == g_t0
    assert stats[tids[0]]["name"] == "t0"
    assert stats[tids[0]]["quota_slots"] == 1 << 8

    for i, (tid, c) in enumerate(zip(tids, worlds)):
        b = gen_traffic(c.pod_ips, batch=64, n_flows=24, seed=60 + i)
        got = dp2.tenant_step(tid, b, now=100)
        want = twin.tenant_step(twin_tids[i], b, now=100)
        assert _fields(got) == _fields(want)
        # Tenant conntrack was dropped on restart: this first round
        # re-commits rather than serving established rows.
        assert int(got.est.sum()) == 0 and int(got.committed.sum()) > 0

    # The default world restores exactly as it did before tenants rode
    # the snapshot (the `tenants` key is additive, checksum-covered).
    bd = gen_traffic(base.pod_ips, batch=64, seed=70)
    assert _fields(dp2.step(bd, now=101)) == _fields(twin.step(bd, now=101))

    # The per-tenant generation keeps climbing monotonically after the
    # restart — never a rollback that could alias a cached denial.
    g_next = dp2.tenant_install_bundle(
        tids[0], copy.deepcopy(worlds[0].ps))
    assert g_next == g_t0 + 1


def _mini_cluster_events(store):
    ctrl = NetworkPolicyController()
    ctrl.subscribe(store.apply)
    ctrl.upsert_namespace(Namespace(name="default"))
    for i, ip in enumerate(("10.0.0.5", "10.0.0.7")):
        ctrl.upsert_pod(Pod(name=f"p{i}", namespace="default",
                            labels={"app": f"a{i}"}, ip=ip, node="n0"))
    ctrl.upsert_k8s_policy(K8sNetworkPolicy(
        uid="np1", name="np1", namespace="default",
        pod_selector=LabelSelector.make({"app": "a1"}),
        ingress=[K8sNPRule(
            peers=[K8sPeer(pod_selector=LabelSelector.make({"app": "a0"}))],
            ports=[PortSpec(protocol=6, port=80)],
        )],
    ))
    return ctrl


def test_agent_restart_boots_from_filestore(tmp_path):
    """An agent restarted while the controller is unreachable enforces the
    last-received policy state from its filestore (FallbackFunc model)."""
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.utils import ip as iputil

    def probe(dp, src, dst, now):
        b = PacketBatch(
            src_ip=np.array([iputil.ip_to_u32(src)], np.uint32),
            dst_ip=np.array([iputil.ip_to_u32(dst)], np.uint32),
            proto=np.array([6], np.int32),
            src_port=np.array([41000], np.int32),
            dst_port=np.array([80], np.int32),
        )
        return dp.step(b, now)

    store = RamStore()
    dp1 = OracleDatapath()
    agent1 = AgentPolicyController(
        "n0", dp1, store=None, filestore_dir=str(tmp_path)
    )
    store.watch("n0", agent1.handle_event)
    _mini_cluster_events(store)
    agent1.sync()
    r = probe(dp1, "10.0.0.5", "10.0.0.7", 1)
    assert int(r.code[0]) == 0  # allowed by np1
    r = probe(dp1, "10.0.0.99", "10.0.0.7", 2)
    assert int(r.code[0]) == 1  # default-deny on the isolated pod
    del agent1, store  # agent crash + controller unreachable

    dp2 = OracleDatapath()
    agent2 = AgentPolicyController(
        "n0", dp2, store=None, filestore_dir=str(tmp_path)
    )
    agent2.sync()  # boots from the filestore
    r = probe(dp2, "10.0.0.5", "10.0.0.7", 3)
    assert int(r.code[0]) == 0
    r = probe(dp2, "10.0.0.99", "10.0.0.7", 4)
    assert int(r.code[0]) == 1
    assert len(agent2.policy_set.policies) == 1


# ---------------------------------------------------------------------------
# Tenant topology latch across restarts (PR 20): snapshot rows carry the
# world's CERTIFIED topology so a crash mid-resize restores each world
# to the generation its own canary certified — and a torn latch boots
# that world fleet-aligned, journaled, never wrong-verdicted.
# ---------------------------------------------------------------------------

def test_tenant_topology_latch_snapshot_roundtrip_mesh(tmp_path):
    """Force a latched world (single-tenant canary veto mid-grow), crash,
    and restore twice: once at the latch's certified width (the latch
    restores) and once at a width that no longer exists (torn — the
    world boots fleet-aligned with a journaled `tenant-rollback`)."""
    import copy

    import jax

    from antrea_tpu.dissemination.faults import FaultPlan
    from antrea_tpu.parallel import MeshDatapath, mesh as pm

    kw = dict(flow_slots=1 << 8, aff_slots=1 << 6, canary_probes=8)
    base = gen_cluster(40, n_nodes=4, pods_per_node=6, seed=7)
    services = gen_services(4, base.pod_ips, seed=11)
    worlds = [gen_cluster(20, n_nodes=2, pods_per_node=5, seed=100 + i)
              for i in range(2)]
    dp = MeshDatapath(copy.deepcopy(base.ps), services,
                      mesh=pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4]),
                      persist_dir=str(tmp_path), **kw)
    tids = [dp.tenant_create(f"w{i}", copy.deepcopy(c.ps), quota=64)
            for i, c in enumerate(worlds)]
    plan = FaultPlan(seed=9)
    plan.every(f"n0.tenant_canary.t{tids[0]}", 1, "forced", times=1)
    dp.arm_reshard_faults(plan, "n0")
    dp.reshard_begin(4)
    t = 101
    while dp.reshard_status() is not None:
        dp.maintenance_tick(now=t)
        t += 1
        assert t < 400, dp.reshard_status()
    assert dp._n_data == 4 and dp._topo_gen == 1

    rows = {r["tid"]: r for r in dp._tenant_snapshot_worlds()}
    assert rows[tids[0]]["latched"] == 1
    assert rows[tids[0]]["topoN"] == 2 and rows[tids[0]]["topoGen"] == 0
    assert rows[tids[1]]["latched"] == 0
    assert rows[tids[1]]["topoN"] == 4 and rows[tids[1]]["topoGen"] == 1
    dp._persist_dirty = True
    dp.checkpoint()
    del dp  # crash mid-latch

    # Boot at the latched world's certified width: the latch restores
    # cleanly (no torn-latch journal) and both worlds serve.
    dp2 = MeshDatapath(
        mesh=pm.make_mesh(2, 2, devices=jax.devices("cpu")[:4]),
        persist_dir=str(tmp_path), **kw)
    assert dp2.tenant_count == 2
    assert not [e for e in dp2.flightrecorder_events()
                if e["kind"] == "tenant-rollback"]
    st = dp2.tenant_stats()
    assert st[tids[0]]["topology_generation"] == 0
    assert st[tids[0]]["latched"] == 0  # certified == boot fleet here
    for i, tid in enumerate(tids):
        b = gen_traffic(worlds[i].pod_ips, 64, n_flows=24, seed=60 + i)
        assert dp2.tenant_step(tid, b, now=200).code.shape == (64,)
    del dp2

    # Boot at a width the latch never certified: torn — journaled, and
    # the world boots fleet-aligned (cold tables, correct verdicts).
    dp3 = MeshDatapath(
        mesh=pm.make_mesh(4, 2, devices=jax.devices("cpu")),
        persist_dir=str(tmp_path), **kw)
    assert dp3.tenant_count == 2
    torn = [e for e in dp3.flightrecorder_events()
            if e["kind"] == "tenant-rollback"
            and "torn topology latch" in e.get("error", "")]
    assert len(torn) == 1 and torn[0]["tenant"] == tids[0]
    st = dp3.tenant_stats()
    for tid in tids:
        assert st[tid]["latched"] == 0
        assert st[tid]["topology_generation"] == 0
    twin = MeshDatapath(
        copy.deepcopy(base.ps), services,
        mesh=pm.make_mesh(4, 2, devices=jax.devices("cpu")), **kw)
    twin_tids = [twin.tenant_create(f"w{i}", copy.deepcopy(c.ps), quota=64)
                 for i, c in enumerate(worlds)]
    for i, (tid, wtid) in enumerate(zip(tids, twin_tids)):
        b = gen_traffic(worlds[i].pod_ips, 64, n_flows=24, seed=60 + i)
        got = dp3.tenant_step(tid, b, now=300)
        want = twin.tenant_step(wtid, b, now=300)
        np.testing.assert_array_equal(np.asarray(got.code),
                                      np.asarray(want.code))


@pytest.mark.parametrize("dp_cls", [TpuflowDatapath, OracleDatapath])
def test_tenant_torn_topology_latch_both_engines(tmp_path, dp_cls):
    """A latched snapshot row landing on an engine whose worlds carry no
    topology latch at all (single-chip boot of a mesh snapshot) is the
    torn case by definition: journaled `tenant-rollback`, world restored
    fleet-aligned, verdicts bitwise-equal to a never-crashed twin."""
    import copy

    base = gen_cluster(40, n_nodes=2, pods_per_node=6, seed=51)
    world = gen_cluster(20, n_nodes=2, pods_per_node=5, seed=52)
    kw = dict(flow_slots=1 << 10, aff_slots=1 << 8)
    tkw = dict(quota=1 << 8, aff_quota=1 << 6)

    dp = dp_cls(copy.deepcopy(base.ps), **kw)
    dp.tenant_create("t0", copy.deepcopy(world.ps), **tkw)
    rows = dp._tenant_snapshot_worlds()
    assert "topoN" not in rows[0]  # single-chip worlds carry no latch
    rows[0].update(topoN=4, topoGen=1, latched=1)

    dp2 = dp_cls(copy.deepcopy(base.ps), **kw)
    dp2._pending_tenant_restore = rows
    dp2._restore_tenant_worlds()
    torn = [e for e in dp2.flightrecorder_events()
            if e["kind"] == "tenant-rollback"
            and "torn topology latch" in e.get("error", "")]
    assert len(torn) == 1
    assert dp2.tenant_count == 1
    tid = rows[0]["tid"]

    twin = dp_cls(copy.deepcopy(base.ps), **kw)
    twin_tid = twin.tenant_create("t0", copy.deepcopy(world.ps), **tkw)
    b = gen_traffic(world.pod_ips, batch=64, n_flows=24, seed=61)
    got = dp2.tenant_step(tid, b, now=100)
    want = twin.tenant_step(twin_tid, b, now=100)
    np.testing.assert_array_equal(np.asarray(got.code),
                                  np.asarray(want.code))
    np.testing.assert_array_equal(np.asarray(got.svc_idx),
                                  np.asarray(want.svc_idx))
