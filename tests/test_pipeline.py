"""Full-pipeline parity + behavior tests: conntrack est-bypass, service LB,
DNAT, session affinity — device pipeline vs scalar pipeline oracle."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.apis import controlplane as cp
from antrea_tpu.apis.service import Endpoint, ServiceEntry
from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.compiler.ir import PolicySet
from antrea_tpu.compiler.services import compile_services
from antrea_tpu.models.pipeline import make_pipeline
from antrea_tpu.ops.match import flip_ips
from antrea_tpu.oracle.pipeline import PipelineOracle
from antrea_tpu.packet import PacketBatch
from antrea_tpu.simulator import gen_cluster, gen_services, gen_traffic
from antrea_tpu.utils import ip as iputil

CONN_SLOTS = 1 << 16
AFF_SLOTS = 1 << 12


def run_step(step, state, drs, dsvc, t: PacketBatch, now: int, gen: int = 0):
    state, out = step(
        state,
        drs,
        dsvc,
        np.asarray(flip_ips(t.src_ip)),
        np.asarray(flip_ips(t.dst_ip)),
        t.proto.astype(np.int32),
        t.src_port.astype(np.int32),
        t.dst_port.astype(np.int32),
        np.int32(now),
        np.int32(gen),
    )
    return state, {k: np.asarray(v) for k, v in out.items()}


def unflip(a):
    return (np.asarray(a, dtype=np.int32).view(np.uint32) ^ np.uint32(0x80000000))


def compare(cps, out, scalar_outs, i):
    so = scalar_outs[i]
    assert int(out["code"][i]) == so.code, (i, "code")
    assert bool(out["est"][i]) == so.est, (i, "est")
    assert bool(out["reply"][i]) == so.reply, (i, "reply")
    assert int(out["reject_kind"][i]) == so.reject_kind, (i, "reject_kind")
    assert int(out["svc_idx"][i]) == so.svc_idx, (i, "svc")
    assert int(unflip(out["dnat_ip_f"][i : i + 1])[0]) == so.dnat_ip, (i, "dnat_ip")
    assert int(out["dnat_port"][i]) == so.dnat_port, (i, "dnat_port")
    for key, ids, want in (
        ("ingress_rule", cps.ingress.rule_ids, so.ingress_rule),
        ("egress_rule", cps.egress.rule_ids, so.egress_rule),
    ):
        ridx = int(out[key][i])
        got = ids[ridx] if ridx >= 0 else None
        assert got == want, (i, key, got, want)


@pytest.mark.parametrize("seed", [0, 3])
def test_pipeline_parity_multistep(seed):
    cluster = gen_cluster(150, seed=seed)
    services = gen_services(24, cluster.pod_ips, seed=seed + 1, no_ep_fraction=0.1)
    traffic = gen_traffic(
        cluster.pod_ips, batch=160, seed=seed + 2, services=services, svc_fraction=0.4
    )
    cps = compile_policy_set(cluster.ps)
    svt = compile_services(services)
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )
    po = PipelineOracle(
        cluster.ps, services, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )

    est_seen = 0
    for step_i, now in enumerate([1000, 1010, 1020]):
        state, out = run_step(step, state, drs, dsvc, traffic, now)
        scalar = po.step(traffic, now, 0)
        for i in range(traffic.size):
            compare(cps, out, scalar, i)
        est_seen += int(out["est"].sum())
        if step_i > 0:
            # Repeat batches must hit the conn table for allowed flows.
            assert out["est"].sum() > 0
    assert est_seen > 0


def _mini_env():
    """One pod, one service with two endpoints, no policies."""
    ps = PolicySet()
    services = [
        ServiceEntry(
            cluster_ip="10.96.0.1",
            port=80,
            protocol=cp.PROTO_TCP,
            endpoints=[Endpoint("10.0.0.10", 8080), Endpoint("10.0.0.11", 8080)],
            affinity_timeout_s=100,
        ),
        ServiceEntry(
            cluster_ip="10.96.0.2", port=80, protocol=cp.PROTO_TCP, endpoints=[]
        ),
    ]
    cps = compile_policy_set(ps)
    svt = compile_services(services)
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )
    return ps, services, cps, step, state, drs, dsvc


def _batch(rows):
    return PacketBatch(
        src_ip=np.array([r[0] for r in rows], dtype=np.uint32),
        dst_ip=np.array([r[1] for r in rows], dtype=np.uint32),
        proto=np.array([r[2] for r in rows], dtype=np.int32),
        src_port=np.array([r[3] for r in rows], dtype=np.int32),
        dst_port=np.array([r[4] for r in rows], dtype=np.int32),
    )


def test_service_dnat_and_no_ep_reject():
    _, services, cps, step, state, drs, dsvc = _mini_env()
    client = iputil.ip_to_u32("10.0.0.5")
    svc1 = iputil.ip_to_u32("10.96.0.1")
    svc2 = iputil.ip_to_u32("10.96.0.2")
    t = _batch(
        [
            (client, svc1, cp.PROTO_TCP, 40000, 80),
            (client, svc2, cp.PROTO_TCP, 40001, 80),
            (client, svc1, cp.PROTO_UDP, 40002, 80),  # wrong proto: not a svc
        ]
    )
    state, out = run_step(step, state, drs, dsvc, t, 100)
    # svc1: DNAT to one of the endpoints, allowed, committed.
    assert int(out["svc_idx"][0]) == 0
    assert int(out["code"][0]) == 0
    dnat0 = int(unflip(out["dnat_ip_f"][:1])[0])
    assert dnat0 in (iputil.ip_to_u32("10.0.0.10"), iputil.ip_to_u32("10.0.0.11"))
    assert int(out["dnat_port"][0]) == 8080
    assert int(out["committed"][0]) == 1
    # svc2: no endpoints -> REJECT, not committed.
    assert int(out["svc_idx"][1]) == 1
    assert int(out["code"][1]) == 2
    assert int(out["committed"][1]) == 0
    # wrong proto: not service traffic, dst unchanged.
    assert int(out["svc_idx"][2]) == -1
    assert int(unflip(out["dnat_ip_f"][2:3])[0]) == svc1


def test_est_bypass_and_ct_timeout():
    """A committed connection bypasses policy until idle timeout expires."""
    # Policy that drops everything to the endpoint IP from anywhere.
    ps = PolicySet()
    ps.applied_to_groups["atg-ep"] = cp.AppliedToGroup(
        "atg-ep", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="drop-ep",
            name="drop-ep",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-ep"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN, action=cp.RuleAction.DROP, priority=0
                )
            ],
        )
    )
    cps = compile_policy_set(ps)
    svt = compile_services([])
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS,
        ct_timeout_s=60,
    )
    client = iputil.ip_to_u32("10.0.0.5")
    ep = iputil.ip_to_u32("10.0.0.10")
    allowed = iputil.ip_to_u32("10.0.0.99")
    t_allowed = _batch([(client, allowed, cp.PROTO_TCP, 40000, 80)])
    t_denied = _batch([(client, ep, cp.PROTO_TCP, 40001, 80)])

    # Denied flow never commits; allowed flow commits then shortcuts.
    state, out = run_step(step, state, drs, dsvc, t_denied, 0)
    assert int(out["code"][0]) == 1 and int(out["committed"][0]) == 0
    state, out = run_step(step, state, drs, dsvc, t_allowed, 0)
    assert int(out["code"][0]) == 0 and int(out["committed"][0]) == 1
    state, out = run_step(step, state, drs, dsvc, t_allowed, 30)
    assert int(out["est"][0]) == 1
    # After idle timeout the flow re-classifies (fresh commit, not est).
    state, out = run_step(step, state, drs, dsvc, t_allowed, 200)
    assert int(out["est"][0]) == 0 and int(out["committed"][0]) == 1


def test_policy_applies_post_dnat():
    """Dropping the ENDPOINT IP must drop service traffic to the ClusterIP —
    proves security stages see the DNAT-ed tuple (PreRouting precedes
    EgressSecurity in the reference stage order)."""
    ps = PolicySet()
    ps.applied_to_groups["atg-ep"] = cp.AppliedToGroup(
        "atg-ep", [cp.GroupMember(ip="10.0.0.10", node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="drop-ep",
            name="drop-ep",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-ep"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN, action=cp.RuleAction.DROP, priority=0
                )
            ],
        )
    )
    services = [
        ServiceEntry(
            cluster_ip="10.96.0.1",
            port=80,
            protocol=cp.PROTO_TCP,
            endpoints=[Endpoint("10.0.0.10", 8080)],
        )
    ]
    cps = compile_policy_set(ps)
    svt = compile_services(services)
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )
    client = iputil.ip_to_u32("10.0.0.5")
    t = _batch([(client, iputil.ip_to_u32("10.96.0.1"), cp.PROTO_TCP, 40000, 80)])
    state, out = run_step(step, state, drs, dsvc, t, 0)
    assert int(out["code"][0]) == 1  # dropped via endpoint-IP rule post-DNAT
    assert cps.ingress.rule_ids[int(out["ingress_rule"][0])] == "drop-ep/In/0"


def test_reply_direction_undnat():
    """A service connection's REPLY (endpoint -> client, post-DNAT tuple with
    ports swapped) must hit the reverse conntrack entry: est bypass + the
    un-DNAT rewrite restoring the original frontend tuple (ref UnSNAT/
    ConntrackState tables, pipeline.go; ovs-pipeline.md ct sections)."""
    _, services, cps, step, state, drs, dsvc = _mini_env()
    client = iputil.ip_to_u32("10.0.0.5")
    svc1 = iputil.ip_to_u32("10.96.0.1")

    # Forward packet: client -> ClusterIP:80, DNAT to an endpoint.
    t_fwd = _batch([(client, svc1, cp.PROTO_TCP, 40000, 80)])
    state, out = run_step(step, state, drs, dsvc, t_fwd, 100)
    assert int(out["committed"][0]) == 1
    ep_ip = int(unflip(out["dnat_ip_f"][:1])[0])
    ep_port = int(out["dnat_port"][0])

    # Reply packet: endpoint -> client with swapped ports.
    t_rpl = _batch([(ep_ip, client, cp.PROTO_TCP, ep_port, 40000)])
    state, out = run_step(step, state, drs, dsvc, t_rpl, 110)
    assert int(out["est"][0]) == 1, "reply must ride the est bypass"
    assert int(out["reply"][0]) == 1
    assert int(out["code"][0]) == 0
    assert int(out["n_miss"]) == 0  # pure fast path, no re-classification
    # un-DNAT: the reply's source is restored to the service frontend.
    assert int(unflip(out["dnat_ip_f"][:1])[0]) == svc1
    assert int(out["dnat_port"][0]) == 80

    # A reply-shaped packet for a NEVER-committed connection is a fresh flow.
    t_cold = _batch([(ep_ip, client, cp.PROTO_TCP, ep_port, 50505)])
    state, out = run_step(step, state, drs, dsvc, t_cold, 120)
    assert int(out["reply"][0]) == 0 and int(out["est"][0]) == 0


def test_reply_bypasses_policy_and_reject_kinds():
    """Reply-leg packets of established connections bypass policy even when
    the rules would deny them; REJECT verdicts carry the synthesis kind
    (TCP -> RST, UDP -> ICMP port-unreachable; ref reject.go)."""
    ps = PolicySet()
    ps.applied_to_groups["atg-client"] = cp.AppliedToGroup(
        "atg-client", [cp.GroupMember(ip="10.0.0.5", node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="reject-client",
            name="reject-client",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-client"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN, action=cp.RuleAction.REJECT,
                    priority=0,
                )
            ],
        )
    )
    cps = compile_policy_set(ps)
    svt = compile_services([])
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )
    client = iputil.ip_to_u32("10.0.0.5")
    server = iputil.ip_to_u32("10.0.0.80")

    # Outbound client -> server is allowed (policy only guards ingress TO
    # the client) and commits both directions.
    t_fwd = _batch([(client, server, cp.PROTO_TCP, 41000, 80)])
    state, out = run_step(step, state, drs, dsvc, t_fwd, 0)
    assert int(out["code"][0]) == 0 and int(out["committed"][0]) == 1

    # The server's reply targets the client — the ingress REJECT rule would
    # hit a fresh flow, but the reply leg rides the reverse ct entry.
    t_rpl = _batch([(server, client, cp.PROTO_TCP, 80, 41000)])
    state, out = run_step(step, state, drs, dsvc, t_rpl, 10)
    assert int(out["code"][0]) == 0 and int(out["reply"][0]) == 1

    # A FRESH connection attempt to the client is rejected with a TCP RST...
    t_tcp = _batch([(server, client, cp.PROTO_TCP, 2000, 9000)])
    state, out = run_step(step, state, drs, dsvc, t_tcp, 20)
    assert int(out["code"][0]) == 2 and int(out["reject_kind"][0]) == 1
    # ...and a UDP one with an ICMP port-unreachable.
    t_udp = _batch([(server, client, cp.PROTO_UDP, 2000, 9000)])
    state, out = run_step(step, state, drs, dsvc, t_udp, 20)
    assert int(out["code"][0]) == 2 and int(out["reject_kind"][0]) == 2


def test_forward_traffic_keeps_reply_entry_alive():
    """Conntrack refreshes both directions: steady forward traffic must keep
    the reverse (reply) entry from idling out, so a late first reply of a
    still-active connection rides the est bypass (ovs-pipeline.md:1200)."""
    ps = PolicySet()
    ps.applied_to_groups["atg-client"] = cp.AppliedToGroup(
        "atg-client", [cp.GroupMember(ip="10.0.0.5", node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="deny-to-client", name="deny-to-client",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg-client"],
            tier_priority=cp.TIER_APPLICATION, priority=1.0,
            rules=[cp.NetworkPolicyRule(
                direction=cp.Direction.IN, action=cp.RuleAction.DROP,
                priority=0,
            )],
        )
    )
    cps = compile_policy_set(ps)
    svt = compile_services([])
    step, state, (drs, dsvc) = make_pipeline(
        cps, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS, ct_timeout_s=100
    )
    client = iputil.ip_to_u32("10.0.0.5")
    server = iputil.ip_to_u32("10.0.0.80")
    t_fwd = _batch([(client, server, cp.PROTO_TCP, 41000, 80)])
    t_rpl = _batch([(server, client, cp.PROTO_TCP, 80, 41000)])

    state, out = run_step(step, state, drs, dsvc, t_fwd, 0)
    assert int(out["committed"][0]) == 1
    # Forward keepalives every 50s; at t=250 the reply entry's ORIGINAL
    # ts=0 is long past the 100s idle timeout...
    for now in (50, 100, 150, 200, 250):
        state, out = run_step(step, state, drs, dsvc, t_fwd, now)
        assert int(out["est"][0]) == 1, now
    # ...but the first reply at t=260 still rides the est bypass, because
    # each forward hit refreshed the partner entry too.
    state, out = run_step(step, state, drs, dsvc, t_rpl, 260)
    assert int(out["reply"][0]) == 1 and int(out["code"][0]) == 0


def test_session_affinity_sticky_and_expiry():
    _, services, cps, step, state, drs, dsvc = _mini_env()
    client = iputil.ip_to_u32("10.0.0.5")
    svc1 = iputil.ip_to_u32("10.96.0.1")

    # Different source ports would normally re-hash; affinity pins them.
    eps = set()
    for sport, now in [(40000, 0), (40010, 10), (40020, 20)]:
        t = _batch([(client, svc1, cp.PROTO_TCP, sport, 80)])
        state, out = run_step(step, state, drs, dsvc, t, now)
        eps.add(int(unflip(out["dnat_ip_f"][:1])[0]))
    assert len(eps) == 1  # sticky

    # After the 100s affinity hard timeout, selection re-hashes (may or may
    # not land elsewhere; verify the entry expired by checking re-learn).
    t = _batch([(client, svc1, cp.PROTO_TCP, 50000, 80)])
    state, out = run_step(step, state, drs, dsvc, t, 500)
    assert int(out["code"][0]) == 0


def _deny_all_ps(target_ip: str) -> PolicySet:
    ps = PolicySet()
    ps.applied_to_groups["atg"] = cp.AppliedToGroup(
        "atg", [cp.GroupMember(ip=target_ip, node="n0")]
    )
    ps.policies.append(
        cp.NetworkPolicy(
            uid="deny-all",
            name="deny-all",
            type=cp.NetworkPolicyType.ACNP,
            applied_to_groups=["atg"],
            tier_priority=cp.TIER_APPLICATION,
            priority=1.0,
            rules=[
                cp.NetworkPolicyRule(
                    direction=cp.Direction.IN, action=cp.RuleAction.DROP, priority=0
                )
            ],
        )
    )
    return ps


def test_generation_semantics():
    """Bundle commits (gen bumps) invalidate cached denials but preserve
    established connections — the ct est-bypass + megaflow-revalidation
    semantics of the reference (docs/design/ovs-pipeline.md:1685-1691)."""
    from antrea_tpu.models.pipeline import make_pipeline as mk
    from antrea_tpu.ops.match import to_device

    client = "10.0.0.5"
    target = "10.0.0.10"
    t = _batch([(iputil.ip_to_u32(client), iputil.ip_to_u32(target),
                 cp.PROTO_TCP, 40000, 80)])

    # gen 0: open policy set -> flow allowed + committed.
    open_ps = PolicySet()
    cps_open = compile_policy_set(open_ps)
    svt = compile_services([])
    step, state, (drs_open, dsvc) = mk(
        cps_open, svt, flow_slots=CONN_SLOTS, aff_slots=AFF_SLOTS
    )
    state, out = run_step(step, state, drs_open, dsvc, t, 0, gen=0)
    assert int(out["code"][0]) == 0 and int(out["committed"][0]) == 1

    # gen 1: rules now deny — but the ESTABLISHED flow persists (est bypass).
    cps_deny = compile_policy_set(_deny_all_ps(target))
    drs_deny, _ = to_device(cps_deny)
    state, out = run_step(step, state, drs_deny, dsvc, t, 10, gen=1)
    assert int(out["est"][0]) == 1 and int(out["code"][0]) == 0
    assert int(out["n_miss"]) == 0  # pure fast path

    # A DIFFERENT flow (new sport) to the same target is denied at gen 1...
    t2 = _batch([(iputil.ip_to_u32(client), iputil.ip_to_u32(target),
                  cp.PROTO_TCP, 40001, 80)])
    state, out = run_step(step, state, drs_deny, dsvc, t2, 20, gen=1)
    assert int(out["code"][0]) == 1
    # ...and the denial is served from cache on repeat (no slow path).
    state, out = run_step(step, state, drs_deny, dsvc, t2, 30, gen=1)
    assert int(out["code"][0]) == 1 and int(out["n_miss"]) == 0

    # gen 2: rules revert to allow — the cached denial is INVALIDATED.
    state, out = run_step(step, state, drs_open, dsvc, t2, 40, gen=2)
    assert int(out["code"][0]) == 0 and int(out["committed"][0]) == 1
    assert int(out["n_miss"]) == 1  # denial re-classified, not cache-served
