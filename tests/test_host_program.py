"""Host route/iptables program renderer, IP assigner, antctl check."""

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.ipassigner import ANNOUNCE_REPEATS, IPAssigner
from antrea_tpu.agent.nodeportlocal import NplController
from antrea_tpu.agent.route import GW_DEV, render_program
from antrea_tpu.compiler.topology import NodeRoute, Topology


def _topo():
    return Topology(
        node_name="node-a", gateway_ip="10.10.0.1", pod_cidr="10.10.0.0/24",
        local_pods=[("10.10.0.5", 3)],
        remote_nodes=[
            NodeRoute("node-c", "192.168.1.3", "10.10.2.0/24"),
            NodeRoute("node-b", "192.168.1.2", "10.10.1.0/24"),
        ],
    )


def test_host_program_renders_deterministically():
    npl = NplController(["192.168.1.10"], port_range=(61000, 61010))
    port = npl.add_pod_port("10.10.0.5", 6, 8080)
    egress = [("10.10.0.5", "203.0.113.9", "eg-1")]
    prog = render_program(
        _topo(), node_ips=["192.168.1.10"], egress_assignments=egress,
        npl_mappings=npl.mappings(),
    )
    # Deterministic: identical re-render (the idempotent-reconcile property
    # the reference's route sync relies on).
    assert prog == render_program(
        _topo(), node_ips=["192.168.1.10"], egress_assignments=egress,
        npl_mappings=npl.mappings(),
    )
    text = "\n".join(prog)
    # Routes sorted by CIDR; one per remote node, via the gateway device.
    assert prog[0] == (
        f"ip route replace 10.10.1.0/24 via 192.168.1.2 dev {GW_DEV} onlink"
    )
    assert "10.10.2.0/24 via 192.168.1.3" in prog[1]
    assert "ipset add ANTREA-POD-IP-NET 10.10.0.0/24" in text
    assert "ipset add ANTREA-NODEPORT-IP 192.168.1.10" in text
    # Egress SNAT precedes the default masquerade.
    snat = [i for i, l in enumerate(prog) if "SNAT --to 203.0.113.9" in l]
    masq = [i for i, l in enumerate(prog) if "MASQUERADE" in l]
    assert snat and masq and snat[0] < masq[0]
    assert (
        f"-p tcp --dport {port} -j DNAT --to-destination 10.10.0.5:8080"
        in text
    )


def test_ip_assigner_announce_and_reconcile():
    anns = []
    a = IPAssigner("node-a", announce=anns.append)
    assert a.assign("203.0.113.9") is True
    assert len(anns) == ANNOUNCE_REPEATS  # gratuitous ARP repeats
    assert anns[0].ip == "203.0.113.9" and anns[0].kind == "gratuitous-arp"
    assert a.assign("203.0.113.9") is False  # idempotent, silent
    assert len(anns) == ANNOUNCE_REPEATS
    added, removed = a.reconcile({"203.0.113.10"})
    assert added == {"203.0.113.10"} and removed == {"203.0.113.9"}
    assert a.assigned() == {"203.0.113.10"}


def test_antctl_check(capsys):
    from antrea_tpu import antctl

    assert antctl.main(["check"]) == 0
    out = capsys.readouterr().out
    assert "native-store: ok" in out
    assert "datapath-parity: ok" in out
    assert "persistence-roundtrip: ok" in out


def test_controller_info_heartbeat():
    from antrea_tpu.apis import crd
    from antrea_tpu.controller.networkpolicy import NetworkPolicyController
    from antrea_tpu.dissemination import RamStore
    from antrea_tpu.observability.agentinfo import collect_controller_info

    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    ctl.upsert_namespace(crd.Namespace(name="d", labels={}))
    ctl.upsert_pod(crd.Pod(namespace="d", name="p", ip="10.0.0.1",
                           node="n1", labels={"a": "1"}))
    w = store.watch_queue("n1")
    info = collect_controller_info(ctl, store=store, now=42)
    assert info["kind"] == "AntreaControllerInfo"
    assert info["connectedAgentNum"] == 1
    assert info["conditions"][0]["type"] == "ControllerHealthy"
    w.stop()
    assert collect_controller_info(ctl, store=store)["connectedAgentNum"] == 0


def test_controller_metrics_render():
    from antrea_tpu.apis import crd
    from antrea_tpu.controller.networkpolicy import NetworkPolicyController
    from antrea_tpu.dissemination import RamStore
    from antrea_tpu.observability.metrics import render_controller_metrics

    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    ctl.upsert_namespace(crd.Namespace(name="d", labels={}))
    text = render_controller_metrics(ctl, store=store)
    assert 'antrea_tpu_controller_objects{kind="network_policies"} 0' in text
    assert "antrea_tpu_controller_connected_agents 0" in text


def test_egress_qos_meters():
    """EgressQoS: per-Egress token buckets drop over-rate traffic at the
    egress boundary (the OVS-meter analog, pipeline.go EgressQoS)."""
    from antrea_tpu.apis.crd import LabelSelector
    from antrea_tpu.controller.egress import (
        EgressController,
        EgressPolicy,
        EgressQoSMeters,
        build_egress_table,
    )
    from antrea_tpu.controller.grouping import GroupEntityIndex

    idx = GroupEntityIndex()
    ec = EgressController(idx)
    ec.upsert(EgressPolicy(name="eg-fast", egress_ip="203.0.113.1",
                           pod_selector=LabelSelector.make({"t": "a"})))
    ec.upsert(EgressPolicy(name="eg-slow", egress_ip="203.0.113.2",
                           pod_selector=LabelSelector.make({"t": "b"}),
                           rate_pps=100, burst_pkts=150))
    assert ec.qos_limits() == {"eg-slow": (100, 150)}
    meters = EgressQoSMeters(ec.qos_limits())
    # Burst admits up to 150, then the bucket is empty.
    assert meters.admit("eg-slow", 120, now=0) == 120
    assert meters.admit("eg-slow", 100, now=0) == 30
    assert meters.dropped["eg-slow"] == 70
    # Refill at rate: 1s -> 100 tokens.
    assert meters.admit("eg-slow", 100, now=1) == 100
    # Unmetered egress admits everything.
    assert meters.admit("eg-fast", 10_000, now=1) == 10_000
    assert meters.admit(None, 5, now=1) == 5
    # Table name resolution feeds the meter key.
    from antrea_tpu.utils import ip as iputil

    table = build_egress_table([("10.0.0.5", "203.0.113.2", "eg-slow")])
    assert table.egress_name_for(iputil.ip_to_u32("10.0.0.5")) == "eg-slow"
    assert table.egress_name_for(iputil.ip_to_u32("10.0.0.6")) is None
