"""mTLS network dissemination wire (apiserver.go:97-99 + certificate/
analog): real X.509 PKI, mutual-TLS sockets, span-filtered event stream,
upstream realization reports — and rejection of unauthenticated peers."""

import socket
import ssl

import pytest

from antrea_tpu.apis import crd
from antrea_tpu.apis import controlplane as cp
from antrea_tpu.controller.networkpolicy import NetworkPolicyController
from antrea_tpu.controller.status import StatusAggregator
from antrea_tpu.datapath import OracleDatapath
from antrea_tpu.dissemination import RamStore
from antrea_tpu.dissemination.netwire import (
    Backoff,
    BackoffPolicy,
    DisseminationServer,
    NetAgent,
    make_ca,
)


def _world(tmp_path):
    certdir = str(tmp_path / "pki")
    make_ca(certdir)
    ctl = NetworkPolicyController()
    store = RamStore()
    ctl.subscribe(store.apply)
    agg = StatusAggregator(ctl)
    srv = DisseminationServer(store, certdir, status_aggregator=agg)
    ctl.upsert_namespace(crd.Namespace(name="default", labels={}))
    for node, ip in (("n1", "10.0.1.1"), ("n2", "10.0.2.1")):
        ctl.upsert_pod(crd.Pod(namespace="default", name=f"p-{node}", ip=ip,
                               node=node, labels={"app": "web"}))
    return certdir, ctl, store, agg, srv


def _policy(uid="P"):
    return crd.AntreaNetworkPolicy(
        uid=uid, name=uid, namespace="", tier_priority=250, priority=1,
        applied_to=[crd.AntreaAppliedTo(
            pod_selector=crd.LabelSelector.make({"app": "web"}),
            ns_selector=crd.LabelSelector.make())],
        rules=[crd.AntreaNPRule(direction=cp.Direction.IN,
                                action=cp.RuleAction.DROP,
                                peers=[crd.AntreaPeer(
                                    ip_block=crd.IPBlock("192.0.2.0/24"))])],
    )


def test_backoff_jitter_diverges_per_node():
    """The thundering-herd regression: 10k agents that lost the controller
    at the same instant must not redial in lockstep.  Two clients with
    IDENTICALLY seeded rngs (the worst case — fleet processes forked from
    one image can share PRNG state) but different node names must produce
    elementwise-diverging schedules, each still capped; and reset() must
    restart the exponential ladder without touching the node factor."""
    import random

    base, cap = 0.05, 2.0
    b1 = BackoffPolicy(base=base, cap=cap, rng=random.Random(7), node="n1")
    b2 = BackoffPolicy(base=base, cap=cap, rng=random.Random(7), node="n2")
    assert BackoffPolicy is Backoff  # the policy name is the class
    s1 = [b1.next_delay() for _ in range(12)]
    s2 = [b2.next_delay() for _ in range(12)]
    # Same seed, same attempt, same base — ONLY the node factor differs:
    # every element must diverge (pre-fix, these schedules were equal and
    # the whole fleet redialed on the same tick).
    assert all(a != b for a, b in zip(s1, s2))
    # Deterministic per node: rebuilding the policy reproduces the factor.
    assert Backoff(node="n1").node_factor == b1.node_factor
    assert b1.node_factor != b2.node_factor
    # Every delay respects the cap regardless of jitter (the factor only
    # shrinks or holds: nobody waits longer than an un-jittered client).
    for s in (s1, s2):
        assert all(0.0 < d <= cap for d in s)
    # The ladder still grows before the cap bites, and reset() restarts
    # it deterministically for the same rng state.
    b3 = Backoff(base=base, cap=cap, rng=random.Random(3), node="n1")
    first = b3.next_delay()
    later = [b3.next_delay() for _ in range(8)]
    assert max(later) > first  # exponential growth happened
    b3.reset()
    assert b3.attempt == 0
    assert b3.next_delay() <= base * b3.node_factor  # back to rung 0


def test_mtls_stream_and_status_roundtrip(tmp_path):
    certdir, ctl, store, agg, srv = _world(tmp_path)
    try:
        a1 = NetAgent("n1", srv.address, certdir,
                      OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4))
        a2 = NetAgent("n2", srv.address, certdir,
                      OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4))
        srv.wait_connected(2)  # acceptor thread registers both watchers
        ctl.upsert_antrea_policy(_policy())
        srv.pump()
        assert a1.pump() > 0 and a2.pump() > 0
        # The policy crossed the wire and compiled into the agent datapath.
        a1.sync_and_report()
        assert [p.uid for p in a1.agent.policy_set.policies] == ["P"]
        assert a1.agent.datapath.generation == 1
        # Status flowed back over the SAME TLS channel: n1 realized, n2 lags.
        srv.pump()
        st = agg.status_of("P")
        assert st.current_nodes == 1 and st.desired_nodes == 2
        assert st.phase == "Realizing"
        a2.sync_and_report()
        srv.pump()
        assert agg.status_of("P").phase == "Realized"
        a1.close(); a2.close()
    finally:
        srv.close()


def test_unauthenticated_client_rejected(tmp_path):
    """A client WITHOUT a CA-signed certificate fails the handshake: the
    server requires client certs (mutual TLS, CERT_REQUIRED)."""
    certdir, ctl, store, agg, srv = _world(tmp_path)
    try:
        raw = socket.create_connection(tuple(srv.address))
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE  # rogue client: no cert, no CA
        with pytest.raises(ssl.SSLError):
            tls = ctx.wrap_socket(raw, server_hostname="localhost")
            srv.pump()  # server side: handshake fails, connection dropped
            tls.sendall(b'{"hello": "evil"}\n')
            srv.pump()
            # force the alert to surface client-side
            tls.recv(1)
            tls.recv(1)
        raw.close()
        assert "evil" not in srv._conns
    finally:
        srv.close()


def test_agent_rejects_wrong_ca(tmp_path):
    """An agent verifying against a DIFFERENT CA refuses the server
    certificate — the server cannot feed an agent it cannot prove itself
    to (the apiserver CA-rotation contract)."""
    certdir, ctl, store, agg, srv = _world(tmp_path)
    other = str(tmp_path / "otherpki")
    make_ca(other, cn="rogue-ca")
    try:
        with pytest.raises(ssl.SSLError):
            NetAgent("n1", srv.address, other,
                     OracleDatapath(flow_slots=1 << 8, aff_slots=1 << 4))
            srv.pump()
    finally:
        srv.close()


def test_reachability_end_to_end_over_netwire(tmp_path):
    """End-to-end REACHABILITY over the production transport: the
    controller computes spans, the mTLS wire disseminates them, each
    NetAgent reconciles its REAL datapath, and packets stepped through
    those datapaths get the hand-authored verdicts — then a policy
    DELETE crosses the wire and the same packets re-classify allow.
    (apiserver.go:97-99: dissemination has exactly one path, this one.)"""
    import numpy as np

    from antrea_tpu.compiler.compile import ACT_ALLOW, ACT_DROP
    from antrea_tpu.packet import Packet, PacketBatch
    from antrea_tpu.utils import ip as iputil

    certdir, ctl, store, agg, srv = _world(tmp_path)
    try:
        agents = {
            node: NetAgent(node, srv.address, certdir,
                           OracleDatapath(flow_slots=1 << 8,
                                          aff_slots=1 << 4))
            for node in ("n1", "n2")
        }
        srv.wait_connected(2)
        ctl.upsert_antrea_policy(_policy())  # DROP 192.0.2.0/24 -> app=web
        srv.pump()
        for a in agents.values():
            assert a.pump() > 0
            a.sync_and_report()
        srv.pump()
        assert agg.status_of("P").phase == "Realized"

        def verdicts(agent, cases):
            batch = PacketBatch.from_packets([
                Packet(src_ip=iputil.ip_to_u32(s),
                       dst_ip=iputil.ip_to_u32(d),
                       proto=6, src_port=41000, dst_port=80)
                for s, d in cases
            ])
            return list(np.asarray(agent.agent.datapath.step(batch, 1).code))

    # Hand-authored verdicts: the denied /24 drops on each node's web
    # pod; other sources pass (default allow).
        assert verdicts(agents["n1"], [
            ("192.0.2.7", "10.0.1.1"), ("10.0.2.1", "10.0.1.1"),
        ]) == [ACT_DROP, ACT_ALLOW]
        assert verdicts(agents["n2"], [
            ("192.0.2.9", "10.0.2.1"), ("10.0.1.1", "10.0.2.1"),
        ]) == [ACT_DROP, ACT_ALLOW]

        # Withdrawal crosses the wire: the drop disappears.
        ctl.delete_policy("P")
        srv.pump()
        for a in agents.values():
            a.pump()
            a.sync_and_report()
        assert verdicts(agents["n1"], [("192.0.2.7", "10.0.1.1")]) == [
            ACT_ALLOW]
        for a in agents.values():
            a.close()
    finally:
        srv.close()
