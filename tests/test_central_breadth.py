"""Central/agent breadth tests: ExternalIPPool, ServiceExternalIP with
failover, BGP reconciliation, ClusterIdentity, stats aggregation,
NodeLatencyMonitor — reference semantics cited in each module."""

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.bgp import BgpController, BgpPeer, BgpPolicy
from antrea_tpu.agent.memberlist import MemberlistCluster
from antrea_tpu.agent.monitortool import NodeLatencyMonitor
from antrea_tpu.clusteridentity import get_or_create_cluster_identity
from antrea_tpu.controller.externalippool import (
    ExternalIPPool,
    ExternalIPPoolController,
    IPRange,
    PoolExhaustedError,
)
from antrea_tpu.controller.serviceexternalip import ServiceExternalIPController
from antrea_tpu.controller.stats import StatsAggregator
from antrea_tpu.datapath.interface import DatapathStats


# ---- ExternalIPPool ---------------------------------------------------------


def _pool(name="pool-a", start="10.100.0.1", end="10.100.0.3"):
    return ExternalIPPool(name=name, ip_ranges=[IPRange(start=start, end=end)])


def test_pool_allocate_release_usage():
    c = ExternalIPPoolController()
    c.upsert(_pool())
    a = c.allocate("pool-a", "egress:a")
    b = c.allocate("pool-a", "egress:b")
    assert a == "10.100.0.1" and b == "10.100.0.2"
    assert c.allocate("pool-a", "egress:a") == a  # idempotent per owner
    assert c.usage("pool-a") == {"total": 3, "used": 2}
    assert c.release("pool-a", "egress:a") == a
    assert c.usage("pool-a")["used"] == 1
    c.allocate("pool-a", "c")
    c.allocate("pool-a", "d")
    with pytest.raises(PoolExhaustedError):
        c.allocate("pool-a", "e")


def test_pool_pinned_ip_and_validation():
    c = ExternalIPPoolController()
    c.upsert(ExternalIPPool("p", ip_ranges=[IPRange(cidr="10.200.0.0/30")]))
    assert c.allocate("p", "x", ip="10.200.0.2") == "10.200.0.2"
    with pytest.raises(ValueError):
        c.allocate("p", "y", ip="10.200.0.2")  # taken
    with pytest.raises(ValueError):
        c.allocate("p", "z", ip="10.9.9.9")  # outside pool
    with pytest.raises(ValueError):  # shrink strands the allocation
        c.upsert(ExternalIPPool("p", ip_ranges=[
            IPRange(start="10.200.0.0", end="10.200.0.1")]))
    with pytest.raises(ValueError):  # delete with live allocations
        c.delete("p")
    c.release("p", "x")
    c.delete("p")


# ---- ServiceExternalIP ------------------------------------------------------


def test_service_external_ip_failover():
    pools = ExternalIPPoolController()
    pools.upsert(_pool())
    sc = ServiceExternalIPController(pools)
    ip = sc.assign("default/web", "pool-a")
    assert sc.assign("default/web", "pool-a") == ip  # idempotent
    nodes = {"node-a": {}, "node-b": {}, "node-c": {}}
    a1 = sc.owner_for("default/web", {"node-a", "node-b", "node-c"}, nodes)
    assert a1.owner in nodes
    # The owner fails: election re-evaluates among survivors (memberlist
    # event -> re-hash, service_external_ip_controller.go failover).
    survivors = set(nodes) - {a1.owner}
    a2 = sc.owner_for("default/web", survivors, nodes)
    assert a2.owner in survivors
    # All nodes gone: unhosted.
    assert sc.owner_for("default/web", set(), nodes).owner is None
    assert sc.unassign("default/web") == ip
    assert pools.usage("pool-a")["used"] == 0


def test_service_external_ip_pool_scoping():
    pools = ExternalIPPoolController()
    from antrea_tpu.apis.crd import LabelSelector

    pools.upsert(ExternalIPPool(
        "edge", ip_ranges=[IPRange(start="10.101.0.1", end="10.101.0.9")],
        node_selector=LabelSelector.make({"role": "edge"}),
    ))
    sc = ServiceExternalIPController(pools)
    sc.assign("default/lb", "edge")
    nodes = {"node-a": {"role": "edge"}, "node-b": {"role": "core"}}
    a = sc.owner_for("default/lb", {"node-a", "node-b"}, nodes)
    assert a.owner == "node-a"  # only the selector-matching node hosts


def test_service_external_ip_assign_rollback():
    """A failed pool/pin change must leave the previous assignment intact
    (release-then-reallocate with rollback)."""
    pools = ExternalIPPoolController()
    pools.upsert(_pool())
    sc = ServiceExternalIPController(pools)
    ip = sc.assign("default/web", "pool-a")
    with pytest.raises(KeyError):
        sc.assign("default/web", "no-such-pool")
    assert sc.assign("default/web", "pool-a") == ip  # still held
    assert pools.usage("pool-a")["used"] == 1


def test_pool_overlapping_ranges_rejected():
    c = ExternalIPPoolController()
    with pytest.raises(ValueError):
        c.upsert(ExternalIPPool("p", ip_ranges=[
            IPRange(cidr="10.0.0.0/30"),
            IPRange(start="10.0.0.1", end="10.0.0.2"),
        ]))


def test_pool_cidr_excludes_network_and_broadcast():
    c = ExternalIPPoolController()
    c.upsert(ExternalIPPool("p", ip_ranges=[IPRange(cidr="10.50.0.0/29")]))
    ips = {c.allocate("p", f"o{i}") for i in range(6)}
    assert "10.50.0.0" not in ips and "10.50.0.7" not in ips
    with pytest.raises(PoolExhaustedError):
        c.allocate("p", "o9")


def test_egress_allocates_from_pool():
    """crd Egress spec.externalIPPool: the controller allocates the SNAT IP
    from the pool and releases on delete."""
    from antrea_tpu.apis.crd import LabelSelector
    from antrea_tpu.controller.egress import EgressController, EgressPolicy
    from antrea_tpu.controller.grouping import GroupEntityIndex

    pools = ExternalIPPoolController()
    pools.upsert(_pool())
    idx = GroupEntityIndex()
    ec = EgressController(idx, pools=pools)
    ec.upsert(EgressPolicy(
        name="eg-1", pod_selector=LabelSelector.make({"team": "a"}),
        external_ip_pool="pool-a",
    ))
    assert pools.usage("pool-a")["used"] == 1
    with pytest.raises(KeyError):  # unknown pool: previous state intact
        ec.upsert(EgressPolicy(name="eg-2", external_ip_pool="nope"))
    with pytest.raises(ValueError):  # neither ip nor pool
        ec.upsert(EgressPolicy(name="eg-3"))
    ec.delete("eg-1")
    assert pools.usage("pool-a")["used"] == 0

    # Spec edits must not leak allocations: pool -> static releases; a
    # static IP WITH a pool pins that address in the pool.
    ec.upsert(EgressPolicy(name="eg-4", external_ip_pool="pool-a"))
    ec.upsert(EgressPolicy(name="eg-4", egress_ip="9.9.9.9"))
    assert pools.usage("pool-a")["used"] == 0
    ec.upsert(EgressPolicy(name="eg-5", egress_ip="10.100.0.2",
                           external_ip_pool="pool-a"))
    assert pools.usage("pool-a")["used"] == 1
    with pytest.raises(ValueError):  # pinned IP already taken
        ec.upsert(EgressPolicy(name="eg-6", egress_ip="10.100.0.2",
                               external_ip_pool="pool-a"))


# ---- BGP --------------------------------------------------------------------


def test_bgp_reconcile_advertise_withdraw():
    events = []
    peer1 = BgpPeer("192.0.2.1", 64512)
    peer2 = BgpPeer("192.0.2.2", 64513)
    ctl = BgpController("node-a", speaker=lambda p, a, pfx: events.append((p.address, a, pfx)))
    ctl.set_policy(BgpPolicy(
        name="bgp", local_asn=64500, peers=[peer1, peer2],
        advertise_service_ips=True, advertise_pod_cidrs=True,
    ))
    ctl.set_pod_cidrs({"10.10.0.0/24"})
    ctl.set_service_ips({"10.96.0.10"})
    assert ctl.rib() == {"10.10.0.0/24", "10.96.0.10/32"}
    assert ctl.advertised(peer1) == ctl.rib()
    assert ctl.sessions()[0]["advertised"] == 2
    events.clear()
    # Service IP withdrawn -> one withdraw per peer, nothing else.
    ctl.set_service_ips(set())
    assert sorted(events) == [
        ("192.0.2.1", "withdraw", "10.96.0.10/32"),
        ("192.0.2.2", "withdraw", "10.96.0.10/32"),
    ]
    # Peer removed from the policy -> full withdraw for it.
    events.clear()
    ctl.set_policy(BgpPolicy(name="bgp", local_asn=64500, peers=[peer1],
                             advertise_pod_cidrs=True))
    assert ("192.0.2.2", "withdraw", "10.10.0.0/24") in events
    # Policy deleted -> RIB empty.
    ctl.set_policy(None)
    assert ctl.rib() == set() and ctl.sessions() == []


# ---- ClusterIdentity --------------------------------------------------------


def test_cluster_identity_minted_once(tmp_path):
    from antrea_tpu.native import ConfigStore

    s1 = ConfigStore(str(tmp_path / "conf.db"))
    ident = get_or_create_cluster_identity(s1)
    assert len(ident) == 36
    s2 = ConfigStore(str(tmp_path / "conf.db"))
    assert get_or_create_cluster_identity(s2) == ident


# ---- stats aggregation ------------------------------------------------------


def test_stats_aggregator_sums_nodes():
    agg = StatsAggregator()
    agg.report("node-a", DatapathStats(
        ingress={"np-1/in/0": 10}, egress={"np-1/out/0": 5},
        default_allow=7, default_deny=3,
    ))
    agg.report("node-b", DatapathStats(
        ingress={"np-1/in/0": 1, "np-2/in/0": 2}, egress={},
        default_allow=1, default_deny=0,
    ))
    assert agg.rule_stats()["np-1/in/0"] == 11
    assert agg.policy_stats() == {"np-1": 16, "np-2": 2}
    s = agg.summary()
    assert s["nodes"] == 2 and s["defaultAllow"] == 8 and s["defaultDeny"] == 3
    # Re-report replaces (cumulative counters, not deltas).
    agg.report("node-b", DatapathStats(ingress={"np-2/in/0": 9}, egress={}))
    assert agg.policy_stats() == {"np-1": 15, "np-2": 9}
    agg.drop_node("node-a")
    assert agg.summary()["nodes"] == 1


# ---- NodeLatencyMonitor -----------------------------------------------------


def test_node_latency_monitor():
    rtts = {"10.0.0.2": 0.004, "10.0.0.3": None}
    mon = NodeLatencyMonitor("node-a", probe=rtts.get, interval_s=60)
    mon.upsert_peer("node-b", "10.0.0.2")
    mon.upsert_peer("node-c", "10.0.0.3")
    mon.upsert_peer("node-a", "10.0.0.1")  # self: ignored
    assert mon.tick(now=100) == 2
    assert mon.tick(now=130) == 0  # interval not elapsed
    rtts["10.0.0.2"] = 0.002
    assert mon.tick(now=170) == 2
    rep = mon.report()
    assert rep["nodeName"] == "node-a"
    by = {r["nodeName"]: r for r in rep["peerNodeLatencyStats"]}
    assert by["node-b"]["minRTT"] == 0.002 and by["node-b"]["maxRTT"] == 0.004
    assert by["node-b"]["lost"] == 0 and by["node-c"]["lost"] == 2
    assert by["node-c"]["lastMeasuredRTT"] is None
    mon.delete_peer("node-c")
    assert len(mon.report()["peerNodeLatencyStats"]) == 1


def test_externalippool_ipv6_ranges():
    """Dual-stack ExternalIPPool (the reference's ipAllocator handles v6
    ranges): allocation, pinning, release and usage over a v6 CIDR; the
    network (anycast) address is excluded; v4 pools unchanged."""
    from antrea_tpu.controller.externalippool import (
        ExternalIPPool, ExternalIPPoolController, IPRange,
    )

    c = ExternalIPPoolController()
    c.upsert(ExternalIPPool(name="p6", ip_ranges=[
        IPRange(cidr="2001:db8:ee::/126"),
        IPRange(start="2001:db8:ff::10", end="2001:db8:ff::11"),
    ]))
    got = [c.allocate("p6", f"o{i}") for i in range(5)]
    assert got == [
        "2001:db8:ee::1", "2001:db8:ee::2", "2001:db8:ee::3",
        "2001:db8:ff::10", "2001:db8:ff::11",
    ]
    import pytest as _pytest
    from antrea_tpu.controller.externalippool import PoolExhaustedError

    with _pytest.raises(PoolExhaustedError):
        c.allocate("p6", "overflow")
    assert c.usage("p6") == {"total": 5, "used": 5}
    assert c.release("p6", "o0") == "2001:db8:ee::1"
    # Pinned v6 allocation.
    assert c.allocate("p6", "pin", ip="2001:db8:ee::1") == "2001:db8:ee::1"
