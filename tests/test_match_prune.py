"""Two-level aggregated-bitmap match pruning (ISSUE 10 tentpole,
ops/match round 7): bitwise verdict/attribution parity of the pruned
path against the unpruned kernel and the scalar oracle, the adversarial
worlds (100% fallback, crafted aggregate false positive), the
aggregate/incidence consistency property (deltas + mesh word-sharding
included), HLO bit-identity at prune_budget=0, canary/audit
certification of the pruned path, and the K-budget autotuner."""

import numpy as np
import pytest

from antrea_tpu.apis.controlplane import Direction, GroupMember, RuleAction
from antrea_tpu.compiler.compile import compile_policy_set
from antrea_tpu.config import ConfigError
from antrea_tpu.datapath import OracleDatapath, TpuflowDatapath
from antrea_tpu.models import pipeline as pl
from antrea_tpu.observability.metrics import render_metrics
from antrea_tpu.ops import match as m
from antrea_tpu.oracle import Oracle
from antrea_tpu.simulator import gen_cluster, gen_traffic

from fixtures_reachability import _ps, acnp, ag, atg, peer, rule

import jax.numpy as jnp

PARITY_KEYS = ("code", "egress_code", "egress_rule", "ingress_code",
               "ingress_rule")


def _classify(drs, meta, tr, fused=False, **kw):
    out = m._classify_jit(
        drs,
        m.flip_ips(tr.src_ip),
        m.flip_ips(tr.dst_ip),
        tr.proto.astype(np.int32),
        tr.dst_port.astype(np.int32),
        meta=meta, fused=fused, **kw,
    )
    return {k: np.asarray(v) for k, v in out.items()}


def _assert_parity(o_ref, o_pruned, ctx):
    for k in PARITY_KEYS:
        assert np.array_equal(o_ref[k], o_pruned[k]), (ctx, k)


# ---------------------------------------------------------------------------
# Kernel parity: pruned vs unpruned vs oracle, fallback path included
# ---------------------------------------------------------------------------


def test_pruned_kernel_parity_and_fallback():
    """A multi-superblock world at K=1 exercises the pow2-rung fallback;
    K=4 exercises the pure candidate path — both must be bitwise equal
    to the unpruned kernel, and spot-equal to the scalar oracle."""
    cluster = gen_cluster(2500, seed=2)
    cps = compile_policy_set(cluster.ps)
    tr = gen_traffic(cluster.pod_ips, batch=192, seed=3)
    drs0, meta0 = m.to_device(cps)
    o0 = _classify(drs0, meta0, tr)
    saw_fb = False
    for k in (1, 4):
        drs1, meta1 = m.to_device(cps, prune_budget=k)
        assert drs1.ingress.at.agg is not None
        o1 = _classify(drs1, meta1, tr)
        _assert_parity(o0, o1, f"K={k}")
        saw_fb = saw_fb or o1["prune_fb"].any()
    assert saw_fb, "the world never exercised the fallback redispatch"
    oracle = Oracle(cluster.ps)
    for i in range(0, tr.size, 4):
        assert int(o1["code"][i]) == int(oracle.classify(tr.packet(i)).code)


def test_pruned_fused_consumer_parity():
    cluster = gen_cluster(400, seed=5)
    cps = compile_policy_set(cluster.ps)
    tr = gen_traffic(cluster.pod_ips, batch=128, seed=6)
    drs0, meta0 = m.to_device(cps)
    drs1, meta1 = m.to_device(cps, prune_budget=2)
    o0 = _classify(drs0, meta0, tr)
    o1 = _classify(drs1, meta1, tr, fused=True)
    _assert_parity(o0, o1, "fused")


def test_summary_only_defaults_and_skips():
    """summary_only (the PH_CLS_SUM surface) must report the same skip
    mask as the full pruned walk, take zero fallbacks, and resolve every
    live lane to the default-verdict image."""
    cluster = gen_cluster(400, seed=5)
    cps = compile_policy_set(cluster.ps)
    tr = gen_traffic(cluster.pod_ips, batch=96, seed=6)
    drs1, meta1 = m.to_device(cps, prune_budget=2)
    o_full = _classify(drs1, meta1, tr)
    o_sum = _classify(drs1, meta1, tr, summary_only=True)
    assert np.array_equal(o_full["prune_skip"], o_sum["prune_skip"])
    assert not o_sum["prune_fb"].any()
    # Skip lanes short-circuit identically in both modes.
    sk = o_sum["prune_skip"].astype(bool)
    assert np.array_equal(o_full["code"][sk], o_sum["code"][sk])


# ---------------------------------------------------------------------------
# Adversarial worlds
# ---------------------------------------------------------------------------


def _dense_ps(n_rules: int):
    """Every rule applies to `web` from ANY peer on any service: every
    incidence word is nonzero in all three dimensions for a matching
    probe, so every superblock is a candidate (the 100%-fallback world
    at small K)."""
    rules = [rule(Direction.IN, peer(), action=RuleAction.ALLOW)
             for _ in range(n_rules)]
    return _ps(
        [acnp("dense", ["at_web"], rules)],
        applied_groups=[atg("at_web", "web")],
    )


def test_dense_world_full_fallback_parity():
    # > 1024 ingress rules => at least 2 superblocks; every one a
    # candidate for web-bound traffic, so K=1 lanes ALL fall back.
    ps = _dense_ps(1100)
    cps = compile_policy_set(ps)
    from antrea_tpu.packet import Packet, PacketBatch

    pkts = [Packet(src_ip=0x0A0A0000 + i, dst_ip=0x0A0A0007, proto=6,
                   src_port=31000 + i, dst_port=80) for i in range(64)]
    batch = PacketBatch.from_packets(pkts)
    tr = batch  # same column surface as gen_traffic's batch
    drs0, meta0 = m.to_device(cps)
    drs1, meta1 = m.to_device(cps, prune_budget=1)
    assert drs1.ingress.at.agg.shape[1] >= 2
    o0 = _classify(drs0, meta0, tr)
    o1 = _classify(drs1, meta1, tr)
    _assert_parity(o0, o1, "dense")
    # 100% fallback: the degenerate case degrades to the unpruned
    # dispatch shape (ONE bounded full-width redispatch covering every
    # lane), never to a wrong verdict.
    assert o1["prune_fb"].all()
    assert not o1["prune_skip"].any()
    oracle = Oracle(ps)
    assert int(o1["code"][0]) == int(oracle.classify(pkts[0]).code) == 0
    # Both engines: the datapaths agree step-for-step on this world too.
    dp = TpuflowDatapath(ps, flow_slots=1 << 8, aff_slots=1 << 6,
                         miss_chunk=16, prune_budget=1, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    od = OracleDatapath(ps, flow_slots=1 << 8, prune_budget=1,
                        canary_probes=0, flightrec_slots=0,
                        realization_slots=0)
    r, ro = dp.step(batch, now=1), od.step(batch, now=1)
    assert list(r.code) == list(ro.code)
    assert dp.prune_stats()["fallbacks_total"] == batch.size


def test_aggregate_false_positive_world():
    """Per-dimension aggregate bits all set on the same word, 3-way AND
    empty: the candidate gather must find nothing and the lane must take
    the DEFAULT verdict with zero fallbacks — a false positive costs a
    narrow gather, never a verdict."""
    ps = _ps(
        [acnp("fp", ["at_web"], [
            rule(Direction.IN, peer("g_a"), action=RuleAction.DROP),
        ]),
         acnp("fp2", ["at_db"], [
             rule(Direction.IN, peer("g_b"), action=RuleAction.DROP),
         ])],
        addr_groups=[ag("g_a", "client"), ag("g_b", "other")],
        applied_groups=[atg("at_web", "web"), atg("at_db", "db")],
    )
    cps = compile_policy_set(ps)
    from antrea_tpu.packet import Packet, PacketBatch

    # src = other (matches ONLY fp2's peer bit), dst = web (matches ONLY
    # fp's appliedTo bit): every dimension's aggregate word is nonzero,
    # the AND is empty.
    pkt = Packet(src_ip=0x0A0A0105, dst_ip=0x0A0A0007, proto=6,
                 src_port=31000, dst_port=80)
    batch = PacketBatch.from_packets([pkt] * 8)
    drs1, meta1 = m.to_device(cps, prune_budget=4)
    o1 = _classify(drs1, meta1, batch)
    drs0, meta0 = m.to_device(cps)
    o0 = _classify(drs0, meta0, batch)
    _assert_parity(o0, o1, "false-positive")
    assert not o1["prune_skip"].any()  # the aggregate AND was NOT zero
    assert not o1["prune_fb"].any()
    assert int(o1["code"][0]) == int(Oracle(ps).classify(pkt).code) == 0
    assert int(o1["ingress_rule"][0]) == -1  # default, no attribution


# ---------------------------------------------------------------------------
# Aggregate/incidence consistency property (build_agg is the invariant)
# ---------------------------------------------------------------------------


def _assert_agg_consistent(drs):
    for dd in (drs.ingress, drs.egress):
        for tab in (dd.at, dd.peer, dd.svc):
            inc = np.asarray(tab.inc)
            assert inc.shape[1] % m.AGG_BLOCK == 0
            assert np.array_equal(np.asarray(tab.agg), m.build_agg(inc))


def test_agg_rebuilds_from_incidence_after_deltas_and_sharding():
    cluster = gen_cluster(300, seed=7)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                         miss_chunk=16, prune_budget=2, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    _assert_agg_consistent(dp._drs)
    # O(1) group delta: tables untouched, aggregate still consistent,
    # and the DELTA path (not a recompile) was actually taken.
    name = next(iter(dp._group_members))
    dp.apply_group_delta(name, added_ips=["10.99.0.1"], removed_ips=[])
    assert dp._n_deltas > 0
    _assert_agg_consistent(dp._drs)
    # Recompile fold (install_bundle) rebuilds both levels together.
    dp.install_bundle(cluster.ps)
    _assert_agg_consistent(dp._drs)

    # Mesh word-sharding: the global tables stay consistent AND each
    # rule shard's slice is superblock-aligned (W/n_rule % 32 == 0), so
    # per-shard aggregates cover exactly their own incidence words.
    from antrea_tpu.parallel.meshpath import MeshDatapath

    md = MeshDatapath(cluster.ps, n_data=2, n_rule=2, flow_slots=1 << 8,
                      aff_slots=1 << 6, miss_chunk=16, prune_budget=2,
                      canary_probes=0, flightrec_slots=0,
                      realization_slots=0)
    _assert_agg_consistent(md._drs)
    for dd in (md._drs.ingress, md._drs.egress):
        w = dd.at.inc.shape[1]
        s = dd.at.agg.shape[1]
        assert w % (2 * m.AGG_BLOCK) == 0  # n_rule=2, dual-level multiple
        assert s % 2 == 0 and s * m.AGG_BLOCK == w
        # Shard d's aggregate slice == build_agg of shard d's inc slice.
        inc = np.asarray(dd.at.inc)
        agg = np.asarray(dd.at.agg)
        for d in range(2):
            lo, hi = d * (w // 2), (d + 1) * (w // 2)
            assert np.array_equal(
                agg[:, d * (s // 2):(d + 1) * (s // 2)],
                m.build_agg(inc[:, lo:hi]))


def test_group_delta_pruned_parity_both_engines():
    """Membership deltas must patch the aggregate level too: fresh
    5-tuples touching the added/removed member classify identically on
    the pruned kernel engine and the scalar oracle engine."""
    cluster = gen_cluster(300, seed=8)
    kw = dict(flow_slots=1 << 8, aff_slots=1 << 6, canary_probes=0,
              flightrec_slots=0, realization_slots=0)
    dp = TpuflowDatapath(cluster.ps, miss_chunk=16, prune_budget=2, **kw)
    od = OracleDatapath(cluster.ps, prune_budget=2, **kw)
    name = next(iter(dp._group_members))
    for eng in (dp, od):
        eng.apply_group_delta(name, added_ips=["10.77.3.9"],
                              removed_ips=[])
    assert dp._n_deltas > 0  # the O(1) slot path, not a recompile
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=9)
    # Aim half the probes AT the new member (both directions).
    tr.src_ip[:16] = 0x0A4D0309
    tr.dst_ip[16:32] = 0x0A4D0309
    r, ro = dp.step(tr, now=1), od.step(tr, now=1)
    assert list(r.code) == list(ro.code)
    assert list(r.ingress_rule) == list(ro.ingress_rule)
    assert list(r.egress_rule) == list(ro.egress_rule)
    # Removal exercises the CLEAR slots (stale aggregate bits are legal
    # false positives resolved by the candidate gather's full words).
    for eng in (dp, od):
        eng.apply_group_delta(name, added_ips=[],
                              removed_ips=["10.77.3.9"])
    r2, ro2 = dp.step(tr, now=2), od.step(tr, now=2)
    assert list(r2.code) == list(ro2.code)


# ---------------------------------------------------------------------------
# HLO identity at prune_budget=0 + engine-mode parity
# ---------------------------------------------------------------------------


def test_step_hlo_bit_identical_with_prune_disabled():
    """prune_budget=0 (explicit) must compile the EXACT default program:
    no aggregate tables, no extra outputs, no candidate/fallback ops."""
    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=5)
    a = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                        miss_chunk=16, canary_probes=0,
                        flightrec_slots=0, realization_slots=0)
    b = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                        miss_chunk=16, prune_budget=0, canary_probes=0,
                        flightrec_slots=0, realization_slots=0)
    assert b._drs.ingress.at.agg is None

    def lower_text(dp):
        z = jnp.zeros(8, jnp.int32)
        return pl.pipeline_step.lower(
            dp._state, dp._drs, dp._dsvc, z, z, z, z, z,
            jnp.int32(0), jnp.int32(0), meta=dp._meta,
        ).as_text()

    assert lower_text(a) == lower_text(b)
    # And the pruned program is genuinely a different (two-level) one.
    c = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                        miss_chunk=16, prune_budget=2, canary_probes=0,
                        flightrec_slots=0, realization_slots=0)
    assert lower_text(c) != lower_text(a)


def test_async_mode_pruned_parity():
    cluster = gen_cluster(300, seed=10)
    kw = dict(flow_slots=1 << 8, aff_slots=1 << 6, async_slowpath=True,
              miss_queue_slots=1 << 10, drain_batch=64, canary_probes=0,
              flightrec_slots=0, realization_slots=0)
    dp = TpuflowDatapath(cluster.ps, miss_chunk=16, prune_budget=2, **kw)
    od = OracleDatapath(cluster.ps, prune_budget=2, **kw)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=11)
    for eng in (dp, od):
        eng.step(tr, now=1)
        eng.drain_slowpath(now=2)
    r, ro = dp.step(tr, now=3), od.step(tr, now=3)
    assert list(r.code) == list(ro.code)
    assert list(r.est) == list(ro.est)
    assert dp.prune_stats()["classified_total"] > 0  # the drain pruned


def test_rule_sharded_prune_observables_replicated():
    """Under rule sharding the prune observables must be COMBINED over
    the rule axis (skip=AND, fb=OR, cand=per-shard MAX), not one
    arbitrary shard's locals: skip must equal the single-chip mask
    exactly, cand must bound the global count from both sides, and no
    lane the global budget covers may report a fallback."""
    from antrea_tpu.parallel.mesh import make_mesh, make_sharded_classifier

    cluster = gen_cluster(2500, seed=2)
    cps = compile_policy_set(cluster.ps)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=3)
    drs1, meta1 = m.to_device(cps, prune_budget=2)
    o1 = _classify(drs1, meta1, tr)
    fn, _drs = make_sharded_classifier(cps, make_mesh(1, 2),
                                       prune_budget=2)
    om = fn(m.flip_ips(tr.src_ip), m.flip_ips(tr.dst_ip),
            tr.proto.astype(np.int32), tr.dst_port.astype(np.int32))
    om = {k: np.asarray(v) for k, v in om.items()}
    assert np.array_equal(om["code"], o1["code"])
    assert np.array_equal(om["prune_skip"], o1["prune_skip"])
    cand_s, cand_g = om["prune_cand"], o1["prune_cand"]
    # max-per-shard is sandwiched by [ceil(global/2), global] on 2 shards.
    assert (cand_s <= cand_g).all() and (2 * cand_s >= cand_g).all()
    # A lane the GLOBAL budget covers can never fall back on any shard.
    assert not om["prune_fb"][cand_g <= 2].any()


def test_mesh_mode_pruned_parity():
    from antrea_tpu.parallel.meshpath import MeshDatapath

    cluster = gen_cluster(300, seed=12)
    kw = dict(flow_slots=1 << 8, aff_slots=1 << 6, miss_chunk=16,
              prune_budget=2, canary_probes=0, flightrec_slots=0,
              realization_slots=0)
    md = MeshDatapath(cluster.ps, n_data=2, n_rule=2, **kw)
    sd = TpuflowDatapath(cluster.ps, **kw)
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=13)
    rm, rs = md.step(tr, now=1), sd.step(tr, now=1)
    assert list(rm.code) == list(rs.code)
    assert list(rm.ingress_rule) == list(rs.ingress_rule)
    assert list(rm.egress_rule) == list(rs.egress_rule)
    assert md.prune_stats()["classified_total"] > 0


def test_toservices_svcref_pruned_parity():
    """The egress svc dimension's SECOND (ServiceReference) probe ORs a
    second aggregate row and a second candidate gather — the frontends
    of a referenced Service must still drop, direct-to-endpoint traffic
    must not, bitwise against the scalar engine."""
    import test_toservices as t
    from antrea_tpu.packet import PacketBatch

    dp = TpuflowDatapath(t._ps(), t.SVCS, flow_slots=1 << 10,
                         aff_slots=1 << 4, node_ips=[t.NODE_IP],
                         node_name="n1", miss_chunk=16, prune_budget=2,
                         canary_probes=0, flightrec_slots=0,
                         realization_slots=0)
    od = OracleDatapath(t._ps(), t.SVCS, flow_slots=1 << 10,
                        aff_slots=1 << 4, node_ips=[t.NODE_IP],
                        node_name="n1", canary_probes=0,
                        flightrec_slots=0, realization_slots=0)
    probes = [t._pkt(t.CLIENT, "10.96.0.10", 5432),
              t._pkt(t.CLIENT, t.NODE_IP, 30032),
              t._pkt(t.CLIENT, t.DB_EP, 5432),
              t._pkt(t.CLIENT, "10.96.0.11", 80),
              t._pkt("10.0.8.8", "10.96.0.10", 5432)]
    r = dp.step(PacketBatch.from_packets(probes), now=5)
    ro = od.step(PacketBatch.from_packets(probes), now=5)
    assert list(r.code) == list(ro.code) == [1, 1, 0, 0, 0]
    assert r.egress_rule == ro.egress_rule


# ---------------------------------------------------------------------------
# Planes certify the pruned path; observability; autotuner
# ---------------------------------------------------------------------------


def test_canary_and_audit_certify_pruned_path():
    cluster = gen_cluster(300, seed=14)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                         miss_chunk=16, prune_budget=2, canary_probes=16)
    assert dp._meta.match.prune_budget == 2  # the canary walks THIS meta
    tr = gen_traffic(cluster.pod_ips, batch=64, seed=15)
    dp.step(tr, now=1)
    gen0 = dp.generation
    dp.install_bundle(cluster.ps)  # canary-gated through the pruned walk
    cp = dp.commit_stats()
    assert dp.generation == gen0 + 1 and not cp["degraded"]
    assert cp["canary_probes_total"] > 0
    assert cp["canary_mismatches_total"] == 0
    dp.audit_scan(now=2, full=True)  # fresh re-proof through the pruned walk
    au = dp.audit_stats()
    assert au["entries_total"] > 0
    assert au["repairs_total"] == 0 and not au["divergences"]


def test_prune_metrics_rendered():
    # Same world/shapes as test_group_delta_pruned_parity_both_engines
    # on purpose (shared jit cache keeps the suite fast).
    cluster = gen_cluster(300, seed=8)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                         miss_chunk=16, prune_budget=2, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    dp.step(gen_traffic(cluster.pod_ips, batch=64, seed=17), now=1)
    txt = render_metrics(dp, node="n")
    for fam in ("antrea_tpu_match_prune_skips_total",
                "antrea_tpu_match_prune_fallbacks_total",
                "antrea_tpu_match_prune_budget",
                "antrea_tpu_match_prune_retunes_total",
                "antrea_tpu_match_prune_candidate_superblocks_bucket"):
        assert fam in txt, fam
    # Off instances expose NO prune families (plane-scoped surface).
    off = TpuflowDatapath(cluster.ps, flow_slots=1 << 8, aff_slots=1 << 6,
                          miss_chunk=16, canary_probes=0,
                          flightrec_slots=0, realization_slots=0)
    assert off.prune_stats() is None
    assert "match_prune" not in render_metrics(off, node="n")


def test_prune_autotuner_unit():
    t = m.PruneAutotuner(4)
    assert t.budget == 4
    # Two consecutive high-fallback windows: one rung up, streak reset.
    assert t.observe(1000, 100) == 4
    assert t.observe(1000, 100) == 8
    assert t.observe(1000, 100) == 8
    # Direction flip resets the streak; two lows walk back down.
    assert t.observe(1000, 0) == 8
    assert t.observe(1000, 0) == 4
    # In-band rates and empty windows hold.
    assert t.observe(1000, 20) == 4
    assert t.observe(0, 0) == 4
    assert t.decisions_up == 1 and t.decisions_down == 1
    # Clamped at the ladder ends.
    t2 = m.PruneAutotuner(m.PRUNE_LADDER[-1])
    for _ in range(6):
        t2.observe(100, 100)
    assert t2.budget == m.PRUNE_LADDER[-1]


def test_autotune_retune_end_to_end():
    """A 100%-fallback world at K=1 presses the controller up the ladder
    within two decision windows; the retune is journaled and subsequent
    steps serve the new rung with unchanged verdicts."""
    ps = _dense_ps(1100)
    dp = TpuflowDatapath(ps, flow_slots=1 << 8, aff_slots=1 << 6,
                         miss_chunk=16, prune_budget=1,
                         autotune_prune=True, canary_probes=0)
    from antrea_tpu.packet import Packet, PacketBatch

    def fresh(n0):
        # 64 lanes on purpose: shares the dense world's compiled step
        # (same meta + shapes as test_dense_world_full_fallback_parity).
        return PacketBatch.from_packets([
            Packet(src_ip=0x0A0A0000 + n0 + i, dst_ip=0x0A0A0007, proto=6,
                   src_port=31000, dst_port=80) for i in range(64)])

    r1 = dp.step(fresh(0), now=1)
    r2 = dp.step(fresh(100), now=2)
    assert dp._prune_budget == 2  # two sticky high-rate signals -> one rung
    assert dp._meta.match.prune_budget == 2
    ev = dp.flightrecorder_events(kind="prune-retune")
    assert ev and ev[-1]["budget_from"] == 1 and ev[-1]["budget_to"] == 2
    assert dp.prune_stats()["retunes_total"] == 1
    r3 = dp.step(fresh(0), now=3)  # same flows: now cache hits, still ALLOW
    assert set(r1.code) == set(r2.code) == set(r3.code) == {0}


def test_prune_config_errors():
    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=5)
    for eng in (TpuflowDatapath, OracleDatapath):
        with pytest.raises(ConfigError):
            eng(cluster.ps, prune_budget=-1)
        with pytest.raises(ConfigError):
            eng(cluster.ps, autotune_prune=True)


def test_profile_prune_mode_both_engines():
    """Structure + telescoped-sum identity on an abbreviated chain (the
    summary/candidate seam — the full 7-entry chain compiles seven
    pruned-pipeline variants and runs in the slow tier below)."""
    from antrea_tpu.models import profile as prof_mod

    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=5)
    hot = gen_traffic(cluster.pod_ips, 32, n_flows=16, seed=6)
    fresh = gen_traffic(cluster.pod_ips, 128, n_flows=128, seed=7,
                        one_per_flow=True)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=16, prune_budget=2, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    short = (("prune_fast_path", 0),
             ("prune_summary_gather",
              pl.PH_SLOW | pl.PH_LB | pl.PH_CLS_SUM),
             ("prune_candidate_gather", pl.PH_ALL))
    prof = prof_mod.profile_churn_prune(
        dp._meta, dp._state, dp._drs, dp._dsvc, prof_mod._dev_cols(hot),
        prof_mod._dev_cols(fresh), n_new=8, k_small=1, k_big=2, repeats=1,
        chain=short,
    )
    assert prof["mode"] == "prune" and prof["prune_budget"] == 2
    assert list(prof["phases_s"]) == [n for n, _m in short]
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-9
    # Unpruned metas refuse the mode (nothing to attribute) — at both
    # the profile_churn_prune layer and the Datapath.profile surface.
    with pytest.raises(ValueError):
        prof_mod.profile_churn_prune(
            dp._meta._replace(match=dp._meta.match._replace(prune_budget=0)),
            dp._state, dp._drs, dp._dsvc, prof_mod._dev_cols(hot),
            prof_mod._dev_cols(fresh), n_new=8)
    dp0 = TpuflowDatapath(cluster.ps, flow_slots=1 << 10, aff_slots=1 << 8,
                          miss_chunk=16, canary_probes=0,
                          flightrec_slots=0, realization_slots=0)
    with pytest.raises(ValueError):
        dp0.profile(hot, fresh, n_new=8, mode="prune")
    od = OracleDatapath(cluster.ps, prune_budget=2, flow_slots=1 << 10,
                        canary_probes=0, flightrec_slots=0,
                        realization_slots=0)
    po = od.profile(hot, fresh, mode="prune")
    assert po["mode"] == "prune" and po["prune_budget"] == 2
    assert "prune_candidate_gather" in po["phases_s"]
    # Twin parity: the scalar engine refuses the mode unpruned too.
    od0 = OracleDatapath(cluster.ps, flow_slots=1 << 10, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    with pytest.raises(ValueError):
        od0.profile(hot, fresh, mode="prune")


@pytest.mark.slow
def test_profile_prune_full_chain():
    from antrea_tpu.models.profile import PRUNE_PHASE_CHAIN

    cluster = gen_cluster(60, n_nodes=2, pods_per_node=4, seed=5)
    hot = gen_traffic(cluster.pod_ips, 32, n_flows=16, seed=6)
    fresh = gen_traffic(cluster.pod_ips, 128, n_flows=128, seed=7,
                        one_per_flow=True)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=16, prune_budget=2, canary_probes=0,
                         flightrec_slots=0, realization_slots=0)
    prof = dp.profile(hot, fresh, n_new=8, k_small=1, k_big=2, repeats=1,
                      mode="prune")
    assert prof["mode"] == "prune" and prof["prune_budget"] == 2
    assert list(prof["phases_s"]) == [n for n, _m in PRUNE_PHASE_CHAIN]
    assert abs(sum(prof["phases_s"].values()) - prof["total_s"]) < 1e-9


# ---------------------------------------------------------------------------
# Full reachability fixtures through the pruned kernel (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_pruned_kernel_matches_all_reachability_fixtures():
    from fixtures_reachability import SCENARIOS
    from test_reachability_fixtures import _probe_packet
    from antrea_tpu.packet import PacketBatch

    for scenario in SCENARIOS:
        cps = compile_policy_set(scenario.ps)
        batch = PacketBatch.from_packets(
            [_probe_packet(p) for p in scenario.probes])
        for k in (1, 4):
            drs, meta = m.to_device(cps, prune_budget=k)
            out = _classify(drs, meta, batch)
            for i, p in enumerate(scenario.probes):
                assert int(out["code"][i]) == p.expect, (
                    scenario.name, k, p)
