"""IPsec certificate workflow, flow-export sinks, antctl supportbundle."""

import json
import os
import tarfile

import pytest

pytestmark = pytest.mark.slow

from antrea_tpu.agent.ipsec import IpsecCertificateController
from antrea_tpu.controller.certificates import (
    SIGNER_IPSEC,
    CertificateAuthority,
    Csr,
    CsrController,
)
from antrea_tpu.observability.flowexport import (
    BatchDirSink,
    FlowExporter,
    JsonlFileSink,
    TableSink,
    fanout,
)


# ---- CSR / CA ---------------------------------------------------------------


def _ca(tmp_path, name="ca.db"):
    from antrea_tpu.native import ConfigStore

    return CertificateAuthority(ConfigStore(str(tmp_path / name)))


def test_csr_auto_approval_and_verify(tmp_path):
    ca = _ca(tmp_path)
    ctl = CsrController(ca)
    # Identity-matching IPsec CSR: auto-approved + signed (approver.go).
    csr = ctl.submit(Csr(name="n1-1", node="node-1", public_key="PK1"),
                     requestor="node-1", now=100)
    assert csr.approved and csr.certificate is not None
    assert ca.verify(csr.certificate, now=200)
    assert not ca.verify(csr.certificate, now=100 + 11 * 24 * 3600)  # expired
    # Tampered subject fails verification.
    forged = dict(csr.certificate, subject="node-x")
    assert not ca.verify(forged, now=200)
    # Identity MISMATCH: no auto-approval; manual deny blocks approve.
    csr2 = ctl.submit(Csr(name="evil", node="node-9", public_key="PK9"),
                      requestor="node-1", now=100)
    assert not csr2.approved and csr2.certificate is None
    ctl.deny("evil")
    with pytest.raises(ValueError):
        ctl.approve("evil", now=101)


def test_csr_name_immutable(tmp_path):
    """K8s CSR immutability: a name resubmit with different content is
    refused (no pending-CSR hijack, no denied-CSR resurrection)."""
    ctl = CsrController(_ca(tmp_path))
    ctl.submit(Csr(name="x", node="node-1", public_key="PK1"),
               requestor="other", now=1)  # pending (identity mismatch)
    with pytest.raises(ValueError):
        ctl.submit(Csr(name="x", node="node-1", public_key="ATTACKER"),
                   requestor="evil", now=2)
    ctl.deny("x")
    with pytest.raises(ValueError):
        ctl.submit(Csr(name="x", node="node-1", public_key="ATTACKER"),
                   requestor="node-1", now=3)
    assert ctl.get("x").denied


def test_ipsec_manual_approval_polled(tmp_path):
    """A CSR awaiting manual approval is polled on later syncs — the agent
    adopts the admin-approved certificate instead of abandoning the name."""
    ca = _ca(tmp_path)
    csrs = CsrController(ca)
    agent = IpsecCertificateController("node-1", csrs)

    # Force the manual path: submit under a different requestor identity by
    # making auto-approval fail — simulate by monkeypatching submit's
    # requestor via a wrapper controller.
    class ManualCsrs:
        def submit(self, csr, requestor, now):
            return csrs.submit(csr, requestor="someone-else", now=now)

        def get(self, name):
            return csrs.get(name)

    agent._csrs = ManualCsrs()
    assert agent.sync(now=0) is False
    pending = agent._pending
    assert pending is not None
    assert agent.sync(now=1) is False  # still waiting, SAME csr polled
    assert agent._pending == pending
    csrs.approve(pending, now=2)
    assert agent.sync(now=3) is True
    assert ca.verify(agent.certificate, now=4)


def test_ca_secret_persists(tmp_path):
    ca1 = _ca(tmp_path)
    cert = ca1.sign("node-1", "PK", now=10)
    ca2 = _ca(tmp_path)  # fresh handle, same store
    assert ca2.verify(cert, now=20)


def test_ipsec_agent_rotation(tmp_path):
    from antrea_tpu.native import ConfigStore

    ca = _ca(tmp_path, "ca.db")
    csrs = CsrController(ca)
    store = ConfigStore(str(tmp_path / "agent.db"))
    agent = IpsecCertificateController("node-1", csrs, store=store)
    assert agent.sync(now=0) is True
    cert1 = agent.certificate
    assert ca.verify(cert1, now=1)
    # Not yet rotation-due: no re-issue; a restarted agent reuses the
    # persisted certificate (ipseccertificate controller restart path).
    assert agent.sync(now=1000) is False
    agent2 = IpsecCertificateController("node-1", csrs, store=ConfigStore(
        str(tmp_path / "agent.db")))
    assert agent2.certificate == cert1
    # Past half the validity: rotation issues a fresh certificate.
    half = (cert1["notAfter"] - cert1["notBefore"]) // 2
    assert agent2.sync(now=cert1["notBefore"] + half + 1) is True
    assert agent2.certificate != cert1
    assert ca.verify(agent2.certificate, now=cert1["notBefore"] + half + 2)


# ---- flow-export sinks ------------------------------------------------------


def test_multi_sink_fanout(tmp_path):
    from antrea_tpu.datapath import TpuflowDatapath
    from antrea_tpu.packet import PacketBatch
    from antrea_tpu.utils import ip as iputil
    import numpy as np

    dp = TpuflowDatapath(flow_slots=1 << 10, aff_slots=1 << 8, miss_chunk=64)
    b = PacketBatch(
        src_ip=np.array([iputil.ip_to_u32("10.0.0.1")] * 3, np.uint32),
        dst_ip=np.array([iputil.ip_to_u32(f"10.0.1.{i}") for i in range(3)],
                        np.uint32),
        proto=np.full(3, 6, np.int32),
        src_port=np.full(3, 40000, np.int32),
        dst_port=np.full(3, 80, np.int32),
    )
    dp.step(b, now=10)
    log = JsonlFileSink(str(tmp_path / "flows.jsonl"))
    table = TableSink()
    s3 = BatchDirSink(str(tmp_path / "objects"), batch_size=4)
    exp = FlowExporter(dp, node="node-a", sink=fanout(log, table, s3))
    n = exp.poll(now=11)
    assert n >= 3  # fwd + reply entries
    # Log sink: one JSON line per record.
    lines = open(log.path).read().splitlines()
    assert len(lines) == n and json.loads(lines[0])["node"] == "node-a"
    # Table sink: rows queryable by column equality.
    assert len(table.rows) == n
    assert len(table.query(node="node-a", event="new")) == n
    # Batch sink: one full object written, tail flushed on demand.
    assert len(os.listdir(s3.dir)) == n // 4
    s3.flush()
    total = sum(
        len(open(os.path.join(s3.dir, f)).read().splitlines())
        for f in os.listdir(s3.dir)
    )
    assert total == n


def test_deny_records_policy_drops():
    """Denied traffic is visible as flow RECORDS, not only counters (the
    reference's deny connection store, pkg/agent/flowexporter): attaching
    an exporter arms the datapath's deny ring, every policy-DROP verdict
    lands in it, and poll() exports one event="deny" reason="policy" row
    per denied lane.  The ring drains on export — no re-emission."""
    import numpy as np

    from antrea_tpu.compiler.compile import ACT_DROP
    from antrea_tpu.datapath import TpuflowDatapath
    from antrea_tpu.simulator import gen_cluster, gen_traffic

    cluster = gen_cluster(300, seed=12)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=64)
    assert dp.deny_ring is None  # off by default: zero cost unexported
    table = TableSink()
    exp = FlowExporter(dp, node="n1", sink=table)
    assert dp.deny_ring is not None  # attach armed it

    t = gen_traffic(cluster.pod_ips, batch=96, seed=5)
    r = dp.step(t, now=10)
    drops = int((np.asarray(r.code) == ACT_DROP).sum())
    assert drops > 0
    exp.poll(now=11)
    rows = table.query(event="deny", reason="policy")
    assert len(rows) == drops
    idx = {c: i for i, c in enumerate(TableSink.COLUMNS)}
    for row in rows:
        assert row[idx["src"]].count(".") == 3  # real dotted-quad tuples
        assert row[idx["proto"]] in (6, 17)
        assert row[idx["reply"]] is False
        assert row[idx["node"]] == "n1"
        assert row[idx["export_ts"]] == 11
    # Drained: a second poll exports no stale deny rows.
    exp.poll(now=12)
    assert len(table.query(event="deny")) == drops


def test_deny_records_shed_reasons_match_engine_meters():
    """The async slow path's three shed paths each stamp their reason on
    the deny record, and the record counts equal the engine's meters
    EXACTLY — the deny export is the meters, itemized."""
    from antrea_tpu.datapath import TpuflowDatapath
    from antrea_tpu.simulator import gen_cluster, gen_traffic

    cluster = gen_cluster(300, seed=12)
    dp = TpuflowDatapath(cluster.ps, flow_slots=1 << 10, aff_slots=1 << 8,
                         miss_chunk=64, async_slowpath=True,
                         miss_queue_slots=32, admission="drop",
                         miss_source_rate=2.0)
    table = TableSink()
    exp = FlowExporter(dp, node="n1", sink=table)
    # Fresh flows every step, never drained: the queue fills (overflow +
    # early-drop) while per-source buckets exhaust (source-limit).
    for i in range(8):
        dp.step(gen_traffic(cluster.pod_ips, batch=96, seed=100 + i),
                now=10 + i)
    exp.poll(now=20)
    eng = dp._slowpath
    by_reason = {
        "source-limit": int(eng.source_limited_total),
        "early-drop": int(eng.early_drops_total),
        "queue-overflow": int(eng.queue.overflows_total),
    }
    assert sum(by_reason.values()) > 0
    for reason, n in by_reason.items():
        assert len(table.query(event="deny", reason=reason)) == n, reason


def test_idle_end_record_carries_final_counters():
    """The idle-end record reports the connection's LAST-KNOWN cumulative
    packets/bytes: by the ending poll the entry has left the live dump,
    so the exporter's connection store carries the volumes across polls
    (the reference's conn.OriginalPackets at deletion time)."""
    import numpy as np

    from antrea_tpu.datapath import TpuflowDatapath
    from antrea_tpu.features import FeatureGates
    from antrea_tpu.packet import Packet, PacketBatch
    from antrea_tpu.utils import ip as iputil

    dp = TpuflowDatapath(flow_slots=1 << 8, aff_slots=1 << 4, miss_chunk=16,
                         ct_timeout_s=30,
                         feature_gates=FeatureGates({"FlowExporter": True}))
    table = TableSink()
    exp = FlowExporter(dp, node="n1", sink=table, keep_records=True)
    pkt = Packet(src_ip=iputil.ip_to_u32("10.0.1.7"),
                 dst_ip=iputil.ip_to_u32("10.0.0.10"),
                 proto=6, src_port=41000, dst_port=80)

    def send(lens, now):
        b = PacketBatch.from_packets([pkt] * len(lens))
        b.pkt_len = np.asarray(lens, np.int32)
        dp.step(b, now=now)

    send([100], now=1)       # commit: 1 pkt / 100 B
    exp.poll(now=2)          # event="new" — volumes enter the conn store
    send([50, 70], now=3)    # est hits: totals now 3 pkts / 220 B
    exp.poll(now=4)          # carry poll (no active export yet)
    exp.poll(now=60)         # entry idled out of the dump -> end record
    ends = [r for r in exp.records if r["event"] == "end"
            and r["reply"] is False]
    assert len(ends) == 1
    assert ends[0]["reason"] == "idle-end"
    assert (ends[0]["packets"], ends[0]["bytes"]) == (3, 220)


def test_batch_sink_resumes_past_existing_objects(tmp_path):
    d = str(tmp_path / "objects")
    s1 = BatchDirSink(d, batch_size=1)
    s1({"a": 1})
    s2 = BatchDirSink(d, batch_size=1)  # restart over the same directory
    s2({"b": 2})
    files = sorted(os.listdir(d))
    assert files == ["records-000000.jsonl", "records-000001.jsonl"]
    assert json.loads(open(os.path.join(d, files[0])).read())["a"] == 1


# ---- antctl supportbundle ---------------------------------------------------


def test_antctl_supportbundle(tmp_path, capsys):
    from antrea_tpu import antctl
    from antrea_tpu.datapath import OracleDatapath
    from antrea_tpu.simulator import gen_cluster
    from antrea_tpu.simulator.genservice import gen_services

    cluster = gen_cluster(40, n_nodes=2, pods_per_node=4, seed=31)
    services = gen_services(3, cluster.pod_ips, seed=32)
    state = str(tmp_path / "state")
    dp = OracleDatapath(cluster.ps, services, flow_slots=1 << 10,
                        aff_slots=1 << 8, persist_dir=state)
    dp.install_bundle(cluster.ps, services)
    out = str(tmp_path / "bundle.tar.gz")
    assert antctl.main(["supportbundle", "--state", state, "--out", out,
                        "--node", "node-a"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert "stats.json" in rep["members"]
    with tarfile.open(out) as tar:
        names = tar.getnames()
        meta = json.loads(tar.extractfile("meta.json").read())
    assert {"meta.json", "metrics.prom", "datapath_snapshot.json"} <= set(names)
    # The bundle reports the snapshot's REAL generation, not a fresh 0.
    assert meta["generation"] == dp.generation >= 1
