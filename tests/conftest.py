"""Test env: force the CPU platform with 8 virtual devices so the suite is
hermetic and deterministic — real-accelerator platforms (e.g. the tunneled
axon TPU) are slow to dispatch and flaky under concurrent use, and every
kernel under test is platform-independent XLA.  TPU execution is covered by
bench.py and the verify harness, not unit tests.  The driver's multi-chip
dryrun provisions the same virtual-device setup itself
(__graft_entry__.dryrun_multichip)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# ANTREA_TPU_TEST_PLATFORM overrides the hermetic default so kernels can
# occasionally be validated on real hardware (e.g. =tpu).
os.environ["JAX_PLATFORMS"] = os.environ.get("ANTREA_TPU_TEST_PLATFORM", "cpu")

# Persistent XLA compilation cache: the suite's wall clock is dominated by
# program compiles (every engine/world/batch-shape variant is its own
# executable), so repeat runs in one container — the developer loop and the
# CI re-run — skip straight to execution.  Cache entries are keyed by
# program + compiler version, so a stale dir can only miss, never serve a
# wrong executable.  ANTREA_TPU_TEST_NO_COMPILE_CACHE=1 opts out (e.g. when
# bisecting compile-time itself).
if not os.environ.get("ANTREA_TPU_TEST_NO_COMPILE_CACHE"):
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/antrea_tpu_xla_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")


def cpu_devices():
    import jax

    return jax.devices("cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: datapath-compile / scale / process-boundary tests (minutes). "
        "Quick developer loop: pytest -m 'not slow' (< 2 min); CI and the "
        "driver run everything.",
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tier (tests/test_chaos_dissemination.py): "
        "scripted connection resets, agent crashes, and install failures "
        "with convergence-to-oracle-parity assertions.  The single-fault "
        "smoke rides the tier-1 'not slow' set; the kill/revive soak and "
        "process-boundary faults are also marked slow.",
    )
