"""Test env: ensure a CPU platform with 8 virtual devices is available so
sharding tests run without real multi-chip hardware (the driver's multi-chip
dryrun uses the same trick).  If a real TPU platform is configured (e.g.
JAX_PLATFORMS=axon), it is kept as the default platform and single-device
tests run on it; the mesh tests explicitly ask for jax.devices("cpu")."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

_plat = os.environ.get("JAX_PLATFORMS", "")
if _plat == "":
    os.environ["JAX_PLATFORMS"] = "cpu"
elif "cpu" not in _plat.split(","):
    os.environ["JAX_PLATFORMS"] = _plat + ",cpu"


def cpu_devices():
    import jax

    return jax.devices("cpu")
