"""Test env: force CPU with 8 virtual devices so sharding tests run without
real multi-chip hardware (the driver's dryrun does the same)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
